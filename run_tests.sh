#!/bin/sh
# Hermetic CPU-only test run: unsetting PALLAS_AXON_POOL_IPS stops the
# container's sitecustomize from dialing the TPU tunnel at interpreter
# start (a wedged tunnel otherwise hangs every python process).
#
# With no arguments, also exercises the driver entry points
# (__graft_entry__.py) on an 8-device virtual CPU mesh after the suite.
# `./run_tests.sh --quick` runs the quick tier: the suite minus
# slow-marked tests plus the telemetry + regress smokes.
set -e
# Hold a CPU-busy sentinel for the whole run so benchmarks/tunnel_watch.py
# never launches a timed TPU session while the suite saturates the 1-core
# host (per-pid file; watcher sweeps it if this script dies). Anchored to
# this script's directory, not the cwd — the watcher scans the repo root.
BUSY_DIR="$(cd "$(dirname "$0")" && pwd)/.cpu_busy.d"
mkdir -p "$BUSY_DIR"
echo "run_tests.sh $*" > "$BUSY_DIR/$$"
trap 'rm -f "$BUSY_DIR/$$"' EXIT INT TERM
run() {
    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu "$@"
}
REPO="$(cd "$(dirname "$0")" && pwd)"
if [ "$1" = "--quick" ]; then
    # quick tier (<2 min): the suite minus the slow-marked fuzz
    # harnesses and seed sweeps, plus the telemetry and regress-gate
    # smokes — every layer still touched once. The smokes run even
    # when pytest fails (a failing suite must not hide a broken
    # telemetry schema); pytest's status is the tier's status.
    shift
    rc=0
    run python -m pytest tests/ -q -m "not slow" "$@" || rc=$?
    TELDIR="$(mktemp -d)"
    trap 'rm -f "$BUSY_DIR/$$"; rm -rf "$TELDIR"' EXIT INT TERM
    run python -m replication_of_minute_frequency_factor_tpu \
        --telemetry-dir "$TELDIR"
    run python -m replication_of_minute_frequency_factor_tpu.telemetry.validate \
        "$TELDIR"
    run python -m replication_of_minute_frequency_factor_tpu.telemetry.regress \
        "$REPO"
    # rolling-parity smoke (ISSUE 3): the fused conv path AND the Pallas
    # interpret-mode kernel vs the f64 reference on two seeds incl. the
    # constant-window degenerate pin — one JSON line, nonzero on drift
    run python -c "import json; \
from replication_of_minute_frequency_factor_tpu.ops.rolling import _smoke; \
print(json.dumps(_smoke()))"
    # sharded-resident smoke (ISSUE 5): the mesh-native resident scan
    # vs the single-device one on 8 virtual CPU devices over a small
    # synthetic year — exposure equality (bitwise outside the two
    # documented ulp-level ratio kernels) plus the overlapped-ingest
    # metric firing; one JSON verdict line, nonzero on any mismatch
    run env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import json, sys, bench; r = bench.sharded_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # resident-2d smoke (ISSUE 13): the 2-D (days, tickers) pipelined
    # resident scan on an 8-virtual-device (2, 4) mesh — one JSON
    # verdict asserting 58-factor parity vs the single-device scan
    # (bitwise outside the two documented ulp-level ratio kernels),
    # zero extra host-blocking syncs per group vs the 1-D sharded
    # loop (the cross-day carry threads on device), a nonzero
    # carry-handoff collective count, and the handed-off year-end
    # carry bit-equal to the stream/carry prefix-state fold
    run env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import json, sys, bench; r = bench.resident_2d_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # serve smoke (ISSUE 6): an in-process FactorServer on CPU under a
    # handful of concurrent synthetic queries — second identical
    # request compiles nothing, >=1 coalesced multi-request dispatch,
    # exposure-cache hits > 0, p50/p99/QPS stamped under the declared
    # r8_serve_v1 methodology; one JSON verdict line, nonzero on drift
    run python -c "import json, sys, bench; r = bench.serve_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # stream smoke (ISSUE 7): the online intraday engine on CPU — a
    # seeded day streamed through the warm executables must reproduce
    # the full-day batch exposures (zero compiles after warmup, empty
    # parity-mismatch list) with bars/sec + per-update p50/p99 stamped
    # under the declared r9_stream_intraday_v1 methodology; one JSON
    # verdict line, nonzero on drift
    run python -c "import json, sys, bench; r = bench.stream_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # result-wire smoke (ISSUE 10): the blocked-quantized device->host
    # exposure wire end to end on a seeded batch — all 58 factors
    # computed raw, encoded on device, fetched as ONE packed payload,
    # host-dequantized; parity verdict (bitwise where widened, pinned
    # bounds where quantized) + the measured byte ratio in one JSON
    # line, nonzero exit on drift or a sub-1.5x ratio
    run python -c "import json, sys, bench; r = bench.result_wire_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # factor-health smoke (ISSUE 12): the per-factor data-quality
    # plane end to end on a seeded day — the fused on-device stats of
    # all 58 factors must match a host-side numpy recompute (counts +
    # min/max exact, moments within f32 reduction tolerance) with the
    # exposures bitwise unchanged, a stable pass must dump nothing,
    # and an injected coverage collapse must produce a validated
    # flight dump naming the factor; one JSON verdict line, nonzero
    # on drift
    run python -c "import json, sys, bench; r = bench.factorplane_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # ops-plane smoke (ISSUE 8): a streaming FactorServer + HTTP under
    # mixed ingest+query load — X-Trace-Id round-trip with the request
    # lifecycle reconstructible from the bundle, Prometheus scrape
    # carrying serving counters + device_hbm_* watermark gauges, a
    # breaker trip producing a flight-recorder dump that
    # telemetry.validate accepts, and a schema-v2-valid bundle; one
    # JSON verdict line, nonzero on any missing piece
    run python -c "import json, sys, bench; r = bench.opsplane_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # meshplane smoke (ISSUE 9): the mesh observability plane on 8
    # virtual CPU devices — a sharded resident group run must publish
    # nonzero per-shard time gauges with a computed skew ratio, an
    # injected straggler must trip a skew-burst flight dump that
    # telemetry.validate accepts and that names the slow shard, and
    # the aggregate CLI must merge two synthetic host bundles into one
    # schema-valid pod bundle whose counter totals equal the per-host
    # sums; one JSON verdict line, nonzero on any missing piece
    run env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import json, sys, bench; r = bench.meshplane_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # fleet smoke (ISSUE 11): 2 FactorServer replicas over disjoint
    # 4-device submeshes of the 8-device virtual mesh behind the
    # coalescing-affinity router — zero compiles during load (warm on
    # every replica), affinity hit-rate > 0, >=1 coalesced dispatch,
    # pod counter totals exactly the per-replica sums, and the
    # per-replica telemetry bundles aggregated into one schema-valid
    # pod bundle; one JSON verdict line, nonzero on any missing piece
    run env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import json, sys, bench; r = bench.fleet_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # discover smoke (ISSUE 14): the factor-discovery engine on 8
    # virtual CPU devices — one seeded population's fused backtest
    # fitness through the population-sharded generation graph vs the
    # single-device one (finite counts + device top-k selection set
    # bitwise, fitness ulp-pinned), the sharded loop's measured
    # contract (exactly 1 host-blocking sync per generation, zero
    # compiles after warmup), and >= 1 top-k gather collective
    # dispatch counted; one JSON verdict line, nonzero on drift
    run env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -c "import json, sys, bench; r = bench.discover_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # session smoke (ISSUE 15): one NON-DEFAULT market session (us_390)
    # end to end — wire encode at the 390-slot layout, the resident
    # scan executable vs the direct fused graph on the same decoded
    # inputs (bitwise outside the documented ulp-pinned fusion-wobble
    # kernels), the 58-kernel S-increment stream parity gate BITWISE,
    # and a sound end-of-day readiness plane; one JSON verdict line,
    # nonzero on drift
    run python -c "import json, sys, bench; r = bench.session_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # slo smoke (ISSUE 16): the SLO plane end to end on CPU — a
    # streaming FactorServer sampling frames at a 20 ms cadence with
    # compressed burn windows (time_scale=3600), an injected breaker
    # shed burst that must fire the multi-window availability burn
    # alert and force a validated slo_burn flight dump, a
    # pure-sampling interval asserted to move ZERO device-work
    # counters, and the telemetry.timeline CLI replaying the written
    # bundle into the incident report (frames spanning the alert
    # window + request traces cross-linked by trace ID); one JSON
    # verdict line, nonzero on any missing piece
    run python -c "import json, sys, bench; r = bench.slo_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # edge smoke (ISSUE 20): the evented binary front door on CPU — a
    # tiny edge-transport serve_bench driving keep-alive wire-encoded
    # HTTP load, then the edge-specific gates: the HTTP wire answer
    # byte-identical to the in-process one (one payload end to end), a
    # chunked range answer reassembling byte-identically to the
    # buffered one, quota exhaustion answering 429 + Retry-After, zero
    # compiles during the HTTP load, and the wire answer >=1.5x
    # smaller on the wire than JSON; one JSON verdict line
    run python -c "import json, sys, bench; r = bench.edge_smoke(); \
print(json.dumps(r)); sys.exit(0 if r['ok'] else 1)"
    # graftlint (ISSUE 4 + 19): AST rules over the whole package +
    # jaxpr contracts over all 58 registered kernels AND the resident
    # scan wrappers (abstract trace on CPU) + the Tier C concurrency
    # contracts over the threaded layers (--tier all is the default),
    # gated on the committed baseline — one JSON verdict line like
    # telemetry/regress.py, nonzero on any new violation
    # (docs/static-analysis.md); --report - keeps the tree clean here
    run python -m replication_of_minute_frequency_factor_tpu analyze \
        --report -
    exit $rc
fi
if [ "$#" -gt 0 ]; then
    # no exec: the EXIT trap must outlive pytest to drop the sentinel
    run python -m pytest -q "$@"
    exit $?
fi
run python -m pytest tests/ -q
run env XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python __graft_entry__.py
# fallback path: a 1-device platform must self-heal onto a virtual mesh
# (unset XLA_FLAGS so an inherited force-host flag can't pre-create 8
# devices and skip the path under test)
run env -u XLA_FLAGS python -c \
    "import __graft_entry__ as g; g.dryrun_multichip(8)"
# telemetry smoke (tier-1 observability contract, docs/observability.md):
# the synthetic pipeline with --telemetry-dir must produce a manifest, a
# Chrome trace, and a metrics.jsonl whose EVERY line validates against
# the schema — the validator exits non-zero otherwise
TELDIR="$(mktemp -d)"
trap 'rm -f "$BUSY_DIR/$$"; rm -rf "$TELDIR"' EXIT INT TERM
run python -m replication_of_minute_frequency_factor_tpu \
    --telemetry-dir "$TELDIR"
run python -m replication_of_minute_frequency_factor_tpu.telemetry.validate \
    "$TELDIR"
# regress smoke: the gate must parse the repo's own banked BENCH_r*.json
# trajectory and emit its one-line verdict (report mode — historical
# deviations are reported, only --strict/--check runs gate on them)
run python -m replication_of_minute_frequency_factor_tpu.telemetry.regress \
    "$REPO"
# graftlint gate (ISSUE 4 + 19, docs/static-analysis.md): AST + jaxpr
# + Tier C concurrency tiers against the committed baseline; nonzero
# on any new violation
run python -m replication_of_minute_frequency_factor_tpu analyze \
    --report -
