#!/bin/sh
# Hermetic CPU-only test run: unsetting PALLAS_AXON_POOL_IPS stops the
# container's sitecustomize from dialing the TPU tunnel at interpreter
# start (a wedged tunnel otherwise hangs every python process).
exec env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
    python -m pytest tests/ -q "$@"
