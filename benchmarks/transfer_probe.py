"""Does the attached-TPU link dedupe repeated identical buffers?
And how asymmetric is it?

bench.py alternates the SAME two packed batches across its timed
iterations. If the tunnel (or any layer under jax.device_put) caches
transfers by content, those iterations ride the cache and the headline
understates true streaming cost over 31 distinct batches. This probe
settles it: time device_put+ready for (a) one buffer sent repeatedly,
(b) a fresh random buffer of the same size each time, (c) the same
LOGICAL bytes in a freshly allocated array each time (catches id()- or
pointer-keyed caching as distinct from content-keyed).

It then times the REVERSE direction — device->host np.asarray of
distinct on-device buffers — because the link is asymmetric in
practice and the upstream leg is what the pipeline's result
materialization pays (~9 MB of [F, D, T] per batch; see the
copy_to_host_async overlap in pipeline._run_device_pipeline).

Since the 2026-08-01 headline (146 s where bandwidth+compute accounts
for ~1.1 s of each 4.8 s batch) it also measures the PER-TRANSFER
LATENCY floor: tiny-payload round trips each way. If the floor is
seconds-scale, the pipeline is dispatch-latency-bound and larger
DAYS_PER_BATCH amortizes it linearly; if it is milliseconds, the gap is
mid-loop bandwidth degradation instead (the probe's bandwidth was
measured immediately before the loop, not during).

Run on the TPU:  python benchmarks/transfer_probe.py [size_mb] [--json]
"""
import json
import sys
import time

import jax
import numpy as np

JSON_MODE = "--json" in sys.argv
_pos = [a for a in sys.argv[1:] if not a.startswith("-")]
SIZE_MB = float(_pos[0]) if _pos else 28.0
N = 6


def timed_put(buf):
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(buf))
    return time.perf_counter() - t0


def main():
    rng = np.random.default_rng(0)
    nbytes = int(SIZE_MB * 1e6)
    base = rng.integers(0, 256, nbytes, dtype=np.uint8)

    print(f"platform={jax.devices()[0].platform}  size={SIZE_MB:.1f}MB")
    timed_put(base)  # first-touch / warmup

    same = [timed_put(base) for _ in range(N)]
    fresh = [timed_put(rng.integers(0, 256, nbytes, dtype=np.uint8))
             for _ in range(N)]
    copies = [timed_put(base.copy()) for _ in range(N)]

    def fmt(ts):
        return (f"min {min(ts)*1e3:7.1f}ms  med {sorted(ts)[len(ts)//2]*1e3:7.1f}ms  "
                f"-> {SIZE_MB/1e3/min(ts):6.2f} GB/s at min")

    print("same buffer      :", fmt(same))
    print("fresh random     :", fmt(fresh))
    print("copy of same     :", fmt(copies))
    ratio = min(same) / min(fresh)
    print(f"same/fresh ratio : {ratio:.3f}  "
          f"({'DEDUP SUSPECTED' if ratio < 0.5 else 'no dedup evidence'})")

    # ---- reverse direction: device->host, distinct bytes each read ----
    n_f32 = nbytes // 4
    x = jax.device_put(rng.random(n_f32).astype(np.float32))
    jax.block_until_ready(x)
    down, down_async = [], []
    for i in range(N):
        y = x * np.float32(i + 2)           # distinct device bytes (i=0
        #   must differ from x itself, whose bytes already crossed)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        np.asarray(y)
        down.append(time.perf_counter() - t0)
    for i in range(N):
        y = x + np.float32(i + 1)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        y.copy_to_host_async()              # issue, then consume
        np.asarray(y)
        down_async.append(time.perf_counter() - t0)
    print("device->host     :", fmt(down))
    print("  (async-issued) :", fmt(down_async))
    updown = min(fresh) / min(down)
    print(f"up/down asymmetry: host->device is {1/updown:.2f}x the "
          f"device->host rate" if updown < 1 else
          f"up/down asymmetry: device->host is {updown:.2f}x the "
          f"host->device rate")

    # ---- per-transfer latency floor: 4 KB round trips each way ----
    tiny = rng.integers(0, 256, 4096, dtype=np.uint8)
    lat_put, lat_get = [], []
    for i in range(N):
        t0 = time.perf_counter()
        d = jax.device_put(tiny + np.uint8(i))  # distinct bytes
        jax.block_until_ready(d)
        lat_put.append(time.perf_counter() - t0)
        d2 = d + np.uint8(1)
        jax.block_until_ready(d2)
        t0 = time.perf_counter()
        np.asarray(d2)
        lat_get.append(time.perf_counter() - t0)
    print(f"latency floor    : put(4KB) min {min(lat_put)*1e3:.1f}ms  "
          f"get(4KB) min {min(lat_get)*1e3:.1f}ms")

    if JSON_MODE:
        med = lambda ts: sorted(ts)[len(ts) // 2]  # noqa: E731
        print(json.dumps({
            "size_mb": SIZE_MB,
            "same_fresh_ratio": round(min(same) / min(fresh), 3),
            # bench.py convention: down = host->device (the `fresh`
            # put timings), up = device->host (the asarray timings)
            "down_MBps_min": round(SIZE_MB / (min(fresh) * 1e3) * 1e3, 1),
            "up_MBps_min": round(SIZE_MB / (min(down) * 1e3) * 1e3, 1),
            "lat_put_ms_min": round(min(lat_put) * 1e3, 1),
            "lat_put_ms_med": round(med(lat_put) * 1e3, 1),
            "lat_get_ms_min": round(min(lat_get) * 1e3, 1),
            "lat_get_ms_med": round(med(lat_get) * 1e3, 1),
        }))


if __name__ == "__main__":
    main()
