"""Does the attached-TPU link dedupe repeated identical buffers?
And how asymmetric is it?

bench.py alternates the SAME two packed batches across its timed
iterations. If the tunnel (or any layer under jax.device_put) caches
transfers by content, those iterations ride the cache and the headline
understates true streaming cost over 31 distinct batches. This probe
settles it: time device_put+ready for (a) one buffer sent repeatedly,
(b) a fresh random buffer of the same size each time, (c) the same
LOGICAL bytes in a freshly allocated array each time (catches id()- or
pointer-keyed caching as distinct from content-keyed).

It then times the REVERSE direction — device->host np.asarray of
distinct on-device buffers — because the link is asymmetric in
practice and the upstream leg is what the pipeline's result
materialization pays (~9 MB of [F, D, T] per batch; see the
copy_to_host_async overlap in pipeline._run_device_pipeline).

Run on the TPU:  python benchmarks/transfer_probe.py [size_mb]
"""
import sys
import time

import jax
import numpy as np

SIZE_MB = float(sys.argv[1]) if len(sys.argv) > 1 else 28.0
N = 6


def timed_put(buf):
    t0 = time.perf_counter()
    jax.block_until_ready(jax.device_put(buf))
    return time.perf_counter() - t0


def main():
    rng = np.random.default_rng(0)
    nbytes = int(SIZE_MB * 1e6)
    base = rng.integers(0, 256, nbytes, dtype=np.uint8)

    print(f"platform={jax.devices()[0].platform}  size={SIZE_MB:.1f}MB")
    timed_put(base)  # first-touch / warmup

    same = [timed_put(base) for _ in range(N)]
    fresh = [timed_put(rng.integers(0, 256, nbytes, dtype=np.uint8))
             for _ in range(N)]
    copies = [timed_put(base.copy()) for _ in range(N)]

    def fmt(ts):
        return (f"min {min(ts)*1e3:7.1f}ms  med {sorted(ts)[len(ts)//2]*1e3:7.1f}ms  "
                f"-> {SIZE_MB/1e3/min(ts):6.2f} GB/s at min")

    print("same buffer      :", fmt(same))
    print("fresh random     :", fmt(fresh))
    print("copy of same     :", fmt(copies))
    ratio = min(same) / min(fresh)
    print(f"same/fresh ratio : {ratio:.3f}  "
          f"({'DEDUP SUSPECTED' if ratio < 0.5 else 'no dedup evidence'})")

    # ---- reverse direction: device->host, distinct bytes each read ----
    n_f32 = nbytes // 4
    x = jax.device_put(rng.random(n_f32).astype(np.float32))
    jax.block_until_ready(x)
    down, down_async = [], []
    for i in range(N):
        y = x * np.float32(i + 2)           # distinct device bytes (i=0
        #   must differ from x itself, whose bytes already crossed)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        np.asarray(y)
        down.append(time.perf_counter() - t0)
    for i in range(N):
        y = x + np.float32(i + 1)
        jax.block_until_ready(y)
        t0 = time.perf_counter()
        y.copy_to_host_async()              # issue, then consume
        np.asarray(y)
        down_async.append(time.perf_counter() - t0)
    print("device->host     :", fmt(down))
    print("  (async-issued) :", fmt(down_async))
    updown = min(fresh) / min(down)
    print(f"up/down asymmetry: host->device is {1/updown:.2f}x the "
          f"device->host rate" if updown < 1 else
          f"up/down asymmetry: device->host is {updown:.2f}x the "
          f"host->device rate")


if __name__ == "__main__":
    main()
