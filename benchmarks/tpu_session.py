"""One-shot TPU capture for a tunnel-up window.

The chip sits behind a tunnel that wedges for hours at a time, so the
moment it is reachable, EVERYTHING the round needs must be captured in
one command (VERDICT r1 items 2/4/6 and weak #5's lesson: don't spend
an up-window on anything else):

  1. the hardened headline bench (bench.py, full methodology);
  2. the BASELINE config ladder (benchmarks/ladder.py 1,2,4,5);
  3. on-chip timing of the rolling-moment kernel (the conv formulation —
     the Pallas alternative was removed in round 3 having never reached
     hardware; docs/ROADMAP.md records the decision);
  4. correctness spot-check of the full 58-kernel graph on-chip vs the
     CPU oracle.

Everything lands in ONE committed artifact (default
``benchmarks/TPU_SESSION.json``) with per-step status, so a window that
closes mid-run still leaves whatever finished.

Run:  python benchmarks/tpu_session.py [--out PATH] [--skip-probe]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _probe(timeout=90):
    try:
        return subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout, capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _run_json_lines(cmd, timeout):
    """Run a child; parse every stdout line that is a JSON object."""
    t0 = time.monotonic()
    try:
        # children inherit MFF_COMPILATION_CACHE_DIR set in main()
        p = subprocess.run(cmd, cwd=REPO, timeout=timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired as e:
        return {"ok": False, "error": f"timeout {timeout}s",
                "tail": str(e.stdout or "")[-1500:]}
    lines = []
    for ln in (p.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                lines.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
    return {"ok": p.returncode == 0, "rc": p.returncode,
            "seconds": round(time.monotonic() - t0, 1), "results": lines,
            "tail": None if p.returncode == 0
            else (p.stdout + p.stderr)[-1500:]}


def step_headline():
    return _run_json_lines([sys.executable, "bench.py"], timeout=1800)


def step_ladder():
    return _run_json_lines(
        [sys.executable, "benchmarks/ladder.py", "--configs", "1,2,4,5"],
        timeout=1800)


def step_sweep():
    """Optional DAYS_PER_BATCH sweep (benchmarks/sweep_batch.py) — run
    when the window allows; not in the default step list."""
    return _run_json_lines([sys.executable, "benchmarks/sweep_batch.py"],
                           timeout=1800)


def step_rolling():
    """On-chip timing of the rolling-moment conv kernel (the mmt_ols_*
    hot op) plus an f64-oracle agreement check on a sample of windows.

    Runs in-process (we already know the tunnel is up). Shapes mirror
    the production use: [tickers, 240] minute panels.
    """
    import jax
    import numpy as np

    from replication_of_minute_frequency_factor_tpu.ops.rolling import (
        rolling_window_stats)

    out = {"backend": jax.devices()[0].platform,
           "device": str(jax.devices()[0])}
    rng = np.random.default_rng(0)
    n_tickers = int(os.environ.get("TPU_SESSION_TICKERS", "4096"))
    shape = (n_tickers, 240)
    low = 10.0 * np.exp(np.cumsum(rng.normal(0, 1e-3, shape), -1)) \
        .astype(np.float32)
    high = (low * (1 + np.abs(rng.normal(0, 1e-3, shape)))) \
        .astype(np.float32)
    mask = rng.random(shape) > 0.03

    def time_impl(fn, iters=20):
        r = jax.block_until_ready(fn())  # compile + warm
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn()
        jax.block_until_ready(r)
        return (time.perf_counter() - t0) / iters, r

    # real device-buffer arguments (a zero-arg jit would bake the
    # inputs in as constants and let XLA fold work at compile time)
    dlow, dhigh = jax.device_put(low), jax.device_put(high)
    dmask = jax.device_put(mask)
    conv_jit = jax.jit(lambda x, y, m: rolling_window_stats(
        x, y, m, 50, impl="conv"))
    t_conv, r_conv = time_impl(lambda: conv_jit(dlow, dhigh, dmask))
    out["conv_ms_per_batch"] = round(t_conv * 1e3, 3)
    out["n_tickers"] = n_tickers

    # f64 two-pass oracle agreement on a row sample (on-chip numerics)
    diffs = {}
    valid = np.asarray(r_conv["valid"])
    for t in range(0, n_tickers, max(1, n_tickers // 8)):
        x = low[t].astype(np.float64)
        y = high[t].astype(np.float64)
        m = mask[t]
        xc = np.where(m, x - x[m].mean() if m.any() else x, 0.0)
        yc = np.where(m, y - y[m].mean() if m.any() else y, 0.0)
        for i in np.nonzero(valid[t])[0][:4]:
            w = slice(i - 49, i + 1)
            xw, yw = xc[w], yc[w]
            cov = ((xw - xw.mean()) * (yw - yw.mean())).mean()
            got = float(np.asarray(r_conv["cov"])[t, i])
            scale = max(abs(cov), 1e-9)
            diffs[f"{t}/{i}"] = abs(got - cov) / scale
    out["max_rel_diff_cov_sample"] = float(max(diffs.values())) \
        if diffs else None
    out["agree_1e-2"] = bool(diffs and max(diffs.values()) < 1e-2)
    return {"ok": True, "results": [out]}


def step_graph_spotcheck():
    """Full 58-kernel fused graph on the chip vs the CPU oracle, using
    the parity suite's FULL comparator protocol (tolerance matrix,
    doc_pdf tie acceptance, degenerate-beta skips) — a hand-rolled
    comparison here would false-alarm on cells the suite deliberately
    accepts and burn the tunnel window."""
    import time as _t

    import jax
    import numpy as np

    from replication_of_minute_frequency_factor_tpu.data import synth_day
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names)

    sys.path.insert(0, os.path.join(REPO, "tests"))
    import test_parity as tp

    rng = np.random.default_rng(1)
    day = synth_day(rng, n_codes=32, missing_prob=0.05,
                    zero_volume_prob=0.05)
    t0 = _t.perf_counter()
    tp._compare(day, "tpu_spot", noisy=True)  # raises on mismatch
    wall = _t.perf_counter() - t0
    return {"ok": True, "results": [{
        "platform": jax.devices()[0].platform,
        "compare_wall_s": round(wall, 1),
        "factors": len(factor_names()), "codes": 32}]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, "benchmarks", "TPU_SESSION.json"))
    ap.add_argument("--skip-probe", action="store_true")
    ap.add_argument("--steps", default="headline,ladder,rolling,spot")
    args = ap.parse_args()

    session = {"started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
               # stamped by tunnel_watch so a capture that raced CPU-heavy
               # work is identifiable in the artifact itself (1-core host);
               # real JSON bool/null so `if session["host_quiet"]` works
               "host_quiet": (
                   None if "TPU_SESSION_HOST_QUIET" not in os.environ
                   else os.environ["TPU_SESSION_HOST_QUIET"] == "True"),
               "steps": {}}
    if not args.skip_probe and not _probe():
        session["steps"]["probe"] = {"ok": False,
                                     "error": "tunnel unreachable"}
        with open(args.out, "w") as fh:  # never leave a stale artifact
            json.dump(session, fh, indent=1)
        print(json.dumps(session))
        return 1

    os.environ.setdefault("MFF_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".xla_cache"))
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    apply_compilation_cache(get_config())
    steps = {"headline": step_headline, "ladder": step_ladder,
             "rolling": step_rolling, "spot": step_graph_spotcheck,
             "sweep": step_sweep}
    want = [s.strip() for s in args.steps.split(",") if s.strip()]
    for name in want:
        print(f"--- step: {name}", flush=True)
        try:
            session["steps"][name] = steps[name]()
        except Exception as e:  # keep capturing the rest of the window
            import traceback
            session["steps"][name] = {
                "ok": False, "error": f"{type(e).__name__}: {e}",
                "trace": traceback.format_exc()[-1500:]}
        with open(args.out, "w") as fh:  # persist after EVERY step
            json.dump(session, fh, indent=1)
        print(json.dumps({name: session["steps"][name].get("ok")}),
              flush=True)
    oks = {k: v.get("ok") for k, v in session["steps"].items()}
    print(json.dumps({"session_done": oks}))
    return 0 if all(oks.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
