"""One-shot TPU capture for a tunnel-up window.

The chip sits behind a tunnel that wedges for hours at a time, so the
moment it is reachable, EVERYTHING the round needs must be captured in
one command (VERDICT r1 items 2/4/6 and weak #5's lesson: don't spend
an up-window on anything else):

  1. the hardened headline bench (bench.py, full methodology) and its
     consolidated-fetch variant;
  2. transfer/link diagnostics incl. the per-transfer latency floor;
  3. the four BASELINE configs (benchmarks/ladder.py, one step each so
     a window closing mid-config doesn't lose the others);
  4. correctness spot-check of the full 58-kernel graph on-chip vs the
     CPU oracle;
  5. the DAYS_PER_BATCH sweep and the real 244-day pipeline run — the
     two long tails, last so they only spend leftover window.

(The conv-vs-pallas rolling step was removed with the Pallas kernel —
three rounds carried, zero tunnel windows coincided with it, dropped
per the round-3 verdict's final prove-or-drop; docs/ROADMAP.md.)

Everything lands in ONE committed artifact (default
``benchmarks/TPU_SESSION.json``) with per-step status, so a window that
closes mid-run still leaves whatever finished.

Run:  python benchmarks/tpu_session.py [--out PATH] [--skip-probe]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def _probe(timeout=90):
    try:
        return subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout, capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _run_json_lines(cmd, timeout, env=None):
    """Run a child; parse every stdout line that is a JSON object."""
    t0 = time.monotonic()
    try:
        # children inherit MFF_COMPILATION_CACHE_DIR set in main()
        p = subprocess.run(cmd, cwd=REPO, timeout=timeout,
                           capture_output=True, text=True, env=env)
    except subprocess.TimeoutExpired as e:
        return {"ok": False, "error": f"timeout {timeout}s",
                "tail": str(e.stdout or "")[-1500:]}
    lines = []
    for ln in (p.stdout or "").splitlines():
        ln = ln.strip()
        if ln.startswith("{"):
            try:
                lines.append(json.loads(ln))
            except json.JSONDecodeError:
                pass
    return {"ok": p.returncode == 0, "rc": p.returncode,
            "seconds": round(time.monotonic() - t0, 1), "results": lines,
            "tail": None if p.returncode == 0
            else (p.stdout + p.stderr)[-1500:]}


def carry_green_steps(artifact_path, max_age_hours, now=None):
    """Green steps from a prior session artifact, age-bounded per step.

    A retry window skips the carried-green steps, and writing a fresh
    artifact would DROP the banked results. Failed entries
    are not carried — they re-run. The bound (default ~one round) is on
    each step's own ``captured_utc`` stamp: the artifact is committed,
    so without it a NEXT round's first fire would carry last round's
    green steps, skip everything, and bank stale numbers without a
    single new hardware execution. It deliberately ignores the
    artifact-level ``started_utc``, which every fire — including
    probe-fail fires — rewrites; aging against that would let a chain
    of rewrites keep stale steps alive forever. A step with no stamp is
    treated as infinitely old.

    ``now`` (epoch seconds) is injectable for tests. Stamps are UTC, so
    they parse via calendar.timegm — time.mktime would interpret the
    struct in LOCAL time and skew every age by the UTC offset on a
    non-UTC machine.
    """
    import calendar
    if now is None:
        now = time.time()

    def _age_hours(stamp):
        try:
            t = calendar.timegm(time.strptime(stamp,
                                              "%Y-%m-%dT%H:%M:%SZ"))
        except (TypeError, ValueError, OverflowError):
            return float("inf")
        return (now - t) / 3600.0

    try:
        with open(artifact_path) as fh:
            prior = json.load(fh)
        return {k: v for k, v in prior.get("steps", {}).items()
                if v.get("ok")
                and _age_hours(v.get("captured_utc")) <= max_age_hours}
    except (OSError, json.JSONDecodeError, ValueError, AttributeError):
        return {}


def drop_conv_only_rolling(steps):
    """Content checks for carried entries, not just names — a green
    entry from an older code/configuration must not satisfy this
    round's step (the carry would skip it forever):

    * 'rolling' entries belong to the step removed with the round-4
      Pallas prove-or-drop — never carried;
    * 'pallas' entries must be records of the REINTRODUCED kernel
      (ISSUE 3): a ``rolling_impl: pallas`` 5000-ticker record under
      the ``_pallas`` metric suffix. Pre-reintroduction green entries
      (the dropped r2-r4 step shared the name) have none of those
      fields and drop — they re-run under the new contract;
    * 'headc' entries belong to the r4 consolidated-fetch A/B, which
      the r5 resident loop supersedes — never carried;
    * 'headline' entries must be the r5 resident methodology (a
      ``mode: resident`` record with the per-phase breakdown) AND a
      5000-ticker record (``tickers: 5000``, stamped by bench.py since
      r6): N_TICKERS is BENCH_TICKERS-overridable, and before the stamp
      a 500-ticker run printed a much faster number under the
      5000-ticker name which this carry would have banked forever
      (round-5 ADVICE medium). Pre-stamp records have no ``tickers``
      key and are dropped — they re-run once under the new schema.
      Since ISSUE 10 the record must ALSO embed the ``result_wire``
      block with ``enabled: true``: the headline's remaining byte
      lever is the quantized result leg, and a run whose fetch
      silently fell back to raw f32 (BENCH_RESULT_WIRE=0, or a spec
      regression) measures the OLD transfer shape — it cannot bank as
      the r10 headline. Since ISSUE 12 the record must ALSO carry an
      AVAILABLE ``factor_health`` block (the fused per-factor stats
      side-output sampled): the first hardware window is what banks
      the ROADMAP's real-data widen-rate answer for the 9
      strict-pinned volume factors, so a record without the
      data-quality plane cannot bank;
    * 'stream' entries must be ``mode: stream`` records (the r1-r4
      series continuation under its own metric suffix);
    * 'resident_sharded' entries must be records of the r7 mesh-native
      resident scan that ACTUALLY sharded: a ``mode: resident`` record
      under the ``_sharded`` metric suffix with ``n_shards > 1`` and
      the 5000-ticker stamp — the same "silent fallback cannot bank"
      rule as the pallas step (a single-device resolution banks
      nothing; the next multi-device window must re-run it);
    * 'stream_intraday' entries must be r9 records that actually
      streamed warm and faithfully: ``r9_stream_intraday_v1`` with
      ``stream.updates > 0``, zero compiles during load and an empty
      parity-mismatch list (ISSUE 7); since ISSUE 18 the window must
      ALSO carry the fast-finalize A/B leg — an r9 record genuinely
      RESOLVED to ``finalize_impl='fast'`` with a green three-class
      parity verdict plus the r14 snapshot-per-bar profile whose
      histogram is present and available (a fast number without its
      flatness evidence, or a fast request that silently degraded to
      exact, cannot bank);
    * since ISSUE 8 both serve and stream records must embed the HBM
      watermark block (``hbm`` with the explicit ``available``
      marker) — carried records feed the ``<metric>.hbm_peak_bytes``
      regress series, so a watermark-less record cannot bank;
    * since ISSUE 9 'resident_sharded' and 'stream_intraday' records
      must additionally embed the ``mesh`` shard-balance block
      (telemetry/meshplane.py — per-shard watermarks/skew for the
      sharded scan, cohort occupancy for the stream): the banked
      trajectory feeds the ``<metric>.shard_skew_ratio`` /
      ``.pad_waste_frac`` regress series, so a record with no
      shard-balance telemetry cannot bank;
    * 'resident_2d' entries (ISSUE 13) must be records of the r12 2-D
      pipelined scan that GENUINELY ran 2-D: the declared
      ``r12_resident_2d_v1`` methodology with ``mesh_shape`` d > 1
      AND t > 1, per-axis mesh watermarks covering both axes, the
      ``result_wire`` block and an available ``factor_health`` block
      (:func:`_resident_2d_record_banks`) — a 1-D fallback re-runs on
      the next multi-device window;
    * 'fleet' entries must be records of the r11 replica fleet that
      actually MULTIPLIED the service (ISSUE 11): the declared
      ``r11_fleet_v1`` methodology with ``live_replicas >= 2`` (a
      single-device window runs one replica at most — that measures
      serve, not the fleet; it fails loudly and re-runs on the next
      multi-device window, the resident_sharded rule's mirror), the
      pod ``hbm`` watermark block, and the pod-folded counter block
      (:func:`_fleet_record_banks`);
    * since ISSUE 16 'serve' and 'fleet' records must additionally
      embed a non-empty ``slo`` block with ``frames > 0`` (the SLO
      plane's timeline sampler genuinely ran): the banked trajectory
      feeds the ``<metric>.burn_rate_max`` regress series, so a
      record with no burn-rate evidence cannot bank — pre-ISSUE-16
      green entries have no ``slo`` block and re-run under the new
      contract;
    * since ISSUE 20 'serve' and 'fleet' windows must ALSO carry the
      evented-edge leg: an ``r15_serve_edge_v1`` /
      ``r15_fleet_edge_v1`` record with ``transport == 'edge'`` and
      an available ``edge`` block whose ``wire_answers`` is a nonzero
      int (the load generators genuinely decoded binary frames), zero
      HTTP failures, and — for the fleet — a nonzero ``routed_wire``
      (the router's replica hop carried the wire, not re-encoded
      JSON). Pre-ISSUE-20 green entries have no edge leg and re-run
      as two-leg windows (:func:`_serve_edge_record_banks` /
      :func:`_fleet_edge_record_banks`).
    """
    def keep(name, v):
        recs = [r for r in v.get("results") or [] if isinstance(r, dict)]
        if name in ("rolling", "headc"):
            return False  # steps removed in r4/r5
        if name == "resident_sharded":
            return any("_sharded" in str(r.get("metric", ""))
                       and r.get("mode") == "resident"
                       and isinstance(r.get("n_shards"), int)
                       and r.get("n_shards") > 1
                       and r.get("tickers") == 5000
                       and isinstance(r.get("mesh"), dict)
                       for r in recs)
        if name == "pallas":
            # rolling_impl_resolved (not just requested): a record whose
            # graphs silently fell back to conv is NOT kernel validation
            return any("_pallas" in str(r.get("metric", ""))
                       and r.get("rolling_impl") == "pallas"
                       and r.get("rolling_impl_resolved") == "pallas"
                       and r.get("tickers") == 5000 for r in recs)
        if name == "headline":
            return any(r.get("mode") == "resident"
                       and r.get("tickers") == 5000
                       and isinstance(r.get("result_wire"), dict)
                       and r["result_wire"].get("enabled") is True
                       and isinstance(r.get("factor_health"), dict)
                       and r["factor_health"].get("available") is True
                       for r in recs)
        if name == "stream":
            return any(r.get("mode") == "stream" for r in recs)
        if name == "serve":
            # ISSUE 6: zero exposure-cache hits means the service never
            # answered warm — the record measured cold dispatch, not
            # serving; it re-runs. ISSUE 20: the window must ALSO
            # carry a bankable evented-edge leg (binary answers
            # through the real front door)
            return (any(_serve_record_banks(r) for r in recs)
                    and any(_serve_edge_record_banks(r) for r in recs))
        if name == "stream_intraday":
            # ISSUE 7 + 18: zero streamed updates means the ingest loop
            # never dispatched (measured nothing), a load-phase compile
            # means the executables were not warm, and a non-empty
            # parity list means the streamed fold diverged on hardware
            # — none of those may bank. Since the step became an
            # exact/fast A/B (ISSUE 18), the window must ALSO carry a
            # bankable fast leg: an r9 record genuinely RESOLVED to
            # 'fast' with a green verdict plus the available r14
            # per-bar histogram — a step that silently lost its fast
            # leg (degraded impl, cold profile) re-runs
            return (any(_stream_record_banks(r) for r in recs)
                    and _stream_fast_record_banks(recs))
        if name == "fleet":
            # ISSUE 11: fewer than 2 live replicas means the pod never
            # multiplied (one replica IS the serve step), and a record
            # without the pod hbm/counter blocks has no degrade-policy
            # or fold evidence — neither may bank. ISSUE 20: the
            # window must ALSO carry a bankable pod-edge leg (wire
            # through the door AND the routed replica hop)
            return (any(_fleet_record_banks(r) for r in recs)
                    and any(_fleet_edge_record_banks(r) for r in recs))
        if name == "resident_2d":
            # ISSUE 13: a record whose mesh fell back to 1-D (or whose
            # balance/wire/data-quality evidence is missing) is not
            # 2-D validation — it fails loudly and re-runs on the next
            # multi-device window
            return any(_resident_2d_record_banks(r) for r in recs)
        if name == "discover":
            # ISSUE 14: zero completed generations means the loop
            # never ran, a loop compile means the fitness executable
            # was not warm, and more than one sync per generation
            # means fitness round-tripped the host mid-generation —
            # none of those measured the discovery engine's contract
            return any(_discover_record_banks(r) for r in recs)
        return True

    return {k: v for k, v in steps.items() if keep(k, v)}


def _run_one_step_child(name, timeout=1500):
    """Run a step's in-process body in a killable child.

    The child re-invokes this script with ``--one-step NAME``, which
    executes the body and prints its result dict as the final JSON
    line; _run_json_lines' line parsing picks it up. On timeout the
    parent records a failed step instead of hanging the whole session
    (and with it the watcher's retry loop) on a wedged backend init.
    """
    r = _run_json_lines(
        [sys.executable, os.path.abspath(__file__), "--one-step", name],
        timeout=timeout)
    # unwrap: the child's last JSON line IS the step result; carry the
    # wrapper's rc + seconds so every step entry shares one schema
    # (ADVICE r4: the spot entry omitted rc, unlike _run_json_lines
    # steps, leaving the artifact schema inconsistent across steps)
    for rec in reversed(r.get("results") or []):
        if isinstance(rec, dict) and "ok" in rec:
            rec.setdefault("rc", r.get("rc"))
            rec.setdefault("seconds", r.get("seconds"))
            return rec
    return r  # child died before printing a result (timeout/crash)


def _run_bench_gated(extra_env):
    """Run bench.py with BENCH_REQUIRE_TPU and refuse to bank a
    CPU-fallback metric as green: a retry fire that raced a tunnel
    drop would otherwise bank a _cpu_fallback number as a headline
    step and no later fire would ever replace it. One gate shared by
    every headline variant so the predicate can't drift."""
    r = _run_json_lines(
        [sys.executable, "bench.py"], timeout=1800,
        env=dict(os.environ, BENCH_REQUIRE_TPU="1", **extra_env))
    if r.get("ok") and any("_cpu_fallback" in str(rec.get("metric", ""))
                           for rec in r.get("results") or []):
        r["ok"] = False
        r["error"] = "bench printed a CPU-fallback metric"
    return r


def step_headline():
    """r5 resident headline (bench.py default mode) with the profiler
    dir wired in (VERDICT r4 #1): the stage pass attempts an on-chip
    jax.profiler trace and banks profile_ok/profile_error either way;
    trace files land uncommitted under .bench_data/profile_r5."""
    return _run_bench_gated({"MFF_PROFILE_DIR": os.path.join(
        REPO, ".bench_data", "profile_r5")})


def step_stream():
    """The r1-r4 per-batch stream loop under its own metric suffix —
    the series continuation that makes the resident loop's gain an
    A/B on the same hardware window rather than a methodology break
    (VERDICT r4 #3). Stage pass AND link probes off — the headline
    step already banks those diagnostics this window."""
    return _run_bench_gated({"BENCH_MODE": "stream",
                             "BENCH_METRIC_SUFFIX": "_stream",
                             "BENCH_STAGES": "0", "BENCH_LINK": "0"})


def step_pallas():
    """The rolling Pallas VMEM kernel vs the fused conv path, SAME
    hardware window as the headline (ISSUE 3: the reintroduced kernel
    must not linger hardware-unvalidated). Runs the resident headline
    workload with ``MFF_ROLLING_IMPL=pallas`` under its own metric
    suffix; the stage pass stays ON so the record carries the pallas
    graph's compile telemetry + HLO op counts and a profiler capture
    (``MFF_PROFILE_DIR``) for the per-op-class before/after that
    docs/BENCHMARKS.md §Round-6 leaves pending. Interpret-mode parity
    is gated in tier-1 (tests/test_parity.py, ``pallas`` marker); this
    step is the hardware half: a compile/runtime failure here records
    loudly, and rolling.impl{requested=pallas,resolved=conv} in the
    bundle would expose a silent fallback."""
    return _run_bench_gated({"MFF_ROLLING_IMPL": "pallas",
                             "BENCH_METRIC_SUFFIX": "_pallas",
                             "BENCH_LINK": "0",
                             "MFF_PROFILE_DIR": os.path.join(
                                 REPO, ".bench_data", "profile_pallas")})


def step_resident_sharded():
    """The r7 mesh-native resident scan (ISSUE 5), SAME hardware window
    as the headline and the still-unvalidated r5/r6 single-device
    resident scan: bench in resident mode under the ``_sharded`` metric
    suffix, banking ONLY when the tickers mesh actually resolved to
    more than one device (``n_shards > 1`` in the record) — the mirror
    of the pallas step's "silent fallback cannot bank" rule. On the
    single attached chip this step fails loudly and keeps re-running
    until a multi-device window exists; interpret-mode parity is
    already gated in tier-1 on 8 virtual CPU devices
    (tests/test_sharded_resident.py), this is the hardware half. Link
    probes + the 8-day stage pass stay off — the headline banks those
    diagnostics this window."""
    r = _run_bench_gated({"BENCH_MODE": "resident",
                          "BENCH_METRIC_SUFFIX": "_sharded",
                          "BENCH_STAGES": "0", "BENCH_LINK": "0"})
    if r.get("ok") and not any(
            isinstance(rec, dict)
            and isinstance(rec.get("n_shards"), int)
            and rec.get("n_shards") > 1
            for rec in r.get("results") or []):
        r["ok"] = False
        r["error"] = ("sharded resident resolved to n_shards<=1 "
                      "(single-device fallback) — not sharded "
                      "validation; cannot bank")
    if r.get("ok") and not any(
            isinstance(rec, dict) and isinstance(rec.get("mesh"), dict)
            for rec in r.get("results") or []):
        # ISSUE 9: the sharded trajectory feeds the shard-skew regress
        # series — a record without the mesh balance block cannot bank
        r["ok"] = False
        r["error"] = ("sharded resident record has no mesh "
                      "shard-balance block — cannot bank")
    return r


def step_resident_2d():
    """The r12 2-D ``(days, tickers)`` pipelined resident scan
    (ISSUE 13), SAME hardware window as the headline and the 1-D
    sharded step: bench in resident mode with ``BENCH_MESH_DAYS=2``
    under the ``_2d`` metric suffix. Banks ONLY through
    :func:`_resident_2d_record_banks` — the mesh must have resolved to
    a genuinely 2-D ``(d > 1, t > 1)`` shape (on fewer than 4 devices
    bench falls back to the 1-D loop and this step fails loudly,
    exactly like resident_sharded on one chip), with the per-axis
    ``mesh`` watermark block, the ``result_wire`` block and an
    available ``factor_health`` block riding the record. CPU parity is
    already gated in tier-1 on the 8-virtual-device ``(2, 4)`` mesh
    (tests/test_sharded_resident.py + bench.resident_2d_smoke); this
    is the hardware half. One clean multi-device window therefore
    banks r12 alongside the carried r7-r11 backlog in one capture."""
    r = _run_bench_gated({"BENCH_MODE": "resident",
                          "BENCH_MESH_DAYS": "2",
                          "BENCH_METRIC_SUFFIX": "_2d",
                          "BENCH_STAGES": "0", "BENCH_LINK": "0"})
    if r.get("ok") and not any(
            _resident_2d_record_banks(rec)
            for rec in r.get("results") or []
            if isinstance(rec, dict)):
        r["ok"] = False
        r["error"] = ("no r12_resident_2d_v1 record with a 2-D "
                      "mesh_shape (d > 1 AND t > 1), per-axis mesh "
                      "watermarks, the result_wire block and an "
                      "available factor_health block — cannot bank")
    return r


def _resident_2d_record_banks(rec) -> bool:
    """A resident_2d record banks only when the scan genuinely ran
    2-D and carried its evidence: the declared ``r12_resident_2d_v1``
    methodology, a ``mesh_shape`` with d > 1 AND t > 1 (a 1-D
    fallback measures the r7/r10 loop, not the day pipeline), a
    ``mesh`` block whose per-axis watermarks cover BOTH axes (PR 9's
    instrument is what says whether the day pipeline balances), the
    ``result_wire`` block with ``enabled`` true, and an available
    ``factor_health`` block — the same silent-fallback-cannot-bank
    rule as every other step."""
    ms = rec.get("mesh_shape")
    mesh = rec.get("mesh")
    rw = rec.get("result_wire")
    fh = rec.get("factor_health")
    axes = (mesh or {}).get("axes") or {}
    return (rec.get("methodology") == "r12_resident_2d_v1"
            and isinstance(ms, (list, tuple)) and len(ms) == 2
            and all(isinstance(x, int) for x in ms)
            and ms[0] > 1 and ms[1] > 1
            and isinstance(mesh, dict) and mesh.get("available") is True
            and isinstance(axes.get("days"), dict)
            and isinstance(axes.get("tickers"), dict)
            and (axes["days"].get("shard_time_s") or {})
            and (axes["tickers"].get("shard_time_s") or {})
            and isinstance(rw, dict) and rw.get("enabled") is True
            and isinstance(fh, dict) and fh.get("available") is True)


def step_serve():
    """The r8 serving layer (ISSUE 6) on the chip: ``bench.py serve``
    load-generates against the in-process FactorServer at 1 and 32
    concurrent clients and banks p50/p99/QPS under the declared
    ``r8_serve_v1`` methodology. 256-client sweeps stay for dedicated
    windows (BENCH_SERVE_CLIENTS); the carry rule below rejects any
    record whose exposure cache never hit — a serve number that
    recomputed every request measures the batch engine, not the
    service. Since ISSUE 20 the step is a two-leg window at the same
    hardware: the in-process leg above plus the evented binary front
    door (BENCH_SERVE_TRANSPORT=edge, ``r15_serve_edge_v1``) — keep-
    alive HTTP load through the real edge, answers on the result wire.
    The window banks only when BOTH legs bank
    (:func:`_serve_record_banks` + :func:`_serve_edge_record_banks`):
    an edge leg with zero binary answers, HTTP failures, or a silent
    fallback off the edge transport measured the wrong door."""
    merged = {"ok": True, "rc": 0, "seconds": 0.0, "results": []}
    for leg, env_extra in (
            ("inproc", {"BENCH_SERVE_TRANSPORT": "inproc"}),
            ("edge", {"BENCH_SERVE_TRANSPORT": "edge"})):
        r = _run_json_lines(
            [sys.executable, "bench.py", "serve"], timeout=1800,
            env=dict(os.environ, BENCH_REQUIRE_TPU="1",
                     BENCH_SERVE_CLIENTS="1,32", **env_extra))
        merged["rc"] = r.get("rc", merged["rc"])
        merged["seconds"] = round(
            merged["seconds"] + (r.get("seconds") or 0.0), 1)
        merged["results"].extend(r.get("results") or [])
        if not r.get("ok"):
            merged["ok"] = False
            merged["error"] = f"serve {leg} leg failed"
            return merged
    recs = [rec for rec in merged["results"] if isinstance(rec, dict)]
    if any("_cpu_fallback" in str(rec.get("metric", ""))
           for rec in recs):
        merged["ok"] = False
        merged["error"] = "serve bench printed a CPU-fallback metric"
    elif not any(_serve_record_banks(rec) for rec in recs):
        merged["ok"] = False
        merged["error"] = ("no r8_serve_v1 record with cache hits > 0 "
                           "and a sampled slo block — a zero-hit or "
                           "unsampled serve run cannot bank")
    elif not any(_serve_edge_record_banks(rec) for rec in recs):
        merged["ok"] = False
        merged["error"] = ("edge leg unbankable: need an "
                           "r15_serve_edge_v1 record with "
                           "transport=edge and nonzero binary wire "
                           "answers, zero HTTP failures — cannot bank")
    return merged


def _serve_record_banks(rec) -> bool:
    """A serve record banks only when the service actually served warm:
    declared methodology AND exposure-cache hits > 0. Since ISSUE 8 the
    record must also carry the HBM watermark block (``hbm`` with its
    explicit ``available`` marker) — the banked serve trajectory is the
    series the ``<metric>.hbm_peak_bytes`` regress gate reads, so a
    record without watermarks is a telemetry regression, not a bankable
    measurement. Since ISSUE 16 the record must ALSO embed a non-empty
    ``slo`` block with ``frames > 0`` (the timeline sampler genuinely
    ran): the banked trajectory feeds the ``<metric>.burn_rate_max``
    regress series, and a record whose SLO plane never sampled carries
    no burn evidence — same contract as the watermark rule."""
    serve = rec.get("serve") or {}
    hbm = rec.get("hbm")
    slo = rec.get("slo")
    return (rec.get("methodology") == "r8_serve_v1"
            and isinstance(serve.get("cache_hits"), int)
            and serve["cache_hits"] > 0
            and isinstance(hbm, dict) and "available" in hbm
            and isinstance(slo, dict)
            and isinstance(slo.get("frames"), int)
            and slo["frames"] > 0)


def _serve_edge_record_banks(rec) -> bool:
    """ISSUE 20: the edge leg banks only when the evented front door
    genuinely answered on the binary wire: declared
    ``r15_serve_edge_v1`` methodology with ``transport == 'edge'``
    (the stdlib A/B leg stamps ``+transport=legacy`` and may never
    bank as the edge), an AVAILABLE ``edge`` block whose
    ``wire_answers`` is a nonzero int (zero binary answers means the
    load generators never decoded a frame — the number measured
    request plumbing, not the wire), and zero HTTP failures (a leg
    that errored requests into its p99 is not a serving measurement).
    The banked edge trajectory is the series the
    ``<metric>.wire_bytes_per_answer`` regress gate reads."""
    edge = rec.get("edge")
    return (rec.get("methodology") == "r15_serve_edge_v1"
            and rec.get("transport") == "edge"
            and isinstance(edge, dict)
            and edge.get("available") is True
            and isinstance(edge.get("wire_answers"), int)
            and not isinstance(edge.get("wire_answers"), bool)
            and edge["wire_answers"] > 0
            and edge.get("http_failures") == 0)


def step_stream_intraday():
    """The r9 online intraday engine (ISSUE 7) on the chip: ``bench.py
    stream`` ingest-loads the streaming carry at the declared cohort
    shapes (1/8/64 tickers per update) and banks bars/sec + per-update
    p50/p99 under ``r9_stream_intraday_v1``, with the on-hardware
    streamed-vs-full-day parity verdict riding the record. Since ISSUE
    18 the step is an exact/fast A/B at the SAME hardware window: the
    r9 load runs once under each ``finalize_impl`` (MFF_FINALIZE_IMPL)
    plus the r14 snapshot-per-bar profile under the fast impl — the
    per-bar histogram whose flatness IS the O(1)-finalize evidence on
    the chip. The carry rule (:func:`_stream_record_banks` +
    :func:`_stream_fast_record_banks`) rejects windows with zero
    streamed updates, any load-phase compile, a parity mismatch, a
    fast leg that silently resolved to exact, or a missing/cold
    per-bar histogram."""
    merged = {"ok": True, "rc": 0, "seconds": 0.0, "results": []}
    for leg, env_extra in (
            ("exact", {"MFF_FINALIZE_IMPL": "exact"}),
            ("fast", {"MFF_FINALIZE_IMPL": "fast"}),
            ("fast_profile", {"BENCH_STREAM_SNAPSHOT_PER_BAR": "fast"})):
        r = _run_json_lines(
            [sys.executable, "bench.py", "stream"], timeout=1800,
            env=dict(os.environ, BENCH_REQUIRE_TPU="1", **env_extra))
        merged["rc"] = r.get("rc", merged["rc"])
        merged["seconds"] = round(
            merged["seconds"] + (r.get("seconds") or 0.0), 1)
        merged["results"].extend(r.get("results") or [])
        if not r.get("ok"):
            merged["ok"] = False
            merged["error"] = f"stream {leg} leg failed"
            return merged
    recs = [rec for rec in merged["results"] if isinstance(rec, dict)]
    if any("_cpu_fallback" in str(rec.get("metric", ""))
           for rec in recs):
        merged["ok"] = False
        merged["error"] = "stream bench printed a CPU-fallback metric"
    elif not any(_stream_record_banks(rec) for rec in recs):
        merged["ok"] = False
        merged["error"] = ("no r9_stream_intraday_v1 record with "
                           "updates > 0, zero load compiles and clean "
                           "parity — cannot bank")
    elif not _stream_fast_record_banks(recs):
        merged["ok"] = False
        merged["error"] = ("fast-finalize A/B leg unbankable: need an "
                           "r9 record RESOLVED to finalize_impl='fast' "
                           "with a green parity verdict AND an "
                           "available r14 per-bar snapshot histogram")
    return merged


def _stream_record_banks(rec) -> bool:
    """A stream record banks only when the engine actually streamed
    warm and faithfully: declared methodology, streamed updates > 0,
    no compiles during load, empty parity-mismatch list — and, since
    ISSUE 8, the embedded HBM watermark block (same rationale as
    :func:`_serve_record_banks`), and, since ISSUE 9, the ``mesh``
    balance block (cohort-occupancy telemetry: a record with no
    shard-balance telemetry cannot bank), and, since ISSUE 12, an
    AVAILABLE ``factor_health`` block (the fused stats + readiness-lag
    sample of the end-of-load snapshot — the stream's data-quality
    evidence feeds the ``<metric>.coverage_frac`` regress series)."""
    stream = rec.get("stream") or {}
    hbm = rec.get("hbm")
    fh = rec.get("factor_health")
    return (rec.get("methodology") == "r9_stream_intraday_v1"
            and isinstance(stream.get("updates"), int)
            and stream["updates"] > 0
            and stream.get("compiles_during_load") == 0
            and stream.get("parity_mismatched") == []
            and isinstance(hbm, dict) and "available" in hbm
            and isinstance(rec.get("mesh"), dict)
            and isinstance(fh, dict)
            and fh.get("available") is True)


def _stream_fast_record_banks(recs) -> bool:
    """ISSUE 18: the fast-finalize A/B leg banks only when the SAME
    window produced (a) an r9 record that actually RESOLVED to the
    fast impl (``finalize_impl == 'fast'`` — a requested-fast engine
    silently degrading to exact must re-run, not bank as a fast
    number) with a green three-class parity verdict and the full warm
    contract of :func:`_stream_record_banks`, and (b) the r14
    snapshot-per-bar profile whose histogram is PRESENT and available
    (warm, enough bars) — a fast throughput number without its
    per-bar flatness evidence proves nothing about the O(1) finalize
    claim."""
    fast_r9 = any(_stream_record_banks(rec)
                  and rec.get("finalize_impl") == "fast"
                  for rec in recs if isinstance(rec, dict))
    hist = any(
        rec.get("methodology") == "r14_stream_snapshot_v1"
        and rec.get("finalize_impl") == "fast"
        and isinstance(rec.get("snapshot"), dict)
        and rec["snapshot"].get("available") is True
        for rec in recs if isinstance(rec, dict))
    return fast_r9 and hist


def step_fleet():
    """The r11 replica fleet (ISSUE 11) on the chip: ``bench.py
    fleet`` load-generates against N FactorServer replicas over
    disjoint device submeshes behind the coalescing-affinity router at
    64/512 simulated clients and banks per-replica-count p50/p99/QPS
    under the declared ``r11_fleet_v1`` methodology (2048-client
    sweeps stay for dedicated windows via BENCH_FLEET_CLIENTS). The
    carry rule (:func:`_fleet_record_banks`) rejects records with
    fewer than 2 live replicas (a single-chip window cannot validate
    the fleet — it fails loudly and re-runs, like resident_sharded) or
    a missing pod ``hbm`` block. Since ISSUE 20 the step is a two-leg
    window like serve: the in-process leg plus the pod's evented
    binary front door (BENCH_FLEET_TRANSPORT=edge,
    ``r15_fleet_edge_v1``) — the router's replica hop carries the
    result wire, counted by ``fleet.routed_wire``. The window banks
    only when BOTH legs bank (:func:`_fleet_record_banks` +
    :func:`_fleet_edge_record_banks`)."""
    merged = {"ok": True, "rc": 0, "seconds": 0.0, "results": []}
    for leg, env_extra in (
            ("inproc", {"BENCH_FLEET_TRANSPORT": "inproc"}),
            ("edge", {"BENCH_FLEET_TRANSPORT": "edge"})):
        r = _run_json_lines(
            [sys.executable, "bench.py", "fleet"], timeout=1800,
            env=dict(os.environ, BENCH_REQUIRE_TPU="1",
                     BENCH_FLEET_CLIENTS="64,512", **env_extra))
        merged["rc"] = r.get("rc", merged["rc"])
        merged["seconds"] = round(
            merged["seconds"] + (r.get("seconds") or 0.0), 1)
        merged["results"].extend(r.get("results") or [])
        if not r.get("ok"):
            merged["ok"] = False
            merged["error"] = f"fleet {leg} leg failed"
            return merged
    recs = [rec for rec in merged["results"] if isinstance(rec, dict)]
    if any("_cpu_fallback" in str(rec.get("metric", ""))
           for rec in recs):
        merged["ok"] = False
        merged["error"] = "fleet bench printed a CPU-fallback metric"
    elif not any(_fleet_record_banks(rec) for rec in recs):
        merged["ok"] = False
        merged["error"] = ("no r11_fleet_v1 record with >= 2 live "
                           "replicas, a pod hbm block, the pod "
                           "counter fold and a sampled slo block — "
                           "cannot bank")
    elif not any(_fleet_edge_record_banks(rec) for rec in recs):
        merged["ok"] = False
        merged["error"] = ("edge leg unbankable: need an "
                           "r15_fleet_edge_v1 record with "
                           "transport=edge, nonzero binary wire "
                           "answers and a wire-carrying routed "
                           "replica hop — cannot bank")
    return merged


def _fleet_record_banks(rec) -> bool:
    """A fleet record banks only when the pod actually multiplied the
    service and carried its evidence: declared methodology,
    ``live_replicas >= 2`` (one replica measures serve, not the
    fleet), the pod HBM watermark block (the degrade policy's input —
    same rationale as :func:`_serve_record_banks`), and the pod
    counter-fold block (the PR 9 exact-merge contract, re-verified in
    the record, with zero mismatches). Since ISSUE 16 the record must
    ALSO embed a non-empty pod ``slo`` block with ``frames > 0`` — the
    control-plane timeline/burn evidence the ``<metric>.burn_rate_max``
    regress series reads (same rationale as :func:`_serve_record_banks`
    )."""
    hbm = rec.get("hbm")
    pod = rec.get("pod")
    slo = rec.get("slo")
    return (rec.get("methodology") == "r11_fleet_v1"
            and isinstance(rec.get("live_replicas"), int)
            and rec["live_replicas"] >= 2
            and isinstance(hbm, dict) and "available" in hbm
            and isinstance(pod, dict)
            and isinstance(pod.get("counter_totals"), dict)
            and pod["counter_totals"].get("mismatched") == 0
            and isinstance(slo, dict)
            and isinstance(slo.get("frames"), int)
            and slo["frames"] > 0)


def _fleet_edge_record_banks(rec) -> bool:
    """ISSUE 20, the fleet twin of :func:`_serve_edge_record_banks`:
    declared ``r15_fleet_edge_v1`` with ``transport == 'edge'``, an
    AVAILABLE ``edge`` block with nonzero int ``wire_answers`` and
    zero HTTP failures, AND a nonzero ``routed_wire`` — the router's
    replica hop must have carried the binary wire (a pod that answered
    binary at the door but double-encoded JSON internally is exactly
    the regression this leg exists to catch)."""
    edge = rec.get("edge")
    return (rec.get("methodology") == "r15_fleet_edge_v1"
            and rec.get("transport") == "edge"
            and isinstance(edge, dict)
            and edge.get("available") is True
            and isinstance(edge.get("wire_answers"), int)
            and not isinstance(edge.get("wire_answers"), bool)
            and edge["wire_answers"] > 0
            and edge.get("http_failures") == 0
            and isinstance(edge.get("routed_wire"), int)
            and not isinstance(edge.get("routed_wire"), bool)
            and edge["routed_wire"] > 0)


def step_discover():
    """The r13 factor-discovery engine (ISSUE 14) on the chip:
    ``bench.py discover`` runs the bounded evolutionary search at the
    first two declared population levels and banks candidates/sec +
    per-generation p50/p99 under ``r13_discover_v1`` (8192-candidate
    sweeps stay for dedicated windows via BENCH_DISCOVER_POP). The
    carry rule (:func:`_discover_record_banks`) refuses records with
    zero completed generations, any compile during the generation
    loop, or more than one measured host-blocking sync per generation
    — a cold or chatty loop measures dispatch overhead, not the
    engine."""
    r = _run_json_lines(
        [sys.executable, "bench.py", "discover"], timeout=1800,
        env=dict(os.environ, BENCH_REQUIRE_TPU="1",
                 BENCH_DISCOVER_POP="512,2048"))
    if r.get("ok"):
        recs = [rec for rec in r.get("results") or []
                if isinstance(rec, dict)]
        if any("_cpu_fallback" in str(rec.get("metric", ""))
               for rec in recs):
            r["ok"] = False
            r["error"] = "discover bench printed a CPU-fallback metric"
        elif not any(_discover_record_banks(rec) for rec in recs):
            r["ok"] = False
            r["error"] = ("no r13_discover_v1 record with completed "
                          "generations, a warm loop and <= 1 sync per "
                          "generation — cannot bank")
    return r


def _discover_record_banks(rec) -> bool:
    """A discover record banks only when the generation loop really
    ran warm and inside its sync budget: declared methodology,
    ``generations > 0`` (zero completed generations measured
    nothing), ``compiles_during_loop == 0`` (a compile inside the
    loop means the fitness executable was cold — the number measures
    XLA, not the engine), and ``syncs_per_generation <= 1`` (the
    loop's whole point is ONE labeled host sync per generation; a
    chattier loop is the r5-era round-trip regression). The ``hbm``
    watermark block rides along like every post-ISSUE-8 carry."""
    d = rec.get("discover")
    hbm = rec.get("hbm")
    return (rec.get("methodology") == "r13_discover_v1"
            and isinstance(d, dict)
            and isinstance(d.get("generations"), int)
            and d["generations"] > 0
            and d.get("compiles_during_loop") == 0
            and isinstance(d.get("syncs_per_generation"), (int, float))
            and not isinstance(d.get("syncs_per_generation"), bool)
            and d["syncs_per_generation"] <= 1
            and isinstance(hbm, dict) and "available" in hbm)


def step_ladder():
    return _run_json_lines(
        [sys.executable, "benchmarks/ladder.py", "--configs", "1,2,4,5"],
        timeout=1800)


def _step_ladder_one(cfg):
    """One BASELINE config per step (VERDICT r3 #5): the r3 window died
    at cfg2's compile and took cfg4/cfg5 down with it because all four
    configs shared one child. Per-config steps bank and retry
    independently."""
    def step():
        return _run_json_lines(
            [sys.executable, "benchmarks/ladder.py", "--configs", cfg],
            timeout=900)
    return step


def step_pipeline():
    """The REAL pipeline end to end (VERDICT r3 #2): 244 on-disk day
    files through compute_exposures — io + grid + encode + device +
    materialize + cache save — not the synthetic pre-gridded loop. The
    dataset is generated once and reused across fires; the step needs a
    long window, so it runs last in the default order."""
    return _run_json_lines(
        [sys.executable, "benchmarks/real_pipeline.py"],
        timeout=2700, env=dict(os.environ, BENCH_REQUIRE_TPU="1"))


def step_sweep():
    """DAYS_PER_BATCH sweep (benchmarks/sweep_batch.py); in the default
    list but near the end — it informs the next round's loop shape
    rather than banking a headline."""
    return _run_json_lines([sys.executable, "benchmarks/sweep_batch.py"],
                           timeout=1800)


def step_link():
    """Transfer diagnostics (benchmarks/transfer_probe.py --json):
    dedupe check, both-direction bandwidth, and the per-transfer
    latency floor — the number that decides whether the headline's
    unaccounted ~3.7 s/batch is dispatch latency (bigger batches fix
    it) or mid-loop bandwidth sag (they don't). ~1 min; cheapest
    first-class evidence a short window can bank."""
    return _run_json_lines(
        [sys.executable, "benchmarks/transfer_probe.py", "28", "--json"],
        timeout=600)


def step_graph_spotcheck():
    """Full 58-kernel fused graph on the chip vs the CPU oracle, using
    the parity suite's FULL comparator protocol (tolerance matrix,
    doc_pdf tie acceptance, degenerate-beta skips) — a hand-rolled
    comparison here would false-alarm on cells the suite deliberately
    accepts and burn the tunnel window. Body in a killable child (see
    step_rolling)."""
    return _run_one_step_child("spot")


def _spot_body():
    import time as _t

    import jax
    import numpy as np

    from replication_of_minute_frequency_factor_tpu.data import synth_day
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names)

    sys.path.insert(0, os.path.join(REPO, "tests"))
    import test_parity as tp

    rng = np.random.default_rng(1)
    day = synth_day(rng, n_codes=32, missing_prob=0.05,
                    zero_volume_prob=0.05)
    t0 = _t.perf_counter()
    tp._compare(day, "tpu_spot", noisy=True)  # raises on mismatch
    wall = _t.perf_counter() - t0
    return {"ok": True, "results": [{
        "platform": jax.devices()[0].platform,
        "compare_wall_s": round(wall, 1),
        "factors": len(factor_names()), "codes": 32}]}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=os.path.join(
        REPO, "benchmarks", "TPU_SESSION.json"))
    ap.add_argument("--skip-probe", action="store_true")
    # value-per-second order for a window that may close any minute:
    # the headline (the round's one must-have), the 1-minute link
    # diagnostics, the stream-loop series continuation, then the
    # four ladder configs cheapest-first, parity spot-check, the
    # batch-size sweep, and the long real-pipeline run last
    # pallas rides directly behind the headline: the conv-vs-pallas A/B
    # is only meaningful inside ONE window, and the kernel's hardware
    # validation is this round's must-bank evidence (ISSUE 3)
    # resident_sharded rides directly behind the headline: the r7
    # sharded scan's hardware validation is this round's must-bank
    # evidence, and it only banks when the mesh really resolved to
    # multiple devices (ISSUE 5)
    # serve rides behind the stream continuation: the r8 serving layer's
    # hardware p50/p99/QPS is this round's must-bank evidence (ISSUE 6),
    # but the headline/link/stream trio still buys the most
    # comparability per second of window
    # stream_intraday rides directly behind serve: the r9 online
    # intraday engine's hardware bars/sec + on-chip streamed parity is
    # this round's must-bank evidence (ISSUE 7)
    # fleet rides directly behind stream_intraday: the r11 replica
    # fleet's hardware p50/p99/QPS per replica count is this round's
    # must-bank evidence (ISSUE 11) — and it only banks when at least
    # two replicas actually served (a single-chip window cannot)
    # resident_2d rides directly behind resident_sharded: the r12 2-D
    # pipelined scan's hardware validation is this round's must-bank
    # evidence (ISSUE 13), and it only banks when the mesh genuinely
    # resolved to d > 1 AND t > 1 (>= 4 devices)
    # discover rides directly behind fleet: the r13 discovery engine's
    # hardware candidates/sec is this round's must-bank evidence
    # (ISSUE 14), and its carry rule refuses cold or chatty loops
    ap.add_argument("--steps", default="headline,resident_sharded,"
                    "resident_2d,pallas,link,stream,"
                    "serve,stream_intraday,fleet,discover,"
                    "lad1,lad2,lad4,lad5,spot,sweep,pipeline")
    ap.add_argument("--one-step", default=None,
                    help="internal: run one step's body in-process and "
                         "print its result dict as the final JSON line "
                         "(the parent wraps this in a killable child)")
    ap.add_argument("--max-carry-age-hours", type=float, default=12.0,
                    help="only carry green steps over from a prior "
                         "artifact younger than this (~one round)")
    args = ap.parse_args()

    if args.one_step:
        # the cache is applied via in-process jax.config.update, so the
        # inherited MFF_COMPILATION_CACHE_DIR env var alone does nothing
        # — without this call the spot body re-pays the ~20-40 s
        # 58-graph compile inside every tunnel up-window
        from replication_of_minute_frequency_factor_tpu.config import (
            apply_compilation_cache, get_config)
        os.environ.setdefault("MFF_COMPILATION_CACHE_DIR",
                              os.path.join(REPO, ".xla_cache"))
        apply_compilation_cache(get_config())
        body = {"spot": _spot_body}[args.one_step]
        result = body()
        # same race step_headline guards against: the pre-step probe saw
        # a TPU, the backend then failed FAST (not wedged) and jax fell
        # back to CPU with only a warning — rolling would time CPU and
        # spot would compare the CPU oracle against itself. A green
        # carried-over step is never re-run, so this must fail here.
        # TPU_SESSION_ALLOW_CPU is the local-testing escape hatch.
        if result.get("ok") \
                and not os.environ.get("TPU_SESSION_ALLOW_CPU"):
            import jax
            if jax.devices()[0].platform == "cpu":
                result = {"ok": False,
                          "error": "jax resolved to CPU inside the "
                                   "one-step child; refusing to bank a "
                                   "non-TPU result", "had": result}
        print(json.dumps(result), flush=True)
        return 0

    session = {"started_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                            time.gmtime()),
               # stamped by tunnel_watch so a capture that raced CPU-heavy
               # work is identifiable in the artifact itself (1-core host);
               # real JSON bool/null so `if session["host_quiet"]` works
               "host_quiet": (
                   None if "TPU_SESSION_HOST_QUIET" not in os.environ
                   else os.environ["TPU_SESSION_HOST_QUIET"] == "True"),
               "steps": {}}
    session["steps"].update(drop_conv_only_rolling(
        carry_green_steps(args.out, args.max_carry_age_hours)))
    if not args.skip_probe and not _probe():
        session["steps"]["probe"] = {"ok": False,
                                     "error": "tunnel unreachable"}
        with open(args.out, "w") as fh:  # keeps carried-over green steps
            json.dump(session, fh, indent=1)
        print(json.dumps(session))
        return 1

    os.environ.setdefault("MFF_COMPILATION_CACHE_DIR",
                          os.path.join(REPO, ".xla_cache"))
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    apply_compilation_cache(get_config())
    steps = {"headline": step_headline, "ladder": step_ladder,
             "spot": step_graph_spotcheck, "sweep": step_sweep,
             "link": step_link, "pipeline": step_pipeline,
             "stream": step_stream, "pallas": step_pallas,
             "resident_sharded": step_resident_sharded,
             "resident_2d": step_resident_2d,
             "serve": step_serve,
             "stream_intraday": step_stream_intraday,
             "fleet": step_fleet,
             "discover": step_discover,
             "lad1": _step_ladder_one("1"), "lad2": _step_ladder_one("2"),
             "lad4": _step_ladder_one("4"), "lad5": _step_ladder_one("5")}
    want = [s.strip() for s in args.steps.split(",") if s.strip()]
    # session-level telemetry rides the same registry subsystem as
    # bench.py/the pipeline (step durations as histograms, outcomes as
    # labeled counters) and is embedded in the artifact, so the session
    # series cannot drift from the in-run telemetry schema
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry)
    tel = Telemetry(annotate_spans=False)
    for name in want:
        if session["steps"].get(name, {}).get("ok"):
            print(f"--- step: {name} (already green, carried over)",
                  flush=True)
            tel.counter("session.steps", outcome="carried")
            continue
        # Re-probe before every step: the tunnel drops mid-session
        # (observed 2026-08-01: up-window closed between headline and
        # ladder), and failing the step in 90 s beats burning the
        # child's full timeout against a dead link.
        if not args.skip_probe and not _probe():
            session["steps"][name] = {
                "ok": False, "error": "tunnel unreachable at step start"}
            tel.counter("session.steps", outcome="unreachable")
            session["telemetry"] = tel.registry.snapshot()
            with open(args.out, "w") as fh:
                json.dump(session, fh, indent=1)
            print(json.dumps({name: False}), flush=True)
            continue
        print(f"--- step: {name}", flush=True)
        t_step = time.monotonic()
        try:
            session["steps"][name] = steps[name]()
        except Exception as e:  # keep capturing the rest of the window
            import traceback
            session["steps"][name] = {
                "ok": False, "error": f"{type(e).__name__}: {e}",
                "trace": traceback.format_exc()[-1500:]}
        tel.observe("session.step_seconds",
                    round(time.monotonic() - t_step, 1), step=name)
        tel.counter("session.steps",
                    outcome="ok" if session["steps"][name].get("ok")
                    else "failed")
        # per-step freshness stamp — what the carry-over bound ages
        session["steps"][name]["captured_utc"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        session["telemetry"] = tel.registry.snapshot()
        with open(args.out, "w") as fh:  # persist after EVERY step
            json.dump(session, fh, indent=1)
        print(json.dumps({name: session["steps"][name].get("ok")}),
              flush=True)
    # regression gate (telemetry.regress) over the repo's banked
    # BENCH_r*.json series, embedded in the artifact: a series drift
    # ships WITH the numbers it flags instead of waiting for a human
    # to eyeball the trajectory next round
    gate = _run_json_lines(
        [sys.executable, "-m",
         "replication_of_minute_frequency_factor_tpu.telemetry.regress",
         REPO], timeout=120)
    recs = [r for r in gate.get("results") or [] if isinstance(r, dict)]
    session["regress"] = recs[-1] if recs else {
        "ok": False, "error": gate.get("error") or gate.get("tail")}
    with open(args.out, "w") as fh:
        json.dump(session, fh, indent=1)
    oks = {k: v.get("ok") for k, v in session["steps"].items()}
    print(json.dumps({"session_done": oks}))
    return 0 if all(oks.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
