"""Sweep DAYS_PER_BATCH on the real TPU to find the best bench config."""
import sys, time, json
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax
import bench
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.pipeline import compute_packed_prepared
from replication_of_minute_frequency_factor_tpu.models.registry import factor_names
from replication_of_minute_frequency_factor_tpu.config import apply_compilation_cache, get_config

apply_compilation_cache(get_config())  # persistent XLA cache when configured

names = factor_names()
# Bracket the headline's new default (bench.py D=32): D=8 re-measures
# the r3 loop shape under the SAME link weather — separating what the
# round-4 reshape bought from what the tunnel's mood bought — and D=61
# (exactly 4 batches/trading year) probes the aggressive end, where the
# decoded grid + rolling-loop carries approach the 16 GB chip's limit.
# Each point is exception-isolated: a D=61 RESOURCE_EXHAUSTED must
# report as a data point, not kill the step (the sweep's whole job is
# mapping where the curve ends). The 2026-08-01 headline showed
# ~4.8 s/batch against ~0.7 s of bandwidth+compute at probe rates —
# per-dispatch-latency-bound, and latency amortizes with batch size.
for D in (8, 61):
    rng = np.random.default_rng(0)
    ITERS = max(3, 32 // D)  # amortize over >= 32 days per config
    # distinct bytes every iteration (incl. warmup) so transfer-path
    # content caching cannot flatter the number — see bench.py
    def ep(b, m):
        w = wire.encode(b, m)
        return wire.pack_arrays(w.arrays) + ("wire",)
    def launch(item):
        buf, spec, kind = item
        return compute_packed_prepared(buf, spec, kind, names=names, replicate_quirks=True)
    q = None
    try:
        batches = [bench.make_batch(rng, n_days=D) for _ in range(ITERS + 1)]
        t0=time.perf_counter(); jax.block_until_ready(launch(ep(*batches[ITERS]))); warm=time.perf_counter()-t0
        import queue, threading
        q = queue.Queue(maxsize=2)
        def produce():
            for i in range(ITERS): q.put(ep(*batches[i]))
        t0=time.perf_counter(); threading.Thread(target=produce, daemon=True).start()
        outs=[]
        for i in range(ITERS):
            out = launch(q.get())
            out.copy_to_host_async()  # mirror bench.py: results cross the link too
            outs.append(out)
            if i >= 2: np.asarray(outs[i-2])
        for o in outs[-2:]: np.asarray(o)
        per = (time.perf_counter()-t0)/ITERS
        print(json.dumps({"days": D, "per_batch_s": round(per,3),
                          "full_year_s": round(per*244/D,3), "warm_s": round(warm,1)}), flush=True)
        del batches, outs
    except Exception as e:  # noqa: BLE001 — per-point isolation
        print(json.dumps({"days": D, "error": f"{type(e).__name__}: "
                          + str(e)[:300]}), flush=True)
        # release the failed point's memory before the next point (61
        # days, the curve's memory-limit probe, must not inherit it):
        # clear this scope's device/host refs, then unblock the
        # producer thread parked in q.put() so its closure (encoded
        # batches + the `batches` list) can exit and free
        batches = outs = out = None  # noqa: F841
        if q is not None:
            try:
                for _ in range(ITERS):
                    q.get(timeout=5)
            except Exception:  # queue drained / producer already done
                pass
