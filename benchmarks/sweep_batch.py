"""Sweep DAYS_PER_BATCH on the real TPU to find the best bench config."""
import sys, time, json
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np, jax
import bench
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.pipeline import compute_packed_prepared
from replication_of_minute_frequency_factor_tpu.models.registry import factor_names
from replication_of_minute_frequency_factor_tpu.config import apply_compilation_cache, get_config

apply_compilation_cache(get_config())  # persistent XLA cache when configured

names = factor_names()
# D=8 is what the headline itself measures (bench.py) — no need to
# re-time it here, and D=16 is dominated either way (if latency-bound,
# 32/61 amortize more; if bandwidth-bound, all D are equal), so two
# points keep the sweep inside a short window (each D pays its own
# ~40 s TPU compile). The large end matters most: the 2026-08-01
# headline showed ~4.8 s/batch against ~0.7 s of bandwidth+compute at
# probe rates, i.e. the pipeline looks per-dispatch-latency-bound over
# the tunnel, and latency amortizes linearly with batch size. 61 days =
# exactly 4 batches per trading year (244/61); decoded grid at D=61 is
# ~1.5 GB f32 in HBM — comfortable on a 16 GB chip.
for D in (32, 61):
    rng = np.random.default_rng(0)
    ITERS = max(3, 32 // D)  # amortize over >= 32 days per config
    # distinct bytes every iteration (incl. warmup) so transfer-path
    # content caching cannot flatter the number — see bench.py
    batches = [bench.make_batch(rng, n_days=D) for _ in range(ITERS + 1)]
    def ep(b, m):
        w = wire.encode(b, m)
        return wire.pack_arrays(w.arrays) + ("wire",)
    def launch(item):
        buf, spec, kind = item
        return compute_packed_prepared(buf, spec, kind, names=names, replicate_quirks=True)
    t0=time.perf_counter(); jax.block_until_ready(launch(ep(*batches[ITERS]))); warm=time.perf_counter()-t0
    import queue, threading
    q = queue.Queue(maxsize=2)
    def produce():
        for i in range(ITERS): q.put(ep(*batches[i]))
    t0=time.perf_counter(); threading.Thread(target=produce, daemon=True).start()
    outs=[]
    for i in range(ITERS):
        out = launch(q.get())
        out.copy_to_host_async()  # mirror bench.py: results cross the link too
        outs.append(out)
        if i >= 2: np.asarray(outs[i-2])
    for o in outs[-2:]: np.asarray(o)
    per = (time.perf_counter()-t0)/ITERS
    print(json.dumps({"days": D, "per_batch_s": round(per,3),
                      "full_year_s": round(per*244/D,3), "warm_s": round(warm,1)}))
