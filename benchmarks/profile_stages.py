"""Per-stage timing of the headline bench loop on the attached device.

Breaks one pipeline step into host encode / pack / device_put / fused
compute, then times the double-buffered loop itself — the gap between
sum-of-stages and measured per-batch is transfer/round-trip overhead the
tunnel adds under load (what the single-buffer path exists to minimize).

Run on the TPU:  python benchmarks/profile_stages.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402
import numpy as np  # noqa: E402

from bench import make_batch  # noqa: E402
from replication_of_minute_frequency_factor_tpu.data import wire  # noqa: E402
from replication_of_minute_frequency_factor_tpu.models.registry import (  # noqa: E402
    factor_names)
from replication_of_minute_frequency_factor_tpu.pipeline import (  # noqa: E402
    _compute_packed_jit)


def main():
    rng = np.random.default_rng(0)
    names = factor_names()
    N = 5
    # every timed transfer ships DISTINCT bytes (separate sets for the
    # put stage, the put+compute stage, and the loop below), so a
    # content-addressed cache anywhere in the transfer path cannot
    # flatter a stage — see bench.py
    batches = [make_batch(rng, n_days=8) for _ in range(N)]
    comp_packed = [wire.pack_arrays(wire.encode(*make_batch(rng, n_days=8)).arrays)
                   for _ in range(N)]

    # warm (compile + first transfers) — its own batch
    w = wire.encode(*make_batch(rng, n_days=8))
    buf, spec = wire.pack_arrays(w.arrays)
    out = _compute_packed_jit(jax.device_put(buf), spec, "wire", names,
                              True, "conv")
    jax.block_until_ready(out)

    def best(f, items):
        """Run f over items, return (min seconds, [f(it) results])."""
        ts, rs = [], []
        for it in items:
            t0 = time.perf_counter()
            r = f(it)
            if r is not None:
                jax.block_until_ready(r)
            ts.append(time.perf_counter() - t0)
            rs.append(r)
        return min(ts), rs

    # the encode/pack timing passes double as the construction of the
    # next stage's inputs — each batch is encoded and packed exactly once
    enc, wires = best(lambda bm: wire.encode(*bm), batches)
    pack, packed = best(lambda wi: wire.pack_arrays(wi.arrays), wires)
    put, _ = best(lambda p: jax.device_put(p[0]), packed)
    comp, _ = best(lambda p: _compute_packed_jit(jax.device_put(p[0]), p[1],
                                                 "wire", names, True,
                                                 "conv"),
                   comp_packed)
    print(f"stages: encode {enc*1e3:.0f}ms  pack {pack*1e3:.0f}ms  "
          f"put {put*1e3:.0f}ms  put+compute {comp*1e3:.0f}ms  "
          f"wire {buf.nbytes/1e6:.1f}MB")

    # the measured double-buffered loop, per-iteration breakdown
    import queue
    import threading
    q: "queue.Queue" = queue.Queue(maxsize=2)
    ITERS = 5

    del batches, wires, packed, comp_packed  # stage-timing data is dead
    loop_batches = [make_batch(rng, n_days=8) for _ in range(ITERS)]

    def produce():
        for i in range(ITERS):
            wi = wire.encode(*loop_batches[i])
            q.put(wire.pack_arrays(wi.arrays))

    t0 = time.perf_counter()
    threading.Thread(target=produce, daemon=True).start()
    outs = []
    for i in range(ITERS):
        ta = time.perf_counter()
        bi, si = q.get()
        tb = time.perf_counter()
        outs.append(_compute_packed_jit(jax.device_put(bi), si, "wire",
                                        names, True, "conv"))
        tc = time.perf_counter()
        if i >= 2:
            jax.block_until_ready(outs[i - 2])
        td = time.perf_counter()
        print(f"iter {i}: queue-wait {1e3*(tb-ta):.0f}ms  "
              f"dispatch {1e3*(tc-tb):.0f}ms  block {1e3*(td-tc):.0f}ms")
    jax.block_until_ready(outs)
    print(f"loop per-batch: {(time.perf_counter()-t0)/ITERS*1e3:.0f}ms")


if __name__ == "__main__":
    main()
