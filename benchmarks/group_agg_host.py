"""Measure the HOST-side period aggregation inside ``group_test``.

VERDICT r3 weak #6 flagged that ``factor.group_test`` does its period
compounding/lagging on host numpy while SURVEY §3.3 sketched an
on-device segmented form, and asked for either the device version or a
measured "host is fine to N x" row (VERDICT r3 #7). This times the REAL
code — :func:`factor.aggregate_period_returns`, the exact function
group_test calls (factored out so this benchmark cannot drift from the
production path) — at the 5-year x 5000-ticker scale of BASELINE
config 4, so the decision is a number, not a guess:

    python benchmarks/group_agg_host.py

Prints one JSON line with seconds per factor and the implied wall time
of a 58-factor sweep; docs/DESIGN.md records the verdict.
"""

import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from replication_of_minute_frequency_factor_tpu.factor import (  # noqa: E402
    aggregate_period_returns)

N_DATES = 1220          # 5 trading years
N_CODES = 5000
GROUPS = 10
REPS = 3


def make_inputs(rng):
    """Synthesized OUTSIDE the timed region: group_test's inputs already
    exist when its host section runs, so ~18M RNG draws per rep would
    inflate the number this script exists to settle."""
    present = rng.random((N_DATES, N_CODES)) > 0.05
    pv_present = rng.random((N_DATES, N_CODES)) > 0.03
    pct = rng.standard_normal((N_DATES, N_CODES)) * 0.02
    labels = rng.integers(0, GROUPS, (N_DATES, N_CODES))
    return labels, present, pv_present, pct


def main():
    rng = np.random.default_rng(0)
    dates = (np.datetime64("2020-01-01")
             + np.arange(N_DATES).astype("timedelta64[D]"))

    # distinct pre-built inputs per rep (a factor sweep aggregates a
    # different exposure matrix each time), built before the clock
    inputs = [make_inputs(rng) for _ in range(REPS + 1)]

    def run(i):
        labels, present, pv_present, pct = inputs[i]
        return aggregate_period_returns(
            labels, present, pv_present, pct, dates, "month", GROUPS)

    run(REPS)  # warm allocator/caches
    t0 = time.perf_counter()
    for r in range(REPS):
        run(r)
    per_factor = (time.perf_counter() - t0) / REPS
    print(json.dumps({
        "metric": "group_test_host_agg_5yr_5000tkr_per_factor",
        "value": round(per_factor, 4),
        "unit": "s",
        "dates": N_DATES, "codes": N_CODES, "groups": GROUPS,
        "implied_58_factor_sweep_s": round(per_factor * 58, 2),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
