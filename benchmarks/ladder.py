"""Benchmark ladder — the five configs of BASELINE.json:6-12, one JSON
line each (the headline config 3 lives in the repo-root ``bench.py``,
which the driver runs; this script reports the full ladder).

  1. realized-volatility factor (vol_return1min), 50 tickers x 1 day —
     reference-semantics CPU oracle path vs the jit path
  2. full CICC handbook (58 kernels), 500 tickers x 1 month
  3. full A-share universe, 5000 tickers x 1 year (delegates to bench.py
     sizing; same measurement loop)
  4. 5-year cross-sectional rank-IC + decile backtest on device
  5. symbolic factor search: vmapped population of candidate expression
     trees over one day's minute-bar tensor

Run:  python benchmarks/ladder.py [--configs 1,2,4,5]
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import jax
import numpy as np

sys.path.insert(0, ".")  # repo root (for bench.py when run from checkout)

# persistent XLA cache (when configured): the fused graphs here are the
# same executables bench.py compiles — pay the ~20-40s TPU compile once
# per tunnel window, not once per script
from replication_of_minute_frequency_factor_tpu.config import (  # noqa: E402
    apply_compilation_cache, get_config)

apply_compilation_cache(get_config())


def _bars(rng, n_days, n_tickers):
    import bench
    return bench.make_batch(rng, n_days=n_days, n_tickers=n_tickers)


def _emit(name, seconds, unit="s", **extra):
    print(json.dumps({"metric": name, "value": round(seconds, 4),
                      "unit": unit, **extra}), flush=True)


def config1(rng):
    """50 tickers x 1 day, vol_return1min: oracle (reference CPU
    semantics) vs fused jit path."""
    import pandas as pd

    from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day
    from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
    from replication_of_minute_frequency_factor_tpu.models.registry import compute_factors_jit
    from replication_of_minute_frequency_factor_tpu.oracle import compute_oracle

    cols = synth_day(rng, n_codes=50)
    df = pd.DataFrame({k: cols[k] for k in
                       ("code", "time", "open", "high", "low", "close",
                        "volume")})
    df["date"] = np.datetime64("2024-01-02")
    t0 = time.perf_counter()
    compute_oracle(df, ("vol_return1min",))
    _emit("cfg1_vol_return1min_50tkr_1day_oracle_cpu",
          time.perf_counter() - t0)

    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None], g.mask[None]
    out = compute_factors_jit(bars, mask, names=("vol_return1min",))
    jax.block_until_ready(out)  # compile
    t0 = time.perf_counter()
    for _ in range(10):
        jax.block_until_ready(
            compute_factors_jit(bars, mask, names=("vol_return1min",)))
    _emit("cfg1_vol_return1min_50tkr_1day_jit",
          (time.perf_counter() - t0) / 10)


def config2(rng):
    """Full 58-kernel handbook, 500 tickers x 1 month (21 days)."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    from replication_of_minute_frequency_factor_tpu.models.registry import factor_names
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_packed_prepared)

    names = factor_names()
    bars, mask = _bars(rng, n_days=21, n_tickers=500)
    w = wire.encode(bars, mask)
    # pack once outside the timed step: the pipeline + headline bench run
    # pack_arrays on a producer thread overlapped with device compute, so
    # timing a serial re-pack of unchanged data would inflate the metric
    buf, spec = wire.pack_arrays(w.arrays)

    def step():
        return compute_packed_prepared(buf, spec, "wire", names=names,
                                       replicate_quirks=True)

    jax.block_until_ready(step())  # compile
    t0 = time.perf_counter()
    for _ in range(3):
        jax.block_until_ready(step())
    # metric renamed when the timed step moved to the pre-packed
    # single-buffer path: not comparable with pre-rename recordings
    _emit("cfg2_cicc58_500tkr_1mo_packed", (time.perf_counter() - t0) / 3,
          factors=len(names))


def config4(rng):
    """5-year rank-IC + decile backtest fully on device: 1220 dates x
    5000 tickers exposure vs forward returns."""
    from replication_of_minute_frequency_factor_tpu import eval_ops

    n_dates, n_tickers = 1220, 5000
    expo = rng.normal(0, 1, (n_dates, n_tickers)).astype(np.float32)
    fwd = rng.normal(0, 0.02, (n_dates, n_tickers)).astype(np.float32)
    valid = rng.random((n_dates, n_tickers)) > 0.05

    def step():
        ic, ric = eval_ops.ic_series(expo, fwd, valid)
        labels = eval_ops.qcut_labels(expo, valid, 10)
        return ic, ric, labels

    jax.block_until_ready(step())
    t0 = time.perf_counter()
    for _ in range(3):
        jax.block_until_ready(step())
    _emit("cfg4_5yr_rankic_decile_5000tkr", (time.perf_counter() - t0) / 3,
          dates=n_dates)


def config5(rng, scale=1.0):
    """Symbolic search: one fitness call over a 10k-candidate population
    (the hot loop of search.evolve). Fitness auto-chunks the population
    through an internal lax.map so its HBM temporaries fit the chip —
    the timing is the sequential chunked pass, not a single 10k vmap.
    Round 3: timed on BOTH skeletons — the round-2 arithmetic default
    and the richer ratio-of-aggregates grammar (rolling ops, time/value
    masks, aggregators), which is what real handbook-factor mining
    exercises."""
    from replication_of_minute_frequency_factor_tpu import search

    pop_n = max(64, int(10_000 * scale))
    bars, mask = _bars(rng, n_days=1, n_tickers=max(50, int(1000 * scale)))
    fwd = rng.normal(0, 0.02, bars.shape[:2]).astype(np.float32)  # [D, T]
    fwd_valid = np.ones_like(fwd, bool)
    for tag, skel in (("", search.DEFAULT_SKELETON),
                      ("_rich", search.RICH_SKELETON)):
        pop = search.random_population(rng, pop_n, skel)
        jax.block_until_ready(search.fitness(pop, bars, mask, fwd,
                                             fwd_valid, skeleton=skel))
        t0 = time.perf_counter()
        for _ in range(3):
            jax.block_until_ready(
                search.fitness(pop, bars, mask, fwd, fwd_valid,
                               skeleton=skel))
        _emit(f"cfg5_symbolic_search_candidates{tag}",
              (time.perf_counter() - t0) / 3, population=pop_n)


def config3():
    """Headline config — same code path as bench.py."""
    import bench
    bench.main()


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--configs", default="1,2,3,4,5")
    ap.add_argument("--scale", type=float, default=1.0,
                    help="shrink config 5's population/universe "
                         "(e.g. 0.05 for a CPU smoke test)")
    args = ap.parse_args()
    wanted = {int(c) for c in args.configs.split(",")}
    rng = np.random.default_rng(0)
    if 1 in wanted:
        config1(rng)
    if 2 in wanted:
        config2(rng)
    if 3 in wanted:
        config3()
    if 4 in wanted:
        config4(rng)
    if 5 in wanted:
        config5(rng, scale=args.scale)


if __name__ == "__main__":
    main()
