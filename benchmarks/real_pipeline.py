"""The REAL pipeline, end to end: 244 on-disk day files through
``compute_exposures`` — parquet io + grid packing + wire encode +
device compute + result materialization + atomic cache save.

Why this exists (VERDICT r3 weak #1): the headline bench times a
synthetic pre-gridded loop and extrapolates; the full-year pipeline the
framework actually ships had never been timed end to end on ANY
backend. This closes that gap with a second metric:

    {"metric": "cicc58_real_pipeline_1yr_wall", ...}

Workload mirrors the reference driver's (SURVEY.md §3.1: one polars
pass per factor per day-file; here one fused device pass per day
batch): 5000 codes x 244 trading days, ~2% missing bars.

The dataset (~244 parquet files, a few GB) is generated ONCE into
``.bench_data/realpipe/`` (deterministic seed) and reused by every
later run — generation is host-side synthesis, not pipeline work, and
must not ride a tunnel up-window. ``--generate-only`` builds it ahead
of time.

Run:  python benchmarks/real_pipeline.py [--generate-only]
Env:  BENCH_REQUIRE_TPU=1  fail (rc 17) instead of timing a CPU run
      under a TPU-named metric (capture-session mode);
      on a CPU platform without it, the metric gains ``_cpu``.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile
import time

import numpy as np
import pyarrow as pa

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from replication_of_minute_frequency_factor_tpu.data import io as dio  # noqa: E402

N_TICKERS = 5000
N_DAYS = 244
MISSING_PROB = 0.02
SEED = 7
DATA_DIR = os.path.join(REPO, ".bench_data", "realpipe")
MARKER = os.path.join(DATA_DIR, "DATASET.json")


def trading_days(n=None, start="2024-01-01"):
    """First ``n`` weekdays from ``start`` — a stand-in trading calendar
    (the pipeline only needs distinct, ordered dates). ``n`` reads
    N_DAYS at call time so tests can shrink the module constants."""
    if n is None:
        n = N_DAYS
    out, d = [], np.datetime64(start)
    while len(out) < n:
        if np.is_busday(d):
            out.append(d)
        d = d + np.timedelta64(1, "D")
    return out


def write_day(path, rng, grid_times, codes_int):
    """One long-format day file from a gridded synthetic day.

    Vectorized (bench.make_batch's price model flattened through the
    ~2% missing-bar mask) — data.synthetic.synth_day loops per code in
    Python and would take ~an hour at 5000 codes x 244 days. Codes are
    written as int64: a real CSMAR export shape the reader normalizes
    (data/io.py read_minute_day zero-pads), and it keeps the dataset
    small enough to regenerate cheaply."""
    import bench
    bars, mask = bench.make_batch(rng, n_days=1, n_tickers=N_TICKERS)
    return _write_day_arrays(path, bars, mask, grid_times, codes_int)


def _write_day_arrays(path, bars, mask, grid_times, codes_int):
    bars, mask = bars[0], mask[0]          # [T, 240, 5], [T, 240]
    keep = mask.reshape(-1)
    cols = {
        "code": np.repeat(codes_int, 240)[keep],
        "time": np.tile(grid_times, N_TICKERS)[keep],
    }
    flat = bars.reshape(-1, 5)[keep]
    for i, name in enumerate(("open", "high", "low", "close", "volume")):
        cols[name] = flat[:, i].astype(np.float64)
    # atomic (tempfile -> os.replace): a generation killed mid-write
    # must not leave a truncated parquet the resume pass would keep
    dio.write_parquet_atomic(pa.table(cols), path)


def _params():
    return {"n_tickers": N_TICKERS, "n_days": N_DAYS,
            "missing_prob": MISSING_PROB, "seed": SEED, "version": 2}


def dataset_ready():
    """True iff the on-disk dataset matches the current parameters."""
    try:
        with open(MARKER) as fh:
            return json.load(fh) == _params()
    except (OSError, ValueError):
        return False


def ensure_dataset(progress=True):
    """Generate the day files once; later calls are a marker-file hit.

    Each day is seeded ``SEED + day_index`` (not one sequential stream)
    so a generation killed part-way RESUMES: existing files are kept
    and only missing days are written — a capture-session timeout
    mid-generation must not restart the whole dataset on the next fire
    (version 2; v1's sequential-rng files are regenerated)."""
    params = _params()
    if dataset_ready():
        return os.path.join(DATA_DIR, "kline")
    from replication_of_minute_frequency_factor_tpu import sessions
    mdir = os.path.join(DATA_DIR, "kline")
    # resume is only safe when the partial files came from THESE params
    # (the in-progress stamp); anything else on disk is another
    # configuration's data and must go
    inprog = MARKER + ".inprogress"
    try:
        with open(inprog) as fh:
            resume = json.load(fh) == params
    except (OSError, ValueError):
        resume = False
    if not resume:
        shutil.rmtree(mdir, ignore_errors=True)
    os.makedirs(DATA_DIR, exist_ok=True)
    os.makedirs(mdir, exist_ok=True)
    with open(inprog, "w") as fh:
        json.dump(params, fh)
    codes_int = np.arange(600000, 600000 + N_TICKERS, dtype=np.int64)
    t0 = time.monotonic()
    for i, d in enumerate(trading_days()):
        path = os.path.join(mdir, str(d).replace("-", "") + ".parquet")
        if os.path.exists(path):  # atomic writes: existing == complete
            continue
        write_day(path, np.random.default_rng(SEED + i),
                  np.asarray(sessions.GRID_TIMES), codes_int)
        if progress and (i + 1) % 20 == 0:
            print(f"# generated {i + 1}/{N_DAYS} day files "
                  f"({time.monotonic() - t0:.0f}s)",
                  file=sys.stderr, flush=True)
    with open(MARKER, "w") as fh:
        json.dump(params, fh)
    os.unlink(inprog)
    return mdir


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--generate-only", action="store_true",
                    help="build the on-disk dataset and exit (run this "
                         "OUTSIDE a tunnel up-window)")
    args = ap.parse_args()

    try:
        from tools.cpu_busy import mark_busy
    except ImportError:
        pass
    else:
        # held BEFORE generation too: a first standalone run otherwise
        # pegs the one host core for minutes with no sentinel and the
        # tunnel watcher could fire a timed capture into the contention
        mark_busy("real_pipeline bench")

    if os.environ.get("BENCH_REQUIRE_TPU") and not args.generate_only \
            and not dataset_ready():
        # capture-session mode must NEVER synthesize inside a tunnel
        # up-window (e.g. the watcher's pre-gen died and its rc was
        # only logged): fail fast; a later down-window rebuilds it
        print("# BENCH_REQUIRE_TPU set but the dataset is not "
              "pre-generated; run --generate-only first",
              file=sys.stderr, flush=True)
        return 18

    mdir = ensure_dataset()
    if args.generate_only:
        print(f"# dataset ready under {mdir}", file=sys.stderr)
        return 0

    if "PALLAS_AXON_POOL_IPS" in os.environ:
        # killable-child reachability probe (bench.py's) before any
        # in-process jax: a tunnel that wedges after interpreter start
        # would otherwise hang backend init forever with nothing able
        # to time out. No CPU self-heal here — a CPU run of this metric
        # is a deliberate choice (hermetic env), not a fallback.
        import bench
        if not bench._tunnel_alive(
                require_tpu=bool(os.environ.get("BENCH_REQUIRE_TPU"))):
            print("# tunnel unreachable; refusing to start the real-"
                  "pipeline run", file=sys.stderr, flush=True)
            return 17

    import jax

    platform = jax.devices()[0].platform
    if os.environ.get("BENCH_REQUIRE_TPU") and platform == "cpu":
        # same race bench.main guards: probe child saw a TPU, THIS
        # process then resolved to CPU — never print a TPU-named metric
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        return 17
    suffix = "_cpu" if platform == "cpu" else ""

    from replication_of_minute_frequency_factor_tpu import (
        Config, compute_exposures, set_config)
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)

    days_per_batch = int(os.environ.get("BENCH_DAYS_PER_BATCH", "32"))
    workdir = tempfile.mkdtemp(prefix="realpipe_")
    set_config(Config(minute_dir=mdir, days_per_batch=days_per_batch))
    apply_compilation_cache(get_config())
    cache_path = os.path.join(workdir, "exposures.parquet")

    # the timed section IS compute_exposures: list + read + grid + wire
    # encode + transfer + fused 58-factor device graph + materialize +
    # atomic cache save, with the pipeline's own producer/consumer
    # overlap — nothing mocked, nothing extrapolated
    t0 = time.perf_counter()
    table = compute_exposures(cache_path=cache_path, progress=False)
    wall = time.perf_counter() - t0

    failures = getattr(table, "failures", None)
    n_failed = len(failures) if failures is not None else 0
    record = {
        "metric": "cicc58_real_pipeline_1yr_wall" + suffix,
        "value": round(wall, 3),
        "unit": "s",
        # same <60 s north star as the headline (BASELINE.json:5): the
        # real pipeline carries io+grid the synthetic loop skips, so
        # parity here is strictly stronger evidence
        "vs_baseline": round(60.0 / wall, 3),
        "days": N_DAYS,
        "tickers": N_TICKERS,
        "days_per_batch": days_per_batch,
        "factors": len(table.factor_names),
        "rows": len(table),
        "failed_days": n_failed,
        "stage_timings": {k: round(v, 3)
                          for k, v in (table.timings or {}).items()}
        if getattr(table, "timings", None) else None,
    }
    shutil.rmtree(workdir, ignore_errors=True)
    print(json.dumps(record))
    # a run that silently skipped days must not read as a green year
    return 0 if n_failed == 0 and len(table) > 0 else 1


if __name__ == "__main__":
    sys.exit(main())
