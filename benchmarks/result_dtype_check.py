"""Quantify a narrower result dtype for the [F, D, T] transfer.

VERDICT r3 #1c: the headline's device->host leg ships f32 factor values
(~9.3 MB per 8-day batch over a ~15 MB/s link); halving the bytes with
f16/bf16 is a real lever ONLY if the quantization error fits inside the
parity tolerance headroom (tests/test_parity.py: default rtol 2e-3 with
per-factor overrides). This measures, per factor, what casting the f32
results to each narrow dtype would do:

  * overflow_lanes — finite f32 values that become inf (f16 max 65504;
    several factors are CNY-volume/amount scaled, order 1e6+);
  * max_rel_err — max |cast(x)-x|/|x| over finite lanes (f16 mantissa
    ~4.9e-4 relative step, bf16 ~3.9e-3 — the latter exceeds the 2e-3
    default tolerance by construction).

Prints one JSON line per dtype plus a verdict line. Run on CPU —
quantization is platform-independent:

    env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python benchmarks/result_dtype_check.py
"""

import json
import os
import sys

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

N_TICKERS = 800
N_DAYS = 4
DEFAULT_RTOL = 2e-3  # tests/test_parity.py RTOL["default"]


def main():
    import jax

    import bench
    from replication_of_minute_frequency_factor_tpu.data import wire
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_packed_prepared)

    rng = np.random.default_rng(3)
    names = factor_names()
    bars, mask = bench.make_batch(rng, n_days=N_DAYS, n_tickers=N_TICKERS)
    w = wire.encode(bars, mask)
    if w is not None:  # wire can refuse a batch; raw f32 path like bench
        buf, spec = wire.pack_arrays(w.arrays)
        kind = "wire"
    else:
        buf, spec = wire.pack_arrays((bars, mask.view(np.uint8)))
        kind = "raw"
    out = np.asarray(jax.block_until_ready(compute_packed_prepared(
        buf, spec, kind, names=names, replicate_quirks=True)))
    # [F, D, T] f32

    verdicts = {}
    for dtype_name, dtype in (("float16", np.float16),
                              ("bfloat16", None)):
        if dtype is None:
            import ml_dtypes
            dtype = ml_dtypes.bfloat16
        cast = out.astype(dtype).astype(np.float32)
        finite = np.isfinite(out)
        overflow = finite & ~np.isfinite(cast)
        denom = np.maximum(np.abs(out), 1e-30)
        rel = np.where(finite & np.isfinite(cast),
                       np.abs(cast - out) / denom, 0.0)
        per_factor_max = rel.reshape(rel.shape[0], -1).max(axis=1)
        worst = int(np.argmax(per_factor_max))
        n_over_factors = int(
            (overflow.reshape(out.shape[0], -1).any(axis=1)).sum())
        rec = {
            "dtype": dtype_name,
            "overflow_lanes": int(overflow.sum()),
            "factors_with_overflow": n_over_factors,
            "max_rel_err": float(per_factor_max.max()),
            "worst_factor": names[worst],
            "factors_over_default_rtol": int(
                (per_factor_max > DEFAULT_RTOL).sum()),
            "factors_over_half_rtol": int(
                (per_factor_max > DEFAULT_RTOL / 2).sum()),
        }
        verdicts[dtype_name] = rec
        print(json.dumps(rec))

    usable = {k: v["overflow_lanes"] == 0
              and v["max_rel_err"] < DEFAULT_RTOL / 2
              for k, v in verdicts.items()}
    print(json.dumps({"metric": "result_dtype_verdict",
                      "usable_without_tolerance_loss": usable,
                      "default_rtol": DEFAULT_RTOL}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
