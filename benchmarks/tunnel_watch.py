"""Tunnel-up watcher: probe the attached-TPU tunnel continuously and
fire the one-shot session capture (`benchmarks/tpu_session.py`) on the
first success.

Why this exists (VERDICT r2 next-round #1): the chip sits behind a
tunnel that wedges for hours; two rounds of manual polling lost every
race with its up-windows, so the perf axis has zero TPU evidence. This
watcher runs from round start, logs EVERY probe to
``benchmarks/TUNNEL_WATCH.jsonl`` (turning "the tunnel was down" from an
assertion into an artifact even when no window ever opens), and spends
the first up-window on the full capture.

Coordination with the 1-core host: the bench must not be timed while a
fuzzer or test suite is saturating the single CPU (they share the core
with the transfer path's host leg). CPU-heavy work in this repo holds a
per-pid sentinel under ``.cpu_busy.d/`` (``tools/with_cpu_busy.sh`` for
shell, ``tools.cpu_busy.cpu_busy`` for Python — run_tests.sh and the
fuzz mains already do); on tunnel-up the watcher waits for all LIVE
owners to exit (dead pids are swept, so a crash can't wedge the watch)
before launching the session, and stamps ``host_quiet`` both in the
watch log and into the session environment so a contended capture is
identifiable in the artifact itself.

Retries never re-burn a window on green steps: the session itself
carries fresh green steps over from TPU_SESSION.json (age- and
content-bounded) and skips them, so every fire passes the FULL step
list (ADVICE r3: a watcher-side pending filter diverged from the
session's carry filters and could drop a step from the artifact).

Run (backgrounded for the round):
  python benchmarks/tunnel_watch.py [--max-hours 10.5] [--interval 150]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from tools.cpu_busy import live_owners  # noqa: E402

LOG = os.path.join(REPO, "benchmarks", "TUNNEL_WATCH.jsonl")
SESSION_OUT = os.path.join(REPO, "benchmarks", "tpu_session.out")


def _log(rec):
    rec["t"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
    with open(LOG, "a") as fh:
        fh.write(json.dumps(rec) + "\n")
    print(json.dumps(rec), flush=True)


def _probe(timeout=75):
    """One reachability probe from a killable child (a wedged tunnel
    hangs jax backend init in-process, before any code can time out).
    Requires a non-CPU device so a misconfigured env can't false-fire."""
    t0 = time.monotonic()
    try:
        rc = subprocess.run(
            [sys.executable, "-c",
             "import jax; assert jax.devices()[0].platform != 'cpu'"],
            timeout=timeout, capture_output=True).returncode
        return rc == 0, round(time.monotonic() - t0, 1)
    except subprocess.TimeoutExpired:
        return False, round(time.monotonic() - t0, 1)


def _wait_quiet(max_wait_s=900.0):
    """Wait for live CPU-busy owners to finish, bounded.

    Live owners get the full bound (repo fuzz chunks are minutes, not
    hours); dead owners' sentinels are swept by live_owners() itself.
    Returns (quiet, owners_still_live) so the caller can stamp an
    honest host_quiet into both the log and the session env."""
    t0 = time.monotonic()
    owners = live_owners()
    while owners and time.monotonic() - t0 < max_wait_s:
        time.sleep(10)
        owners = live_owners()
    return not owners, owners


def plan_steps(want, pregen_running):
    """Steps for one fire: ALWAYS the full wanted list (the session
    owns the skip decision via its carry filters — ADVICE r3), except
    the pipeline step is deferred while its dataset is still
    generating (it would otherwise synthesize inside the window)."""
    if not pregen_running:
        return list(want)
    return [s for s in want if s != "pipeline"]


def watch_complete(rc, steps, want):
    """Done only when an all-green session covered the FULL wanted
    list: an rc-0 fire that deferred the pipeline step must keep
    watching or the real-pipeline metric is never captured."""
    return rc == 0 and list(steps) == list(want)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--max-hours", type=float, default=10.5)
    ap.add_argument("--interval", type=float, default=150.0,
                    help="sleep between probes while down (s)")
    # mirror tpu_session.py's default value-per-second order; the two
    # long tails (sweep, real pipeline) run last so a window that
    # closes mid-run has already banked the core steps
    ap.add_argument("--steps", default="headline,link,stream,"
                    "lad1,lad2,lad4,lad5,spot,sweep,pipeline")
    args = ap.parse_args()

    want = [s.strip() for s in args.steps.split(",") if s.strip()]
    deadline = time.monotonic() + args.max_hours * 3600
    _log({"event": "watch_start", "interval_s": args.interval,
          "max_hours": args.max_hours, "steps": want})
    gen_proc = None
    if "pipeline" in want:
        # pre-build the real-pipeline dataset in the BACKGROUND, so the
        # pipeline step never spends a tunnel up-window on host-side
        # synthesis — but without delaying the first probe (a brief
        # up-window open right now must not be lost to ~minutes of
        # parquet writing). While it runs, fires simply defer the
        # pipeline step. Resumable and marker-gated: a warm start is a
        # fast no-op. Hermetic CPU env: with the pool var set, a wedged
        # tunnel hangs the child at interpreter start (sitecustomize
        # dials it) — this is a pure host-side task.
        genv = {k: v for k, v in os.environ.items()
                if k != "PALLAS_AXON_POOL_IPS"}
        genv["JAX_PLATFORMS"] = "cpu"
        def _spawn_pregen():
            out = open(os.path.join(REPO, "benchmarks", "pregen.out"),
                       "ab")
            p = subprocess.Popen(
                [sys.executable, "benchmarks/real_pipeline.py",
                 "--generate-only"],
                cwd=REPO, env=genv, stdout=out, stderr=out)
            _log({"event": "dataset_pregen_start", "pid": p.pid})
            return p

        gen_proc = _spawn_pregen()
        pregen_tries = 1
    n = 0
    while time.monotonic() < deadline:
        alive, probe_s = _probe()
        n += 1
        _log({"event": "probe", "n": n, "alive": alive,
              "probe_s": probe_s})
        if alive:
            quiet, owners = _wait_quiet()
            # poll AFTER the quiet wait: generation often finishes
            # DURING it (the pre-gen child holds the cpu_busy sentinel;
            # a complete cold run measured ~70 s on this host), and a
            # stale pre-wait poll would defer the pipeline step from a
            # fire that is quiet precisely because pre-gen just ended —
            # then an all-green session would exit the watcher with the
            # real-pipeline metric never captured
            if gen_proc is not None and gen_proc.poll() is not None:
                _log({"event": "dataset_pregen_done",
                      "rc": gen_proc.returncode})
                if gen_proc.returncode == 0:
                    gen_proc = None
                elif pregen_tries < 3:
                    # a died pre-gen (disk, import error — see
                    # benchmarks/pregen.out) must be retried, not
                    # dropped: without a dataset the pipeline step
                    # perma-fails its REQUIRE_TPU dataset gate
                    gen_proc = _spawn_pregen()
                    pregen_tries += 1
                else:
                    _log({"event": "dataset_pregen_gave_up"})
                    gen_proc, want = None, \
                        [s for s in want if s != "pipeline"]
            steps = plan_steps(want, gen_proc is not None)
            _log({"event": "fire_session", "host_quiet": quiet,
                  "busy_owners": owners, "steps": steps})
            env = dict(os.environ, TPU_SESSION_HOST_QUIET=str(quiet))
            t0 = time.monotonic()
            # child output goes to a file, not a pipe: on a 3 h timeout
            # TimeoutExpired carries no output on POSIX, and the tail
            # identifying the hung step would be lost (same rationale as
            # the tempfile capture in __graft_entry__.py)
            with open(SESSION_OUT, "ab") as out:
                out.write(b"\n=== fire %b ===\n"
                          % time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                          time.gmtime()).encode())
                out.flush()
                try:
                    # 5 h kill: the default step list's worst-case
                    # child timeouts sum to ~3.6 h (headline 1800 +
                    # link 600 + headc 1800 + 4x900 ladder + spot 600
                    # + sweep 1800 + pipeline 2700) before per-step
                    # probes and inter-step overhead — a kill sized
                    # below that would always sacrifice the pipeline
                    # step, the last and longest, in a slow-but-
                    # progressing window; per-step re-probes make a
                    # dead-tunnel session fail fast regardless
                    p = subprocess.run(
                        [sys.executable, "benchmarks/tpu_session.py",
                         "--steps", ",".join(steps)],
                        cwd=REPO, timeout=5 * 3600, env=env,
                        stdout=out, stderr=subprocess.STDOUT)
                    rc = p.returncode
                except subprocess.TimeoutExpired:
                    rc = "timeout"
            with open(SESSION_OUT, "rb") as fh:
                try:
                    fh.seek(-800, os.SEEK_END)
                except OSError:
                    pass
                tail = fh.read().decode(errors="replace")
            _log({"event": "session_done", "rc": rc,
                  "seconds": round(time.monotonic() - t0, 1),
                  "tail": tail})
            # Otherwise (rc!=0 or timeout): the window likely closed
            # mid-run; TPU_SESSION.json has per-step status, and the
            # next fire's session skips the carried-green steps.
            if watch_complete(rc, steps, want):
                return 0
        time.sleep(args.interval)
    _log({"event": "watch_expired", "probes": n})
    return 3


if __name__ == "__main__":
    sys.exit(main())
