"""Build tests/golden/ — a tiny hand-crafted day file + daily-PV table
exercising the messy edges of the CSMAR export contract in one place
(VERDICT r2 #8): integer stock codes (vs the PV table's zero-padded
strings), an 11:30 bar (the reference's trade-minute formula would alias
it onto 13:00 — our loader must DROP it, sessions.py), sub-minute and
pre-open timestamps, zero-volume bars, a limit-locked (constant-price)
stock, a halted stock whose only row is off-grid, and compact-``YYYYMMDD``
date strings in the PV file.

Deterministic: re-running reproduces byte-identical content modulo
parquet metadata; the committed fixture is authoritative, this script is
its provenance. Run:  python tools/make_golden_fixture.py
"""

from __future__ import annotations

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
OUT = os.path.join(REPO, "tests", "golden")

# Four stocks, int-coded in the minute file:
#   2      -> "000002"  normal stock, full-ish day with quirks
#   600519 -> "600519"  limit-locked: constant price all day, volume>0
#   300750 -> "300750"  sparse day: AM bars only + one zero-volume bar
#   999999 -> "999999"  halted: single row at an off-grid time (dropped
#                       entirely -> all-invalid grid row -> NaN factors)
DAY = "20240102"


def minute_rows():
    rows = []  # (code:int, time:int, open, high, low, close, volume)

    # -- 000002: whole AM + PM grid, with deliberate contract edges
    t_am = [93000000 + m * 100000 for m in range(0, 60)]       # 09:30-10:29
    t_am += [103000000 + m * 100000 for m in range(0, 60)]     # 10:30-11:29
    t_pm = [130000000 + m * 100000 for m in range(0, 60)]      # 13:00-13:59
    t_pm += [140000000 + m * 100000 for m in range(0, 60)]     # 14:00-14:59
    px = 10.00
    for i, t in enumerate(t_am + t_pm):
        o = round(px, 2)
        c = round(px + (0.01 if i % 3 == 0 else -0.01 if i % 3 == 1
                        else 0.0), 2)
        rows.append((2, t, o, max(o, c) + 0.01, min(o, c) - 0.01, c,
                     100.0 * (i % 7 + 1)))
        px = c
    # contract edges, all of which the loader must DROP:
    rows.append((2, 113000000, 9.0, 9.0, 9.0, 9.0, 1e6))   # 11:30 bar
    rows.append((2, 93000500, 9.0, 9.0, 9.0, 9.0, 1e6))    # sub-minute
    rows.append((2, 91500000, 9.0, 9.0, 9.0, 9.0, 1e6))    # pre-open auction
    rows.append((2, 150000000, 9.0, 9.0, 9.0, 9.0, 1e6))   # 15:00 close
    # duplicate (code, slot): LAST wins — first 09:31 row is overwritten
    rows.append((2, 93100000, 7.77, 7.77, 7.77, 7.77, 777.0))

    # -- 600519: limit-locked at 1700.00 the whole day (var == 0 paths)
    for t in t_am + t_pm:
        rows.append((600519, t, 1700.00, 1700.00, 1700.00, 1700.00,
                     200.0))

    # -- 300750: AM only, sparse (every 7th minute), one zero-volume bar
    for m in range(0, 120, 7):
        t = t_am[m]
        v = 0.0 if m == 14 else 300.0
        rows.append((300750, t, 400.0 + m * 0.1, 400.2 + m * 0.1,
                     399.8 + m * 0.1, 400.1 + m * 0.1, v))

    # -- 999999: halted; its one row is off-grid (12:00) -> dropped
    rows.append((999999, 120000000, 50.0, 50.0, 50.0, 50.0, 0.0))
    return rows


def main():
    os.makedirs(OUT, exist_ok=True)
    rows = minute_rows()
    # duplicate-slot rule is last-WRITE-wins: keep the overriding 09:31
    # row physically after the original (writers append corrections)
    cols = list(zip(*rows))
    minute = pa.table({
        "code": pa.array(cols[0], pa.int32()),      # INT codes on purpose
        "time": pa.array(cols[1], pa.int64()),
        "open": pa.array(cols[2], pa.float64()),
        "high": pa.array(cols[3], pa.float64()),
        "low": pa.array(cols[4], pa.float64()),
        "close": pa.array(cols[5], pa.float64()),
        "volume": pa.array(cols[6], pa.float64()),
    })
    pq.write_table(minute, os.path.join(OUT, f"{DAY}_cleaned.parquet"))

    # daily PV: CSMAR column spellings, int codes, compact YYYYMMDD
    # dates-as-strings; covers the evaluation join for two trading days
    codes = [2, 600519, 300750, 999999]
    days = ["20240102", "20240103"]
    pv = pa.table({
        "Stkcd": pa.array([c for _ in days for c in codes], pa.int32()),
        "Trddt": pa.array([d for d in days for _ in codes], pa.string()),
        "ChangeRatio": pa.array(
            [0.001, 0.0, -0.002, 0.0, 0.004, 0.0, 0.01, 0.0],
            pa.float64()),
        "Dsmvtll": pa.array([1e6, 2e7, 5e6, 1e5] * 2, pa.float64()),
        "Dsmvosd": pa.array([8e5, 1.5e7, 4e6, 9e4] * 2, pa.float64()),
    })
    pq.write_table(pv, os.path.join(OUT, "daily_pv.parquet"))
    print(f"wrote {OUT}: {minute.num_rows} minute rows, "
          f"{pv.num_rows} pv rows")


if __name__ == "__main__":
    main()
