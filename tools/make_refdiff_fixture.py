"""Generate tests/fixtures/refdiff_snapshot.json (ISSUE 6 satellite).

The polars-backend differential test used to xfail in containers
without the audited reference tree (`/root/reference`), which meant
tier-1 had ZERO executing coverage of reference-output parity. This
script vendors a minimal reference-output snapshot instead: the
deterministic 3-day synthetic minute dir that `tests/test_pipeline.py`'s
``minute_dir`` fixture builds (BYTE-IDENTICAL: same seed, same writer),
pushed through the **f64 oracle backend** — the audited stand-in whose
parity with the reference's actual ``cal_*`` polars code is enforced at
f64-tight tolerances by ``tools/refdiff`` (tests/test_refdiff.py)
whenever the reference tree IS mounted. The chain is therefore:

    reference cal_* code  ==(refdiff, when mounted)==  numpy oracle
    numpy oracle          ==(this snapshot, tier-1)==  committed values
    committed values      ~=(tier-1, f32 tolerance)==  jax device path

Regenerate after an intentional semantic change:

    JAX_PLATFORMS=cpu python tools/make_refdiff_fixture.py

and re-run the refdiff suite against a mounted reference before
committing the new values — the snapshot is only as audited as its
last differential run.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys
import tempfile

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

OUT = os.path.join(REPO, "tests", "fixtures", "refdiff_snapshot.json")
#: the factor subset the old xfailed differential compared
NAMES = ("vol_return1min", "mmt_pm", "doc_pdf60")
DAYS = ("2024-01-02", "2024-01-03", "2024-01-04")
SEED = 0
MISSING_PROB = 0.05


def build_minute_dir(dirpath: str) -> None:
    """EXACTLY tests/test_pipeline.py's ``minute_dir`` fixture: one
    shared rng consumed day by day."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from replication_of_minute_frequency_factor_tpu.data.synthetic import (
        synth_day)

    rng = np.random.default_rng(SEED)
    for ds in DAYS:
        cols = synth_day(rng, n_codes=6, date=ds,
                         missing_prob=MISSING_PROB)
        arrays = {"code": pa.array([str(c) for c in cols["code"]]),
                  "time": pa.array(cols["time"])}
        for k in ("open", "high", "low", "close", "volume"):
            arrays[k] = pa.array(cols[k])
        pq.write_table(pa.table(arrays),
                       os.path.join(dirpath,
                                    ds.replace("-", "") + ".parquet"))


def main() -> int:
    from replication_of_minute_frequency_factor_tpu.config import Config
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_exposures)

    with tempfile.TemporaryDirectory() as md:
        build_minute_dir(md)
        table = compute_exposures(
            md, NAMES, cfg=Config(backend="numpy", days_per_batch=2),
            progress=False)
    rows = {
        "code": [str(c) for c in table.columns["code"]],
        "date": [str(d) for d in table.columns["date"]],
    }
    for n in NAMES:
        # float32 values serialized at full round-trip precision
        rows[n] = [None if np.isnan(v)
                   else float(np.format_float_positional(
                       np.float32(v), unique=True))
                   for v in table.columns[n]]
    digest = hashlib.sha256(
        json.dumps(rows, sort_keys=True).encode()).hexdigest()
    doc = {
        "provenance": {
            "generator": "tools/make_refdiff_fixture.py",
            "backend": "numpy (f64 oracle; reference semantics — see "
                       "module docstring for the audit chain)",
            "seed": SEED, "days": list(DAYS), "n_codes": 6,
            "missing_prob": MISSING_PROB, "names": list(NAMES),
            "sha256": digest,
        },
        "rows": rows,
    }
    with open(OUT, "w") as fh:
        json.dump(doc, fh, indent=1)
    print(json.dumps({"written": OUT, "rows": len(rows["code"]),
                      "sha256": digest[:16]}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
