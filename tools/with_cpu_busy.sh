#!/bin/sh
# Run a CPU-heavy command while holding a per-pid sentinel under
# .cpu_busy.d/ so benchmarks/tunnel_watch.py delays a TPU bench launch
# until the single host core is quiet (bench-measurement hygiene: never
# time TPU runs with fuzzers live). Per-pid files make concurrent
# invocations safe: each removes only its own sentinel on exit, and the
# watcher checks pid liveness so a crashed owner can't wedge the watch.
# Usage: tools/with_cpu_busy.sh <cmd> [args...]
set -u
REPO="$(cd "$(dirname "$0")/.." && pwd)"
DIR="$REPO/.cpu_busy.d"
mkdir -p "$DIR"
SENTINEL="$DIR/$$"
echo "$*" > "$SENTINEL"
trap 'rm -f "$SENTINEL"' EXIT INT TERM
"$@"
