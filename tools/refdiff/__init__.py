"""Differential testing of the reference's *actual code* (tools/refdiff).

``polars_shim`` is a minimal interpreter for the polars expression API
surface used by ``/root/reference`` (all three files). ``harness`` makes
it resolvable as ``polars`` only for the duration of each reference
``exec_module`` (hash-pinned to the audited snapshot), executes the real
``cal_*`` expression graphs on synthetic day data, and compares against
this repo's JAX and numpy-oracle backends.

Why a shim and not real polars: this container has no polars wheel and no
network egress, so the reference cannot run on its real engine here. The
shim executes the reference's own expression *graphs* (catching any
transcription error in our reimplementations — wrong column, wrong filter,
wrong operation order), while engine-level semantics polars doesn't spell
out in the expression text (null handling, tie-breaking, group order) are
pinned explicitly in one place (``polars_shim.SEMANTIC_PINS``) where they
can be audited against polars documentation.
"""
