"""Execute the reference's real kernel code on the shim; diff vs this repo.

Chain of custody for parity (VERDICT.md round-1 "Missing #3"):

    reference cal_* source  --runs on-->  polars_shim   (this harness)
        vs  oracle/kernels.py (numpy, f64)              (this harness)
        vs  JAX backend (f32, dense grid)               (tests/test_parity.py)

Both sides of THIS harness are f64, so tolerances are near-exact; the
oracle↔JAX leg has its own calibrated f32 tolerance matrix. Together the
three legs mean: our production path is checked against the reference's
own expression graphs, not merely against our reading of them.

Containment (ADVICE r2, medium): the reference modules EXECUTE
IN-PROCESS with full interpreter access — module import runs arbitrary
top-level code, and nothing about the shim constrains what the executed
modules themselves can do. The mitigation is provenance, not a sandbox:
before any ``exec_module`` the target file's SHA-256 is checked against
the pinned hash of the audited snapshot (``_REFERENCE_SHA256``), so the
only code that can run is the exact bytes reviewed in SURVEY.md — a
tampered or swapped reference tree fails closed. To run a deliberately
different snapshot (e.g. a future refresh), re-audit it and update the
pins, or set ``REFDIFF_ALLOW_UNPINNED=1`` to accept the risk
explicitly. This applies to the production ``--backend polars`` path
too (pipeline._load_refdiff_harness routes through these loaders).
"""

from __future__ import annotations

import contextlib
import hashlib
import importlib.util
import os
import sys
import types

import numpy as np

REFERENCE_DIR = os.environ.get("REFDIFF_REFERENCE_DIR", "/root/reference")
_KERNELS = "MinuteFrequentFactorCalculateMethodsCICC.py"

# SHA-256 of the audited reference snapshot (2025-10-24). exec of any
# file that does not match fails closed — see module docstring.
_REFERENCE_SHA256 = {
    "Factor.py":
        "ccfa843b81a3aa2ebe8a8716306c467737fb0124969b045905b5f74ea4fff997",
    "MinuteFrequentFactorCICC.py":
        "543a9242b42d41342acadc3044b291181947c7d21e857edc8b59b3b694e026fb",
    _KERNELS:
        "d242416203f1c42a3a315a9a28fd8bf3142fda444aed87a5a4bbe203ad328f52",
}


def _verified_reference_source(filename):
    """Read ``filename`` under REFERENCE_DIR ONCE and hash exactly the
    bytes that will be executed (hash-then-reread would reopen the
    TOCTOU window the pin exists to close). Returns (path, source)."""
    path = os.path.join(REFERENCE_DIR, filename)
    with open(path, "rb") as fh:
        source = fh.read()
    digest = hashlib.sha256(source).hexdigest()
    if digest != _REFERENCE_SHA256.get(filename):
        if os.environ.get("REFDIFF_ALLOW_UNPINNED") == "1":
            return path, source
        raise RuntimeError(
            f"refusing to execute unpinned reference file {path}: "
            f"sha256 {digest} != audited pin "
            f"{_REFERENCE_SHA256.get(filename)}; re-audit the snapshot "
            "and update harness._REFERENCE_SHA256, or set "
            "REFDIFF_ALLOW_UNPINNED=1 to accept the risk")
    return path, source


def _exec_reference_module(filename, modname, extra_modules=None):
    """Compile + exec the verified bytes of a reference file as a module,
    with the shim (and any ``extra_modules``) resolvable only for the
    exec's duration."""
    path, source = _verified_reference_source(filename)
    mod = types.ModuleType(modname)
    mod.__file__ = path
    code = compile(source, path, "exec")
    with _modules_installed(polars=install_shim(),
                            **(extra_modules or {})):
        exec(code, mod.__dict__)
    return mod


@contextlib.contextmanager
def _modules_installed(**mods):
    """Temporarily install ``sys.modules`` entries for the duration of a
    reference ``exec_module`` (its top-level ``import polars`` /
    ``from Factor import Factor`` must resolve to the shim-backed
    modules), restoring the previous state afterwards so a later genuine
    ``import polars`` or ``import Factor`` in this process cannot
    silently resolve to refdiff internals (ADVICE r2, low)."""
    missing = object()
    prior = {name: sys.modules.get(name, missing) for name in mods}
    sys.modules.update(mods)
    try:
        yield
    finally:
        for name, old in prior.items():
            if old is missing:
                sys.modules.pop(name, None)
            else:
                sys.modules[name] = old

# f64-vs-f64, but not bit-identical: the oracle anchors moment passes
# (oracle/stats.py pearson) and orders summations differently. Defaults
# are near-machine; per-factor widenings must cite evidence.
RTOL_DEFAULT = 1e-9
ATOL_DEFAULT = 1e-12
RTOL = {
    # rolling cov/var chains: shim uses np.var/np.cov-style two-pass per
    # window, oracle uses cumulative-sum identities (oracle/kernels.py
    # _rolling50) — f64 agreement to ~1e-12 normally, but the qrs
    # z-score divides by beta_std which can be ~1e-10 on near-constant
    # windows, amplifying the summation-order difference
    "mmt_ols_qrs": 1e-6, "mmt_ols_beta_zscore_last": 1e-6,
    "mmt_ols_corr_square_mean": 1e-8, "mmt_ols_corr_mean": 1e-8,
    "mmt_ols_beta_mean": 1e-8,
    # skew/kurt ratios: both sides compute biased g1/g2 but with
    # different centering-order; near-zero kurtosis amplifies
    "shape_skratio": 1e-7, "shape_skratioVol": 1e-7,
}
ATOL = {
    # correlations: near-zero r is a cancelling sum in both backends
    "corr_prv": 1e-10, "corr_prvr": 1e-10, "corr_pv": 1e-10,
    "corr_pvd": 1e-10, "corr_pvl": 1e-10, "corr_pvr": 1e-10,
}


_shim_proxy = None


def install_shim() -> types.ModuleType:
    """Return the polars module the differentials run on: a REAL polars
    if one is importable (strictly better than the shim), else a proxy
    around ``tools.refdiff.polars_shim``.

    Despite the historical name this does NOT mutate ``sys.modules``:
    callers use the returned module directly, and the reference
    ``exec_module`` sites install it only for the duration of the exec
    via ``_modules_installed`` (ADVICE r2, low). The proxy exists
    because the shim cannot define a module-level ``len`` without
    shadowing the builtin for its own internals.
    """
    global _shim_proxy
    if _shim_proxy is not None:
        return _shim_proxy
    existing = sys.modules.get("polars")
    if (existing is not None
            and not getattr(existing, "__is_refdiff_shim__", False)) \
            or importlib.util.find_spec("polars"):
        import polars as real

        _shim_proxy = real
        return real
    from tools.refdiff import polars_shim as shim

    mod = types.ModuleType("polars")
    for k in dir(shim):
        if not k.startswith("_"):
            setattr(mod, k, getattr(shim, k))
    mod.len = shim._pl_len
    mod.__is_refdiff_shim__ = True
    _shim_proxy = mod
    return mod


_ref_kernels_mod = None


def load_reference_kernels():
    """Import the reference's kernel module against the shim (cached)."""
    global _ref_kernels_mod
    if _ref_kernels_mod is not None:
        return _ref_kernels_mod
    _ref_kernels_mod = _exec_reference_module(_KERNELS,
                                              "refdiff_ref_kernels")
    return _ref_kernels_mod


def day_frame(day: dict):
    """Long-format day columns (data.synth_day output) -> shim DataFrame,
    sorted (code, time) like a real day file."""
    pl = install_shim()
    order = np.lexsort((np.asarray(day["time"]), np.asarray(day["code"])))
    cols = {
        "code": np.asarray(day["code"])[order],
        "date": np.asarray(day["date"])[order]
        if "date" in day else np.repeat("2024-01-02", order.size),
        "time": np.asarray(day["time"], dtype=np.int64)[order],
    }
    for k in ("open", "high", "low", "close", "volume"):
        cols[k] = np.asarray(day[k], dtype=np.float64)[order]
    return pl.DataFrame(cols)


def run_reference(day: dict, names=None) -> dict:
    """{factor_name: {code: float}} via the reference's own cal_* code."""
    mod = load_reference_kernels()
    df = day_frame(day)
    if names is None:
        names = [n[4:] for n in dir(mod) if n.startswith("cal_")]
    out = {}
    for name in names:
        res = getattr(mod, "cal_" + name)(df)
        codes = res["code"].to_numpy()
        vals = res[name].to_numpy()
        out[name] = {str(c): float(v) for c, v in zip(codes, vals)}
    return out


def run_oracle(day: dict, names=None) -> dict:
    """Same shape via this repo's numpy oracle (f64)."""
    import pandas as pd

    from replication_of_minute_frequency_factor_tpu.oracle import (
        compute_oracle)

    df = pd.DataFrame({
        "code": day["code"],
        "date": day.get("date", np.repeat("2024-01-02",
                                          len(day["code"]))),
        "time": day["time"],
        **{k: day[k] for k in ("open", "high", "low", "close", "volume")},
    })
    wide = compute_oracle(df, names=names)
    out = {}
    for name in wide.columns:
        if name in ("code", "date"):
            continue
        out[name] = {str(c): float(v)
                     for c, v in zip(wide["code"], wide[name])}
    return out


_ref_factor_mod = None


def load_reference_factor_module():
    """Import the reference's Factor.py (evaluation layer) on the shim."""
    global _ref_factor_mod
    if _ref_factor_mod is not None:
        return _ref_factor_mod
    os.environ.setdefault("MPLBACKEND", "Agg")
    _ref_factor_mod = _exec_reference_module("Factor.py",
                                             "refdiff_ref_factor")
    return _ref_factor_mod


def synth_eval_data(rng, n_codes=18, n_days=90, nan_prob=0.06,
                    missing_row_prob=0.04, start="2024-01-01"):
    """Synthetic exposure + daily-PV long tables for the eval differential.

    Weekday dates only; exposure has NaN patches (reference filters them
    in ic_test, buckets them null in group_test); PV drops some rows
    (suspended stocks) and includes tmc/cmc weights.
    """
    all_days = np.arange(np.datetime64(start, "D"),
                         np.datetime64(start, "D") + int(n_days * 1.5))
    dates = all_days[(all_days.astype(int) + 3) % 7 < 5][:n_days]
    codes = np.array([f"{600000 + i:06d}" for i in range(n_codes)])
    cc, dd = np.meshgrid(np.arange(n_codes), np.arange(len(dates)),
                         indexing="ij")
    code_col = codes[cc.ravel()]
    date_col = dates[dd.ravel()]
    n = code_col.size
    # exposure: f32-representable values so both stacks see identical bits
    expo_val = rng.normal(0, 1, n).astype(np.float32).astype(np.float64)
    expo_val[rng.random(n) < nan_prob] = np.nan
    exposure = {"code": code_col, "date": date_col, "value": expo_val}
    keep = rng.random(n) >= missing_row_prob
    pv = {
        "code": code_col[keep],
        "date": date_col[keep],
        "pct_change": np.round(rng.normal(0, 0.02, keep.sum()), 6),
        "tmc": np.round(np.exp(rng.normal(10, 1, keep.sum())), 2),
        "cmc": np.round(np.exp(rng.normal(9, 1, keep.sum())), 2),
    }
    # a few zero-cap rows exercise the sum==0 -> 0 weight fallback
    zero = rng.random(keep.sum()) < 0.01
    pv["tmc"][zero] = 0.0
    return exposure, pv


def _exposure_frame(pl, exposure, factor_name, nan_as_value=False):
    """Exposure long table as the reference would hold it.

    Default: NaN factor values are NULLS (what its parquet cache holds
    when kernels emit null for undefined values). ``nan_as_value=True``
    keeps them as float NaN with a true validity bit — the provenance
    polars arithmetic like 0/0 produces, and the scenario where the
    ``qcut_nan`` pin ambiguity is actually reachable in group_test
    (see SEMANTIC_PINS; tests/test_pin_bounds.py)."""
    from tools.refdiff.polars_shim import Series as ShimSeries

    vals = np.asarray(exposure["value"], np.float64)
    if getattr(pl, "__is_refdiff_shim__", False):
        validity = (np.ones(vals.size, bool) if nan_as_value
                    else np.isfinite(vals))
        val_col = ShimSeries(vals, validity)
    elif nan_as_value:  # real polars: NaN is an ordinary float value
        val_col = [float(v) for v in vals]
    else:  # real polars: None marks null
        val_col = [None if not np.isfinite(v) else float(v) for v in vals]
    return pl.DataFrame({
        "code": exposure["code"],
        "date": exposure["date"].astype("datetime64[D]"),
        factor_name: val_col,
    })


def run_reference_eval(exposure, pv, factor_name="f", future_days=5,
                       frequency="monthly", weight_param=None,
                       group_num=5, nan_as_value=False):
    """Reference Factor.ic_test + group_test on the shim.

    ``_read_daily_pv_data`` is replaced (its body is a read of a
    hardcoded Windows path, Factor.py:49); everything from Factor.py:127
    onward runs verbatim. Returns (stats, ic_rows, group_rows).
    """
    pl = install_shim()
    mod = load_reference_factor_module()
    f = mod.Factor(factor_name, _exposure_frame(pl, exposure,
                                                factor_name,
                                                nan_as_value))

    def fake_read(column_need=None):
        cols = column_need or list(pv)
        return pl.DataFrame({c: pv[c] for c in cols})

    orig = mod.Factor._read_daily_pv_data
    mod.Factor._read_daily_pv_data = staticmethod(fake_read)
    try:
        cov_df = f.coverage(plot_out=False, return_df=True)
        ic_df = f.ic_test(future_days=future_days, plot_out=False,
                          return_df=True)
        stats = {"IC": f.IC, "ICIR": f.ICIR, "rank_IC": f.rank_IC,
                 "rank_ICIR": f.rank_ICIR}
        group_df = f.group_test(frequency=frequency,
                                weight_param=weight_param,
                                group_num=group_num, plot_out=False,
                                return_df=True)
    finally:
        mod.Factor._read_daily_pv_data = orig
    ic_rows = {np.datetime64(d, "D"): (float(i), float(r))
               for d, i, r in zip(ic_df["date"].to_numpy(),
                                  ic_df["IC"].to_numpy(),
                                  ic_df["rank_IC"].to_numpy())}
    # coverage: dates whose whole cross-section is NaN are absent from
    # the reference's output (the filter drops their rows pre-group_by)
    cov_rows = {np.datetime64(d, "D"): int(v)
                for d, v in zip(cov_df["date"].to_numpy(),
                                cov_df[factor_name].to_numpy())}
    group_rows = {}
    labels = group_df["group"].to_numpy()
    for d, g, r in zip(group_df["date"].to_numpy(), labels,
                       group_df["pct_change"].to_numpy()):
        gi = int(str(g).rsplit("_", 1)[1]) - 1
        group_rows[(np.datetime64(d, "D"), gi)] = float(r)
    return stats, ic_rows, group_rows, cov_rows


_FREQ_REF_TO_REPO = {"weekly": "week", "monthly": "month",
                     "quarterly": "quarter", "yearly": "year"}
_EVERY = {"weekly": "1w", "monthly": "1mo", "quarterly": "1q",
          "yearly": "1y"}


def run_repo_eval(exposure, pv, tmp_dir, factor_name="f", future_days=5,
                  frequency="monthly", weight_param=None, group_num=5):
    """Same scenario through this repo's Factor (production eval path)."""
    import pyarrow as pa

    from replication_of_minute_frequency_factor_tpu import Factor
    from replication_of_minute_frequency_factor_tpu.data.io import (
        write_parquet_atomic)

    pv_path = os.path.join(tmp_dir, "daily_pv.parquet")
    write_parquet_atomic(pa.table({
        "Stkcd": pv["code"],
        "Trddt": np.datetime_as_string(pv["date"].astype("datetime64[D]")),
        "ChangeRatio": pv["pct_change"],
        "Dsmvtll": pv["tmc"],
        "Dsmvosd": pv["cmc"],
    }), pv_path)
    f = Factor(factor_name).set_exposure(exposure["code"],
                                         exposure["date"],
                                         exposure["value"])
    cov = f.coverage(plot=False, return_df=True)
    cov_rows = {np.datetime64(d, "D"): int(v)
                for d, v in zip(cov["date"], cov["coverage"]) if v > 0}
    ic = f.ic_test(future_days=future_days, plot=False, return_df=True,
                   daily_pv_path=pv_path)
    stats = {"IC": f.IC, "ICIR": f.ICIR, "rank_IC": f.rank_IC,
             "rank_ICIR": f.rank_ICIR}
    gt = f.group_test(frequency=_FREQ_REF_TO_REPO[frequency],
                      weight_param=weight_param, group_num=group_num,
                      plot=False, return_df=True,
                      daily_pv_path=pv_path)
    ic_rows = {np.datetime64(d, "D"): (float(i), float(r))
               for d, i, r in zip(ic["date"], ic["IC"], ic["rank_IC"])}
    # repo periods are labeled by period START; the reference labels by
    # the right edge — map start -> right edge for comparison
    group_rows = {}
    from tools.refdiff.polars_shim import _bucket_label
    for pi, p in enumerate(gt["period"]):
        right = _bucket_label(np.datetime64(p, "D"),
                              _EVERY[frequency], "right")
        for gi in range(group_num):
            v = gt["group_return"][pi, gi]
            if np.isfinite(v):
                group_rows[(right, gi)] = float(v)
    return stats, ic_rows, group_rows, cov_rows


def compare_eval(rng_seed=0, future_days=5, frequency="monthly",
                 weight_param=None, group_num=5, tmp_dir=None,
                 nan_as_value=False, **synth_kw):
    """Full eval differential; returns a list of mismatch strings."""
    import tempfile

    rng = np.random.default_rng(rng_seed)
    exposure, pv = synth_eval_data(rng, **synth_kw)
    own_tmp = tmp_dir is None
    if own_tmp:
        tmp_ctx = tempfile.TemporaryDirectory()
        tmp_dir = tmp_ctx.name
    try:
        ref_stats, ref_ic, ref_grp, ref_cov = run_reference_eval(
            exposure, pv, future_days=future_days, frequency=frequency,
            weight_param=weight_param, group_num=group_num,
            nan_as_value=nan_as_value)
        repo_stats, repo_ic, repo_grp, repo_cov = run_repo_eval(
            exposure, pv, tmp_dir, future_days=future_days,
            frequency=frequency, weight_param=weight_param,
            group_num=group_num)
    finally:
        if own_tmp:
            tmp_ctx.cleanup()
    failures = []
    if ref_cov != repo_cov:
        extra = set(ref_cov.items()) ^ set(repo_cov.items())
        failures.append(f"coverage mismatch on {sorted(extra)[:6]}")
    # IC series: repo eval kernels run f32 on device -> ~1e-4 absolute
    for d in sorted(set(ref_ic) | set(repo_ic)):
        if d not in ref_ic or d not in repo_ic:
            failures.append(f"ic date {d}: only in "
                            f"{'reference' if d in ref_ic else 'repo'}")
            continue
        for j, nm in enumerate(("IC", "rank_IC")):
            a, b = ref_ic[d][j], repo_ic[d][j]
            if np.isnan(a) != np.isnan(b) or (
                    not np.isnan(a)
                    and not np.isclose(a, b, rtol=5e-4, atol=2e-4)):
                failures.append(f"{nm}@{d}: ref={a!r} repo={b!r}")
    for k in ("IC", "ICIR", "rank_IC", "rank_ICIR"):
        a, b = ref_stats[k], repo_stats[k]
        if a is None or b is None:
            if a is not None or b is not None:
                failures.append(f"{k}: ref={a!r} repo={b!r}")
            continue
        if not np.isclose(a, b, rtol=1e-3, atol=2e-4):
            failures.append(f"{k}: ref={a!r} repo={b!r}")
    # group returns: all-f64 on both sides -> tight
    for key in sorted(set(ref_grp) | set(repo_grp)):
        if key not in ref_grp or key not in repo_grp:
            failures.append(f"group {key}: only in "
                            f"{'reference' if key in ref_grp else 'repo'}")
            continue
        a, b = ref_grp[key], repo_grp[key]
        if not np.isclose(a, b, rtol=1e-8, atol=1e-10):
            failures.append(f"group {key}: ref={a!r} repo={b!r}")
    return failures


class RefdiffUnsupported(RuntimeError):
    """Raised when a differential cannot run in this environment (e.g.
    the reference's own code is invalid on real polars — quirk Q13)."""


def _require_shim():
    """The minfreq differentials need the SHIM specifically: the
    reference's calendar-mode ``group_by_dynamic`` call omits the
    required ``index_column`` (quirk Q13), which modern real polars
    rejects with TypeError before any comparison can happen."""
    pl = install_shim()
    if not getattr(pl, "__is_refdiff_shim__", False):
        raise RefdiffUnsupported(
            "reference MinuteFrequentFactorCICC code cannot run on real "
            "polars (quirk Q13: group_by_dynamic without index_column); "
            "these differentials require the shim")
    return pl


class _OsRedirect:
    """Stand-in for the ``os`` module inside the reference's
    MinuteFrequentFactorCICC module: its minute-dir and cache-dir paths
    are hardcoded Windows literals (:64,68), so ``listdir``/``path.join``
    redirect those two roots to harness-provided directories. Everything
    else passes through."""

    _KLINE = r"D:\QuantData\KLine_cleaned"
    _CACHE = r"D:\QuantData\MinuteFreqFactor\CICC Factor"

    class _PathNS:
        def __init__(self, outer):
            self._o = outer

        def join(self, a, *rest):
            a = self._o._map(a)
            return os.path.join(a, *rest)

        def __getattr__(self, name):
            return getattr(os.path, name)

    def __init__(self, kline_dir, cache_dir):
        self._kline = kline_dir
        self._cache = cache_dir
        self.path = self._PathNS(self)

    def _map(self, p):
        if p == self._KLINE:
            return self._kline
        if p == self._CACHE:
            return self._cache
        return p

    def listdir(self, p):
        return sorted(os.listdir(self._map(p)))

    def __getattr__(self, name):
        return getattr(os, name)


def load_reference_minfreq_module(kline_dir, cache_dir):
    """Import the reference's MinuteFrequentFactorCICC.py on the shim.

    ``from Factor import Factor`` resolves to the shim-backed reference
    Factor module; the hardcoded data roots redirect via _OsRedirect.
    Re-imported per call because the redirect dirs change per scenario.
    """
    _require_shim()
    fmod = load_reference_factor_module()
    mod = _exec_reference_module("MinuteFrequentFactorCICC.py",
                                 "refdiff_ref_minfreq",
                                 extra_modules={"Factor": fmod})
    mod.os = _OsRedirect(kline_dir, cache_dir)
    return mod


def write_day_files(minute_dir, days, n_codes=8, seed=0, **synth_kw):
    """Synthetic day parquets named YYYYMMDD.parquet (the reference's
    file-table contract, MinuteFrequentFactorCICC.py:69-77)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    os.makedirs(minute_dir, exist_ok=True)
    from replication_of_minute_frequency_factor_tpu.data.synthetic import (
        synth_day)
    for i, date in enumerate(days):
        rng = np.random.default_rng(seed + i)
        day = synth_day(rng, n_codes=n_codes, date=str(date), **synth_kw)
        n = len(day["code"])
        t = pa.table({
            "code": pa.array(list(day["code"]), pa.string()),
            "date": pa.array([np.datetime64(date, "D").item()] * n,
                             pa.date32()),
            "time": pa.array(np.asarray(day["time"], np.int64)),
            **{k: pa.array(np.asarray(day[k], np.float64))
               for k in ("open", "high", "low", "close", "volume")},
        })
        fname = str(date).replace("-", "") + ".parquet"
        pq.write_table(t, os.path.join(minute_dir, fname))


def run_reference_pipeline(kline_dir, cache_dir, factor_name,
                           path=None):
    """Reference cal_exposure_by_min_data (incremental resume included)
    -> {(code, date): value} and the raw row count."""
    mod = load_reference_minfreq_module(kline_dir, cache_dir)
    kmod = load_reference_kernels()
    f = mod.MinFreqFactor(factor_name)
    f.cal_exposure_by_min_data(getattr(kmod, "cal_" + factor_name),
                               path=path, n_jobs=1)
    out = f.factor_exposure
    codes = out["code"].to_numpy()
    dates = out["date"].to_numpy().astype("datetime64[D]")
    vals = out[factor_name].to_numpy()
    return {(str(c), d): float(v)
            for c, d, v in zip(codes, dates, vals)}, len(codes)


def run_repo_pipeline(minute_dir, factor_name, cache_path=None):
    """Same scenario through this repo's MinFreqFactor.

    ``cache_path`` must always be explicit: ``path=None`` would resolve
    the GLOBAL ``Config.factor_dir`` cache, leaking state between
    scenarios (this exact leak produced phantom dates in an early fuzz
    run of this harness).
    """
    from replication_of_minute_frequency_factor_tpu import MinFreqFactor
    from replication_of_minute_frequency_factor_tpu.config import Config

    if cache_path is None:
        cache_path = os.path.join(minute_dir,
                                  f"_refdiff_{factor_name}.parquet")
    cfg = Config(minute_dir=minute_dir, days_per_batch=4,
                 factor_dir=os.path.dirname(cache_path))
    f = MinFreqFactor(factor_name)
    f.cal_exposure_by_min_data(path=cache_path, minute_dir=minute_dir,
                               cfg=cfg, progress=False)
    exp = f.factor_exposure
    return {(str(c), np.datetime64(d, "D")): float(v)
            for c, d, v in zip(exp["code"], exp["date"],
                               exp[factor_name])}, len(exp["code"])


def compare_pipeline(tmp_dir, factor_name="vol_return1min", n_days=5,
                     n_codes=8, precompute_days=0, seed=0, **synth_kw):
    """Pipeline differential: day files + optional pre-seeded cache ->
    reference incremental driver vs repo driver."""
    _require_shim()  # fail in ms, before the expensive repo precompute
    kline = os.path.join(tmp_dir, "kline")
    ref_cache_dir = os.path.join(tmp_dir, "ref_cache")
    os.makedirs(ref_cache_dir, exist_ok=True)
    all_days = np.arange(np.datetime64("2024-03-04"),
                         np.datetime64("2024-03-04") + n_days * 2)
    days = [d for d in all_days if (d.astype(int) + 3) % 7 < 5][:n_days]
    write_day_files(kline, days, n_codes=n_codes, seed=seed, **synth_kw)

    repo_cache = None
    if precompute_days:
        # seed both caches from the repo's first-pass on the early days
        early = os.path.join(tmp_dir, "early")
        write_day_files(early, days[:precompute_days], n_codes=n_codes,
                        seed=seed, **synth_kw)
        from replication_of_minute_frequency_factor_tpu import MinFreqFactor
        from replication_of_minute_frequency_factor_tpu.config import (
            Config)
        f0 = MinFreqFactor(factor_name)
        f0.cal_exposure_by_min_data(
            minute_dir=early, cfg=Config(minute_dir=early),
            progress=False)
        repo_cache = os.path.join(tmp_dir, f"{factor_name}.parquet")
        f0.to_parquet(repo_cache)
        import shutil
        shutil.copy(repo_cache,
                    os.path.join(ref_cache_dir,
                                 f"{factor_name}.parquet"))

    ref_rows, ref_n = run_reference_pipeline(kline, ref_cache_dir,
                                             factor_name)
    repo_rows, repo_n = run_repo_pipeline(kline, factor_name,
                                          cache_path=repo_cache)
    failures = []
    if ref_n != len(ref_rows):
        failures.append(f"reference emitted duplicate rows "
                        f"({ref_n} vs {len(ref_rows)})")
    if repo_n != len(repo_rows):
        failures.append(f"repo emitted duplicate rows "
                        f"({repo_n} vs {len(repo_rows)})")
    for key in sorted(set(ref_rows) | set(repo_rows)):
        if key not in ref_rows or key not in repo_rows:
            failures.append(
                f"{key}: only in "
                f"{'reference' if key in ref_rows else 'repo'}")
            continue
        a, b = ref_rows[key], repo_rows[key]
        if np.isnan(a) != np.isnan(b):
            failures.append(f"{key}: nan mismatch ref={a!r} repo={b!r}")
        elif not np.isnan(a) and not np.isclose(a, b, rtol=2e-3,
                                                atol=1e-6):
            failures.append(f"{key}: ref={a!r} repo={b!r}")
    return failures


def compare_final_exposure(rng_seed=0, n_codes=10, n_days=60,
                           nan_prob=0.1):
    """cal_final_exposure differential across every (mode, method,
    frequency) config (reference MinuteFrequentFactorCICC.py:114-245)."""
    pl = _require_shim()
    rng = np.random.default_rng(rng_seed)
    exposure, _ = synth_eval_data(rng, n_codes=n_codes, n_days=n_days,
                                  nan_prob=nan_prob)
    name = "f"
    mod = load_reference_minfreq_module("/nonexistent", "/nonexistent")
    rf = mod.MinFreqFactor(name, _exposure_frame(pl, exposure, name))
    from replication_of_minute_frequency_factor_tpu import MinFreqFactor
    pf = MinFreqFactor(name).set_exposure(
        exposure["code"], exposure["date"], exposure["value"])

    _freq_map = {"weekly": "week", "monthly": "month"}
    failures = []
    configs = ([("calendar", f, m) for f in ("weekly", "monthly")
                for m in ("o", "m", "z", "std")]
               + [("days", t, m) for t in (3, 5)
                  for m in ("o", "m", "z", "std")])
    for mode, freq, method in configs:
        tag = f"{mode}/{freq}/{method}"
        ref_df = rf.cal_final_exposure(frequency=freq, method=method,
                                       mode=mode)
        repo_freq = _freq_map.get(freq, freq)
        res = pf.cal_final_exposure(frequency=repo_freq, method=method,
                                    mode=mode)
        out_name = (f"{freq}_{name}_{method}" if mode == "calendar"
                    else f"{name}_{freq}_{method}")
        repo_name = (f"{repo_freq}_{name}_{method}"
                     if mode == "calendar" else out_name)
        rcodes = ref_df["code"].to_numpy()
        rdates = ref_df["date"].to_numpy().astype("datetime64[D]")
        rvals = ref_df[out_name].to_numpy()
        ref_rows = {(str(c), d): float(v)
                    for c, d, v in zip(rcodes, rdates, rvals)}
        rex = res.factor_exposure
        repo_rows = {(str(c), np.datetime64(d, "D")): float(v)
                     for c, d, v in zip(rex["code"], rex["date"],
                                        rex[repo_name])}
        for key in sorted(set(ref_rows) | set(repo_rows)):
            if key not in ref_rows or key not in repo_rows:
                failures.append(
                    f"{tag} {key}: only in "
                    f"{'reference' if key in ref_rows else 'repo'}")
                continue
            a, b = ref_rows[key], repo_rows[key]
            if np.isnan(a) != np.isnan(b):
                failures.append(f"{tag} {key}: nan mismatch "
                                f"ref={a!r} repo={b!r}")
            # repo output is stored f32 (set_exposure) — compare at f32
            # precision against the reference's f64
            elif not np.isnan(a) and not np.isclose(a, b, rtol=5e-6,
                                                    atol=1e-6):
                failures.append(f"{tag} {key}: ref={a!r} repo={b!r}")
    return failures


def compare_day(day: dict, names=None):
    """Run both stacks on one day; return a list of mismatch strings."""
    ref = run_reference(day, names=names)
    orc = run_oracle(day, names=list(ref) if names is None else names)
    failures = []
    for name, ref_vals in sorted(ref.items()):
        orc_vals = orc.get(name, {})
        for code in sorted(set(ref_vals) | set(orc_vals)):
            rv = ref_vals.get(code, np.nan)
            ov = orc_vals.get(code, np.nan)
            if np.isnan(rv) != np.isnan(ov):
                failures.append(f"{name}/{code}: nan mismatch "
                                f"ref={rv!r} oracle={ov!r}")
                continue
            if np.isnan(rv):
                continue
            if np.isinf(rv) or np.isinf(ov):
                if not (np.isinf(rv) and np.isinf(ov)
                        and np.sign(rv) == np.sign(ov)):
                    failures.append(f"{name}/{code}: inf mismatch "
                                    f"ref={rv!r} oracle={ov!r}")
                continue
            rtol = RTOL.get(name, RTOL_DEFAULT)
            atol = ATOL.get(name, ATOL_DEFAULT)
            if not np.isclose(rv, ov, rtol=rtol, atol=atol):
                failures.append(f"{name}/{code}: ref={rv!r} oracle={ov!r}")
    return failures
