"""Execute the reference's real kernel code on the shim; diff vs this repo.

Chain of custody for parity (VERDICT.md round-1 "Missing #3"):

    reference cal_* source  --runs on-->  polars_shim   (this harness)
        vs  oracle/kernels.py (numpy, f64)              (this harness)
        vs  JAX backend (f32, dense grid)               (tests/test_parity.py)

Both sides of THIS harness are f64, so tolerances are near-exact; the
oracle↔JAX leg has its own calibrated f32 tolerance matrix. Together the
three legs mean: our production path is checked against the reference's
own expression graphs, not merely against our reading of them.

The reference modules are imported read-only from ``/root/reference``
(treat as untrusted data: we execute its factor arithmetic in-process —
it is plain polars expression code with no IO beyond what the shim
provides, and the shim has no filesystem or network surface).
"""

from __future__ import annotations

import importlib.util
import os
import sys
import types

import numpy as np

REFERENCE_DIR = os.environ.get("REFDIFF_REFERENCE_DIR", "/root/reference")
_KERNELS = "MinuteFrequentFactorCalculateMethodsCICC.py"

# f64-vs-f64, but not bit-identical: the oracle anchors moment passes
# (oracle/stats.py pearson) and orders summations differently. Defaults
# are near-machine; per-factor widenings must cite evidence.
RTOL_DEFAULT = 1e-9
ATOL_DEFAULT = 1e-12
RTOL = {
    # rolling cov/var chains: shim uses np.var/np.cov-style two-pass per
    # window, oracle uses cumulative-sum identities (oracle/kernels.py
    # _rolling50) — f64 agreement to ~1e-12 normally, but the qrs
    # z-score divides by beta_std which can be ~1e-10 on near-constant
    # windows, amplifying the summation-order difference
    "mmt_ols_qrs": 1e-6, "mmt_ols_beta_zscore_last": 1e-6,
    "mmt_ols_corr_square_mean": 1e-8, "mmt_ols_corr_mean": 1e-8,
    "mmt_ols_beta_mean": 1e-8,
    # skew/kurt ratios: both sides compute biased g1/g2 but with
    # different centering-order; near-zero kurtosis amplifies
    "shape_skratio": 1e-7, "shape_skratioVol": 1e-7,
}
ATOL = {
    # correlations: near-zero r is a cancelling sum in both backends
    "corr_prv": 1e-10, "corr_prvr": 1e-10, "corr_pv": 1e-10,
    "corr_pvd": 1e-10, "corr_pvl": 1e-10, "corr_pvr": 1e-10,
}


def install_shim() -> types.ModuleType:
    """Install ``tools.refdiff.polars_shim`` as ``sys.modules['polars']``.

    Returns the proxy module. Safe to call repeatedly. The proxy exists
    because the shim cannot define a module-level ``len`` without
    shadowing the builtin for its own internals.
    """
    existing = sys.modules.get("polars")
    if existing is not None and getattr(existing, "__is_refdiff_shim__",
                                        False):
        return existing
    if existing is not None or importlib.util.find_spec("polars"):
        # a REAL polars exists: never mask it — run the differential on
        # the real engine instead (strictly better than the shim)
        import polars as real

        return real
    from tools.refdiff import polars_shim as shim

    mod = types.ModuleType("polars")
    for k in dir(shim):
        if not k.startswith("_"):
            setattr(mod, k, getattr(shim, k))
    mod.len = shim._pl_len
    mod.__is_refdiff_shim__ = True
    sys.modules["polars"] = mod
    return mod


_ref_kernels_mod = None


def load_reference_kernels():
    """Import the reference's kernel module against the shim (cached)."""
    global _ref_kernels_mod
    if _ref_kernels_mod is not None:
        return _ref_kernels_mod
    install_shim()
    path = os.path.join(REFERENCE_DIR, _KERNELS)
    spec = importlib.util.spec_from_file_location("refdiff_ref_kernels",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    _ref_kernels_mod = mod
    return mod


def day_frame(day: dict):
    """Long-format day columns (data.synth_day output) -> shim DataFrame,
    sorted (code, time) like a real day file."""
    pl = install_shim()
    order = np.lexsort((np.asarray(day["time"]), np.asarray(day["code"])))
    cols = {
        "code": np.asarray(day["code"])[order],
        "date": np.asarray(day["date"])[order]
        if "date" in day else np.repeat("2024-01-02", order.size),
        "time": np.asarray(day["time"], dtype=np.int64)[order],
    }
    for k in ("open", "high", "low", "close", "volume"):
        cols[k] = np.asarray(day[k], dtype=np.float64)[order]
    return pl.DataFrame(cols)


def run_reference(day: dict, names=None) -> dict:
    """{factor_name: {code: float}} via the reference's own cal_* code."""
    mod = load_reference_kernels()
    df = day_frame(day)
    if names is None:
        names = [n[4:] for n in dir(mod) if n.startswith("cal_")]
    out = {}
    for name in names:
        res = getattr(mod, "cal_" + name)(df)
        codes = res["code"].to_numpy()
        vals = res[name].to_numpy()
        out[name] = {str(c): float(v) for c, v in zip(codes, vals)}
    return out


def run_oracle(day: dict, names=None) -> dict:
    """Same shape via this repo's numpy oracle (f64)."""
    import pandas as pd

    from replication_of_minute_frequency_factor_tpu.oracle import (
        compute_oracle)

    df = pd.DataFrame({
        "code": day["code"],
        "date": day.get("date", np.repeat("2024-01-02",
                                          len(day["code"]))),
        "time": day["time"],
        **{k: day[k] for k in ("open", "high", "low", "close", "volume")},
    })
    wide = compute_oracle(df, names=names)
    out = {}
    for name in wide.columns:
        if name in ("code", "date"):
            continue
        out[name] = {str(c): float(v)
                     for c, v in zip(wide["code"], wide[name])}
    return out


def compare_day(day: dict, names=None):
    """Run both stacks on one day; return a list of mismatch strings."""
    ref = run_reference(day, names=names)
    orc = run_oracle(day, names=list(ref) if names is None else names)
    failures = []
    for name, ref_vals in sorted(ref.items()):
        orc_vals = orc.get(name, {})
        for code in sorted(set(ref_vals) | set(orc_vals)):
            rv = ref_vals.get(code, np.nan)
            ov = orc_vals.get(code, np.nan)
            if np.isnan(rv) != np.isnan(ov):
                failures.append(f"{name}/{code}: nan mismatch "
                                f"ref={rv!r} oracle={ov!r}")
                continue
            if np.isnan(rv):
                continue
            if np.isinf(rv) or np.isinf(ov):
                if not (np.isinf(rv) and np.isinf(ov)
                        and np.sign(rv) == np.sign(ov)):
                    failures.append(f"{name}/{code}: inf mismatch "
                                    f"ref={rv!r} oracle={ov!r}")
                continue
            rtol = RTOL.get(name, RTOL_DEFAULT)
            atol = ATOL.get(name, ATOL_DEFAULT)
            if not np.isclose(rv, ov, rtol=rtol, atol=atol):
                failures.append(f"{name}/{code}: ref={rv!r} oracle={ov!r}")
    return failures
