"""A minimal polars-API interpreter, enough to run ``/root/reference``.

The reference (`Factor.py`, `MinuteFrequentFactorCICC.py`,
`MinuteFrequentFactorCalculateMethodsCICC.py`) is pure polars. This
container has no polars and no way to install it, so this module
implements the exact expression-API subset those files use, backed by
numpy (f64), and is made resolvable as ``polars`` by
``tools.refdiff.harness`` only while a reference module is being
imported (the ``sys.modules`` entry is restored right after exec).
The reference's own expression graphs then execute unmodified — a true
differential against our reimplementations' *structure* (columns,
filters, operation order, quirks Q1-Q7 of SURVEY.md §2.5).

What it cannot test: engine behaviors the expression text doesn't spell
out. Those are pinned here, once, in ``SEMANTIC_PINS`` — each entry
states the behavior this shim implements and the polars documentation it
was pinned against. A wrong pin is a shared-oracle risk, but an audited,
single-location one (VERDICT.md round-1, "Missing #3").

Null vs NaN: polars distinguishes them; so does this shim. A ``Series``
carries a validity mask; NaN is an ordinary float value.
"""

from __future__ import annotations

import numpy as np

SEMANTIC_PINS = {
    "group_order": (
        "group_by emits groups in ascending key order. Real polars order "
        "is nondeterministic unless maintain_order=True (then it is "
        "first-appearance). Affects doc_pdf* cum_sum (quirk Q7) and "
        "mmt_paratio last-minus-first; ascending == the repo-wide Q7 pin "
        "(oracle/kernels.py). For time-sorted input, ascending and "
        "first-appearance coincide for mmt_paratio (AM session sorts and "
        "appears first)."),
    "sum_empty": "sum of an empty/all-null selection is 0 (polars sum).",
    "product_empty": "product of empty/all-null selection is 1.",
    "mean_empty": "mean/min/max/first/last of empty selection is null.",
    "std_small_n": (
        "std/var with n - ddof <= 0 valid observations is null (so a "
        "following fill_null applies; vol_upVol relies on this)."),
    "skew_kurt": (
        "skew is biased Fisher-Pearson g1, kurtosis is biased Fisher "
        "excess g2 (polars defaults, bias=True); zero variance yields "
        "NaN (a value, NOT null — fill_null does not touch it); empty "
        "selection is null."),
    "agg_skip_null": (
        "sum/mean/std/var/skew/kurtosis/product/min/max skip nulls; NaN "
        "participates and propagates (polars treats NaN as a float "
        "value)."),
    "corr_pairwise": (
        "pl.corr/pl.cov use pairwise-complete observations: rows where "
        "both sides are non-null AND (corr only) non-NaN are kept; corr "
        "with <2 pairs is NaN; matches oracle/stats.py pearson. Real "
        "polars likely PROPAGATES a NaN input instead — unreachable in "
        "the reference's usage (every corr input is built from positive "
        "prices, or zero-volume rows are pre-filtered), so the drop "
        "semantics are never exercised differently, but an auditor "
        "should know the shim is oracle-aligned here, not "
        "polars-verified."),
    "total_order": (
        "top_k/bottom_k/sort/rank use polars' total float order: NaN is "
        "greater than +inf; nulls are dropped by top_k/bottom_k, sorted "
        "first by Expr.sort(), ranked null by rank()."),
    "when_null_cond": (
        "a null condition in when/then/otherwise selects the otherwise "
        "branch (null is not 'true')."),
    "pct_change": (
        "x.pct_change() = x / x.shift(1) - 1 with null propagation from "
        "the shifted null (leading element null); 0/0 is NaN, x/0 is "
        "±inf (float semantics, no error)."),
    "cast_int": "cast to integer truncates toward zero (polars cast).",
    "true_division": "/ always yields float, including int/int.",
    "filter_null": "filter drops rows whose predicate is null.",
    "first_last_nulls": "first()/last() include nulls (positional).",
    "cum_sum_null": "cum_sum leaves nulls null and skips them in the "
                    "running total.",
    "rank": "rank() is method='average', ascending (polars default).",
    "len": "pl.len() counts rows including nulls.",
    "qcut_nan": (
        "qcut buckets a NaN value to null (excluded), and computes "
        "breakpoints from finite values only. UNVERIFIABLE against real "
        "polars here: under polars' total float order a NaN could "
        "plausibly land in the TOP bin instead, which would mean the "
        "reference's group_test (which, unlike its ic_test, never "
        "filters NaN exposures — Factor.py:280-292) puts NaN-exposure "
        "stocks into the best-factor bucket. The repo pins the "
        "exclude-NaN reading (eval_ops.qcut_labels); revisit on real "
        "polars."),
    "align_left": (
        "concat(how='align_left') joins every later frame onto the "
        "FIRST frame's rows on the automatically-determined common "
        "columns, output sorted ascending by those columns — so "
        "group_test's period aggregation runs over the exposure grid's "
        "(code, date) rows, and '.last()' picks the last EXPOSURE date "
        "of each period, not the last trading row (quirk Q10)."),
    "constant_window": (
        "var/std/cov/corr anchor the series at its first observation "
        "before the moment pass, so a constant window yields EXACTLY "
        "zero variance and the reference's degenerate-branch guards "
        "(when(var_x*var_y != 0) in mmt_ols_*; corr denominators) take "
        "the degenerate path (null / NaN). UNVERIFIABLE against real "
        "polars in this container: polars' two-pass variance yields "
        "exact 0 on a constant window only when the f64 mean rounds "
        "exactly, so on e.g. a limit-locked stock real polars may emit "
        "cov^2/(var_x*var_y) ~ 1.0 (shared rounding noise) where this "
        "pin yields 0.0 via fill_null. The repo pins the degenerate "
        "reading everywhere (oracle/stats.py anchored pearson; JAX "
        "masked ops); revisit if a real-polars environment becomes "
        "available."),
}

# The two pins flagged UNVERIFIABLE above are parameterized so their
# blast radius can be measured instead of trusted (VERDICT r2 #3): the
# differential runs under BOTH readings and tests/test_pin_bounds.py
# records exactly which outputs change. The single authoritative
# registry is replication_of_minute_frequency_factor_tpu/pins.py — the
# shim consults it lazily so shim and repo can never drift apart; flip
# THERE if a real-polars run ever contradicts a default.


def _pin_reading(name):
    from replication_of_minute_frequency_factor_tpu import pins

    return pins.reading(name)


def pin_reading(**overrides):
    """Context manager: temporarily select alternative pin readings
    (``with pin_reading(constant_window="noise"): ...``). Delegates to
    the one registry in the repo's ``pins.pinned`` — readings validated
    there, jit caches cleared there."""
    from replication_of_minute_frequency_factor_tpu import pins

    return pins.pinned(**overrides)


# --------------------------------------------------------------------------
# Series: values + validity
# --------------------------------------------------------------------------

class Series:
    __slots__ = ("v", "ok")

    def __init__(self, v, ok=None):
        self.v = np.asarray(v)
        if ok is None:
            ok = np.ones(self.v.shape[0], dtype=bool)
        self.ok = np.asarray(ok, dtype=bool)

    def __len__(self):
        return self.v.shape[0]

    @staticmethod
    def scalar(value, valid=True):
        return Series(np.asarray([value]), np.asarray([bool(valid)]))

    def fl(self):
        """Float64 view with NaN at invalid slots."""
        v = self.v.astype(np.float64, copy=True)
        v[~self.ok] = np.nan
        return v

    def to_numpy(self):
        """Match polars Series.to_numpy: nulls become NaN for numerics."""
        return self.fl() if self.v.dtype.kind in "iuf" else self.v

    # eager Series API (the reference uses these on extracted columns,
    # e.g. ic_df['IC'].mean() / .std() / .cum_sum(), Factor.py:187-207)
    def __array__(self, dtype=None, copy=None):
        arr = self.to_numpy()
        return arr.astype(dtype) if dtype is not None else arr

    def __iter__(self):
        return iter(self.to_numpy())

    def __getitem__(self, i):
        if isinstance(i, slice):
            return Series(self.v[i], self.ok[i])
        return self.v[i] if self.ok[i] else None

    def mean(self):
        s = _agg_mean(self)
        return float(s.v[0]) if s.ok[0] else None

    def std(self, ddof=1):
        s = _agg_std(self, ddof)
        return float(s.v[0]) if s.ok[0] else None

    def sum(self):
        return _agg_sum(self).v[0]

    def cum_sum(self):
        filled = np.where(self.ok, self.fl(), 0.0)
        out = np.cumsum(filled)
        out[~self.ok] = np.nan
        return Series(out, self.ok.copy())

    def cum_prod(self):
        filled = np.where(self.ok, self.fl(), 1.0)
        out = np.cumprod(filled)
        out[~self.ok] = np.nan
        return Series(out, self.ok.copy())

    def unique(self):
        return Series(np.unique(self.v[self.ok]))

    def max(self):
        v = self.v[self.ok]
        return v.max() if v.size else None

    def min(self):
        v = self.v[self.ok]
        return v.min() if v.size else None

    def sort(self, descending=False):
        vv = np.sort(self.v[self.ok], kind="stable")
        if descending:
            vv = vv[::-1]
        nulls = int((~self.ok).sum())
        if nulls:
            return Series(np.concatenate([self.v[~self.ok], vv]),
                          np.r_[np.zeros(nulls, bool),
                                np.ones(vv.size, bool)])
        return Series(vv)

    def __add__(self, other):
        if isinstance(other, Series):
            return _binop(self, other, "add")
        return _binop(self, Series.scalar(other), "add")

    def __sub__(self, other):
        if isinstance(other, Series):
            return _binop(self, other, "sub")
        return _binop(self, Series.scalar(other), "sub")

    def __truediv__(self, other):
        if isinstance(other, Series):
            return _binop(self, other, "truediv")
        return _binop(self, Series.scalar(other), "truediv")

    def __mul__(self, other):
        if isinstance(other, Series):
            return _binop(self, other, "mul")
        return _binop(self, Series.scalar(other), "mul")


def _broadcast(a: Series, b: Series):
    if len(a) == len(b):
        return a, b
    if len(a) == 1:
        return Series(np.repeat(a.v, len(b)), np.repeat(a.ok, len(b))), b
    if len(b) == 1:
        return a, Series(np.repeat(b.v, len(a)), np.repeat(b.ok, len(a)))
    raise ValueError(f"length mismatch {len(a)} vs {len(b)}")


def _is_int(arr):
    return arr.dtype.kind in "iu"


def _binop(a: Series, b: Series, op: str) -> Series:
    a, b = _broadcast(a, b)
    ok = a.ok & b.ok
    av, bv = a.v, b.v
    with np.errstate(all="ignore"):
        if op == "truediv":
            out = av.astype(np.float64) / bv.astype(np.float64)
        elif op == "floordiv":
            out = av // bv
        elif op == "mod":
            out = av % bv
        elif op == "add":
            out = av + bv
        elif op == "sub":
            out = av - bv
        elif op == "mul":
            out = av * bv
        elif op == "pow":
            out = av.astype(np.float64) ** bv.astype(np.float64)
        elif op in ("eq", "ne", "lt", "le", "gt", "ge"):
            fn = {"eq": np.equal, "ne": np.not_equal, "lt": np.less,
                  "le": np.less_equal, "gt": np.greater,
                  "ge": np.greater_equal}[op]
            out = fn(av, bv)
        elif op == "and":
            out = av.astype(bool) & bv.astype(bool)
        elif op == "or":
            out = av.astype(bool) | bv.astype(bool)
        else:  # pragma: no cover
            raise ValueError(op)
    return Series(out, ok)


# --------------------------------------------------------------------------
# Aggregations on Series (null-aware). Each returns a length-1 Series.
# --------------------------------------------------------------------------

def _valid(s: Series) -> np.ndarray:
    return s.v[s.ok]


def _agg_sum(s: Series) -> Series:
    v = _valid(s)
    if v.size == 0:
        zero = 0 if _is_int(s.v) else 0.0
        return Series.scalar(zero)
    return Series.scalar(v.sum())


def _agg_product(s: Series) -> Series:
    v = _valid(s)
    return Series.scalar(v.prod() if v.size else 1.0)


def _agg_mean(s: Series) -> Series:
    v = _valid(s)
    if v.size == 0:
        return Series.scalar(np.nan, valid=False)
    return Series.scalar(float(np.mean(v.astype(np.float64))))


def _anchor(v: np.ndarray) -> np.ndarray:
    """Shift a series to its first observation before a moment pass.

    Mathematically a no-op for var/cov/corr (shift invariance); makes a
    constant series produce *exactly* zero variance instead of f64
    rounding noise, so degenerate-branch guards like
    ``when(var_x * var_y != 0)`` (mmt_ols_*) take the degenerate path.
    PIN (``SEMANTIC_PINS['constant_window']``): real polars' behavior on
    a constant window is bit-level data-dependent (its two-pass variance
    yields exact 0 only when the mean rounds exactly); we pin the
    degenerate reading repo-wide (oracle/stats.py anchors identically).
    Under the alternative ``pins.READINGS['constant_window'] == 'noise'``
    reading this is the identity, exposing raw two-pass rounding.
    """
    if _pin_reading("constant_window") == "noise":
        return v
    return v - v[0] if v.size else v


def _agg_std(s: Series, ddof=1) -> Series:
    v = _valid(s).astype(np.float64)
    if v.size - ddof <= 0:
        return Series.scalar(np.nan, valid=False)
    with np.errstate(all="ignore"):
        return Series.scalar(float(np.std(_anchor(v), ddof=ddof)))


def _agg_var(s: Series, ddof=1) -> Series:
    v = _valid(s).astype(np.float64)
    if v.size - ddof <= 0:
        return Series.scalar(np.nan, valid=False)
    with np.errstate(all="ignore"):
        return Series.scalar(float(np.var(_anchor(v), ddof=ddof)))


def _agg_skew(s: Series) -> Series:
    v = _valid(s).astype(np.float64)
    if v.size == 0:
        return Series.scalar(np.nan, valid=False)
    m = v.mean()
    m2 = ((v - m) ** 2).mean()
    m3 = ((v - m) ** 3).mean()
    with np.errstate(all="ignore"):
        return Series.scalar(float(m3 / m2 ** 1.5))


def _agg_kurtosis(s: Series) -> Series:
    v = _valid(s).astype(np.float64)
    if v.size == 0:
        return Series.scalar(np.nan, valid=False)
    m = v.mean()
    m2 = ((v - m) ** 2).mean()
    m4 = ((v - m) ** 4).mean()
    with np.errstate(all="ignore"):
        return Series.scalar(float(m4 / (m2 * m2) - 3.0))


def _agg_first(s: Series) -> Series:
    if len(s) == 0:
        return Series.scalar(np.nan, valid=False)
    return Series(s.v[:1], s.ok[:1])


def _agg_last(s: Series) -> Series:
    if len(s) == 0:
        return Series.scalar(np.nan, valid=False)
    return Series(s.v[-1:], s.ok[-1:])


def _agg_min(s: Series) -> Series:
    v = _valid(s)
    if v.size == 0:
        return Series.scalar(np.nan, valid=False)
    return Series.scalar(v.min())


def _agg_max(s: Series) -> Series:
    v = _valid(s)
    if v.size == 0:
        return Series.scalar(np.nan, valid=False)
    return Series.scalar(v.max())


def _pairwise(a: Series, b: Series):
    a, b = _broadcast(a, b)
    ok = a.ok & b.ok
    return a.fl()[ok], b.fl()[ok]


def _corr2(a: Series, b: Series) -> Series:
    av, bv = _pairwise(a, b)
    keep = ~(np.isnan(av) | np.isnan(bv))
    av, bv = av[keep], bv[keep]
    if av.size < 2:
        return Series.scalar(np.nan)
    av, bv = _anchor(av), _anchor(bv)  # constant -> exactly-zero var -> NaN
    da, db = av - av.mean(), bv - bv.mean()
    with np.errstate(all="ignore"):
        r = (da * db).sum() / np.sqrt((da * da).sum() * (db * db).sum())
    return Series.scalar(float(r))


def _cov2(a: Series, b: Series, ddof=1) -> Series:
    av, bv = _pairwise(a, b)
    n = av.size
    if n - ddof <= 0:
        if n == 0:
            return Series.scalar(np.nan, valid=False)
        return Series.scalar(np.nan)
    av, bv = _anchor(av), _anchor(bv)
    with np.errstate(all="ignore"):
        c = ((av - av.mean()) * (bv - bv.mean())).sum() / (n - ddof)
    return Series.scalar(float(c))


# total float order (polars): NaN > +inf — numpy sort/argsort already
# place NaN last (greatest) for floats, so the identity suffices
def _order_key(v: np.ndarray) -> np.ndarray:
    return v


def _topk(s: Series, k: int, largest: bool) -> Series:
    v = _valid(s)
    srt = np.sort(_order_key(v), kind="stable")  # NaN last == greatest
    sel = srt[-k:] if largest else srt[:k]
    return Series(sel)


def _rank_avg(s: Series) -> Series:
    out = np.full(len(s), np.nan)
    v = s.fl()[s.ok]
    if v.size:
        order = np.argsort(_order_key(v), kind="stable")
        ranks = np.empty(v.size, dtype=np.float64)
        sorted_v = v[order]
        i = 0
        while i < v.size:
            j = i
            while (j + 1 < v.size
                   and (sorted_v[j + 1] == sorted_v[i]
                        or (np.isnan(sorted_v[j + 1])
                            and np.isnan(sorted_v[i])))):
                j += 1
            ranks[order[i:j + 1]] = 0.5 * (i + j) + 1.0
            i = j + 1
        out[s.ok] = ranks
    return Series(out, s.ok.copy())


# --------------------------------------------------------------------------
# Expressions
# --------------------------------------------------------------------------

class Ctx:
    """One evaluation scope: named columns of equal height."""

    __slots__ = ("cols", "height")

    def __init__(self, cols: dict, height: int):
        self.cols = cols
        self.height = height

    def take(self, idx) -> "Ctx":
        cols = {k: Series(s.v[idx], s.ok[idx]) for k, s in self.cols.items()}
        n = int(np.asarray(idx).sum()) if np.asarray(idx).dtype == bool \
            else len(np.asarray(idx))
        return Ctx(cols, n)


def _to_expr(x) -> "Expr":
    if isinstance(x, Expr):
        return x
    return lit(x)


class Expr:
    __slots__ = ("_ev", "_name")

    def __init__(self, ev, name="literal"):
        self._ev = ev
        self._name = name

    # -- operators ---------------------------------------------------------
    def _bin(self, other, op, rhs=False):
        o = _to_expr(other)
        a, b = (o, self) if rhs else (self, o)
        nm = a._name if isinstance(a, Expr) else self._name
        return Expr(lambda c: _binop(a._ev(c), b._ev(c), op), self._name
                    if not rhs else nm)

    def __add__(self, o):
        return self._bin(o, "add")

    def __radd__(self, o):
        return self._bin(o, "add", rhs=True)

    def __sub__(self, o):
        return self._bin(o, "sub")

    def __rsub__(self, o):
        return self._bin(o, "sub", rhs=True)

    def __mul__(self, o):
        return self._bin(o, "mul")

    def __rmul__(self, o):
        return self._bin(o, "mul", rhs=True)

    def __truediv__(self, o):
        return self._bin(o, "truediv")

    def __rtruediv__(self, o):
        return self._bin(o, "truediv", rhs=True)

    def __floordiv__(self, o):
        return self._bin(o, "floordiv")

    def __mod__(self, o):
        return self._bin(o, "mod")

    def __pow__(self, o):
        return self._bin(o, "pow")

    def __eq__(self, o):  # noqa: D105
        return self._bin(o, "eq")

    def __ne__(self, o):
        return self._bin(o, "ne")

    def __lt__(self, o):
        return self._bin(o, "lt")

    def __le__(self, o):
        return self._bin(o, "le")

    def __gt__(self, o):
        return self._bin(o, "gt")

    def __ge__(self, o):
        return self._bin(o, "ge")

    def __and__(self, o):
        return self._bin(o, "and")

    def __or__(self, o):
        return self._bin(o, "or")

    def __invert__(self):
        def ev(c):
            s = self._ev(c)
            return Series(~s.v.astype(bool), s.ok.copy())
        return Expr(ev, self._name)

    def __neg__(self):
        def ev(c):
            s = self._ev(c)
            return Series(-s.v, s.ok.copy())
        return Expr(ev, self._name)

    def __hash__(self):  # __eq__ is overloaded; keep Expr hashable
        return id(self)

    # -- naming / casting --------------------------------------------------
    def alias(self, name):
        return Expr(self._ev, name)

    def cast(self, dtype):
        def ev(c):
            s = self._ev(c)
            if dtype in _INT_DTYPES:
                if s.ok.all() and s.v.dtype.kind in "iuf":
                    return Series(
                        np.trunc(s.v).astype(np.int64) if s.v.dtype.kind
                        == "f" else s.v.astype(np.int64), s.ok.copy())
                # nulls present: keep float carrier, truncate values
                v = s.fl()
                t = np.where(np.isnan(v), v, np.trunc(v))
                return Series(t, s.ok.copy())
            if dtype in _FLOAT_DTYPES:
                return Series(s.v.astype(np.float64), s.ok.copy())
            if dtype in _STR_DTYPES:
                return Series(s.v.astype(str), s.ok.copy())
            raise NotImplementedError(f"cast to {dtype!r}")
        return Expr(ev, self._name)

    # -- elementwise -------------------------------------------------------
    def abs(self):
        def ev(c):
            s = self._ev(c)
            return Series(np.abs(s.v), s.ok.copy())
        return Expr(ev, self._name)

    def pow(self, p):
        return self.__pow__(p)

    def log(self):
        def ev(c):
            s = self._ev(c)
            with np.errstate(all="ignore"):
                return Series(np.log(s.v.astype(np.float64)), s.ok.copy())
        return Expr(ev, self._name)

    def exp(self):
        def ev(c):
            s = self._ev(c)
            with np.errstate(all="ignore"):
                return Series(np.exp(s.v.astype(np.float64)), s.ok.copy())
        return Expr(ev, self._name)

    def fill_null(self, value):
        def ev(c):
            s = self._ev(c)
            if s.ok.all():
                return s
            v = s.v.astype(np.float64, copy=True) \
                if s.v.dtype.kind in "iuf" else s.v.copy()
            v[~s.ok] = value
            return Series(v, np.ones(len(s), bool))
        return Expr(ev, self._name)

    def is_null(self):
        def ev(c):
            s = self._ev(c)
            return Series(~s.ok)
        return Expr(ev, self._name)

    def is_not_null(self):
        def ev(c):
            s = self._ev(c)
            return Series(s.ok.copy())
        return Expr(ev, self._name)

    def is_nan(self):
        def ev(c):
            s = self._ev(c)
            return Series(np.isnan(s.fl()), s.ok.copy())
        return Expr(ev, self._name)

    def is_in(self, values):
        vals = list(values)

        def ev(c):
            s = self._ev(c)
            return Series(np.isin(s.v, vals), s.ok.copy())
        return Expr(ev, self._name)

    def shift(self, n=1):
        def ev(c):
            s = self._ev(c)
            v = s.v.astype(np.float64, copy=True) \
                if s.v.dtype.kind in "iuf" else s.v.astype(object)
            out_v = np.empty_like(v)
            out_ok = np.zeros(len(s), bool)
            if n >= 0:
                out_v[n:] = v[:len(s) - n] if n else v
                out_ok[n:] = s.ok[:len(s) - n] if n else s.ok
                out_v[:n] = np.nan
            else:
                out_v[:n] = v[-n:]
                out_ok[:n] = s.ok[-n:]
                out_v[n:] = np.nan
            return Series(out_v, out_ok)
        return Expr(ev, self._name)

    def pct_change(self, n=1):
        return self / self.shift(n) - 1

    def cum_sum(self):
        def ev(c):
            s = self._ev(c)
            v = s.fl()
            filled = np.where(s.ok, v, 0.0)
            out = np.cumsum(filled)
            out[~s.ok] = np.nan
            return Series(out, s.ok.copy())
        return Expr(ev, self._name)

    def cum_prod(self):
        def ev(c):
            s = self._ev(c)
            v = s.fl()
            filled = np.where(s.ok, v, 1.0)
            out = np.cumprod(filled)
            out[~s.ok] = np.nan
            return Series(out, s.ok.copy())
        return Expr(ev, self._name)

    def rank(self, method="average"):
        if method != "average":
            raise NotImplementedError(method)

        def ev(c):
            return _rank_avg(self._ev(c))
        return Expr(ev, self._name)

    def rolling_mean(self, window_size, min_samples=None):
        return self._rolling_window(window_size, min_samples, "mean")

    def rolling_sum(self, window_size, min_samples=None):
        return self._rolling_window(window_size, min_samples, "sum")

    def rolling_std(self, window_size, min_samples=None, ddof=1):
        return self._rolling_window(window_size, min_samples, "std", ddof)

    def _rolling_window(self, w, min_samples, kind, ddof=1):
        mn = w if min_samples is None else min_samples

        def ev(c):
            s = self._ev(c)
            v = s.fl()
            n = len(s)
            out = np.full(n, np.nan)
            ok = np.zeros(n, bool)
            for i in range(n):
                win = v[max(0, i - w + 1):i + 1]
                winok = s.ok[max(0, i - w + 1):i + 1]
                vv = win[winok]  # nulls skipped inside the window
                # polars min_samples counts NON-NULL values in the
                # window, not slots (verified indirectly: the repo's
                # NaN-window nulling matches only under this reading)
                if vv.size < mn:
                    continue
                if kind == "sum":
                    out[i], ok[i] = vv.sum(), True
                elif kind == "mean":
                    out[i], ok[i] = vv.mean(), True
                elif kind == "std":
                    if vv.size - ddof > 0:
                        out[i], ok[i] = np.std(vv, ddof=ddof), True
            return Series(out, ok)
        return Expr(ev, self._name)

    # -- length-changing ---------------------------------------------------
    def filter(self, cond):
        cexp = _to_expr(cond)

        def ev(c):
            s = self._ev(c)
            cd = cexp._ev(c)
            s2, cd = _broadcast(s, cd)
            keep = cd.ok & cd.v.astype(bool)
            return Series(s2.v[keep], s2.ok[keep])
        return Expr(ev, self._name)

    def sort(self, descending=False):
        def ev(c):
            s = self._ev(c)
            vv = s.v[s.ok]
            order = np.argsort(_order_key(np.asarray(vv)), kind="stable")
            if descending:
                order = order[::-1]
            nulls = int((~s.ok).sum())
            if s.v.dtype.kind in "iuf":
                out = np.concatenate(
                    [np.full(nulls, np.nan), vv[order].astype(np.float64)]) \
                    if nulls else vv[order]
            else:
                out = np.concatenate([s.v[~s.ok], vv[order]])
            ok = np.concatenate([np.zeros(nulls, bool),
                                 np.ones(len(vv), bool)])
            return Series(out, ok)
        return Expr(ev, self._name)

    def top_k(self, k):
        def ev(c):
            return _topk(self._ev(c), k, largest=True)
        return Expr(ev, self._name)

    def bottom_k(self, k):
        def ev(c):
            return _topk(self._ev(c), k, largest=False)
        return Expr(ev, self._name)

    def unique(self):
        def ev(c):
            s = self._ev(c)
            return Series(np.unique(s.v[s.ok]))
        return Expr(ev, self._name)

    # -- aggregations ------------------------------------------------------
    def sum(self):
        return Expr(lambda c: _agg_sum(self._ev(c)), self._name)

    def product(self):
        return Expr(lambda c: _agg_product(self._ev(c)), self._name)

    def mean(self):
        return Expr(lambda c: _agg_mean(self._ev(c)), self._name)

    def std(self, ddof=1):
        return Expr(lambda c: _agg_std(self._ev(c), ddof), self._name)

    def var(self, ddof=1):
        return Expr(lambda c: _agg_var(self._ev(c), ddof), self._name)

    def skew(self):
        return Expr(lambda c: _agg_skew(self._ev(c)), self._name)

    def kurtosis(self):
        return Expr(lambda c: _agg_kurtosis(self._ev(c)), self._name)

    def first(self):
        return Expr(lambda c: _agg_first(self._ev(c)), self._name)

    def last(self):
        return Expr(lambda c: _agg_last(self._ev(c)), self._name)

    def min(self):
        return Expr(lambda c: _agg_min(self._ev(c)), self._name)

    def max(self):
        return Expr(lambda c: _agg_max(self._ev(c)), self._name)

    def len(self):
        return Expr(lambda c: Series.scalar(c.height), self._name)

    def count(self):
        def ev(c):
            s = self._ev(c)
            return Series.scalar(int(s.ok.sum()))
        return Expr(ev, self._name)

    @property
    def str(self):
        return _StrNS(self)

    def qcut(self, quantiles, labels=None, allow_duplicates=False):
        """Quantile bucketing (Factor.py:286-290).

        Breakpoints are linear-interpolation quantiles at i/k over the
        FINITE values; bins are right-closed ``(-inf, q1], (q1, q2],
        ...``; with ``allow_duplicates`` duplicate breakpoints collapse
        and the first ``n_bins`` labels are used (PIN — polars' exact
        label behavior under collapsed bins is not documented; the
        repo's eval_ops.qcut_labels pins the same reading). Null in ->
        null out, and NaN ALSO buckets to null — the pinned side of the
        Q12 ambiguity (see ``SEMANTIC_PINS['qcut_nan']``; the
        unverified alternative is NaN -> top bin under total order).
        """
        if not isinstance(quantiles, int):
            raise NotImplementedError("only integer qcut supported")
        k = quantiles

        def ev(c):
            s = self._ev(c)
            v = s.fl()
            val = v[s.ok]
            out = np.empty(len(s), dtype=object)
            ok = s.ok.copy()
            if val.size == 0:
                return Series(out, np.zeros(len(s), bool))
            finite = val[~np.isnan(val)]
            if finite.size == 0:
                breaks = np.empty(0)
            else:
                breaks = np.quantile(finite, np.arange(1, k) / k,
                                     method="linear")
            if allow_duplicates:
                breaks = np.unique(breaks)
            elif np.unique(breaks).size != breaks.size:
                raise ValueError("duplicate qcut breakpoints "
                                 "(allow_duplicates=False)")
            lab = labels if labels is not None else [
                f"bin_{i}" for i in range(breaks.size + 1)]
            if len(lab) < breaks.size + 1:
                raise ValueError("not enough qcut labels")
            # right-closed bins: index = first break >= value
            idx = np.searchsorted(breaks, v, side="left")
            if _pin_reading("qcut_nan") == "top_bin":
                # alternative reading: NaN sorts above +inf (polars
                # total order) -> last bucket; stays non-null
                idx = np.where(np.isnan(v), breaks.size, idx)
            else:
                # PIN (SEMANTIC_PINS['qcut_nan']): NaN buckets to null
                ok &= ~np.isnan(v)
            for i in np.nonzero(ok)[0]:
                out[i] = lab[idx[i]]
            return Series(out, ok)
        return Expr(ev, self._name)

    # -- window ------------------------------------------------------------
    def over(self, keys, *more):
        key_list = [keys] if isinstance(keys, str) else list(keys)
        key_list += list(more)

        def ev(c):
            out_v = None
            out_ok = np.zeros(c.height, bool)
            for idx in _partition_indices(c, key_list):
                sub = self._ev(c.take(idx))
                if out_v is None:
                    proto = sub.v if sub.v.dtype.kind not in "iu" \
                        else sub.v.astype(np.float64)
                    out_v = np.empty(c.height, dtype=np.float64
                                     if proto.dtype.kind in "iuf"
                                     else object)
                    if out_v.dtype.kind == "f":
                        out_v[:] = np.nan
                if len(sub) == 1 and idx.size != 1:
                    out_v[idx] = sub.v[0]
                    out_ok[idx] = sub.ok[0]
                elif len(sub) == idx.size:
                    out_v[idx] = sub.v
                    out_ok[idx] = sub.ok
                else:
                    raise ValueError(
                        f"over(): window produced length {len(sub)} for "
                        f"partition of {idx.size}")
            if out_v is None:
                out_v = np.empty(0)
            return Series(out_v, out_ok)
        return Expr(ev, self._name)


class _StrNS:
    """``Expr.str`` namespace — the slice/parse ops the reference's
    day-filename indexing uses (MinuteFrequentFactorCICC.py:74-77)."""

    def __init__(self, expr):
        self._e = expr

    def head(self, n):
        e = self._e

        def ev(c):
            s = e._ev(c)
            vals = np.asarray([x[:n] if isinstance(x, str) else x
                               for x in s.v], dtype=object)
            return Series(vals, s.ok.copy())
        return Expr(ev, e._name)

    def to_date(self, format="%Y-%m-%d"):
        import datetime as _dt
        e = self._e

        def ev(c):
            s = e._ev(c)
            out = np.zeros(len(s.v), dtype="datetime64[D]")
            ok = s.ok.copy()
            for i, x in enumerate(s.v):
                if ok[i]:
                    # a bad input raises ValueError (real polars raises
                    # its ComputeError; both abort the query loudly)
                    out[i] = np.datetime64(
                        _dt.datetime.strptime(str(x), format).date())
            return Series(out, ok)
        return Expr(ev, e._name)


class _Col(Expr):
    __slots__ = ()

    def __init__(self, name):
        def ev(c, _n=name):
            try:
                return c.cols[_n]
            except KeyError:
                raise KeyError(
                    f"column {_n!r} not in scope "
                    f"{sorted(c.cols)}") from None
        super().__init__(ev, name)


def col(name):
    if isinstance(name, (list, tuple)):
        raise NotImplementedError("pl.col(list) not used by the reference")
    return _Col(name)


def lit(value):
    if value is None:
        return Expr(lambda c: Series(np.full(1, np.nan),
                                     np.zeros(1, bool)), "literal")
    return Expr(lambda c, _v=value: Series.scalar(_v), "literal")


# pl.len() — exposed as the ``len`` attribute of the proxy module the
# harness installs (defining a module-level ``len`` here would shadow the
# builtin for every internal call in this file).
def _pl_len():
    return Expr(lambda c: Series.scalar(c.height), "len")


def corr(a, b, method="pearson", **kw):
    ea, eb = _to_col(a), _to_col(b)
    if method == "pearson":
        return Expr(lambda c: _corr2(ea._ev(c), eb._ev(c)), "corr")
    if method == "spearman":
        # rank (average ties) the pairwise-complete pairs, then Pearson
        def ev(c):
            sa, sb = ea._ev(c), eb._ev(c)
            sa, sb = _broadcast(sa, sb)
            both = sa.ok & sb.ok
            av, bv = sa.fl()[both], sb.fl()[both]
            keep = ~(np.isnan(av) | np.isnan(bv))
            ra = _rank_avg(Series(av[keep]))
            rb = _rank_avg(Series(bv[keep]))
            return _corr2(ra, rb)
        return Expr(ev, "corr")
    raise NotImplementedError(method)


def cov(a, b=None, ddof=1, **kw):
    ea, eb = _to_col(a), _to_col(b)
    return Expr(lambda c: _cov2(ea._ev(c), eb._ev(c), ddof), "cov")


def var(column, ddof=1):
    e = _to_col(column)
    return Expr(lambda c: _agg_var(e._ev(c), ddof), "var")


def _to_col(x):
    return col(x) if isinstance(x, str) else _to_expr(x)


# --------------------------------------------------------------------------
# when / then / otherwise
# --------------------------------------------------------------------------

class _When:
    def __init__(self, branches, cond):
        self._branches = branches  # [(cond_expr, value_expr), ...]
        self._cond = _to_expr(cond)

    def then(self, value):
        return _Then(self._branches + [(self._cond, _to_expr(value))])


class _Then:
    def __init__(self, branches):
        self._branches = branches

    def when(self, cond):
        return _When(self._branches, cond)

    def otherwise(self, value):
        branches = self._branches
        other = _to_expr(value)

        def ev(c):
            # natural length = the non-scalar part length (0 counts: an
            # empty frame stays empty); when every condition and branch
            # is a scalar aggregate (agg context), the result stays
            # scalar — broadcasting to frame height is the caller's job
            # (_expand in select/with_columns)
            evs = []
            lengths = set()
            for cond, val in branches:
                cs, vs = cond._ev(c), val._ev(c)
                evs.append((cs, vs))
                lengths.update((_shim_len(cs), _shim_len(vs)))
            os_ = other._ev(c)
            lengths.add(_shim_len(os_))
            non_scalar = lengths - {1}
            if len(non_scalar) > 1:
                raise ValueError(f"when/then length mix {sorted(lengths)}")
            height = non_scalar.pop() if non_scalar else 1
            taken = np.zeros(height, bool)
            out_v = np.full(height, np.nan)
            out_ok = np.zeros(height, bool)
            for cs, vs in evs:
                cs = _expand(cs, height)
                vs = _expand(vs, height)
                if vs.v.dtype.kind not in "iufb":
                    raise NotImplementedError(
                        "when/then branches must be numeric; got dtype "
                        f"{vs.v.dtype} (the reference only branches on "
                        "numerics)")
                hit = (~taken) & cs.ok & cs.v.astype(bool)
                out_v[hit] = vs.v[hit].astype(np.float64)
                out_ok[hit] = vs.ok[hit]
                taken |= hit
            os_ = _expand(os_, height)
            if os_.v.dtype.kind not in "iufb":
                raise NotImplementedError(
                    f"otherwise branch must be numeric; got {os_.v.dtype}")
            rest = ~taken
            out_v[rest] = os_.v[rest].astype(np.float64)
            out_ok[rest] = os_.ok[rest]
            return Series(out_v, out_ok)
        # polars names the result after the first then-branch
        name = branches[0][1]._name if branches else "literal"
        return Expr(ev, name)


def _shim_len(s: Series) -> int:
    return s.v.shape[0]


def _expand(s: Series, height: int) -> Series:
    if _shim_len(s) == height:
        return s
    if _shim_len(s) == 1:
        return Series(np.repeat(s.v, height), np.repeat(s.ok, height))
    raise ValueError(f"length {_shim_len(s)} vs height {height}")


def when(cond):
    return _When([], cond)


# --------------------------------------------------------------------------
# DataFrame / LazyFrame / GroupBy / Rolling
# --------------------------------------------------------------------------

def _partition_indices(c: Ctx, keys):
    """Row-index arrays per group, groups in ascending key order (PIN:
    ``SEMANTIC_PINS['group_order']``)."""
    if c.height == 0:
        return []
    cols = [c.cols[k] for k in keys]
    rows = {}
    arrs = [s.v for s in cols]
    for i in range(c.height):
        key = tuple(a[i].item() if hasattr(a[i], "item") else a[i]
                    for a in arrs)
        rows.setdefault(key, []).append(i)
    out = []
    for key in sorted(rows):
        out.append(np.asarray(rows[key], dtype=np.int64))
    return out


class DataFrame:
    def __init__(self, data=None, schema=None):
        # ``schema`` (MinuteFrequentFactorCICC.py:72) is accepted and
        # only sanity-checked: the shim infers dtypes from the values
        if data is None:
            data = {}
        if schema is not None and isinstance(data, dict):
            unknown = set(schema) - set(data)
            if unknown:
                raise ValueError(f"schema names {unknown} not in data")
        if isinstance(data, dict):
            cols = {}
            height = None
            for k, v in data.items():
                s = v if isinstance(v, Series) else Series(np.asarray(v))
                cols[k] = s
                if height is not None and _shim_len(s) != height:
                    raise ValueError(  # real polars raises ShapeError
                        f"column {k!r} length {_shim_len(s)} != {height}")
                height = _shim_len(s)
            self._cols = cols
            self._height = height or 0
        else:
            raise NotImplementedError(type(data))

    # internal
    @staticmethod
    def _from_ctx(ctx: Ctx) -> "DataFrame":
        df = DataFrame()
        df._cols = ctx.cols
        df._height = ctx.height
        return df

    @staticmethod
    def _raw(cols: dict) -> "DataFrame":
        df = DataFrame()
        df._cols = cols
        df._height = _shim_len(next(iter(cols.values()))) if cols else 0
        return df

    def _ctx(self) -> Ctx:
        return Ctx(dict(self._cols), self._height)

    # introspection
    @property
    def height(self):
        return self._height

    @property
    def columns(self):
        return list(self._cols)

    def __len__(self):
        return self._height

    def __getitem__(self, name):
        return self._cols[name]

    def to_dict_of_numpy(self):
        """values with NaN at nulls (harness convenience, not polars API)."""
        out = {}
        for k, s in self._cols.items():
            out[k] = s.fl() if s.v.dtype.kind in "iuf" else s.v
        return out

    # lazy API is a no-op: every op here is eager and pure
    def lazy(self):
        return self

    def collect(self):
        return self

    # core verbs
    def filter(self, *conds):
        keep = np.ones(self._height, bool)
        c = self._ctx()
        for cond in conds:
            s = _to_expr(cond)._ev(c)
            s = _expand(s, self._height)
            keep &= s.ok & s.v.astype(bool)
        return DataFrame._from_ctx(c.take(keep))

    def _eval_into(self, exprs, base):
        c = self._ctx()
        out = dict(base)
        for e in exprs:
            if isinstance(e, str):
                out[e] = self._cols[e]
                continue
            s = e._ev(c)
            s = _expand(s, self._height) if self._height else s
            out[e._name] = s
        return out

    def with_columns(self, *exprs):
        exprs = _flatten(exprs)
        cols = self._eval_into(exprs, self._cols)
        df = DataFrame()
        df._cols = cols
        df._height = self._height
        return df

    def select(self, *exprs):
        exprs = _flatten(exprs)
        cols = self._eval_into(exprs, {})
        df = DataFrame()
        df._cols = cols
        heights = {_shim_len(s) for s in cols.values()}
        df._height = max(heights) if heights else 0
        return df

    def sort(self, by=None, *more, descending=False):
        keys = [by] if isinstance(by, str) else list(by)
        keys += list(more)
        cols = [self._cols[k] for k in keys]
        if all(s.ok.all() and s.v.dtype.kind != "O" for s in cols):
            order = np.lexsort([s.v for s in reversed(cols)])
        else:
            # null-aware path (nulls first, polars default); object
            # columns can hold None cells that break lexsort
            def row_key(i):
                out = []
                for s in cols:
                    if s.ok[i]:
                        out.append((1, s.v[i]))
                    else:
                        out.append((0, 0))
                return tuple(out)
            order = sorted(range(self._height), key=row_key)
            order = np.asarray(order, dtype=np.int64)
        if descending:
            order = order[::-1]
        return DataFrame._from_ctx(self._ctx().take(order))

    def rename(self, mapping):
        df = DataFrame()
        df._cols = {mapping.get(k, k): v for k, v in self._cols.items()}
        df._height = self._height
        return df

    def drop(self, *names):
        names = set(_flatten(names))
        df = DataFrame()
        df._cols = {k: v for k, v in self._cols.items() if k not in names}
        df._height = self._height
        return df

    def group_by(self, keys, *more, maintain_order=False):
        key_list = [keys] if isinstance(keys, str) else list(keys)
        key_list += [m for m in more if isinstance(m, str)]
        return GroupBy(self, key_list)

    def rolling(self, index_column, period, group_by=None, **kw):
        return Rolling(self, index_column, period, group_by or [])

    def group_by_dynamic(self, index_column=None, *, every, label="left",
                         group_by=None, closed="left", **kw):
        if index_column is None:
            # PIN (quirk Q13): cal_final_exposure calls group_by_dynamic
            # with NO index_column (MinuteFrequentFactorCICC.py:145,155,
            # 165,178) — modern polars rejects that call outright
            # (index_column is required), so the reference's calendar
            # mode cannot have run as written on a current engine. The
            # shim infers the frame's unique datetime column, the only
            # reading under which the method works at all; the repo's
            # resampler implements the same reading.
            dt_cols = [k for k, s in self._cols.items()
                       if s.v.dtype.kind == "M"]
            if len(dt_cols) != 1:
                raise ValueError(
                    f"cannot infer dynamic index column from {dt_cols}")
            index_column = dt_cols[0]
        keys = [] if group_by is None else (
            [group_by] if isinstance(group_by, str) else list(group_by))
        return DynamicGroupBy(self, index_column, every, label, keys)

    def join(self, other, on, how="inner"):
        on_list = [on] if isinstance(on, str) else list(on)
        return _join(self, other, on_list, how)

    def write_parquet(self, path, **kw):
        import pyarrow as pa
        import pyarrow.parquet as pq

        arrays = {}
        for k, s in self._cols.items():
            if s.v.dtype.kind == "M":
                arrays[k] = pa.array(s.v.astype("datetime64[D]"),
                                     mask=~s.ok)
            elif s.v.dtype.kind in "iufb":
                arrays[k] = pa.array(s.v, mask=~s.ok)
            else:
                arrays[k] = pa.array(
                    [str(x) if o else None for x, o in zip(s.v, s.ok)],
                    type=pa.string())
        pq.write_table(pa.table(arrays), path)


LazyFrame = DataFrame


class GroupBy:
    def __init__(self, df: DataFrame, keys):
        self._df = df
        self._keys = keys

    def agg(self, *exprs):
        exprs = _flatten(exprs)
        c = self._df._ctx()
        parts = _partition_indices(c, self._keys)
        key_out = {k: [] for k in self._keys}
        names = [e._name for e in exprs]
        if len(set(names)) != len(names):
            raise ValueError(  # real polars raises DuplicateError
                f"duplicate agg output names {names}; use .alias()")
        # pre-create expr columns so zero groups still yield the schema
        agg_out = {e._name: [] for e in exprs}
        agg_ok = {e._name: [] for e in exprs}
        for idx in parts:
            sub = c.take(idx)
            for k in self._keys:
                key_out[k].append(sub.cols[k].v[0])
            for e in exprs:
                s = e._ev(sub)
                if _shim_len(s) != 1:
                    raise ValueError(
                        f"agg expression {e._name!r} returned length "
                        f"{_shim_len(s)} (list aggs unsupported)")
                agg_out.setdefault(e._name, []).append(s.v[0])
                agg_ok.setdefault(e._name, []).append(bool(s.ok[0]))
        df = DataFrame()
        cols = {}
        for k in self._keys:
            cols[k] = Series(np.asarray(key_out[k]))
        for name, vals in agg_out.items():
            oka = np.asarray(agg_ok[name], bool)
            cols[name] = Series(_column_from_cells(vals, oka), oka)
        df._cols = cols
        df._height = len(parts)
        return df


class Rolling:
    """LazyFrame.rolling(index_column, period='Ni', group_by=[...]).

    One output row per input row: aggregates over the same-group rows
    whose index value lies in ``(t - N, t]`` (polars' default closed=
    'right' window with offset=-period). The index must be sorted
    non-decreasing within each group, as real polars requires.
    """

    def __init__(self, df, index_column, period, group_by):
        if not (isinstance(period, str) and period.endswith("i")):
            raise NotImplementedError(f"period {period!r}")
        self._df = df
        self._idx = index_column
        self._n = int(period[:-1])
        self._keys = [group_by] if isinstance(group_by, str) \
            else list(group_by)

    def agg(self, *exprs):
        exprs = _flatten(exprs)
        c = self._df._ctx()
        parts = _partition_indices(c, self._keys) if self._keys \
            else [np.arange(c.height)]
        key_cols = {k: [] for k in self._keys}
        idx_vals = []
        agg_out = {e._name: [] for e in exprs}
        agg_ok = {e._name: [] for e in exprs}
        for idx in parts:
            sub = c.take(idx)
            ts = sub.cols[self._idx]
            if not ts.ok.all():
                raise ValueError("null in rolling index column")
            tv = ts.v.astype(np.int64)
            if len(tv) and (np.diff(tv) < 0).any():
                raise ValueError("rolling index not sorted within group")
            for i in range(sub.height):
                t = tv[i]
                lo = np.searchsorted(tv, t - self._n, side="right")
                hi = np.searchsorted(tv, t, side="right")
                win = sub.take(np.arange(lo, hi))
                for k in self._keys:
                    key_cols[k].append(sub.cols[k].v[0])
                idx_vals.append(t)
                for e in exprs:
                    s = e._ev(win)
                    if _shim_len(s) != 1:
                        raise ValueError("rolling agg must be scalar")
                    agg_out.setdefault(e._name, []).append(s.v[0])
                    agg_ok.setdefault(e._name, []).append(bool(s.ok[0]))
        df = DataFrame()
        cols = {}
        for k in self._keys:
            cols[k] = Series(np.asarray(key_cols[k]))
        cols[self._idx] = Series(np.asarray(idx_vals))
        for name, vals in agg_out.items():
            va = np.asarray(vals, dtype=np.float64)
            oka = np.asarray(agg_ok[name], bool)
            va[~oka] = np.nan
            cols[name] = Series(va, oka)
        df._cols = cols
        df._height = len(idx_vals)
        return df


def _bucket_start(d: np.ndarray, every: str) -> np.ndarray:
    """Calendar window start per date (polars dynamic-window truncation:
    weeks start Monday, months/quarters/years at their first day)."""
    d = d.astype("datetime64[D]")
    if every == "1d":
        return d
    if every == "1w":
        di = d.astype(np.int64)  # days since 1970-01-01 (a Thursday)
        return (di - (di + 3) % 7).astype("datetime64[D]")
    if every == "1mo":
        return d.astype("datetime64[M]").astype("datetime64[D]")
    if every == "1q":
        m = d.astype("datetime64[M]").astype(np.int64)
        return ((m // 3) * 3).astype("datetime64[M]").astype("datetime64[D]")
    if every == "1y":
        return d.astype("datetime64[Y]").astype("datetime64[D]")
    raise NotImplementedError(f"every={every!r}")


def _bucket_label(start: np.datetime64, every: str, label: str):
    if label == "left":
        return start
    if label != "right":
        raise NotImplementedError(f"label={label!r}")
    if every == "1d":
        return start + np.timedelta64(1, "D")
    if every == "1w":
        return start + np.timedelta64(7, "D")
    if every == "1mo":
        return ((start.astype("datetime64[M]") + 1)
                .astype("datetime64[D]"))
    if every == "1q":
        return ((start.astype("datetime64[M]") + 3)
                .astype("datetime64[D]"))
    if every == "1y":
        return ((start.astype("datetime64[Y]") + 1)
                .astype("datetime64[D]"))
    raise NotImplementedError(f"every={every!r}")


class DynamicGroupBy:
    """group_by_dynamic(index, every=..., label=..., group_by=...)
    (Factor.py:293-304, MinuteFrequentFactorCICC.py:145-178): calendar
    windows per group; one output row per non-empty window, windows in
    ascending start order, rows within a window in input order."""

    def __init__(self, df, index_column, every, label, keys):
        self._df = df
        self._idx = index_column
        self._every = every
        self._label = label
        self._keys = keys

    def agg(self, *exprs):
        exprs = _flatten(exprs)
        c = self._df._ctx()
        parts = _partition_indices(c, self._keys) if self._keys \
            else [np.arange(c.height)]
        key_cols = {k: [] for k in self._keys}
        idx_vals = []
        agg_out = {e._name: [] for e in exprs}
        agg_ok = {e._name: [] for e in exprs}
        for idx in parts:
            sub = c.take(idx)
            d = sub.cols[self._idx]
            if not d.ok.all():
                raise ValueError("null in dynamic index column")
            starts = _bucket_start(d.v, self._every)
            for b in np.unique(starts):
                sel = np.nonzero(starts == b)[0]
                win = sub.take(sel)
                for k in self._keys:
                    key_cols[k].append(sub.cols[k].v[0])
                idx_vals.append(_bucket_label(b, self._every, self._label))
                for e in exprs:
                    s = e._ev(win)
                    if _shim_len(s) != 1:
                        raise ValueError("dynamic agg must be scalar")
                    agg_out[e._name].append(s.v[0])
                    agg_ok[e._name].append(bool(s.ok[0]))
        df = DataFrame()
        cols = {}
        for k in self._keys:
            cols[k] = Series(np.asarray(key_cols[k]))
        cols[self._idx] = Series(np.asarray(idx_vals,
                                            dtype="datetime64[D]"))
        for name, vals in agg_out.items():
            oka = np.asarray(agg_ok[name], bool)
            va = _column_from_cells(vals, oka)
            cols[name] = Series(va, oka)
        df._cols = cols
        df._height = len(idx_vals)
        return df


def _column_from_cells(vals, oka):
    """Assemble an agg output column from per-group scalar cells,
    keeping numeric dtype when possible and NaN-ing invalid slots."""
    va = np.asarray(vals)
    if va.dtype.kind in "iu" and not oka.all():
        va = va.astype(np.float64)
    if va.dtype.kind == "f":
        va = va.copy()
        va[~oka] = np.nan
    return va


def _flatten(exprs):
    out = []
    for e in exprs:
        if isinstance(e, (list, tuple)):
            out.extend(e)
        else:
            out.append(e)
    return out


def _join(left: DataFrame, right: DataFrame, on, how):
    lc, rc = left._ctx(), right._ctx()
    rkeys = {}
    for i in range(rc.height):
        key = tuple(rc.cols[k].v[i].item() if hasattr(rc.cols[k].v[i],
                    "item") else rc.cols[k].v[i] for k in on)
        rkeys.setdefault(key, []).append(i)
    li, ri = [], []
    for i in range(lc.height):
        key = tuple(lc.cols[k].v[i].item() if hasattr(lc.cols[k].v[i],
                    "item") else lc.cols[k].v[i] for k in on)
        matches = rkeys.get(key, [])
        if matches:
            for j in matches:
                li.append(i)
                ri.append(j)
        elif how == "left":
            li.append(i)
            ri.append(-1)
    li = np.asarray(li, dtype=np.int64)
    ri = np.asarray(ri, dtype=np.int64)
    cols = {}
    for k, s in lc.cols.items():
        cols[k] = Series(s.v[li], s.ok[li])
    miss = ri < 0
    rj = np.where(miss, 0, ri)
    for k, s in rc.cols.items():
        if k in on:
            continue
        name = k if k not in cols else k + "_right"
        v = s.v[rj]
        ok = s.ok[rj] & ~miss
        if v.dtype.kind in "iu" and miss.any():
            v = v.astype(np.float64)
        if v.dtype.kind == "f":
            v = v.copy()
            v[miss] = np.nan
        cols[name] = Series(v, ok)
    df = DataFrame()
    df._cols = cols
    df._height = len(li)
    return df


def concat(items, how="vertical"):
    frames = list(items)
    if how == "vertical":
        cols = {}
        names = frames[0].columns
        for k in names:
            vs = [f._cols[k].v for f in frames]
            oks = [f._cols[k].ok for f in frames]
            vals = np.concatenate([np.asarray(v) for v in vs])
            cols[k] = Series(vals, np.concatenate(oks))
        df = DataFrame()
        df._cols = cols
        df._height = sum(f.height for f in frames)
        return df
    if how == "align_left":
        # PIN (quirk Q10, Factor.py:163-171,280-283): align on the
        # columns common to every frame, keep only the left-most frame's
        # key rows (left join), output sorted ascending by the common
        # columns in the left frame's column order — matching polars'
        # documented align behavior.
        common = [c for c in frames[0].columns
                  if all(c in f.columns for f in frames[1:])]
        out = frames[0]
        for f in frames[1:]:
            out = _join(out, f, common, "left")
        return out.sort(by=common)
    raise NotImplementedError(f"concat how={how!r}")


def read_parquet(path, **kw):
    """Parquet -> shim DataFrame via pyarrow, preserving null masks
    (polars nulls round-trip; NaN stays a value)."""
    import pyarrow.parquet as pq

    t = pq.read_table(path)
    cols = {}
    for name in t.column_names:
        col = t.column(name)
        arr = col.to_numpy(zero_copy_only=False)
        ok = ~np.asarray(col.is_null().to_numpy(zero_copy_only=False))
        if arr.dtype.kind == "M":
            arr = arr.astype("datetime64[D]")
        elif arr.dtype.kind == "O":
            first = next((x for x in arr if x is not None), None)
            import datetime as _dt
            if isinstance(first, _dt.date):
                vals = np.zeros(len(arr), dtype="datetime64[D]")
                for i, x in enumerate(arr):
                    if ok[i]:
                        vals[i] = np.datetime64(x)
                arr = vals
            else:
                arr = np.asarray([x if x is not None else "" for x in arr],
                                 dtype=object)
        cols[name] = Series(arr, ok)
    return DataFrame._raw(cols)


def scan_parquet(path, **kw):
    return read_parquet(path, **kw)


# dtypes (identity objects; only compared by ``is`` / equality)
class _DType:
    def __init__(self, name):
        self._n = name

    def __repr__(self):
        return self._n


Int8 = _DType("Int8")
Int16 = _DType("Int16")
Int32 = _DType("Int32")
Int64 = _DType("Int64")
UInt32 = _DType("UInt32")
UInt64 = _DType("UInt64")
Float32 = _DType("Float32")
Float64 = _DType("Float64")
Boolean = _DType("Boolean")
String = _DType("String")
Utf8 = String
Date = _DType("Date")

_INT_DTYPES = {Int8, Int16, Int32, Int64, UInt32, UInt64}
_FLOAT_DTYPES = {Float32, Float64}
_STR_DTYPES = {String}
