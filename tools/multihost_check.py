"""Two-process multi-host check, runnable on localhost CPUs.

One process per "host", each with 4 virtual CPU devices, joined through
``jax.distributed.initialize`` into one 8-device global mesh — the
process-level coverage ``parallel/multihost.py`` cannot get from a
single-process test (VERDICT r1 weak #4). Each process feeds only its
own half of the tickers axis via :func:`shard_from_host_local`, runs the
collective-free sharded factor graph, and checks its addressable output
shards against a locally recomputed full-batch reference. When the CPU
backend has a cross-process collectives implementation, a psum
round-trip across hosts is exercised too.

Usage (the parent test spawns these):
    python tools/multihost_check.py <process_id> <port> <out_dir>
Env: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=4
"""

import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

import numpy as np  # noqa: E402

NAMES = ("vol_return1min", "mmt_pm", "corr_pv", "liq_openvol")
N_DAYS, N_TICKERS = 2, 32


def make_batch():
    rng = np.random.default_rng(7)
    shape = (N_DAYS, N_TICKERS, 240)
    close = 10.0 * np.exp(np.cumsum(rng.normal(0, 1e-3, shape), -1))
    open_ = close * (1 + rng.normal(0, 1e-4, shape))
    bars = np.stack([open_, np.maximum(open_, close) * 1.0002,
                     np.minimum(open_, close) * 0.9998, close,
                     rng.integers(0, 1000, shape).astype(np.float64)],
                    axis=-1).astype(np.float32)
    mask = rng.random(shape) > 0.05
    return bars, mask


def main():
    pid, port, outdir = int(sys.argv[1]), sys.argv[2], sys.argv[3]
    import jax

    gloo = True
    try:
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except Exception:
        gloo = False

    from replication_of_minute_frequency_factor_tpu.parallel import (
        multihost, sharded_compute_factors)

    multihost.initialize(coordinator_address=f"127.0.0.1:{port}",
                         num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())
    assert len(jax.local_devices()) == 4

    mesh = multihost.global_mesh((1, 8))
    bars, mask = make_batch()
    half = N_TICKERS // 2
    lo, hi = pid * half, (pid + 1) * half
    gbars, gmask = multihost.shard_from_host_local(
        bars[:, lo:hi], mask[:, lo:hi], mesh)
    assert gbars.shape == (N_DAYS, N_TICKERS, 240, 5), gbars.shape

    out = sharded_compute_factors(gbars, gmask, mesh, names=NAMES)

    # local full-batch reference (deterministic data — every process can
    # rebuild the whole batch even though it only fed half of it)
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        compute_factors_jit)
    ref = compute_factors_jit(bars, mask, names=NAMES)
    for n in NAMES:
        want = np.asarray(ref[n])
        for shard in out[n].addressable_shards:
            got = np.asarray(shard.data)
            w = want[shard.index]
            same_nan = np.isnan(got) == np.isnan(w)
            assert same_nan.all(), (n, shard.index)
            f = ~np.isnan(got)
            np.testing.assert_allclose(got[f], w[f], rtol=2e-5, atol=1e-6,
                                       err_msg=n)

    psum_ok = eval_ok = False
    if gloo:
        # cross-host collective round trip: psum over the tickers axis
        from functools import partial

        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        from jax.experimental.shard_map import shard_map

        @partial(shard_map, mesh=mesh, in_specs=P("days", "tickers"),
                 out_specs=P("days", None))
        def total(x):
            return jax.lax.psum(jnp.sum(x, -1, keepdims=True), "tickers")

        x = np.where(mask.any(-1), 1.0, 0.0)  # [D, T]
        got = np.asarray(jax.block_until_ready(total(x)))
        np.testing.assert_allclose(got[:, 0], x.sum(-1), rtol=1e-6)
        psum_ok = True

        # Distributed EVALUATION across hosts: the production per-date
        # cross-sectional helpers (psum-moment Pearson IC; all_gather
        # ranks -> Spearman) on a [dates, tickers] exposure sharded over
        # both processes, against a locally recomputed reference — the
        # SURVEY §5 "distributed communication backend" exercised at
        # process level, not just a bare psum.
        from replication_of_minute_frequency_factor_tpu.parallel import (
            collectives)

        rng = np.random.default_rng(11)
        expo = np.asarray(ref["vol_return1min"], np.float32)  # [D, T]
        fwd = rng.normal(0, 0.02, expo.shape).astype(np.float32)
        valid = np.isfinite(expo)
        spec = NamedSharding(mesh, P(None, "tickers"))

        def gput(a):
            # every process holds the full (deterministic) value; jax
            # assembles the global array from each process's shards
            return jax.make_array_from_callback(
                a.shape, spec, lambda idx: a[idx])

        ge = gput(np.where(valid, expo, 0.0).astype(np.float32))
        gf = gput(fwd)
        gm = gput(valid)
        ic = np.asarray(jax.block_until_ready(
            collectives.xs_pearson(mesh, ge, gf, gm)))
        ranks = collectives.xs_rank(mesh, ge, gm)
        rx = collectives.xs_rank(mesh, gf, gm)
        rank_ic = np.asarray(jax.block_until_ready(
            collectives.xs_pearson(
                mesh, jnp.where(gm, ranks, 0.0),
                jnp.where(gm, rx, 0.0), gm)))

        # local single-process reference via the eval kernels
        from replication_of_minute_frequency_factor_tpu import eval_ops
        want_ic, want_rank_ic = (
            np.asarray(v) for v in eval_ops.ic_series(
                np.where(valid, expo, 0.0), fwd, valid))
        np.testing.assert_allclose(ic, want_ic, rtol=5e-5, atol=1e-5)
        np.testing.assert_allclose(rank_ic, want_rank_ic, rtol=5e-5,
                                   atol=1e-5)
        eval_ok = True

    # per-host telemetry bundle (ISSUE 9): every process writes its OWN
    # schema-v3 bundle — identity stamps from jax.process_index() — and
    # the parent test merges them through telemetry.aggregate into one
    # pod bundle, the real 2-process exercise of the multihost
    # aggregation path
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        get_telemetry)
    get_telemetry().write(os.path.join(outdir, f"telemetry{pid}"))

    with open(os.path.join(outdir, f"ok{pid}"), "w") as fh:
        fh.write(f"devices=8 psum={'yes' if psum_ok else 'skipped'} "
                 f"eval={'yes' if eval_ok else 'skipped'}")
    print(f"process {pid}: ok (psum "
          f"{'executed' if psum_ok else 'skipped — no cpu collectives'}; "
          f"distributed eval "
          f"{'executed' if eval_ok else 'skipped'})")


if __name__ == "__main__":
    main()
