"""Per-pid CPU-busy sentinel, the Python twin of tools/with_cpu_busy.sh.

CPU-heavy entry points (fuzz harnesses, the test runner) hold a pid file
under ``.cpu_busy.d/`` while they run; ``benchmarks/tunnel_watch.py``
waits for all LIVE owners to finish before launching a timed TPU
session on this 1-core host. Dead owners' files are ignored (and swept)
by the watcher, so a crash can't wedge the watch.

Usage::

    from tools.cpu_busy import cpu_busy
    with cpu_busy("fuzz_refdiff eval"):
        ...
"""

from __future__ import annotations

import contextlib
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BUSY_DIR = os.path.join(REPO, ".cpu_busy.d")


@contextlib.contextmanager
def cpu_busy(label=""):
    os.makedirs(BUSY_DIR, exist_ok=True)
    path = os.path.join(BUSY_DIR, str(os.getpid()))
    with open(path, "w") as fh:
        fh.write(label)
    try:
        yield
    finally:
        with contextlib.suppress(OSError):
            os.remove(path)


def mark_busy(label=""):
    """Create this process's sentinel now, removed at interpreter exit —
    for flat scripts (the fuzz harnesses) where a ``with`` block around
    the whole file would be noise. Crash-safe: a dead pid's file is
    swept by live_owners()."""
    import atexit

    cm = cpu_busy(label)
    cm.__enter__()
    atexit.register(cm.__exit__, None, None, None)


def live_owners():
    """Pids of live sentinel holders; sweeps files of dead pids."""
    try:
        names = os.listdir(BUSY_DIR)
    except OSError:
        return []
    live = []
    for name in names:
        try:
            pid = int(name)
        except ValueError:
            continue
        try:
            os.kill(pid, 0)
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(os.path.join(BUSY_DIR, name))
            continue
        live.append(pid)
    return live
