"""grid_day native-vs-numpy fuzz: random long rows with off-grid times,
unknown codes, duplicates (last-write-wins), sub-minute stamps."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_grid')  # gate timed TPU sessions off this 1-core host
import numpy as np
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu import sessions

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    n = int(rng.integers(1, 2000))
    n_codes = int(rng.integers(1, 12))
    codes = np.array([f"{600000 + rng.integers(0, n_codes):06d}"
                      for _ in range(n)])
    kind = rng.random(n)
    times = np.where(kind < 0.7,
                     sessions.GRID_TIMES[rng.integers(0, 240, n)],
                     rng.choice([92900000, 113000000, 120000000, 150000000,
                                 93000001, 130000000 - 100000, 0, 235959999,
                                 93000000 + 50000], n))
    f = [np.round(rng.uniform(1, 100, n), 2) for _ in range(4)]
    v = rng.integers(0, 1e6, n).astype(np.float64)
    a = grid_day(codes, times, *f, v, use_native=True)
    b = grid_day(codes, times, *f, v, use_native=False)
    try:
        np.testing.assert_array_equal(a.codes, b.codes)
        np.testing.assert_array_equal(a.mask, b.mask)
        np.testing.assert_array_equal(a.bars, b.bars)
    except AssertionError as e:
        fails.append(seed); print(f"SEED {seed}: {str(e)[:200]}", flush=True)
    if (seed - lo + 1) % 100 == 0:
        print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
