"""L3 fuzz: Factor.ic_test / group_test vs pandas oracles over random
ragged panels (NaNs, disjoint codes, short histories, ties)."""
import sys, os, tempfile
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_l3')  # gate timed TPU sessions off this 1-core host
import numpy as np, pandas as pd, scipy.stats
import pyarrow as pa, pyarrow.parquet as pq
from replication_of_minute_frequency_factor_tpu import Factor

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
td = tempfile.mkdtemp()
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    # seeds >= 10k widen the scenario space (historical shapes below
    # keep regression-pinned seeds reproducible)
    if seed < 10_000:
        n_codes = int(rng.integers(3, 12)); n_days = int(rng.integers(6, 25))
    else:
        n_codes = int(rng.integers(3, 30)); n_days = int(rng.integers(4, 60))
    codes = [f"{600000+i:06d}" for i in range(n_codes)]
    days = np.array([np.datetime64("2024-01-01") + i for i in
                     rng.choice(200, n_days, replace=False)])
    days.sort()
    # daily pv: ragged per code
    pv_rows = []
    for c in codes:
        keep = rng.random(n_days) > rng.choice([0.0, 0.25])
        for d in days[keep]:
            pv_rows.append((c, d, rng.normal(0, 0.02),
                            rng.uniform(1e9, 1e10), rng.uniform(7e8, 9e9)))
    pv = pd.DataFrame(pv_rows, columns=["code", "date", "pct_change",
                                        "tmc", "cmc"])
    pv_path = os.path.join(td, f"pv{seed}.parquet")
    pq.write_table(pa.table({
        "code": pa.array(pv["code"]),
        "date": pa.array(pv["date"].to_numpy().astype("datetime64[D]")),
        "pct_change": pa.array(pv["pct_change"]),
        "tmc": pa.array(pv["tmc"]), "cmc": pa.array(pv["cmc"])}), pv_path)
    # exposure: subset of pv rows + some rows NOT in pv + NaNs + ties
    exp = pv.sample(frac=rng.uniform(0.5, 1.0), random_state=seed)[
        ["code", "date"]].copy()
    exp["v"] = rng.normal(0, 1, len(exp))
    if rng.random() < 0.5:
        exp["v"] = np.round(exp["v"], 1)  # ties
    exp.loc[exp.sample(frac=0.1, random_state=seed + 1).index, "v"] = np.nan
    f = Factor("toy").set_exposure(exp["code"].to_numpy(object),
                                   exp["date"].to_numpy().astype("datetime64[D]"),
                                   exp["v"].to_numpy(np.float32))
    N = int(rng.integers(1, 4))
    try:
        f.ic_test(future_days=N, plot=False, daily_pv_path=pv_path)
        # oracle: forward N-day compounded return per code over ITS OWN
        # trading days, joined left onto exposure, per-date correlations
        pvs = pv.sort_values(["code", "date"]).copy()
        fwd = []
        for c, g in pvs.groupby("code"):
            p = g["pct_change"].to_numpy()
            for i in range(len(g)):
                if i + N < len(g):
                    fwd.append(np.prod(1 + p[i + 1:i + N + 1]) - 1)
                else:
                    fwd.append(np.nan)
        pvs["fwd"] = fwd
        j = exp.merge(pvs[["code", "date", "fwd"]], on=["code", "date"],
                      how="left").dropna(subset=["v", "fwd"])
        ics, rics = [], []
        for d, g in j.groupby("date"):
            if len(g) < 2 or g["v"].std() == 0 or g["fwd"].std() == 0:
                continue
            ic = scipy.stats.pearsonr(g["v"], g["fwd"])[0]
            ric = scipy.stats.spearmanr(g["v"], g["fwd"])[0]
            if np.isfinite(ic):
                ics.append(ic)
            if np.isfinite(ric):
                rics.append(ric)
        if ics:
            assert abs(f.IC - np.mean(ics)) < 5e-4, (f.IC, np.mean(ics))
            assert abs(f.rank_IC - np.mean(rics)) < 5e-4, \
                (f.rank_IC, np.mean(rics))
            icir = np.mean(ics) / np.std(ics, ddof=1) if len(ics) > 1 else None
            if icir is not None and np.isfinite(icir):
                assert abs(f.ICIR - icir) < 5e-3 * max(1, abs(icir)), \
                    (f.ICIR, icir)
        else:
            assert f.IC is None or np.isnan(f.IC), f.IC
        # group_test smoke across params (oracle: monotone bucket sizes
        # checked in-suite; here assert clean execution + finite output)
        freq = rng.choice(["week", "month"])
        w = rng.choice([None, "tmc", "cmc"])
        g = f.group_test(frequency=freq, weight_param=None if w is None else str(w),
                         group_num=int(rng.integers(2, 7)), plot=False,
                         return_df=True, daily_pv_path=pv_path)
        assert g is None or np.isfinite(g["cum_return"]).any() or \
            len(g["period"]) == 0
    except AssertionError as e:
        fails.append(seed); print(f"SEED {seed}: {str(e)[:250]}", flush=True)
    except Exception as e:
        fails.append(seed); print(f"SEED {seed} CRASH: {e!r}", flush=True)
    if (seed - lo + 1) % 25 == 0:
        print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
    if (seed - lo + 1) % 10 == 0:
        # bound the in-process XLA-CPU executable cache: shape-varying
        # seeds each compile fresh graphs and the cache never evicts
        # (a 140-seed wide-shape parity run exhausted 128 GB, 2026-08-01)
        import jax
        jax.clear_caches()
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
import shutil
shutil.rmtree(td, ignore_errors=True)
