"""Reference-differential fuzz: the reference's actual cal_* code (via
tools/refdiff/polars_shim) vs the numpy oracle, many seeds x day shapes.

Usage: python tools/fuzz/fuzz_refdiff.py LO HI
"""
import os
import sys
import traceback

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, _REPO)

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_refdiff')  # gate timed TPU sessions off this 1-core host

import numpy as np  # noqa: E402

from replication_of_minute_frequency_factor_tpu.data.synthetic import (  # noqa: E402
    synth_day)
from tools.refdiff import harness  # noqa: E402

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    try:
        rng = np.random.default_rng(seed)
        if seed >= 200_000:
            # resampler + pipeline differential: the reference's actual
            # cal_final_exposure / cal_exposure_by_min_data vs the repo
            mism = harness.compare_final_exposure(
                rng_seed=seed, n_codes=int(rng.integers(4, 14)),
                n_days=int(rng.integers(20, 90)),
                nan_prob=float(rng.choice([0.0, 0.1, 0.3])))
            if not mism and seed % 3 == 0:
                import tempfile
                with tempfile.TemporaryDirectory() as td:
                    mism = harness.compare_pipeline(
                        td, n_days=int(rng.integers(3, 7)),
                        precompute_days=int(rng.integers(0, 3)),
                        n_codes=int(rng.integers(4, 10)), seed=seed)
            if mism:
                fails.append((seed, mism[:5]))
                print(f"SEED {seed} FAILED ({len(mism)}):", flush=True)
                for m in mism[:5]:
                    print("   ", m, flush=True)
            continue
        if seed >= 100_000:
            # evaluation-layer differential: the reference's actual
            # ic_test/group_test (Factor.py) vs this repo's Factor
            mism = harness.compare_eval(
                rng_seed=seed,
                future_days=int(rng.integers(1, 8)),
                frequency=str(rng.choice(
                    ["weekly", "monthly", "quarterly", "yearly"])),
                weight_param=rng.choice([None, "tmc", "cmc"]),
                group_num=int(rng.integers(3, 8)),
                n_codes=int(rng.integers(5, 35)),
                n_days=int(rng.integers(30, 220)),
                nan_prob=float(rng.choice([0.0, 0.05, 0.2, 0.4])),
                missing_row_prob=float(rng.choice([0.0, 0.05, 0.15])),
                # rotate NaN provenance: null (parquet cache) vs value-
                # NaN (polars 0/0 arithmetic) — the qcut_nan pin scenario
                nan_as_value=bool(rng.integers(0, 2)),
            )
            if mism:
                fails.append((seed, mism[:5]))
                print(f"SEED {seed} FAILED ({len(mism)}):", flush=True)
                for m in mism[:5]:
                    print("   ", m, flush=True)
            continue
        # rotate day shapes: universe size, sparsity, degenerate codes
        kw = dict(
            n_codes=int(rng.integers(3, 12)),
            missing_prob=float(rng.choice([0.0, 0.05, 0.2, 0.5])),
            zero_volume_prob=float(rng.choice([0.0, 0.1, 0.3])),
            constant_price_codes=int(rng.integers(0, 3)),
            short_day_codes=int(rng.integers(0, 3)),
        )
        day = synth_day(rng, **kw)
        mism = harness.compare_day(day)
        if mism:
            fails.append((seed, mism[:5]))
            print(f"SEED {seed} FAILED ({len(mism)}):", flush=True)
            for m in mism[:5]:
                print("   ", m, flush=True)
    except Exception as e:
        fails.append((seed, f"crash: {e}"))
        print(f"SEED {seed} CRASHED: {e}", flush=True)
        traceback.print_exc()
    if (seed - lo + 1) % 10 == 0:
        print(f"...{seed - lo + 1} seeds done, {len(fails)} failures",
              flush=True)
        # bound the in-process XLA-CPU executable cache: shape-varying
        # seeds each compile fresh graphs and the cache never evicts
        # (a 140-seed wide-shape parity run exhausted 128 GB, 2026-08-01)
        import jax
        jax.clear_caches()
print(f"DONE {hi - lo} seeds, {len(fails)} failures: "
      f"{[s for s, _ in fails]}")
sys.exit(1 if fails else 0)
