"""Symbolic-search interpreter fuzz: the vmapped postfix evaluator
(search.eval_programs) vs an independent same-precision (f32) numpy
oracle over random genomes, day shapes, and masks.

Comparison policy: condition-aware tolerance (error relative to the
chain's own max intermediate magnitude, since cancellation makes the
final value arbitrarily smaller than its inputs) and degeneracy skips
for measure-zero branch flips (zscore of a near-constant series; a
protected-divide divisor within its accumulated f32 uncertainty of the
gate). A systematic interpreter bug diverges on healthy lanes across
many seeds, which neither escape hatch can mask."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_search')  # gate timed TPU sessions off this 1-core host
import numpy as np
from replication_of_minute_frequency_factor_tpu import search


def np_features(bars, mask):
    # f32 throughout: this is an IMPLEMENTATION oracle (same formulas,
    # same precision, independent code), not a precision oracle — an f64
    # oracle branches differently at the protected-divide |b| > eps and
    # zscore sd > 0 gates and diverges arbitrarily on measure-zero input
    o, h, l, c, v = (bars[..., i].astype(np.float32) for i in range(5))
    eps = np.float32(1e-12)
    ret = (c - o) / np.where(np.abs(o) > eps, o, 1.0)
    vshare = v / np.maximum(
        np.sum(np.where(mask, v, 0.0), axis=-1, keepdims=True), 1.0)
    hlr = (h - l) / np.where(np.abs(l) > eps, l, 1.0)
    tod = np.broadcast_to(
        np.linspace(-1.0, 1.0, bars.shape[-2]).astype(np.float32),
        mask.shape)

    # cross-day state: per-(day, ticker) aggregates over valid bars,
    # shifted to the next day along the leading axis (NaN on day 0)
    def first_valid(x):
        idx = np.argmax(mask, axis=-1)
        val = np.take_along_axis(x, idx[..., None], axis=-1)[..., 0]
        return np.where(mask.any(-1), val, np.float32(np.nan))

    def last_valid(x):
        idx = mask.shape[-1] - 1 - np.argmax(mask[..., ::-1], axis=-1)
        val = np.take_along_axis(x, idx[..., None], axis=-1)[..., 0]
        return np.where(mask.any(-1), val, np.float32(np.nan))

    def prev_day(a):
        return np.concatenate(
            [np.full_like(a[:1], np.nan), a[:-1]], axis=0)

    day_open = first_valid(o)
    prev_close = prev_day(last_valid(c))
    with np.errstate(invalid="ignore", divide="ignore"):
        gap = np.where(np.abs(prev_close) > eps,
                       day_open / prev_close - np.float32(1.0),
                       np.float32(np.nan)).astype(np.float32)
        prev_ret = prev_day(np.where(
            np.abs(day_open) > eps,
            last_valid(c) / day_open - np.float32(1.0),
            np.float32(np.nan))).astype(np.float32)
        # NaN when the previous day has no valid bars (mirrors search
        # _features: 0 would make vprev today's raw volume)
        prev_vol = prev_day(np.where(
            mask.any(-1),
            np.sum(np.where(mask, v, np.float32(0.0)), axis=-1,
                   dtype=np.float32), np.float32(np.nan)))
        vprev = (v / np.maximum(prev_vol[..., None],
                                np.float32(1.0))).astype(np.float32)
    bcast = np.broadcast_to
    feats = np.stack([o, h, l, c, v, ret, vshare, hlr, tod,
                      bcast(gap[..., None], mask.shape),
                      bcast(prev_ret[..., None], mask.shape),
                      vprev])
    assert feats.dtype == np.float32  # a single f64 input would promote all
    return feats


def np_masked_mean(x, m):
    n = m.sum(-1)
    s = np.where(m, x, np.float32(0.0)).sum(-1, dtype=np.float32)
    return np.where(n > 0, s / np.maximum(n, 1),
                    np.float32(np.nan)).astype(np.float32)


def np_masked_std(x, m, ddof=1):
    n = m.sum(-1)
    mu = np_masked_mean(x, m)
    d = np.where(m, x - mu[..., None], np.float32(0.0))
    m2 = (d ** 2).sum(-1, dtype=np.float32)
    with np.errstate(invalid="ignore"):
        return np.sqrt(np.where(n > ddof, m2 / np.maximum(n - ddof, 1),
                                np.float32(np.nan))).astype(np.float32)


def np_windowed_sum(x, w):
    cs = np.cumsum(x, axis=-1, dtype=np.float32)
    shifted = np.concatenate(
        [np.zeros_like(cs[..., :w]), cs[..., :-w]], axis=-1)
    return cs - shifted


def np_rolling_mean(x, m, w):
    s = np_windowed_sum(np.where(m, x, np.float32(0.0)), w)
    n = np_windowed_sum(m.astype(np.float32), w)
    return np.where(n > 0, s / np.maximum(n, np.float32(1.0)),
                    np.float32(0.0)).astype(np.float32)


def np_rolling_std(x, m, w):
    xc = np.where(m, x - np_masked_mean(x, m)[..., None],
                  np.float32(0.0)).astype(np.float32)
    n = np_windowed_sum(m.astype(np.float32), w)
    nn = np.maximum(n, np.float32(1.0))
    mu = np_windowed_sum(xc, w) / nn
    m2 = np_windowed_sum(xc * xc, w) / nn
    return np.sqrt(np.maximum(m2 - mu * mu,
                              np.float32(0.0))).astype(np.float32)


def np_rolling_corr(a, b, m, w):
    ac = np.where(m, a - np_masked_mean(a, m)[..., None],
                  np.float32(0.0)).astype(np.float32)
    bc = np.where(m, b - np_masked_mean(b, m)[..., None],
                  np.float32(0.0)).astype(np.float32)
    n = np_windowed_sum(m.astype(np.float32), w)
    nn = np.maximum(n, np.float32(1.0))
    sa = np_windowed_sum(ac, w) / nn
    sb = np_windowed_sum(bc, w) / nn
    sab = np_windowed_sum(ac * bc, w) / nn
    saa = np_windowed_sum(ac * ac, w) / nn
    sbb = np_windowed_sum(bc * bc, w) / nn
    cov = sab - sa * sb
    va = np.maximum(saa - sa * sa, np.float32(0.0))
    vb = np.maximum(sbb - sb * sb, np.float32(0.0))
    denom = np.sqrt(va * vb)
    ok = (denom > 0) & (n > 1.5)
    with np.errstate(invalid="ignore", divide="ignore"):
        r = np.where(ok, cov / np.where(ok, denom, np.float32(1.0)),
                     np.float32(0.0))
    r = np.clip(r, -1.0, 1.0).astype(np.float32)
    # mirror search.rolling_corr: NaN inputs (cross-day features) must
    # propagate, not launder to 0 through the ok gate
    return np.where(np.isnan(cov) | np.isnan(denom),
                    np.float32(np.nan), r)


def np_unary(k, x, m, flag=None):
    mu = np_masked_mean(x, m)
    sd = np_masked_std(x, m)
    if flag is not None and k == 4:
        # zscore of a (near-)constant series: whether f32 sd rounds to
        # exactly 0 depends on reduction order, and the sd > 0 branch
        # then swings the result by ~1/ulp — incomparable by
        # construction, like the parity suite's beta-std snap band
        with np.errstate(invalid="ignore"):
            xmax = np.max(np.where(m, np.abs(x), 0.0), axis=-1)
        # only where sd is defined: an all-masked/n<=1 lane has sd NaN
        # and must STAY comparable so the halted-ticker -> NaN property
        # is still asserted
        flag |= np.isfinite(sd) & (sd <= 64 * np.finfo(np.float32).eps
                                   * np.nan_to_num(xmax))
    with np.errstate(invalid="ignore"):
        z = (x - mu[..., None]) / np.where(sd[..., None] > 0,
                                           sd[..., None], 1.0)
    lag = np.concatenate([x[..., :1], x[..., :-1]], axis=-1)
    return [x, -x, np.abs(x), np.log1p(np.abs(x)).astype(np.float32),
            z.astype(np.float32), lag,
            np.cumsum(np.where(m, x, np.float32(0.0)), axis=-1,
                      dtype=np.float32),
            (x - lag).astype(np.float32),
            np_rolling_mean(x, m, search.ROLL_FAST),
            np_rolling_mean(x, m, search.ROLL_SLOW),
            np_rolling_std(x, m, search.ROLL_FAST),
            np_rolling_std(x, m, search.ROLL_SLOW)][k]


def np_binary(k, a, b, m=None, flag=None, scale=None):
    eps = np.float32(1e-6)
    if flag is not None and k == 3:
        # protected divide: a divisor within its own accumulated f32
        # uncertainty of the 1e-6 gate (or of zero) can take either
        # branch / either sign depending on upstream rounding — e.g. a
        # masked cumsum of the tod ramp crosses zero mid-series with
        # ~240*eps*scale of noise. Flag those lanes as incomparable;
        # systematic interpreter bugs diverge on lanes far from the
        # gate too, so this cannot mask one.
        uncert = 480 * np.finfo(np.float32).eps \
            * np.maximum(scale, 1.0)[..., None]
        near = m & (np.abs(b).astype(np.float64) <= eps + uncert)
        flag |= near.any(axis=-1)
    with np.errstate(divide="ignore", invalid="ignore"):
        return [a + b, a - b, a * b,
                (a / np.where(np.abs(b) > eps, b,
                              np.where(b >= 0, eps,
                                       -eps))).astype(np.float32),
                np.minimum(a, b), np.maximum(a, b),
                np_rolling_corr(a, b, m, search.ROLL_SLOW)][k]


def np_mask(k, x, m):
    slot = np.arange(m.shape[-1])
    return [m & (slot < 120), m & (slot >= 120), m & (slot < 30),
            m & (slot >= m.shape[-1] - 30),
            m & (x > 0), m & (x < 0)][k]


def np_agg(k, x, m):
    """Masked scalar aggregators, f32, mirroring ops.masked semantics
    (empty mean/std/last/max/min -> NaN; empty sum -> 0; std n<2 NaN)."""
    n = m.sum(-1)
    with np.errstate(invalid="ignore"):
        mean = np_masked_mean(x, m)
        std = np_masked_std(x, m)
        ssum = np.where(m, x, np.float32(0.0)).sum(-1, dtype=np.float32)
        idx_last = np.where(
            m.any(-1), m.shape[-1] - 1 - np.argmax(m[..., ::-1], -1), 0)
        last = np.where(m.any(-1),
                        np.take_along_axis(x, idx_last[..., None],
                                           -1)[..., 0], np.nan)
        mx = np.where(n > 0, np.where(m, x, -np.inf).max(-1), np.nan)
        mn = np.where(n > 0, np.where(m, x, np.inf).min(-1), np.nan)
    return [mean, std, ssum.astype(np.float32), last.astype(np.float32),
            mx.astype(np.float32), mn.astype(np.float32)][k]


_EPS32 = np.float64(np.finfo(np.float32).eps)


def np_eval(genome, bars, mask, skeleton):
    """Returns (value [D,T], chain_scale [D,T], degenerate [D,T],
    err [D,T]).

    ``chain_scale`` is the max |intermediate| per (day, ticker) across
    the program. ``err`` is a first-order propagated bound on how far
    two correct f32 implementations of the same chain may disagree per
    final value: each op adds its own rounding (~eps * |result|) and
    AMPLIFIES upstream error by its condition number — the crucial case
    being protected divide, where a divisor that is itself a
    cancellation-noisy intermediate (e.g. a zscore near its zero
    crossing) multiplies upstream noise by 1/|b| (fuzz seeds
    30229/30676: rel-to-scale diffs of 3.8e-3 and 4.7e-3 on
    divide-by-zscore chains, conditioning the flat 2e-3 bound cannot
    see). Propagation runs in f64 on the f32 values, lanewise."""
    feats = np_features(bars, mask)
    stack = []  # entries: (x [D,T,240] f32, m [D,T,240] bool)
    errs = []   # per-slot f64 [D, T, 240] disagreement bounds
    scale = np.zeros(mask.shape[:-1], np.float64)
    degenerate = np.zeros(mask.shape[:-1], bool)

    def see(x):
        with np.errstate(invalid="ignore"):
            mx = np.max(np.where(mask, np.abs(x.astype(np.float64)), 0.0),
                        axis=-1)
        np.maximum(scale, np.nan_to_num(mx), out=scale)
        return x

    def a64(x):
        return np.abs(x.astype(np.float64))

    def wsum64(x, w):
        cs = np.cumsum(x, axis=-1, dtype=np.float64)
        sh = np.concatenate([np.zeros_like(cs[..., :w]), cs[..., :-w]],
                            axis=-1)
        return cs - sh

    def roll_err(x, m, ex, r, w):
        """Shared bound for the cumsum-implemented rolling ops: upstream
        error averages through the window; the cumsum trick adds reorder
        noise proportional to the running l1 mass (same structure as the
        plain-cumsum rule)."""
        n = np.maximum(wsum64(m.astype(np.float64), w), 1.0)
        xc64 = np.where(m, a64(x), 0.0)
        l1 = np.cumsum(xc64, axis=-1)  # running mass bound for cs noise
        return (wsum64(np.where(m, ex, 0.0), w) / n
                + _EPS32 * 8.0 * l1 / n + _EPS32 * a64(r))

    with np.errstate(invalid="ignore", divide="ignore", over="ignore"):
        for slot, kind in enumerate(skeleton):
            g = int(genome[slot])
            if kind == search.PUSH:
                x = see(feats[g])
                stack.append((x, mask))
                errs.append(4 * _EPS32 * a64(x))
            elif kind == search.UNARY:
                x, m = stack.pop()
                ex = errs.pop()
                r = see(np_unary(g, x, m, flag=degenerate))
                if g == 3:    # log1p|x|: derivative 1/(1+|x|) contracts
                    er = ex / (1.0 + a64(x)) + _EPS32 * a64(r)
                elif g == 4:  # zscore: (x-mu)/sd amplifies by 1/sd
                    mu = np_masked_mean(x, m).astype(np.float64)
                    sd = np_masked_std(x, m).astype(np.float64)
                    xm = np.where(m, a64(x), 0.0)
                    nv = np.maximum(m.sum(-1), 1)
                    e_mu = (np.where(m, ex, 0.0).sum(-1)
                            + _EPS32 * xm.sum(-1)) / nv
                    e_sd = e_mu  # same cancellation structure
                    sd_f = np.where(sd > 0, sd, 1.0)[..., None]
                    er = ((ex + e_mu[..., None]
                           + a64(r) * e_sd[..., None]) / sd_f
                          + _EPS32 * a64(r))
                elif g == 5:  # lag
                    er = np.concatenate([ex[..., :1], ex[..., :-1]], -1)
                elif g == 6:  # cumsum: errors accumulate + reorder noise
                    r64 = np.nan_to_num(a64(r))
                    er = (np.cumsum(np.where(m, ex, 0.0), -1)
                          + _EPS32 * np.maximum.accumulate(r64, -1)
                          * np.arange(1, r.shape[-1] + 1))
                elif g == 7:  # delta1 = x - lag1
                    er = (ex
                          + np.concatenate([ex[..., :1], ex[..., :-1]],
                                           -1) + _EPS32 * a64(r))
                elif g in (8, 9):   # rolling mean
                    w = (search.ROLL_FAST if g == 8 else search.ROLL_SLOW)
                    er = roll_err(x, m, ex, r, w)
                elif g in (10, 11):  # rolling std: sqrt conditioning
                    w = (search.ROLL_FAST if g == 10
                         else search.ROLL_SLOW)
                    # m2's bound, then |Δsqrt| <= Δm2/(2 sqrt(m2)) with a
                    # sqrt(Δm2) floor where m2 is within its own noise
                    sc = np.where(m, a64(x - np_masked_mean(x, m)
                                         [..., None]), 0.0).max(-1)
                    e_m2 = (2.0 * sc[..., None] * roll_err(x, m, ex, r, w)
                            + _EPS32 * 8.0 * sc[..., None] ** 2)
                    r64 = a64(r)
                    er = np.where(r64 > np.sqrt(e_m2),
                                  e_m2 / np.maximum(2.0 * r64, 1e-300),
                                  np.sqrt(e_m2))
                else:         # id / neg / abs
                    er = ex + _EPS32 * a64(r)
                stack.append((r, m))
                errs.append(np.nan_to_num(er, nan=np.inf, posinf=np.inf,
                                          neginf=np.inf))
            elif kind == search.BINARY:
                b, mb = stack.pop()
                a, ma = stack.pop()
                m = ma & mb
                eb = errs.pop()
                ea = errs.pop()
                r = see(np_binary(g, a, b, m, flag=degenerate,
                                  scale=scale))
                if g == 2:    # mul
                    er = a64(a) * eb + a64(b) * ea + _EPS32 * a64(r)
                elif g == 3:  # protected divide: 1/|b| amplification
                    gate = np.float64(1e-6)
                    babs = np.maximum(a64(b), gate)
                    er = (ea + a64(r) * eb) / babs + _EPS32 * a64(r)
                    # divisor within its own noise of the gate/zero:
                    # branch and sign are implementation-dependent
                    near = m & (a64(b) <= gate + eb)
                    degenerate |= near.any(axis=-1)
                elif g in (4, 5):  # min/max: flips stay within ea+eb
                    er = ea + eb
                elif g == 6:  # rolling corr: bounded, denominator-gated
                    w = search.ROLL_SLOW
                    # lanes where ANY window's denominator sits within
                    # upstream noise of 0 flip the ok-gate between
                    # implementations -> incomparable
                    ac = np.where(m, a - np_masked_mean(a, m)[..., None],
                                  np.float32(0.0))
                    bc = np.where(m, b - np_masked_mean(b, m)[..., None],
                                  np.float32(0.0))
                    n_w = wsum64(m.astype(np.float64), w)
                    nn = np.maximum(n_w, 1.0)
                    va = np.maximum(
                        wsum64(np.where(m, a64(ac) ** 2, 0.0), w) / nn
                        - (wsum64(np.where(m, ac.astype(np.float64), 0.0),
                                  w) / nn) ** 2, 0.0)
                    vb = np.maximum(
                        wsum64(np.where(m, a64(bc) ** 2, 0.0), w) / nn
                        - (wsum64(np.where(m, bc.astype(np.float64), 0.0),
                                  w) / nn) ** 2, 0.0)
                    denom = np.sqrt(va * vb)
                    sc_a = np.where(m, a64(ac), 0.0).max(-1)[..., None]
                    sc_b = np.where(m, a64(bc), 0.0).max(-1)[..., None]
                    e_den = (2.0 * sc_a * sc_b + sc_a ** 2 + sc_b ** 2) \
                        * _EPS32 * 16.0 \
                        + 2.0 * (sc_a * roll_err(b, m, eb, r, w)
                                 + sc_b * roll_err(a, m, ea, r, w))
                    flip = m & (n_w > 1.5) & (denom <= e_den)
                    degenerate |= flip.any(axis=-1)
                    den_f = np.maximum(denom, 1e-300)
                    er = np.where(
                        denom > 0,
                        (sc_b * ea + sc_a * eb) * 4.0 / den_f
                        + _EPS32 * 64.0, 0.0)
                else:         # add / sub
                    er = ea + eb + _EPS32 * a64(r)
                stack.append((r, m))
                # NaN error (from inf-inf etc.) means "unbounded"; keep
                # real infs as inf too so e_fin goes non-finite and the
                # comparison falls back to the flat scale bound
                errs.append(np.nan_to_num(er, nan=np.inf, posinf=np.inf,
                                          neginf=np.inf))
            elif kind == search.MASK:
                x, m = stack.pop()
                ex = errs[-1]
                if g in (4, 5):
                    # pos/neg: a value within its own noise of 0 flips
                    # membership between implementations
                    near = m & (a64(x) <= ex)
                    degenerate |= near.any(axis=-1)
                stack.append((x, np_mask(g, x, m)))
            elif kind == search.AGG:
                x, m = stack.pop()
                ex = errs.pop()
                s = np_agg(g, x, m)  # [D, T]
                nv = np.maximum(m.sum(-1), 1)
                xm = np.where(m, a64(x), 0.0)
                e_mu = (np.where(m, ex, 0.0).sum(-1)
                        + _EPS32 * xm.sum(-1)) / nv
                if g == 0:      # mean
                    e_s = e_mu
                elif g == 1:    # std: same cancellation structure as mu;
                    e_s = 2.0 * e_mu + _EPS32 * xm.max(-1)
                    # near-zero std under noise: value fine (both ~0)
                elif g == 2:    # sum
                    e_s = (np.where(m, ex, 0.0).sum(-1)
                           + _EPS32 * xm.sum(-1))
                elif g == 3:    # last: same index both sides (mask equal)
                    idx = np.where(
                        m.any(-1),
                        m.shape[-1] - 1 - np.argmax(m[..., ::-1], -1), 0)
                    e_s = np.take_along_axis(ex, idx[..., None],
                                             -1)[..., 0]
                else:           # max / min: tie flips stay within max ex
                    e_s = np.where(m, ex, 0.0).max(-1)
                r = np.broadcast_to(
                    s[..., None].astype(np.float32), mask.shape)
                stack.append((r, mask))
                errs.append(np.nan_to_num(
                    np.broadcast_to(e_s[..., None], mask.shape).copy(),
                    nan=np.inf, posinf=np.inf, neginf=np.inf))
            else:
                raise AssertionError(f"unknown kind {kind}")
        x_fin, m_fin = stack[0]
        n_fin = np.maximum(m_fin.sum(-1), 1)
        e_fin = np.where(m_fin, errs[0], 0.0).sum(-1) / n_fin \
            + _EPS32 * scale
        # a NON-FINITE propagated bound means the first-order model
        # itself diverged (stacked 1/sd and 1/|b| amplifications can
        # overflow f64): the model is then asserting the lane's
        # magnitude is unbounded by rounding alone, and judging it by
        # the flat 2e-3-of-scale fallback claims more than the model
        # can promise — seed 3470 (program 13) produced two correct f32
        # evaluations 1.1e-2 of scale apart on exactly such a lane.
        # Fold these into `degenerate`, the same treatment near-gate
        # divides already get; systematic interpreter bugs still show
        # on well-conditioned lanes, which this cannot mask.
        degenerate |= ~np.isfinite(e_fin)
    return np_masked_mean(x_fin, m_fin), scale, degenerate, e_fin


fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    # D up to 4: the cross-day features (gap/prev_ret/vprev) need
    # multi-day shift chains — D=1 leaves them all-NaN and D=2 gives
    # exactly one real day, so the shift/empty-day paths would
    # otherwise go unexercised
    D = int(rng.integers(1, 5))
    T = int(rng.integers(2, 8))
    shape = (D, T, 240)
    close = 10.0 * np.exp(np.cumsum(
        rng.normal(0, 1e-3, shape), -1)).astype(np.float32)
    open_ = (close * (1 + rng.normal(0, 1e-4, shape))).astype(np.float32)
    high = np.maximum(open_, close) * 1.0002
    low = np.minimum(open_, close) * 0.9998
    vol = (rng.integers(0, 1000, shape) * 100).astype(np.float32)
    bars = np.stack([open_, high, low, close, vol], -1).astype(np.float32)
    mask = rng.random(shape) > rng.choice([0.0, 0.1, 0.6])
    if rng.random() < 0.3:
        mask[:, 0] = False  # halted ticker -> NaN factor
    if rng.random() < 0.3 and D > 1:
        # single-DAY halt: exercises the 'previous day halted, current
        # day alive' cross-day branch (gap/prev_ret NaN via empty
        # masked_first/last, vprev NaN via the prev_vol guard)
        mask[int(rng.integers(0, D)), int(rng.integers(0, T))] = False
    P = int(rng.integers(1, 24))
    # rotate skeletons: the round-2 default (PUSH/UNARY/BINARY only) and
    # the round-3 ratio-of-aggregates shape (MASK + AGG kinds)
    skel = (search.RICH_SKELETON if rng.random() < 0.4
            else search.DEFAULT_SKELETON)
    genomes = search.random_population(rng, P, skel)
    got = np.asarray(search.eval_programs(genomes, bars, mask, skel))
    try:
        for p in range(P):
            want, scale, degen, e_fin = np_eval(genomes[p], bars, mask,
                                                skel)
            cmp_ok = ~degen
            assert (np.isnan(got[p][cmp_ok]) == np.isnan(want[cmp_ok])).all(), \
                (seed, p, got[p], want)
            # both sides inf: agreement iff the signs match (a product
            # chain can legitimately overflow f32)
            ji, oi = np.isinf(got[p]), np.isinf(want)
            both_inf = ji & oi & cmp_ok
            assert (np.sign(got[p][both_inf])
                    == np.sign(want[both_inf])).all(), (seed, p)
            assert (ji == oi)[cmp_ok].all(), (seed, p, got[p], want)
            fin = ~np.isnan(want) & ~oi & cmp_ok
            if fin.any():
                # condition-aware tolerance: XLA's fusion/FMA and numpy
                # differ by ~1 ulp per op, and cancellation-heavy chains
                # (zero-mean cumsum -> mean) make the FINAL value
                # arbitrarily smaller than the intermediates it was
                # computed from — so error is judged relative to the
                # chain's own magnitude. The bound: chained 240-term
                # cumsums compound rounding ~n*eps per link relative to
                # their INPUT l1-mass, which can exceed the max-|value|
                # scale tracked here when the cumsum itself cancels
                # (observed up to 1.1e-3 on cumsum-of-zscore chains,
                # seeds 224/310). 2e-3 covers that with 2x margin and
                # still exposes real op bugs: a systematic distortion at
                # op magnitude shows as >= 1/240 ~ 4e-3 of chain scale
                # even when diluted by the final mean over 240 slots.
                denom = np.maximum(scale[fin], 1.0)
                diff = np.abs(got[p][fin].astype(np.float64)
                              - want[fin].astype(np.float64))
                rel = diff / denom
                # a lane passes on EITHER bound: the legacy flat 2e-3 of
                # chain scale, or the lane's propagated conditioning
                # (x32: two correct implementations each carry ~e_fin,
                # plus headroom for the first-order model's slack). A
                # systematic op bug violates both — it distorts healthy,
                # well-conditioned lanes where e_fin is tiny.
                cond = np.where(np.isfinite(e_fin[fin]), e_fin[fin], 0.0)
                ok = (rel < 2e-3) | (diff <= 32 * cond + 1e-7)
                assert ok.all(), (seed, p, rel[~ok].max(),
                                  genomes[p].tolist())
    except AssertionError as e:
        fails.append(seed)
        print(f"SEED {seed} FAILED: {str(e)[:300]}", flush=True)
    except Exception as e:  # keep the sweep alive like the sibling harnesses
        fails.append(seed)
        print(f"SEED {seed} CRASH: {e!r}", flush=True)
    if (seed - lo + 1) % 25 == 0:
        print(f"...{seed - lo + 1} done, {len(fails)} failures", flush=True)
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
