"""Pipeline fuzz: random day sequences, cache staleness, fault injection,
batch sizes — incremental resume must be exact and failure isolation
complete."""
import sys, os, tempfile, shutil
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_pipeline')  # gate timed TPU sessions off this 1-core host
import numpy as np
import pyarrow as pa, pyarrow.parquet as pq
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day
from replication_of_minute_frequency_factor_tpu import pipeline as _pl
from replication_of_minute_frequency_factor_tpu.pipeline import (
    compute_exposures, ExposureTable)
from replication_of_minute_frequency_factor_tpu.config import Config

_REAL_CPP = _pl.compute_packed_prepared


class _DeviceChaos:
    """Randomized transient device-failure injector for the batch-level
    elasticity machinery: each compute_packed_prepared call fails with
    probability p, at most ``max_fails`` total."""

    def __init__(self, rng, p, max_fails):
        self.rng = rng
        self.p = p
        self.left = max_fails

    def __call__(self, *a, **kw):
        if self.left > 0 and self.rng.random() < self.p:
            self.left -= 1
            raise RuntimeError("chaos: injected device failure")
        return _REAL_CPP(*a, **kw)

def write_day(d, rng, date_str, n_codes):
    cols = synth_day(rng, n_codes=n_codes, date=date_str, missing_prob=0.05)
    # ~40% of days carry integer code columns (the CSMAR int-export
    # shape): the device pipeline's raw-reader + integer-axis fast path
    # (pipeline._grid_batch) then runs under every chaos/resume/batch-
    # geometry scenario below, including mixed int/str batches, and the
    # cross-phase cache equality assertions prove the two forms
    # normalize identically
    if rng.random() < 0.4:
        code_col = pa.array(cols["code"].astype(np.int64))
    else:
        code_col = pa.array([str(c) for c in cols["code"]])
    arrays = {"code": code_col, "time": pa.array(cols["time"])}
    for k in ("open", "high", "low", "close", "volume"):
        arrays[k] = pa.array(cols[k])
    pq.write_table(pa.table(arrays),
                   os.path.join(d, date_str.replace("-", "") + ".parquet"))

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
NAMES = ("vol_return1min", "mmt_pm", "doc_kurt")
_REAL_GRID = _pl._grid_batch

for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    td = tempfile.mkdtemp()
    try:
        if seed >= 10_000:
            # --- per-day isolation invariant (round 3): ONE persistently
            # poisoned day (host prep fails deterministically) must cost
            # exactly itself whatever the batch geometry; after healing,
            # --retry-failed recovers it from the ledger.
            kline = os.path.join(td, "kline"); os.mkdir(kline)
            n_codes = int(rng.integers(3, 8))
            n_days = int(rng.integers(2, 9))
            days = sorted(str(np.datetime64("2024-02-01") + int(i))
                          for i in rng.choice(40, n_days, replace=False))
            for ds in days:
                write_day(kline, rng, ds, n_codes)
            poison = days[int(rng.integers(0, n_days))]
            cache = os.path.join(td, "cache.parquet")
            cfg = Config(days_per_batch=int(rng.integers(1, 5)))

            def bad_grid(day_data, shard_mult=1):
                if any(str(d) == poison for d, _ in day_data):
                    raise RuntimeError("poisoned day")
                return _REAL_GRID(day_data, shard_mult=shard_mult)

            _pl._grid_batch = bad_grid
            try:
                t1 = compute_exposures(kline, NAMES, cache_path=cache,
                                       cfg=cfg, progress=False)
            finally:
                _pl._grid_batch = _REAL_GRID
            assert t1.failures.keys() == [poison], (
                t1.failures.keys(), poison)
            assert set(map(str, t1.columns["date"])) == \
                set(days) - {poison}
            t2 = compute_exposures(kline, NAMES, cache_path=cache,
                                   cfg=cfg, progress=False,
                                   retry_failed=True)
            assert set(map(str, t2.columns["date"])) == set(days)
            assert not t2.failures
            assert not os.path.exists(cache + ".failures.json")
            continue
        kline = os.path.join(td, "kline"); os.mkdir(kline)
        n_codes = int(rng.integers(3, 10))
        n1 = int(rng.integers(1, 8)); n2 = int(rng.integers(1, 6))
        all_days = sorted(str(np.datetime64("2024-01-02") + int(i))
                          for i in rng.choice(60, n1 + n2, replace=False))
        for ds in all_days[:n1]:
            write_day(kline, rng, ds, n_codes)
        cache = os.path.join(td, "cache.parquet")
        dpb = int(rng.integers(1, 5))
        cfg = Config(days_per_batch=dpb)
        # fault injection on a random first-phase day
        bad_day = (np.datetime64(all_days[int(rng.integers(0, n1))])
                   if rng.random() < 0.3 else None)
        def hook(date):
            if bad_day is not None and date == bad_day:
                raise RuntimeError("injected")
        # device chaos: ONE transient failure rides the batch retry and
        # must be fully invisible (two could land on the same batch's
        # launch + retry and legitimately fail the day, so the exact
        # day-set assertions below only hold for a single injection)
        if rng.random() < 0.5:
            _pl.compute_packed_prepared = _DeviceChaos(
                np.random.default_rng(seed + 7),
                p=float(rng.choice([0.2, 0.5])), max_fails=1)
        try:
            t1 = compute_exposures(kline, NAMES, cache_path=cache, cfg=cfg,
                                   progress=False, fault_hook=hook)
        finally:
            _pl.compute_packed_prepared = _REAL_CPP
        days1 = set(map(str, t1.columns["date"]))
        want1 = set(all_days[:n1]) - ({str(bad_day)} if bad_day is not None
                                      else set())
        assert days1 == want1, (days1, want1)
        if bad_day is not None:
            assert len(t1.failures) == 1
            assert os.path.exists(cache + ".failures.json")
        # phase 2: add newer days, resume (no hook) — only new days compute;
        # the injected day stays absent (it is older than cache max)
        for ds in all_days[n1:]:
            write_day(kline, rng, ds, n_codes)
        t2 = compute_exposures(kline, NAMES, cache_path=cache,
                               cfg=cfg, progress=False)
        days2 = set(map(str, t2.columns["date"]))
        if not days1:
            # phase 1 wrote no cache (its only day failed): full recompute
            want2 = set(all_days)
        else:
            # resume: everything strictly newer than the cache max is
            # (re)computed — including a failed day that sorts after it
            want2 = days1 | {d for d in all_days if d > max(days1)}
        assert days2 == want2, (days2, want2)
        # cache reload
        t3 = ExposureTable.load(cache)
        assert set(map(str, t3.columns["date"])) == days2
        assert t3.factor_names == NAMES
        # values stable across a no-op rerun
        t4 = compute_exposures(kline, NAMES, cache_path=cache,
                               cfg=cfg, progress=False)
        for n in NAMES:
            np.testing.assert_array_equal(
                np.asarray(t4.columns[n]), np.asarray(t2.columns[n]), err_msg=n)
    except AssertionError as e:
        fails.append(seed); print(f"SEED {seed}: {str(e)[:250]}", flush=True)
    except Exception as e:
        fails.append(seed); print(f"SEED {seed} CRASH: {e!r}", flush=True)
    finally:
        shutil.rmtree(td, ignore_errors=True)
    if (seed - lo + 1) % 20 == 0:
        print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
