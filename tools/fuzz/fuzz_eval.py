"""Evaluation-layer fuzz: ic_series vs scipy, qcut_labels vs pandas,
forward_returns vs naive, across random shapes/sparsity/ties."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_eval')  # gate timed TPU sessions off this 1-core host
import numpy as np, pandas as pd, scipy.stats
from replication_of_minute_frequency_factor_tpu import eval_ops, frames

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    n_d = int(rng.integers(2, 12)); n_t = int(rng.integers(3, 60))
    x = rng.normal(0, 1, (n_d, n_t)).astype(np.float32)
    if rng.random() < 0.4:  # heavy ties
        x = np.round(x, 1).astype(np.float32)
    y = (0.3 * x + rng.normal(0, 1, x.shape)).astype(np.float32)
    m = rng.random(x.shape) > rng.choice([0.0, 0.1, 0.6])
    try:
        ic, ric = eval_ops.ic_series(np.nan_to_num(x), np.nan_to_num(y), m)
        ic, ric = np.asarray(ic), np.asarray(ric)
        for d in range(n_d):
            xs, ys = x[d, m[d]], y[d, m[d]]
            if len(xs) < 2 or xs.std() == 0 or ys.std() == 0:
                assert np.isnan(ic[d]), (seed, d, "expected NaN ic")
                continue
            w_ic = scipy.stats.pearsonr(xs, ys)[0]
            w_rk = scipy.stats.spearmanr(xs, ys)[0]
            if np.isnan(w_rk):  # constant ranks
                assert np.isnan(ric[d]), (seed, d)
            else:
                assert abs(ric[d] - w_rk) < 5e-5, (seed, d, ric[d], w_rk)
            assert abs(ic[d] - w_ic) < 5e-5, (seed, d, ic[d], w_ic)
        k = int(rng.integers(2, 11))
        labels = np.asarray(eval_ops.qcut_labels(np.nan_to_num(x), m, k))
        for d in range(n_d):
            xs = x[d, m[d]].astype(np.float64)
            if len(xs) == 0:
                continue
            # polars qcut(allow_duplicates=True) oracle: duplicate
            # quantile breaks are KEPT (labels gapped, not compacted);
            # value v -> first bin with v <= break
            breaks = np.quantile(xs, [(i + 1) / k for i in range(k - 1)])
            want = np.searchsorted(breaks, xs, side="left")
            got = labels[d][m[d]]
            np.testing.assert_array_equal(got, want,
                                          err_msg=f"{seed}/{d}/k={k}")
            # invalid lanes carry a sentinel, never a bin label
            assert not np.isin(labels[d][~m[d]], np.arange(k)).any()
            # (pandas cross-check lives in the suite on tie-free
            # fixtures; at fuzz scale values land exactly on interpolated
            # breaks and pandas' boundary handling differs by one label)
    except AssertionError as e:
        fails.append(seed); print(f"SEED {seed}: {str(e)[:250]}", flush=True)
    if (seed - lo + 1) % 50 == 0:
        print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
    if (seed - lo + 1) % 10 == 0:
        # bound the in-process XLA-CPU executable cache: shape-varying
        # seeds each compile fresh graphs and the cache never evicts
        # (a 140-seed wide-shape parity run exhausted 128 GB, 2026-08-01)
        import jax
        jax.clear_caches()
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
