"""cal_final_exposure fuzz vs pandas across all (mode, frequency, method)
combos, random sparsity and series lengths."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_resample')  # gate timed TPU sessions off this 1-core host
import numpy as np, pandas as pd
from replication_of_minute_frequency_factor_tpu import MinFreqFactor

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    n_codes = int(rng.integers(2, 8))
    n_days = int(rng.integers(3, 40))
    start = np.datetime64("2024-01-01") + int(rng.integers(0, 300))
    # each code holds a random subset of business days (ragged panels)
    all_days = np.array([start + i for i in range(n_days)],
                        dtype="datetime64[D]")
    rows = {"code": [], "date": [], "value": []}
    for c in range(n_codes):
        keep = rng.random(n_days) > rng.choice([0.0, 0.3])
        if not keep.any():
            keep[0] = True
        d = all_days[keep]
        rows["code"] += [f"{600000+c:06d}"] * len(d)
        rows["date"].append(d)
        v = rng.normal(0, 1, len(d))
        v[rng.random(len(d)) < 0.1] = np.nan  # NaN exposures allowed
        rows["value"].append(v)
    code = np.array(rows["code"], dtype=object)
    date = np.concatenate(rows["date"])
    value = np.concatenate(rows["value"]).astype(np.float32)

    f = MinFreqFactor("toy").set_exposure(code, date, value)
    # f64 once here: pandas on raw f32 loses the z-score's deviations to
    # cancellation (seed 10706) — the library computes in f64 too
    df = pd.DataFrame({"code": code, "date": date,
                       "value": value.astype(np.float64)})

    try:
        for mode, freq in (("calendar", "week"), ("calendar", "month"),
                           ("days", int(rng.integers(1, 6)))):
            for method in ("o", "m", "z", "std"):
                f2 = MinFreqFactor("toy").set_exposure(code, date, value)
                out = f2.cal_final_exposure(freq, method=method,
                                            mode=mode).factor_exposure
                col = [k for k in out if k not in ("code", "date")][0]
                got = pd.DataFrame({k: out[k] for k in ("code", "date")} |
                                   {"v": out[col]})
                # pandas oracle
                want_rows = []
                for c, g in df.groupby("code"):
                    g = g.sort_values("date").set_index("date")["value"]
                    g.index = pd.to_datetime(g.index)
                    if mode == "calendar":
                        # polars group_by_dynamic: windows start Monday /
                        # month start, label = window start
                        grp = g.groupby(pd.Grouper(freq="W-MON", label="left",
                                        closed="left") if freq == "week"
                                        else pd.Grouper(freq="MS"))
                        for period, s in grp:
                            if not len(s):
                                continue
                            # calendar mode: polars default ddof=1
                            # (SURVEY Q11; ddof=0 is ONLY the rolling
                            # mode's explicit :222,234)
                            if method == "o": w = s.iloc[-1]
                            elif method == "m": w = s.mean()
                            elif method == "z":
                                sd = s.std(ddof=1)
                                w = ((s.iloc[-1] - s.mean()) / sd
                                     if sd > 0 else np.nan)
                            else: w = s.std(ddof=1)
                            want_rows.append((c, period, w))
                    else:
                        t = freq
                        r = g.rolling(t, min_periods=t)
                        # 'o' is a passthrough rename in the reference
                        # (no rolling window; MinuteFrequentFactorCICC.py
                        # :190-198, verified by tools/refdiff)
                        if method == "o": w = g
                        elif method == "m": w = r.mean()
                        elif method == "z":
                            sd = r.std(ddof=0)
                            w = (g - r.mean()) / sd
                        else: w = r.std(ddof=0)
                        for dt_, wv in w.items():
                            want_rows.append((c, dt_, wv))
                want = pd.DataFrame(want_rows, columns=["code", "date", "w"])
                want["date"] = want["date"].dt.normalize()
                got["date"] = pd.to_datetime(got["date"])
                merged = got.merge(want, on=["code", "date"], how="outer",
                                   indicator=True)
                if mode == "days":  # same grid: full outer match expected
                    bad = merged[(merged._merge != "both")]
                    if len(bad):
                        raise AssertionError(
                            f"{mode}/{freq}/{method}: row mismatch\n{bad.head()}")
                both = merged[merged._merge == "both"]
                a, b = both["v"].to_numpy(float), both["w"].to_numpy(float)
                ok = np.isclose(a, b, rtol=2e-4, atol=1e-5) | (
                    np.isnan(a) & np.isnan(b))
                if not ok.all():
                    i = np.flatnonzero(~ok)[0]
                    raise AssertionError(
                        f"{mode}/{freq}/{method}: {both.iloc[i].to_dict()}")
    except AssertionError as e:
        fails.append(seed)
        print(f"SEED {seed}: {str(e)[:300]}", flush=True)
    if (seed - lo + 1) % 20 == 0:
        print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
    if (seed - lo + 1) % 10 == 0:
        # bound the in-process XLA-CPU executable cache: shape-varying
        # seeds each compile fresh graphs and the cache never evicts
        # (a 140-seed wide-shape parity run exhausted 128 GB, 2026-08-01)
        import jax
        jax.clear_caches()
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
