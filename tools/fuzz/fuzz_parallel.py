"""Sharded-vs-local differential fuzz over the virtual 8-device CPU mesh.

The seam under test is parallel/: shard_map collectives (xs_masked_mean /
xs_masked_std / xs_pearson / xs_rank) and sharded_compute_factors with
shard_day_batch padding, against their single-device counterparts
(ops.masked_* / compute_factors_jit). Randomizes mesh shape, device-subset
size, pre-pad ticker counts (so the zero-pad + mask=False lanes are
exercised), degenerate masks (all-masked dates, single valid lane), exact
ties across shard boundaries, constant cross-sections, and inf/NaN
poison in masked-out lanes (which psum-style zeroing must ignore).

Shapes and factor-name subsets draw from small fixed pools so the compile
count stays bounded while the data randomizes freely.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_parallel')  # gate timed TPU sessions off this 1-core host

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from replication_of_minute_frequency_factor_tpu import ops  # noqa: E402
from replication_of_minute_frequency_factor_tpu.models.registry import (  # noqa: E402
    compute_factors_jit, factor_names)
from replication_of_minute_frequency_factor_tpu.parallel import (  # noqa: E402
    make_mesh, shard_day_batch, sharded_compute_factors,
    xs_masked_mean, xs_masked_std, xs_pearson, xs_qcut, xs_rank)

MESH_POOL = ((1, 8), (2, 4), (4, 2), (1, 4), (2, 2), (1, 2), (1, 1))
XS_D_POOL = (1, 3, 6)
XS_T_POOL = (5, 17, 40)
FACTOR_D_POOL = (1, 2)
FACTOR_T_POOL = (7, 16, 23)
NAME_POOL = (
    ("vol_return1min", "mmt_pm", "liq_openvol", "shape_skew"),
    ("doc_pdf60", "doc_vol10_ratio", "doc_kurt"),
    ("mmt_ols_qrs", "mmt_ols_beta_zscore_last"),
    ("corr_prv", "trade_headRatio", "vol_upRatio", "liq_amihud_1min"),
)
FULL_NAMES = factor_names()

_meshes = {}


def get_mesh(shape):
    if shape not in _meshes:
        n = shape[0] * shape[1]
        _meshes[shape] = make_mesh(shape, devices=jax.devices()[:n])
    return _meshes[shape]


def pad_xs(x, m, mult):
    """Zero-pad the tickers axis to a shard multiple, mask=False lanes."""
    rem = x.shape[-1] % mult
    if rem == 0:
        return x, m
    pad = mult - rem
    xp = np.pad(x, [(0, 0), (0, pad)])
    mp = np.pad(m, [(0, 0), (0, pad)])
    return xp, mp


def xs_case(rng, seed):
    shape = MESH_POOL[int(rng.integers(len(MESH_POOL)))]
    mesh = get_mesh(shape)
    n_d = XS_D_POOL[int(rng.integers(len(XS_D_POOL)))]
    n_t = XS_T_POOL[int(rng.integers(len(XS_T_POOL)))]
    x = rng.normal(0, 1, (n_d, n_t)).astype(np.float32)
    y = (0.4 * x + rng.normal(0, 1, x.shape)).astype(np.float32)
    m = rng.random(x.shape) > rng.choice([0.0, 0.2, 0.7])
    if rng.random() < 0.3:
        m[int(rng.integers(n_d))] = False       # all-masked date
    if rng.random() < 0.3:
        d = int(rng.integers(n_d))
        m[d] = False
        m[d, int(rng.integers(n_t))] = True     # single valid lane
    if rng.random() < 0.4:
        # constant cross-section at an f32-INEXACT value: an exactly
        # representable constant (0.25) cancels bit-for-bit even in the
        # one-pass form and would never trigger the cancellation bug class
        x[int(rng.integers(n_d))] = 0.1
    if rng.random() < 0.5:
        x = np.round(x, 1).astype(np.float32)   # shard-crossing ties
    if rng.random() < 0.4:
        poison = rng.choice([np.inf, -np.inf, np.nan])
        x = np.where(m, x, np.float32(poison))
        y = np.where(m, y, np.float32(poison))
    xp, mp = pad_xs(x, m, shape[1])
    yp, _ = pad_xs(y, m, shape[1])
    # masked-out lanes may hold poison; the wrappers contract is that they
    # never read them, so ship them as-is
    mean = np.asarray(xs_masked_mean(mesh, xp, mp))
    std = np.asarray(xs_masked_std(mesh, xp, mp))
    ic = np.asarray(xs_pearson(mesh, xp, yp, mp))
    rk = np.asarray(xs_rank(mesh, xp, mp))[:, :n_t]
    k = int(rng.choice([3, 5, 10]))
    qc = np.asarray(xs_qcut(mesh, xp, mp, group_num=k))[:, :n_t]

    xc = np.where(m, x, 0.0).astype(np.float32)
    yc = np.where(m, y, 0.0).astype(np.float32)
    ref_mean = np.asarray(ops.masked_mean(xc, m))
    ref_std = np.asarray(ops.masked_std(xc, m))
    ref_ic = np.asarray(ops.masked_corr(xc, yc, m))
    ref_rk = np.asarray(ops.rank_average(xc, m))
    from replication_of_minute_frequency_factor_tpu import eval_ops
    # reference on the same padded matrix the sharded call saw: what the
    # fuzz exercises is the gather/slice shard roundtrip, not the core
    # (xs_qcut reuses _qcut_labels_jit by design)
    ref_qc = np.asarray(eval_ops._qcut_labels_jit(xp, mp, k))[:, :n_t]

    np.testing.assert_allclose(mean, ref_mean, rtol=2e-4, atol=1e-5,
                               equal_nan=True, err_msg=f"{seed} mean")
    np.testing.assert_allclose(std, ref_std, rtol=2e-4, atol=1e-5,
                               equal_nan=True, err_msg=f"{seed} std")
    # the finite pattern must match BOTH ways: sharded-finite where the
    # oracle is NaN is exactly the divergence class this fuzzer exists to
    # catch (a dropped n>1 gate or anchoring turns zero-variance NaN into
    # finite garbage), so it is never excused; oracle-finite where the
    # sharded path is NaN is excused only when the true correlation is
    # near zero (catastrophic f32 cancellation on either side of 0)
    fin, ref_fin = np.isfinite(ic), np.isfinite(ref_ic)
    assert not (fin & ~ref_fin).any(), \
        (seed, "sharded finite where oracle NaN", ic, ref_ic)
    lost = ~fin & ref_fin
    assert np.abs(ref_ic[lost]).max(initial=0) < 2e-3, \
        (seed, "sharded NaN where oracle finite", ic, ref_ic)
    both = fin & ref_fin
    np.testing.assert_allclose(ic[both], ref_ic[both], rtol=2e-3, atol=2e-3,
                               err_msg=f"{seed} ic")
    np.testing.assert_allclose(rk[m], ref_rk[m], rtol=1e-6,
                               err_msg=f"{seed} rank")
    np.testing.assert_array_equal(qc, ref_qc, err_msg=f"{seed} qcut k={k}")


def factor_case(rng, seed):
    shape = MESH_POOL[int(rng.integers(len(MESH_POOL)))]
    mesh = get_mesh(shape)
    n_d = FACTOR_D_POOL[int(rng.integers(len(FACTOR_D_POOL)))]
    n_t = FACTOR_T_POOL[int(rng.integers(len(FACTOR_T_POOL)))]
    if rng.random() < 0.06:
        names, n_d, n_t = FULL_NAMES, 1, 16  # bound the big compile to one shape
    else:
        names = NAME_POOL[int(rng.integers(len(NAME_POOL)))]
    s = (n_d, n_t, 240)
    close = 10.0 * np.exp(np.cumsum(rng.normal(0, 1e-3, s), -1))
    open_ = close * (1 + rng.normal(0, 1e-4, s))
    high = np.maximum(open_, close) * (1 + np.abs(rng.normal(0, 2e-4, s)))
    low = np.minimum(open_, close) * (1 - np.abs(rng.normal(0, 2e-4, s)))
    volume = (rng.integers(0, 500, s) * rng.choice([1, 100])).astype(float)
    if rng.random() < 0.5:
        volume[rng.random(s) < 0.2] = 0.0       # zero-volume bars
    bars = np.stack([open_, high, low, close, volume], -1).astype(np.float32)
    mask = rng.random(s) > rng.choice([0.0, 0.05, 0.5])
    if rng.random() < 0.3:
        t = int(rng.integers(n_t))
        mask[:, t] = False
        mask[:, t, :int(rng.integers(1, 60))] = True   # <50-bar ticker
    if rng.random() < 0.2:
        mask[:, int(rng.integers(n_t))] = False        # fully halted ticker

    local = compute_factors_jit(bars, mask, names=names)
    sb, sm, nt = shard_day_batch(bars, mask, mesh)
    shd = sharded_compute_factors(sb, sm, mesh, names=names)
    assert nt == n_t
    for k in names:
        a = np.asarray(local[k])
        b = np.asarray(shd[k])[:n_d, :n_t]  # days axis pads to a shard
        # multiple too (mask=False lanes); slice both axes back
        assert a.shape == b.shape, (seed, k, a.shape, b.shape)
        same_finite = np.isfinite(a) == np.isfinite(b)
        assert same_finite.all(), (seed, k, "finite pattern",
                                   np.argwhere(~same_finite)[:4])
        f = np.isfinite(a)
        np.testing.assert_allclose(
            a[f], b[f], rtol=5e-5, atol=1e-7,
            err_msg=f"{seed} {k}")
        nan_a, nan_b = np.isnan(a), np.isnan(b)
        assert (nan_a == nan_b).all(), (seed, k, "nan pattern")


def main():
    lo, hi = int(sys.argv[1]), int(sys.argv[2])
    fails = []
    for seed in range(lo, hi):
        rng = np.random.default_rng(seed)
        try:
            xs_case(rng, seed)
            if rng.random() < 0.6:
                factor_case(rng, seed)
        except AssertionError as e:
            fails.append(seed)
            print(f"SEED {seed}: {str(e)[:300]}", flush=True)
        if (seed - lo + 1) % 25 == 0:
            print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
    print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")


if __name__ == "__main__":
    main()
