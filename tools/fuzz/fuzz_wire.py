"""Wire encoder fuzz: native vs numpy byte parity + decode roundtrip
across random batch shapes, price scales, and pathologies."""
import sys
import os
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_wire')  # gate timed TPU sessions off this 1-core host
import numpy as np
from replication_of_minute_frequency_factor_tpu.data import wire

fails = []
modes_seen = set()
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    D = int(rng.integers(1, 3)); T = int(rng.integers(2, 30))
    base_price = float(rng.choice([0.05, 3.0, 12.0, 80.0, 300.0, 1700.0,
                                   30000.0, 41000.0]))
    shape = (D, T, 240)
    close = base_price * np.exp(np.cumsum(
        rng.normal(0, rng.choice([1e-4, 1e-3, 5e-3]), shape), -1))
    open_ = close * (1 + rng.normal(0, 1e-4, shape))
    high = np.maximum(open_, close) * (1 + np.abs(rng.normal(0, 2e-4, shape)))
    low = np.minimum(open_, close) * (1 - np.abs(rng.normal(0, 2e-4, shape)))
    # volume kinds span the full mode ladder: 10-bit lots / 10-bit
    # shares / i32 / u16 shares / u16 lots
    vol_kind = rng.integers(0, 5)
    if vol_kind in (0, 1):
        volume = (rng.integers(0, 1000, shape) *
                  (100 if vol_kind == 0 else 1)).astype(np.float64)
    elif vol_kind == 2:
        volume = rng.integers(0, 1000, shape).astype(np.float64) * 1e5
    elif vol_kind == 3:
        volume = rng.integers(0, 60000, shape).astype(np.float64)
    else:
        volume = (rng.integers(0, 60000, shape) * 100).astype(np.float64)
    bars = np.stack([open_, high, low, close, volume], -1)
    bars[..., :4] = np.round(bars[..., :4], 2)
    bars = np.maximum(bars, 0.01 * (np.arange(5) < 4)).astype(np.float32)
    mask = rng.random(shape) > rng.choice([0.0, 0.05, 0.5])
    if rng.random() < 0.3:  # halted ticker
        mask[:, rng.integers(0, T)] = False
    if rng.random() < 0.3:  # garbage on dead lanes
        dead = np.argwhere(~mask)
        for i in range(min(3, len(dead))):
            bars[tuple(dead[i])] = [np.nan, np.inf, -5, 1e12, -3.3][i % 5]
    if rng.random() < 0.2:  # off-tick poison on a live lane
        live = np.argwhere(mask)
        if len(live):
            bars[tuple(live[0])][3] += 0.003
    fa, fb = {}, {}
    a = wire.encode(bars, mask, use_native=True, floor=fa)
    b = wire.encode(bars, mask, use_native=False, floor=fb)
    if a is not None:
        modes_seen.add(("c%d" % fa.get("dclose_mode", 0),
                        "o%d" % fa.get("ohl_mode", 0),
                        "v%d" % fa.get("vol_mode", 0)))
    try:
        assert (a is None) == (b is None), (a is None, b is None)
        if a is not None:
            assert fa == fb, (fa, fb)
            for x, y, nm in zip(a.arrays, b.arrays,
                                "base dclose dohl volume mask vs".split()):
                assert np.asarray(x).dtype == np.asarray(y).dtype, nm
                np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                              err_msg=nm)
            dec, dm = wire.decode(*a.arrays)
            assert np.array_equal(np.asarray(dm), mask)
            db = np.asarray(dec)
            err = np.abs(db[mask] - bars[mask]) / np.maximum(
                np.abs(bars[mask]), 1e-6)
            assert err.max() < 3e-7, err.max()
    except AssertionError as e:
        fails.append(seed)
        print(f"SEED {seed} FAILED: {str(e)[:300]}", flush=True)
    if (seed - lo + 1) % 100 == 0:
        print(f"...{seed - lo + 1} done, {len(fails)} failures", flush=True)
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}; modes: {sorted(modes_seen)}")
