"""Extended parity fuzz: many seeds x adversarial day shapes."""
import sys, traceback
import os
_REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, _REPO); sys.path.insert(0, os.path.join(_REPO, 'tests'))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_parity')  # gate timed TPU sessions off this 1-core host
import numpy as np
import test_parity as tp
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
for seed in range(lo, hi):
    try:
        # rotate the scenario shape too (universe size, sparsity,
        # degenerate-code mix) so sweeps explore beyond one fixed
        # day-shape distribution; seeds below 10k keep the historical
        # shape so the regression-pinned seeds stay reproducible
        if seed < 10_000:
            rng = np.random.default_rng(seed)
            kw = dict(n_codes=10, missing_prob=0.12, zero_volume_prob=0.12,
                      constant_price_codes=2, short_day_codes=3)
            tp._compare(synth_day(rng, **kw), f"fuzz{seed}", noisy=True)
        else:
            # wide scenario space; seeds >= 31k may take the BATCHED
            # multiday branch (the production shape). The runner lives in
            # test_parity so pinned regressions replay it bit-for-bit.
            tp.run_wide_scenario_seed(seed, label=f"fuzz{seed}")
    except AssertionError as e:
        fails.append((seed, str(e)[:400]))
        print(f"SEED {seed} FAILED:\n{str(e)[:400]}\n", flush=True)
    except Exception as e:
        fails.append((seed, f"crash: {e}"))
        print(f"SEED {seed} CRASHED: {e}", flush=True)
        traceback.print_exc()
    if (seed - lo + 1) % 20 == 0:
        print(f"...{seed - lo + 1} seeds done, {len(fails)} failures", flush=True)
    if (seed - lo + 1) % 10 == 0:
        # the wide tiers compile a DISTINCT fused 58-kernel graph per
        # seed (universe/day-count vary), and XLA-CPU's in-process
        # executable cache never evicts: ~140 wide seeds in one process
        # exhausted a 128 GB host (LLVM 'Cannot allocate memory' then
        # SIGSEGV, 2026-08-01). Shapes rarely repeat there, so dropping
        # the cache costs no recompiles worth keeping.
        import jax
        jax.clear_caches()
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {[s for s,_ in fails]}")
