#!/bin/sh
# Refdiff fuzz campaign in short chunks (VERDICT r2 #4: ~10x the eval
# and resampler/pipeline differential coverage). Each chunk runs under
# the CPU-busy sentinel and stays well under tunnel_watch's 900 s
# quiet-wait bound, so a tunnel-up window never has to choose between
# waiting forever and timing a bench against a live fuzzer: the current
# chunk drains, the sentinel drops, the capture fires on a quiet host.
# Usage: tools/fuzz/run_refdiff_campaign.sh LO HI CHUNK LOG
set -u
REPO="$(cd "$(dirname "$0")/../.." && pwd)"
LO="$1"; HI="$2"; CHUNK="$3"; LOG="$4"
lo="$LO"
status=0
while [ "$lo" -lt "$HI" ]; do
    hi=$((lo + CHUNK))
    [ "$hi" -gt "$HI" ] && hi="$HI"
    echo "=== chunk $lo..$hi $(date -u +%FT%TZ)" >> "$LOG"
    if ! "$REPO/tools/with_cpu_busy.sh" \
        env -u PALLAS_AXON_POOL_IPS JAX_PLATFORMS=cpu \
        python "$REPO/tools/fuzz/fuzz_refdiff.py" "$lo" "$hi" \
        >> "$LOG" 2>&1; then
        echo "=== chunk $lo..$hi FAILED" >> "$LOG"
        status=1
    fi
    lo="$hi"
    sleep 20  # sentinel-free gap: lets a waiting tunnel capture start
done
echo "=== campaign $LO..$HI done status=$status $(date -u +%FT%TZ)" \
    >> "$LOG"
exit "$status"
