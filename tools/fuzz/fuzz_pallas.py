"""Pallas-vs-conv differential fuzz for the rolling-moment backend.

The seam under test is ops/pallas_rolling.py: the Pallas TPU kernel for
the rolling 50-bar moment family (run here in interpret mode, which
executes the same kernel logic with the same blocking/masking structure)
against the XLA conv formulation (ops/rolling.py) that the parity suite
has already pinned to the f64 oracle. Two tiers per seed:

* moments: rolling_window_stats{,_pallas} on random (rows, 240) low/high
  grids — random row counts (block-edge handling), window sizes, masks
  with <window tails, all-masked rows, constant series (var=0), and
  tick-rounded ties.
* factors: the five mmt_ols_* kernels end to end via compute_factors_jit
  with rolling_impl="pallas" vs "conv" on synthetic day batches.

Comparator: `valid` must match exactly; moments compare where valid with
f32-summation-order tolerance. Factor outputs must agree on finite
pattern and value except where the window family is degenerate (rolling
var_x below f32 noise => the beta fallback branch can route differently
between backends); those lanes are skipped the same way the parity
comparator's measure-zero channels are.
"""
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_pallas')  # gate timed TPU sessions off this 1-core host

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402

from replication_of_minute_frequency_factor_tpu.models.registry import (  # noqa: E402
    compute_factors_jit, factor_names)
from replication_of_minute_frequency_factor_tpu.ops.pallas_rolling import (  # noqa: E402
    rolling_window_stats_pallas)
from replication_of_minute_frequency_factor_tpu.ops.rolling import (  # noqa: E402
    rolling_window_stats)

# derived from the registry so a new rolling-family factor is
# covered automatically instead of silently skipped
ROLLING_FACTORS = tuple(n for n in factor_names()
                        if n.startswith("mmt_ols_"))
assert len(ROLLING_FACTORS) >= 5, ROLLING_FACTORS
ROW_POOL = (1, 2, 3, 5, 8, 9)
WINDOW_POOL = (10, 50, 50, 50, 120)   # reference uses 50; weight it
FACTOR_T_POOL = (7, 16, 23)


def make_grids(rng, rows):
    shape = (rows, 240)
    low = 10.0 * np.exp(np.cumsum(rng.normal(0, 1e-3, shape), -1))
    high = low * (1 + np.abs(rng.normal(0, 5e-4, shape)))
    mask = rng.random(shape) > rng.choice([0.0, 0.1, 0.5])
    if rng.random() < 0.4 and rows > 1:
        r = int(rng.integers(rows))
        mask[r] = False
        mask[r, :int(rng.integers(0, 70))] = True   # sub-window tail
    if rng.random() < 0.2:
        mask[int(rng.integers(rows))] = False        # fully masked row
    if rng.random() < 0.3 and rows > 1:
        low[int(rng.integers(rows))] = 10.0          # constant series
    if rng.random() < 0.4:
        low = np.round(low, 2)                       # tick ties
        high = np.round(high, 2)
    return low.astype(np.float32), high.astype(np.float32), mask


def moments_case(rng, seed):
    rows = ROW_POOL[int(rng.integers(len(ROW_POOL)))]
    window = WINDOW_POOL[int(rng.integers(len(WINDOW_POOL)))]
    low, high, mask = make_grids(rng, rows)
    a = rolling_window_stats(low, high, mask, window, impl="conv")
    b = rolling_window_stats_pallas(low, high, mask, window,
                                    interpret=True)
    va, vb = np.asarray(a["valid"]), np.asarray(b["valid"])
    np.testing.assert_array_equal(va, vb, err_msg=f"{seed} valid")
    for k in ("mean_x", "mean_y", "cov", "var_x", "var_y"):
        np.testing.assert_allclose(
            np.asarray(a[k])[va], np.asarray(b[k])[va],
            rtol=3e-5, atol=1e-9, err_msg=f"{seed} {k} w={window}")


def factors_case(rng, seed):
    n_t = FACTOR_T_POOL[int(rng.integers(len(FACTOR_T_POOL)))]
    s = (1, n_t, 240)
    close = 10.0 * np.exp(np.cumsum(rng.normal(0, 1e-3, s), -1))
    open_ = close * (1 + rng.normal(0, 1e-4, s))
    high = np.maximum(open_, close) * (1 + np.abs(rng.normal(0, 2e-4, s)))
    low = np.minimum(open_, close) * (1 - np.abs(rng.normal(0, 2e-4, s)))
    volume = (rng.integers(0, 500, s) * 100).astype(float)
    bars = np.stack([open_, high, low, close, volume], -1).astype(np.float32)
    if rng.random() < 0.4:
        bars[..., :4] = np.round(bars[..., :4], 2)
    mask = rng.random(s) > rng.choice([0.0, 0.05, 0.5])
    if rng.random() < 0.3:
        t = int(rng.integers(n_t))
        mask[:, t] = False
        mask[:, t, :int(rng.integers(1, 60))] = True

    conv = compute_factors_jit(bars, mask, names=ROLLING_FACTORS,
                               rolling_impl="conv")
    pal = compute_factors_jit(bars, mask, names=ROLLING_FACTORS,
                              rolling_impl="pallas")
    # degeneracy gate: lanes whose windowed var_x ever sits at f32 noise
    # can route the beta fallback differently between backends. The gate
    # is computed from the UNION of both backends' stats — a lane where
    # var_x is exactly 0 under one backend but a hair above the
    # threshold under the other would otherwise route the fallback
    # asymmetrically and surface as a spurious failure (ADVICE r1)
    degenerate = np.zeros(bars.shape[1], dtype=bool)
    for impl in ("conv", "pallas"):
        st = rolling_window_stats(bars[0, :, :, 2], bars[0, :, :, 1],
                                  mask[0], 50, impl=impl)
        vx = np.where(np.asarray(st["valid"]), np.asarray(st["var_x"]),
                      np.inf)
        mx = np.asarray(st["mean_x"])
        degenerate |= ((vx == 0.0)
                       | (vx < 1e-8 * np.maximum(mx * mx, 1e-12))).any(-1)
    for k in ROLLING_FACTORS:
        a, b = np.asarray(conv[k])[0], np.asarray(pal[k])[0]
        keep = ~degenerate
        same_finite = np.isfinite(a) == np.isfinite(b)
        assert same_finite[keep].all(), (
            seed, k, "finite pattern", np.argwhere(~same_finite)[:4])
        f = np.isfinite(a) & np.isfinite(b) & keep
        np.testing.assert_allclose(a[f], b[f], rtol=5e-4, atol=1e-6,
                                   err_msg=f"{seed} {k}")


def main():
    lo, hi = int(sys.argv[1]), int(sys.argv[2])
    fails = []
    for seed in range(lo, hi):
        rng = np.random.default_rng(seed)
        try:
            moments_case(rng, seed)
            if rng.random() < 0.5:
                factors_case(rng, seed)
        except AssertionError as e:
            fails.append(seed)
            print(f"SEED {seed}: {str(e)[:300]}", flush=True)
        if (seed - lo + 1) % 25 == 0:
            print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
    print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")


if __name__ == "__main__":
    main()
