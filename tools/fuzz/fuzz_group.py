"""group_test full-value fuzz vs a pandas oracle implementing the
reference chain: per-date polars-qcut -> per-(code,period) compounded
return + last group/caps -> 1-period lag per code -> weighted group
means -> cumprod."""
import sys, os, tempfile
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))

from tools.cpu_busy import mark_busy  # noqa: E402

mark_busy('fuzz_group')  # gate timed TPU sessions off this 1-core host
import numpy as np, pandas as pd
import pyarrow as pa, pyarrow.parquet as pq
from replication_of_minute_frequency_factor_tpu import Factor
from replication_of_minute_frequency_factor_tpu import frames

def polars_qcut(xs, k):
    breaks = np.quantile(xs, [(i + 1) / k for i in range(k - 1)])
    return np.searchsorted(breaks, xs, side="left")

fails = []
lo, hi = int(sys.argv[1]), int(sys.argv[2])
td = tempfile.mkdtemp()
for seed in range(lo, hi):
    rng = np.random.default_rng(seed)
    # seeds >= 10k widen the scenario space (historical shapes below
    # keep regression-pinned seeds reproducible)
    if seed < 10_000:
        n_codes = int(rng.integers(3, 10)); n_days = int(rng.integers(8, 30))
    else:
        n_codes = int(rng.integers(3, 25)); n_days = int(rng.integers(5, 70))
    K = int(rng.integers(2, 6))
    freq = str(rng.choice(["week", "month"]))
    wparam = rng.choice([None, "tmc", "cmc"])
    codes = [f"{600000+i:06d}" for i in range(n_codes)]
    days = np.array(sorted(np.datetime64("2024-01-01") + i for i in
                    rng.choice(120, n_days, replace=False)))
    pv_rows = []
    for c in codes:
        keep = rng.random(n_days) > rng.choice([0.0, 0.2])
        for d in days[keep]:
            pv_rows.append((c, d, rng.normal(0, 0.02),
                            rng.uniform(1e9, 1e10), rng.uniform(7e8, 9e9)))
    pv = pd.DataFrame(pv_rows, columns=["code", "date", "pct_change",
                                        "tmc", "cmc"])
    pv_path = os.path.join(td, f"pv{seed}.parquet")
    pq.write_table(pa.table({
        "code": pa.array(pv["code"]),
        "date": pa.array(pv["date"].to_numpy().astype("datetime64[D]")),
        "pct_change": pa.array(pv["pct_change"]),
        "tmc": pa.array(pv["tmc"]), "cmc": pa.array(pv["cmc"])}), pv_path)
    exp = pv.sample(frac=rng.uniform(0.6, 1.0), random_state=seed)[
        ["code", "date"]].copy()
    exp["v"] = rng.normal(0, 1, len(exp)).astype(np.float32)
    if rng.random() < 0.4:
        exp["v"] = np.round(exp["v"], 1)
    exp.loc[exp.sample(frac=0.08, random_state=seed + 1).index, "v"] = np.nan
    f = Factor("toy").set_exposure(
        exp["code"].to_numpy(object),
        exp["date"].to_numpy().astype("datetime64[D]"),
        exp["v"].to_numpy(np.float32))
    try:
        got = f.group_test(frequency=freq, weight_param=wparam, group_num=K,
                           plot=False, return_df=True,
                           daily_pv_path=pv_path)
        # ---- oracle (align-left semantics, verified against the
        # reference's actual Factor.py by tools/refdiff: rows are the
        # EXPOSURE rows; 'last' picks the last exposure date of the
        # period; null weights drop from both weighted sums) ----
        e = exp.copy()
        e["date"] = e["date"].to_numpy().astype("datetime64[D]")
        # per-date polars qcut over the non-NaN exposure cross-section;
        # NaN exposures keep rows but get a null (-1) label
        e["grp"] = -1
        for d, g in e.groupby("date"):
            vals = g["v"].to_numpy(np.float32).astype(np.float64)
            ok = np.isfinite(vals)
            labs = np.full(len(vals), -1)
            if ok.any():
                labs[ok] = polars_qcut(vals[ok], K)
            e.loc[g.index, "grp"] = labs
        pvo = pv.copy()
        pvo["date"] = pvo["date"].to_numpy().astype("datetime64[D]")
        j = e[["code", "date", "grp"]].merge(
            pvo[["code", "date", "pct_change", "tmc", "cmc"]],
            on=["code", "date"], how="left")
        j["period"] = frames.period_start(
            j["date"].to_numpy().astype("datetime64[D]"), freq)
        # positional last (reference .last()); pandas' 'last' skips NaN
        plast = lambda s: s.iloc[-1] if len(s) else np.nan
        agg = j.sort_values("date").groupby(["code", "period"]).agg(
            ret=("pct_change", lambda s: np.prod(1 + s.dropna()) - 1),
            grp=("grp", plast), tmc=("tmc", plast), cmc=("cmc", plast),
        ).reset_index()
        agg = agg.sort_values(["code", "period"])
        for col in ("grp", "tmc", "cmc"):
            agg[col] = agg.groupby("code")[col].shift(1)
        agg = agg[agg["grp"].notna() & (agg["grp"] >= 0)]

        def wmean(g):
            if wparam is None:
                return float(g["ret"].mean())
            ok = g[wparam].notna()
            den = g.loc[ok, wparam].sum()
            if den == 0:
                return 0.0
            return float((g.loc[ok, "ret"] * g.loc[ok, wparam]).sum()
                         / den)

        want = agg.groupby(["period", "grp"]).apply(
            wmean, include_groups=False)
        # compare
        periods = got["period"]; rm = got["group_return"]
        for (p, gl), wv in want.items():
            pi = np.searchsorted(periods, np.datetime64(p, "D"))
            assert pi < len(periods) and periods[pi] == np.datetime64(p, "D"), (p, periods)
            gv = rm[pi, int(gl)]
            assert np.isclose(gv, wv, rtol=2e-4, atol=1e-6), \
                (p, gl, gv, wv)
        # and no extra values where oracle has none
        want_keys = {(np.datetime64(p, "D"), int(gl)) for (p, gl) in want.index}
        for pi, p in enumerate(periods):
            for gl in range(K):
                if np.isfinite(rm[pi, gl]):
                    assert (p, gl) in want_keys, ("extra", p, gl, rm[pi, gl])
    except AssertionError as e_:
        fails.append(seed); print(f"SEED {seed}: {str(e_)[:250]}", flush=True)
    except Exception as e_:
        fails.append(seed); print(f"SEED {seed} CRASH: {e_!r}", flush=True)
    if (seed - lo + 1) % 20 == 0:
        print(f"...{seed-lo+1} done, {len(fails)} failures", flush=True)
    if (seed - lo + 1) % 10 == 0:
        # bound the in-process XLA-CPU executable cache: shape-varying
        # seeds each compile fresh graphs and the cache never evicts
        # (a 140-seed wide-shape parity run exhausted 128 GB, 2026-08-01)
        import jax
        jax.clear_caches()
print(f"DONE {hi-lo} seeds, {len(fails)} failures: {fails}")
import shutil
shutil.rmtree(td, ignore_errors=True)
