"""Headline benchmark: full CICC handbook (58 kernels) over 5000 tickers x
one trading year of minute bars, on the attached TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

Baseline is the north-star target of BASELINE.json:5 — the full set in
< 60 s. ``vs_baseline`` = 60 / measured (>1 means faster than target).

The reference publishes no numbers (BASELINE.md); its implied workload is
one polars pass per factor per day-file on all CPU cores.
"""

from __future__ import annotations

import json
import time

import jax
import numpy as np

from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit, factor_names)

N_TICKERS = 5000
DAYS_PER_BATCH = 8
TRADING_DAYS_PER_YEAR = 244
WARMUP = 1
ITERS = 5


def make_batch(rng, n_days=DAYS_PER_BATCH, n_tickers=N_TICKERS):
    shape = (n_days, n_tickers, 240)
    close = (10.0 * np.exp(np.cumsum(
        rng.normal(0, 1e-3, shape).astype(np.float32), axis=-1)))
    open_ = close * (1 + rng.normal(0, 1e-4, shape).astype(np.float32))
    high = np.maximum(open_, close) * 1.0002
    low = np.minimum(open_, close) * 0.9998
    volume = rng.integers(0, 100_000, shape).astype(np.float32)
    bars = np.stack([open_, high, low, close, volume], axis=-1)
    mask = rng.random(shape) > 0.02  # sparse missing bars
    return bars.astype(np.float32), mask


def main():
    rng = np.random.default_rng(0)
    names = factor_names()
    bars, mask = make_batch(rng)

    def step(b, m):
        out = compute_factors_jit(b, m, names=names)
        jax.block_until_ready(out)
        return out

    # warmup: host->device + compile
    db, dm = jax.device_put(bars), jax.device_put(mask)
    for _ in range(WARMUP):
        step(db, dm)

    # steady state: include the host->device copy each batch (the pipeline
    # streams day files through; transfer is part of the real step)
    times = []
    for _ in range(ITERS):
        t0 = time.perf_counter()
        db, dm = jax.device_put(bars), jax.device_put(mask)
        step(db, dm)
        times.append(time.perf_counter() - t0)

    per_batch = float(np.median(times))
    full_year = per_batch * (TRADING_DAYS_PER_YEAR / DAYS_PER_BATCH)
    target = 60.0
    print(json.dumps({
        "metric": "cicc58_5000tickers_1yr_wall",
        "value": round(full_year, 3),
        "unit": "s",
        "vs_baseline": round(target / full_year, 3),
    }))


if __name__ == "__main__":
    main()
