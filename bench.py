"""Headline benchmark: full CICC handbook (58 kernels) over 5000 tickers x
one trading year of minute bars, on the attached TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

Baseline is the north-star target of BASELINE.json:5 — the full set in
< 60 s. ``vs_baseline`` = 60 / measured (>1 means faster than target).

The reference publishes no numbers (BASELINE.md); its implied workload is
one polars pass per factor per day-file on all CPU cores.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np

from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.models.registry import (
    factor_names)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    TraceCapture, get_telemetry, reconcile)

N_TICKERS = int(os.environ.get("BENCH_TICKERS", "5000"))
TRADING_DAYS_PER_YEAR = 244
# r12: the synthetic workload scales to the "decades x global
# universe" shape a production factor service actually runs —
# BENCH_YEARS=10 BENCH_TICKERS=20000 is the 2-D mesh item's target
# shape (ROADMAP). YEARS multiplies the default timed iteration count
# (BENCH_ITERS still pins it explicitly) and the headline metric name
# carries it, so a multi-year number can never be read as the 1-year
# series.
YEARS = max(1, int(os.environ.get("BENCH_YEARS", "1")))
# The r3 capture decomposed the 146 s headline as ~0.7 s/batch of
# bandwidth+compute against a 4.8 s/batch wall — the gap is per-round-
# trip cost, so the loop now ships FEWER, BIGGER batches: 8 x 32 days
# times slightly more than a full trading year (256 days) with 4x fewer
# blocking transfers each way. 61-day batches would amortize further
# but flirt with HBM exhaustion on the 16 GB chip (the 58-factor graph
# holds ~7 [D, T, 240] f32 rolling-loop carries + shared intermediates
# live); the warmup catches RESOURCE_EXHAUSTED and retries at the
# proven 8-day shape instead of losing the window (see main).
DAYS_PER_BATCH = int(os.environ.get("BENCH_DAYS_PER_BATCH", "32"))
ITERS = int(os.environ.get("BENCH_ITERS", str(8 * YEARS)))
WARMUP = 1

# r5 loop shapes (VERDICT r4 #2): the r4 sweep measured ~12 s of FIXED
# cost per host-blocking round trip (8-day batch 14.8 s vs 61-day
# 34.6 s => fixed ~11.8 s + 0.37 s/day marginal), so even the
# consolidated-fetch loop (one fetch, 8 executes) paid ~100 s/yr of
# pure dispatch. ``resident`` ships the whole year, runs ONE scan
# executable over all batches device-side, and fetches once — 3
# host-blocking syncs per year total. ``stream`` is the r1-r4
# double-buffered per-batch loop, kept for series comparability (the
# CPU fallback pins it).
MODE = os.environ.get("BENCH_MODE", "resident")

# r7: the resident scan goes mesh-native when more than one device is
# visible — the year's packed buffers shard over the tickers axis of a
# (1, n) mesh (pipeline.compute_packed_resident_sharded), ingest of
# scan group g+1 double-buffers against the execution of group g, and
# outputs stay sharded until one consolidated per-group fetch.
# BENCH_SHARDS pins the shard count (0/unset = all local devices);
# n_shards == 1 falls back to the single-device resident scan and the
# record's ``n_shards``/``methodology`` fields say which one ran.
N_SHARDS = int(os.environ.get("BENCH_SHARDS", "0"))

# r12: the 2-D (days, tickers) pipelined resident scan (ISSUE 13).
# BENCH_MESH_DAYS=d (>1) splits each batch's day axis over d day-shards
# alongside the ticker split — the mesh resolves to (d, total//d) with
# total = BENCH_SHARDS or every local device; when total//d < 2 the run
# falls back to the 1-D sharded loop (a 2-D record REQUIRES d > 1 AND
# t > 1 — tpu_session's resident_2d carry rule enforces it).
MESH_DAYS = int(os.environ.get("BENCH_MESH_DAYS", "0"))

# r10: the device->host RESULT leg ships blocked-quantized int16 with
# per-slice bitwise-f32 widening (data/result_wire.py) — the headline's
# remaining byte lever after the ingest wire (~2.9 bytes/bar) and the
# ones the uniform-dtype narrowing rejection left on the table
# (docs/BENCHMARKS.md "Narrow result dtype"). BENCH_RESULT_WIRE=0
# disables it (and the record's methodology drops back to the r6/r7
# series, so a silent f32 fallback can never bank under the r10 name —
# tpu_session's headline carry additionally REQUIRES the result_wire
# block). The CPU fallback keeps the wire OFF: its value is
# comparability with its own r6_stream_v3 indicator series.
RESULT_WIRE = os.environ.get("BENCH_RESULT_WIRE", "1") != "0"

_SUFFIX = os.environ.get("BENCH_METRIC_SUFFIX", "")

# ISSUE 15: the market session the loops run (markets/registry.py).
# Stamped on EVERY record; telemetry/regress.py keys its sub-series on
# it (a non-default session suffixes the methodology with
# ``+session=<name>``), so a us_390 or crypto_1440 number can never
# smear into the banked 240-day baselines — the same declared-break
# discipline as BENCH_RESULT_WIRE and the mesh discriminators.
SESSION = os.environ.get("BENCH_SESSION", "cn_ashare_240")


def _tunnel_alive(timeout=90, require_tpu=False):
    """One reachability probe from a killable child (a wedged tunnel
    hangs jax backend init in-process, before any code can time out).

    ``require_tpu`` additionally asserts a non-CPU platform in the
    child: if the axon backend fails FAST instead of wedging, jax falls
    back to CPU with a warning and bare ``jax.devices()`` succeeds — a
    BENCH_REQUIRE_TPU run would then time a CPU run under the
    un-suffixed TPU metric name."""
    code = ("import jax; d = jax.devices(); "
            + ("assert d[0].platform != 'cpu'" if require_tpu else "pass"))
    try:
        return subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _ensure_device_reachable():
    """The attached-TPU tunnel occasionally wedges, and a wedged tunnel
    hangs the interpreter at backend init — before any code can time out.
    Probe it from a killable child; after a few failed probes, fall back
    to the host CPU with the metric renamed so the number can't be read
    as a TPU result."""
    if "PALLAS_AXON_POOL_IPS" not in os.environ:
        return  # not tunnel-attached; let jax pick its platform
    # the tunnel flaps: minutes-long down-windows with brief up-windows
    # between (observed 2026-07-31). Probe on a ~6.5 min wall-clock
    # budget (not a fixed attempt count — a fast-failing probe would
    # otherwise burn all attempts inside one down-window) so the bench
    # rides out a typical window before settling for the labeled CPU
    # fallback; that patience is cheap next to recording a fallback
    # number when a real TPU run was a minute of patience away.
    require_tpu = bool(os.environ.get("BENCH_REQUIRE_TPU"))
    deadline = time.monotonic() + 390.0
    while True:
        if _tunnel_alive(require_tpu=require_tpu):
            return
        if time.monotonic() + 30.0 >= deadline:
            break
        time.sleep(30)
    if require_tpu:
        # session-capture mode (benchmarks/tpu_session.py): a CPU
        # fallback must fail loudly, never print a metric line — the
        # session would otherwise bank it as a green headline step
        print("# BENCH_REQUIRE_TPU set and tunnel unreachable; aborting "
              "instead of CPU fallback", file=sys.stderr, flush=True)
        sys.exit(17)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_METRIC_SUFFIX"] = "_cpu_fallback_tunnel_down"
    # pin the fallback to the 8-day/2-iter STREAM shape every prior
    # round's fallback used: the number is a tunnel-down indicator whose
    # only value is comparability with its own series (597/618/602/736 s)
    env["BENCH_DAYS_PER_BATCH"] = "8"
    env["BENCH_ITERS"] = "2"
    env["BENCH_MODE"] = "stream"
    # re-exec THIS script only (sys.argv could be a caller like
    # benchmarks/ladder.py, which would re-emit its earlier configs)
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)], env)


class _NullTimer:
    """No-op stand-in for utils.tracing.Timer in the hot loop."""

    def __call__(self, name):
        return contextlib.nullcontext()


#: every _count_sync call-site label, for the per-point measured
#: breakdown in the headline record's ``round_trips``
_SYNC_POINTS = ("resident_ingest", "resident_compute", "resident_fetch",
                "resident_carry_fetch",
                "stream_lagged_fetch", "stream_drain_fetch",
                "stream_consolidated_fetch")


def _count_sync(point: str) -> None:
    """Count one host-BLOCKING device sync (block_until_ready or a
    materializing np.asarray) in the registry. The headline's
    ``round_trips.host_blocking_syncs`` is the measured delta of this
    counter over the timed loop — counted at the call sites, not
    predicted from the loop shape (round-5 ADVICE low #4: the predicted
    numbers under/over-counted per branch)."""
    get_telemetry().counter("bench.host_blocking_syncs", point=point)


def _observe_factor_stats(names, stats_rows, boundary) -> None:
    """Feed the fused per-batch ``[F, 9]`` data-quality sketches
    (ISSUE 12) into the run's factor-health plane; the record's
    ``factor_health`` block reads the plane's summary back. Never
    raises — a quality observation must not cost a hardware window."""
    try:
        plane = get_telemetry().factorplane
        for row in stats_rows:
            plane.observe_block(names, row, boundary=boundary)
    except Exception:  # noqa: BLE001 — diagnostics only
        pass


def _encode_kind_marks() -> dict:
    """Snapshot of the encode-kind counters (see encode_year/encode_pack);
    diff two snapshots with :func:`_encode_kind_delta`."""
    reg = get_telemetry().registry
    return {k: reg.counter_value("bench.encode_kind", kind=k)
            for k in ("wire", "raw")}


def _rolling_impl_resolved(requested: str):
    """What the rolling engine actually traced with this process
    (ops/rolling counts every trace-time resolution in the registry):
    'conv'/'pallas'/'pallas_interpret', 'mixed' if retraces diverged,
    None if no graph traced. requested='pallas' resolving to 'conv' is
    the off-TPU fallback — the record must say so, or a pallas A/B
    could silently bank conv numbers under the pallas name."""
    reg = get_telemetry().registry
    seen = [r for r in ("conv", "pallas", "pallas_interpret")
            if reg.counter_value("rolling.impl", requested=requested,
                                 resolved=r) > 0]
    if not seen:
        return None
    return seen[0] if len(seen) == 1 else "mixed"


def _encode_kind_delta(before: dict) -> str:
    """'wire' / 'raw' / 'mixed' / None over a counter window."""
    after = _encode_kind_marks()
    dw = after["wire"] - before["wire"]
    dr = after["raw"] - before["raw"]
    if dw and not dr:
        return "wire"
    if dr and not dw:
        return "raw"
    return "mixed" if (dw and dr) else None


def make_batch(rng, n_days=None, n_tickers=None, session=None):
    # f32 draws throughout (standard_normal/random with dtype=) — the
    # synth preamble runs on one host core inside a precious tunnel
    # up-window, and f64-draw-then-cast doubled its cost for bytes the
    # bench immediately threw away; distributions are unchanged.
    # n_tickers resolves the module global at CALL time (not def time):
    # tests monkeypatch bench.N_TICKERS, and the result-wire decode
    # derives the payload geometry from it — a def-time default would
    # silently desynchronize the two
    if n_days is None:
        n_days = DAYS_PER_BATCH
    if n_tickers is None:
        n_tickers = N_TICKERS
    from replication_of_minute_frequency_factor_tpu.markets import (
        get_session)
    shape = (n_days, n_tickers,
             get_session(session if session is not None
                         else SESSION).n_slots)
    close = (10.0 * np.exp(np.cumsum(
        rng.standard_normal(shape, dtype=np.float32) * np.float32(1e-3),
        axis=-1)))
    open_ = close * (1 + rng.standard_normal(shape, dtype=np.float32)
                     * np.float32(1e-4))
    high = np.maximum(open_, close) * 1.0002
    low = np.minimum(open_, close) * 0.9998
    # board lots of 100 shares, like real A-share minute volume
    volume = (rng.integers(0, 1000, shape) * 100).astype(np.float32)
    bars = np.stack([open_, high, low, close, volume], axis=-1)
    bars[..., :4] = np.round(bars[..., :4], 2)  # tick-aligned (0.01 CNY)
    mask = rng.random(shape, dtype=np.float32) > 0.02  # sparse missing bars
    return bars.astype(np.float32), mask


def encode_year(batches, use_wire, max_passes=4):
    """Encode every batch under ONE shared widen-only floor so all
    buffers land on a single (spec, length) — the resident scan path
    stacks them device-side, which needs uniform shapes.

    The FLOOR is monotonic but the SPEC is not a simple ladder: the
    volume-mode switch (lots -> shares) can change a straggler's packed
    width when re-encoded under the final floor, so one extra pass does
    NOT always converge (round-5 ADVICE low #2 — the old single pass
    silently dropped the whole year to raw-f32, 4x the wire bytes,
    invisibly). Now re-encode passes repeat until every batch shares one
    spec (the floor is bounded, so this terminates; ``max_passes``
    guards the pathological case), and the outcome lands in the
    ``bench.encode_kind`` registry counter either way — the headline
    record's ``encode_kind`` field reads it back, so a raw fallback can
    never again be invisible."""
    tel = get_telemetry()
    if use_wire:
        floor: dict = {}
        encs = [wire.encode(b, m, floor=floor) for b, m in batches]
        for _ in range(max_passes):
            if not all(e is not None for e in encs):
                break  # unrepresentable under wire: raw fallback
            packs = [wire.pack_arrays(e.arrays) for e in encs]
            if len({p[1] for p in packs}) == 1:
                tel.counter("bench.encode_kind", kind="wire")
                return [p[0] for p in packs], packs[0][1], "wire"
            # divergent specs: re-encode EVERYTHING under the
            # accumulated floor — once a full pass stops widening it,
            # every batch encodes at the floor's widths and the specs
            # are uniform
            encs = [wire.encode(b, m, floor=floor) for b, m in batches]
    packs = [wire.pack_arrays((b, m.view(np.uint8))) for b, m in batches]
    tel.counter("bench.encode_kind", kind="raw")
    return [p[0] for p in packs], packs[0][1], "raw"


def encode_year_sharded(batches, use_wire, n_shards, max_passes=4,
                        bucket=1):
    """Sharded twin of :func:`encode_year`: same shared widen-only
    floor + spec-convergence loop, then each batch splits into
    ``n_shards`` contiguous ticker blocks packed as one ``[S, L]``
    stack (wire.pack_sharded). The tickers axis pads with masked lanes
    to a multiple of lcm(bucket, n_shards) first — the same
    TICKER_BUCKET x shard_mult lcm rule the pipeline's grid uses
    (pipeline._grid_batch); the headline passes
    ``bucket=pipeline.TICKER_BUCKET``, tests keep bucket=1 so tiny
    years don't pad 8x. Returns ``(stacks, spec, kind, t_pad)`` where
    ``stacks[i]`` is batch i's ``[S, L]`` uint8 stack."""
    mult = int(bucket * n_shards // np.gcd(bucket, n_shards))
    t = batches[0][0].shape[1]
    t_pad = -(-t // mult) * mult
    if t_pad != t:
        pad_b = [(0, 0), (0, t_pad - t), (0, 0), (0, 0)]
        pad_m = [(0, 0), (0, t_pad - t), (0, 0)]
        batches = [(np.pad(b, pad_b), np.pad(m, pad_m))
                   for b, m in batches]
    tel = get_telemetry()
    if use_wire:
        floor: dict = {}
        encs = [wire.encode(b, m, floor=floor) for b, m in batches]
        for _ in range(max_passes):
            if not all(e is not None for e in encs):
                break  # unrepresentable under wire: raw fallback
            packs = [wire.pack_sharded(e.arrays, n_shards) for e in encs]
            if len({p[1] for p in packs}) == 1:
                tel.counter("bench.encode_kind", kind="wire")
                return ([p[0] for p in packs], packs[0][1], "wire",
                        t_pad)
            encs = [wire.encode(b, m, floor=floor) for b, m in batches]
    packs = [wire.pack_sharded((b, m.view(np.uint8)), n_shards)
             for b, m in batches]
    tel.counter("bench.encode_kind", kind="raw")
    return [p[0] for p in packs], packs[0][1], "raw", t_pad


def encode_year_2d(batches, use_wire, d_shards, t_shards, max_passes=4,
                   bucket=1):
    """2-D twin of :func:`encode_year_sharded` (ISSUE 13): the tickers
    axis pads with masked lanes to lcm(bucket, t_shards) AND the days
    axis pads with fully-masked filler days to a multiple of
    ``d_shards`` (day-group padding — its waste is the ``axis=days``
    entry of ``mesh.pad_waste_frac``); then the same shared widen-only
    floor + spec-convergence loop, with each batch packed as a
    ``[Sd, St, L]`` per-tile stack (wire.pack_sharded_2d). Returns
    ``(stacks, spec, kind, t_pad, d_pad)``."""
    mult = int(bucket * t_shards // np.gcd(bucket, t_shards))
    t = batches[0][0].shape[1]
    t_pad = -(-t // mult) * mult
    d = batches[0][0].shape[0]
    d_pad = -(-d // d_shards) * d_shards
    if t_pad != t or d_pad != d:
        pad_b = [(0, d_pad - d), (0, t_pad - t), (0, 0), (0, 0)]
        pad_m = [(0, d_pad - d), (0, t_pad - t), (0, 0)]
        batches = [(np.pad(b, pad_b), np.pad(m, pad_m))
                   for b, m in batches]
    tel = get_telemetry()
    if use_wire:
        floor: dict = {}
        encs = [wire.encode(b, m, floor=floor) for b, m in batches]
        for _ in range(max_passes):
            if not all(e is not None for e in encs):
                break  # unrepresentable under wire: raw fallback
            packs = [wire.pack_sharded_2d(e.arrays, d_shards, t_shards)
                     for e in encs]
            if len({p[1] for p in packs}) == 1:
                tel.counter("bench.encode_kind", kind="wire")
                return ([p[0] for p in packs], packs[0][1], "wire",
                        t_pad, d_pad)
            encs = [wire.encode(b, m, floor=floor) for b, m in batches]
    packs = [wire.pack_sharded_2d((b, m.view(np.uint8)), d_shards,
                                  t_shards) for b, m in batches]
    tel.counter("bench.encode_kind", kind="raw")
    return [p[0] for p in packs], packs[0][1], "raw", t_pad, d_pad


#: AOT-compiled resident executables, keyed on everything that shapes
#: the module — lowering re-traces the whole 58-kernel graph (seconds
#: of host work), so a memo hit must skip the .lower() call itself,
#: not just the .compile(). The r5 dict memo that lived here became the
#: serving layer's keyed ExecutableCache (ISSUE 6); bench rides the
#: generalized object so the two cannot drift.
from replication_of_minute_frequency_factor_tpu.serve.executables import (  # noqa: E402
    ExecutableCache)

_AOT_COMPILED = ExecutableCache()

_FLIGHT = None


def _flight_note(trigger, **detail):
    """Anomaly hook for the OOM ladder (ISSUE 8): capture a
    flight-recorder dump at each demotion so the decision — which rung,
    what error, what the registry said — is diagnosable after the
    tunnel window closes. Dump files land in BENCH_TELEMETRY_DIR when
    set (the counter/event record lands regardless); never raises."""
    global _FLIGHT
    try:
        from replication_of_minute_frequency_factor_tpu.telemetry import (
            FlightRecorder)
        if _FLIGHT is None:
            _FLIGHT = FlightRecorder(
                telemetry=get_telemetry(),
                dump_dir=os.environ.get("BENCH_TELEMETRY_DIR") or None)
        _FLIGHT.dump(trigger, extra=detail, force=True)
    except Exception:  # noqa: BLE001 — diagnostics must not cost a run
        pass


def _aot_resident(label, key, lower_fn, phases):
    """First build of a resident scan executable through
    telemetry.attribution.compile_with_telemetry (AOT lower+compile),
    memoised per module shape (serve.ExecutableCache): the
    ``compile``/``compile_s`` stage then MEANS compile (and agrees with
    the manifest's ``xla`` block), and every later execute stage means
    execute — the old jit path folded the real compile cost into the
    first execute's wall. A warm hit keeps the ``compile_s`` key at
    ~0 so the stage series keeps its column."""
    phases.setdefault("compile_s", 0.0)
    return _AOT_COMPILED.get(label, key, lower_fn, compile_cost=phases)


def run_resident(batches, names, use_wire, group, keep_results=False,
                 result_spec=None):
    """The whole year in O(1) host round trips (VERDICT r4 #2):

      encode  — host: wire-encode + pack all batches (shared floor)
      ingest  — N async device_puts, ONE blocking sync when all landed
      compute — ONE scan executable over the resident buffers (per
                ``group``; group == N unless HBM forced a split)
      fetch   — the year's [N, F, D, T] results in one np.asarray pass

    Returns (phases dict, kind, results) where ``results`` is the
    fetched per-batch ``[F, D, T]`` list only when ``keep_results``
    (the resident_diag equality check needs them; the timed loops
    don't, and a year of results held live would double host RSS).
    2 + ceil(N/group) host-blocking syncs per year vs the stream
    loop's 2 per batch; the ~12 s/round-trip fixed cost (TPU_SESSION
    sweep) is paid once per scan group.

    The scan executable is AOT-built through
    ``compile_with_telemetry`` (memoised per module shape — see
    :func:`_aot_resident`), so ``phases['compile_s']`` is real compile
    wall on the first run and ~0 on warm reruns, and ``compute_s``
    always means execute.

    ``result_spec`` (ISSUE 10) fuses the blocked-quantized RESULT wire
    as the scan body's final stage: the fetch ships packed ``[N, L]``
    uint8 payloads (~half the f32 bytes), host-decoded here
    (``phases['decode_s']``; ``phases['result_wire']`` carries the
    widen/overflow/byte verdict and ``keep_results`` returns DECODED
    blocks). Overflowed spill budgets are reported, not raised — the
    caller owns the widen-only floor (see main's warmup).

    The per-factor data-quality sketch (ISSUE 12) is ALWAYS fused as a
    scan side-output: each group's tiny ``[g, F, 9]`` stats array
    materializes right after the group's main fetch (the bytes are
    already landed — the measured host-blocking sync count is
    unchanged, no new ``_count_sync`` point) and feeds
    ``telemetry.factorplane``; the record's ``factor_health`` block
    reads the plane back."""
    from replication_of_minute_frequency_factor_tpu.config import (
        get_config)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        lower_packed_resident)
    phases = {}
    t0 = time.perf_counter()
    bufs, spec, kind = encode_year(batches, use_wire)
    phases["encode_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    dbufs = [jax.device_put(b) for b in bufs]  # all puts in flight
    _count_sync("resident_ingest")
    jax.block_until_ready(dbufs)
    phases["ingest_s"] = round(time.perf_counter() - t0, 3)
    phases["ingest_MB"] = round(sum(b.nbytes for b in bufs) / 1e6, 1)
    roll = get_config().rolling_impl
    outs = []
    t0 = time.perf_counter()
    compute_t0 = None
    for g0 in range(0, len(dbufs), group):
        gbufs = tuple(dbufs[g0:g0 + group])
        compiled = _aot_resident(
            "bench_resident_scan",
            ("resident", len(gbufs), gbufs[0].shape, spec, kind, names,
             roll, result_spec, "stats"),
            lambda: lower_packed_resident(gbufs, spec, kind,
                                          names=names,
                                          rolling_impl=roll,
                                          result_spec=result_spec,
                                          factor_stats=True),
            phases)
        if compute_t0 is None:  # compile attributed apart from execute
            compute_t0 = time.perf_counter()
        outs.append(compiled(gbufs))
    _count_sync("resident_compute")
    jax.block_until_ready(outs)
    phases["compute_s"] = round(
        time.perf_counter() - (compute_t0 or t0), 3)
    t0 = time.perf_counter()
    results = [] if keep_results else None
    fetched_mb = 0.0
    payload_rows = []  # result-wire mode: fetched [g, L] u8 stacks
    stats_rows = []    # fused [g, F, 9] data-quality sketches
    for o in outs:
        ys, st = o
        _count_sync("resident_fetch")
        h = np.asarray(ys)  # [group, F, D, T] f32, or [group, L] u8
        # the stats side-output rode the same executable; its bytes are
        # ready the moment the main fetch lands (no new sync point)
        stats_rows.extend(np.asarray(st))
        fetched_mb += h.nbytes
        if result_spec is not None:
            payload_rows.extend(h)
        elif keep_results:
            results.extend(h)
    phases["fetch_s"] = round(time.perf_counter() - t0, 3)
    phases["fetch_MB"] = round(fetched_mb / 1e6, 3)
    n_d, n_t = batches[0][0].shape[0], batches[0][0].shape[1]
    phases["fetch_logical_MB"] = round(
        len(batches) * len(names) * n_d * n_t * 4 / 1e6, 3)
    _observe_factor_stats(names, stats_rows, "resident.fetch")
    if result_spec is not None:
        _decode_result_phases(phases, payload_rows, names, n_d, n_t,
                              n_t, result_spec, results)
    return phases, kind, results


def _decode_result_phases(phases, payload_rows, names, n_d, t_pad,
                          n_tickers, result_spec, results,
                          n_days=None):
    """Shared host half of the result wire for the resident loops:
    decode every fetched payload row (strict=False — the caller owns
    the widen-only floor), fold the verdicts into
    ``phases['result_wire']``, time the numpy dequantize as its own
    serial stage, and fill ``results`` with DECODED ``[F, D, :n_tickers]``
    blocks when the caller kept them. ``n_days`` (2-D loop) slices the
    day-group padding back off kept results — ``n_d`` stays the PADDED
    extent the payload geometry encodes."""
    from replication_of_minute_frequency_factor_tpu.data import (
        result_wire as rw)
    t0 = time.perf_counter()
    widened = overflow = quantized = 0
    by_factor: dict = {}
    keep_days = n_days if n_days is not None else n_d
    for row in payload_rows:
        dec, v = rw.decode_block(row, len(names), n_d, t_pad,
                                 result_spec.spill_rows, strict=False,
                                 names=names)
        widened += v["widened"]
        overflow += v["overflow"]
        quantized += v["quantized"]
        for n, c in (v.get("widened_by_factor") or {}).items():
            by_factor[n] = by_factor.get(n, 0) + c
        if results is not None:
            results.append(dec[:, :keep_days, :n_tickers])
    phases["decode_s"] = round(time.perf_counter() - t0, 3)
    payload_b = phases["fetch_MB"] * 1e6
    logical_b = phases["fetch_logical_MB"] * 1e6
    phases["result_wire"] = {
        "enabled": True,
        "spill_rows": result_spec.spill_rows,
        "quantized_slices": quantized,
        "widened_slices": widened,
        "overflow_slices": overflow,
        # per-factor widen attribution (ISSUE 12): WHICH factors'
        # slices fail the round-trip check — the ROADMAP's open
        # question about the 9 strict-pinned volume factors reads
        # straight off the banked record
        "widened_by_factor": by_factor,
        "payload_MB": phases["fetch_MB"],
        "f32_logical_MB": phases["fetch_logical_MB"],
        "ratio_vs_f32": round(logical_b / payload_b, 3)
        if payload_b else None,
    }
    tel = get_telemetry()
    tel.gauge("result.widened_slices", widened)
    if overflow:
        tel.counter("result.overflow_slices", overflow)
    # fold the widen disposition into the factor-health plane: the
    # record's factor_health.widen_rate (and regress's
    # <metric>.widen_rate sub-series) read it back
    try:
        tel.factorplane.observe_widen(
            names, by_factor,
            slices_per_factor=n_d * max(1, len(payload_rows)))
    except Exception:  # noqa: BLE001 — diagnostics only
        pass


def run_resident_sharded(batches, names, use_wire, group, mesh,
                         keep_results=False, bucket=1,
                         result_spec=None):
    """The resident year, mesh-native AND ingest-overlapped:

      encode  — host: shared-floor wire-encode + per-shard pack
                (encode_year_sharded; tickers padded to the shard
                multiple with masked lanes)
      ingest  — scan group 0's ``[g, S, L]`` stack device_puts with a
                NamedSharding over the mesh tickers axis; every LATER
                group's put is dispatched while the previous group's
                scan executes, so its transfer hides behind device
                compute instead of serializing ahead of it. No ingest
                ever blocks the host: the executable's data dependency
                orders transfer before compute.
      compute — one sharded scan executable per group
                (pipeline.compute_packed_resident_sharded's module,
                AOT-built via compile_with_telemetry), zero collectives
                outside the doc_pdf* rank gather; outputs stay sharded
                on device.
      fetch   — one consolidated per-group ``np.asarray`` (gathers
                each shard's contiguous block once).

    Host-blocking syncs per year: 1 (the compute block) +
    ceil(N/group) fetches — O(1), not O(batches), and ingest
    contributes ZERO blocking syncs. ``phases['ingest_hidden_s']``
    (also the ``resident.ingest_hidden_s`` gauge) is the host wall
    spent dispatching puts while earlier groups' compute was in flight
    — on async transports it is a lower bound of the hidden transfer
    time; on the CPU backend the put IS the copy, so it is exact.
    """
    from replication_of_minute_frequency_factor_tpu.config import (
        get_config)
    from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
        put_packed_year)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        lower_packed_resident_sharded)
    tel = get_telemetry()
    n_shards = mesh.devices.size
    # counts stay OUT of phases: reconcile() sums every bare numeric
    # entry as seconds (the record carries n_shards/groups separately)
    phases = {}
    t0 = time.perf_counter()
    stacks, spec, kind, t_pad = encode_year_sharded(
        batches, use_wire, n_shards, bucket=bucket)
    phases["encode_s"] = round(time.perf_counter() - t0, 3)
    # lcm ticker-padding waste (ISSUE 9): dead lanes every shard still
    # computes — the mesh.pad_waste_frac gauge + the record's mesh
    # block carry it
    tel.meshplane.record_pad_waste(batches[0][0].shape[1], t_pad,
                                   axis="tickers")
    groups = [np.stack(stacks[g0:g0 + group])  # [g, S, L] per group
              for g0 in range(0, len(stacks), group)]
    phases["ingest_MB"] = round(
        sum(g.nbytes for g in groups) / 1e6, 1)
    roll = get_config().rolling_impl
    t0 = time.perf_counter()
    pend = put_packed_year(groups[0], mesh)
    phases["ingest_s"] = round(time.perf_counter() - t0, 3)
    outs = []
    hidden = 0.0
    compute_t0 = None
    t0 = time.perf_counter()
    # fused factor stats over the LOGICAL tickers only (ISSUE 12): the
    # lcm pad lanes are masked filler and must not read as missing data
    n_tickers_logical = batches[0][0].shape[1]
    for gi in range(len(groups)):
        d = pend
        compiled = _aot_resident(
            "bench_resident_scan_sharded",
            ("sharded", d.shape, spec, kind, names, roll, mesh,
             result_spec, "stats", n_tickers_logical),
            lambda: lower_packed_resident_sharded(
                d, spec, kind, mesh, names=names, rolling_impl=roll,
                result_spec=result_spec,
                factor_stats=n_tickers_logical),
            phases)
        if compute_t0 is None:
            compute_t0 = time.perf_counter()
        t_dispatch = time.perf_counter()
        outs.append(compiled(d))
        # shard-balance watermarks per scan group (ISSUE 9): a daemon
        # watcher blocks on each shard of this group's output in the
        # background and records its completion time since dispatch —
        # the hot loop never blocks, so the measured sync counts and
        # the double-buffered overlap are untouched (the main result,
        # not the replicated stats side-output, carries the shards)
        tel.meshplane.watch_async(outs[-1][0],
                                  boundary="resident.group",
                                  t0=t_dispatch)
        # HBM watermark per scan group (ISSUE 8): the first measured
        # signal the OOM ladder's group-halving gets, sampled while
        # this group's buffers and the double-buffered next put are
        # both live (the resident loop's peak shape)
        tel.hbm.sample("resident.group")
        if gi + 1 < len(groups):
            # double-buffer: group gi+1's transfer rides behind group
            # gi's execution; dispatch only, never block
            t1 = time.perf_counter()
            pend = put_packed_year(groups[gi + 1], mesh)
            hidden += time.perf_counter() - t1
    _count_sync("resident_compute")
    jax.block_until_ready(outs)
    phases["compute_s"] = round(
        time.perf_counter() - (compute_t0 or t0), 3)
    # join the shard watchers (their blocks resolved with the barrier
    # above) so the mesh block read after this run is complete
    tel.meshplane.drain()
    # 6 decimals, not the usual 3: a small smoke's overlapped put
    # dispatch is sub-millisecond, and "overlap happened at all" must
    # survive the rounding
    phases["ingest_hidden_s"] = round(hidden, 6)
    tel.gauge("resident.ingest_hidden_s", round(hidden, 6),
              n_shards=str(n_shards))
    t0 = time.perf_counter()
    results = [] if keep_results else None
    fetched_mb = 0.0
    n_tickers = batches[0][0].shape[1]
    n_days = batches[0][0].shape[0]
    payload_rows = []
    stats_rows = []
    for o in outs:
        ys, st = o
        _count_sync("resident_fetch")
        h = np.asarray(ys)  # [g, F, D, T_pad] f32, or [g, L] u8 (wire)
        # the [g, F, 9] stats side-output rode the same module; its
        # bytes land with the consolidated fetch (no new sync point)
        stats_rows.extend(np.asarray(st))
        fetched_mb += h.nbytes
        if result_spec is not None:
            payload_rows.extend(h)
        elif keep_results:
            results.extend(h[..., :n_tickers])
    phases["fetch_s"] = round(time.perf_counter() - t0, 3)
    _observe_factor_stats(names, stats_rows, "resident.fetch")
    # RAW fetched bytes (pad lanes included) AND the logical payload
    # (ISSUE 10 satellite): the old single number silently reported
    # padded-ticker bytes on sharded runs — h[..., :n_tickers] sliced
    # AFTER counting — so any compression ratio computed from it was
    # flattered by dead lanes. The ratio below is against the LOGICAL
    # f32 payload.
    phases["fetch_MB"] = round(fetched_mb / 1e6, 3)
    phases["fetch_logical_MB"] = round(
        len(batches) * len(names) * n_days * n_tickers * 4 / 1e6, 3)
    if result_spec is not None:
        _decode_result_phases(phases, payload_rows, names, n_days,
                              t_pad, n_tickers, result_spec, results)
    return phases, kind, results


def run_resident_2d(batches, names, use_wire, group, mesh,
                    keep_results=False, bucket=1, result_spec=None,
                    keep_carry=False):
    """The resident year on the full 2-D ``(days, tickers)`` mesh,
    pipelined across the day axis (ISSUE 13):

      encode  — host: shared-floor wire-encode + per-tile pack
                (encode_year_2d; tickers padded to the shard multiple,
                days padded to the day-shard multiple — both wastes in
                ``mesh.pad_waste_frac{axis=}``)
      ingest  — group 0's ``[g, Sd, St, L]`` stack device_puts over
                BOTH mesh axes; every later group's put dispatches
                while the previous group's scan executes (the same
                double-buffer as the 1-D loop, now per day-shard too:
                day-shard i computes its span while day-shard i+1's
                bytes ingest). No ingest ever blocks the host.
      compute — one 2-D scan executable per group
                (pipeline.compute_packed_resident_2d's module): per
                tile unpack + decode + 58 kernels, the doc_pdf* rank
                the only cross-ticker collective and the cross-day
                carry handoff (ppermute on the days axis) the only
                cross-day one. The carry THREADS between groups as a
                device array — zero extra host-blocking syncs per
                group vs the 1-D sharded loop (the resident_2d smoke's
                gate).
      fetch   — one consolidated per-group ``np.asarray``; the
                year-end carry is fetched ONCE (and only under
                ``keep_carry``), counted at its own sync point.

    Returns ``(phases, kind, results, carry)`` — ``carry`` is the
    host-fetched ``{last_close, n_bars, has}`` year-end intraday
    prefix state when ``keep_carry``, else None (it still threaded
    between groups on device)."""
    from replication_of_minute_frequency_factor_tpu.config import (
        get_config)
    from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
        DAYS_AXIS, TICKERS_AXIS, put_packed_year_2d, put_span_carry)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        lower_packed_resident_2d)
    from replication_of_minute_frequency_factor_tpu.stream.carry import (
        init_span_state)
    tel = get_telemetry()
    d_shards = mesh.shape[DAYS_AXIS]
    t_shards = mesh.shape[TICKERS_AXIS]
    phases = {}
    t0 = time.perf_counter()
    stacks, spec, kind, t_pad, d_pad = encode_year_2d(
        batches, use_wire, d_shards, t_shards, bucket=bucket)
    phases["encode_s"] = round(time.perf_counter() - t0, 3)
    n_tickers = batches[0][0].shape[1]
    n_days = batches[0][0].shape[0]
    # padding waste per AXIS (ISSUE 13 satellite): the lcm ticker pad
    # AND the day-group pad to d — dead lanes/days every shard still
    # computes; both land in mesh.pad_waste_frac{axis=} and the
    # record's mesh block
    tel.meshplane.record_pad_waste(n_tickers, t_pad, axis="tickers")
    tel.meshplane.record_pad_waste(n_days, d_pad, axis="days")
    groups = [np.stack(stacks[g0:g0 + group])  # [g, Sd, St, L]
              for g0 in range(0, len(stacks), group)]
    phases["ingest_MB"] = round(sum(g.nbytes for g in groups) / 1e6, 1)
    roll = get_config().rolling_impl
    t0 = time.perf_counter()
    pend = put_packed_year_2d(groups[0], mesh)
    carry = put_span_carry(init_span_state(t_pad), mesh)
    phases["ingest_s"] = round(time.perf_counter() - t0, 3)
    outs = []
    hidden = 0.0
    compute_t0 = None
    t0 = time.perf_counter()
    for gi in range(len(groups)):
        d = pend
        cin = carry
        compiled = _aot_resident(
            "bench_resident_scan_2d",
            ("2d", d.shape, spec, kind, names, roll, mesh, result_spec,
             "stats", n_days, n_tickers),
            lambda: lower_packed_resident_2d(
                d, cin, spec, kind, mesh, names=names,
                rolling_impl=roll, result_spec=result_spec,
                factor_stats=(n_days, n_tickers)),
            phases)
        if compute_t0 is None:
            compute_t0 = time.perf_counter()
        t_dispatch = time.perf_counter()
        out = compiled(d, cin)
        outs.append(out)
        carry = out[-1]  # threads on device into the next group
        tel.meshplane.note_collective("carry_handoff")
        # per-axis shard watermarks (ISSUE 13): the daemon watcher maps
        # each device back to its (day-shard, ticker-shard) coordinate
        # — day-axis skew is the number that says whether the day
        # pipeline balances, apart from the ticker split
        tel.meshplane.watch_async_mesh(out[0], mesh,
                                       boundary="resident.group2d",
                                       t0=t_dispatch)
        tel.hbm.sample("resident.group")
        if gi + 1 < len(groups):
            t1 = time.perf_counter()
            pend = put_packed_year_2d(groups[gi + 1], mesh)
            hidden += time.perf_counter() - t1
    _count_sync("resident_compute")
    jax.block_until_ready([o[0] for o in outs])
    phases["compute_s"] = round(
        time.perf_counter() - (compute_t0 or t0), 3)
    tel.meshplane.drain()
    phases["ingest_hidden_s"] = round(hidden, 6)
    tel.gauge("resident.ingest_hidden_s", round(hidden, 6),
              n_shards=str(d_shards * t_shards))
    t0 = time.perf_counter()
    results = [] if keep_results else None
    fetched_mb = 0.0
    payload_rows = []
    stats_rows = []
    for o in outs:
        ys, st = o[0], o[1]
        _count_sync("resident_fetch")
        h = np.asarray(ys)  # [g, F, D_pad, T_pad] f32, or [g, L] u8
        stats_rows.extend(np.asarray(st))
        fetched_mb += h.nbytes
        if result_spec is not None:
            payload_rows.extend(h)
        elif keep_results:
            results.extend(h[..., :n_days, :n_tickers])
    phases["fetch_s"] = round(time.perf_counter() - t0, 3)
    _observe_factor_stats(names, stats_rows, "resident.fetch")
    # RAW fetched bytes include BOTH paddings; the logical payload
    # strips them (the PR 10 ticker fix, extended to the day axis)
    phases["fetch_MB"] = round(fetched_mb / 1e6, 3)
    phases["fetch_logical_MB"] = round(
        len(batches) * len(names) * n_days * n_tickers * 4 / 1e6, 3)
    if result_spec is not None:
        _decode_result_phases(phases, payload_rows, names, d_pad,
                              t_pad, n_tickers, result_spec, results,
                              n_days=n_days)
    host_carry = None
    if keep_carry:
        # once per YEAR, never per group — and only when asked: the
        # timed loops leave the carry on device so the sync budget
        # stays <= the 1-D loop's 1 + n_groups
        _count_sync("resident_carry_fetch")
        host_carry = {k: np.asarray(v)[:n_tickers]
                      for k, v in carry.items()}
    return phases, kind, results, host_carry


def resident_diag(batches, names, use_wire, stream_results):
    """One-shot resident-path driver artifact (VERDICT r5 weak #5):
    every CPU-fallback artifact to date exercised only the stream loop,
    leaving the resident ``lax.scan`` path with ZERO coverage in any
    banked driver artifact. This re-runs the SAME timed batches through
    ``compute_packed_resident`` once, checks exposure equality against
    the stream loop's materialized results, and returns a
    timing/equality block recorded alongside the headline stream
    series. Never raises — the fallback record must print even when the
    diag itself trips."""
    try:
        t0 = time.perf_counter()
        phases, kind, results = run_resident(
            batches, names, use_wire, group=len(batches),
            keep_results=True)
        block = {"total_s": round(time.perf_counter() - t0, 3),
                 "phases": phases, "encode_kind": kind,
                 "batches": len(batches)}
        if stream_results is None or len(stream_results) != len(results):
            block["equal"] = None
            block["note"] = (f"stream results unavailable for comparison "
                             f"({0 if not stream_results else len(stream_results)} "
                             f"vs {len(results)} batches)")
            return block
        equal = True
        max_diff = 0.0
        for s, r in zip(stream_results, results):
            s, r = np.asarray(s), np.asarray(r)
            if s.shape != r.shape or not np.array_equal(
                    np.isfinite(s), np.isfinite(r)):
                equal = False
                continue
            finite = np.isfinite(s)
            if finite.any():
                max_diff = max(max_diff, float(np.max(
                    np.abs(s[finite] - r[finite]))))
            # scan vs per-batch executes may fuse differently; exact
            # zero is typical but a few ulps of drift is not a driver
            # bug — the parity suites own tight numerics
            if not np.allclose(s, r, rtol=1e-5, atol=1e-6,
                               equal_nan=True):
                equal = False
        block["equal"] = equal
        block["max_abs_diff"] = max_diff
        return block
    except Exception as e:  # noqa: BLE001 — diagnostic only
        return {"equal": None, "error": f"{type(e).__name__}: {e}"[:300]}


#: factors the sharded-resident smoke drives: one per family shape
#: class — plain masked reduction, rolling-window (the scan-sensitive
#: family), segment sort, top-k, the cross-sectional doc_pdf rank (the
#: ONLY collective), and the two std-ratio kernels whose division XLA
#: fuses shape-dependently (ulp-level, the documented non-bitwise pair)
_SMOKE_FACTORS = ("vol_return1min", "mmt_ols_qrs", "doc_kurt",
                  "doc_vol10_ratio", "doc_pdf60", "vol_upRatio",
                  "trade_headRatio")

#: kernels where sharded-vs-single equality is ulp-level, not bitwise:
#: their sqrt/sqrt division fuses differently per module shape (XLA
#: cost-model-dependent; observed 1-4 ulps on CPU). Everything else —
#: including the collective-routed doc_pdf* — must be BITWISE.
_ULP_FACTORS = frozenset({"vol_upRatio", "vol_downRatio"})


def sharded_smoke(n_batches=2, days=2, tickers=32, names=None,
                  group=None):
    """run_tests.sh --quick smoke: the sharded resident scan vs the
    single-device resident scan over every visible device, on a small
    synthetic year. Returns ONE JSON-able verdict dict; ``ok`` is True
    iff every factor matches bitwise (ulp-tolerance only for the
    documented ``_ULP_FACTORS`` pair) AND the overlap metric fired when
    more than one scan group ran."""
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)
    rng = np.random.default_rng(11)
    names = tuple(names or _SMOKE_FACTORS)
    batches = [make_batch(rng, n_days=days, n_tickers=tickers)
               for _ in range(n_batches)]
    use_wire = wire.encode(*batches[0]) is not None
    group = group or max(1, -(-n_batches // 2))
    mesh = resident_mesh()
    n_shards = mesh.devices.size
    _, _, single = run_resident(batches, names, use_wire,
                                group=n_batches, keep_results=True)
    phases, kind, sharded = run_resident_sharded(
        batches, names, use_wire, group, mesh, keep_results=True)
    bad, max_diff = [], 0.0
    for i, (s, r) in enumerate(zip(single, sharded)):
        for j, n in enumerate(names):
            a, b = np.asarray(s[j]), np.asarray(r[j])
            if np.array_equal(a, b, equal_nan=True):
                continue
            f = np.isfinite(a) & np.isfinite(b)
            d = float(np.abs(a[f] - b[f]).max(initial=0.0))
            max_diff = max(max_diff, d)
            scale = float(np.abs(a[f]).max(initial=1.0)) or 1.0
            if n in _ULP_FACTORS and np.array_equal(
                    np.isfinite(a), np.isfinite(b)) \
                    and d <= 16 * np.finfo(np.float32).eps * scale:
                continue
            bad.append(n)
    groups = -(-n_batches // group)
    overlap_ok = groups < 2 or phases.get("ingest_hidden_s", 0) > 0
    return {"smoke": "sharded_resident", "n_shards": n_shards,
            "batches": n_batches, "factors": len(names),
            "encode_kind": kind, "scan_groups": groups,
            "ingest_hidden_s": phases.get("ingest_hidden_s"),
            "mismatched": sorted(set(bad)), "max_abs_diff": max_diff,
            "ok": not bad and overlap_ok and n_shards > 1}


def resident_2d_smoke(n_batches=2, days=2, tickers=32, names=None,
                      group=None, mesh_shape=(2, 4)):
    """run_tests.sh --quick smoke (8 virtual CPU devices): the 2-D
    ``(days, tickers)`` pipelined resident scan end to end (ISSUE 13).
    One JSON verdict; ``ok`` iff

    * ALL 58 factors equal the single-device resident scan (bitwise
      outside the documented ``_ULP_FACTORS`` pair) on the ``(2, 4)``
      mesh;
    * the 2-D loop's measured host-blocking syncs are <= the 1-D
      sharded loop's on the same year/groups (zero extra syncs per
      group — the carry threads on device);
    * the carry-handoff collective count is nonzero;
    * the handed-off year-end carry bit-equals the single-device
      ``stream/carry`` prefix-state fold over the same decoded days.
    """
    import jax.numpy as jnp

    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)
    from replication_of_minute_frequency_factor_tpu.stream import (
        carry as scarry)
    rng = np.random.default_rng(13)
    names = tuple(names or factor_names())
    batches = [make_batch(rng, n_days=days, n_tickers=tickers)
               for _ in range(n_batches)]
    use_wire = wire.encode(*batches[0]) is not None
    group = group or max(1, -(-n_batches // 2))
    d_sh, t_sh = mesh_shape
    mesh2d = resident_mesh(shape=mesh_shape)
    reg = get_telemetry().registry
    # the reference runs at the SAME scan-group structure: XLA fuses a
    # handful of kernels (the documented _ULP_FACTORS class) ulp-
    # differently between an N=1 and an N=2 scan module on ANY
    # backend, sharded or not — matching N isolates exactly the
    # sharding question this smoke gates
    _, _, single = run_resident(batches, names, use_wire,
                                group=group, keep_results=True)
    # 1-D sharded sync budget on the same year/groups (the comparison
    # baseline for "zero extra host-blocking syncs per group")
    mesh1d = resident_mesh(d_sh * t_sh)
    s0 = reg.counter_total("bench.host_blocking_syncs")
    run_resident_sharded(batches, names, use_wire, group, mesh1d)
    syncs_1d = int(reg.counter_total("bench.host_blocking_syncs") - s0)
    handoff0 = reg.counter_value("mesh.collective_dispatches",
                                 label="carry_handoff")
    s0 = reg.counter_total("bench.host_blocking_syncs")
    phases, kind, sharded, _ = run_resident_2d(
        batches, names, use_wire, group, mesh2d, keep_results=True)
    syncs_2d = int(reg.counter_total("bench.host_blocking_syncs") - s0)
    handoffs = int(reg.counter_value("mesh.collective_dispatches",
                                     label="carry_handoff") - handoff0)
    # the carry fetch rides a SEPARATE run so it cannot blur the sync
    # comparison above
    _, _, _, carry = run_resident_2d(batches, names, use_wire,
                                     len(batches), mesh2d,
                                     keep_carry=True)
    bad, max_diff = [], 0.0
    for s, r in zip(single, sharded):
        for j, n in enumerate(names):
            a, b = np.asarray(s[j]), np.asarray(r[j])
            if np.array_equal(a, b, equal_nan=True):
                continue
            f = np.isfinite(a) & np.isfinite(b)
            d = float(np.abs(a[f] - b[f]).max(initial=0.0))
            max_diff = max(max_diff, d)
            scale = float(np.abs(a[f]).max(initial=1.0)) or 1.0
            if n in _ULP_FACTORS and np.array_equal(
                    np.isfinite(a), np.isfinite(b)) \
                    and d <= 16 * np.finfo(np.float32).eps * scale:
                continue
            bad.append(n)
    # single-device reference fold of the cross-day carry: the SAME
    # decoded days through stream/carry's span_prefix_state — pure
    # selections + integer counts, so equality must be bitwise
    bufs, sspec, _ = encode_year(batches, use_wire)
    if use_wire:
        dec = jax.jit(lambda b: wire.decode(*wire.unpack(b, sspec)))
    else:
        def _dec_raw(b):
            bars, m = wire.unpack(b, sspec)
            return bars, m.astype(bool)
        dec = jax.jit(_dec_raw)
    state = jax.device_put({**scarry.init_span_state(tickers),
                            "day": np.full(tickers, -1, np.int32)})
    fold = jax.jit(lambda s, b, n: scarry.combine_span_state(
        s, scarry.span_prefix_state(*dec(b), day_base=n * days)))
    for n_, b in enumerate(bufs):
        state = fold(state, jax.device_put(b), jnp.int32(n_))
    ref = jax.device_get(state)
    carry_ok = bool(
        carry is not None
        and np.array_equal(ref["n_bars"], carry["n_bars"])
        and np.array_equal(ref["has"], carry["has"])
        and np.array_equal(np.isnan(ref["last_close"]),
                           np.isnan(carry["last_close"]))
        and np.array_equal(ref["last_close"][ref["has"]],
                           carry["last_close"][carry["has"]]))
    groups = -(-n_batches // group)
    overlap_ok = groups < 2 or phases.get("ingest_hidden_s", 0) > 0
    mesh_block = get_telemetry().meshplane.summary()
    return {"smoke": "resident_2d", "mesh_shape": [d_sh, t_sh],
            "batches": n_batches, "factors": len(names),
            "encode_kind": kind, "scan_groups": groups,
            "syncs_1d": syncs_1d, "syncs_2d": syncs_2d,
            "carry_handoffs": handoffs, "carry_ok": carry_ok,
            "ingest_hidden_s": phases.get("ingest_hidden_s"),
            "pad_waste_by_axis": mesh_block.get(
                "pad_waste_frac_by_axis"),
            "mismatched": sorted(set(bad)), "max_abs_diff": max_diff,
            "ok": (not bad and overlap_ok and carry_ok
                   and handoffs > 0 and syncs_2d <= syncs_1d)}


def probe_latency(rng, n=3):
    """Per-transfer latency floor: tiny (4 KB) round trips each way,
    min over ``n`` samples, milliseconds. The r3 headline left
    ~4 s/batch unaccounted after bandwidth+compute; if this floor is
    seconds-scale the pipeline is dispatch-latency-bound and fewer,
    bigger batches are the fix — without it in the headline JSON that
    diagnosis was a guess (VERDICT r3 weak #2). Distinct bytes per put
    (same caching rationale as the link probe)."""
    tiny = rng.integers(0, 256, 4096, dtype=np.uint8)
    lat_put, lat_get = [], []
    for i in range(n):
        t0 = time.perf_counter()
        d = jax.device_put(tiny + np.uint8(i))
        jax.block_until_ready(d)
        lat_put.append(time.perf_counter() - t0)
        d2 = d + np.uint8(1)
        jax.block_until_ready(d2)
        t0 = time.perf_counter()
        np.asarray(d2)
        lat_get.append(time.perf_counter() - t0)
    return round(min(lat_put) * 1e3, 1), round(min(lat_get) * 1e3, 1)


def stale_tpu_headline(path=None):
    """Most recent hardened TPU headline banked by a capture session,
    for the CPU-fallback JSON (VERDICT r3 #3): three rounds of round-end
    artifacts carried only fallback numbers while the real TPU evidence
    sat in benchmarks/TPU_SESSION.json — surface it (clearly stamped as
    stale) so the round artifact is readable without spelunking."""
    if path is None:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "benchmarks", "TPU_SESSION.json")
    try:
        with open(path) as fh:
            step = json.load(fh).get("steps", {}).get("headline") or {}
        if not step.get("ok"):
            return None, None
        for rec in step.get("results") or []:
            if (isinstance(rec, dict)
                    and str(rec.get("metric", "")).startswith("cicc58")
                    and "_cpu_fallback" not in str(rec.get("metric"))):
                return rec, step.get("captured_utc")
    except (OSError, ValueError, AttributeError):
        pass
    return None, None


def probe_link(rng, nbytes=28_000_000):
    """One bandwidth sample each way, distinct bytes (see the caching
    note in main): host->device via device_put of ``nbytes``, then
    device->host sized like one batch's [F, D, T] result (~9.3 MB;
    2_325_000 u8 elements widened to f32), with a smaller warm read
    first so stream setup isn't counted as bandwidth."""
    buf = np.frombuffer(rng.bytes(nbytes), np.uint8)
    t0 = time.perf_counter()
    dev = jax.device_put(buf)
    jax.block_until_ready(dev)
    down = round(buf.nbytes / 1e6 / (time.perf_counter() - t0), 1)
    np.asarray(dev[:1_000_000].astype(np.float32))
    up = dev[:2_325_000].astype(np.float32) + np.float32(1)
    jax.block_until_ready(up)
    t0 = time.perf_counter()
    np.asarray(up)
    return down, round(up.size * 4 / 1e6 / (time.perf_counter() - t0), 1)


def measure_link(rng, threshold_mbps=20.0, threshold_up_mbps=10.0,
                 wait_budget_s=240.0, sleep_s=45.0):
    """Link probe with a bounded wait-for-weather loop.

    The tunnel's bandwidth swings >10x hour to hour. If the probe
    catches it badly degraded, wait (bounded) for a healthier window
    rather than recording link weather as the headline — the wait is
    reported in the bench JSON (``link_wait_s``), and the timed loop
    still pays whatever the link does while it runs. The budget bounds
    when a new iteration may START; the final iteration's sleep +
    reachability check + probe can run past it (on a degraded link,
    roughly one sleep + 90 s child timeout + one slow probe beyond)."""
    down, up = probe_link(rng)
    t_wait = time.monotonic()
    # budget check counts the upcoming sleep so the cap can't be
    # overshot by a whole iteration; retries reuse the full probe size
    # (a smaller payload amortizes fixed per-transfer overhead over
    # fewer bytes and would not be comparable with the first sample)
    # gate on BOTH legs: the headline result crosses device->host too,
    # and the up leg is the one observed degrading worst (3-9 MB/s while
    # down did 57 MB/s) — gating only on down would never trigger a wait
    # in exactly the documented bad-weather scenario (ADVICE r1)
    while ((down < threshold_mbps or up < threshold_up_mbps)
           and time.monotonic() - t_wait + sleep_s < wait_budget_s):
        time.sleep(sleep_s)
        # the tunnel can wedge outright while we wait; a wedged tunnel
        # hangs device_put forever, so re-check reachability from a
        # killable child before probing in-process again
        if not _tunnel_alive():
            break
        down, up = probe_link(rng)
    return down, up, round(time.monotonic() - t_wait, 1)


def _wait_host_quiet(max_wait_s=600.0):
    """Bounded wait for live CPU-busy sentinel holders (fuzz chunks, the
    test suite) to drain before timing anything: one CPU core, so a
    background sweep inflates the wall clock 5-20x. Chunked campaigns
    (tools/fuzz/run_refdiff_campaign.sh) drop the sentinel between
    chunks, so this normally returns within a few minutes."""
    try:
        from tools.cpu_busy import live_owners
    except ImportError:  # not running from a repo checkout
        return True
    me = os.getpid()
    t0 = time.monotonic()
    owners = [p for p in live_owners() if p != me]
    while owners and time.monotonic() - t0 < max_wait_s:
        print(f"# waiting for CPU-busy pids {owners} before timing",
              file=sys.stderr, flush=True)
        time.sleep(15)
        owners = [p for p in live_owners() if p != me]
    return not owners


# --------------------------------------------------------------------------
# serve mode (ISSUE 6): load-generate against the resident factor service
# --------------------------------------------------------------------------

#: serve-mode knobs (python bench.py serve). Defaults size for the TPU
#: session; the CPU smoke/demo passes small overrides.
SERVE_CLIENTS = os.environ.get("BENCH_SERVE_CLIENTS", "1,32,256")
SERVE_REQUESTS = int(os.environ.get("BENCH_SERVE_REQUESTS", "192"))
SERVE_TICKERS = int(os.environ.get("BENCH_SERVE_TICKERS", "1024"))
SERVE_DAYS = int(os.environ.get("BENCH_SERVE_DAYS", "32"))
SERVE_WINDOW_DAYS = int(os.environ.get("BENCH_SERVE_WINDOW_DAYS", "8"))
#: front-door transport for the serve load (ISSUE 20): ``inproc``
#: keeps the r8 in-process queue loop byte-for-byte; ``edge`` drives
#: keep-alive wire-encoded HTTP load through the evented selectors
#: front door (methodology ``r15_serve_edge_v1``); ``legacy`` drives
#: the SAME HTTP load through the stdlib thread-per-connection server
#: (``r15_serve_edge_v1+transport=legacy`` — the A/B leg).
SERVE_TRANSPORT = os.environ.get("BENCH_SERVE_TRANSPORT", "inproc")


def _http_wire_load(host, port, ranges, levels, total_requests,
                    stages, hbm=None, stage_tag="serve.load",
                    stage_prefix=""):
    """Per-level threaded keep-alive wire load against a bound front
    door (ISSUE 20). Each thread owns ONE persistent
    :class:`serve.WireClient` for its whole request cycle — keep-alive
    reuse IS the measured contract (the pre-ISSUE-20 comparison paid
    TCP connect + teardown per request) — and every request is a
    wire-encoded full-set factors query whose framed body is decoded
    by the first-party client, so bytes-on-wire per answer is counted
    at the consumer, not inferred from server counters.

    Returns ``(level_stats, wire_totals)`` where ``wire_totals`` is
    ``{"wire_answers", "wire_bytes", "http_failures"}`` summed over
    every level."""
    import threading as _th

    from replication_of_minute_frequency_factor_tpu.serve import (
        WireClient, decode_frames)
    from replication_of_minute_frequency_factor_tpu.serve.http import (
        WIRE_CONTENT_TYPE)

    level_stats = {}
    totals = {"wire_answers": 0, "wire_bytes": 0, "http_failures": 0}
    tot_lock = _th.Lock()
    for level in levels:
        lat_lock = _th.Lock()
        latencies = []
        n_threads = max(1, level)
        per_thread = max(1, total_requests // n_threads)

        def run_client(tid):
            # one persistent connection per simulated client; a
            # distinct tenant stripe keeps any configured quota from
            # funneling the whole fleet through one bucket
            cli = WireClient(host, port, timeout=600.0,
                             tenant=f"bench-{tid % 16}")
            mine = []
            body_bytes = answers = failures = 0
            try:
                for j in range(per_thread):
                    s, e = ranges[(tid + j) % len(ranges)]
                    t_req = time.perf_counter()
                    status, _hdrs, data = cli.post_json(
                        "/v1/query",
                        {"kind": "factors", "start": s, "end": e},
                        headers={"Accept": WIRE_CONTENT_TYPE})
                    if status != 200:
                        failures += 1
                        continue
                    decode_frames(data)
                    mine.append(time.perf_counter() - t_req)
                    body_bytes += len(data)
                    answers += 1
            finally:
                cli.close()
            with lat_lock:
                latencies.extend(mine)
            with tot_lock:
                totals["wire_bytes"] += body_bytes
                totals["wire_answers"] += answers
                totals["http_failures"] += failures

        t0 = time.perf_counter()
        threads = [_th.Thread(target=run_client, args=(i,))
                   for i in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = time.perf_counter() - t0
        lat = np.sort(np.asarray(latencies))
        level_stats[str(level)] = {
            "requests": len(lat),
            "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
            "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
            "qps": round(len(lat) / wall, 1),
        }
        stages[f"{stage_prefix}load_{level}_s"] = round(wall, 3)
        if hbm is not None:
            hbm.sample(f"{stage_tag}_{level}", force=True)
    return level_stats, totals


def serve_bench(levels=None, total_requests=None, tickers=None,
                days=None, window_days=None, names=None, telemetry=None,
                transport=None):
    """Load-generate against a :class:`serve.FactorServer` and return
    the serving record: per-concurrency-level p50/p99 latency + QPS,
    plus the serving counters the acceptance gate reads
    (exposure-cache hits, coalesced dispatches, and the compile count
    over the loaded window — ZERO compiles during load is the
    warm-executable contract).

    ``transport`` (default ``BENCH_SERVE_TRANSPORT``) picks the entry
    path (ISSUE 20):

      inproc — the r8 in-process queue loop, byte-for-byte
               (methodology ``r8_serve_v1``);
      edge   — keep-alive wire-encoded HTTP load through the evented
               selectors front door (``r15_serve_edge_v1``); the
               record additionally carries the ``edge`` block with
               client-side bytes-on-wire per answer and the JSON A/B
               ratio;
      legacy — the SAME HTTP wire load through the stdlib
               thread-per-connection server
               (``r15_serve_edge_v1+transport=legacy``) — the door A/B
               leg; per-answer bytes match edge (same payload), the
               latency/QPS columns are what differ.

    Three phases, each a ``stages`` column:

      coalesce — a paused-queue probe: K identical fresh-range queries
                 drain as ONE micro-batch, so exactly one device
                 dispatch answers all K (deterministic evidence; under
                 live load coalescing additionally happens whenever
                 concurrent clients land in one collection window);
      warm     — every (kind, factor, range) combo the load uses, once:
                 all compiles happen here (HTTP transports additionally
                 warm the full-set wire encode per range THROUGH the
                 bound door, and bank the JSON answer size for the A/B);
      load     — per level: N threads issuing the combo cycle,
                 per-request wall collected client-side.
    """
    import threading as _th

    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names as _fnames)
    from replication_of_minute_frequency_factor_tpu.serve import (
        FactorServer, Query, ServeConfig, SyntheticSource)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    transport = (transport or SERVE_TRANSPORT).strip() or "inproc"
    if transport not in ("inproc", "edge", "legacy"):
        raise ValueError(f"unknown serve transport {transport!r} "
                         "(inproc, edge or legacy)")

    levels = tuple(levels if levels is not None else
                   (int(s) for s in SERVE_CLIENTS.split(",") if s.strip()))
    total_requests = total_requests or SERVE_REQUESTS
    tickers = tickers or SERVE_TICKERS
    days = days or SERVE_DAYS
    window_days = window_days or SERVE_WINDOW_DAYS
    if names is None:
        factors_env = os.environ.get("BENCH_FACTORS")
        names = (tuple(s.strip() for s in factors_env.split(",")
                       if s.strip()) if factors_env else _fnames())
    names = tuple(names)
    tel = telemetry if telemetry is not None else set_telemetry(Telemetry())
    reg = tel.registry
    stages = {}

    source = SyntheticSource(n_days=days, n_tickers=tickers, seed=7)
    ranges = [(s, s + window_days)
              for s in range(0, days - window_days + 1, window_days)]
    # the query mix every phase shares: raw exposures + an IC and a
    # decile question per range, factors cycling through the set
    combos = []
    for i, r in enumerate(ranges):
        combos.append(Query("factors", *r,
                            names=(names[i % len(names)],)))
        combos.append(Query("ic", *r, factor=names[(i + 1) % len(names)]))
        combos.append(Query("decile", *r,
                            factor=names[(i + 2) % len(names)],
                            group_num=5))

    server = FactorServer(source, names=names, telemetry=tel,
                          serve_cfg=ServeConfig(), start=False)
    # --- coalesce probe: the queue drains K identical queries at once
    t0 = time.perf_counter()
    probe = [server.submit(Query("factors", *ranges[0],
                                 names=(names[0],)))
             for _ in range(8)]
    server.start()
    for f in probe:
        f.result(600)
    stages["coalesce_s"] = round(time.perf_counter() - t0, 3)
    # --- warm every combo the load will issue (all compiles land here)
    t0 = time.perf_counter()
    for q in combos:
        server.submit(q).result(600)
    stages["warm_s"] = round(time.perf_counter() - t0, 3)

    door = None
    wire_totals = None
    json_bytes_per_answer = None
    if transport != "inproc":
        from replication_of_minute_frequency_factor_tpu.serve import (
            WireClient)
        from replication_of_minute_frequency_factor_tpu.serve.http import (
            serve_frontdoor)
        t0 = time.perf_counter()
        door = serve_frontdoor(server, transport=transport)
        d_host, d_port = door.server_address[:2]
        # full-set wire warm THROUGH the door: the AOT pack compiles
        # once per block geometry here, and the buffered JSON answer
        # for the same query banks the A/B denominator
        cli = WireClient(d_host, d_port, timeout=600.0,
                         tenant="bench-warm")
        json_sizes = []
        for s, e in ranges:
            cli.query_wire(s, e)
            status, _hdrs, data = cli.post_json(
                "/v1/query", {"kind": "factors", "start": s, "end": e})
            if status == 200:
                json_sizes.append(len(data))
        cli.close()
        if json_sizes:
            json_bytes_per_answer = round(
                sum(json_sizes) / len(json_sizes), 1)
        stages["warm_wire_s"] = round(time.perf_counter() - t0, 3)

    compiles_before = reg.counter_total("xla.compiles")
    if transport != "inproc":
        d_host, d_port = door.server_address[:2]
        level_stats, wire_totals = _http_wire_load(
            d_host, d_port, ranges, levels, total_requests, stages,
            hbm=tel.hbm, stage_tag="serve.load")
        door.shutdown()
        if hasattr(door, "server_close"):
            door.server_close()
    else:
        level_stats = {}
        for level in levels:
            lat_lock = _th.Lock()
            latencies = []
            n_threads = max(1, level)
            per_thread = max(1, total_requests // n_threads)

            def run_client(tid):
                mine = []
                for j in range(per_thread):
                    q = combos[(tid + j) % len(combos)]
                    t_req = time.perf_counter()
                    server.submit(q).result(600)
                    mine.append(time.perf_counter() - t_req)
                with lat_lock:
                    latencies.extend(mine)

            t0 = time.perf_counter()
            threads = [_th.Thread(target=run_client, args=(i,))
                       for i in range(n_threads)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            wall = time.perf_counter() - t0
            lat = np.sort(np.asarray(latencies))
            level_stats[str(level)] = {
                "requests": len(lat),
                "p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 2),
                "p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 2),
                "qps": round(len(lat) / wall, 1),
            }
            stages[f"load_{level}_s"] = round(wall, 3)
            tel.hbm.sample(f"serve.load_{level}", force=True)
    # SLO block (ISSUE 16): one explicit frame before close so even the
    # shortest run banks a nonzero timeline, then the objective verdicts
    server.timeline.sample()
    slo_block = server.sloplane.summary()
    server.close()

    top = str(levels[-1])
    serve_counters = {
        "cache_hits": int(reg.counter_value("serve.cache",
                                            outcome="hit")),
        "cache_misses": int(reg.counter_value("serve.cache",
                                              outcome="miss")),
        "cache_evictions": int(reg.counter_total("serve.cache_evictions")),
        "dispatches": int(reg.counter_total("serve.dispatches")),
        "coalesced_dispatches": int(
            reg.counter_total("serve.coalesced_dispatches")),
        "coalesced_requests": int(
            reg.counter_total("serve.coalesced_requests")),
        "compiles_total": int(reg.counter_total("xla.compiles")),
        "compiles_during_load": int(reg.counter_total("xla.compiles")
                                    - compiles_before),
        "load_shed": int(reg.counter_total("serve.load_shed")),
        "failures": int(reg.counter_total("serve.failures")),
    }
    # DECLARED series (telemetry/regress.py): the HTTP front doors are
    # a new entry path AND a new answer encoding, so their records
    # start their own baselines — and the legacy thread-per-connection
    # door keys separately from the evented edge (the A/B must never
    # gate one against the other)
    methodology = {"inproc": "r8_serve_v1",
                   "edge": "r15_serve_edge_v1",
                   "legacy": "r15_serve_edge_v1+transport=legacy",
                   }[transport]
    edge_block = None
    if wire_totals is not None:
        wa = wire_totals["wire_answers"]
        wb = wire_totals["wire_bytes"]
        wbpa = round(wb / wa, 1) if wa else None
        edge_block = {
            # gates the regress-derived `<metric>.wire_bytes_per_answer`
            # sub-series: only a load that actually decoded wire
            # answers seeds or gates it
            "available": wa > 0,
            "transport": transport,
            "wire_answers": wa,
            "wire_bytes": wb,
            "wire_bytes_per_answer": wbpa,
            "json_bytes_per_answer": json_bytes_per_answer,
            # the ISSUE 20 acceptance ratio: JSON bytes-on-wire per
            # answer over wire bytes-on-wire per answer (>= 1.5 at the
            # top client level is the gate)
            "ab_ratio": (round(json_bytes_per_answer / wbpa, 2)
                         if wbpa and json_bytes_per_answer else None),
            "http_failures": wire_totals["http_failures"],
        }
        if transport == "edge":
            edge_block["conns_opened"] = int(
                reg.counter_total("edge.conns_opened"))
            edge_block["requests"] = int(
                reg.counter_total("edge.requests"))
            edge_block["quota_rejected"] = int(
                reg.counter_total("edge.quota_rejected"))
    record = {
        # metric name derives from the ACTUAL factor/ticker counts, like
        # the headline (a restricted smoke can never print under the
        # full-set name)
        "metric": f"serve{len(names)}_{tickers}tickers_qps" + _SUFFIX,
        "value": level_stats[top]["qps"],
        "unit": "req/s",
        "tickers": tickers,
        # market session discriminator (ISSUE 15): regress keys every
        # sub-series on it, so non-240 records start their own baseline
        "session": SESSION,
        "days": days,
        "window_days": window_days,
        "factors": len(names),
        # DECLARED series (telemetry/regress.py): the serving layer is a
        # new workload — p50/p99/QPS records start their own baseline;
        # the HTTP doors declare their own r15 series (see above)
        "methodology": methodology,
        # entry-path stamps (ISSUE 20): which door answered and how the
        # answers went over the wire — the record is self-describing
        # even before the methodology is consulted
        "transport": transport,
        "encoding": "wire" if transport != "inproc" else "json",
        "p50_ms": level_stats[top]["p50_ms"],
        "p99_ms": level_stats[top]["p99_ms"],
        "levels": level_stats,
        "serve": serve_counters,
        # HBM watermarks over the loaded window (ISSUE 8): real
        # memory_stats on accelerator backends, the live-arrays
        # estimate with the explicit `available: false` marker on CPU;
        # regress derives the `<metric>.hbm_peak_bytes` series from it
        "hbm": tel.hbm.summary(),
        # mesh-plane block (ISSUE 9): micro-batch fill at the serve
        # dispatch boundary rides the same summary shape as the
        # sharded records
        "mesh": tel.meshplane.summary(),
        # factor-health block (ISSUE 12): every block BUILD fed the
        # plane its fused [F, 9] sketch; IC queries fed realized-IC
        "factor_health": tel.factorplane.summary(),
        # SLO block (ISSUE 16): timeline frame count + per-objective
        # worst burn rate / alert count over the loaded window; the
        # tpu_session serve carry rule refuses a record without it and
        # regress derives the `<metric>.burn_rate_max` series from it
        "slo": slo_block,
        "stages": stages,
    }
    if edge_block is not None:
        # only HTTP-transport records carry the block — an r8 inproc
        # record keeps its banked shape byte-for-byte
        record["edge"] = edge_block
    return record


def serve_smoke():
    """run_tests.sh --quick smoke (and the CPU acceptance demo): a tiny
    serve_bench on CPU. ``ok`` iff the three acceptance signals hold —
    zero compiles during load (warm executables), >=1 coalesced
    multi-request dispatch, exposure-cache hits > 0 — and nothing
    failed or shed."""
    record = serve_bench(levels=(1, 8), total_requests=48, tickers=32,
                         days=16, window_days=4,
                         names=("vol_return1min", "mmt_am",
                                "liq_openvol"))
    s = record["serve"]
    return {
        "smoke": "serve",
        "compiles_during_load": s["compiles_during_load"],
        "coalesced_dispatches": s["coalesced_dispatches"],
        "cache_hits": s["cache_hits"],
        "failures": s["failures"] + s["load_shed"],
        "p50_ms": record["p50_ms"], "p99_ms": record["p99_ms"],
        "qps": record["value"], "methodology": record["methodology"],
        "ok": (s["compiles_during_load"] == 0
               and s["coalesced_dispatches"] >= 1
               and s["cache_hits"] > 0
               and s["failures"] == 0 and s["load_shed"] == 0),
    }


def edge_smoke():
    """run_tests.sh --quick smoke for the evented binary edge (ISSUE
    20): a tiny edge-transport serve_bench plus the edge-specific
    gates on a dedicated quota-configured server. ``ok`` iff

    * the HTTP wire answer decodes byte-identically to the in-process
      wire answer for the same range (one payload end to end — the
      dequantize twin gate rides tier-1 in tests/test_edge.py);
    * a chunked range answer reassembles byte-identically to the
      buffered one (>= 2 frames actually streamed);
    * quota exhaustion answers 429 WITH a Retry-After hint (the shed
      contract's mirror);
    * zero compiles during the HTTP load (the warm-executable
      contract survives the new front door);
    * the wire answer beats the JSON answer on bytes-on-wire
      (``ab_ratio`` >= 1.5 — the acceptance direction, smoke-sized).

    One JSON verdict line, like every other smoke."""
    from replication_of_minute_frequency_factor_tpu.serve import (
        FactorServer, SyntheticSource, ServeClient, ServeConfig,
        WireClient, WireError, serve_edge)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry)

    names = ("vol_return1min", "mmt_am", "liq_openvol")
    record = serve_bench(transport="edge", levels=(1, 8),
                         total_requests=48, tickers=32, days=16,
                         window_days=4, names=names)
    s = record["serve"]
    eb = record["edge"]

    # --- the edge-specific gates on a dedicated tiny server (fresh
    # telemetry, a deliberately exhaustible token bucket)
    tel = Telemetry()
    server = FactorServer(
        SyntheticSource(n_days=8, n_tickers=32, seed=7), names=names,
        telemetry=tel,
        serve_cfg=ServeConfig(tenant_quota_rps=1.0,
                              tenant_quota_burst=2.0))
    door = serve_edge(server)
    host, port = door.server_address[:2]
    cli = WireClient(host, port, tenant="smoke-a")
    try:
        http_out, http_meta = cli.query_wire(0, 8)
        inproc_out, _ = ServeClient(server).factors_wire(0, 8)
        byte_identical = (http_out.tobytes() == inproc_out.tobytes())
        chunked_out, chunked_meta = cli.query_wire(0, 8, chunk_days=2)
        chunk_ok = (chunked_meta["frames"] >= 2
                    and chunked_out.tobytes() == http_out.tobytes())
        # a second tenant owns a FRESH bucket: burst 2 admits two,
        # the third refuses with the backoff hint
        quota_cli = WireClient(host, port, tenant="smoke-quota")
        quota_429 = False
        retry_after = None
        try:
            for _ in range(4):
                quota_cli.query_wire(0, 8)
        except WireError as e:
            quota_429 = (e.status == 429)
            retry_after = e.retry_after
        finally:
            quota_cli.close()
    finally:
        cli.close()
        door.shutdown()
        server.close()
    ab_ratio = eb.get("ab_ratio") or 0.0
    return {
        "smoke": "edge",
        "byte_identical": byte_identical,
        "chunk_frames": chunked_meta["frames"],
        "chunk_ok": chunk_ok,
        "quota_429": quota_429,
        "quota_retry_after": retry_after,
        "compiles_during_load": s["compiles_during_load"],
        "wire_answers": eb["wire_answers"],
        "wire_bytes_per_answer": eb["wire_bytes_per_answer"],
        "json_bytes_per_answer": eb["json_bytes_per_answer"],
        "ab_ratio": ab_ratio,
        "http_failures": eb["http_failures"],
        "p50_ms": record["p50_ms"], "p99_ms": record["p99_ms"],
        "qps": record["value"], "methodology": record["methodology"],
        "ok": (byte_identical and chunk_ok
               and quota_429 and retry_after is not None
               and s["compiles_during_load"] == 0
               and eb["wire_answers"] > 0
               and eb["http_failures"] == 0
               and ab_ratio >= 1.5
               and s["failures"] == 0 and s["load_shed"] == 0),
    }


def serve_main():
    """``python bench.py serve`` — the serve-mode entry point. Tunnel
    handling mirrors the headline's CPU fallback, but preserves the
    ``serve`` argv (the headline's execve drops argv by design) and the
    metric suffix flips with it so a CPU number can never be read as a
    TPU one."""
    if "PALLAS_AXON_POOL_IPS" in os.environ and not _tunnel_alive():
        if os.environ.get("BENCH_REQUIRE_TPU"):
            print("# BENCH_REQUIRE_TPU set and tunnel unreachable; "
                  "aborting instead of CPU fallback", file=sys.stderr,
                  flush=True)
            return 17
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_METRIC_SUFFIX"] = "_cpu_fallback_tunnel_down"
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__), "serve"],
                  env)
    if os.environ.get("BENCH_REQUIRE_TPU") \
            and jax.devices()[0].platform == "cpu":
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        return 17
    _wait_host_quiet()
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    apply_compilation_cache(get_config())
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry, get_telemetry)
    set_telemetry(Telemetry())
    record = serve_bench(telemetry=get_telemetry())
    print(json.dumps(record))
    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if tdir:
        get_telemetry().write(tdir,
                              manifest_extra={"run_kind": "bench_serve"})
    return 0


# --------------------------------------------------------------------------
# fleet mode (ISSUE 11): N replicas behind the coalescing-affinity router
# --------------------------------------------------------------------------

#: fleet-mode knobs (python bench.py fleet). Client levels are the pod's
#: SIMULATED CONCURRENT CLIENTS; replica counts must each fit the
#: visible device count (disjoint submeshes) — counts that don't fit
#: are skipped and stamped, and the tpu_session carry refuses records
#: with fewer than 2 live replicas.
FLEET_CLIENTS = os.environ.get("BENCH_FLEET_CLIENTS", "64,512,2048")
FLEET_REPLICAS = os.environ.get("BENCH_FLEET_REPLICAS", "1,2,4")
FLEET_REQUESTS = int(os.environ.get("BENCH_FLEET_REQUESTS", "1024"))
FLEET_TICKERS = int(os.environ.get("BENCH_FLEET_TICKERS", "1024"))
FLEET_DAYS = int(os.environ.get("BENCH_FLEET_DAYS", "32"))
FLEET_WINDOW_DAYS = int(os.environ.get("BENCH_FLEET_WINDOW_DAYS", "8"))
#: pod front-door transport (ISSUE 20): ``inproc`` keeps the r11
#: router-queue loop byte-for-byte; ``edge`` binds the evented binary
#: edge on the POD front door and drives keep-alive wire-encoded HTTP
#: load through the router's replica leg (methodology
#: ``r15_fleet_edge_v1``); ``legacy`` the stdlib thread-per-connection
#: pod server (``r15_fleet_edge_v1+transport=legacy`` — the A/B leg).
FLEET_TRANSPORT = os.environ.get("BENCH_FLEET_TRANSPORT", "inproc")


def fleet_bench(replica_counts=None, levels=None, total_requests=None,
                tickers=None, days=None, window_days=None, names=None,
                transport=None):
    """Load-generate against a :class:`fleet.FactorFleet` per replica
    count and return the ``r11_fleet_v1`` record: per-replica-count
    p50/p99/QPS at every client level, the coalesce + affinity
    counters, the pod HBM block, and the pod-folded counter check
    (pod totals == Σ per-replica — the PR 9 merge contract,
    RE-VERIFIED per count, never assumed).

    Per replica count, four phases (each a ``stages`` column):

      coalesce — the paused-queue probe through the ROUTER: 8
                 same-range queries must land on ONE replica (affinity)
                 and drain as ONE coalesced dispatch there;
      warm     — every combo the load uses, once, through the router —
                 affinity pins each range's compile to its owner, so
                 all compiles land here and the load phase is warm on
                 every replica;
      load     — per client level: N threads issuing the combo cycle;
      bundle   — (top count only) per-replica telemetry bundles written
                 with the replica identity stamps, folded by the
                 telemetry.aggregate CLI path, and the pod bundle
                 re-validated — the fleet's bundles ARE multihost
                 bundles.
    """
    import tempfile
    import threading as _th

    from replication_of_minute_frequency_factor_tpu.fleet import (
        FactorFleet, FleetConfig)
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names as _fnames)
    from replication_of_minute_frequency_factor_tpu.serve import (
        Query, ServeConfig, SyntheticSource)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry)
    from replication_of_minute_frequency_factor_tpu.telemetry.aggregate \
        import aggregate_dirs
    from replication_of_minute_frequency_factor_tpu.telemetry.validate \
        import validate_dir

    transport = (transport or FLEET_TRANSPORT).strip() or "inproc"
    if transport not in ("inproc", "edge", "legacy"):
        raise ValueError(f"unknown fleet transport {transport!r} "
                         "(inproc, edge or legacy)")
    levels = tuple(levels if levels is not None else
                   (int(s) for s in FLEET_CLIENTS.split(",")
                    if s.strip()))
    counts = tuple(replica_counts if replica_counts is not None else
                   (int(s) for s in FLEET_REPLICAS.split(",")
                    if s.strip()))
    total_requests = total_requests or FLEET_REQUESTS
    tickers = tickers or FLEET_TICKERS
    days = days or FLEET_DAYS
    window_days = window_days or FLEET_WINDOW_DAYS
    if names is None:
        factors_env = os.environ.get("BENCH_FACTORS")
        names = (tuple(s.strip() for s in factors_env.split(",")
                       if s.strip()) if factors_env else _fnames())
    names = tuple(names)

    n_devices = len(jax.devices())
    runnable = [c for c in counts if c <= n_devices]
    skipped = [c for c in counts if c > n_devices]
    if skipped:
        print(f"# fleet: skipping replica counts {skipped} — only "
              f"{n_devices} device(s) visible (disjoint submeshes "
              "required)", file=sys.stderr, flush=True)
    if not runnable:
        raise RuntimeError(
            f"no requested replica count fits {n_devices} device(s)")

    source = SyntheticSource(n_days=days, n_tickers=tickers, seed=7)
    ranges = [(s, s + window_days)
              for s in range(0, days - window_days + 1, window_days)]
    combos = []
    for i, r in enumerate(ranges):
        combos.append(Query("factors", *r,
                            names=(names[i % len(names)],)))
        combos.append(Query("ic", *r, factor=names[(i + 1) % len(names)]))
        combos.append(Query("decile", *r,
                            factor=names[(i + 2) % len(names)],
                            group_num=5))

    stages = {}
    per_count = {}
    pod_block = None
    hbm_block = None
    fh_block = None
    slo_block = None
    edge_block = None

    for c in runnable:
        tel_pod = Telemetry()
        fleet = FactorFleet(source, c, names=names,
                            serve_cfg=ServeConfig(),
                            fleet_cfg=FleetConfig(),
                            start=False, telemetry=tel_pod)
        # --- coalesce probe: same-range queries through the router
        # land on ONE replica and drain as one dispatch there
        t0 = time.perf_counter()
        probe = [fleet.submit(Query("factors", *ranges[0],
                                    names=(names[0],)))
                 for _ in range(8)]
        fleet.start()
        for f in probe:
            f.result(600)
        stages[f"r{c}_coalesce_s"] = round(time.perf_counter() - t0, 3)
        # --- warm every combo (affinity pins each range's compiles)
        t0 = time.perf_counter()
        for q in combos:
            fleet.submit(q).result(600)
        stages[f"r{c}_warm_s"] = round(time.perf_counter() - t0, 3)

        door = None
        wire_totals = None
        json_sizes = []
        if transport != "inproc":
            from replication_of_minute_frequency_factor_tpu.fleet import (
                serve_fleet_frontdoor)
            from replication_of_minute_frequency_factor_tpu.serve import (
                WireClient)
            t0 = time.perf_counter()
            door = serve_fleet_frontdoor(fleet, transport=transport)
            d_host, d_port = door.server_address[:2]
            # full-set wire warm THROUGH the pod door: affinity pins
            # each range's AOT pack to its owner replica, and the JSON
            # answer size banks the A/B denominator
            cli = WireClient(d_host, d_port, timeout=600.0,
                             tenant="bench-warm")
            for ws, we in ranges:
                cli.query_wire(ws, we)
                status, _hdrs, data = cli.post_json(
                    "/v1/query",
                    {"kind": "factors", "start": ws, "end": we})
                if status == 200:
                    json_sizes.append(len(data))
            cli.close()
            stages[f"r{c}_warm_wire_s"] = round(
                time.perf_counter() - t0, 3)

        def pod_total(name):
            return (tel_pod.registry.counter_total(name)
                    + sum(r.telemetry.registry.counter_total(name)
                          for r in fleet.replicas))

        compiles_before = pod_total("xla.compiles")
        if transport != "inproc":
            level_stats, wire_totals = _http_wire_load(
                d_host, d_port, ranges, levels, total_requests, stages,
                hbm=tel_pod.hbm, stage_tag=f"fleet.load_r{c}",
                stage_prefix=f"r{c}_")
            door.shutdown()
            if hasattr(door, "server_close"):
                door.server_close()
        else:
            level_stats = {}
            for level in levels:
                lat_lock = _th.Lock()
                latencies = []
                n_threads = max(1, level)
                per_thread = max(1, total_requests // n_threads)

                def run_client(tid):
                    mine = []
                    for j in range(per_thread):
                        q = combos[(tid + j) % len(combos)]
                        t_req = time.perf_counter()
                        fleet.submit(q).result(600)
                        mine.append(time.perf_counter() - t_req)
                    with lat_lock:
                        latencies.extend(mine)

                t0 = time.perf_counter()
                threads = [_th.Thread(target=run_client, args=(i,))
                           for i in range(n_threads)]
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                wall = time.perf_counter() - t0
                lat = np.sort(np.asarray(latencies))
                level_stats[str(level)] = {
                    "requests": len(lat),
                    "p50_ms": round(
                        float(np.percentile(lat, 50)) * 1e3, 2),
                    "p99_ms": round(
                        float(np.percentile(lat, 99)) * 1e3, 2),
                    "qps": round(len(lat) / wall, 1),
                }
                stages[f"r{c}_load_{level}_s"] = round(wall, 3)
                tel_pod.hbm.sample(f"fleet.load_r{c}_{level}",
                                   force=True)

        # --- the pod fold, RE-VERIFIED: every merged counter equals
        # the control-plane + per-replica sum (the PR 9 contract)
        merged = fleet.pod_registry()
        snap = merged.snapshot()
        regs = ([tel_pod.registry]
                + [r.telemetry.registry for r in fleet.replicas])
        checked = mismatched = 0
        for key, total in snap["counters"].items():
            per = sum(reg.snapshot()["counters"].get(key, 0.0)
                      for reg in regs)
            checked += 1
            if abs(per - total) > 1e-9 * max(1.0, abs(total)):
                mismatched += 1
        preg = tel_pod.registry
        health = fleet.health()
        per_count[str(c)] = {
            "levels": level_stats,
            "live": health["pod"]["live"],
            "demoted": health["pod"]["demoted"],
            "per_replica_dispatches": {
                r.label: int(r.telemetry.registry.counter_total(
                    "serve.dispatches")) for r in fleet.replicas},
            "counters": {
                "compiles_during_load": int(pod_total("xla.compiles")
                                            - compiles_before),
                "compiles_total": int(pod_total("xla.compiles")),
                "coalesced_dispatches": int(
                    pod_total("serve.coalesced_dispatches")),
                "coalesced_requests": int(
                    pod_total("serve.coalesced_requests")),
                "cache_hits": int(sum(
                    r.telemetry.registry.counter_value(
                        "serve.cache", outcome="hit")
                    for r in fleet.replicas)),
                "routed": int(preg.counter_total("fleet.routed")),
                "affinity_hits": int(preg.counter_value(
                    "fleet.affinity", outcome="hit")),
                "affinity_misses": int(preg.counter_value(
                    "fleet.affinity", outcome="miss")),
                "reroutes": int(preg.counter_total("fleet.reroutes")),
                "load_shed": int(pod_total("serve.load_shed")
                                 + preg.counter_total("fleet.load_shed")),
                "failures": int(pod_total("serve.failures")),
            },
            "counter_totals": {"checked": checked,
                               "mismatched": mismatched},
        }
        if c == runnable[-1]:
            hbm_block = tel_pod.hbm.summary()
            # --- the bundle leg: the fleet's per-replica bundles fold
            # through the SAME aggregate path as multihost bundles and
            # the emitted pod bundle must re-validate
            t0 = time.perf_counter()
            try:
                with tempfile.TemporaryDirectory() as td:
                    dirs = []
                    for r in fleet.replicas:
                        d = os.path.join(td, r.label)
                        r.write_bundle(d)
                        dirs.append(d)
                    pod_dir = os.path.join(td, "pod")
                    agg = aggregate_dirs(dirs, pod_dir)
                    val = validate_dir(pod_dir)
                    bundle = {"ok": bool(agg["ok"] and val["ok"]),
                              "hosts": agg["hosts"],
                              "merged_counters": agg["merged_counters"],
                              "counter_totals": agg["counter_totals"],
                              "validate_ok": val["ok"]}
            except Exception as e:  # noqa: BLE001 — record the failure
                bundle = {"ok": False,
                          "error": f"{type(e).__name__}: {e}"}
            stages[f"r{c}_bundle_s"] = round(time.perf_counter() - t0, 3)
            pod_block = {
                "counter_totals": per_count[str(c)]["counter_totals"],
                "affinity_hits":
                    per_count[str(c)]["counters"]["affinity_hits"],
                "routed": per_count[str(c)]["counters"]["routed"],
                "bundle": bundle,
            }
            # pod factor-health rollup (ISSUE 12): worst-coverage
            # factor per replica + the stream cursor skew beside it —
            # read from the same healthz rollup the front door serves
            fh_block = health["pod"].get("factor_health")
            # pod SLO block (ISSUE 16): one explicit control-plane
            # frame so the shortest run banks a nonzero timeline, then
            # the pod objectives' verdicts
            fleet.timeline.sample()
            slo_block = fleet.sloplane.summary()
            # binary-edge block (ISSUE 20): client-side bytes-on-wire
            # at the top replica count, plus the router's evidence the
            # encoding rode the replica leg end to end
            if wire_totals is not None:
                wa = wire_totals["wire_answers"]
                wb = wire_totals["wire_bytes"]
                wbpa = round(wb / wa, 1) if wa else None
                jbpa = (round(sum(json_sizes) / len(json_sizes), 1)
                        if json_sizes else None)
                edge_block = {
                    "available": wa > 0,
                    "transport": transport,
                    "wire_answers": wa,
                    "wire_bytes": wb,
                    "wire_bytes_per_answer": wbpa,
                    "json_bytes_per_answer": jbpa,
                    "ab_ratio": (round(jbpa / wbpa, 2)
                                 if wbpa and jbpa else None),
                    "http_failures": wire_totals["http_failures"],
                    "routed_wire": int(
                        preg.counter_total("fleet.routed_wire")),
                }
                if transport == "edge":
                    edge_block["conns_opened"] = int(
                        preg.counter_total("edge.conns_opened"))
                    edge_block["quota_rejected"] = int(
                        preg.counter_total("edge.quota_rejected"))
        fleet.close()

    top = str(runnable[-1])
    top_level = str(levels[-1])
    record = {
        # metric name derives from the ACTUAL factor/ticker counts,
        # like every other mode (a restricted smoke can never print
        # under the full-set name)
        "metric": f"fleet{len(names)}_{tickers}tickers_qps" + _SUFFIX,
        "value": per_count[top]["levels"][top_level]["qps"],
        "unit": "req/s",
        "mode": "fleet",
        "tickers": tickers,
        "days": days,
        "window_days": window_days,
        "factors": len(names),
        "clients": list(levels),
        "replica_counts": runnable,
        "replica_counts_skipped": skipped,
        #: how many replicas actually served at the top count — the
        #: tpu_session carry refuses < 2 (a 1-replica record measures
        #: serve, not the fleet)
        "live_replicas": per_count[top]["live"],
        # DECLARED series (telemetry/regress.py): a new workload AND a
        # new topology — fleet records start their own baseline; the
        # pod HTTP doors declare their own r15 series, keyed apart so
        # the door A/B can never gate one leg against the other
        "methodology": {"inproc": "r11_fleet_v1",
                        "edge": "r15_fleet_edge_v1",
                        "legacy": "r15_fleet_edge_v1+transport=legacy",
                        }[transport],
        # entry-path stamps (ISSUE 20), same contract as serve records
        "transport": transport,
        "encoding": "wire" if transport != "inproc" else "json",
        "session": SESSION,
        "p50_ms": per_count[top]["levels"][top_level]["p50_ms"],
        "p99_ms": per_count[top]["levels"][top_level]["p99_ms"],
        "replicas": per_count,
        "pod": pod_block,
        "hbm": hbm_block,
        # per-replica factor health at the top count (ISSUE 12): the
        # pod healthz rollup's data-quality view, banked so a replica
        # whose factors degraded is visible in the trajectory
        "factor_health": fh_block,
        # pod SLO block (ISSUE 16): the control-plane burn-rate view at
        # the top count; the tpu_session fleet carry rule requires it
        "slo": slo_block,
        "stages": stages,
    }
    if edge_block is not None:
        # only HTTP-transport records carry the block — an r11 inproc
        # record keeps its banked shape byte-for-byte
        record["edge"] = edge_block
    return record


def fleet_smoke():
    """run_tests.sh --quick smoke (8 virtual CPU devices): a tiny
    2-replica fleet_bench. ``ok`` iff the pod served warm and as one —
    zero compiles during load, affinity hit-rate > 0, >= 1 coalesced
    dispatch, pod counter totals exactly the per-replica sums, the
    aggregated pod bundle schema-valid, both replicas live, nothing
    shed or failed."""
    record = fleet_bench(replica_counts=(2,), levels=(2, 8),
                         total_requests=48, tickers=32, days=8,
                         window_days=4,
                         names=("vol_return1min", "mmt_am",
                                "liq_openvol"))
    two = record["replicas"]["2"]
    pod = record["pod"]
    cnt = two["counters"]
    return {
        "smoke": "fleet",
        "replicas": 2,
        "live_replicas": record["live_replicas"],
        "compiles_during_load": cnt["compiles_during_load"],
        "affinity_hits": cnt["affinity_hits"],
        "coalesced_dispatches": cnt["coalesced_dispatches"],
        "counter_mismatched": two["counter_totals"]["mismatched"],
        "bundle_ok": pod["bundle"].get("ok", False),
        "failures": cnt["failures"] + cnt["load_shed"],
        "p50_ms": record["p50_ms"], "p99_ms": record["p99_ms"],
        "qps": record["value"], "methodology": record["methodology"],
        "ok": (record["live_replicas"] == 2
               and cnt["compiles_during_load"] == 0
               and cnt["affinity_hits"] > 0
               and cnt["coalesced_dispatches"] >= 1
               and two["counter_totals"]["mismatched"] == 0
               and pod["bundle"].get("ok") is True
               and cnt["failures"] == 0 and cnt["load_shed"] == 0),
    }


def fleet_main():
    """``python bench.py fleet`` — the fleet-mode entry point. Tunnel
    handling mirrors serve_main (argv preserved, metric suffix flips on
    the CPU fallback); additionally, a CPU resolution with fewer
    devices than the largest requested replica count self-heals onto an
    8-device virtual mesh (the same trick as __graft_entry__/conftest —
    disjoint submeshes need devices to partition)."""
    if "PALLAS_AXON_POOL_IPS" in os.environ and not _tunnel_alive():
        if os.environ.get("BENCH_REQUIRE_TPU"):
            print("# BENCH_REQUIRE_TPU set and tunnel unreachable; "
                  "aborting instead of CPU fallback", file=sys.stderr,
                  flush=True)
            return 17
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_METRIC_SUFFIX"] = "_cpu_fallback_tunnel_down"
        if "--xla_force_host_platform_device_count" \
                not in env.get("XLA_FLAGS", ""):
            env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                                + " --xla_force_host_platform_device_"
                                  "count=8").strip()
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__), "fleet"],
                  env)
    if os.environ.get("BENCH_REQUIRE_TPU") \
            and jax.devices()[0].platform == "cpu":
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        return 17
    counts = [int(s) for s in FLEET_REPLICAS.split(",") if s.strip()]
    if (jax.devices()[0].platform == "cpu"
            and len(jax.devices()) < max(counts)
            and "--xla_force_host_platform_device_count"
            not in os.environ.get("XLA_FLAGS", "")):
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_"
                              "count=8").strip()
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__), "fleet"],
                  env)
    _wait_host_quiet()
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    apply_compilation_cache(get_config())
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry, get_telemetry)
    set_telemetry(Telemetry())
    record = fleet_bench()
    print(json.dumps(record))
    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if tdir:
        get_telemetry().write(tdir,
                              manifest_extra={"run_kind": "bench_fleet"})
    return 0


# --------------------------------------------------------------------------
# stream mode (ISSUE 7): online intraday ingest against the streaming engine
# --------------------------------------------------------------------------

#: stream-mode knobs (python bench.py stream). Cohort sizes are the
#: TICKERS-PER-UPDATE ingest shapes (the live-feed path's executable
#: shapes); defaults size for the TPU session, the CPU smoke overrides.
STREAM_COHORTS = os.environ.get("BENCH_STREAM_COHORTS", "1,8,64")
STREAM_TICKERS = int(os.environ.get("BENCH_STREAM_TICKERS", "1024"))
#: per-cohort-level update budget: bounds the load phase's wall clock
#: independently of the universe size (1024 tickers at K=1 would
#: otherwise be 245k dispatches per streamed day)
STREAM_UPDATES = int(os.environ.get("BENCH_STREAM_UPDATES", "960"))
#: snapshot-per-bar profile (ISSUE 18): any non-empty/non-"0" value
#: flips ``python bench.py stream`` from the ingest-load record to the
#: per-bar finalize profile (``r14_stream_snapshot_v1``); the literal
#: values "exact"/"fast" additionally force that finalize impl,
#: anything else profiles the configured one (MFF_FINALIZE_IMPL)
STREAM_SNAPSHOT_PER_BAR = os.environ.get("BENCH_STREAM_SNAPSHOT_PER_BAR",
                                         "")
SNAPSHOT_TICKERS = int(os.environ.get("BENCH_SNAPSHOT_TICKERS", "64"))


def stream_bench(cohorts=None, tickers=None, updates=None, names=None,
                 telemetry=None, finalize_impl=None):
    """Ingest-load the online intraday engine (stream/) and return the
    ``r9_stream_intraday_v1`` record: bars/sec + per-update p50/p99
    latency at each cohort ingest shape, the streaming counters, and
    the parity + compile evidence the acceptance gate reads (streamed
    day == full-day batch exposures; ZERO compiles after warmup).

    Three phases, each a ``stages`` column:

      warm   — compile every executable the load shapes need (scan
               micro-batch, each cohort size, advance, snapshot);
      parity — one seeded synthetic day streamed through the scan path
               in 16-minute micro-batches, snapshot vs the jitted
               full-day batch graph (bitwise outside the documented
               ``_ULP_FACTORS`` pair — the same pin policy as the
               sharded smoke, and the bench-side twin of the tier-1
               tests/test_stream.py gate);
      load   — per cohort size K: minute-by-minute cohort ingest
               (K tickers per dispatch, cursor advance at each minute
               boundary), per-update wall collected host-side.

    ``finalize_impl`` threads to the engine (ISSUE 18; None adopts
    ``Config.finalize_impl``) and the record stamps the RESOLVED
    choice. Under a resolved 'fast' the parity phase swaps the bitwise
    gate for ``stream.fastpath.parity_report``'s three-class verdict:
    exact_fold/batch_only stay bitwise, stat_fold factors check
    against their pinned docs/PIN_BOUNDS.md envelopes.
    """
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        compute_factors_jit, factor_names as _fnames)
    from replication_of_minute_frequency_factor_tpu.stream.engine import (
        StreamEngine)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    cohorts = tuple(cohorts if cohorts is not None else
                    (int(s) for s in STREAM_COHORTS.split(",")
                     if s.strip()))
    tickers = tickers or STREAM_TICKERS
    updates = updates or STREAM_UPDATES
    if names is None:
        factors_env = os.environ.get("BENCH_FACTORS")
        names = (tuple(s.strip() for s in factors_env.split(",")
                       if s.strip()) if factors_env else _fnames())
    names = tuple(names)
    tel = telemetry if telemetry is not None else set_telemetry(Telemetry())
    reg = tel.registry
    stages = {}

    rng = np.random.default_rng(9)
    bars4, mask4 = make_batch(rng, n_days=1, n_tickers=tickers)
    day_bars, day_mask = bars4[0], mask4[0]     # [T, 240, 5], [T, 240]

    engine = StreamEngine(tickers, names=names, telemetry=tel,
                          finalize_impl=finalize_impl)
    # SLO plane (ISSUE 16): ingest-freshness objective sampled on the
    # timeline cadence while the bench runs — registry snapshots and
    # the engine's host-side ingest stamp only, never a device read
    from replication_of_minute_frequency_factor_tpu.telemetry.slo import (
        Objective)

    def _ingest_freshness(eng=engine):
        s = eng.staleness_s()
        return {} if s is None else {"stream.staleness_s": round(s, 6)}

    tel.timeline.add_source(_ingest_freshness)
    tel.sloplane.configure(
        (Objective(name="ingest_freshness", kind="freshness",
                   target=0.99, staleness_gauge="stream.staleness_s",
                   threshold_s=60.0),),
        timeline=tel.timeline)
    tel.timeline.start(0.05)
    # --- warm: all compiles land here (micro-batch scan, cohorts,
    # advance, snapshot)
    t0 = time.perf_counter()
    engine.warmup(micro_batches=(16,), cohorts=cohorts)
    stages["warm_s"] = round(time.perf_counter() - t0, 3)

    # --- parity: the streamed fold must reproduce the full-day batch
    # exposures on the SAME warmed executables
    t0 = time.perf_counter()
    for s in range(0, 240, 16):
        engine.ingest_minutes(
            np.ascontiguousarray(np.swapaxes(day_bars[:, s:s + 16], 0, 1)),
            np.ascontiguousarray(day_mask[:, s:s + 16].T))
    streamed, _ready = engine.snapshot()
    streamed = np.asarray(streamed)
    want = compute_factors_jit(day_bars, day_mask, names=names)
    mismatched = []
    if engine.finalize_impl_resolved == "fast":
        # three-class verdict (ISSUE 18): exact_fold/batch_only
        # bitwise, stat_fold within its pinned envelope — the bench
        # twin of the tier-1 fast-parity gate
        from replication_of_minute_frequency_factor_tpu.stream import (
            fastpath)
        for j, n in enumerate(names):
            if not fastpath.parity_report(
                    n, np.asarray(want[n]), streamed[j])["ok"]:
                mismatched.append(n)
        names_checked = ()
    else:
        names_checked = names
    for j, n in enumerate(names_checked):
        a, b = np.asarray(want[n]), streamed[j]
        if np.array_equal(a, b, equal_nan=True):
            continue
        f = np.isfinite(a) & np.isfinite(b)
        d = float(np.abs(a[f] - b[f]).max(initial=0.0))
        scale = float(np.abs(a[f]).max(initial=1.0)) or 1.0
        if n in _ULP_FACTORS and np.array_equal(
                np.isfinite(a), np.isfinite(b)) \
                and d <= 16 * np.finfo(np.float32).eps * scale:
            continue
        mismatched.append(n)
    stages["parity_s"] = round(time.perf_counter() - t0, 3)

    # --- load: per cohort shape, minute-by-minute live-feed ingest
    compiles_before = reg.counter_total("xla.compiles")
    level_stats = {}
    for k in cohorts:
        engine.reset()
        lat = []
        n_bars = 0
        done = False
        t0 = time.perf_counter()
        for t in range(240):
            if done:
                break
            for c0 in range(0, tickers, k):
                sel = np.arange(c0, min(c0 + k, tickers))
                present = day_mask[sel, t]
                idx = np.where(present, sel, tickers).astype(np.int32)
                rows = np.ascontiguousarray(day_bars[sel, t])
                if len(sel) < k:    # ragged tail: pad with dropped rows
                    pad = k - len(sel)
                    idx = np.concatenate(
                        [idx, np.full(pad, tickers, np.int32)])
                    rows = np.concatenate(
                        [rows, np.zeros((pad, 5), np.float32)])
                t_u = time.perf_counter()
                engine.ingest_cohort(rows, idx)
                lat.append(time.perf_counter() - t_u)
                n_bars += int(present.sum())
                if len(lat) >= updates:
                    done = True
                    break
            else:
                engine.advance()
        wall = time.perf_counter() - t0
        a = np.sort(np.asarray(lat))
        level_stats[str(k)] = {
            "updates": len(a),
            "p50_ms": round(float(np.percentile(a, 50)) * 1e3, 3),
            "p99_ms": round(float(np.percentile(a, 99)) * 1e3, 3),
            "bars_per_s": round(n_bars / wall, 1),
        }
        stages[f"load_{k}_s"] = round(wall, 3)
    # one warm snapshot after load: the intraday query the live feed
    # interleaves (also proves snapshot stayed compiled). The
    # stats-fused variant (ISSUE 12) is warmed alongside the plain one,
    # so this is still a zero-compile dispatch — and its [F, 9] sketch
    # + readiness plane feed the factor-health block below
    t0 = time.perf_counter()
    _exp, _ready, _stats = engine.snapshot_stats()
    stages["snapshot_s"] = round(time.perf_counter() - t0, 3)
    tel.factorplane.observe_stream(
        names, np.asarray(_stats),
        ready_frac=np.asarray(_ready).mean(axis=1),
        minute=engine.minutes, boundary="stream.snapshot")
    tel.hbm.sample("stream.load_end", force=True)
    tel.timeline.stop()
    tel.timeline.sample()   # one final explicit frame: frames > 0 even
    slo_block = tel.sloplane.summary()  # on the fastest machine

    top = str(cohorts[-1])
    stream_counters = {
        "updates": int(reg.counter_total("stream.updates")),
        "bars": int(reg.counter_total("stream.bars")),
        "snapshots": int(reg.counter_total("stream.snapshots")),
        "carry_bytes": int(reg.gauge_value("stream.carry_bytes")),
        "compiles_total": int(reg.counter_total("xla.compiles")),
        "compiles_during_load": int(reg.counter_total("xla.compiles")
                                    - compiles_before),
        "parity_checked": len(names),
        "parity_mismatched": sorted(mismatched),
    }
    return {
        # metric name derives from the ACTUAL factor/ticker counts, like
        # the headline (a restricted smoke can never print under the
        # full-set name)
        "metric": f"stream{len(names)}_{tickers}tickers_bars_per_s"
                  + _SUFFIX,
        "value": level_stats[top]["bars_per_s"],
        "unit": "bars/s",
        "tickers": tickers,
        # market session discriminator (ISSUE 15): regress keys every
        # sub-series on it, so non-240 records start their own baseline
        "session": SESSION,
        "factors": len(names),
        "cohorts": list(cohorts),
        # RESOLVED snapshot finalize impl (ISSUE 18): 'fast' only when
        # requested AND a foldable kernel is served
        "finalize_impl": engine.finalize_impl_resolved,
        # DECLARED series (telemetry/regress.py): per-bar intraday
        # ingest is a new workload — its records start their own
        # baseline
        "methodology": "r9_stream_intraday_v1",
        "p50_ms": level_stats[top]["p50_ms"],
        "p99_ms": level_stats[top]["p99_ms"],
        "levels": level_stats,
        "stream": stream_counters,
        # HBM watermarks (ISSUE 8) — same contract as the serve record
        "hbm": tel.hbm.summary(),
        # mesh-plane balance block (ISSUE 9): for the single-device
        # streaming carry this is cohort occupancy (real rows per
        # K-row scatter) with available=False until the carry itself
        # shards; tpu_session's stream carry rule requires the block
        "mesh": tel.meshplane.summary(),
        # factor-health block (ISSUE 12): the end-of-load fused
        # snapshot's per-factor stats + readiness lag; tpu_session's
        # stream_intraday carry rule requires an available block
        "factor_health": tel.factorplane.summary(),
        # SLO block (ISSUE 16): ingest-freshness burn over the load —
        # a live feed that goes stale mid-run shows up here, not in p99
        "slo": slo_block,
        "stages": stages,
    }


def stream_snapshot_bench(tickers=None, names=None, finalize_impl=None,
                          telemetry=None):
    """Snapshot-PER-BAR finalize profile (ISSUE 18): one warm
    ``snapshot()`` after every ingested minute of a seeded day, per-bar
    finalize latency collected host-side. Returns the
    ``r14_stream_snapshot_v1`` record: per-bar p50/p99 plus the
    last-quartile-of-day vs first-quartile-of-day flatness ratios —
    the exact finalize's O(prefix) batch graph grows through the day
    (ratio >> 1), the fast path's sufficient-statistic
    materialization must stay flat (the acceptance pin is p50 ratio
    <= 1.25 on the CPU instrument).

    The metric name embeds the RESOLVED finalize impl, so exact and
    fast profiles bank as separate series under the one DECLARED
    methodology; ``snapshot.available`` is true only for a warm run
    (zero compiles while profiling) with enough bars to quartile —
    regress's ``<metric>.snapshot_p99_flat_ratio`` sub-series gates on
    it (telemetry/regress.py).
    """
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names as _fnames)
    from replication_of_minute_frequency_factor_tpu.stream.engine import (
        StreamEngine)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    tickers = tickers or SNAPSHOT_TICKERS
    if names is None:
        factors_env = os.environ.get("BENCH_FACTORS")
        names = (tuple(s.strip() for s in factors_env.split(",")
                       if s.strip()) if factors_env else _fnames())
    names = tuple(names)
    tel = telemetry if telemetry is not None else set_telemetry(Telemetry())
    reg = tel.registry
    stages = {}

    rng = np.random.default_rng(9)
    bars4, mask4 = make_batch(rng, n_days=1, n_tickers=tickers)
    day_bars, day_mask = bars4[0], mask4[0]     # [T, S, 5], [T, S]
    n_slots = day_mask.shape[1]

    engine = StreamEngine(tickers, names=names, telemetry=tel,
                          finalize_impl=finalize_impl)
    impl = engine.finalize_impl_resolved
    t0 = time.perf_counter()
    engine.warmup(micro_batches=(1,))
    stages["warm_s"] = round(time.perf_counter() - t0, 3)

    compiles_before = reg.counter_total("xla.compiles")
    lat = np.empty(n_slots)
    t0 = time.perf_counter()
    for t in range(n_slots):
        engine.ingest_minutes(
            np.ascontiguousarray(day_bars[:, t:t + 1].swapaxes(0, 1)),
            np.ascontiguousarray(day_mask[:, t:t + 1].T))
        t_s = time.perf_counter()
        exp, _ready = engine.snapshot()
        np.asarray(exp)             # block: the latency IS the finalize
        lat[t] = time.perf_counter() - t_s
    stages["profile_s"] = round(time.perf_counter() - t0, 3)
    compiles_during = int(reg.counter_total("xla.compiles")
                          - compiles_before)

    q = n_slots // 4
    first, last = lat[:q], lat[n_slots - q:]

    def _ms(a, p):
        return round(float(np.percentile(a, p)) * 1e3, 4)

    def _ratio(p):
        lo = float(np.percentile(first, p))
        return round(float(np.percentile(last, p)) / lo, 4) if lo > 0 \
            else None

    snapshot_block = {
        "bars": n_slots,
        "p50_ms": _ms(lat, 50), "p99_ms": _ms(lat, 99),
        "first_quartile_p50_ms": _ms(first, 50),
        "last_quartile_p50_ms": _ms(last, 50),
        # flat-finalize evidence: last-quartile-of-day latency over
        # first-quartile-of-day, per percentile. ~1.0 = per-snapshot
        # work independent of the bar cursor; the exact impl's
        # O(prefix) growth shows up here long before it IS the wall
        "p50_flat_ratio": _ratio(50),
        "p99_flat_ratio": _ratio(99),
        "compiles_during_profile": compiles_during,
        # gates the derived regress sub-series: only a WARM profile
        # with enough bars to quartile measures finalize flatness (a
        # compiling run measures XLA)
        "available": compiles_during == 0 and q >= 4,
    }
    return {
        "metric": f"stream_snapshot{len(names)}_{tickers}tickers_"
                  f"{impl}_p50_ms" + _SUFFIX,
        "value": snapshot_block["p50_ms"],
        "unit": "ms",
        "tickers": tickers,
        "session": SESSION,
        "factors": len(names),
        "finalize_impl": impl,
        # DECLARED series (telemetry/regress.py): per-bar finalize
        # profiling is a new instrument — its records start their own
        # baseline, split per resolved impl by the metric name
        "methodology": "r14_stream_snapshot_v1",
        "p50_ms": snapshot_block["p50_ms"],
        "p99_ms": snapshot_block["p99_ms"],
        "snapshot": snapshot_block,
        "stream": {
            "snapshots": int(reg.counter_total(
                "stream.finalize_snapshots")),
            "fold_factors": int(reg.gauge_value(
                "stream.finalize_fold_factors")),
            "residual_factors": int(reg.gauge_value(
                "stream.finalize_residual_factors")),
        },
        "hbm": tel.hbm.summary(),
        "stages": stages,
    }


def _fast_fold_mix_bit_identity(tickers=16, minutes=24, k=8):
    """The fast path's statistic fold must be ingest-shape-blind
    (ISSUE 18): the same minutes fed (a) wholesale through the scan
    path and (b) as a cohort-scatter/advance + single-minute-scan MIX
    must land bit-identical statistic leaves AND a bit-identical fast
    snapshot — cohort and scan route through the one shared
    ``ops.incremental._fold_stats`` arithmetic, so any divergence is
    a real fold bug, not float noise."""
    from replication_of_minute_frequency_factor_tpu.stream.engine import (
        StreamEngine)

    names = ("vol_return1min", "mmt_am", "liq_openvol")
    rng = np.random.default_rng(5)
    bars4, mask4 = make_batch(rng, n_days=1, n_tickers=tickers)
    day_bars, day_mask = bars4[0], mask4[0]
    eng_scan = StreamEngine(tickers, names=names, finalize_impl="fast")
    eng_mix = StreamEngine(tickers, names=names, finalize_impl="fast")
    eng_scan.ingest_minutes(
        np.ascontiguousarray(day_bars[:, :minutes].swapaxes(0, 1)),
        np.ascontiguousarray(day_mask[:, :minutes].T))
    for t in range(minutes):
        if t % 2 == 0:      # cohort scatter in K-row slices + advance
            for c0 in range(0, tickers, k):
                sel = np.arange(c0, min(c0 + k, tickers))
                idx = np.where(day_mask[sel, t], sel,
                               tickers).astype(np.int32)
                eng_mix.ingest_cohort(
                    np.ascontiguousarray(day_bars[sel, t]), idx)
            eng_mix.advance()
        else:               # whole minute through the scan path
            eng_mix.ingest_minutes(
                np.ascontiguousarray(
                    day_bars[:, t:t + 1].swapaxes(0, 1)),
                np.ascontiguousarray(day_mask[:, t:t + 1].T))
    leaves_differ = sorted(
        key for key in eng_scan.carry["inc"]
        if not np.array_equal(np.asarray(eng_scan.carry["inc"][key]),
                              np.asarray(eng_mix.carry["inc"][key]),
                              equal_nan=True))
    snap_a = np.asarray(eng_scan.snapshot()[0])
    snap_b = np.asarray(eng_mix.snapshot()[0])
    return {
        "leaves_differ": leaves_differ,
        "snapshot_bitwise": bool(
            np.array_equal(snap_a, snap_b, equal_nan=True)),
    }


def stream_smoke():
    """run_tests.sh --quick smoke (and the CPU acceptance demo): a tiny
    stream_bench on CPU, run under BOTH finalize impls (ISSUE 18).
    ``ok`` iff the acceptance signals hold for each — zero compiles
    after warmup (warm executables across every ingest shape) and
    streamed-vs-full-day parity on the seeded day (bitwise for exact;
    the three-class verdict for fast) — and the fast path's statistic
    fold survives a cohort<->scan ingest mix bit-identically (the
    full-58 sweep lives in tier-1 tests/test_stream.py; this drives
    the same restricted family set as the serve smoke)."""
    impls = {}
    for impl in ("exact", "fast"):
        record = stream_bench(cohorts=(1, 8), tickers=32, updates=96,
                              names=("vol_return1min", "mmt_am",
                                     "liq_openvol"),
                              finalize_impl=impl)
        s = record["stream"]
        impls[impl] = {
            "finalize_impl_resolved": record["finalize_impl"],
            "compiles_during_load": s["compiles_during_load"],
            "parity_mismatched": s["parity_mismatched"],
            "updates": s["updates"],
            "bars": s["bars"],
            "p50_ms": record["p50_ms"], "p99_ms": record["p99_ms"],
            "bars_per_s": record["value"],
        }
    mix = _fast_fold_mix_bit_identity()
    record_ok = all(v["compiles_during_load"] == 0
                    and v["parity_mismatched"] == []
                    and v["updates"] > 0 and v["bars"] > 0
                    for v in impls.values())
    return {
        "smoke": "stream",
        "impls": impls,
        "fast_fold_mix": mix,
        # back-compat top-level fields read the exact run (the
        # pre-ISSUE-18 smoke shape)
        "compiles_during_load": impls["exact"]["compiles_during_load"],
        "parity_mismatched": impls["exact"]["parity_mismatched"],
        "updates": impls["exact"]["updates"],
        "bars": impls["exact"]["bars"],
        "p50_ms": impls["exact"]["p50_ms"],
        "p99_ms": impls["exact"]["p99_ms"],
        "bars_per_s": impls["exact"]["bars_per_s"],
        "methodology": "r9_stream_intraday_v1",
        "ok": (record_ok
               and impls["fast"]["finalize_impl_resolved"] == "fast"
               and mix["leaves_differ"] == []
               and mix["snapshot_bitwise"]),
    }


def stream_main():
    """``python bench.py stream`` — the intraday-stream entry point.
    Tunnel handling mirrors serve_main: preserve the ``stream`` argv
    through the CPU-fallback execve and flip the metric suffix so a CPU
    number can never be read as a TPU one. (BENCH_MODE=stream remains
    the UNRELATED r1-r4 per-batch year loop of the default entry
    point.)"""
    if "PALLAS_AXON_POOL_IPS" in os.environ and not _tunnel_alive():
        if os.environ.get("BENCH_REQUIRE_TPU"):
            print("# BENCH_REQUIRE_TPU set and tunnel unreachable; "
                  "aborting instead of CPU fallback", file=sys.stderr,
                  flush=True)
            return 17
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_METRIC_SUFFIX"] = "_cpu_fallback_tunnel_down"
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__), "stream"],
                  env)
    if os.environ.get("BENCH_REQUIRE_TPU") \
            and jax.devices()[0].platform == "cpu":
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        return 17
    _wait_host_quiet()
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    apply_compilation_cache(get_config())
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry, get_telemetry)
    set_telemetry(Telemetry())
    if STREAM_SNAPSHOT_PER_BAR and STREAM_SNAPSHOT_PER_BAR != "0":
        # snapshot-per-bar finalize profile (ISSUE 18): the run's ONE
        # banked record becomes the r14 flatness profile; a literal
        # "exact"/"fast" env value forces that impl, anything else
        # adopts Config.finalize_impl
        impl = (STREAM_SNAPSHOT_PER_BAR
                if STREAM_SNAPSHOT_PER_BAR in ("exact", "fast")
                else None)
        record = stream_snapshot_bench(finalize_impl=impl,
                                       telemetry=get_telemetry())
    else:
        record = stream_bench(telemetry=get_telemetry())
    print(json.dumps(record))
    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if tdir:
        get_telemetry().write(tdir,
                              manifest_extra={"run_kind": "bench_stream"})
    return 0


# --------------------------------------------------------------------------
# discover mode (ISSUE 14): the factor-discovery engine's candidates/sec
# --------------------------------------------------------------------------

#: discover-mode knobs (python bench.py discover): population levels
#: per generation, bounded generation count, and the day-slab shape
#: the fitness backtest runs over. The headline value is the TOP
#: level's candidates/sec.
DISCOVER_POP = os.environ.get("BENCH_DISCOVER_POP", "512,2048,8192")
DISCOVER_GENERATIONS = int(os.environ.get("BENCH_DISCOVER_GENERATIONS",
                                          "6"))
DISCOVER_DAYS = int(os.environ.get("BENCH_DISCOVER_DAYS", "16"))
DISCOVER_TICKERS = int(os.environ.get("BENCH_DISCOVER_TICKERS", "512"))


def discover_bench(pops=None, generations=None, days=None, tickers=None,
                   skeleton="default", seed=0, telemetry=None,
                   mesh=None):
    """Run the bounded evolutionary search at each population level
    and return the ``r13_discover_v1`` record: banked
    **candidates/sec** (population x generations / loop wall) plus
    per-generation p50/p99 and the MEASURED syncs-per-generation
    (the ``research.host_blocking_syncs`` counter delta over the loop
    divided by generations — the loop's contract is exactly 1) and
    the ``xla.compiles`` delta over the generation loop (contract: 0;
    warmup compiles before the loop).

    The population shards over a ``parallel.resident_mesh`` when more
    than one device is visible (``discover.n_shards`` in the record
    says what resolved); the only collective is the end-of-generation
    top-k gather, counted in
    ``mesh.collective_dispatches{label=discover_topk}``.
    """
    from replication_of_minute_frequency_factor_tpu import search
    from replication_of_minute_frequency_factor_tpu.research.evolve import (
        DiscoveryEngine)
    from replication_of_minute_frequency_factor_tpu.research.fitness import (
        host_forward_returns)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    pops = tuple(pops if pops is not None else
                 (int(s) for s in DISCOVER_POP.split(",") if s.strip()))
    generations = generations or DISCOVER_GENERATIONS
    days = days or DISCOVER_DAYS
    tickers = tickers or DISCOVER_TICKERS
    tel = telemetry if telemetry is not None else set_telemetry(Telemetry())
    reg = tel.registry

    if mesh is None and len(jax.devices()) > 1:
        from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
            resident_mesh)
        mesh = resident_mesh(len(jax.devices()))

    rng = np.random.default_rng(11)
    bars, mask = make_batch(rng, n_days=days, n_tickers=tickers)
    fwd_ret, fwd_valid = host_forward_returns(bars, mask, horizon=1)

    engine = DiscoveryEngine(skeleton=skeleton, telemetry=tel, mesh=mesh)
    data = engine.prepare(bars, mask, fwd_ret, fwd_valid)

    # SLO plane (ISSUE 16): discovery-progress freshness — the engine's
    # host-mirror gauges (generations done, candidates/sec, seconds
    # since the last completed generation) ride the timeline sampler,
    # and the objective burns when generations stop landing. Host-side
    # reads only; the loop's 1-sync/generation budget is untouched.
    from replication_of_minute_frequency_factor_tpu.telemetry.slo import (
        Objective)
    tel.timeline.add_source(engine.progress)
    tel.sloplane.configure(
        (Objective(name="discovery_progress", kind="freshness",
                   target=0.99, staleness_gauge="discover.stall_s",
                   threshold_s=120.0),),
        timeline=tel.timeline)
    tel.timeline.start(0.05)

    stages = {}
    level_stats = {}
    results = {}
    for pop in pops:
        t0 = time.perf_counter()
        engine.warmup(data, pop)  # compiles land OUTSIDE the loop
        stages[f"warm_{pop}_s"] = round(time.perf_counter() - t0, 3)
        t0 = time.perf_counter()
        res = engine.evolve(data, pop=pop, generations=generations,
                            rng=np.random.default_rng(seed))
        wall = time.perf_counter() - t0
        stages[f"evolve_{pop}_s"] = round(wall, 3)
        walls = np.sort(np.asarray(res.gen_walls_s))
        level_stats[str(pop)] = {
            "candidates_per_s": round(pop * generations / wall, 1),
            "gen_p50_ms": round(
                float(np.percentile(walls, 50)) * 1e3, 2),
            "gen_p99_ms": round(
                float(np.percentile(walls, 99)) * 1e3, 2),
            "syncs_per_generation": res.syncs_per_generation,
            "compiles_during_loop": res.compiles_during_loop,
            "best_fitness": round(res.fitness, 6),
            "occupancy": round(res.occupancy, 4),
        }
        results[pop] = res
        tel.hbm.sample(f"discover.load_{pop}", force=True)

    tel.timeline.stop()
    tel.timeline.sample()   # one final explicit frame: frames > 0 even
    slo_block = tel.sloplane.summary()  # on the fastest machine

    top = max(pops)
    top_res = results[top]
    top_stats = level_stats[str(top)]
    record = {
        # metric name derives from the ACTUAL search shape, like every
        # other mode (a restricted smoke can never print under the
        # full-shape name)
        "metric": (f"discover{len(engine.skeleton)}slot_"
                   f"{tickers}tickers_candidates_per_s" + _SUFFIX),
        "value": top_stats["candidates_per_s"],
        "unit": "candidates/s",
        "tickers": tickers,
        "days": days,
        "skeleton_slots": len(engine.skeleton),
        # DECLARED series (telemetry/regress.py): the discovery engine
        # is a new workload — candidates/sec records start their own
        # baseline (the r8/r9/r11 pattern)
        "methodology": "r13_discover_v1",
        "session": SESSION,
        "p50_ms": top_stats["gen_p50_ms"],
        "p99_ms": top_stats["gen_p99_ms"],
        "levels": level_stats,
        # the discovery block the tpu_session carry rule and the
        # regress `<metric>.candidates_per_s` sub-series read: zero
        # completed generations, any loop compile, or a sync budget
        # past 1/generation all refuse to bank
        "discover": {
            "population": top,
            "generations": top_res.generations,
            "candidates_per_s": top_stats["candidates_per_s"],
            "syncs_per_generation": top_stats["syncs_per_generation"],
            "compiles_during_loop": top_stats["compiles_during_loop"],
            "best_fitness": round(top_res.fitness, 6),
            "best_ic": round(top_res.mean_ic, 6),
            "best_rank_ic": round(top_res.mean_rank_ic, 6),
            "best_spread": round(top_res.spread, 8),
            "best_describe": search.describe(top_res.genome,
                                             engine.skeleton),
            "n_shards": engine.n_shards,
            "occupancy": top_stats["occupancy"],
            "collective_dispatches": int(reg.counter_value(
                "mesh.collective_dispatches", label="discover_topk")),
            "data_fingerprint": data.fingerprint,
        },
        "hbm": tel.hbm.summary(),
        "mesh": tel.meshplane.summary(),
        "factor_health": tel.factorplane.summary(),
        # SLO block (ISSUE 16): discovery-progress freshness burn —
        # a search whose generations stall reads as budget spend here
        "slo": slo_block,
        "stages": stages,
    }
    return record


def discover_smoke(pop=32, generations=3, days=4, tickers=24):
    """run_tests.sh --quick smoke (8 virtual CPU devices): the
    population-sharded generation graph vs the single-device one on a
    seeded population — finite-fitness COUNT and the device top-k
    selection set bitwise, the fitness moments ulp-pinned (different
    module shapes may fuse differently, the vol_upRatio class), zero
    compiles after warmup across the sharded loop, exactly 1 measured
    host-blocking sync per generation, and >= 1 top-k collective
    dispatch counted. One JSON verdict line, nonzero exit on drift."""
    from replication_of_minute_frequency_factor_tpu import search
    from replication_of_minute_frequency_factor_tpu.research import (
        fitness as rf)
    from replication_of_minute_frequency_factor_tpu.research.evolve import (
        DiscoveryEngine)
    from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
        resident_mesh)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    tel = set_telemetry(Telemetry())
    reg = tel.registry
    n_dev = len(jax.devices())
    rng = np.random.default_rng(1)
    bars, mask = make_batch(rng, n_days=days, n_tickers=tickers)
    fwd_ret, fwd_valid = rf.host_forward_returns(bars, mask, horizon=1)

    # --- fitness equality: one seeded population through BOTH layouts
    genomes = search.random_population(np.random.default_rng(5), pop)
    n_elite = 4
    s1, tv1, ti1 = rf.generation_fitness(
        genomes, bars, mask, fwd_ret, fwd_valid, chunk=pop // 2,
        n_elite=n_elite)
    mesh = resident_mesh(n_dev)
    from jax.sharding import NamedSharding, PartitionSpec as P
    pad = -pop % n_dev
    gp = (genomes if not pad else
          np.concatenate([genomes,
                          np.zeros((pad, genomes.shape[1]), np.int32)]))
    rep = NamedSharding(mesh, P())
    gd = jax.device_put(gp, NamedSharding(mesh, P("tickers", None)))
    ss, tvs, tis = rf.generation_fitness_sharded(
        gd, jax.device_put(bars, rep),
        jax.device_put(mask, rep), jax.device_put(fwd_ret, rep),
        jax.device_put(fwd_valid, rep), mesh=mesh,
        skeleton=search.DEFAULT_SKELETON, group_num=5,
        chunk=max(1, (pop + pad) // n_dev // 2), n_elite=n_elite,
        n_pop=pop)
    s1, ti1 = np.asarray(s1), np.asarray(ti1)
    ss, tis = np.asarray(ss)[:pop], np.asarray(tis)
    finite_match = bool(np.array_equal(np.isfinite(s1),
                                       np.isfinite(ss)))
    topk_match = bool(set(ti1.tolist()) == set(tis.tolist()))
    scale = np.maximum(np.abs(np.nan_to_num(s1)), 1e-6)
    max_ulp = float(np.nanmax(
        np.abs(np.nan_to_num(s1) - np.nan_to_num(ss))
        / (np.finfo(np.float32).eps * scale)))

    # --- the sharded loop's measured contract on the same data
    sharded = DiscoveryEngine(telemetry=tel, mesh=mesh)
    single = DiscoveryEngine(telemetry=tel)
    data_s = sharded.prepare(bars, mask, fwd_ret, fwd_valid)
    data_1 = single.prepare(bars, mask, fwd_ret, fwd_valid)
    sharded.warmup(data_s, pop)
    single.warmup(data_1, pop)
    compiles_before = reg.counter_total("xla.compiles")
    res_s = sharded.evolve(data_s, pop=pop, generations=generations,
                           rng=np.random.default_rng(3))
    res_1 = single.evolve(data_1, pop=pop, generations=generations,
                          rng=np.random.default_rng(3))
    compiles_during = int(reg.counter_total("xla.compiles")
                          - compiles_before)
    same_genome = bool(np.array_equal(res_s.genome, res_1.genome))
    collectives = int(reg.counter_value("mesh.collective_dispatches",
                                        label="discover_topk"))
    ok = (finite_match and topk_match and max_ulp <= 16.0
          and same_genome
          and res_s.syncs_per_generation == 1.0
          and res_s.compiles_during_loop == 0
          and compiles_during == 0
          and res_s.n_shards == n_dev and n_dev > 1
          and collectives >= 1)
    return {
        "smoke": "discover",
        "n_devices": n_dev,
        "n_shards": res_s.n_shards,
        "finite_count_match": finite_match,
        "topk_set_match": topk_match,
        "fitness_max_ulp": round(max_ulp, 3),
        "same_best_genome": same_genome,
        "syncs_per_generation": res_s.syncs_per_generation,
        "compiles_during_loop": compiles_during,
        "topk_collective_dispatches": collectives,
        "ok": bool(ok),
    }


def discover_main():
    """``python bench.py discover`` — the discovery-mode entry point.
    Tunnel handling mirrors serve_main/stream_main: preserve the
    ``discover`` argv through the CPU-fallback execve and flip the
    metric suffix so a CPU candidates/sec can never be read as a TPU
    one."""
    if "PALLAS_AXON_POOL_IPS" in os.environ and not _tunnel_alive():
        if os.environ.get("BENCH_REQUIRE_TPU"):
            print("# BENCH_REQUIRE_TPU set and tunnel unreachable; "
                  "aborting instead of CPU fallback", file=sys.stderr,
                  flush=True)
            return 17
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env["JAX_PLATFORMS"] = "cpu"
        env["BENCH_METRIC_SUFFIX"] = "_cpu_fallback_tunnel_down"
        os.execve(sys.executable,
                  [sys.executable, os.path.abspath(__file__),
                   "discover"], env)
    if os.environ.get("BENCH_REQUIRE_TPU") \
            and jax.devices()[0].platform == "cpu":
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        return 17
    _wait_host_quiet()
    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    apply_compilation_cache(get_config())
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry, get_telemetry)
    set_telemetry(Telemetry())
    record = discover_bench(telemetry=get_telemetry())
    print(json.dumps(record))
    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if tdir:
        get_telemetry().write(
            tdir, manifest_extra={"run_kind": "bench_discover"})
    return 0


# --------------------------------------------------------------------------
# ops-plane smoke (ISSUE 8): tracing + flight recorder + watermarks +
# Prometheus, end to end
# --------------------------------------------------------------------------


def opsplane_smoke():
    """run_tests.sh --quick smoke: the live ops plane end to end on
    CPU. Starts a streaming FactorServer with its HTTP binding, drives
    mixed ingest+query load (one request with a propagated
    ``X-Trace-Id``), scrapes the Prometheus exposition, trips the
    breaker, and checks that:

      * the propagated trace ID round-trips (response header == body)
        and the written bundle holds a schema-v2 ``request`` record
        reconstructing that request's lifecycle (queue-wait, dispatch
        id, group size, device-time share, total);
      * the Prometheus text scrape carries the serving counters AND
        the ``device_hbm_*`` watermark gauges with the explicit
        availability marker;
      * tripping the breaker writes a flight-recorder dump that
        ``telemetry.validate`` accepts, holding the failed requests;
      * ``POST /v1/debug/dump`` captures on demand and validates;
      * the full bundle schema-validates (v2, request records included).
    """
    import tempfile
    import threading as _th
    import urllib.request

    from replication_of_minute_frequency_factor_tpu.serve import (
        FactorServer, Query, ServeConfig, SyntheticSource, serve_http)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)
    from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
        validate_dir, validate_dump)

    names = ("vol_return1min", "mmt_am")
    tel = set_telemetry(Telemetry())
    tmp = tempfile.mkdtemp(prefix="mff_opsplane_")
    src = SyntheticSource(n_days=8, n_tickers=16, seed=11)
    server = FactorServer(
        src, names=names, telemetry=tel,
        serve_cfg=ServeConfig(flight_dir=tmp, breaker_threshold=2,
                              breaker_cooldown_s=30.0),
        stream=True, stream_batches=(4,))
    httpd, _t = serve_http(server)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    checks = {}

    def post(path, doc, headers=None):
        req = urllib.request.Request(
            base + path, data=json.dumps(doc).encode(),
            headers=headers or {})
        with urllib.request.urlopen(req, timeout=300) as resp:
            return resp.status, dict(resp.headers), \
                json.loads(resp.read())

    try:
        # mixed load: a burst of concurrent queries (coalescing) + one
        # streaming ingest, all through HTTP
        bars, mask = src.slab(0, 1)
        ing_bars = np.ascontiguousarray(
            np.swapaxes(bars[0][:, :4], 0, 1))
        ing_present = np.ascontiguousarray(mask[0][:, :4].T)
        threads = [_th.Thread(target=post, args=(
            "/v1/query", {"kind": "factors", "start": 0, "end": 4}))
            for _ in range(6)]
        threads.append(_th.Thread(target=post, args=(
            "/v1/ingest", {"bars": ing_bars.tolist(),
                           "present": ing_present.tolist()})))
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        status, headers, body = post(
            "/v1/query", {"kind": "factors", "start": 0, "end": 4},
            headers={"X-Trace-Id": "smoke-trace-1"})
        checks["trace_round_trip"] = (
            status == 200
            and headers.get("X-Trace-Id") == "smoke-trace-1"
            and body.get("trace_id") == "smoke-trace-1")
        _, _, snap = post("/v1/query", {"kind": "intraday"})
        checks["intraday_minute"] = snap.get("minute") == 4
        # Prometheus scrape (content-negotiated text exposition)
        req = urllib.request.Request(base + "/v1/metrics",
                                     headers={"Accept": "text/plain"})
        with urllib.request.urlopen(req, timeout=60) as resp:
            prom = resp.read().decode()
            ct = resp.headers.get("Content-Type", "")
        checks["prometheus"] = (
            "text/plain" in ct
            and "serve_requests_total" in prom
            and "device_hbm_bytes_in_use" in prom
            and "device_hbm_stats_available" in prom)
        with urllib.request.urlopen(base + "/healthz",
                                    timeout=60) as resp:
            h = json.loads(resp.read())
        checks["healthz"] = all(
            k in h for k in ("uptime_s", "queue_depth", "flight",
                             "hbm_available", "stream_minute"))
        # on-demand capture
        req = urllib.request.Request(base + "/v1/debug/dump", data=b"")
        with urllib.request.urlopen(req, timeout=60) as resp:
            dump = json.loads(resp.read())
        checks["debug_dump_valid"] = validate_dump(dump["path"])["ok"]
        # trip the breaker on a fresh range: the anomaly dump must
        # fire and validate, holding the failed requests' traces
        def _boom(bars, mask):
            raise RuntimeError("injected opsplane smoke failure")
        server.engine.build_block = _boom
        for _ in range(2):
            try:
                server.submit(Query("factors", 4, 8)).result(120)
            except RuntimeError:
                pass
        # the future fails before the worker writes the dump — poll
        # briefly for the file instead of racing the worker thread
        trip_dumps = []
        deadline = time.monotonic() + 10.0
        while not trip_dumps and time.monotonic() < deadline:
            trip_dumps = [p for p in server.flight.dumps
                          if "breaker_trip" in p]
            if not trip_dumps:
                time.sleep(0.05)
        checks["breaker_dump_valid"] = (
            bool(trip_dumps) and validate_dump(trip_dumps[-1])["ok"])
        checks["dump_holds_errors"] = False
        if trip_dumps:
            with open(trip_dumps[-1]) as fh:
                recs = [json.loads(ln) for ln in fh if ln.strip()]
            checks["dump_holds_errors"] = any(
                r.get("kind") == "request"
                and r.get("status") == "error" for r in recs)
    finally:
        httpd.shutdown()
        server.close()
    bundle = os.path.join(tmp, "bundle")
    tel.write(bundle)
    checks["bundle_valid"] = validate_dir(bundle)["ok"]
    # lifecycle reconstruction: the traced request, back out of the
    # bundle with its full admission→answer story
    rec = None
    with open(os.path.join(bundle, "metrics.jsonl")) as fh:
        for line in fh:
            r = json.loads(line)
            if r.get("kind") == "request" \
                    and r.get("trace_id") == "smoke-trace-1":
                rec = r
    d = (rec or {}).get("data") or {}
    checks["lifecycle_reconstructed"] = (
        rec is not None and rec.get("status") == "ok"
        and all(k in d for k in ("queue_wait_s", "dispatch_id",
                                 "group_size", "device_share_s",
                                 "answer_s", "total_s")))
    gauges = tel.registry.snapshot()["gauges"]
    checks["hbm_gauges"] = (
        any(k.startswith("device.hbm_bytes_in_use") for k in gauges)
        and any(k.startswith("device.hbm_peak_bytes") for k in gauges)
        and any(k.startswith("device.hbm_stats_available")
                for k in gauges))
    return {"smoke": "opsplane", **checks,
            "ok": all(checks.values())}


def slo_smoke():
    """run_tests.sh --quick smoke: the SLO plane end to end on CPU
    (ISSUE 16). Starts a streaming FactorServer with the timeline
    sampler at a 20 ms cadence and ``slo_time_scale=3600`` (the SRE
    5m/1h fast pair compressed to 83 ms/1 s of test time), then:

      * drives warm synthetic load with propagated trace IDs and
        checks frames accrue — and that a pure-sampling interval moves
        ZERO device-work counters (``xla.compiles``,
        ``research.host_blocking_syncs``): the sampler is host-side by
        construction, counter-asserted here;
      * forces the breaker open and keeps submitting — REAL
        ``LoadShedError`` sheds, real ``serve.load_shed`` increments —
        until the availability burn-rate alert fires and force-dumps
        the flight recorder with trigger ``slo_burn``;
      * validates the dump (schema + header) and that its ``extra``
        names the objective, the burn rate, and the top-moving series;
      * writes the bundle INTO the flight dir (one incident-replay
        root), re-validates it at schema v4 (frame + slo records
        included), and replays it through the actual
        ``telemetry.timeline`` CLI — the report must reconstruct the
        incident offline: frames spanning the alert window and request
        traces cross-linked by trace ID.
    """
    import contextlib
    import io
    import tempfile

    from replication_of_minute_frequency_factor_tpu.serve import (
        FactorServer, Query, ServeConfig, SyntheticSource)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        timeline as _tl)
    from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
        validate_dir, validate_dump)

    names = ("vol_return1min", "mmt_am")
    tel = set_telemetry(Telemetry())
    tmp = tempfile.mkdtemp(prefix="mff_slo_")
    src = SyntheticSource(n_days=8, n_tickers=16, seed=11)
    # slo_latency_ms is lifted out of the way: cold CPU dispatches
    # would trip the latency objective and muddy the one injected
    # incident this smoke asserts on (availability)
    server = FactorServer(
        src, names=names, telemetry=tel,
        serve_cfg=ServeConfig(flight_dir=tmp,
                              timeline_sample_period_s=0.02,
                              slo_time_scale=3600.0,
                              slo_latency_ms=10_000.0),
        stream=True, stream_batches=(4,))
    checks = {}
    reg = tel.registry
    try:
        # warm load with trace IDs: the good half of the incident story
        for i in range(8):
            server.submit(Query("factors", 0, 4),
                          trace_id=f"slo-trace-{i}").result(300)
        # pure-sampling interval: frames accrue, device counters don't
        frames0 = len(server.timeline)
        compiles0 = reg.counter_total("xla.compiles")
        syncs0 = reg.counter_total("research.host_blocking_syncs")
        deadline = time.monotonic() + 5.0
        while len(server.timeline) < frames0 + 5 \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        checks["frames_sampled"] = len(server.timeline) >= frames0 + 5
        checks["sampling_pure_host"] = (
            reg.counter_total("xla.compiles") == compiles0
            and reg.counter_total("research.host_blocking_syncs")
            == syncs0)
        # injected shed burst: force the breaker open and keep
        # submitting until the multi-window availability burn fires
        with server._state_lock:
            server._open_until = time.monotonic() + 30.0
        shed = 0
        alert_dumps = []
        deadline = time.monotonic() + 10.0
        while not alert_dumps and time.monotonic() < deadline:
            try:
                server.submit(Query("factors", 0, 4),
                              trace_id=f"slo-shed-{shed}")
            except Exception:  # noqa: BLE001 — the shed IS the load
                shed += 1
            alert_dumps = [p for p in server.flight.dumps
                           if "slo_burn" in p]
            time.sleep(0.01)
        checks["burn_alert_fired"] = bool(alert_dumps)
        checks["real_sheds"] = shed > 0 and int(
            reg.counter_value("serve.load_shed", reason="breaker")) > 0
        if alert_dumps:
            checks["dump_valid"] = validate_dump(alert_dumps[-1])["ok"]
            header = next(
                (r for r in _tl._load_jsonl(alert_dumps[-1])
                 if r.get("kind") == "dump"), {})
            extra = (header.get("data") or {}).get("extra") or {}
            checks["dump_names_incident"] = (
                extra.get("event") == "alert"
                and extra.get("objective") == "availability"
                and float(extra.get("burn_rate") or 0.0) > 0.0
                and bool(extra.get("top_moving")))
        else:
            checks["dump_valid"] = False
            checks["dump_names_incident"] = False
        s = server.sloplane.summary()
        checks["slo_summary"] = (
            s["available"] and s["frames"] > 0
            and s["objectives"]["availability"]["alerts"] >= 1
            and s["worst_burn_rate"] > 1.0)
        h = server.health()
        checks["healthz_slo"] = (
            "suppressed" in h.get("flight", {})
            and "stream_staleness_s" in h)
    finally:
        server.close()
    # one incident-replay root: the bundle lands NEXT TO the dumps
    tel.write(tmp)
    checks["bundle_valid"] = validate_dir(tmp)["ok"]
    # offline replay through the ACTUAL CLI (stdout captured: the
    # harness reads this smoke's own verdict line)
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = _tl.main([tmp, "--require-incident"])
    try:
        report = json.loads(buf.getvalue().strip().splitlines()[-1])
    except (ValueError, IndexError):
        report = {}
    inc = [i for i in report.get("incidents", [])
           if i.get("objective") == "availability"]
    checks["cli_replays_incident"] = (
        rc == 0 and report.get("ok") is True and bool(inc)
        and inc[-1]["frames_in_window"] > 0
        and inc[-1]["requests"]["linked"] > 0)
    return {"smoke": "slo", **checks,
            "frames": report.get("frames", 0),
            "incidents": len(report.get("incidents", [])),
            "sheds": shed,
            "ok": all(checks.values())}


# --------------------------------------------------------------------------
# meshplane smoke (ISSUE 9): shard-balance gauges + skew-burst flight
# dump + multihost aggregation, end to end on the virtual mesh
# --------------------------------------------------------------------------


def meshplane_smoke():
    """run_tests.sh --quick smoke: the mesh observability plane end to
    end on 8 virtual CPU devices. Runs a sharded resident group run
    and checks that:

      * every shard of the mesh has a nonzero ``mesh.shard_time_s``
        gauge and the record-level ``mesh`` block carries a computed
        ``shard_skew_ratio`` + the lcm-padding ``pad_waste_frac``;
      * an injected artificial straggler trips a skew-burst flight
        dump that ``telemetry.validate`` accepts and whose header
        names the slow shard;
      * two synthetic per-host bundles (distinct ``process_index``
        stamps) merge through the ``telemetry.aggregate`` CLI into one
        schema-valid pod bundle whose counter totals equal the
        per-host sums.
    """
    import tempfile

    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        aggregate as _agg)
    from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
        validate_dir, validate_dump)

    tel = set_telemetry(Telemetry())
    tmp = tempfile.mkdtemp(prefix="mff_meshplane_")
    checks = {}

    # --- sharded resident group run on every visible device
    rng = np.random.default_rng(13)
    names = ("vol_return1min", "mmt_am", "doc_pdf60")
    batches = [make_batch(rng, n_days=2, n_tickers=32) for _ in range(2)]
    use_wire = wire.encode(*batches[0]) is not None
    mesh = resident_mesh()
    n_shards = mesh.devices.size
    run_resident_sharded(batches, names, use_wire, group=1, mesh=mesh)
    summary = tel.meshplane.summary()
    gauges = tel.registry.snapshot()["gauges"]
    shard_gauges = {k: v for k, v in gauges.items()
                    if k.startswith("mesh.shard_time_s")}
    checks["per_shard_gauges"] = (
        len(shard_gauges) == n_shards > 1
        and all(v > 0 for v in shard_gauges.values()))
    checks["skew_computed"] = (
        summary["available"]
        and isinstance(summary["shard_skew_ratio"], float)
        and summary["shard_skew_ratio"] >= 1.0
        and summary["samples"] >= 2)  # one per scan group
    checks["pad_waste"] = isinstance(summary["pad_waste_frac"], float)

    # --- injected straggler -> skew-burst dump that names the shard
    tel.meshplane.configure(dump_dir=tmp)
    slow = {f"cpu:{i}": 0.01 for i in range(n_shards)}
    slow[f"cpu:{n_shards - 1}"] = 0.5
    dump_path = None
    for _ in range(tel.meshplane.burst):
        r = tel.meshplane.record_shard_times(slow, boundary="injected")
        dump_path = r.get("burst_dump") or dump_path
    named = False
    if dump_path:
        with open(dump_path) as fh:
            recs = [json.loads(ln) for ln in fh if ln.strip()]
        named = any(
            rec.get("kind") == "dump"
            and (rec["data"].get("extra") or {}).get("slow_shard")
            == f"cpu:{n_shards - 1}" for rec in recs)
    checks["skew_burst_dump_valid"] = (
        bool(dump_path) and validate_dump(dump_path)["ok"])
    checks["skew_burst_names_slow_shard"] = named

    # --- two synthetic host bundles -> one pod bundle via the CLI
    host_dirs = []
    for i, n_req in enumerate((3, 5)):
        ht = Telemetry(annotate_spans=False)
        ht.counter("pod.requests", n_req)
        ht.observe("pod.latency_s", 0.01 * (i + 1))
        with ht.tracer("pod.step"):
            pass
        d = os.path.join(tmp, f"host{i}")
        ht.write(d, process_index=i, host=f"host{i}")
        host_dirs.append(d)
    pod = os.path.join(tmp, "pod")
    agg_rc = _agg.main([*host_dirs, "--out", pod])
    checks["aggregate_cli_ok"] = agg_rc == 0
    checks["pod_bundle_valid"] = validate_dir(pod)["ok"]
    total = None
    with open(os.path.join(pod, "metrics.jsonl")) as fh:
        for line in fh:
            rec = json.loads(line)
            if rec.get("kind") == "counter" \
                    and rec.get("name") == "pod.requests":
                total = rec["value"]
    checks["pod_counters_sum"] = total == 8
    return {"smoke": "meshplane", "n_shards": n_shards, **checks,
            "ok": all(checks.values())}


# --------------------------------------------------------------------------
# result-wire smoke (ISSUE 10): encode -> fetch -> decode round trip
# --------------------------------------------------------------------------


def result_wire_smoke(days=2, tickers=48, names=None):
    """run_tests.sh --quick smoke: the blocked-quantized result wire
    end to end on a seeded batch — the full factor set computed raw,
    then encoded ON DEVICE (one fused dispatch), fetched as ONE packed
    payload, and host-dequantized. ``ok`` iff the all-factor parity
    gate is green under the pinned contract (bitwise where widened,
    pinned range-relative/rtol bounds where quantized — data/
    result_wire.RESULT_BOUNDS, docs/PIN_BOUNDS.md), no slice overflowed
    the spill budget, and the measured byte ratio clears 1.5x on this
    tiny shape (meta overhead amortizes to ~1.9x+ at the headline
    5000-ticker shape). One JSON line; nonzero exit on drift."""
    import jax.numpy as jnp

    from replication_of_minute_frequency_factor_tpu.data import (
        result_wire as rw)
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        compute_factors_jit, factor_names as _fnames)

    rng = np.random.default_rng(21)
    names = tuple(names or _fnames())
    bars, mask = make_batch(rng, n_days=days, n_tickers=tickers)
    out = compute_factors_jit(jax.device_put(bars),
                              jax.device_put(mask), names=names)
    raw = np.stack([np.asarray(out[n]) for n in names])
    spec = rw.ResultWireSpec.for_names(names, days=days)
    enc = jax.jit(rw.encode_block, static_argnums=1)
    payload = np.asarray(enc(jnp.asarray(raw), spec))
    dec, v = rw.decode_block(payload, len(names), days, tickers,
                             spec.spill_rows, strict=False)
    chk = rw.check_bounds(raw, dec, names, sidx=v["sidx"])
    ratio = (v["f32_bytes"] / v["payload_bytes"]
             if v["payload_bytes"] else 0.0)
    return {
        "smoke": "result_wire", "factors": len(names), "days": days,
        "tickers": tickers, "spill_rows": spec.spill_rows,
        "quantized": v["quantized"], "widened": v["widened"],
        "overflow": v["overflow"], "byte_ratio": round(ratio, 3),
        "max_rel_err": float(chk["max_rel_err"]),
        "parity_bad": chk["bad_factors"],
        "ok": (chk["ok"] and v["overflow"] == 0 and ratio >= 1.5),
    }


# --------------------------------------------------------------------------
# session smoke (ISSUE 15): a non-default market end to end
# --------------------------------------------------------------------------


def session_smoke(session="us_390", days=2, tickers=32, names=None):
    """run_tests.sh --quick smoke: one NON-DEFAULT session (us_390)
    through the full device path — wire encode -> packed resident scan
    -> S-increment stream parity. ``ok`` iff:

      * the seeded batch ENCODES (wire, not the raw fallback) at the
        session's slot count and the on-device decode round-trips the
        mask exactly;
      * the resident-scan executable's exposures at the session shape
        are BITWISE the direct fused graph's on the same (bars, mask);
      * streaming day 0 through the session-sized carry (every one of
        the S minutes) finalizes BITWISE equal to the batch graph —
        the 240-increment parity gate generalized to S increments;
      * the readiness plane is SOUND at end of day (every present-bar
        lane of every kernel reports ready).

    One JSON line; nonzero exit on drift."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    from replication_of_minute_frequency_factor_tpu.markets import (
        get_session)
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        compute_factors_jit, factor_names as _fnames)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_packed_resident)
    from replication_of_minute_frequency_factor_tpu.stream.engine import (
        StreamEngine)

    spec_s = get_session(session)
    n_slots = spec_s.n_slots
    names = tuple(names or _fnames())
    rng = np.random.default_rng(15)
    bars, mask = make_batch(rng, n_days=days, n_tickers=tickers,
                            session=spec_s)

    # 1. wire encode at the session layout (widen-only floor like the
    # year loops; a raw fallback fails the smoke — the session must be
    # REPRESENTABLE, not merely runnable)
    w = wire.encode(bars, mask)
    encoded = w is not None
    decode_ok = False
    if encoded:
        dec_bars, dec_m = (np.asarray(x) for x in jax.device_get(
            wire.decode(*[jax.device_put(a) for a in w.arrays])))
        decode_ok = bool((dec_m == mask).all()
                         and np.allclose(dec_bars, np.where(
                             mask[..., None], bars, 0.0), rtol=3e-7))
    else:
        dec_bars, dec_m = bars, mask

    # 2. resident scan vs the direct fused graph on the SAME decoded
    # inputs, bitwise (the decode's documented ~1-ulp tick wobble vs
    # the raw cast is gated by decode_ok above, not smeared into the
    # executable-parity check — the 240 smokes' contract)
    direct = compute_factors_jit(jax.device_put(dec_bars),
                                 jax.device_put(dec_m), names=names,
                                 session=spec_s)
    direct_stack = np.stack([np.asarray(direct[n]) for n in names])
    if encoded:
        buf, spec = wire.pack_arrays(w.arrays)
        kind = "wire"
    else:
        buf, spec = wire.pack_arrays((bars, mask.view(np.uint8)))
        kind = "raw"
    ys = compute_packed_resident((jax.device_put(buf),), spec, kind,
                                 names, session=spec_s)
    scan_stack = np.asarray(ys)[0]
    # the scan executable and the direct jit are DIFFERENT XLA
    # modules; shape-dependent fusion wobbles a handful of sqrt/
    # division kernels at the tens-of-ulps level (the PR 5
    # vol_upRatio observation — measured ~45 ulps on corr_pv at 240
    # AND 390, i.e. not a session effect). Bitwise where possible,
    # pinned <= 64 f32 ulps relative otherwise.
    eps = np.finfo(np.float32).eps
    resident_mismatch = []
    resident_ulp_pinned = []
    for j, n in enumerate(names):
        if _bitwise_equal(scan_stack[j], direct_stack[j]):
            continue
        a, b = scan_stack[j], direct_stack[j]
        if bool((np.isnan(a) != np.isnan(b)).any()):
            resident_mismatch.append(n)
            continue
        rel = np.abs(a - b) / np.maximum(np.abs(b), 1e-30)
        if np.nanmax(np.where(np.isnan(a), 0.0, rel)) <= 64 * eps:
            resident_ulp_pinned.append(n)
        else:
            resident_mismatch.append(n)

    # 3. S-increment stream parity on day 0 (generalizes the 240 gate)
    eng = StreamEngine(tickers, names=names, session=spec_s)
    eng.warmup(micro_batches=(n_slots,))
    eng.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(dec_bars[0], 0, 1)),
        np.ascontiguousarray(dec_m[0].T))
    exposures, ready = (np.asarray(x) for x in eng.snapshot())
    stream_mismatch = [
        n for j, n in enumerate(names)
        if not _bitwise_equal(exposures[j], direct_stack[j][0])]

    # 4. end-of-day readiness soundness: a NaN exposure on a ready lane
    # is allowed (degenerate data); a FINITE exposure on a not-ready
    # lane is the soundness violation
    unsound = int((np.isfinite(exposures) & ~ready).sum())

    return {
        "smoke": "session", "session": spec_s.name, "n_slots": n_slots,
        "days": days, "tickers": tickers, "factors": len(names),
        "encoded": encoded, "decode_ok": decode_ok,
        "resident_mismatched": resident_mismatch,
        "resident_ulp_pinned": resident_ulp_pinned,
        "stream_mismatched": stream_mismatch,
        "ready_frac": round(float(ready.mean()), 4),
        "unsound_lanes": unsound,
        "ok": (encoded and decode_ok and not resident_mismatch
               and not stream_mismatch and unsound == 0),
    }


def _bitwise_equal(a, b):
    """NaN-aware bitwise equality of two f32 arrays."""
    a, b = np.asarray(a), np.asarray(b)
    return bool((a.view(np.uint32) == b.view(np.uint32)).all())


# --------------------------------------------------------------------------
# factor-health smoke (ISSUE 12): fused stats parity + drift round trip
# --------------------------------------------------------------------------


def factorplane_smoke(days=2, tickers=48, names=None):
    """run_tests.sh --quick smoke: the factor-health plane end to end
    on a seeded day batch. ``ok`` iff:

      * the FUSED on-device stats of the full factor set (computed as a
        side-output of the packed dispatch — the exact production
        fusion point) match a host-side numpy recompute over the same
        fetched exposures: lane/NaN/±inf counts and min/max EXACTLY,
        mean/std within f32 reduction-order tolerance;
      * the fused dispatch's exposures are BITWISE the plain
        dispatch's (the side-output reads, never rewrites);
      * an injected coverage collapse trips a ``factor_drift_burst``
        flight dump that ``telemetry.validate`` accepts and that names
        the collapsed factor, while the stable seeded pass produced
        ZERO dumps.

    One JSON verdict line; nonzero exit on drift."""
    import tempfile

    from replication_of_minute_frequency_factor_tpu import pipeline
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        factor_names as _fnames)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        factorplane as fp)
    from replication_of_minute_frequency_factor_tpu.telemetry.validate \
        import validate_dump

    rng = np.random.default_rng(23)
    names = tuple(names or _fnames())
    bars, mask = make_batch(rng, n_days=days, n_tickers=tickers)
    arrays = (bars, mask.view(np.uint8))
    out, st = pipeline.compute_packed(arrays, "raw", names,
                                      factor_stats=True)
    base = pipeline.compute_packed(arrays, "raw", names)
    exp = np.asarray(out)
    dev_stats = np.asarray(st)
    bitwise = np.array_equal(exp, np.asarray(base), equal_nan=True)
    host_stats = fp.factor_stats_host(exp)
    counts_ok = np.array_equal(dev_stats[:, :5], host_stats[:, :5])
    minmax_ok = np.array_equal(dev_stats[:, 7:], host_stats[:, 7:],
                               equal_nan=True)
    moments_ok = bool(np.allclose(dev_stats[:, 5:7], host_stats[:, 5:7],
                                  rtol=1e-4, atol=1e-6, equal_nan=True))
    # drift round trip: bank the seeded baseline, re-observe it (zero
    # dumps on stable data), then collapse one factor's coverage and
    # check the burst dump names it and schema-validates
    victim = names[0]
    with tempfile.TemporaryDirectory() as td:
        plane = fp.FactorPlane(telemetry=Telemetry(), dump_dir=td,
                               burst=2)
        plane.observe_block(names, dev_stats)       # banks baselines
        stable = [plane.observe_block(names, dev_stats)["bursts"]
                  for _ in range(3)]
        collapsed = dev_stats.copy()
        collapsed[0, 1] = 0.0                        # finite -> 0
        collapsed[0, 2] = collapsed[0, 0]            # all NaN
        collapsed[0, 5:] = np.nan
        dumps = []
        for _ in range(3):
            s = plane.observe_block(names, collapsed)
            dumps.extend(s.get("burst_dumps") or [])
        dump_ok = named_ok = False
        if dumps:
            rep = validate_dump(dumps[0])
            dump_ok = bool(rep.get("ok"))
            with open(dumps[0]) as fh:
                named_ok = victim in fh.read()
    return {
        "smoke": "factorplane", "factors": len(names), "days": days,
        "tickers": tickers, "exposures_bitwise": bitwise,
        "counts_exact": bool(counts_ok),
        "minmax_exact": bool(minmax_ok), "moments_ok": moments_ok,
        "stable_bursts": sum(stable), "drift_dumps": len(dumps),
        "dump_valid": dump_ok, "dump_names_factor": named_ok,
        "ok": (bitwise and bool(counts_ok) and bool(minmax_ok)
               and moments_ok and sum(stable) == 0 and dump_ok
               and named_ok),
    }


def main():
    _ensure_device_reachable()  # may exec into a CPU-fallback run
    if os.environ.get("BENCH_REQUIRE_TPU") \
            and jax.devices()[0].platform == "cpu":
        # the probe child saw a TPU but THIS process resolved to CPU
        # (e.g. backend failed fast after the probe): refuse to print a
        # TPU-named metric from a CPU run
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        sys.exit(17)
    _wait_host_quiet()
    try:
        from tools.cpu_busy import mark_busy
    except ImportError:  # not running from a repo checkout
        pass
    else:
        # hold the sentinel for the rest of the run: the tunnel watcher
        # must not fire a capture session mid-bench (two TPU workloads
        # over one tunnel + one host core would corrupt both timings) —
        # notably the driver's round-end bench run, which the watcher
        # can outlive
        mark_busy("bench headline")
    import queue
    import threading

    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_packed_prepared)

    # persistent XLA cache (when configured) turns the ~20-40s warmup
    # compile into a disk hit on repeat runs
    apply_compilation_cache(get_config())

    rng = np.random.default_rng(0)
    # BENCH_FACTORS (csv) restricts the graph — smoke/diag use on
    # containers whose jaxlib can't trace every kernel; the metric
    # prefix below derives from the ACTUAL factor count, so a
    # restricted run can never print under the 58-factor name
    factors_env = os.environ.get("BENCH_FACTORS")
    names = (tuple(s.strip() for s in factors_env.split(",") if s.strip())
             if factors_env else factor_names())
    # days/iters come from BENCH_DAYS_PER_BATCH/BENCH_ITERS; the CPU
    # fallback's execve pins them to the historical 8/2 shape so the
    # tunnel-down indicator stays comparable with its own series
    days, iters, warmup = DAYS_PER_BATCH, ITERS, WARMUP
    is_cpu_fallback = _SUFFIX == "_cpu_fallback_tunnel_down"

    probe_bars, probe_mask = make_batch(rng, n_days=1)
    use_wire = wire.encode(probe_bars, probe_mask) is not None

    def encode_pack(b, m, t=None):
        """Host half of a step: wire-encode (C++, GIL released) + pack
        into the single transfer buffer; raw-f32 fallback when the wire
        format can't represent the batch. ``t`` (a Timer) attributes the
        two stages for the diagnostic pass — same code path either way,
        so the breakdown can never drift from what the timed loop runs."""
        ctx = t if t is not None else _NullTimer()
        with ctx("wire_encode"):
            w = wire.encode(b, m) if use_wire else None
        get_telemetry().counter("bench.encode_kind",
                                kind="wire" if w is not None else "raw")
        with ctx("pack"):
            if w is not None:
                return wire.pack_arrays(w.arrays) + ("wire",)
            return wire.pack_arrays((b, m.view(np.uint8))) + ("raw",)

    def launch(item):
        """Device half: ONE buffer over the wire -> fused on-device unpack
        + decode + 58-factor graph -> ONE stacked output tensor (or,
        through the result wire, ONE packed quantized payload)."""
        buf, spec, kind = item
        return compute_packed_prepared(buf, spec, kind, names=names,
                                       replicate_quirks=True,
                                       result_spec=rspec)

    # warmup ships its own batches so the timed loop's bytes are cold in
    # any transfer-path cache; it runs BEFORE the timed batches are
    # synthesized so an OOM retry doesn't waste a year's worth of synth
    # Stage attribution, now on EVERY backend (VERDICT r3 #1a: three
    # rounds of TPU headlines could not be decomposed into transfer vs
    # compute, so the optimization target was a guess). One serial
    # 8-day batch — always 8 regardless of the loop's batch size, so
    # the stage series stays comparable across configurations and with
    # the r1-r3 fallback series; it runs BEFORE the warmup so its AOT
    # lower+compile is the process's FIRST build of the packed graph —
    # the ``compile`` stage therefore measures a real compile (and
    # agrees with the manifest's xla block) instead of reading ~0.001 s
    # off a cache the jit warmup already populated, with the real cost
    # folded into device_exec_first; being pre-loop also means a tunnel
    # window that closes mid-loop never half-times it.
    # BENCH_STAGES=0 skips it when an up-window is too short to spare.
    stages = None
    if os.environ.get("BENCH_STAGES", "1") != "0":
        from replication_of_minute_frequency_factor_tpu.pipeline import (
            _compute_packed_jit)
        # StageTimer: Timer semantics for the stages dict below, PLUS
        # every stage lands in the shared registry as a
        # span_seconds{span=...} histogram — the BENCH series and the
        # pipeline's telemetry can no longer drift apart (they are the
        # same records)
        t = get_telemetry().stage_timer(
            rolling_impl=get_config().rolling_impl)
        with t("synth_batch"):
            b, m = make_batch(np.random.default_rng(99), n_days=8)
        sbuf, sspec, skind = encode_pack(b, m, t)  # wire_encode + pack
        with t("ingest_put"):
            dbuf = jax.device_put(sbuf)
            jax.block_until_ready(dbuf)
        # Compile timed APART from execution via the AOT API (VERDICT r4
        # weak #1: the old pass timed the jit COLD on an 8-day shape the
        # 32-day warmup never compiled, so its 116 s "device_compute"
        # folded remote compile + cache handling into "compute" and
        # contradicted the ~3 ms graph time the ladder measures).
        # compile_with_telemetry stamps compile seconds, FLOPs/bytes and
        # the HLO op-count fingerprint (while/dot/gather counts — the
        # "fori_loop is gone" evidence) into the registry, so a
        # BENCH_TELEMETRY_DIR bundle's manifest carries them.
        from replication_of_minute_frequency_factor_tpu.telemetry import (
            attribution as _attr)
        roll = get_config().rolling_impl
        with t("compile"):
            compiled = _attr.compile_with_telemetry(
                "bench_packed_8day",
                _compute_packed_jit.lower(
                    dbuf, sspec, skind, names, True, roll))
        # Per-dispatch fixed cost on a trivial resident graph: if this
        # floor is seconds-scale, the sweep's ~12 s/round-trip term is
        # transport DISPATCH overhead (not graph time, not bandwidth) —
        # exactly what the resident loop's single execute amortizes.
        tiny = jax.device_put(np.arange(256, dtype=np.float32))
        jax.block_until_ready(tiny)
        triv = jax.jit(lambda x: x * 2.0)
        jax.block_until_ready(triv(tiny))  # compile outside the floor
        floors = []
        for _ in range(3):
            f0 = time.perf_counter()
            jax.block_until_ready(triv(tiny))
            floors.append(time.perf_counter() - f0)
        with t("device_exec_first"):
            out = compiled(dbuf)
            jax.block_until_ready(out)
        with t("device_exec_steady"):
            out = compiled(dbuf)
            jax.block_until_ready(out)
        with t("result_to_host"):
            np.asarray(out)
        stages = {k: round(v, 3) for k, v in t.totals().items()}
        stages["dispatch_floor_ms"] = round(min(floors) * 1e3, 1)
        # On-chip profiler trace around one more execute (VERDICT r4 #1:
        # Config.profile_dir existed but was never exercised on
        # hardware). Failure is recorded, not fatal — the axon transport
        # may not support device-side tracing.
        pdir = (get_config().profile_dir
                or os.environ.get("BENCH_PROFILE_DIR"))
        if pdir and not is_cpu_fallback:
            try:
                with jax.profiler.trace(pdir):
                    jax.block_until_ready(compiled(dbuf))
                stages["profile_ok"] = True
                stages["profile_dir"] = pdir
            except Exception as e:  # noqa: BLE001 — diagnostic only
                stages["profile_ok"] = False
                stages["profile_error"] = str(e)[:200]
        # free the stage pass's device + host buffers before the timed
        # loop: they add HBM/host footprint the OOM-guarded warmup
        # never tested, and an OOM mid-loop is uncatchable there
        del b, m, sbuf, dbuf, out, compiled

    consolidate = os.environ.get("BENCH_CONSOLIDATE") == "1"
    mode = "stream" if is_cpu_fallback else MODE
    # result wire (ISSUE 10): on by default for the TPU loops; off on
    # the CPU fallback (own comparability series + the resident diag's
    # raw equality check) and under BENCH_CONSOLIDATE=1 (the device
    # concat A/B concatenates [F, D, T] blocks along days, which the
    # packed payload shape does not admit)
    from replication_of_minute_frequency_factor_tpu.data import (
        result_wire as _rw)
    rspec = None
    if RESULT_WIRE and not is_cpu_fallback and not consolidate:
        rspec = _rw.ResultWireSpec.for_names(names, days=days)
    #: stream-loop result-wire totals (the resident loops carry theirs
    #: in phases['result_wire'])
    result_totals = {"widened": 0, "overflow": 0, "quantized": 0,
                     "payload_bytes": 0}

    def _decode_stream(h):
        """Host dequantize of one stream-loop payload fetch."""
        dec, v = _rw.decode_block(h, len(names), days, N_TICKERS,
                                  rspec.spill_rows, strict=False)
        for k in ("widened", "overflow", "quantized"):
            result_totals[k] += v[k]
        result_totals["payload_bytes"] += int(h.nbytes)
        return dec
    # r7 mesh resolution: the resident scan shards the tickers axis
    # over every visible device (BENCH_SHARDS pins it; 1 device = the
    # single-device r6 loop). The headline pads tickers to the
    # TICKER_BUCKET x n_shards lcm like the pipeline grid; tiny
    # BENCH_TICKERS smokes pad to the shard multiple only.
    n_shards = 1
    mesh = None
    mesh2d = None
    mesh_shape = None
    shard_bucket = 1
    if mode == "resident" and not is_cpu_fallback:
        avail = len(jax.devices())
        n_shards = max(1, min(N_SHARDS or avail, avail))
        from replication_of_minute_frequency_factor_tpu.pipeline import (
            TICKER_BUCKET)
        # r12: BENCH_MESH_DAYS=d lifts the scan to the full (d, t)
        # mesh — the day axis pipelines groups of scan steps, the
        # ticker axis stays the wide data-parallel one. Falls back to
        # the 1-D tickers mesh when t would collapse to 1 (a 2-D
        # record REQUIRES d > 1 AND t > 1).
        if MESH_DAYS > 1 and n_shards // MESH_DAYS > 1:
            from replication_of_minute_frequency_factor_tpu.parallel import (
                resident_mesh)
            d_sh = MESH_DAYS
            t_sh = n_shards // d_sh
            n_shards = d_sh * t_sh
            mesh2d = resident_mesh(shape=(d_sh, t_sh))
            mesh_shape = (d_sh, t_sh)
            if N_TICKERS >= TICKER_BUCKET:
                shard_bucket = TICKER_BUCKET
        elif n_shards > 1:
            from replication_of_minute_frequency_factor_tpu.parallel import (
                resident_mesh)
            mesh = resident_mesh(n_shards)
            mesh_shape = (1, n_shards)
            if N_TICKERS >= TICKER_BUCKET:
                shard_bucket = TICKER_BUCKET
    if mesh2d is not None and rspec is not None:
        # the 2-D payload geometry carries the day-group padding: the
        # result spec must describe the PADDED day extent or the host
        # decode misreads the slice table (d divides DAYS_PER_BATCH in
        # the default shapes, so this is usually a no-op)
        d_pad_days = -(-days // mesh_shape[0]) * mesh_shape[0]
        if d_pad_days != days:
            rspec = _rw.ResultWireSpec.for_names(names, days=d_pad_days)
    # sharded default: two scan groups, so group 1's ingest genuinely
    # double-buffers behind group 0's execution (ingest_hidden_s > 0);
    # single-device default stays one group (the r6 3-sync shape)
    group = int(os.environ.get("BENCH_RESIDENT_GROUP", "0")) or (
        -(-iters // 2) if (mesh is not None or mesh2d is not None)
        else iters)
    warm_info: dict = {}

    class _ResidentOOM(RuntimeError):
        """Resident scan still OOMs at group == 1 — signal for the
        stream-mode fallback below (ADVICE r5: re-raising here lost the
        hardware window with nothing banked)."""

    def _grow_result_floor(wp) -> bool:
        """Widen-only spill floor (the result wire's analogue of
        encode_year's spec-convergence loop): a warmup overflow grows
        the static budget and the warm pass re-runs under a fresh
        executable, so the TIMED loop can never hit a strict-decode
        overflow on same-shaped data. Returns True when it grew."""
        nonlocal rspec
        info = (wp or {}).get("result_wire") or {}
        if not info.get("overflow_slices"):
            return False
        need = info["widened_slices"] + info["overflow_slices"]
        rspec = rspec.grow(need)
        warm_info["result_floor_grown_to"] = rspec.spill_rows
        print(f"# result wire spill budget overflowed in warmup; "
              f"growing floor to {rspec.spill_rows} rows",
              file=sys.stderr, flush=True)
        return True

    def _warm_resident(group):
        """Compile + first-execute the resident scan graph on DISTINCT
        warm bytes (same caching rationale as the stream warmup), full
        fetch included so every path the timed run takes is warm. OOM
        halves ``group`` (smaller scan groups shrink the resident
        input + output footprint) down to single-batch groups; an OOM
        at group == 1 raises ``_ResidentOOM`` so the caller can fall
        back to the stream loop instead of losing the window."""
        wb = [make_batch(rng, n_days=days) for _ in range(iters)]
        while True:
            try:
                t0 = time.perf_counter()
                wp, _, _ = run_resident(wb, names, use_wire, group,
                                        result_spec=rspec)
                if rspec is not None and _grow_result_floor(wp):
                    continue
                warm_info["warm_total_s"] = round(
                    time.perf_counter() - t0, 1)
                warm_info["warm_phases"] = wp
                return group
            except Exception as e:  # noqa: BLE001 — filtered to OOM
                oom = any(s in str(e) for s in
                          ("RESOURCE_EXHAUSTED", "Out of memory",
                           "out of memory"))
                if not oom:
                    raise
                if group <= 1:
                    raise _ResidentOOM(str(e)[:300]) from e
                group = max(1, group // 2)
                _flight_note("oom_ladder_demotion", rung="resident",
                             action="halve_group", group=group,
                             error=str(e)[:200])
                print(f"# resident scan exhausted device memory; "
                      f"retrying with group={group}",
                      file=sys.stderr, flush=True)

    def _warm(n_days):
        # launch BOTH warm batches before blocking, with the result
        # copies in flight — the timed loop keeps 2-3 batches' buffers
        # live simultaneously, and an OOM that only manifests at the
        # pipelined peak must fire HERE, inside the fallback's
        # try/except, not mid-loop where it would lose the window
        w = [make_batch(rng, n_days=n_days) for _ in range(2)]
        for _ in range(warmup):
            outs_w = [launch(encode_pack(*b)) for b in w]
            for o in outs_w:
                o.copy_to_host_async()
            for o in outs_w:
                jax.block_until_ready(o)
            if consolidate:
                # warm the consolidated path's device concat at the
                # EXACT shape the timed loop uses (iters refs of
                # [F, days, T] — XLA specializes on arity/shape), or
                # its first compile lands inside the timed window and
                # biases the A/B this mode exists to decide
                import jax.numpy as jnp
                refs = (outs_w * ((iters + 1) // 2))[:iters]
                jax.block_until_ready(jnp.concatenate(refs, axis=1))

    def _warm_resident_sharded(group):
        """Sharded twin of ``_warm_resident``: compile + first-execute
        the SHARDED scan (AOT, so the compile lands in the registry)
        on distinct warm bytes, full overlapped-ingest + fetch
        included. OOM halves the scan group; an OOM at group == 1
        raises ``_ResidentOOM`` and the caller steps DOWN THE LADDER to
        the single-device resident scan (then stream) instead of
        losing the window."""
        wb = [make_batch(rng, n_days=days) for _ in range(iters)]
        g = group
        while True:
            try:
                t0 = time.perf_counter()
                wp, _, _ = run_resident_sharded(wb, names, use_wire, g,
                                                mesh,
                                                bucket=shard_bucket,
                                                result_spec=rspec)
                if rspec is not None and _grow_result_floor(wp):
                    continue
                warm_info["warm_total_s"] = round(
                    time.perf_counter() - t0, 1)
                warm_info["warm_phases"] = wp
                return g
            except Exception as e:  # noqa: BLE001 — filtered to OOM
                oom = any(s in str(e) for s in
                          ("RESOURCE_EXHAUSTED", "Out of memory",
                           "out of memory"))
                if not oom:
                    raise
                if g <= 1:
                    raise _ResidentOOM(str(e)[:300]) from e
                g = max(1, g // 2)
                _flight_note("oom_ladder_demotion",
                             rung="resident_sharded",
                             action="halve_group", group=g,
                             error=str(e)[:200])
                print(f"# sharded resident scan exhausted device "
                      f"memory; retrying with group={g}",
                      file=sys.stderr, flush=True)

    def _warm_resident_2d(group):
        """2-D twin of ``_warm_resident_sharded``: compile +
        first-execute the (d, t) pipelined scan on distinct warm
        bytes, carry threading + overlapped ingest + fetch included.
        OOM halves the scan group; an OOM at group == 1 raises
        ``_ResidentOOM`` and the caller steps down the ladder to the
        1-D sharded scan (then single-device, then stream)."""
        wb = [make_batch(rng, n_days=days) for _ in range(iters)]
        g = group
        while True:
            try:
                t0 = time.perf_counter()
                wp, _, _, _ = run_resident_2d(wb, names, use_wire, g,
                                              mesh2d,
                                              bucket=shard_bucket,
                                              result_spec=rspec)
                if rspec is not None and _grow_result_floor(wp):
                    continue
                warm_info["warm_total_s"] = round(
                    time.perf_counter() - t0, 1)
                warm_info["warm_phases"] = wp
                return g
            except Exception as e:  # noqa: BLE001 — filtered to OOM
                oom = any(s in str(e) for s in
                          ("RESOURCE_EXHAUSTED", "Out of memory",
                           "out of memory"))
                if not oom:
                    raise
                if g <= 1:
                    raise _ResidentOOM(str(e)[:300]) from e
                g = max(1, g // 2)
                _flight_note("oom_ladder_demotion",
                             rung="resident_2d",
                             action="halve_group", group=g,
                             error=str(e)[:200])
                print(f"# 2-D resident scan exhausted device memory; "
                      f"retrying with group={g}",
                      file=sys.stderr, flush=True)

    if mode == "resident" and mesh2d is not None:
        try:
            group = _warm_resident_2d(group)
        except _ResidentOOM as e:
            # first rung of the r12 ladder: 2-D -> 1-D tickers-sharded
            # (the record's mesh_shape/methodology fields flip with
            # the fallback, so a 1-D number can never read as 2-D)
            print("# 2-D resident scan OOM at group=1; falling back "
                  "to the 1-D tickers-sharded resident scan",
                  file=sys.stderr, flush=True)
            _flight_note("oom_ladder_demotion", rung="resident_2d",
                         action="fallback_1d_sharded",
                         error=str(e)[:200])
            warm_info["mesh2d_oom_fallback"] = str(e)[:200]
            from replication_of_minute_frequency_factor_tpu.parallel import (
                resident_mesh)
            mesh2d = None
            mesh = resident_mesh(n_shards)
            mesh_shape = (1, n_shards)
            group = int(os.environ.get("BENCH_RESIDENT_GROUP",
                                       "0")) or -(-iters // 2)
    if mode == "resident" and mesh is not None:
        try:
            group = _warm_resident_sharded(group)
        except _ResidentOOM as e:
            # first rung of the r7 ladder: sharded -> single-device
            # resident. The record's n_shards/methodology fields flip
            # with the fallback, so a single-device number can never
            # be read as a sharded one.
            print("# sharded resident scan OOM at group=1; falling "
                  "back to the single-device resident scan",
                  file=sys.stderr, flush=True)
            _flight_note("oom_ladder_demotion", rung="resident_sharded",
                         action="fallback_single_device",
                         error=str(e)[:200])
            warm_info["sharded_oom_fallback"] = str(e)[:200]
            mesh = None
            mesh_shape = None
            n_shards = 1
            group = int(os.environ.get("BENCH_RESIDENT_GROUP",
                                       "0")) or iters
    if mode == "resident" and mesh is None:
        try:
            group = _warm_resident(group)
        except _ResidentOOM as e:
            # even single-batch scan groups exhaust HBM: keep the
            # hardware window and bank a STREAM number at the proven
            # 8-day shape instead of re-raising with nothing recorded
            # (ADVICE r5); the record's mode/methodology fields flip
            # with it, so the number can never be read as resident
            print("# resident scan OOM at group=1; falling back to "
                  "stream mode at the proven 8-day shape",
                  file=sys.stderr, flush=True)
            _flight_note("oom_ladder_demotion", rung="resident",
                         action="fallback_stream", error=str(e)[:200])
            mode = "stream"
            warm_info["resident_oom_fallback"] = str(e)[:200]
            days, iters = 8, max(iters, 5)
    if mode == "stream":
        try:
            _warm(days)
        except Exception as e:  # noqa: BLE001 — filtered to OOM below
            oom = any(s in str(e) for s in
                      ("RESOURCE_EXHAUSTED", "Out of memory",
                       "out of memory"))
            if not oom or days <= 8:
                raise
            # the 32-day shape is this round's bet; a chip that can't
            # hold it must not cost the up-window — fall back to the
            # proven 8-day shape (r3's configuration) and keep going
            print(f"# {days}-day batch exhausted device memory; retrying "
                  "with 8-day batches", file=sys.stderr, flush=True)
            _flight_note("oom_ladder_demotion", rung="stream",
                         action="fallback_8day", error=str(e)[:200])
            days, iters = 8, max(iters, 5)
            _warm(days)

    # one DISTINCT batch per timed iteration: the real driver never ships
    # the same bytes twice, and repeating a buffer would let any
    # content-addressed caching in the transfer path (tunnel or
    # otherwise) flatter the number — distinct batches cost nothing if
    # no such layer exists
    batches = [make_batch(rng, n_days=days) for _ in range(iters)]

    # Link-quality probe, reported alongside the headline: the chip sits
    # behind a tunnel whose bandwidth swings by >10x hour to hour, and
    # the headline is transfer-bound — without these keys a slow-link
    # run is indistinguishable from a slow-code run. Distinct bytes both
    # ways (see the caching note above). Tunnel-attached runs only: on
    # the CPU fallback (or any local platform) it would time memcpy.
    # The latency floor comes first — it's the cheapest number and the
    # one that decides the batch-size story (VERDICT r3 weak #2).
    # BENCH_LINK=0 skips both probes (~1 min): a variant step fired in
    # the same up-window as the main headline would only re-measure
    # what the headline/link steps already banked.
    link_down = link_up = link_wait = lat_put_ms = lat_get_ms = None
    if ("PALLAS_AXON_POOL_IPS" in os.environ and not is_cpu_fallback
            and os.environ.get("BENCH_LINK", "1") != "0"):
        lat_put_ms, lat_get_ms = probe_latency(rng)
        link_down, link_up, link_wait = measure_link(rng)

    # Steady state, double-buffered exactly like the real driver
    # (pipeline._run_device_pipeline): a producer thread encodes batch
    # i+1 while the device runs batch i, at most two batches in flight.
    # Every batch's ingest (encode+pack+transfer) is inside the timed
    # window; only its overlap with device compute is what the pipeline
    # itself would give.
    q: "queue.Queue" = queue.Queue(maxsize=2)

    def produce():
        for i in range(iters):
            q.put(encode_pack(*batches[i]))

    # BENCH_CONSOLIDATE=1: accumulate every batch's [F, D, T] result on
    # DEVICE and materialize the whole year in ONE device->host fetch at
    # the end (a real driver saving once per year could do exactly
    # this). Same bytes cross the link either way; what it saves is
    # (iters - 1) per-fetch latency floors — decisive iff the link step
    # shows a seconds-scale floor. The default loop instead fetches per
    # batch with async overlap, like pipeline._run_device_pipeline.
    # (``consolidate`` resolved above so _warm could pre-compile the
    # device concat.)
    # measured-counter windows over the timed loop ONLY (warmup and the
    # stage pass incremented the same counters; deltas exclude them):
    # host_blocking_syncs comes from _count_sync call sites, encode_kind
    # from encode_year/encode_pack's registry counter
    reg = get_telemetry().registry
    syncs_before = reg.counter_total("bench.host_blocking_syncs")
    syncs_before_by_point = {
        p: reg.counter_value("bench.host_blocking_syncs", point=p)
        for p in _SYNC_POINTS}
    kind_before = _encode_kind_marks()
    packed_bytes_before = reg.counter_total("wire.packed_bytes")
    phases = None
    # one-shot resident-path driver artifact on the CPU fallback
    # (VERDICT r5 weak #5: every fallback artifact exercised only the
    # stream loop, so the resident lax.scan path had ZERO coverage in
    # any banked driver artifact); the diag needs the stream loop's
    # materialized results for the equality check, so flag it up front
    want_resident_diag = (is_cpu_fallback and mode == "stream"
                          and not consolidate
                          and os.environ.get("BENCH_RESIDENT_DIAG",
                                             "1") != "0")
    stream_host_results = [] if want_resident_diag else None
    # the timed loop under a crash-safe profiler window when
    # Config.profile_dir is set (the stage pass captures one serial
    # execute; this captures the REAL pipelined loop) — start/stop
    # sit outside the t0..wall window so capture setup/serialization
    # never pollutes the measured number
    pdir_loop = (get_config().profile_dir
                 or os.environ.get("BENCH_PROFILE_DIR"))
    loop_trace = TraceCapture(
        os.path.join(pdir_loop, "timed_loop")
        if pdir_loop and not is_cpu_fallback else None)
    with loop_trace:
        if mode == "resident":
            t0 = time.perf_counter()
            if mesh2d is not None:
                phases, _kind, _, _ = run_resident_2d(
                    batches, names, use_wire, group, mesh2d,
                    bucket=shard_bucket, result_spec=rspec)
                # same loop shape as the 1-D sharded loop: per-group
                # stacked puts (none host-blocking), the carry threads
                # on device and is never fetched here
                round_trips = {"puts_async": -(-iters // group),
                               "executes": -(-iters // group),
                               "fetches": -(-iters // group)}
            elif mesh is not None:
                phases, _kind, _ = run_resident_sharded(
                    batches, names, use_wire, group, mesh,
                    bucket=shard_bucket, result_spec=rspec)
                # puts are per GROUP stack (none of them host-blocking;
                # group >= 1 overlaps the previous group's execution)
                round_trips = {"puts_async": -(-iters // group),
                               "executes": -(-iters // group),
                               "fetches": -(-iters // group)}
            else:
                phases, _kind, _ = run_resident(batches, names,
                                                use_wire, group,
                                                result_spec=rspec)
                round_trips = {"puts_async": iters,
                               "executes": -(-iters // group),
                               "fetches": -(-iters // group)}
            wall = time.perf_counter() - t0
            per_batch = wall / iters
            recon_components = phases
        else:
            # serial consumer-side decomposition for the reconciliation
            # block: the consumer loop is strictly q.get -> launch ->
            # fetch, so these three terms sum to the wall by
            # construction (producer encode/pack overlaps inside
            # produce_wait_s)
            recon_components = {"produce_wait_s": 0.0, "dispatch_s": 0.0,
                                "fetch_s": 0.0}
            if rspec is not None:
                recon_components["decode_s"] = 0.0

            def _timed(key, fn, *a):
                t_ = time.perf_counter()
                r = fn(*a)
                recon_components[key] += time.perf_counter() - t_
                return r

            t0 = time.perf_counter()
            threading.Thread(target=produce, daemon=True).start()
            outs = []
            if consolidate:
                import jax.numpy as jnp
                for i in range(iters):
                    outs.append(_timed("dispatch_s", launch,
                                       _timed("produce_wait_s", q.get)))
                big = jnp.concatenate(outs, axis=1)  # [F, iters*days, T]
                del outs
                _count_sync("stream_consolidated_fetch")
                # the year's results land in one transfer
                _timed("fetch_s", np.asarray, big)
            else:
                for i in range(iters):
                    out = _timed("dispatch_s", launch,
                                 _timed("produce_wait_s", q.get))
                    # start the result's device->host copy immediately
                    # (as the real driver does) so the slow upstream
                    # link overlaps the next batch's ingest; np.asarray
                    # below finds the bytes landed
                    out.copy_to_host_async()
                    outs.append(out)
                    if i >= 2:
                        # materialize to host like the real driver's
                        # pipeline lag (pipeline.materialize): the
                        # [58,D,T] result crosses the link too, so it
                        # belongs in the wall clock (through the result
                        # wire it is the packed payload + a host
                        # dequantize, timed as its own component)
                        _count_sync("stream_lagged_fetch")
                        h = _timed("fetch_s", np.asarray, outs[i - 2])
                        if rspec is not None:
                            h = _timed("decode_s", _decode_stream, h)
                        if stream_host_results is not None:
                            stream_host_results.append(h)
                for o in outs[-2:]:
                    _count_sync("stream_drain_fetch")
                    h = _timed("fetch_s", np.asarray, o)
                    if rspec is not None:
                        h = _timed("decode_s", _decode_stream, h)
                    if stream_host_results is not None:
                        stream_host_results.append(h)
            wall = time.perf_counter() - t0
            per_batch = wall / iters
            round_trips = {"puts_async": iters, "executes": iters,
                           "fetches": 1 if consolidate else iters}
    # the ACTUAL number of host-blocking sync points the timed loop hit,
    # counted at the call sites (ADVICE r5 low #4: the old per-branch
    # formulas under-counted the stream drain and the resident
    # group-level blocks), with a per-point breakdown so each branch's
    # blocking profile is auditable (stream non-consolidated: iters-2
    # lagged blocks + 2 drain blocks; resident: 1 ingest + 1 compute +
    # ceil(iters/group) fetches). puts_async/executes/fetches above
    # remain LOOP-SHAPE PREDICTIONS, marked as such in the record.
    round_trips["host_blocking_syncs"] = int(
        reg.counter_total("bench.host_blocking_syncs") - syncs_before)
    round_trips["host_blocking_syncs_by_point"] = {
        p: int(reg.counter_value("bench.host_blocking_syncs", point=p)
               - syncs_before_by_point[p])
        for p in _SYNC_POINTS
        if reg.counter_value("bench.host_blocking_syncs", point=p)
        - syncs_before_by_point[p]}
    round_trips["predicted_fields"] = ["puts_async", "executes",
                                       "fetches"]
    encode_kind = _encode_kind_delta(kind_before)
    full_year = per_batch * (TRADING_DAYS_PER_YEAR * YEARS / days)

    # the bytes program (ISSUE 10): per-day bytes each way over the
    # timed window, banked as first-class gauges + record blocks so the
    # regress gate grows <metric>.wire_bytes_per_day /
    # .result_bytes_per_day sub-series (both flag directions — byte
    # GROWTH is a regression, a silent byte DROP usually means the
    # payload lost content)
    days_total = iters * days
    wire_bytes = reg.counter_total("wire.packed_bytes") \
        - packed_bytes_before
    if mode == "resident":
        result_bytes = (phases.get("fetch_MB") or 0.0) * 1e6
        rw_block = dict(phases.get("result_wire")
                        or {"enabled": False})
    else:
        if rspec is not None:
            result_bytes = result_totals["payload_bytes"]
            rw_block = {
                "enabled": True,
                "spill_rows": rspec.spill_rows,
                "quantized_slices": result_totals["quantized"],
                "widened_slices": result_totals["widened"],
                "overflow_slices": result_totals["overflow"],
                "payload_MB": round(result_bytes / 1e6, 3),
                "f32_logical_MB": round(
                    iters * len(names) * days * N_TICKERS * 4 / 1e6, 3),
            }
            rw_block["ratio_vs_f32"] = (round(
                rw_block["f32_logical_MB"] / rw_block["payload_MB"], 3)
                if rw_block["payload_MB"] else None)
        else:
            result_bytes = iters * len(names) * days * N_TICKERS * 4
            rw_block = {"enabled": False}
    wire_bytes_per_day = round(wire_bytes / days_total, 1)
    result_bytes_per_day = round(result_bytes / days_total, 1)
    tel_reg = get_telemetry()
    tel_reg.gauge("wire.bytes_per_day", wire_bytes_per_day)
    tel_reg.gauge("result.bytes_per_day", result_bytes_per_day)

    # wall-clock reconciliation (telemetry.attribution): the timed
    # loop's serial components vs its measured wall with the
    # unattributed residual explicit — a >tolerance residual means the
    # loop decomposition is missing a term, and the record says so
    # loudly rather than shipping an unexplained wall
    # (BENCH_STRICT_RECONCILE=1 turns that into a nonzero exit)
    recon = reconcile(wall, recon_components,
                      tolerance=get_config().attribution_tolerance)
    if not recon["ok"]:
        print(f"# RECONCILIATION FAILURE: {recon['unattributed_s']}s of "
              f"{recon['wall_s']}s timed-loop wall unattributed "
              f"(tolerance {recon['tolerance']:.0%}; components "
              f"{recon['stages']})", file=sys.stderr, flush=True)
    diag = None
    if want_resident_diag:
        diag = resident_diag(batches, names, use_wire,
                             stream_host_results)
        if diag.get("equal") is False:
            print("# RESIDENT DIAG MISMATCH: resident-scan exposures "
                  "differ from the stream loop's "
                  f"(max_abs_diff={diag.get('max_abs_diff')})",
                  file=sys.stderr, flush=True)

    # per-op-class device-time breakdown (ISSUE 9, closing PR 3's
    # pending item): whenever a profile capture ran around the timed
    # loop, post-process the trace dir into the device_time block —
    # class totals + the device.collective_time_s collective
    # attribution, with available=False (never silence) on captures
    # without device pids (the CPU backend)
    device_time = None
    if loop_trace.profile_dir:
        from replication_of_minute_frequency_factor_tpu.telemetry import (
            attribution as _devattr)
        try:
            device_time = _devattr.device_time_block(
                pdir_loop, telemetry=get_telemetry())
        except Exception as e:  # noqa: BLE001 — diagnostics only
            device_time = {"available": False,
                           "error": f"{type(e).__name__}: {e}"[:200]}

    target = 60.0
    record = {
        # the name is DERIVED from the ticker count (ADVICE r5 medium:
        # a BENCH_TICKERS=500 run used to print a much faster number
        # under the hardcoded 5000-ticker name, and the session carry
        # would bank it as the headline series); tpu_session's carry
        # additionally rejects non-5000-ticker headline records
        "metric": f"cicc{len(names)}_{N_TICKERS}tickers_{YEARS}yr_wall"
                  + _SUFFIX,
        "value": round(full_year, 3),
        "unit": "s",
        "tickers": N_TICKERS,
        # market session discriminator (ISSUE 15): regress keys every
        # sub-series on it, so non-240 records start their own baseline
        "session": SESSION,
        # BENCH_YEARS workload multiplier (r12: the decades-x-global-
        # universe shape); 1 keeps the historical "1yr" metric name
        "years": YEARS,
        # 'wire' / 'raw' / 'mixed', measured from the registry counter
        # the timed loop's encoders incremented — a raw fallback ships
        # ~4x the bytes and must be visible in the record it distorted
        "encode_kind": encode_kind,
        "vs_baseline": round(target / full_year, 3),
        # loop shape: with 32-day batches the 8 timed iterations cover
        # 256 days — MORE than the 244-day year the metric names, so
        # the per-batch scale-down is mildly conservative rather than
        # a 6x extrapolation (VERDICT r3 weak #1)
        "days_per_batch": days,
        "iters": iters,
        "consolidated_fetch": consolidate,
        # loop methodology (VERDICT r4 #3: series breaks must be
        # explicit): "resident" = the O(1)-round-trip year (encode ->
        # N async puts -> scan execute(s) -> single fetch pass);
        # "stream" = the double-buffered per-batch loop (the CPU
        # fallback pins stream/8-day/2-iter shape). r6 DECLARES a new
        # series for both modes: the fused rolling engine (the
        # fori_loop-of-roll second moments became one gather+Gram-dot
        # pass) changes device compute on every backend, and the
        # packed/resident buffers are now donated on accelerators —
        # r5_resident_v1/r4_stream_v2 numbers are not comparable.
        # docs/BENCHMARKS.md records the series history. r7 DECLARES
        # "r7_resident_sharded_v1" for the mesh-native resident scan
        # (tickers-sharded buffers + overlapped group ingest change
        # both the module and the loop); a resident run whose mesh
        # resolved to one device stays on the r6 series, and the
        # record's n_shards field is the discriminator. r10 DECLARES
        # "r10_resident_v3" / "r10_resident_sharded_v2" /
        # "r10_stream_v4" for the result-wire loops (the fetch leg
        # ships blocked-quantized payloads + a host dequantize — both
        # the module and the fetch bytes change); BENCH_RESULT_WIRE=0
        # runs stay on their r6/r7 series, so a silent f32 fallback can
        # never smear into the r10 baselines.
        "mode": mode,
        # r12 DECLARES "r12_resident_2d_v1" for the 2-D (days,
        # tickers) pipelined scan (day-axis split + cross-day carry
        # handoff change both the module and the loop); a run whose
        # mesh fell back to 1-D stays on the r7/r10 sharded series,
        # and mesh_shape is the discriminator.
        "methodology": (
            "r12_resident_2d_v1"
            if mode == "resident" and mesh2d is not None
            else ("r10_resident_sharded_v2" if rspec is not None
                  else "r7_resident_sharded_v1")
            if mode == "resident" and n_shards > 1
            else ("r10_resident_v3" if rspec is not None
                  else "r6_resident_v2") if mode == "resident"
            else ("r10_stream_v4" if rspec is not None
                  else "r6_stream_v3")),
        # the resolved resident mesh layout ([d, t]; [1, n] for the
        # 1-D tickers mesh; null when single-device/stream) — the
        # resident_2d carry rule refuses records without d > 1 AND
        # t > 1, so a silent 1-D fallback can never bank as 2-D
        "mesh_shape": (list(mesh_shape)
                       if mode == "resident" and mesh_shape else None),
        # the result-wire verdict (ISSUE 10): enabled flag, spill
        # budget, per-slice disposition counts, payload vs logical-f32
        # bytes. tpu_session's headline carry REQUIRES this block with
        # enabled=True, so a silent f32 fallback cannot bank.
        "result_wire": rw_block,
        # the bytes program: per-day bytes each way over the timed
        # window (regress derives gateable sub-series from these)
        "wire": {"bytes_per_day": wire_bytes_per_day},
        "result": {"bytes_per_day": result_bytes_per_day},
        # how many mesh shards the tickers axis actually resolved to
        # (1 = single-device; tpu_session's resident_sharded step banks
        # only n_shards > 1 — a silent single-device fallback cannot
        # count as sharded validation)
        "n_shards": n_shards if mode == "resident" else 1,
        # shard-balance telemetry (ISSUE 9): per-shard completion
        # watermarks, skew ratio, lcm-padding waste — present exactly
        # when the run was actually sharded (tpu_session's
        # resident_sharded carry rule requires it: a record with no
        # shard-balance telemetry cannot bank); regress derives the
        # <metric>.shard_skew_ratio / .pad_waste_frac series from it
        "mesh": (get_telemetry().meshplane.summary()
                 if mode == "resident" and n_shards > 1 else None),
        # per-factor data-quality block (ISSUE 12): worst coverage,
        # widen rate, drift bursts — fused stats ride the resident
        # fetch, so resident-mode records always carry a live block
        # (available=False on the stream-mode CPU fallback, which runs
        # without the fused side-output); tpu_session's headline carry
        # REQUIRES an available block, and regress derives the
        # <metric>.widen_rate / .coverage_frac sub-series from it
        "factor_health": get_telemetry().factorplane.summary(),
        # per-op-class device time from the loop's profiler capture
        # (null when no profile dir was configured/captured)
        "device_time": device_time,
        # which rolling backend was REQUESTED (config) and which one
        # the graphs actually RESOLVED to at trace time (registry
        # counter; 'conv' under a 'pallas' request = the off-TPU
        # fallback fired); the per-stage span histograms carry the
        # requested value as their rolling_impl tag
        "rolling_impl": get_config().rolling_impl,
        "rolling_impl_resolved": _rolling_impl_resolved(
            get_config().rolling_impl),
        "phases": phases,
        # sum(components) vs the timed wall, residual explicit — the
        # telemetry.regress gate diffs these across rounds
        "reconciliation": recon,
        # one-shot resident-scan coverage on the CPU fallback (null on
        # TPU runs, where the resident headline IS the coverage)
        "resident_diag": diag,
        "round_trips": round_trips,
        "scan_group": group if mode == "resident" else None,
        "warm": warm_info or None,
        # diagnostics, not part of the metric contract: tunnel bandwidth
        # and per-transfer latency floor at measurement time (the
        # headline is transfer-bound; a slow link, not slow code, is
        # the usual cause of a high value); null when not
        # tunnel-attached
        "link_down_MBps": link_down,
        "link_up_MBps": link_up,
        "link_wait_s": link_wait,
        "lat_put_ms": lat_put_ms,
        "lat_get_ms": lat_get_ms,
        # per-batch stage seconds, pure seconds map (every backend):
        # full-year cost of a stage ~= value * 30.5; measured on an
        # 8-day batch regardless of the loop's shape, for series
        # comparability (see stage pass comment)
        "stages": stages,
        "stages_days_per_batch": 8 if stages is not None else None,
    }
    if is_cpu_fallback:
        # the fallback number is only a tunnel-down indicator; carry the
        # most recent HARDENED TPU headline (clearly stamped stale) so
        # the round artifact holds the TPU evidence too (VERDICT r3 #3)
        stale, captured = stale_tpu_headline()
        record["stale_tpu_headline"] = stale
        record["stale_tpu_captured_utc"] = captured
    print(json.dumps(record))
    tdir = os.environ.get("BENCH_TELEMETRY_DIR")
    if tdir:
        # full bundle (manifest + metrics.jsonl + Chrome trace) of
        # everything the run counted/spanned — including warmup and the
        # stage pass, which the record's measured deltas exclude
        get_telemetry().write(tdir, manifest_extra={"run_kind": "bench"})
    if not recon["ok"] \
            and os.environ.get("BENCH_STRICT_RECONCILE") == "1":
        sys.exit(18)  # record printed above; the residual is the failure


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "serve":
        sys.exit(serve_main())
    if len(sys.argv) > 1 and sys.argv[1] == "stream":
        sys.exit(stream_main())
    if len(sys.argv) > 1 and sys.argv[1] == "fleet":
        sys.exit(fleet_main())
    if len(sys.argv) > 1 and sys.argv[1] == "discover":
        sys.exit(discover_main())
    main()
