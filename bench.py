"""Headline benchmark: full CICC handbook (58 kernels) over 5000 tickers x
one trading year of minute bars, on the attached TPU chip.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "s", "vs_baseline": N}

Baseline is the north-star target of BASELINE.json:5 — the full set in
< 60 s. ``vs_baseline`` = 60 / measured (>1 means faster than target).

The reference publishes no numbers (BASELINE.md); its implied workload is
one polars pass per factor per day-file on all CPU cores.
"""

from __future__ import annotations

import contextlib
import json
import os
import subprocess
import sys
import time

import jax
import numpy as np

from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.models.registry import (
    factor_names)

N_TICKERS = 5000
DAYS_PER_BATCH = 8
TRADING_DAYS_PER_YEAR = 244
WARMUP = 1
ITERS = 5

_SUFFIX = os.environ.get("BENCH_METRIC_SUFFIX", "")


def _tunnel_alive(timeout=90, require_tpu=False):
    """One reachability probe from a killable child (a wedged tunnel
    hangs jax backend init in-process, before any code can time out).

    ``require_tpu`` additionally asserts a non-CPU platform in the
    child: if the axon backend fails FAST instead of wedging, jax falls
    back to CPU with a warning and bare ``jax.devices()`` succeeds — a
    BENCH_REQUIRE_TPU run would then time a CPU run under the
    un-suffixed TPU metric name."""
    code = ("import jax; d = jax.devices(); "
            + ("assert d[0].platform != 'cpu'" if require_tpu else "pass"))
    try:
        return subprocess.run(
            [sys.executable, "-c", code],
            timeout=timeout, capture_output=True).returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _ensure_device_reachable():
    """The attached-TPU tunnel occasionally wedges, and a wedged tunnel
    hangs the interpreter at backend init — before any code can time out.
    Probe it from a killable child; after a few failed probes, fall back
    to the host CPU with the metric renamed so the number can't be read
    as a TPU result."""
    if "PALLAS_AXON_POOL_IPS" not in os.environ:
        return  # not tunnel-attached; let jax pick its platform
    # the tunnel flaps: minutes-long down-windows with brief up-windows
    # between (observed 2026-07-31). Probe on a ~6.5 min wall-clock
    # budget (not a fixed attempt count — a fast-failing probe would
    # otherwise burn all attempts inside one down-window) so the bench
    # rides out a typical window before settling for the labeled CPU
    # fallback; that patience is cheap next to recording a fallback
    # number when a real TPU run was a minute of patience away.
    require_tpu = bool(os.environ.get("BENCH_REQUIRE_TPU"))
    deadline = time.monotonic() + 390.0
    while True:
        if _tunnel_alive(require_tpu=require_tpu):
            return
        if time.monotonic() + 30.0 >= deadline:
            break
        time.sleep(30)
    if require_tpu:
        # session-capture mode (benchmarks/tpu_session.py): a CPU
        # fallback must fail loudly, never print a metric line — the
        # session would otherwise bank it as a green headline step
        print("# BENCH_REQUIRE_TPU set and tunnel unreachable; aborting "
              "instead of CPU fallback", file=sys.stderr, flush=True)
        sys.exit(17)
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["BENCH_METRIC_SUFFIX"] = "_cpu_fallback_tunnel_down"
    # re-exec THIS script only (sys.argv could be a caller like
    # benchmarks/ladder.py, which would re-emit its earlier configs)
    os.execve(sys.executable,
              [sys.executable, os.path.abspath(__file__)], env)


class _NullTimer:
    """No-op stand-in for utils.tracing.Timer in the hot loop."""

    def __call__(self, name):
        return contextlib.nullcontext()


def make_batch(rng, n_days=DAYS_PER_BATCH, n_tickers=N_TICKERS):
    shape = (n_days, n_tickers, 240)
    close = (10.0 * np.exp(np.cumsum(
        rng.normal(0, 1e-3, shape).astype(np.float32), axis=-1)))
    open_ = close * (1 + rng.normal(0, 1e-4, shape).astype(np.float32))
    high = np.maximum(open_, close) * 1.0002
    low = np.minimum(open_, close) * 0.9998
    # board lots of 100 shares, like real A-share minute volume
    volume = (rng.integers(0, 1000, shape) * 100).astype(np.float32)
    bars = np.stack([open_, high, low, close, volume], axis=-1)
    bars[..., :4] = np.round(bars[..., :4], 2)  # tick-aligned (0.01 CNY)
    mask = rng.random(shape) > 0.02  # sparse missing bars
    return bars.astype(np.float32), mask


def probe_link(rng, nbytes=28_000_000):
    """One bandwidth sample each way, distinct bytes (see the caching
    note in main): host->device via device_put of ``nbytes``, then
    device->host sized like one batch's [F, D, T] result (~9.3 MB;
    2_325_000 u8 elements widened to f32), with a smaller warm read
    first so stream setup isn't counted as bandwidth."""
    buf = np.frombuffer(rng.bytes(nbytes), np.uint8)
    t0 = time.perf_counter()
    dev = jax.device_put(buf)
    jax.block_until_ready(dev)
    down = round(buf.nbytes / 1e6 / (time.perf_counter() - t0), 1)
    np.asarray(dev[:1_000_000].astype(np.float32))
    up = dev[:2_325_000].astype(np.float32) + np.float32(1)
    jax.block_until_ready(up)
    t0 = time.perf_counter()
    np.asarray(up)
    return down, round(up.size * 4 / 1e6 / (time.perf_counter() - t0), 1)


def measure_link(rng, threshold_mbps=20.0, threshold_up_mbps=10.0,
                 wait_budget_s=240.0, sleep_s=45.0):
    """Link probe with a bounded wait-for-weather loop.

    The tunnel's bandwidth swings >10x hour to hour. If the probe
    catches it badly degraded, wait (bounded) for a healthier window
    rather than recording link weather as the headline — the wait is
    reported in the bench JSON (``link_wait_s``), and the timed loop
    still pays whatever the link does while it runs. The budget bounds
    when a new iteration may START; the final iteration's sleep +
    reachability check + probe can run past it (on a degraded link,
    roughly one sleep + 90 s child timeout + one slow probe beyond)."""
    down, up = probe_link(rng)
    t_wait = time.monotonic()
    # budget check counts the upcoming sleep so the cap can't be
    # overshot by a whole iteration; retries reuse the full probe size
    # (a smaller payload amortizes fixed per-transfer overhead over
    # fewer bytes and would not be comparable with the first sample)
    # gate on BOTH legs: the headline result crosses device->host too,
    # and the up leg is the one observed degrading worst (3-9 MB/s while
    # down did 57 MB/s) — gating only on down would never trigger a wait
    # in exactly the documented bad-weather scenario (ADVICE r1)
    while ((down < threshold_mbps or up < threshold_up_mbps)
           and time.monotonic() - t_wait + sleep_s < wait_budget_s):
        time.sleep(sleep_s)
        # the tunnel can wedge outright while we wait; a wedged tunnel
        # hangs device_put forever, so re-check reachability from a
        # killable child before probing in-process again
        if not _tunnel_alive():
            break
        down, up = probe_link(rng)
    return down, up, round(time.monotonic() - t_wait, 1)


def _wait_host_quiet(max_wait_s=600.0):
    """Bounded wait for live CPU-busy sentinel holders (fuzz chunks, the
    test suite) to drain before timing anything: one CPU core, so a
    background sweep inflates the wall clock 5-20x. Chunked campaigns
    (tools/fuzz/run_refdiff_campaign.sh) drop the sentinel between
    chunks, so this normally returns within a few minutes."""
    try:
        from tools.cpu_busy import live_owners
    except ImportError:  # not running from a repo checkout
        return True
    me = os.getpid()
    t0 = time.monotonic()
    owners = [p for p in live_owners() if p != me]
    while owners and time.monotonic() - t0 < max_wait_s:
        print(f"# waiting for CPU-busy pids {owners} before timing",
              file=sys.stderr, flush=True)
        time.sleep(15)
        owners = [p for p in live_owners() if p != me]
    return not owners


def main():
    _ensure_device_reachable()  # may exec into a CPU-fallback run
    if os.environ.get("BENCH_REQUIRE_TPU") \
            and jax.devices()[0].platform == "cpu":
        # the probe child saw a TPU but THIS process resolved to CPU
        # (e.g. backend failed fast after the probe): refuse to print a
        # TPU-named metric from a CPU run
        print("# BENCH_REQUIRE_TPU set but jax resolved to CPU; aborting",
              file=sys.stderr, flush=True)
        sys.exit(17)
    _wait_host_quiet()
    try:
        from tools.cpu_busy import mark_busy
    except ImportError:  # not running from a repo checkout
        pass
    else:
        # hold the sentinel for the rest of the run: the tunnel watcher
        # must not fire a capture session mid-bench (two TPU workloads
        # over one tunnel + one host core would corrupt both timings) —
        # notably the driver's round-end bench run, which the watcher
        # can outlive
        mark_busy("bench headline")
    import queue
    import threading

    from replication_of_minute_frequency_factor_tpu.config import (
        apply_compilation_cache, get_config)
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_packed_prepared)

    # persistent XLA cache (when configured) turns the ~20-40s warmup
    # compile into a disk hit on repeat runs
    apply_compilation_cache(get_config())

    rng = np.random.default_rng(0)
    names = factor_names()
    iters, warmup = ITERS, WARMUP
    is_cpu_fallback = _SUFFIX == "_cpu_fallback_tunnel_down"
    if is_cpu_fallback:
        # CPU fallback specifically (not any externally set suffix): the
        # number is a tunnel-down indicator, not a TPU perf claim — one
        # warmup + two timed batches keeps the round-end run a few
        # minutes instead of ten (the per-batch -> full-year
        # extrapolation is unchanged)
        iters, warmup = 2, 1
    # one DISTINCT batch per timed iteration: the real driver never ships
    # the same bytes twice, and repeating a buffer would let any
    # content-addressed caching in the transfer path (tunnel or
    # otherwise) flatter the number — distinct batches cost nothing if
    # no such layer exists
    batches = [make_batch(rng) for _ in range(iters)]
    bars, mask = batches[0]

    use_wire = wire.encode(bars[:1], mask[:1]) is not None

    def encode_pack(b, m, t=None):
        """Host half of a step: wire-encode (C++, GIL released) + pack
        into the single transfer buffer; raw-f32 fallback when the wire
        format can't represent the batch. ``t`` (a Timer) attributes the
        two stages for the diagnostic pass — same code path either way,
        so the breakdown can never drift from what the timed loop runs."""
        ctx = t if t is not None else _NullTimer()
        with ctx("wire_encode"):
            w = wire.encode(b, m) if use_wire else None
        with ctx("pack"):
            if w is not None:
                return wire.pack_arrays(w.arrays) + ("wire",)
            return wire.pack_arrays((b, m.view(np.uint8))) + ("raw",)

    def launch(item):
        """Device half: ONE buffer over the wire -> fused on-device unpack
        + decode + 58-factor graph -> ONE stacked output tensor."""
        buf, spec, kind = item
        return compute_packed_prepared(buf, spec, kind, names=names,
                                       replicate_quirks=True)

    # warmup ships its own batches so the timed loop's bytes are cold in
    # any transfer-path cache
    warm = [make_batch(rng) for _ in range(2)]
    for _ in range(warmup):
        jax.block_until_ready(launch(encode_pack(*warm[0])))
        jax.block_until_ready(launch(encode_pack(*warm[1])))
    del warm

    # Link-quality probe, reported alongside the headline: the chip sits
    # behind a tunnel whose bandwidth swings by >10x hour to hour, and
    # the headline is transfer-bound — without these keys a slow-link
    # run is indistinguishable from a slow-code run. Distinct bytes both
    # ways (see the caching note above). Tunnel-attached runs only: on
    # the CPU fallback (or any local platform) it would time memcpy.
    link_down = link_up = link_wait = None
    if "PALLAS_AXON_POOL_IPS" in os.environ and not is_cpu_fallback:
        link_down, link_up, link_wait = measure_link(rng)

    # Steady state, double-buffered exactly like the real driver
    # (pipeline._run_device_pipeline): a producer thread encodes batch
    # i+1 while the device runs batch i, at most two batches in flight.
    # Every batch's ingest (encode+pack+transfer) is inside the timed
    # window; only its overlap with device compute is what the pipeline
    # itself would give.
    q: "queue.Queue" = queue.Queue(maxsize=2)

    def produce():
        for i in range(iters):
            q.put(encode_pack(*batches[i]))

    t0 = time.perf_counter()
    threading.Thread(target=produce, daemon=True).start()
    outs = []
    for i in range(iters):
        out = launch(q.get())
        # start the result's device->host copy immediately (as the real
        # driver does) so the slow upstream link overlaps the next
        # batch's ingest; np.asarray below then finds the bytes landed
        out.copy_to_host_async()
        outs.append(out)
        if i >= 2:
            # materialize to host like the real driver's pipeline lag
            # (pipeline.materialize): the [58, D, T] result crosses the
            # link too (~9 MB/batch), so it belongs in the wall clock
            np.asarray(outs[i - 2])
    for o in outs[-2:]:
        np.asarray(o)
    per_batch = (time.perf_counter() - t0) / iters
    full_year = per_batch * (TRADING_DAYS_PER_YEAR / DAYS_PER_BATCH)

    # Stage attribution for the CPU fallback (VERDICT r2 #7): a 600 s
    # fallback number should decompose into host-side (synth/encode/
    # pack) vs XLA-CPU compute vs result readback, so it reads as a
    # diagnostic rather than a mystery. Measured serially on one batch
    # AFTER the timed loop; skipped on TPU runs (an up-window's seconds
    # are too precious for a redundant serial pass).
    stages = None
    if is_cpu_fallback:
        from replication_of_minute_frequency_factor_tpu.utils.tracing \
            import Timer
        t = Timer()
        with t("synth_batch"):
            b, m = make_batch(np.random.default_rng(99))
        item = encode_pack(b, m, t)  # times wire_encode + pack
        with t("device_compute"):
            out = launch(item)
            jax.block_until_ready(out)
        with t("result_to_host"):
            np.asarray(out)
        stages = {k: round(v, 3) for k, v in t.totals().items()}

    target = 60.0
    print(json.dumps({
        "metric": "cicc58_5000tickers_1yr_wall" + _SUFFIX,
        "value": round(full_year, 3),
        "unit": "s",
        "vs_baseline": round(target / full_year, 3),
        # diagnostics, not part of the metric contract: tunnel bandwidth
        # at measurement time (the headline is transfer-bound; a slow
        # link, not slow code, is the usual cause of a high value);
        # null when not tunnel-attached
        "link_down_MBps": link_down,
        "link_up_MBps": link_up,
        "link_wait_s": link_wait,
        # per-batch stage seconds, fallback runs only (null on TPU):
        # full-year cost of a stage ~= value * 30.5 batches
        "stages": stages,
    }))


if __name__ == "__main__":
    main()
