"""The evented binary front door (ISSUE 20): keep-alive connection
multiplexing, the result wire carried end to end, chunked range
streaming, per-tenant admission quotas, and the robustness ladder
(malformed requests, slow loris, mid-response disconnects).

Runs under ``jax.transfer_guard("disallow")``
(conftest.TRANSFER_GUARDED_MODULES): the edge hands HOST bytes only —
the device fetch happens on the server's worker threads at the
declared ``serve/service.py`` boundary, never on the loop, aux or
client thread.
"""

import json
import socket
import time

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.fleet import (
    FactorFleet, FleetConfig, serve_fleet_frontdoor)
from replication_of_minute_frequency_factor_tpu.serve import (
    FactorServer, Query, ServeConfig, SyntheticSource, WireClient,
    WireError, serve_edge, serve_frontdoor)
from replication_of_minute_frequency_factor_tpu.serve.edge import (
    EdgeServer, ServerEdgeBackend)
from replication_of_minute_frequency_factor_tpu.serve.http import (
    WIRE_CONTENT_TYPE)
from replication_of_minute_frequency_factor_tpu.telemetry import Telemetry

NAMES = ("vol_return1min", "mmt_am", "liq_openvol")


def _server(n_days=8, n_tickers=32, names=NAMES, start=True, **scfg):
    tel = Telemetry()
    src = SyntheticSource(n_days=n_days, n_tickers=n_tickers, seed=3)
    srv = FactorServer(src, names=names, telemetry=tel,
                       serve_cfg=ServeConfig(**scfg), start=start)
    return srv, tel


def _connect(door):
    host, port = door.server_address[:2]
    sock = socket.create_connection((host, port), timeout=30)
    sock.settimeout(30)
    return sock


def _request_bytes(method, path, body=b"", headers=()):
    head = [f"{method} {path} HTTP/1.1", "Host: edge"]
    head += [f"{k}: {v}" for k, v in headers]
    if body or method == "POST":
        head.append(f"Content-Length: {len(body)}")
    return ("\r\n".join(head) + "\r\n\r\n").encode("latin-1") + body


def _read_response(sock, buf=b""):
    """One buffered HTTP response off ``sock`` ->
    ``(status, headers, body, leftover)`` — leftover carries any bytes
    of the NEXT pipelined response already received."""
    while b"\r\n\r\n" not in buf:
        data = sock.recv(65536)
        if not data:
            raise ConnectionError("peer closed before headers")
        buf += data
    head, _, rest = buf.partition(b"\r\n\r\n")
    lines = head.decode("latin-1").split("\r\n")
    status = int(lines[0].split()[1])
    headers = {}
    for line in lines[1:]:
        key, _, value = line.partition(":")
        headers[key.strip().lower()] = value.strip()
    length = int(headers.get("content-length", "0"))
    while len(rest) < length:
        data = sock.recv(65536)
        if not data:
            raise ConnectionError("peer closed mid-body")
        rest += data
    return status, headers, rest[:length], rest[length:]


def _wait_counter(reg, name, minimum=1.0, deadline_s=30.0, **labels):
    t0 = time.monotonic()
    while time.monotonic() - t0 < deadline_s:
        value = (reg.counter_value(name, **labels) if labels
                 else reg.counter_total(name))
        if value >= minimum:
            return value
        time.sleep(0.02)
    raise AssertionError(f"counter {name} never reached {minimum}")


# --------------------------------------------------------------------------
# the result wire end to end
# --------------------------------------------------------------------------


def test_http_wire_answer_byte_identical_to_host_dequantize():
    """The ISSUE 20 acceptance gate, tier-1: the packed payload the
    edge ships is the SAME buffer the in-process wire answer carries,
    so the HTTP client's dequantize and the host-side dequantize of
    the in-process answer agree BYTE for byte."""
    srv, _tel = _server()
    door = serve_edge(srv)
    cli = WireClient(*door.server_address[:2])
    try:
        http_out, meta = cli.query_wire(0, 8)
        inproc_out, inproc_meta = srv.client().factors_wire(0, 8)
        assert http_out.dtype == np.float32
        assert http_out.shape == (len(NAMES), 8, 32)
        assert http_out.tobytes() == inproc_out.tobytes()
        assert meta["payload_bytes"] == inproc_meta["payload_bytes"]
        # the exposure block was served from the SAME cached entry
        assert meta["n_factors"] == len(NAMES)
    finally:
        cli.close()
        door.shutdown()
        srv.close()


def test_wire_answers_reuse_one_connection():
    """Keep-alive is the default: any number of wire answers ride one
    TCP connection — exactly one ``edge.conns_opened`` for the whole
    cycle."""
    srv, tel = _server()
    door = serve_edge(srv)
    cli = WireClient(*door.server_address[:2])
    try:
        for _ in range(5):
            cli.query_wire(0, 4)
        reg = tel.registry
        assert reg.counter_total("edge.conns_opened") == 1
        assert reg.counter_value("edge.answers", encoding="wire") == 5
    finally:
        cli.close()
        door.shutdown()
        srv.close()


def test_chunked_stream_reassembles_to_buffered():
    """A ``chunk_days`` range answer streams >= 2 framed chunks and
    reassembles byte-identically to the buffered answer for the same
    range; a chunk size covering the whole range stays buffered."""
    srv, tel = _server()
    door = serve_edge(srv)
    cli = WireClient(*door.server_address[:2])
    try:
        buffered, _ = cli.query_wire(0, 8)
        chunked, meta = cli.query_wire(0, 8, chunk_days=2)
        assert meta["frames"] == 4
        assert meta["ranges"] == [(0, 2), (2, 4), (4, 6), (6, 8)]
        assert chunked.tobytes() == buffered.tobytes()
        assert tel.registry.counter_total("edge.chunks") == 4
        whole, meta1 = cli.query_wire(0, 4, chunk_days=8)
        assert meta1["frames"] == 1
        assert whole.tobytes() == buffered[:, :4, :].tobytes()
    finally:
        cli.close()
        door.shutdown()
        srv.close()


def test_chunking_requires_wire_factors():
    """``chunk_days`` outside its contract is a clean 400: JSON accept
    (no frame format to stream) and negative values both refuse."""
    srv, _tel = _server()
    door = serve_edge(srv)
    cli = WireClient(*door.server_address[:2])
    try:
        status, _hdrs, body = cli.post_json(
            "/v1/query",
            {"kind": "factors", "start": 0, "end": 4, "chunk_days": 2})
        assert status == 400
        assert "chunk_days" in json.loads(body)["error"]
        status, _hdrs, body = cli.post_json(
            "/v1/query",
            {"kind": "factors", "start": 0, "end": 4,
             "chunk_days": -1},
            headers={"Accept": WIRE_CONTENT_TYPE})
        assert status == 400
    finally:
        cli.close()
        door.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# parity with the legacy door
# --------------------------------------------------------------------------


def test_get_surface_parity_legacy_vs_edge():
    """Both doors answer the whole GET surface through the SAME
    :func:`serve.http.get_payload`: status, content type and document
    shape agree endpoint by endpoint."""
    srv, _tel = _server()
    legacy = serve_frontdoor(srv, transport="legacy")
    edge = serve_frontdoor(srv, transport="edge")
    lcli = WireClient(*legacy.server_address[:2])
    ecli = WireClient(*edge.server_address[:2])
    try:
        for path in ("/healthz", "/v1/factors", "/v1/metrics",
                     "/v1/slo", "/v1/timeline?window=60"):
            ls, _lh, lbody = lcli.request("GET", path)
            es, _eh, ebody = ecli.request("GET", path)
            assert ls == es == 200, path
            ldoc, edoc = json.loads(lbody), json.loads(ebody)
            assert type(ldoc) is type(edoc), path
            if isinstance(ldoc, dict):
                # counters move between the two calls (the doors share
                # one registry); the SHAPE may not
                assert set(ldoc) == set(edoc), path
        for cli in (lcli, ecli):
            status, _h, body = cli.request("GET", "/nope")
            assert status == 404
            assert "error" in json.loads(body)
    finally:
        lcli.close()
        ecli.close()
        legacy.shutdown()
        legacy.server_close()
        edge.shutdown()
        srv.close()


def test_query_json_parity_and_trace_id_round_trip():
    """The same JSON query through both doors returns the same
    exposures, and an ``X-Trace-Id`` echoes back verbatim from both."""
    srv, _tel = _server()
    legacy = serve_frontdoor(srv, transport="legacy")
    edge = serve_frontdoor(srv, transport="edge")
    lcli = WireClient(*legacy.server_address[:2])
    ecli = WireClient(*edge.server_address[:2])
    doc = {"kind": "factors", "start": 0, "end": 4,
           "names": [NAMES[0]]}
    try:
        ls, lh, lbody = lcli.post_json(
            "/v1/query", doc, headers={"X-Trace-Id": "edge-parity-1"})
        es, eh, ebody = ecli.post_json(
            "/v1/query", doc, headers={"X-Trace-Id": "edge-parity-2"})
        assert ls == es == 200
        assert lh.get("x-trace-id") == "edge-parity-1"
        assert eh.get("x-trace-id") == "edge-parity-2"
        lexp = json.loads(lbody)["exposures"][NAMES[0]]
        eexp = json.loads(ebody)["exposures"][NAMES[0]]
        np.testing.assert_array_equal(np.asarray(lexp),
                                      np.asarray(eexp))
        # the wire negotiation answers the legacy door too — the
        # payload bytes agree with the edge's
        ls, lh, lbody = lcli.post_json(
            "/v1/query", {"kind": "factors", "start": 0, "end": 4},
            headers={"Accept": WIRE_CONTENT_TYPE})
        es, eh, ebody = ecli.post_json(
            "/v1/query", {"kind": "factors", "start": 0, "end": 4},
            headers={"Accept": WIRE_CONTENT_TYPE})
        assert ls == es == 200
        assert lh["content-type"] == eh["content-type"] \
            == WIRE_CONTENT_TYPE
        assert lbody == ebody
    finally:
        lcli.close()
        ecli.close()
        legacy.shutdown()
        legacy.server_close()
        edge.shutdown()
        srv.close()


def test_frontdoor_transport_selection():
    """``ServeConfig.edge`` picks the door; an unknown transport is a
    loud ValueError, not a silent fallback."""
    srv, _tel = _server(edge="legacy")
    try:
        door = serve_frontdoor(srv)
        assert not isinstance(door, EdgeServer)
        door.shutdown()
        door.server_close()
        door = serve_frontdoor(srv, transport="edge")
        assert isinstance(door, EdgeServer)
        door.shutdown()
        with pytest.raises(ValueError):
            serve_frontdoor(srv, transport="carrier-pigeon")
    finally:
        srv.close()


# --------------------------------------------------------------------------
# multiplexing
# --------------------------------------------------------------------------


def test_pipelined_requests_flush_in_request_order():
    """A client may write request N+1 before answer N arrives; the
    edge dispatches both but flushes strictly in request order — a
    wire query then a healthz GET written back-to-back come back as
    (wire, json) in that order on one connection."""
    srv, tel = _server()
    door = serve_edge(srv)
    sock = _connect(door)
    try:
        body = json.dumps({"kind": "factors", "start": 0,
                           "end": 4}).encode()
        sock.sendall(_request_bytes(
            "POST", "/v1/query", body,
            headers=[("Content-Type", "application/json"),
                     ("Accept", WIRE_CONTENT_TYPE)])
            + _request_bytes("GET", "/healthz"))
        s1, h1, b1, rest = _read_response(sock)
        s2, h2, b2, _ = _read_response(sock, rest)
        assert s1 == 200 and h1["content-type"] == WIRE_CONTENT_TYPE
        assert s2 == 200 and "json" in h2["content-type"]
        assert json.loads(b2)["factors"] == len(NAMES)
        assert tel.registry.counter_total("edge.conns_opened") == 1
    finally:
        sock.close()
        door.shutdown()
        srv.close()


def test_connection_close_honored_after_final_answer():
    """``Connection: close`` still answers the request, then drops the
    connection once the response has flushed."""
    srv, _tel = _server()
    door = serve_edge(srv)
    sock = _connect(door)
    try:
        sock.sendall(_request_bytes("GET", "/healthz",
                                    headers=[("Connection", "close")]))
        status, _headers, body, _rest = _read_response(sock)
        assert status == 200
        assert json.loads(body)["ok"] is True
        assert sock.recv(4096) == b""  # flushed, then dropped
    finally:
        sock.close()
        door.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# robustness: malformed input, slow loris, disconnects
# --------------------------------------------------------------------------


def test_malformed_request_line_is_400_and_close():
    srv, tel = _server()
    door = serve_edge(srv)
    try:
        for raw in (b"GARBAGE\r\n\r\n",
                    b"GET /healthz HTTP/2.0\r\n\r\n",
                    b"GET /healthz HTTP/1.1\r\nbroken line\r\n\r\n",
                    b"POST /v1/query HTTP/1.1\r\n"
                    b"Content-Length: banana\r\n\r\n",
                    b"POST /v1/query HTTP/1.1\r\n"
                    b"Transfer-Encoding: chunked\r\n\r\n"):
            sock = _connect(door)
            try:
                sock.sendall(raw)
                status, _h, body, _rest = _read_response(sock)
                assert status in (400, 505), raw
                assert "error" in json.loads(body)
                assert sock.recv(4096) == b""  # malformation closes
            finally:
                sock.close()
        assert tel.registry.counter_total("edge.http_errors") >= 5
    finally:
        door.shutdown()
        srv.close()


def test_truncated_json_body_is_400_but_keeps_the_connection():
    """A syntactically complete request with an undecodable body is
    the CLIENT's bug, not a protocol breakdown: 400, connection stays
    usable for the next request."""
    srv, _tel = _server()
    door = serve_edge(srv)
    sock = _connect(door)
    try:
        sock.sendall(_request_bytes(
            "POST", "/v1/query", b'{"kind": "fac',
            headers=[("Content-Type", "application/json")]))
        status, _h, body, rest = _read_response(sock)
        assert status == 400
        assert "malformed" in json.loads(body)["error"]
        sock.sendall(_request_bytes("GET", "/healthz"))
        status, _h, _body, _rest = _read_response(sock, rest)
        assert status == 200
    finally:
        sock.close()
        door.shutdown()
        srv.close()


def test_oversized_body_is_413():
    srv, _tel = _server()
    door = serve_edge(srv)
    sock = _connect(door)
    try:
        sock.sendall(b"POST /v1/query HTTP/1.1\r\n"
                     b"Content-Length: 99999999\r\n\r\n")
        status, _h, _body, _rest = _read_response(sock)
        assert status == 413
    finally:
        sock.close()
        door.shutdown()
        srv.close()


def test_slow_loris_is_reaped_by_the_idle_timeout():
    """A peer that dribbles half a request forever is reaped after
    ``idle_timeout_s`` — never parked on a blocked thread — and the
    door keeps serving everyone else."""
    srv, tel = _server()
    door = EdgeServer(ServerEdgeBackend(srv), idle_timeout_s=0.3,
                      tick_s=0.05)
    sock = _connect(door)
    cli = WireClient(*door.server_address[:2])
    try:
        sock.sendall(b"POST /v1/query HTTP/1.1\r\nCont")
        assert sock.recv(4096) == b""  # reaped, no response owed
        _wait_counter(tel.registry, "edge.conns_closed", reason="idle")
        out, _meta = cli.query_wire(0, 4)
        assert out.shape == (len(NAMES), 4, 32)
    finally:
        cli.close()
        sock.close()
        door.shutdown()
        srv.close()


def test_in_flight_dispatch_is_never_reaped_as_idle():
    """The idle reaper only fires on connections with NO answer in
    flight: a request the server is still computing keeps its
    connection alive past the timeout."""
    srv, tel = _server(start=False)  # queue paused: answers pend
    door = EdgeServer(ServerEdgeBackend(srv), idle_timeout_s=0.2,
                      tick_s=0.05)
    sock = _connect(door)
    try:
        body = json.dumps({"kind": "factors", "start": 0,
                           "end": 4}).encode()
        sock.sendall(_request_bytes(
            "POST", "/v1/query", body,
            headers=[("Content-Type", "application/json")]))
        time.sleep(0.8)  # several timeouts with the dispatch in flight
        assert tel.registry.counter_value("edge.conns_closed",
                                          reason="idle") == 0
        srv.start()
        status, _h, _body, _rest = _read_response(sock)
        assert status == 200
    finally:
        sock.close()
        door.shutdown()
        srv.close()


def test_mid_response_disconnect_orphans_the_answer():
    """A client that vanishes mid-request is reaped when the loop sees
    EOF; its in-flight answer resolves into ``edge.orphan_answers``
    (the worker never blocks on the dead socket) and the door keeps
    serving."""
    srv, tel = _server(start=False)  # paused: the answer can't win
    door = serve_edge(srv)
    sock = _connect(door)
    body = json.dumps({"kind": "factors", "start": 0,
                       "end": 4}).encode()
    sock.sendall(_request_bytes(
        "POST", "/v1/query", body,
        headers=[("Content-Type", "application/json")]))
    time.sleep(0.1)  # let the loop parse + dispatch
    sock.close()     # vanish before the answer exists
    try:
        _wait_counter(tel.registry, "edge.conns_closed",
                      reason="peer_closed")
        srv.start()  # now the answer completes — into a dead slot
        _wait_counter(tel.registry, "edge.orphan_answers")
        cli = WireClient(*door.server_address[:2])
        try:
            out, _meta = cli.query_wire(0, 4)
            assert out.shape == (len(NAMES), 4, 32)
        finally:
            cli.close()
    finally:
        door.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# per-tenant admission quotas
# --------------------------------------------------------------------------


def test_tenant_quota_429_retry_after_and_isolation():
    """Token buckets meter PER TENANT: exhausting one tenant's burst
    answers 429 + Retry-After + the ``quota`` marker (the shed
    contract's mirror) while another tenant is untouched; GETs are
    never metered."""
    srv, tel = _server(tenant_quota_rps=0.2, tenant_quota_burst=2.0)
    door = serve_edge(srv)
    a = WireClient(*door.server_address[:2], tenant="tenant-a")
    b = WireClient(*door.server_address[:2], tenant="tenant-b")
    try:
        a.query_wire(0, 4)
        a.query_wire(0, 4)
        with pytest.raises(WireError) as err:
            a.query_wire(0, 4)
        assert err.value.status == 429
        assert err.value.retry_after is not None
        assert err.value.retry_after >= 1.0
        assert err.value.doc.get("quota") is True
        out, _meta = b.query_wire(0, 4)  # b's bucket is its own
        assert out.shape == (len(NAMES), 4, 32)
        status, _h, _body = a.request("GET", "/healthz")
        assert status == 200  # the GET surface is not metered
        assert tel.registry.counter_value("edge.quota_rejected",
                                          tenant="tenant-a") == 1.0
    finally:
        a.close()
        b.close()
        door.shutdown()
        srv.close()


def test_quota_off_by_default():
    srv, _tel = _server()
    door = serve_edge(srv)
    cli = WireClient(*door.server_address[:2], tenant="anyone")
    try:
        for _ in range(8):
            cli.query_wire(0, 4)
    finally:
        cli.close()
        door.shutdown()
        srv.close()


# --------------------------------------------------------------------------
# the fleet's pod door
# --------------------------------------------------------------------------


def test_fleet_edge_carries_wire_through_the_router():
    """The pod front door rides the same edge: wire answers through
    the router's replica leg byte-identical between the edge and
    legacy pod doors, with ``fleet.routed_wire`` counting the encoding
    on the routed hop and the pod healthz intact."""
    src = SyntheticSource(n_days=8, n_tickers=24, seed=3)
    fleet = FactorFleet(src, 2, names=NAMES[:2],
                        serve_cfg=ServeConfig(),
                        fleet_cfg=FleetConfig())
    edge = serve_fleet_frontdoor(fleet, transport="edge")
    legacy = serve_fleet_frontdoor(fleet, transport="legacy")
    ecli = WireClient(*edge.server_address[:2])
    lcli = WireClient(*legacy.server_address[:2])
    try:
        eout, emeta = ecli.query_wire(0, 8)
        lout, _lmeta = lcli.query_wire(0, 8)
        assert eout.shape == (2, 8, 24)
        assert eout.tobytes() == lout.tobytes()
        chunked, meta = ecli.query_wire(0, 8, chunk_days=4)
        assert meta["frames"] == 2
        assert chunked.tobytes() == eout.tobytes()
        preg = fleet.telemetry.registry
        assert preg.counter_total("fleet.routed_wire") >= 4
        status, _h, body = ecli.request("GET", "/healthz")
        assert status == 200
        assert json.loads(body)["pod"]["live"] == 2
    finally:
        ecli.close()
        lcli.close()
        edge.shutdown()
        legacy.shutdown()
        legacy.server_close()
        fleet.close()
