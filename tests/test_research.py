"""research/ — the distributed factor-discovery engine (ISSUE 14).

Coverage map:

* the fused generation graph's fitness column is BITWISE the existing
  ``search.fitness`` (evaluation is shared, the backtest extras ride
  the same module);
* population-sharded == single-device on the 8-virtual-device mesh
  (finite counts + device top-k selection bitwise, moments
  ulp-pinned), including non-dividing populations whose padding rows
  must never be selected;
* the loop's measured contract: exactly ONE labeled host-blocking
  sync per generation and ZERO compiles during the generation loop,
  both counter-asserted under ``jax.transfer_guard`` (this module is
  in ``conftest.TRANSFER_GUARDED_MODULES``);
* the registry: content-addressed names, persisted records
  round-tripping through ``search.describe``, corrupted records
  refused, registered kernels computing next to the built-ins;
* the serve integration end to end on CPU: ``research=True`` +
  ``POST /v1/discover`` registers a factor, ``GET /v1/factors`` lists
  it, and ``/v1/query`` answers for it match a host re-evaluation of
  the PERSISTED genome within f32 tolerance — the acceptance demo.
"""

import json
import urllib.request

import jax
import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import search
from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
    resident_mesh)
from replication_of_minute_frequency_factor_tpu.research import (
    DiscoveryEngine, genome_name, host_forward_returns, load_record,
    register_genome)
from replication_of_minute_frequency_factor_tpu.research import (
    fitness as research_fitness)
from replication_of_minute_frequency_factor_tpu.research.evolve import (
    resolve_skeleton)
from replication_of_minute_frequency_factor_tpu.serve import (
    FactorServer, Query, ServeConfig, SyntheticSource, serve_http)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    Telemetry, set_telemetry)


def _day_data(days=5, tickers=12, seed=0, horizon=1):
    rng = np.random.default_rng(seed)
    shape = (days, tickers, 240)
    close = 10.0 * np.exp(np.cumsum(
        rng.standard_normal(shape, dtype=np.float32)
        * np.float32(1e-3), axis=-1))
    open_ = close * (1 + rng.standard_normal(shape, dtype=np.float32)
                     * np.float32(1e-4))
    bars = np.stack([open_, np.maximum(open_, close) * 1.0002,
                     np.minimum(open_, close) * 0.9998, close,
                     (rng.integers(0, 1000, shape) * 100.0
                      ).astype(np.float32)], axis=-1).astype(np.float32)
    mask = rng.random(shape) > 0.05
    fwd_ret, fwd_valid = host_forward_returns(bars, mask, horizon)
    return bars, mask, fwd_ret, fwd_valid


@pytest.fixture()
def tel():
    return set_telemetry(Telemetry())


# --------------------------------------------------------------------------
# the fused generation graph
# --------------------------------------------------------------------------


@pytest.mark.transfers
def test_fused_fitness_column_matches_search_fitness(tel):
    """Column 0 of the generation stats is bitwise ``search.fitness``:
    the IC/decile extras fused into the module must not perturb the
    selection scalar the GA already trusted."""
    bars, mask, fr, fv = _day_data()
    genomes = search.random_population(np.random.default_rng(2), 12)
    stats, _tv, _ti = research_fitness.generation_fitness(
        genomes, bars, mask, fr, fv, chunk=6, n_elite=3)
    ref = search.fitness(genomes, bars, mask, fr, fv, chunk=6)
    stats, ref = np.asarray(stats), np.asarray(ref)
    assert np.array_equal(np.nan_to_num(stats[:, 0], nan=-1.0),
                          np.nan_to_num(ref, nan=-1.0))
    # fitness IS |mean_ic| where defined
    fin = np.isfinite(stats[:, 1])
    assert np.array_equal(stats[fin, 0], np.abs(stats[fin, 1]))


@pytest.mark.transfers
def test_generation_stats_components_match_unfused(tel):
    """The rank-IC and decile-spread columns equal the standalone
    eval_ops computations on the candidate's exposures (the fused
    module reuses — not reimplements — the production stats)."""
    from replication_of_minute_frequency_factor_tpu.eval_ops import (
        decile_spread, ic_series)
    bars, mask, fr, fv = _day_data(seed=3)
    genomes = search.random_population(np.random.default_rng(4), 4)
    stats = np.asarray(research_fitness.generation_stats(
        genomes, bars, mask, fr, fv, search.DEFAULT_SKELETON, 5, None))
    vals = np.asarray(search.eval_programs(genomes, bars, mask))
    for i in range(len(genomes)):
        valid = np.isfinite(vals[i]) & fv
        x = np.where(valid, vals[i], 0.0).astype(np.float32)
        y = np.where(valid, fr, 0.0).astype(np.float32)
        ic, rank_ic = ic_series(x, y, valid)
        np.testing.assert_allclose(
            stats[i, 2], np.nanmean(np.asarray(rank_ic)),
            rtol=1e-5, atol=1e-7)
        spr = np.asarray(decile_spread(
            jax.numpy.asarray(vals[i]), jax.numpy.asarray(fr),
            jax.numpy.asarray(valid), 5))
        np.testing.assert_allclose(
            stats[i, 3], np.nanmean(spr), rtol=1e-5, atol=1e-7)


@pytest.mark.transfers
def test_sharded_generation_matches_single_device(tel):
    """Population-sharded over the 8-virtual-device mesh vs
    single-device at MATCHED chunk structure (chunk=1 on both — the
    per-candidate module bodies are then the same shape): the full
    stats matrix and the device top-k selection are BITWISE equal,
    including a NON-dividing population (pop=10 over 8 shards) whose
    padding rows must never be selected.

    The matched chunk matters: different chunk extents fuse the
    per-candidate body differently (ulp-level exposure drift, the
    vol_upRatio class), and the discrete statistics downstream — rank
    IC with T=12 lanes, qcut buckets of 2-3 tickers — amplify one
    exposure ulp at a tie into an O(1/T) stat jump. The engine pins
    its chunk into the executable key for exactly this reason (a
    resumed search must reproduce bitwise)."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    bars, mask, fr, fv = _day_data(seed=5)
    pop = 10
    n_dev = len(jax.devices())
    assert n_dev == 8
    genomes = search.random_population(np.random.default_rng(6), pop)
    s1, tv1, ti1 = research_fitness.generation_fitness(
        genomes, bars, mask, fr, fv, chunk=1, n_elite=4)
    mesh = resident_mesh(n_dev)
    pad = -pop % n_dev
    gp = np.concatenate(
        [genomes, np.zeros((pad, genomes.shape[1]), np.int32)])
    rep = NamedSharding(mesh, P())
    ss, tvs, tis = research_fitness.generation_fitness_sharded(
        jax.device_put(gp, NamedSharding(mesh, P("tickers", None))),
        jax.device_put(bars, rep), jax.device_put(mask, rep),
        jax.device_put(fr, rep), jax.device_put(fv, rep), mesh=mesh,
        skeleton=search.DEFAULT_SKELETON, group_num=5, chunk=1,
        n_elite=4, n_pop=pop)
    s1 = np.asarray(s1)
    ss = np.asarray(ss)[:pop]
    assert np.array_equal(np.isfinite(s1), np.isfinite(ss))
    assert np.array_equal(np.nan_to_num(s1), np.nan_to_num(ss))
    np.testing.assert_array_equal(np.asarray(ti1), np.asarray(tis))
    np.testing.assert_array_equal(np.asarray(tv1), np.asarray(tvs))
    assert np.all(np.asarray(tis) < pop)  # padding never selected


def test_evolve_sync_budget_and_zero_compiles(tel):
    """The acceptance counters, asserted at the engine level under the
    transfer guard: exactly ONE measured host-blocking sync per
    generation (the labeled ``research.host_blocking_syncs`` counter)
    and ZERO ``xla.compiles`` during the generation loop."""
    bars, mask, fr, fv = _day_data(seed=7)
    eng = DiscoveryEngine(telemetry=tel)
    data = eng.prepare(bars, mask, fr, fv)
    eng.warmup(data, 12)
    reg = tel.registry
    syncs0 = reg.counter_value("research.host_blocking_syncs",
                               point="generation_fetch")
    compiles0 = reg.counter_total("xla.compiles")
    res = eng.evolve(data, pop=12, generations=4,
                     rng=np.random.default_rng(8))
    assert reg.counter_value("research.host_blocking_syncs",
                             point="generation_fetch") - syncs0 == 4
    assert reg.counter_total("xla.compiles") == compiles0
    assert res.syncs_per_generation == 1.0
    assert res.compiles_during_loop == 0
    assert res.generations == 4
    assert len(res.history) == 4
    # the final generation's device top-k agrees with host selection
    tv, ti = res.device_topk
    assert np.asarray(ti).shape[0] >= 2


def test_evolve_deterministic_under_explicit_rng(tel):
    """Identical generator state -> identical discovered genome AND
    identical content-addressed name, across engine instances (the
    reproducibility contract)."""
    bars, mask, fr, fv = _day_data(seed=9)
    out = []
    for _ in range(2):
        eng = DiscoveryEngine(telemetry=tel)
        data = eng.prepare(bars, mask, fr, fv)
        res = eng.evolve(data, pop=14, generations=3,
                         rng=np.random.default_rng(42))
        out.append(res)
    assert np.array_equal(out[0].genome, out[1].genome)
    assert out[0].fitness == out[1].fitness
    assert (genome_name(out[0].genome)
            == genome_name(out[1].genome))
    assert out[0].fingerprint == out[1].fingerprint


@pytest.mark.transfers
def test_search_evolve_threads_explicit_rng(tel):
    """ISSUE 14 determinism fix in search.py: an explicit generator
    reproduces ``seed``'s result exactly, and two searches with
    identically-seeded generators agree genome-for-genome."""
    bars, mask, fr, fv = _day_data(seed=10, days=4, tickers=8)
    a = search.evolve(bars, mask, fr, fv, pop=10, generations=2,
                      seed=5)
    b = search.evolve(bars, mask, fr, fv, pop=10, generations=2,
                      rng=np.random.default_rng(5))
    assert np.array_equal(a.genome, b.genome)
    assert a.fitness == b.fitness
    np.testing.assert_array_equal(a.history, b.history)


# --------------------------------------------------------------------------
# the registry
# --------------------------------------------------------------------------


def test_registry_roundtrip_and_describe(tmp_path, tel):
    g = search.random_population(np.random.default_rng(11), 1)[0]
    rec = register_genome(g, fitness=0.4, mean_ic=-0.4, spread=0.01,
                          generations=3, pop=16,
                          data_fingerprint="abc123",
                          save_dir=str(tmp_path), telemetry=tel)
    assert rec.name.startswith("disc_") and len(rec.name) == 15
    assert rec.description == search.describe(g)
    back = load_record(str(tmp_path / f"{rec.name}.json"))
    assert back.name == rec.name
    assert back.genome == rec.genome
    assert back.description == rec.description
    assert back.data_fingerprint == "abc123"
    # registration is idempotent on the content-addressed name
    again = register_genome(g, telemetry=tel)
    assert again.name == rec.name


def test_registry_refuses_corrupted_records(tmp_path, tel):
    g = search.random_population(np.random.default_rng(12), 1)[0]
    rec = register_genome(g, save_dir=str(tmp_path), telemetry=tel)
    path = str(tmp_path / f"{rec.name}.json")
    doc = json.load(open(path))
    doc["genome"][0] = (doc["genome"][0] + 1) % 12
    json.dump(doc, open(path, "w"))
    with pytest.raises(ValueError, match="hashes to"):
        load_record(path)


@pytest.mark.transfers
def test_registered_kernel_computes_next_to_builtins(tel):
    """A discovered factor computes through the normal
    ``compute_factors`` path (DayContext + alias resolution), matching
    the jitted evaluator bitwise."""
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        compute_factors_jit)
    bars, mask, _fr, _fv = _day_data(seed=13)
    g = search.random_population(np.random.default_rng(14), 1)[0]
    rec = register_genome(g, telemetry=tel)
    out = compute_factors_jit(bars, mask,
                              names=("vol_return1min", rec.name))
    got = np.asarray(out[rec.name])
    import functools
    ref = np.asarray(jax.jit(functools.partial(
        search.eval_programs, skeleton=search.DEFAULT_SKELETON))(
            np.asarray(rec.genome, np.int32)[None], bars, mask)[0])
    np.testing.assert_array_equal(got, ref)


def test_resolve_skeleton_names():
    assert resolve_skeleton("default") == search.DEFAULT_SKELETON
    assert resolve_skeleton("rich") == search.RICH_SKELETON
    assert resolve_skeleton((0, 1)) == (0, 1)
    with pytest.raises(ValueError, match="unknown skeleton"):
        resolve_skeleton("nope")


# --------------------------------------------------------------------------
# serve integration — the CPU acceptance demo
# --------------------------------------------------------------------------


def _research_server(tmp_path, tel, **kw):
    src = SyntheticSource(n_days=10, n_tickers=24, seed=21)
    scfg = ServeConfig(research_dir=str(tmp_path),
                       hbm_sample_period_s=0)
    return src, FactorServer(src, names=("vol_return1min", "mmt_am"),
                             serve_cfg=scfg, telemetry=tel,
                             research=True, **kw)


def test_serve_discover_end_to_end(tmp_path, tel):
    """The acceptance demo: a research server discovers a factor on
    the request queue, registers it, persists its genome record, and
    answers ``/v1/query`` for the new name with exposures matching a
    host re-evaluation of the PERSISTED genome within f32 tolerance —
    with the generation loop's sync/compile counters asserted."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    src, server = _research_server(tmp_path, tel)
    try:
        reg = tel.registry
        syncs0 = reg.counter_value("research.host_blocking_syncs",
                                   point="generation_fetch")
        ans = server.discover(0, 8, generations=3, pop=24,
                              seed=7).result(600)
        # the loop's measured contract, from the answer AND the registry
        assert ans["generations"] == 3
        assert ans["syncs_per_generation"] == 1.0
        assert ans["compiles_during_loop"] == 0
        assert reg.counter_value("research.host_blocking_syncs",
                                 point="generation_fetch") - syncs0 == 3
        name = ans["name"]
        assert name.startswith("disc_")
        assert name in server.names
        fl = server.factor_list()
        assert fl["builtin"] == ["vol_return1min", "mmt_am"]
        assert name in fl["discovered"]
        assert fl["research"] is True
        # the persisted record round-trips and re-derives the answer
        rec = load_record(ans["record_path"])
        assert rec.description == ans["describe"]
        assert rec.fitness == pytest.approx(ans["fitness"])
        # /v1/query for the discovered name: parity vs a host
        # re-evaluation of the PERSISTED genome, built entirely from
        # the record + the source slab (no serve machinery): the same
        # ingest-wire encode the block graph consumes, decoded and
        # evaluated in one fused module. Observed bitwise; asserted at
        # f32 tolerance (module-shape fusion may move ulps). A
        # raw-bars re-evaluation differs by the wire's quantization
        # envelope (~1e-4 here) — the wire, not the genome, owns that
        # gap (docs/discovery.md).
        q = server.submit(Query("factors", 0, 8,
                                names=(name,))).result(120)
        got = np.asarray(q["exposures"][name], dtype=np.float32)
        bars, mask = src.slab(0, 8)
        w = wire.encode(bars, mask)
        buf, spec = wire.pack_arrays(w.arrays)
        genome_dev = jax.device_put(
            np.ascontiguousarray(rec.genome, np.int32)[None])
        skeleton = tuple(rec.skeleton)

        def host_reeval(packed):
            arrs = wire.unpack(packed, spec)
            b, m = wire.decode(*arrs)
            return search.eval_programs(genome_dev, b,
                                        m.astype(bool), skeleton)
        ref = np.asarray(jax.jit(host_reeval)(
            jax.device_put(buf)))[0]
        assert np.array_equal(np.isnan(got), np.isnan(ref))
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
        # IC/decile queries work on the discovered name too
        ic = server.submit(Query("ic", 0, 8, factor=name)).result(120)
        assert ic["factor"] == name
        assert isinstance(ic["mean_ic"], float)
    finally:
        server.close()


def test_serve_discover_idempotent_and_cache_invalidation(tmp_path,
                                                          tel):
    """The same seed re-discovers the same genome -> same name, no
    duplicate registration; block queries before and after discovery
    both answer (the exposure cache invalidates cleanly on the [F]
    extent change)."""
    _src, server = _research_server(tmp_path, tel)
    try:
        before = server.submit(Query("factors", 0, 8,
                                     names=("mmt_am",))).result(120)
        assert "mmt_am" in before["exposures"]
        a = server.discover(0, 8, generations=2, pop=16,
                            seed=3).result(600)
        b = server.discover(0, 8, generations=2, pop=16,
                            seed=3).result(600)
        assert a["name"] == b["name"]
        assert list(server.names).count(a["name"]) == 1
        after = server.submit(Query("factors", 0, 8,
                                    names=("mmt_am",
                                           a["name"]))).result(120)
        assert set(after["exposures"]) == {"mmt_am", a["name"]}
    finally:
        server.close()


def test_serve_discover_validation(tmp_path, tel):
    src, server = _research_server(tmp_path, tel)
    try:
        with pytest.raises(ValueError, match="day range"):
            server.discover(0, src.n_days + 1)
        with pytest.raises(ValueError, match="generations"):
            server.discover(0, 8, generations=10_000)
        with pytest.raises(ValueError, match="pop"):
            server.discover(0, 8, pop=10 ** 9)
        with pytest.raises(ValueError, match="horizon"):
            server.discover(0, 2, horizon=2)
        with pytest.raises(ValueError, match="unknown skeleton"):
            server.discover(0, 8, skeleton="nope")
    finally:
        server.close()


def test_streamed_server_refuses_intraday_on_discovered(tmp_path,
                                                        tel):
    """A stream+research server: discovery grows the BLOCK factor set
    but the streaming carry's warm executables were compiled over the
    construction-time set (genome factors have no incremental class
    yet — ROADMAP residue), so an intraday query for a discovered
    name must refuse loudly while plain intraday keeps answering over
    the original names."""
    src = SyntheticSource(n_days=10, n_tickers=16, seed=22)
    server = FactorServer(
        src, names=("vol_return1min", "mmt_am"),
        serve_cfg=ServeConfig(research_dir=str(tmp_path),
                              hbm_sample_period_s=0),
        telemetry=tel, research=True, stream=True)
    try:
        ans = server.discover(0, 8, generations=2, pop=16,
                              seed=4).result(600)
        with pytest.raises(ValueError, match="non-streamable"):
            server.submit(Query("intraday", names=(ans["name"],)))
        intra = server.submit(Query("intraday")).result(120)
        assert set(intra["exposures"]) == {"vol_return1min", "mmt_am"}
        # the block leg still serves the discovered name
        blk = server.submit(Query("factors", 0, 8,
                                  names=(ans["name"],))).result(120)
        assert ans["name"] in blk["exposures"]
    finally:
        server.close()


def test_discover_needs_research_mode(tel):
    src = SyntheticSource(n_days=6, n_tickers=8, seed=1)
    server = FactorServer(src, names=("vol_return1min",),
                          serve_cfg=ServeConfig(hbm_sample_period_s=0),
                          telemetry=tel)
    try:
        with pytest.raises(ValueError, match="research=True"):
            server.discover(0, 4)
    finally:
        server.close()


@pytest.mark.transfers
def test_http_discover_and_factor_routes(tmp_path, tel):
    """The HTTP face: POST /v1/discover round-trips the job (with the
    trace-ID header echoed), GET /v1/factors lists the result, and a
    malformed discover body 400s."""
    _src, server = _research_server(tmp_path, tel)
    httpd, _thread = serve_http(server, port=0, timeout=600)
    port = httpd.server_address[1]
    base = f"http://127.0.0.1:{port}"
    try:
        req = urllib.request.Request(
            f"{base}/v1/discover",
            data=json.dumps({"start": 0, "end": 8, "generations": 2,
                             "pop": 16, "seed": 1}).encode(),
            headers={"X-Trace-Id": "disc-test-1"})
        with urllib.request.urlopen(req, timeout=600) as resp:
            assert resp.headers["X-Trace-Id"] == "disc-test-1"
            ans = json.loads(resp.read())
        assert ans["name"].startswith("disc_")
        assert ans["trace_id"] == "disc-test-1"
        with urllib.request.urlopen(f"{base}/v1/factors",
                                    timeout=60) as resp:
            fl = json.loads(resp.read())
        assert ans["name"] in fl["discovered"]
        # malformed body -> 400
        bad = urllib.request.Request(f"{base}/v1/discover",
                                     data=b'{"start": 0}')
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(bad, timeout=60)
        assert ei.value.code == 400
        # query the discovered factor over HTTP
        qreq = urllib.request.Request(
            f"{base}/v1/query",
            data=json.dumps({"kind": "factors", "start": 0, "end": 8,
                             "names": [ans["name"]]}).encode())
        with urllib.request.urlopen(qreq, timeout=120) as resp:
            q = json.loads(resp.read())
        assert ans["name"] in q["exposures"]
    finally:
        httpd.shutdown()
        server.close()
