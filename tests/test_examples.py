"""Every example must run end to end as a real subprocess (the docs
point users at them; a stale API reference dies here, not on a user)."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.mark.parametrize("script", [
    "quickstart.py",
    "advanced_evaluation.py",
    # the symbolic-search example is an evolution seed sweep (~1 min on
    # the 1-core host) — slow tier, like the search suite's sweeps
    pytest.param("symbolic_search.py", marks=pytest.mark.slow),
])
def test_example_runs(script, tmp_path):
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    env["PYTHONPATH"] = os.pathsep.join(
        filter(None, [REPO, env.get("PYTHONPATH")]))
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script),
         str(tmp_path)],
        capture_output=True, text=True, timeout=280, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
