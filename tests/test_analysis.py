"""graftlint (the static analyzer) — docs/static-analysis.md.

Three layers of coverage:

* every AST rule (GL-A1..A6) fires on its injected-violation fixture
  under ``tests/fixtures/graftlint/`` with the exact code AND location,
  and the paired-resource negative fixture stays silent;
* every jaxpr contract (GL-B0..B3) fires on a deliberately-bad kernel —
  including the PR 3 revert scenario (a ``fori_loop``-of-``roll``
  moment pass) tripping the serial-loop gate;
* every concurrency rule (GL-C1..C4, ISSUE 19) fires on its
  injected-violation fixture under ``tests/fixtures/graftlint/
  concurrency/`` and stays silent on the compliant twin;
* the baseline workflow round-trips (new violation -> nonzero; accepted
  into the baseline with a justification -> clean; justification
  mandatory; stale entries reported), and the REPO ITSELF is clean:
  the acceptance test runs the real CLI exactly as run_tests.sh does.
"""

import json
import os
import subprocess
import sys

import pytest

from replication_of_minute_frequency_factor_tpu.analysis import (
    Baseline, Violation, run_ast_tier, run_concurrency_tier)
from replication_of_minute_frequency_factor_tpu.analysis.jaxpr_tier import (
    check_kernel)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "graftlint")


def _codes_by_file(violations):
    out = {}
    for v in violations:
        out.setdefault(os.path.basename(v.path), []).append(
            (v.code, v.line, v.symbol))
    return out


@pytest.fixture(scope="module")
def fixture_violations():
    violations, n_files = run_ast_tier(FIXTURES, display_base=REPO)
    assert n_files == 30
    return violations


# --------------------------------------------------------------------------
# Tier A: one fixture per rule, exact codes + locations
# --------------------------------------------------------------------------


def test_a1_fires_on_missing_jax_attributes(fixture_violations):
    hits = _codes_by_file(fixture_violations)["bad_a1.py"]
    assert ("GL-A1", 10, "jnp.maximum.accumulate") in hits
    assert ("GL-A1", 16, "jax.distributed.is_initialized") in hits
    # the resolvable chain (lax.cummax) must NOT fire
    assert not any(h[1] == 21 for h in hits)
    assert all(c == "GL-A1" for c, _, _ in hits)


def test_a2_fires_on_serial_loops_in_ops(fixture_violations):
    hits = _codes_by_file(fixture_violations)["bad_roll_loop.py"]
    assert ("GL-A2", 11, "roll in loop") in hits
    assert ("GL-A2", 18, "fori_loop") in hits


def test_a3_fires_on_host_syncs_in_ops(fixture_violations):
    hits = _codes_by_file(fixture_violations)["bad_hostsync.py"]
    symbols = {s for _, _, s in hits}
    assert symbols == {".item()", ".block_until_ready()", "np.asarray",
                       "float(jax expression)"}
    assert [c for c, _, _ in hits] == ["GL-A3"] * 4
    assert sorted(ln for _, ln, _ in hits) == [10, 11, 12, 13]


def test_a4_fires_on_unpaired_start_trace(fixture_violations):
    hits = _codes_by_file(fixture_violations)["bad_unpaired_trace.py"]
    assert hits == [("GL-A4", 9, "start_trace")]


def test_a4_accepts_all_pairing_shapes(fixture_violations):
    """try/finally, @contextmanager, and __enter__/__exit__ are all
    guaranteed-release shapes — zero violations in the good fixture."""
    assert "good_paired_trace.py" not in _codes_by_file(
        fixture_violations)


def test_a5_fires_on_raw_reductions_in_models(fixture_violations):
    hits = _codes_by_file(fixture_violations)["bad_rawmean.py"]
    assert [(c, s) for c, _, s in hits] == [
        ("GL-A5", "jnp.mean"), ("GL-A5", "jnp.std"),
        ("GL-A5", "jnp.nanmean")]


def test_a6_fires_on_missing_or_bad_finalize_class(fixture_violations):
    """ISSUE 18: a registered kernel with no finalize_class flags, a
    non-literal exactness class flags, a computed kernel name flags —
    and both declaration idioms (direct literal, literal-tuple loop)
    count as coverage (the two declared kernels stay silent)."""
    hits = _codes_by_file(fixture_violations)["bad_nofinalize.py"]
    assert [c for c, _, _ in hits] == ["GL-A6"] * 3
    symbols = {s for _, _, s in hits}
    assert symbols == {"register('fx_missing')",
                       "finalize_class(..., <class>)",
                       "finalize_class(<dynamic>)"}
    assert not any("fx_declared" in s for s in symbols)


def test_a6_is_clean_on_the_real_family_modules():
    """All 58 kernels declare their class in their family module — the
    static rule agrees with the runtime registry's loud check."""
    from replication_of_minute_frequency_factor_tpu.analysis import (
        ast_tier)
    from replication_of_minute_frequency_factor_tpu.models.registry \
        import FINALIZE_CLASS_VALUES, finalize_classes

    violations, _ = ast_tier.run_ast_tier()
    assert not [v for v in violations if v.code == "GL-A6"]
    # the linter's pinned literal set mirrors the registry's
    assert ast_tier.FINALIZE_CLASS_LITERALS == FINALIZE_CLASS_VALUES
    assert len(finalize_classes()) >= 58


def test_a3_boundary_policy_allows_listed_symbol_only(
        fixture_violations):
    """ISSUE 6: serve/ joined the GL-A3 scope with a per-symbol
    boundary-module policy. The fixture at the policy key
    ``serve/service.py`` uses the one allowed symbol (``np.asarray``)
    plus two banned ones — only the banned ones flag."""
    hits = _codes_by_file(fixture_violations)["service.py"]
    symbols = {s for _, _, s in hits}
    assert symbols == {".block_until_ready()", ".item()"}
    assert all(c == "GL-A3" for c, _, _ in hits)


def test_a3_boundary_policy_is_not_a_blanket_exclusion(
        fixture_violations):
    """A serve/ module that is NOT the declared boundary gets the full
    rule: its np.asarray flags."""
    hits = _codes_by_file(fixture_violations)["engine_like.py"]
    assert [(c, s) for c, _, s in hits] == [("GL-A3", "np.asarray")]


def test_a3_policy_matches_the_real_request_loop():
    """The committed policy has exactly eight entries — the serving
    request loop with its one declared sync, the ops-plane sampler
    with its device-memory reads (ISSUE 8), the mesh-plane
    shard-watermark prober with its per-shard blocking (ISSUE 9), the
    factor-health plane's one fused-stats materialization (ISSUE 12),
    the fleet layer's two boundaries (ISSUE 11: the router's one
    ingest normalization, the replica lifecycle's one device-liveness
    block), the discovery loop's one per-generation fitness fetch
    (ISSUE 14), and the timeline sampler's one top-movers
    normalization (ISSUE 16) — and scanning the real package stays
    clean under it (the policy is load-bearing: docs list it)."""
    from replication_of_minute_frequency_factor_tpu.analysis import (
        ast_tier)
    assert ast_tier.GLA3_BOUNDARY_SYNCS == {
        "serve/service.py": frozenset({"np.asarray"}),
        "research/evolve.py": frozenset({"np.asarray"}),
        "telemetry/opsplane.py": frozenset({".memory_stats()",
                                            "jax.live_arrays"}),
        "telemetry/meshplane.py": frozenset({".block_until_ready()"}),
        "telemetry/factorplane.py": frozenset({"np.asarray"}),
        "telemetry/timeline.py": frozenset({"np.asarray"}),
        "fleet/router.py": frozenset({"np.asarray"}),
        "fleet/replica.py": frozenset({".block_until_ready()"})}
    violations, _ = ast_tier.run_ast_tier()
    assert not [v for v in violations if "/serve/" in v.path]
    assert not [v for v in violations if "/telemetry/" in v.path]
    assert not [v for v in violations if "/fleet/" in v.path]
    assert not [v for v in violations if "/research/" in v.path]


def test_a3_edge_modules_are_pinned_with_no_allowance(
        fixture_violations):
    """ISSUE 20: the evented edge and its wire client are pinned
    device-hot by MODULE (HOST_SYNC_MODULES) with NO boundary
    allowance — both injected sync symbols flag in the bad twin, and
    the host-bytes-only twin (np.frombuffer + concatenate) stays
    silent."""
    from replication_of_minute_frequency_factor_tpu.analysis import (
        ast_tier)
    assert ast_tier.HOST_SYNC_MODULES == frozenset(
        {"data/result_wire.py", "serve/edge.py",
         "serve/wireclient.py"})
    by_file = _codes_by_file(fixture_violations)
    hits = by_file["bad_edge_sync.py"]
    assert {s for _, _, s in hits} == {"np.asarray",
                                      ".block_until_ready()"}
    assert all(c == "GL-A3" for c, _, _ in hits)
    assert "edge_like.py" not in by_file, by_file.get("edge_like.py")


def test_a3_research_evolve_boundary_allows_asarray_only(
        fixture_violations):
    """ISSUE 14: the research boundary fixture uses its one allowed
    symbol (np.asarray, the per-generation fitness fetch) plus two
    banned ones — only the banned ones flag."""
    hits = _codes_by_file(fixture_violations)["evolve.py"]
    symbols = {s for _, _, s in hits}
    assert symbols == {".block_until_ready()", ".item()"}
    assert all(c == "GL-A3" for c, _, _ in hits)


def test_a3_research_scope_is_not_a_blanket_exclusion(
        fixture_violations):
    """A research/ module that is NOT the declared boundary gets the
    full rule: its np.asarray flags (the generation loop's one-sync
    budget would silently double otherwise)."""
    hits = _codes_by_file(fixture_violations)["fitness_like.py"]
    assert [(c, s) for c, _, s in hits] == [("GL-A3", "np.asarray")]


def test_a3_timeline_scope_is_not_a_blanket_exclusion(
        fixture_violations):
    """ISSUE 16: ``telemetry/timeline.py`` declares ``np.asarray``
    (the top-movers range normalization) — a telemetry/ module that is
    NOT that boundary still gets the full rule (its np.asarray flags),
    and a sync symbol beyond the declared set (.item()) flags even in
    a sampler-styled module."""
    hits = _codes_by_file(fixture_violations)["timeline_like.py"]
    assert {s for _, _, s in hits} == {"np.asarray", ".item()"}
    assert all(c == "GL-A3" for c, _, _ in hits)


def test_a3_fleet_router_boundary_allows_asarray_only(
        fixture_violations):
    """ISSUE 11: the fleet router boundary fixture uses its one
    allowed symbol (np.asarray, the pre-fan-out ingest normalization)
    plus two banned ones — only the banned ones flag."""
    hits = _codes_by_file(fixture_violations)["router.py"]
    assert {s for _, _, s in hits} == {".block_until_ready()",
                                      ".item()"}
    assert all(c == "GL-A3" for c, _, _ in hits)


def test_a3_fleet_replica_boundary_allows_blocking_only(
        fixture_violations):
    """The fleet replica boundary fixture uses its one allowed sync
    (.block_until_ready(), the device-liveness probe) plus a banned
    np.asarray — only the banned symbol flags."""
    hits = _codes_by_file(fixture_violations)["replica.py"]
    assert [(c, s) for c, _, s in hits] == [("GL-A3", "np.asarray")]


def test_a3_fleet_scope_is_not_a_blanket_exclusion(
        fixture_violations):
    """A fleet/ module that is NOT a declared boundary gets the full
    rule: both its np.asarray and its .block_until_ready() flag."""
    hits = _codes_by_file(fixture_violations)["policy_like.py"]
    assert {s for _, _, s in hits} == {"np.asarray",
                                      ".block_until_ready()"}
    assert all(c == "GL-A3" for c, _, _ in hits)


def test_a3_meshplane_boundary_allows_blocking_only(
        fixture_violations):
    """ISSUE 9: the meshplane boundary fixture uses its one allowed
    sync (.block_until_ready()) plus a banned np.asarray — only the
    banned symbol flags, and blocking still flags in every OTHER
    telemetry module (sampler_like's scope test covers the layer)."""
    hits = _codes_by_file(fixture_violations)["meshplane.py"]
    assert [(c, s) for c, _, s in hits] == [("GL-A3", "np.asarray")]


def test_a3_factorplane_boundary_allows_asarray_only(
        fixture_violations):
    """ISSUE 12: the factor-health plane's boundary fixture uses its
    one allowed sync (np.asarray — the tiny fused-stats
    materialization) plus a banned .block_until_ready() — only the
    banned symbol flags."""
    hits = _codes_by_file(fixture_violations)["factorplane.py"]
    assert [(c, s) for c, _, s in hits] == [("GL-A3",
                                             ".block_until_ready()")]


def test_a3_memreads_flag_outside_the_opsplane_boundary(
        fixture_violations):
    """ISSUE 8: device-memory host reads (.memory_stats() /
    .live_buffers() / jax.live_arrays) are GL-A3 syncs in the scanned
    layers — a telemetry module that is not the declared sampler
    boundary flags on all three."""
    hits = _codes_by_file(fixture_violations)["sampler_like.py"]
    assert {s for _, _, s in hits} == {".memory_stats()",
                                      ".live_buffers()",
                                      "jax.live_arrays"}
    assert all(c == "GL-A3" for c, _, _ in hits)


def test_a3_opsplane_boundary_allows_its_memreads_only(
        fixture_violations):
    """The opsplane boundary fixture uses its two allowed reads plus a
    banned .item() — only the banned symbol flags."""
    hits = _codes_by_file(fixture_violations)["opsplane.py"]
    assert [(c, s) for c, _, s in hits] == [("GL-A3", ".item()")]


def test_scope_rules_do_not_leak_outside_their_layers(
        fixture_violations):
    """bad_a1.py sits at the fixture root (not ops/ or models/), so the
    scoped rules A2/A3/A5 must not fire there even though it imports
    jax — scoping is what keeps host-side layers legal."""
    hits = _codes_by_file(fixture_violations)["bad_a1.py"]
    assert {c for c, _, _ in hits} == {"GL-A1"}


# --------------------------------------------------------------------------
# Tier B: jaxpr contracts on deliberately-bad kernels
# --------------------------------------------------------------------------


def test_b1_trips_on_the_pr3_revert():
    """Reintroducing the pre-PR-3 serial moment pass — a fori_loop of
    jnp.roll accumulations — must trip the serial-loop gate."""
    import jax
    import jax.numpy as jnp

    def reverted_kernel(ctx):
        def body(j, acc):
            return acc + jnp.roll(ctx.low, j, axis=-1) * ctx.high
        acc = jax.lax.fori_loop(0, 50, body, jnp.zeros_like(ctx.low))
        return jnp.sum(acc, axis=-1)

    vs, fp = check_kernel("reverted", reverted_kernel)
    assert fp["traced"]
    codes = {v.code for v in vs}
    assert "GL-B1" in codes
    assert any(v.symbol in ("while", "scan") and v.kernel == "reverted"
               for v in vs)


def test_b2_trips_on_f64_promotion():
    import jax
    import jax.numpy as jnp

    def f64_kernel(ctx):
        wide = jax.lax.convert_element_type(ctx.close, jnp.float64)
        return jnp.sum(wide, axis=-1).astype(jnp.float32)

    from jax.experimental import enable_x64
    with enable_x64():
        vs, fp = check_kernel("f64", f64_kernel)
    assert fp["traced"]
    assert any(v.code == "GL-B2"
               and v.symbol == "convert_element_type[float64]"
               for v in vs)


def test_b3_trips_on_host_callback():
    import jax
    import jax.numpy as jnp
    import numpy as np

    def callback_kernel(ctx):
        return jax.pure_callback(
            lambda c: np.mean(c, axis=-1),
            jax.ShapeDtypeStruct(ctx.close.shape[:-1], jnp.float32),
            ctx.close)

    vs, fp = check_kernel("cb", callback_kernel)
    assert fp["traced"]
    assert any(v.code == "GL-B3" and "callback" in v.symbol for v in vs)


def test_b0_trips_on_untraceable_kernel():
    def crashy_kernel(ctx):
        raise RuntimeError("boom at trace time")

    vs, fp = check_kernel("crashy", crashy_kernel)
    assert not fp["traced"]
    assert vs[0].code == "GL-B0" and "boom" in vs[0].message


def test_fingerprints_are_stable_and_loop_free():
    """A clean kernel yields a deterministic primitive-count
    fingerprint with no serial primitives — the committed
    analysis_report.json diffs are meaningful."""
    from replication_of_minute_frequency_factor_tpu.models.registry \
        import resolve

    vs1, fp1 = check_kernel("mmt_ols_qrs", resolve("mmt_ols_qrs"))
    vs2, fp2 = check_kernel("mmt_ols_qrs", resolve("mmt_ols_qrs"))
    assert vs1 == [] and fp1 == fp2
    assert "while" not in fp1["primitives"]
    assert "scan" not in fp1["primitives"]
    assert fp1["primitives"].get("conv_general_dilated", 0) >= 1


# --------------------------------------------------------------------------
# Tier C: concurrency contracts on injected-violation fixtures (ISSUE 19)
# --------------------------------------------------------------------------


@pytest.fixture(scope="module")
def concurrency_violations():
    violations, n_files = run_concurrency_tier(FIXTURES,
                                               display_base=REPO)
    assert n_files == 30
    return violations


def test_c1_fires_on_unlocked_writes_and_foreign_reach(
        concurrency_violations):
    """Both GL-C1 arms: a RMW and a mutator call on guarded attributes
    outside the lock flag in the owning class, and reaching through an
    object attribute into another class's guarded internals flags at
    the reader."""
    hits = _codes_by_file(concurrency_violations)["bad_c1.py"]
    assert ("GL-C1", 23, "BadCounter._c1_total") in hits
    assert ("GL-C1", 26, "BadCounter._c1_rows") in hits
    assert ("GL-C1", 34, "counter._c1_total") in hits
    assert [c for c, _, _ in hits] == ["GL-C1"] * 3


def test_c2_fires_on_undisciplined_thread(concurrency_violations):
    """A Thread without daemon=True, with no join path, whose target
    mutates a foreign class's guarded state: all three GL-C2 arms."""
    hits = _codes_by_file(concurrency_violations)["bad_c2.py"]
    symbols = {s for _, _, s in hits}
    assert symbols == {"Thread(daemon=...)",
                       "Thread(no stop/join path)",
                       "target mutates STORE._c2_bins"}
    assert all(c == "GL-C2" for c, _, _ in hits)


def test_c3_fires_on_nonatomic_threaded_write(concurrency_violations):
    hits = _codes_by_file(concurrency_violations)["bad_c3.py"]
    assert hits == [("GL-C3", 25, "Dumper.dump open('w')")]


def test_c4_fires_on_silent_swallow_in_thread_target(
        concurrency_violations):
    hits = _codes_by_file(concurrency_violations)["bad_c4.py"]
    assert hits == [("GL-C4", 16, "run_loop except:pass")]


def test_compliant_concurrency_fixtures_stay_silent(
        concurrency_violations):
    """Each rule's compliant twin — locked writes with declared
    init/locked methods, a joined daemon sampler, the
    returned-to-caller thread, the tmp+os.replace write, the counting
    except handler — produces zero violations."""
    by_file = _codes_by_file(concurrency_violations)
    for f in ("good_c1.py", "good_c2.py", "good_c2_return.py",
              "good_c3.py", "good_c4.py"):
        assert f not in by_file, by_file.get(f)


def test_tier_c_repo_is_clean_and_contracts_are_declared():
    """The real package passes its own lock-discipline lint, and the
    contract index covers the threaded planes the runtime twin arms."""
    from replication_of_minute_frequency_factor_tpu.analysis.concurrency_tier \
        import contract_index

    violations, n_files = run_concurrency_tier()
    assert violations == [], [f"{v.location()}: {v.symbol}"
                              for v in violations]
    assert n_files > 40
    idx = contract_index()
    for cls in ("MetricsRegistry", "SpanTracer", "Telemetry",
                "TimelineStore", "HbmSampler", "FlightRecorder",
                "MeshPlane", "SloPlane", "ShedPolicy", "FleetRouter",
                "FactorServer", "EdgeServer"):
        assert cls in idx, sorted(idx)
        assert idx[cls]["lock"] and idx[cls]["guards"]


# --------------------------------------------------------------------------
# baseline workflow
# --------------------------------------------------------------------------


def _v(code="GL-A3", path="pkg/ops/x.py", symbol="np.asarray"):
    return Violation(code=code, path=path, line=7, symbol=symbol,
                     message="m")


def test_baseline_roundtrip(tmp_path):
    p = str(tmp_path / "baseline.json")
    b = Baseline()
    new, accepted, stale = b.split([_v()])
    assert len(new) == 1 and not accepted
    b.extend(new, "intentional: host-side oracle")
    b.save(p)
    # accepted after reload; line drift doesn't matter
    b2 = Baseline.load(p)
    drifted = _v()
    drifted.line = 99
    new, accepted, stale = b2.split([drifted])
    assert not new and len(accepted) == 1 and not stale
    # a different symbol is a NEW violation, not covered
    new, accepted, stale = b2.split([_v(symbol=".item()")])
    assert len(new) == 1
    # nothing matched -> the entry is reported stale
    new, accepted, stale = b2.split([])
    assert len(stale) == 1


def test_baseline_requires_justification(tmp_path):
    with pytest.raises(ValueError, match="justification"):
        Baseline([{"code": "GL-A1", "path": "x", "symbol": "y",
                   "kernel": "", "justification": "  "}])
    with pytest.raises(ValueError, match="justification"):
        Baseline().extend([_v()], "")


# --------------------------------------------------------------------------
# CLI acceptance: the repo itself is clean, violations exit nonzero
# --------------------------------------------------------------------------


def _run_cli(*args):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    return subprocess.run(
        [sys.executable, "-m",
         "replication_of_minute_frequency_factor_tpu", "analyze",
         *args], capture_output=True, text=True, cwd=REPO, env=env,
        timeout=600)


def test_repo_is_clean_against_committed_baseline(tmp_path):
    """THE acceptance gate: the default invocation walks all 58
    kernels + the whole package AST and exits 0 against the committed
    baseline, writing the machine-readable report."""
    report = str(tmp_path / "report.json")
    out = _run_cli("--report", report)
    assert out.returncode == 0, out.stderr[-2000:]
    verdict = json.loads(out.stdout.strip().splitlines()[-1])
    assert verdict["ok"] is True and verdict["kernels"] == 58
    rep = json.load(open(report))
    assert rep["verdict"]["clean"] is True
    assert len(rep["jaxpr"]["fingerprints"]) == 58
    assert all(fp["traced"] and "while" not in fp["primitives"]
               and "scan" not in fp["primitives"]
               for fp in rep["jaxpr"]["fingerprints"].values())


def test_cli_flags_fixtures_then_baseline_clears_them(tmp_path):
    """Baseline workflow end-to-end through the real CLI: violations ->
    exit 1; --update-baseline with a justification -> exit 0; the
    justification is mandatory."""
    base = str(tmp_path / "b.json")
    report = str(tmp_path / "r.json")
    args = ("--tier", "ast", "--paths", FIXTURES, "--baseline", base,
            "--report", report)
    out = _run_cli(*args)
    assert out.returncode == 1
    assert json.loads(out.stdout.strip().splitlines()[-1])["new"] == 36
    # refuse to baseline without a why
    out = _run_cli(*args, "--update-baseline")
    assert out.returncode == 2
    out = _run_cli(*args, "--update-baseline", "--justification",
                   "fixtures: deliberate violations under test")
    assert out.returncode == 0
    entries = json.load(open(base))["entries"]
    assert entries and all(e["justification"] for e in entries)
    # and the accepted state is durable
    out = _run_cli(*args)
    assert out.returncode == 0
    assert json.loads(
        out.stdout.strip().splitlines()[-1])["baselined"] == 36


def test_cli_tier_c_flags_fixtures_and_reports_contracts(tmp_path):
    """``analyze --tier c`` over the fixture tree: all 8 injected
    violations flag with the right per-rule split, the report grows
    the committed ``concurrency`` section, and the verdict carries the
    contract count."""
    base = str(tmp_path / "b.json")
    report = str(tmp_path / "r.json")
    out = _run_cli("--tier", "c", "--paths", FIXTURES,
                   "--baseline", base, "--report", report)
    assert out.returncode == 1
    verdict = json.loads(out.stdout.strip().splitlines()[-1])
    assert verdict["new"] == 8
    rep = json.load(open(report))
    conc = rep["concurrency"]
    assert conc["by_rule"] == {"GL-C1": 3, "GL-C2": 3,
                               "GL-C3": 1, "GL-C4": 1}
    assert conc["files_scanned"] == 30
    assert "BadCounter" in conc["contracts"]
    assert conc["contracts"]["BadCounter"]["lock"] == "_glock"


def test_cli_explain_prints_rationale_for_any_rule():
    for code in ("GL-C1", "GL-C4", "GL-A3", "GL-B1"):
        out = _run_cli("--explain", code)
        assert out.returncode == 0, out.stderr[-500:]
        assert code in out.stdout
        assert "why:" in out.stdout and "fix:" in out.stdout
    out = _run_cli("--explain", "GL-Z9")
    assert out.returncode == 2
    assert "unknown rule code" in out.stderr


def test_manifest_carries_the_analysis_block(tmp_path):
    from replication_of_minute_frequency_factor_tpu.telemetry.manifest \
        import build_manifest

    m = build_manifest()
    blk = m["analysis"]
    assert blk["ast"]["clean"] is True, blk
    assert blk["ast"]["files_scanned"] > 40
    # the committed repo report is condensed in, when present
    if os.path.exists(os.path.join(REPO, "analysis_report.json")):
        assert blk["report"]["present"] is True
        assert blk["report"]["kernels"] == 58


def test_resident_wrappers_trace_clean_and_scan_exempt_by_symbol():
    """ISSUE 5 + ISSUE 7: Tier B abstractly traces the driving-scan
    wrappers — the resident scan entrypoints (single-device + sharded,
    canonical per-shard shape) and the streaming minute fold
    (``__stream_update__``). Their ONE driving scan is exempt from
    GL-B1 by SYMBOL — the wrapper names are reserved in
    jaxpr_tier.RESIDENT_WRAPPERS, no baseline entry exists for them —
    while the kernel tier's zero-scan rule is untouched."""
    from replication_of_minute_frequency_factor_tpu.analysis import (
        jaxpr_tier)
    from replication_of_minute_frequency_factor_tpu.analysis.violations import (
        BASELINE_PATH, Baseline)

    violations, fps = jaxpr_tier.run_resident_tier()
    assert violations == []
    assert set(fps) == set(jaxpr_tier.RESIDENT_WRAPPERS)
    assert "__stream_update__" in fps
    assert "__result_encode__" in fps      # ISSUE 10
    assert "__discover_generation__" in fps  # ISSUE 14
    for name, fp in fps.items():
        assert fp["traced"] is True
        allowed = jaxpr_tier.WRAPPER_SCAN_ALLOWANCE.get(name, 1)
        assert fp["primitives"].get("scan", 0) == allowed, name
        assert "while" not in fp["primitives"], name
    # the result-wire encode gets NO scan exemption at all — its
    # cumsum/scatter compaction must never trace to a serial loop
    assert jaxpr_tier.WRAPPER_SCAN_ALLOWANCE["__result_encode__"] == 0
    # exemption is by symbol, NOT by baseline entry
    entries = Baseline.load(BASELINE_PATH).entries
    assert not any(e.get("kernel", "").startswith(("__resident",
                                                   "__stream",
                                                   "__result"))
                   for e in entries)


def test_resident_wrapper_second_scan_flags():
    """A second scan inside the wrapper (a serial loop leaking out of a
    kernel and through the exemption) must flag GL-B1."""
    import jax
    import jax.numpy as jnp

    from replication_of_minute_frequency_factor_tpu.analysis import (
        jaxpr_tier)

    def double_scan(xs):
        def inner(_, x):
            _, ys = jax.lax.scan(lambda c, v: (c, v * 2.0), None, x)
            return None, jnp.sum(ys)
        _, out = jax.lax.scan(inner, None, xs)
        return out

    closed = jax.make_jaxpr(double_scan)(
        jax.ShapeDtypeStruct((3, 4), jnp.float32))
    vs, fp = jaxpr_tier.check_resident_wrapper("__resident_scan__",
                                               closed)
    assert any(v.code == "GL-B1" and v.symbol == "scan" for v in vs)


def test_report_carries_resident_wrapper_fingerprints():
    """The committed analysis_report.json carries the wrapper
    fingerprints apart from the 58 kernel ones (the kernels' zero-scan
    contract must stay visually unblurred in the diffable artifact)."""
    import os

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(repo, "analysis_report.json")) as fh:
        rep = json.load(fh)
    assert len(rep["jaxpr"]["fingerprints"]) == 58
    wrappers = rep["jaxpr"]["resident_wrappers"]
    assert set(wrappers) == {"__resident_scan__",
                             "__resident_scan_sharded__",
                             "__resident_scan_2d__",
                             "__stream_update__",
                             "__result_encode__",
                             "__discover_generation__",
                             "__stream_finalize_fast__"}
    for name, fp in wrappers.items():
        want = 0 if name in ("__result_encode__",
                             "__stream_finalize_fast__") else 1
        assert fp["primitives"].get("scan", 0) == want, name
    # the 2-D wrapper's committed fingerprint pins the cross-day carry
    # handoff in the collective class (ISSUE 13)
    assert wrappers["__resident_scan_2d__"]["primitives"].get(
        "ppermute", 0) > 0
    # the discovery wrapper's committed fingerprint pins the
    # end-of-generation top-k gather's collective class (ISSUE 14)
    assert wrappers["__discover_generation__"]["primitives"].get(
        "all_gather", 0) > 0
