"""The SLO plane (ISSUE 16): the continuous telemetry timeline,
multi-window burn-rate objectives, the ``slo_burn`` flight-dump
correlation, schema-v4 ``frame``/``slo`` records (both directions),
the pod timeline fold, and the offline incident replay CLI.

Everything here is host-side by construction — no jax import: the
sampler reads registry snapshots and host mirrors only, and the tests
COUNTER-ASSERT that sampling moves no device-work counters.
"""

import json
import os
import time

from replication_of_minute_frequency_factor_tpu.telemetry import (
    FlightRecorder, MetricsRegistry, Telemetry, validate_record)
from replication_of_minute_frequency_factor_tpu.telemetry.aggregate import (
    aggregate_dirs, fold_timelines)
from replication_of_minute_frequency_factor_tpu.telemetry.slo import (
    BURN_WINDOWS, Objective, SloPlane, fleet_objectives,
    serve_objectives, slo_prometheus)
from replication_of_minute_frequency_factor_tpu.telemetry.timeline import (
    TimelineStore, incident_report)
from replication_of_minute_frequency_factor_tpu.telemetry.timeline import (
    main as timeline_main)
from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
    validate_dir, validate_dump)

import pytest


class _Clock:
    """Controllable monotonic clock: burn windows become test time."""

    def __init__(self, t=100.0):
        self.t = float(t)

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += float(dt)


# --------------------------------------------------------------------------
# timeline store
# --------------------------------------------------------------------------


def test_timeline_counter_rates_gauges_and_quantiles():
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    tel.counter("req", 5.0, kind="factors")
    f0 = tl.sample()
    # first frame has no prior interval: rates pin to 0
    assert f0["interval_s"] == 0.0
    assert f0["series"]["rate:req{kind=factors}"] == 0.0
    clk.advance(2.0)
    tel.counter("req", 10.0, kind="factors")
    tel.gauge("depth", 7.0)
    for v in (0.1, 0.2, 0.3):
        tel.observe("lat", v)
    f1 = tl.sample()
    assert f1["interval_s"] == 2.0
    assert f1["series"]["rate:req{kind=factors}"] == 5.0  # 10 over 2 s
    assert f1["series"]["gauge:depth"] == 7.0
    for q in ("p50", "p95", "p99"):
        assert f"{q}:lat" in f1["series"]
    # frames carry both clocks: the plane's monotone t and wall ts
    assert f1["t"] == clk.t and f1["ts"] >= f0["ts"]
    assert f1["seq"] == f0["seq"] + 1


def test_timeline_counter_rate_never_negative():
    """A registry swap/reset between samples must not print a negative
    pod rate — rates clamp at 0."""
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    tel.counter("c", 10.0)
    tl.sample()
    clk.advance(1.0)
    tl._last_counters["c"] = 100.0  # simulate a counter going backward
    f = tl.sample()
    assert f["series"]["rate:c"] == 0.0


def test_timeline_sources_feed_gauge_series_and_never_kill():
    tel = Telemetry()
    tl = TimelineStore(telemetry=tel, clock=_Clock())

    def good():
        return {"stream.staleness_s": 1.25, "skipped": None}

    def bad():
        raise RuntimeError("a source must not kill the sampler")

    tl.add_source(good)
    tl.add_source(bad)
    tl.add_source(good)  # re-registration is idempotent
    f = tl.sample()
    assert f["series"]["gauge:stream.staleness_s"] == 1.25
    assert "gauge:skipped" not in f["series"]


def test_timeline_query_filters_name_since_and_limit():
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    tel.counter("serve.requests", 1.0, kind="factors")
    tel.gauge("other", 1.0)
    for _ in range(4):
        clk.advance(0.5)
        tel.counter("serve.requests", 1.0, kind="factors")
        tl.sample()
        time.sleep(0.02)  # distinct wall-ms stamps for `since`
    # substring filter matches the prefix-qualified labeled key
    out = tl.query(name="serve.requests")
    assert len(out) == 4
    assert all(set(f["series"]) ==
               {"rate:serve.requests{kind=factors}"} for f in out)
    # since filters on the WALL clock (bundle correlation contract):
    # ts >= since keeps the cut frame and everything after it
    frames = tl.frames()
    assert len(tl.query(since=frames[2]["ts"])) == 2
    assert len(tl.query(limit=2)) == 2


def test_timeline_ring_bound_keeps_seq_monotone():
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, ring=4, clock=clk)
    for _ in range(7):
        clk.advance(0.1)
        tl.sample()
    frames = tl.frames()
    assert len(tl) == 4
    assert [f["seq"] for f in frames] == [4, 5, 6, 7]
    assert tl.latest()["seq"] == 7


def test_timeline_top_movers_ranks_by_normalized_delta():
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    for i in range(5):
        tel.gauge("moving", float(i * 10))
        tel.gauge("flat", 5.0)
        tl.sample()
        clk.advance(0.1)
    top = tl.top_movers(window_s=10.0, k=2)
    assert top and top[0]["series"] == "gauge:moving"
    assert top[0]["delta"] == 40.0
    flat = [r for r in top if r["series"] == "gauge:flat"]
    assert all(r["delta"] == 0.0 for r in flat)


def test_timeline_sampler_thread_start_stop_idempotent():
    tel = Telemetry()
    tl = TimelineStore(telemetry=tel)
    tl.start(0.01)
    tl.start(0.01)  # second start is a no-op, not a second thread
    deadline = time.monotonic() + 5.0
    while len(tl) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    assert len(tl) >= 3
    tl.stop()
    n = len(tl)
    time.sleep(0.05)
    assert len(tl) == n  # joined, not leaked
    tl.stop()  # idempotent


def test_on_frame_callback_errors_are_swallowed():
    tel = Telemetry()
    tl = TimelineStore(telemetry=tel, clock=_Clock())
    seen = []

    def boom(frame):
        raise RuntimeError("callback must not kill sampling")

    tl.on_frame(boom)
    tl.on_frame(seen.append)
    tl.sample()
    assert len(seen) == 1 and seen[0]["seq"] == 1


def test_sampling_moves_no_counters():
    """The tentpole's hot-path contract, counter-asserted: a sample
    (and a no-alert SLO evaluation riding it) reads registry state and
    publishes gauges — it must never increment ANY counter (the
    device-work counters ``xla.compiles`` /
    ``research.host_blocking_syncs`` included)."""
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    plane = SloPlane(telemetry=tel)
    plane.configure(serve_objectives(streaming=True), timeline=tl,
                    time_scale=3600.0, clock=clk)
    tel.counter("xla.compiles", 3.0)
    tel.counter("research.host_blocking_syncs", 2.0, point="g")
    before = dict(tel.registry.snapshot()["counters"])
    for _ in range(10):
        clk.advance(0.05)
        tl.sample()
    assert dict(tel.registry.snapshot()["counters"]) == before


# --------------------------------------------------------------------------
# burn-rate objectives
# --------------------------------------------------------------------------


def _availability_plane(tmp_path, target=0.99):
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    fr = FlightRecorder(telemetry=tel, dump_dir=str(tmp_path))
    plane = SloPlane(telemetry=tel)
    plane.configure(
        (Objective(name="availability", kind="availability",
                   target=target, total_counter="serve.requests",
                   bad_counter="serve.load_shed"),),
        flight=fr, timeline=tl, time_scale=3600.0, clock=clk)
    return tel, clk, tl, fr, plane


def test_objective_validates_kind_and_target():
    with pytest.raises(ValueError):
        Objective(name="x", kind="vibes", target=0.5)
    with pytest.raises(ValueError):
        Objective(name="x", kind="latency", target=1.0)
    assert len(serve_objectives(streaming=True)) == 3
    assert len(serve_objectives(streaming=False)) == 2
    assert len(fleet_objectives(streaming=True)) == 2


def test_availability_burn_alert_fires_and_dumps(tmp_path):
    """A sustained shed burst must fire (both fast windows over 14.4x)
    exactly once, force a ``slo_burn`` flight dump naming the
    objective, and count/retain the transition."""
    tel, clk, tl, fr, plane = _availability_plane(tmp_path)
    fr.record_request({"trace_id": "t-1", "op": "factors",
                       "status": "ok", "total_s": 0.01})
    for _ in range(30):                       # healthy history
        tel.counter("serve.requests", 5.0, kind="factors")
        clk.advance(0.05)
        tl.sample()
    assert plane.summary()["alerts"] == 0
    for _ in range(30):                       # sustained pure shed
        tel.counter("serve.load_shed", 5.0, reason="breaker")
        clk.advance(0.05)
        tl.sample()
    s = plane.summary()
    assert s["available"] and s["frames"] == 60
    assert s["objectives"]["availability"]["alerts"] == 1  # one edge
    assert s["objectives"]["availability"]["alerting"] is True
    assert s["worst_burn_rate"] >= 14.4
    assert int(tel.registry.counter_value(
        "slo.alerts", objective="availability")) == 1
    # the published scrape state
    g = tel.registry.snapshot()["gauges"]
    assert g["slo.alert{objective=availability}"] == 1.0
    assert "slo.burn_rate{objective=availability,window=fast}" in g
    assert "slo.error_budget_remaining{objective=availability}" in g
    # the forced dump: validated, pre-correlated with the incident
    dumps = [p for p in fr.dumps if "slo_burn" in p]
    assert len(dumps) == 1
    assert validate_dump(dumps[0])["ok"]
    with open(dumps[0]) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    header = next(r for r in recs if r["kind"] == "dump")
    extra = header["data"]["extra"]
    assert extra["objective"] == "availability"
    # the first edge may come from the slow pair (threshold 1x) while
    # the fast pair is still filling — either way it is over budget
    assert extra["event"] == "alert" and extra["burn_rate"] > 1.0
    assert extra["top_moving"]  # the moved series ride the dump
    assert any(r.get("kind") == "request"
               and r.get("trace_id") == "t-1" for r in recs)
    # retained as a schema-v4 slo event + per-objective verdict
    recs = plane.slo_records()
    assert any(r["data"].get("event") == "alert" for r in recs)
    assert any(r["data"].get("event") == "verdict" for r in recs)


def test_disjoint_counters_burn_on_pure_shed_window(tmp_path):
    """``serve.requests`` counts only ADMITTED work (a shed raises
    before it), so demand = total + bad: a window of nothing but sheds
    must read error-rate 1, not 0/0 -> 0."""
    tel, clk, tl, fr, plane = _availability_plane(tmp_path)
    for _ in range(5):
        tel.counter("serve.load_shed", 5.0, reason="breaker")
        clk.advance(0.05)
        tl.sample()
    hist = list(plane._history["availability"])
    err = plane._window_error_rate(plane.objectives[0], hist,
                                   clk(), 10.0)
    assert err == 1.0


def test_transient_spike_stays_quiet(tmp_path):
    """The multi-window pair's whole point: one bad frame amid heavy
    good traffic saturates no pair (fast short alone is not an alert),
    so nothing fires and no dump is written."""
    tel, clk, tl, fr, plane = _availability_plane(tmp_path)
    for i in range(40):
        tel.counter("serve.requests", 20.0, kind="factors")
        if i == 20:
            tel.counter("serve.load_shed", 1.0, reason="breaker")
        clk.advance(0.05)
        tl.sample()
    s = plane.summary()
    assert s["alerts"] == 0
    assert s["objectives"]["availability"]["alerting"] is False
    assert not fr.dumps


def test_latency_objective_burns_on_p99_over_threshold(tmp_path):
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    fr = FlightRecorder(telemetry=tel, dump_dir=str(tmp_path))
    plane = SloPlane(telemetry=tel)
    plane.configure(
        (Objective(name="latency", kind="latency", target=0.99,
                   latency_hist="serve.request_seconds",
                   threshold_ms=250.0),),
        flight=fr, timeline=tl, time_scale=3600.0, clock=clk)
    for _ in range(40):                       # every frame over budget
        tel.observe("serve.request_seconds", 0.5, kind="factors")
        clk.advance(0.05)
        tl.sample()
    s = plane.summary()
    assert s["objectives"]["latency"]["alerts"] >= 1
    assert any("slo_burn" in p for p in fr.dumps)


def test_freshness_objective_reads_source_gauge(tmp_path):
    tel = Telemetry()
    clk = _Clock()
    tl = TimelineStore(telemetry=tel, clock=clk)
    staleness = {"v": 0.5}
    tl.add_source(lambda: {"stream.staleness_s": staleness["v"]})
    plane = SloPlane(telemetry=tel)
    plane.configure(
        (Objective(name="freshness", kind="freshness", target=0.99,
                   staleness_gauge="stream.staleness_s",
                   threshold_s=120.0),),
        timeline=tl, time_scale=3600.0, clock=clk)
    for _ in range(10):                       # fresh: no burn
        clk.advance(0.05)
        tl.sample()
    assert plane.summary()["worst_burn_rate"] == 0.0
    staleness["v"] = 500.0                    # feed goes stale
    for _ in range(30):
        clk.advance(0.05)
        tl.sample()
    s = plane.summary()
    assert s["objectives"]["freshness"]["alerts"] >= 1


def test_slo_prometheus_renders_only_slo_metrics():
    tel = Telemetry()
    tel.gauge("slo.burn_rate", 2.5, objective="availability",
              window="fast")
    tel.counter("slo.alerts", 1.0, objective="availability")
    tel.counter("serve.requests", 5.0, kind="factors")
    text = slo_prometheus(tel.registry)
    assert "slo_burn_rate" in text and "slo_alerts" in text
    assert "serve_requests" not in text


# --------------------------------------------------------------------------
# schema v4: both directions
# --------------------------------------------------------------------------


def _v(schema, kind, **fields):
    return {"schema": schema, "ts": 1.0, "kind": kind, **fields}


def test_schema_v4_frame_and_slo_records_validate():
    assert validate_record(_v(4, "frame", seq=1, interval_s=0.5,
                              series={"rate:x": 2.0})) == []
    assert validate_record(_v(4, "slo", name="availability",
                              data={"event": "alert"})) == []
    # identity stamps ride v4 records like every other kind
    assert validate_record(_v(4, "frame", seq=1, interval_s=0.5,
                              series={}, process_index=0,
                              host="pod")) == []


def test_v4_only_kinds_flag_on_older_records():
    """The other direction: a record declaring ``schema <= 3`` cannot
    carry the v4 kinds, and malformed v4 fields flag."""
    for old in (1, 2, 3):
        assert any("schema>=4" in p for p in validate_record(
            _v(old, "frame", seq=1, interval_s=0.5, series={})))
        assert any("schema>=4" in p for p in validate_record(
            _v(old, "slo", name="x", data={})))
    assert any("seq" in p for p in validate_record(
        _v(4, "frame", seq="one", interval_s=0.5, series={})))
    assert any("series" in p for p in validate_record(
        _v(4, "frame", seq=1, interval_s=0.5, series=[1, 2])))
    assert any("name" in p for p in validate_record(
        _v(4, "slo", name=7, data={})))


def test_bundle_persists_frames_and_slo_records(tmp_path):
    """``Telemetry.write`` persists the ring as v4 ``frame`` records
    carrying each frame's OWN wall ts (not write time) plus the SLO
    events/verdicts, and the bundle re-validates."""
    tel = Telemetry()
    clk = _Clock()
    tl = tel.timeline
    tl.clock = clk
    tel.sloplane.configure(serve_objectives(), timeline=tl,
                           time_scale=3600.0, clock=clk)
    tel.counter("serve.requests", 2.0, kind="factors")
    tl.sample()
    frame_ts = tl.latest()["ts"]
    time.sleep(0.05)  # write time is measurably later
    out = str(tmp_path / "bundle")
    tel.write(out)
    assert validate_dir(out)["ok"]
    with open(os.path.join(out, "metrics.jsonl")) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    frames = [r for r in recs if r["kind"] == "frame"]
    slo = [r for r in recs if r["kind"] == "slo"]
    assert frames and frames[0]["schema"] == 4
    assert frames[0]["ts"] == frame_ts
    # one verdict per configured objective (no alerts fired)
    assert {r["name"] for r in slo} == {"availability", "latency"}


# --------------------------------------------------------------------------
# pod timeline fold
# --------------------------------------------------------------------------


def _frame(seq, ts, series):
    return {"seq": seq, "ts": ts, "interval_s": 0.5, "series": series}


def test_fold_timelines_sums_rates_and_maxes_gauges():
    a = [_frame(1, 10.0, {"rate:req": 5.0, "gauge:depth": 2.0,
                          "p99:lat": 0.1})]
    b = [_frame(1, 10.2, {"rate:req": 2.5, "gauge:depth": 7.0,
                          "p99:lat": 0.4})]
    pod = fold_timelines([a, b])
    assert len(pod) == 1
    s = pod[0]["series"]
    assert s["rate:req"] == 7.5           # rates SUM exactly
    assert s["gauge:depth"] == 7.0        # gauges fold MAX
    assert s["p99:lat"] == 0.4            # quantiles fold MAX
    assert pod[0]["ts"] == 10.2           # pod clock = latest host
    # a seq only one host reached still folds (its own values)
    pod2 = fold_timelines([a + [_frame(2, 11.0, {"rate:req": 1.0})], b])
    assert len(pod2) == 2 and pod2[1]["series"]["rate:req"] == 1.0


def test_aggregate_folds_replica_timelines_with_exact_rate_sums(
        tmp_path):
    """Two replica bundles with real sampled timelines -> one pod
    bundle: the folded pod frames land stamped ``host="pod"``, every
    pod rate series equals the per-host sum (re-verified by the
    aggregator, asserted again here), and the pod bundle re-validates
    at schema v4."""
    dirs = []
    rates = {}
    for host, burst in (("r0", 6.0), ("r1", 10.0)):
        tel = Telemetry()
        clk = _Clock()
        tl = tel.timeline
        tl.clock = clk
        tel.counter("serve.requests", 2.0, kind="factors")
        tl.sample()
        clk.advance(2.0)
        tel.counter("serve.requests", burst, kind="factors")
        tl.sample()
        rates[host] = burst / 2.0
        d = str(tmp_path / host)
        tel.write(d, host=host,
                  process_index=int(host[1]))
        dirs.append(d)
    out = str(tmp_path / "pod")
    verdict = aggregate_dirs(dirs, out)
    assert verdict["ok"]
    t = verdict["timeline"]
    assert t["pod_frames"] == 2 and t["per_host_frames"] == [2, 2]
    assert t["rate_sums"]["checked"] > 0
    assert t["rate_sums"]["mismatched"] == 0
    assert validate_dir(out)["ok"]
    with open(os.path.join(out, "metrics.jsonl")) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    pod_frames = [r for r in recs
                  if r["kind"] == "frame" and r.get("host") == "pod"]
    assert len(pod_frames) == 2
    key = "rate:serve.requests{kind=factors}"
    assert pod_frames[1]["series"][key] == sum(rates.values())
    # per-host frames are re-emitted with identity, distinct from the
    # fold
    per_host = [r for r in recs
                if r["kind"] == "frame" and r.get("host") in rates]
    assert len(per_host) == 4


# --------------------------------------------------------------------------
# incident replay (the CLI)
# --------------------------------------------------------------------------


def _write_incident_bundle(root, with_incident=True):
    """A hand-built bundle: frames spanning an alert window, request
    records sharing trace IDs with the dump, one slo alert event."""
    os.makedirs(root, exist_ok=True)
    t1 = 1000.0
    lines = []
    for i in range(5):
        lines.append({"schema": 4, "ts": t1 - 0.8 + i * 0.2,
                      "kind": "frame", "seq": i + 1, "interval_s": 0.2,
                      "series": {"rate:serve.load_shed{reason=breaker}":
                                 float(i * 10)}})
    lines.append({"schema": 4, "ts": t1, "kind": "slo",
                  "name": "availability",
                  "data": {"event": "alert", "burn_rate": 99.0}})
    for tid in ("tr-a", "tr-b"):
        lines.append({"schema": 4, "ts": t1 - 0.5, "kind": "request",
                      "trace_id": tid, "op": "factors", "status": "ok",
                      "data": {"total_s": 0.01}})
    with open(os.path.join(root, "metrics.jsonl"), "w") as fh:
        for rec in lines:
            fh.write(json.dumps(rec) + "\n")
    if not with_incident:
        return
    dump = [{"schema": 4, "ts": t1, "kind": "dump",
             "trigger": "slo_burn",
             "data": {"requests": 2,
                      "extra": {"event": "alert",
                                "objective": "availability",
                                "burn_rate": 99.0, "window": "fast",
                                "window_s": 1.0,
                                "top_moving": [{"series": "x"}]}}},
            {"schema": 4, "ts": t1 - 0.5, "kind": "request",
             "trace_id": "tr-a", "op": "factors", "status": "ok",
             "data": {"total_s": 0.01}},
            {"schema": 4, "ts": t1 - 0.4, "kind": "request",
             "trace_id": "tr-missing", "op": "factors",
             "status": "error", "data": {"total_s": 0.02}}]
    with open(os.path.join(root, "flight_1_1_slo_burn.jsonl"),
              "w") as fh:
        for rec in dump:
            fh.write(json.dumps(rec) + "\n")


def test_incident_report_reconstructs_burn_offline(tmp_path):
    root = str(tmp_path / "bundle")
    _write_incident_bundle(root)
    rep = incident_report(root)
    assert rep["ok"] and rep["frames"] == 5 and rep["slo_events"] == 1
    assert len(rep["incidents"]) == 1
    inc = rep["incidents"][0]
    assert inc["objective"] == "availability"
    assert inc["trigger"] == "slo_burn" and inc["window_s"] == 1.0
    # frames inside [t1 - window_s, t1] (with edge slack) all land
    assert inc["frames_in_window"] == 5
    assert inc["frame_diff"][0]["series"] \
        == "rate:serve.load_shed{reason=breaker}"
    assert inc["frame_diff"][0]["delta"] == 40.0
    # trace IDs cross-link dump <-> bundle request records; the dump's
    # unmatched trace stays unlinked (counted, not invented)
    assert inc["requests"] == {"in_dump": 2, "linked": 1,
                               "trace_ids": ["tr-a"]}
    assert inc["slo_events"] == 1


def test_timeline_cli_verdict_and_exit_codes(tmp_path, capsys):
    root = str(tmp_path / "bundle")
    _write_incident_bundle(root)
    rc = timeline_main([root, "--require-incident",
                        "--out", str(tmp_path / "report.json")])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    rep = json.loads(out)
    assert rc == 0 and rep["ok"] and len(rep["incidents"]) == 1
    with open(str(tmp_path / "report.json")) as fh:
        assert json.load(fh)["incidents"]
    # no incident + --require-incident -> exit 1 (the smoke-harness
    # mode); without the flag the empty report is still exit 0
    quiet = str(tmp_path / "quiet")
    _write_incident_bundle(quiet, with_incident=False)
    assert timeline_main([quiet, "--require-incident"]) == 1
    assert timeline_main([quiet]) == 0
    capsys.readouterr()
    # unreadable bundle -> exit 2 with a machine-readable error line
    rc = timeline_main([str(tmp_path / "missing")])
    err = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 2 and err["ok"] is False
