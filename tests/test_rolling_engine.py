"""Rolling-engine mechanics (ISSUE 3): the fused conv graph carries no
sequential loop (HLO-verified, not asserted from source), the 'pallas'
impl auto-falls back to conv off-TPU with the outcome counted, per-stage
telemetry carries the rolling_impl tag, buffer donation gates on
config + backend, and bench.py's resident-OOM path falls back to the
stream loop instead of re-raising.

Numerical parity lives in tests/test_parity.py (the fuzz-seeded sweeps
against the f64 oracle); this file owns the engine's plumbing contracts.
"""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.ops import rolling
from replication_of_minute_frequency_factor_tpu.telemetry import (
    Telemetry, attribution, get_telemetry)


def _lower_rolling(impl):
    f = jax.jit(lambda x, y, m: rolling.rolling_window_stats(
        x, y, m, 50, impl=impl))
    x = jnp.ones((2, 3, 240), jnp.float32)
    m = jnp.ones((2, 3, 240), bool)
    return f.lower(x, x, m)


def test_conv_graph_has_no_while_op():
    """Acceptance gate: the sequential fori_loop formulation is GONE
    from the conv graph — zero ``while`` ops in the lowered module, and
    the fused replacement's fingerprint (gather + dot_general +
    convolution) is present."""
    counts = attribution.hlo_op_counts(_lower_rolling("conv").as_text())
    assert counts["while"] == 0, counts
    assert counts["gather"] >= 1      # the strided window materialization
    assert counts["convolution"] >= 1  # the ones-kernel windowed sums


def test_hlo_op_counts_parses_both_dialects():
    text = ("%0 = stablehlo.while ... \n"
            "%1 = mhlo.dot_general ...\n"
            "%2 = stablehlo.reduce_window ...\n"  # must NOT count as reduce
            "%3 = stablehlo.gather ...")
    counts = attribution.hlo_op_counts(text)
    assert counts == {"while": 1, "dot_general": 1, "convolution": 0,
                      "gather": 1, "reduce": 0, "sort": 0}


def test_unknown_rolling_impl_raises():
    with pytest.raises(ValueError, match="rolling_impl"):
        rolling.rolling_window_stats(
            jnp.ones((1, 240)), jnp.ones((1, 240)),
            jnp.ones((1, 240), bool), 50, impl="cumsum")


def test_pallas_falls_back_to_conv_on_cpu():
    """impl='pallas' off-TPU resolves to conv — bit-identical results to
    an explicit conv call, and the resolution lands in the registry so
    attribution output says which backend actually ran."""
    rng = np.random.default_rng(5)
    x = jnp.asarray(rng.normal(10, 0.1, (2, 240)).astype(np.float32))
    y = x * 1.001
    m = jnp.asarray(rng.random((2, 240)) > 0.1)
    reg = get_telemetry().registry
    before = reg.counter_value("rolling.impl", requested="pallas",
                               resolved="conv")
    jax.clear_caches()  # force a retrace so the trace-time counter fires
    out_p = rolling.rolling_window_stats(x, y, m, 50, impl="pallas")
    out_c = rolling.rolling_window_stats(x, y, m, 50, impl="conv")
    for k in out_c:
        np.testing.assert_array_equal(np.asarray(out_p[k]),
                                      np.asarray(out_c[k]))
    assert reg.counter_value("rolling.impl", requested="pallas",
                             resolved="conv") > before


@pytest.mark.pallas
def test_pallas_kernel_odd_row_counts_pad_and_slice():
    """Row counts that don't divide the VMEM block (including fewer rows
    than one f32 sublane tile) round-trip through the pad/slice path."""
    from replication_of_minute_frequency_factor_tpu.ops import rolling_pallas

    rng = np.random.default_rng(0)
    for lead in ((1,), (3,), (2, 5)):
        xc = jnp.asarray(rng.normal(0, 0.1, lead + (64,)).astype(np.float32))
        yc = xc * 0.5
        mu_x = jnp.zeros_like(xc)
        mu_y = jnp.zeros_like(xc)
        s_xx, s_yy, s_xy = rolling_pallas.second_moments(
            xc, yc, mu_x, mu_y, 10, interpret=True, block_rows=8)
        assert s_xx.shape == lead + (64,)
        ref = np.zeros(lead + (64,), np.float32)
        xnp = np.asarray(xc)
        for j in range(10):
            sh = np.zeros_like(xnp)
            sh[..., j:] = xnp[..., :64 - j]
            ref += sh * sh
        np.testing.assert_allclose(np.asarray(s_xx), ref,
                                   rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(np.asarray(s_xy),
                                   np.asarray(s_xx) * 0.5,
                                   rtol=1e-5, atol=1e-7)


def test_stage_timer_carries_rolling_impl_label():
    tel = Telemetry(annotate_spans=False)
    t = tel.stage_timer(rolling_impl="conv")
    with t("device"):
        pass
    keys = tel.registry.snapshot()["histograms"]
    assert "span_seconds{rolling_impl=conv,span=device}" in keys
    # Timer semantics intact for ExposureTable.timings
    assert "device" in t.totals()


def test_donation_gates_on_config_and_backend(monkeypatch):
    from replication_of_minute_frequency_factor_tpu import config as cfgmod
    from replication_of_minute_frequency_factor_tpu import pipeline

    cfg = cfgmod.Config()
    assert cfg.donate_buffers  # default on
    # CPU backend (the test harness): never donate — CPU PJRT ignores
    # donation with a per-compile warning
    assert pipeline._donate_device_buffers(cfg) is False
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    assert pipeline._donate_device_buffers(cfg) is True
    cfg.donate_buffers = False
    assert pipeline._donate_device_buffers(cfg) is False


def test_donated_and_plain_packed_paths_share_one_function():
    """The donated twins must wrap the SAME python callables — a fix to
    the graph that only lands in one twin would silently fork the
    device semantics by backend."""
    from replication_of_minute_frequency_factor_tpu import pipeline

    assert (pipeline._compute_packed_jit.__wrapped__
            is pipeline._compute_packed_jit_donated.__wrapped__)
    assert (pipeline._compute_packed_scan_jit.__wrapped__
            is pipeline._compute_packed_scan_jit_donated.__wrapped__)
    assert (pipeline._compute_from_wire_jit.__wrapped__
            is pipeline._compute_from_wire_jit_donated.__wrapped__)


def test_bench_resident_oom_falls_back_to_stream(monkeypatch, capsys):
    """ADVICE r5 (bench.py:430) + ISSUE 5 ladder: a resident warmup
    that still OOMs at group == 1 must walk the WHOLE fallback ladder —
    sharded -> single-device resident -> stream at the proven 8-day
    shape — and print a record, not re-raise and lose the hardware
    window. The emitted record must say so (mode/methodology/n_shards
    flip, warm.sharded_oom_fallback + warm.resident_oom_fallback carry
    the errors)."""
    import sys
    import types

    import bench

    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    monkeypatch.setenv("BENCH_FACTORS", "vol_return1min")
    monkeypatch.setenv("BENCH_STAGES", "0")
    monkeypatch.delenv("BENCH_MODE", raising=False)
    monkeypatch.setattr(bench, "MODE", "resident")
    monkeypatch.setattr(bench, "N_TICKERS", 30)
    monkeypatch.setattr(bench, "DAYS_PER_BATCH", 2)
    monkeypatch.setattr(bench, "ITERS", 2)
    monkeypatch.setattr(bench, "_SUFFIX", "")
    monkeypatch.setattr(bench, "_wait_host_quiet", lambda *a, **k: True)
    stub = types.ModuleType("tools.cpu_busy")
    stub.mark_busy = lambda *a, **k: None
    stub.live_owners = lambda: []
    monkeypatch.setitem(sys.modules, "tools.cpu_busy", stub)

    def oom(*a, **k):
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                           "(synthetic, injected by test)")

    monkeypatch.setattr(bench, "run_resident", oom)
    monkeypatch.setattr(bench, "run_resident_sharded", oom)
    bench.main()
    lines = [ln for ln in capsys.readouterr().out.splitlines()
             if ln.startswith("{")]
    rec = json.loads(lines[-1])
    assert rec["mode"] == "stream"
    # the result wire stays on through the OOM ladder (it shrinks the
    # fetch footprint too), so the fallback lands on the r10 stream
    # series with the wire block stamped
    assert rec["methodology"] == "r10_stream_v4"
    assert rec["result_wire"]["enabled"] is True
    assert rec["n_shards"] == 1
    assert rec["days_per_batch"] == 8
    # both ladder rungs recorded: sharded scan OOM'd first (the test
    # harness exposes 8 virtual devices), then the single-device scan
    assert "RESOURCE_EXHAUSTED" in rec["warm"]["sharded_oom_fallback"]
    assert "RESOURCE_EXHAUSTED" in rec["warm"]["resident_oom_fallback"]
    assert rec["round_trips"]["host_blocking_syncs"] > 0
    assert set(rec["round_trips"]["predicted_fields"]) == {
        "puts_async", "executes", "fetches"}
