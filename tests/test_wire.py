"""Wire format: exact round-trip, fallback detection, factor equality."""

import jax.numpy as jnp
import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day
from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit)


@pytest.fixture
def batch(rng):
    days = []
    for _ in range(2):
        cols = synth_day(rng, n_codes=10, missing_prob=0.1,
                         zero_volume_prob=0.1, short_day_codes=2)
        g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                     cols["low"], cols["close"], cols["volume"])
        days.append(g)
    bars = np.stack([g.bars for g in days])
    mask = np.stack([g.mask for g in days])
    return bars, mask


def test_roundtrip_exact(batch):
    bars, mask = batch
    w = wire.encode(bars, mask)
    assert w is not None
    # synthetic intra-bar wicks are narrow -> 2-byte wick packing
    assert w.dohl.dtype == np.uint8 and w.dohl.shape[-1] == 2
    # synthetic volumes aren't board lots, so volume ships int32 here;
    # lot data reaches ~0.25 (bench batches)
    assert w.nbytes < 0.4 * (bars.nbytes + mask.nbytes)
    out_bars, out_mask = wire.decode(*w.arrays)
    out_bars = np.asarray(out_bars)
    np.testing.assert_array_equal(np.asarray(out_mask), mask)
    # prices within 1 ulp (XLA reciprocal-multiply, see wire.py docstring);
    # volumes exact
    np.testing.assert_allclose(out_bars[mask][:, :4],
                               bars[mask][:, :4].astype(np.float32),
                               rtol=2.5e-7)
    np.testing.assert_array_equal(out_bars[mask][:, 4], bars[mask][:, 4])
    # equal tick counts decode identically: a flat bar stays exactly flat
    flat = bars[mask][:, 0] == bars[mask][:, 3]  # open == close
    np.testing.assert_array_equal(out_bars[mask][flat, 0],
                                  out_bars[mask][flat, 3])


def test_factors_identical_through_wire(batch):
    bars, mask = batch
    w = wire.encode(bars, mask)
    direct = compute_factors_jit(bars, mask)
    via = compute_factors_jit(*wire.decode(*w.arrays))
    for k in direct:
        a, b = np.asarray(direct[k]), np.asarray(via[k])
        np.testing.assert_array_equal(np.isfinite(a), np.isfinite(b),
                                      err_msg=f"{k} NaN pattern differs")
        ok = np.isfinite(a)
        # higher-moment factors amplify the 1-ulp price wobble (returns are
        # ~3e-4, so a 1e-7 price shift is ~3e-4 relative on a return)
        np.testing.assert_allclose(
            a[ok], b[ok], rtol=2e-3, atol=5e-4,
            err_msg=f"{k} differs through wire decode")


def test_encode_rejects_unrepresentable(batch):
    bars, mask = batch
    b = bars.copy()
    b[0, 0, 0, 3] = 1.005  # off-tick price on a valid lane
    mask2 = mask.copy()
    mask2[0, 0, 0] = True
    assert wire.encode(b, mask2) is None

    b = bars.copy()
    b[mask][:, 4]  # volumes are ints; make one fractional
    b2 = bars.copy()
    i = tuple(np.argwhere(mask)[0])
    b2[i][4] = 10.5
    assert wire.encode(b2, mask) is None

    b3 = bars.copy()
    b3[i][3] = b3[i][3] + 400.0  # 40k-tick jump overflows int16
    assert wire.encode(b3, mask) is None


def test_widen_only_floor_is_sticky(batch):
    """A pipeline-run floor keeps later batches at the widest dtype seen,
    so the jit cache sees a bounded set of signatures."""
    bars, mask = batch
    wide = bars.copy()
    i = tuple(np.argwhere(mask)[0])
    wide[i][1] = wide[i][3] + 3.0  # 300-tick intra-bar range
    mid = bars.copy()
    mid[i][1] = mid[i][3] + 0.25  # 25-tick wick: too wide for nibbles
    floor = {}
    a = wire.encode(bars, mask, floor=floor)
    assert a.dohl.dtype == np.uint8 and a.dohl.shape[-1] == 2
    m_ = wire.encode(mid, mask, floor=floor)
    assert m_.dohl.dtype == np.int8 and m_.dohl.shape[-1] == 3
    b = wire.encode(wide, mask, floor=floor)
    assert b.dohl.dtype == np.int16
    c = wire.encode(bars, mask, floor=floor)  # narrow again -> stays wide
    assert c.dohl.dtype == np.int16
    # the mid batch round-trips exactly through the int8 path
    out_mid, _ = wire.decode(*wire.encode(mid, mask).arrays)
    np.testing.assert_allclose(np.asarray(out_mid)[i][1], mid[i][1],
                               rtol=2.5e-7)
    # and decode of the widened batch still round-trips
    out_bars, _ = wire.decode(*c.arrays)
    np.testing.assert_allclose(np.asarray(out_bars)[mask][:, 3],
                               bars[mask][:, 3], rtol=2.5e-7)


def test_coerce_dates_formats():
    from replication_of_minute_frequency_factor_tpu.data.io import coerce_dates
    want = np.array(["2024-01-02"], "datetime64[D]")
    for raw in (["2024-01-02"], ["20240102"], [b"20240102"], [20240102],
                np.array(["2024-01-02"], "datetime64[s]")):
        np.testing.assert_array_equal(coerce_dates(np.array(raw)), want,
                                      err_msg=str(raw))
    # missing entries stay NaT instead of failing the whole read, in both
    # ISO and compact columns, even when the first element is the empty one
    for col in (["2024-01-02", ""], ["20240102", ""], ["", "20240102"],
                [" ", "20240102"]):
        out = coerce_dates(np.array(col))
        good = [i for i, x in enumerate(col) if x.strip()]
        assert not np.isnat(out[good[0]]), col
        assert np.isnat(out[1 - good[0]]), col
    # garbage that numpy would parse as a year raises loudly
    with pytest.raises(ValueError):
        coerce_dates(np.array(["2024010"]))


def test_wide_intrabar_range_widens_dohl_not_fallback(batch):
    """A bar whose high-close spread exceeds 127 ticks widens dohl to
    int16 (e.g. a 1000+ CNY ticker) instead of rejecting the batch."""
    bars, mask = batch
    b = bars.copy()
    i = tuple(np.argwhere(mask)[0])
    b[i][1] = b[i][3] + 3.0  # high 300 ticks above close
    for use_native in (True, False):
        try:
            w = wire.encode(b, mask, use_native=use_native)
        except RuntimeError:
            continue  # no C++ toolchain
        assert w is not None and w.dohl.dtype == np.int16
        out_bars, out_mask = wire.decode(*w.arrays)
        np.testing.assert_allclose(
            np.asarray(out_bars)[i][1], b[i][1], rtol=2.5e-7)


def test_pack_unpack_roundtrip(batch):
    """Single-buffer transfer: pack_arrays -> device unpack must return
    every wire array bit-exactly (dtypes, shapes, scalar included)."""
    bars, mask = batch
    w = wire.encode(bars, mask, use_native=False)
    buf, spec = wire.pack_arrays(w.arrays)
    assert buf.dtype == np.uint8 and buf.ndim == 1
    out = wire.unpack(jnp.asarray(buf), spec)
    for got, want in zip(out, w.arrays):
        want = np.asarray(want)
        assert np.asarray(got).dtype == want.dtype
        np.testing.assert_array_equal(np.asarray(got), want)


def test_packed_compute_matches_dict_paths(batch):
    """The packed single-buffer pipeline path (wire and raw-f32 fallback)
    must equal the per-array dict path factor-for-factor."""
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        _compute_from_wire, compute_packed)

    bars, mask = batch
    names = ("vol_return1min", "mmt_pm", "doc_kurt", "liq_amihud_1min")
    w = wire.encode(bars, mask, use_native=False)
    want = _compute_from_wire(*w.arrays, names=names, replicate_quirks=True)
    got = np.asarray(compute_packed(w.arrays, "wire", names=names,
                                    replicate_quirks=True))
    assert got.shape == (len(names),) + bars.shape[:2]
    for j, n in enumerate(names):
        np.testing.assert_array_equal(got[j], np.asarray(want[n]), err_msg=n)
    got_raw = np.asarray(compute_packed(
        (bars, mask.view(np.uint8)), "raw", names=names,
        replicate_quirks=True))
    for j, n in enumerate(names):
        np.testing.assert_allclose(got_raw[j], got[j], rtol=2e-5, atol=1e-7,
                                   err_msg=n)


def test_resident_scan_matches_per_batch(rng):
    """The resident scan path (one executable over N packed buffers —
    the r5 O(1)-round-trip headline loop) must equal the per-batch
    packed path bit-for-bit, including when a later batch widens the
    shared floor (bench.encode_year's uniform-spec contract)."""
    import jax
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        compute_packed_prepared, compute_packed_resident)

    names = ("vol_return1min", "mmt_ols_qrs", "doc_pdf60", "trade_headRatio")
    batches = []
    for i in range(3):
        days = []
        for _ in range(2):
            cols = synth_day(rng, n_codes=8, missing_prob=0.1,
                             zero_volume_prob=0.1)
            if i == 2:
                # integer price scale keeps ticks aligned while blowing
                # up the tick deltas, so the shared floor widens AFTER
                # batches 0/1 were encoded (exercises the re-encode)
                for f in ("open", "high", "low", "close"):
                    cols[f] = (cols[f] * 50).astype(np.float32)
            g = grid_day(cols["code"], cols["time"], cols["open"],
                         cols["high"], cols["low"], cols["close"],
                         cols["volume"])
            days.append(g)
        batches.append((np.stack([g.bars for g in days]),
                        np.stack([g.mask for g in days])))

    import bench
    bufs, spec, kind = bench.encode_year(batches, use_wire=True)
    assert len({b.nbytes for b in bufs}) == 1  # uniform length
    dbufs = tuple(jax.device_put(b) for b in bufs)
    got = np.asarray(compute_packed_resident(dbufs, spec, kind,
                                             names=names))
    assert got.shape == (3, len(names), 2, batches[0][0].shape[1])
    for i, buf in enumerate(bufs):
        want = np.asarray(compute_packed_prepared(buf, spec, kind,
                                                  names=names))
        np.testing.assert_array_equal(got[i], want, err_msg=f"batch {i}")


def test_wire_fuzz_native_numpy_byte_parity():
    """Compact randomized sweep (the long-run version cleared 700 seeds):
    random shapes, price scales from 0.05 to 41000 CNY, volume modes,
    halts, dead-lane garbage, off-tick poison — native and numpy must
    agree byte-for-byte (including widen floors and None verdicts) and
    decode must round-trip within 3e-7."""
    from replication_of_minute_frequency_factor_tpu import native
    if not native.available():
        pytest.skip("no C++ toolchain")
    for seed in range(24):
        rng = np.random.default_rng(9000 + seed)
        D = int(rng.integers(1, 3))
        T = int(rng.integers(2, 12))
        base_price = float(rng.choice([0.05, 12.0, 300.0, 1700.0, 41000.0]))
        shape = (D, T, 240)
        close = base_price * np.exp(np.cumsum(
            rng.normal(0, rng.choice([1e-4, 5e-3]), shape), -1))
        open_ = close * (1 + rng.normal(0, 1e-4, shape))
        high = np.maximum(open_, close) * (
            1 + np.abs(rng.normal(0, 2e-4, shape)))
        low = np.minimum(open_, close) * (
            1 - np.abs(rng.normal(0, 2e-4, shape)))
        volume = (rng.integers(0, 1000, shape) *
                  int(rng.choice([1, 100, 100000]))).astype(np.float64)
        bars = np.stack([open_, high, low, close, volume], -1)
        bars[..., :4] = np.round(bars[..., :4], 2)
        bars = np.maximum(bars, 0.01 * (np.arange(5) < 4)).astype(np.float32)
        mask = rng.random(shape) > rng.choice([0.0, 0.05, 0.5])
        if rng.random() < 0.4:
            mask[:, int(rng.integers(0, T))] = False  # halt
            dead = np.argwhere(~mask)
            bars[tuple(dead[0])] = np.nan  # dead-lane garbage
        if rng.random() < 0.25:
            live = np.argwhere(mask)
            if len(live):
                bars[tuple(live[0])][3] += 0.003  # off-tick poison
        fa, fb = {}, {}
        a = wire.encode(bars, mask, use_native=True, floor=fa)
        b = wire.encode(bars, mask, use_native=False, floor=fb)
        assert (a is None) == (b is None), seed
        if a is None:
            continue
        assert fa == fb, seed
        for x, y in zip(a.arrays, b.arrays):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y),
                                          err_msg=str(seed))
        dec, dm = wire.decode(*a.arrays)
        assert np.array_equal(np.asarray(dm), mask), seed
        err = np.abs(np.asarray(dec)[mask] - bars[mask]) / np.maximum(
            np.abs(bars[mask]), 1e-6)
        assert err.max() < 3e-7, (seed, err.max())


# --------------------------------------------------------------------------
# framed exposure-cache IO (ISSUE 10: the on-disk half of the wire
# program — data/io.frame_bytes / write_framed_table_atomic)
# --------------------------------------------------------------------------


def test_frame_roundtrip_and_telemetry():
    from replication_of_minute_frequency_factor_tpu.data import io as dio
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    tel = set_telemetry(Telemetry())
    data = b"minute-factor" * 1000
    blob = dio.frame_bytes(data)
    assert blob[:4] == dio.FRAME_MAGIC
    assert len(blob) < len(data)          # compresses repetitive bytes
    assert dio.unframe_bytes(blob) == data
    kind = dio.pick_frame_codec()         # zlib in this container
    reg = tel.registry
    assert reg.counter_value("io.frame_codec", kind=kind,
                             op="encode") == 1
    assert reg.counter_value("io.frame_codec", kind=kind,
                             op="decode") == 1


def test_frame_codec_chain_falls_back_gracefully(monkeypatch):
    """zstd -> lz4 -> stdlib zlib: with neither wheel installed (this
    container) the chain lands on zlib; an explicit unavailable codec
    raises with the chain named; a corrupt magic raises."""
    from replication_of_minute_frequency_factor_tpu.data import io as dio

    real = dio._codec_module

    def no_wheels(kind):
        return None if kind in ("zstd", "lz4") else real(kind)

    monkeypatch.setattr(dio, "_codec_module", no_wheels)
    assert dio.pick_frame_codec() == "zlib"
    blob = dio.frame_bytes(b"x" * 100)
    assert dio.unframe_bytes(blob) == b"x" * 100
    with pytest.raises(ValueError, match="not available"):
        dio.frame_bytes(b"x", codec="zstd")
    with pytest.raises(ValueError, match="magic"):
        dio.unframe_bytes(b"NOPE" + blob[4:])
    # a frame written with a codec this host lacks names the codec
    zstd_framed = bytes(dio.FRAME_MAGIC) + bytes([0]) \
        + (8).to_bytes(8, "little") + b"payload!"
    with pytest.raises(ValueError, match="zstd"):
        dio.unframe_bytes(zstd_framed)


def test_exposure_cache_framed_and_parquet_roundtrip(tmp_path):
    """ExposureTable.save/load: `.mffz` takes the framed arrow-IPC
    format, everything else parquet (codec-picked, counted); both
    round-trip the cache bit-for-bit."""
    from replication_of_minute_frequency_factor_tpu.pipeline import (
        ExposureTable)
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        Telemetry, set_telemetry)

    tel = set_telemetry(Telemetry())
    cols = {
        "code": np.array(["000001", "000002"], object),
        "date": np.array(["2024-01-02", "2024-01-03"],
                         "datetime64[D]"),
        "vol_return1min": np.array([0.5, np.nan], np.float32),
    }
    t = ExposureTable(dict(cols))
    for name in ("cache.parquet", "cache.mffz"):
        path = str(tmp_path / name)
        t.save(path)
        back = ExposureTable.load(path)
        assert list(back.columns) == list(cols)
        np.testing.assert_array_equal(back.columns["code"],
                                      cols["code"])
        np.testing.assert_array_equal(back.columns["date"],
                                      cols["date"])
        np.testing.assert_array_equal(back.columns["vol_return1min"],
                                      cols["vol_return1min"])
    reg = tel.registry
    assert reg.counter_total("io.parquet_codec") == 1
    assert reg.counter_total("io.framed_writes") == 1
    assert reg.counter_value("io.frame_codec", kind="zlib",
                             op="encode") == 1
