"""Wire format: exact round-trip, fallback detection, factor equality."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day
from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit)


@pytest.fixture
def batch(rng):
    days = []
    for _ in range(2):
        cols = synth_day(rng, n_codes=10, missing_prob=0.1,
                         zero_volume_prob=0.1, short_day_codes=2)
        g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                     cols["low"], cols["close"], cols["volume"])
        days.append(g)
    bars = np.stack([g.bars for g in days])
    mask = np.stack([g.mask for g in days])
    return bars, mask


def test_roundtrip_exact(batch):
    bars, mask = batch
    w = wire.encode(bars, mask)
    assert w is not None
    assert w.nbytes < 0.65 * (bars.nbytes + mask.nbytes)
    out_bars, out_mask = wire.decode(w.base, w.deltas, w.volume, w.mask)
    out_bars = np.asarray(out_bars)
    np.testing.assert_array_equal(np.asarray(out_mask), mask)
    # prices within 1 ulp (XLA reciprocal-multiply, see wire.py docstring);
    # volumes exact
    np.testing.assert_allclose(out_bars[mask][:, :4],
                               bars[mask][:, :4].astype(np.float32),
                               rtol=2.5e-7)
    np.testing.assert_array_equal(out_bars[mask][:, 4], bars[mask][:, 4])
    # equal tick counts decode identically: a flat bar stays exactly flat
    flat = bars[mask][:, 0] == bars[mask][:, 3]  # open == close
    np.testing.assert_array_equal(out_bars[mask][flat, 0],
                                  out_bars[mask][flat, 3])


def test_factors_identical_through_wire(batch):
    bars, mask = batch
    w = wire.encode(bars, mask)
    direct = compute_factors_jit(bars, mask)
    via = compute_factors_jit(*wire.decode(w.base, w.deltas, w.volume, w.mask))
    for k in direct:
        a, b = np.asarray(direct[k]), np.asarray(via[k])
        np.testing.assert_array_equal(np.isfinite(a), np.isfinite(b),
                                      err_msg=f"{k} NaN pattern differs")
        ok = np.isfinite(a)
        # higher-moment factors amplify the 1-ulp price wobble (returns are
        # ~3e-4, so a 1e-7 price shift is ~3e-4 relative on a return)
        np.testing.assert_allclose(
            a[ok], b[ok], rtol=2e-3, atol=5e-4,
            err_msg=f"{k} differs through wire decode")


def test_encode_rejects_unrepresentable(batch):
    bars, mask = batch
    b = bars.copy()
    b[0, 0, 0, 3] = 1.005  # off-tick price on a valid lane
    mask2 = mask.copy()
    mask2[0, 0, 0] = True
    assert wire.encode(b, mask2) is None

    b = bars.copy()
    b[mask][:, 4]  # volumes are ints; make one fractional
    b2 = bars.copy()
    i = tuple(np.argwhere(mask)[0])
    b2[i][4] = 10.5
    assert wire.encode(b2, mask) is None

    b3 = bars.copy()
    b3[i][3] = b3[i][3] + 400.0  # 40k-tick jump overflows int16
    assert wire.encode(b3, mask) is None
