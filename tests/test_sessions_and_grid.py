"""Session grid and gridding round-trips."""

import numpy as np

from replication_of_minute_frequency_factor_tpu import sessions
from replication_of_minute_frequency_factor_tpu.data import grid_day, synth_day
from replication_of_minute_frequency_factor_tpu.data.minute import (
    F_CLOSE, F_VOLUME)


def test_grid_times_shape_and_sentinels():
    t = sessions.GRID_TIMES
    assert len(t) == 240
    assert t[0] == 93000000
    assert t[119] == 112900000
    assert t[120] == 130000000
    assert t[239] == 145900000
    assert t[237] == 145700000


def test_time_to_slot_roundtrip():
    slots = np.arange(240)
    assert np.array_equal(sessions.time_to_slot(sessions.GRID_TIMES), slots)


def test_time_to_slot_rejects_offgrid():
    # 11:30 must NOT alias onto 13:00 (reference formula would collide)
    bad = np.array([113000000, 92900000, 150000000, 125900000, 93000500])
    assert np.all(sessions.time_to_slot(bad) == -1)


def test_grid_day_scatter(rng):
    day = synth_day(rng, n_codes=5, missing_prob=0.2)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"])
    assert g.bars.shape == (5, 240, 5)
    assert g.mask.sum() == len(day["code"])
    # spot-check one row round-trips
    i = 7
    code, t = day["code"][i], day["time"][i]
    ti = list(g.codes).index(code)
    si = int(sessions.time_to_slot(np.array([t]))[0])
    assert g.mask[ti, si]
    np.testing.assert_allclose(g.bars[ti, si, F_CLOSE], day["close"][i],
                               rtol=1e-6)
    np.testing.assert_allclose(g.bars[ti, si, F_VOLUME], day["volume"][i],
                               rtol=1e-6)


def test_grid_day_short_and_constant(rng):
    day = synth_day(rng, n_codes=6, constant_price_codes=2, short_day_codes=2)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"])
    # short-day codes only hold the last 30 slots
    assert g.mask[-1].sum() == 30
    assert g.mask[-1, -30:].all()
    # constant-price codes are flat
    const_close = g.bars[0, :, F_CLOSE]
    assert np.allclose(const_close, const_close[0])


def test_grid_day_unsorted_pinned_codes(rng):
    """Regression: caller-supplied unsorted universe must not drop rows."""
    day = synth_day(rng, n_codes=3)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"],
                 codes=["600002", "600000", "600001"])
    assert g.mask.sum() == len(day["code"])
    assert list(g.codes) == sorted(g.codes)
