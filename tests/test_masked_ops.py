"""Property tests: masked ops vs straight numpy/scipy on the valid subset."""

import numpy as np
import pytest
import scipy.stats

from replication_of_minute_frequency_factor_tpu.ops import (
    bottomk_threshold,
    ffill,
    masked_corr,
    masked_first,
    masked_kurtosis,
    masked_last,
    masked_mean,
    masked_product,
    masked_skew,
    masked_std,
    pct_change_valid,
    rank_average,
    rolling_window_stats,
    shift_valid,
    topk_sum,
    topk_threshold,
)


def _data(rng, rows=6, L=40, p_valid=0.7):
    x = rng.normal(0, 1, (rows, L))
    mask = rng.random((rows, L)) < p_valid
    mask[0] = True          # fully valid row
    mask[1] = False         # empty row
    mask[2, :2] = True      # nearly-empty row
    mask[2, 2:] = False
    return x, mask


def test_moments_match_numpy(rng):
    x, mask = _data(rng)
    mean = np.asarray(masked_mean(x, mask))
    std = np.asarray(masked_std(x, mask))
    skew = np.asarray(masked_skew(x, mask))
    kurt = np.asarray(masked_kurtosis(x, mask))
    for i in range(x.shape[0]):
        v = x[i, mask[i]]
        if len(v) == 0:
            assert np.isnan(mean[i]) and np.isnan(std[i])
            continue
        np.testing.assert_allclose(mean[i], v.mean(), rtol=1e-5)
        if len(v) >= 2:
            np.testing.assert_allclose(std[i], v.std(ddof=1), rtol=1e-4)
            np.testing.assert_allclose(
                skew[i], scipy.stats.skew(v, bias=True), rtol=1e-3, atol=1e-5)
            np.testing.assert_allclose(
                kurt[i], scipy.stats.kurtosis(v, bias=True, fisher=True),
                rtol=1e-3, atol=1e-5)
        else:
            assert np.isnan(std[i])


def test_corr_matches_numpy(rng):
    x, mask = _data(rng)
    y = np.asarray(rng.normal(0, 1, x.shape))
    r = np.asarray(masked_corr(x, y, mask))
    for i in range(x.shape[0]):
        v, w = x[i, mask[i]], y[i, mask[i]]
        if len(v) < 2:
            assert np.isnan(r[i])
        else:
            np.testing.assert_allclose(r[i], np.corrcoef(v, w)[0, 1],
                                       rtol=1e-4, atol=1e-6)


def test_first_last_product(rng):
    x, mask = _data(rng)
    first = np.asarray(masked_first(x, mask))
    last = np.asarray(masked_last(x, mask))
    prod = np.asarray(masked_product(1.0 + 0.01 * x, mask))
    for i in range(x.shape[0]):
        v = x[i, mask[i]]
        if len(v) == 0:
            assert np.isnan(first[i]) and np.isnan(last[i])
            continue
        assert first[i] == pytest.approx(v[0], rel=1e-6)
        assert last[i] == pytest.approx(v[-1], rel=1e-6)
        np.testing.assert_allclose(prod[i], np.prod(1.0 + 0.01 * v), rtol=1e-5)


def test_rank_average_matches_scipy(rng):
    x, mask = _data(rng)
    x = np.round(x, 1)  # force ties
    r = np.asarray(rank_average(x, mask))
    for i in range(x.shape[0]):
        v = x[i, mask[i]]
        if len(v) == 0:
            assert np.all(np.isnan(r[i]))
            continue
        np.testing.assert_allclose(r[i, mask[i]],
                                   scipy.stats.rankdata(v, method="average"),
                                   rtol=1e-6)
        assert np.all(np.isnan(r[i, ~mask[i]]))


def test_topk_threshold(rng):
    x, mask = _data(rng)
    for k in (1, 5, 100):
        thr = np.asarray(topk_threshold(x, mask, k))
        thb = np.asarray(bottomk_threshold(x, mask, k))
        ts = np.asarray(topk_sum(x, mask, k))
        for i in range(x.shape[0]):
            v = np.sort(x[i, mask[i]])
            if len(v) == 0:
                assert np.isnan(thr[i]) and np.isnan(thb[i]) and np.isnan(ts[i])
                continue
            top = v[-k:] if k <= len(v) else v
            assert thr[i] == pytest.approx(top.min(), rel=1e-6)
            bot = v[:k] if k <= len(v) else v
            assert thb[i] == pytest.approx(bot.max(), rel=1e-6)
            np.testing.assert_allclose(ts[i], top.sum(), rtol=1e-5)


def test_shift_and_pct_change(rng):
    x, mask = _data(rng)
    x = np.abs(x) + 1.0
    sv, sm = shift_valid(x, mask, 1)
    sv, sm = np.asarray(sv), np.asarray(sm)
    pv, pm = pct_change_valid(x, mask)
    pv, pm = np.asarray(pv), np.asarray(pm)
    lv, lm = shift_valid(x, mask, -1)
    lv, lm = np.asarray(lv), np.asarray(lm)
    for i in range(x.shape[0]):
        idx = np.flatnonzero(mask[i])
        v = x[i, idx]
        # forward shift: first valid lane has no predecessor
        if len(idx):
            assert not sm[i, idx[0]]
            assert not pm[i, idx[0]]
            assert not lm[i, idx[-1]]
        for j in range(1, len(idx)):
            assert sm[i, idx[j]]
            assert sv[i, idx[j]] == pytest.approx(v[j - 1], rel=1e-6)
            assert pv[i, idx[j]] == pytest.approx(v[j] / v[j - 1] - 1, rel=1e-4, abs=1e-6)
            assert lv[i, idx[j - 1]] == pytest.approx(v[j], rel=1e-6)


def test_ffill(rng):
    x, mask = _data(rng)
    f, has = ffill(x, mask)
    f, has = np.asarray(f), np.asarray(has)
    for i in range(x.shape[0]):
        last = None
        for j in range(x.shape[1]):
            if mask[i, j]:
                last = x[i, j]
            if last is None:
                assert not has[i, j]
            else:
                assert has[i, j]
                assert f[i, j] == pytest.approx(last, rel=1e-6)


def test_rolling_window_stats_vs_naive(rng):
    L, W = 60, 10
    x = rng.normal(10, 1, (3, L))
    y = x + rng.normal(0, 0.3, (3, L))
    mask = rng.random((3, L)) < 0.9
    mask[0] = True
    st = {k: np.asarray(v) for k, v in
          rolling_window_stats(x, y, mask, window=W).items()}
    for i in range(3):
        for m in range(L):
            lo = m - W + 1
            if lo < 0:
                continue
            sel = mask[i, lo:m + 1]
            expected_valid = sel.all()
            assert bool(st["valid"][i, m]) == expected_valid
            if not expected_valid:
                continue
            xs, ys = x[i, lo:m + 1], y[i, lo:m + 1]
            np.testing.assert_allclose(st["mean_x"][i, m], xs.mean(), rtol=1e-5)
            np.testing.assert_allclose(st["mean_y"][i, m], ys.mean(), rtol=1e-5)
            np.testing.assert_allclose(st["cov"][i, m],
                                       np.cov(xs, ys, ddof=0)[0, 1],
                                       rtol=1e-3, atol=1e-6)
            np.testing.assert_allclose(st["var_x"][i, m], xs.var(ddof=0),
                                       rtol=1e-3, atol=1e-6)


def test_inf_values_do_not_collide_with_invalid_sentinel():
    """Regression: valid +inf lanes must not tie-group with invalid lanes."""
    import jax.numpy as jnp
    from replication_of_minute_frequency_factor_tpu.ops import pdf_quantile_rank

    x = np.array([[1.0, np.inf, 2.0, 123.0]])
    mask = np.array([[True, True, True, False]])
    r = np.asarray(rank_average(jnp.asarray(x), jnp.asarray(mask)))
    np.testing.assert_allclose(r[0, :3], [1.0, 3.0, 2.0])

    vals = np.array([[0.0, np.inf, 1.0, 9.0]])
    w = np.array([[0.2, 0.5, 0.3, 9.0]])
    out = np.asarray(pdf_quantile_rank(jnp.asarray(vals), jnp.asarray(w),
                                       jnp.asarray(mask), 0.6))
    assert np.isinf(out[0])
