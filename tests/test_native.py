"""Native C++ grid packer == numpy packer, bit for bit."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import native, sessions
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def test_native_matches_numpy(rng):
    cols = synth_day(rng, n_codes=20, missing_prob=0.1, zero_volume_prob=0.1,
                     short_day_codes=3, constant_price_codes=2)
    # inject off-grid rows the packer must drop: lunch break + sub-minute
    cols["time"][::37] = 120000000
    cols["time"][5] = 93000500
    a = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], use_native=True)
    b = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], use_native=False)
    np.testing.assert_array_equal(a.mask, b.mask)
    np.testing.assert_array_equal(a.bars, b.bars)
    np.testing.assert_array_equal(a.codes, b.codes)


def test_native_unknown_codes_and_pinned_axis(rng):
    cols = synth_day(rng, n_codes=4)
    pinned = np.array(["600000", "600002", "999999"], dtype=object)
    a = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], codes=pinned,
                 use_native=True)
    b = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], codes=pinned,
                 use_native=False)
    np.testing.assert_array_equal(a.mask, b.mask)
    np.testing.assert_array_equal(a.bars, b.bars)
    assert not a.mask[list(a.codes).index("999999")].any()


def test_native_last_write_wins():
    code = np.array(["600000", "600000"])
    time = np.array([93000000, 93000000], np.int64)
    one = np.array([1.0, 2.0])
    g = grid_day(code, time, one, one, one, one, one, use_native=True)
    assert g.bars[0, 0, 0] == 2.0


def test_native_wire_encode_matches_numpy(rng):
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=12, missing_prob=0.1, zero_volume_prob=0.1,
                     short_day_codes=2)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None], g.mask[None]
    a = wire.encode(bars, mask, use_native=True)
    b = wire.encode(bars, mask, use_native=False)
    assert a is not None and b is not None
    np.testing.assert_array_equal(a.base, b.base)
    # identical narrowing choices (int8 deltas / uint16 lot volume)
    assert a.dclose.dtype == b.dclose.dtype
    assert a.dohl.dtype == b.dohl.dtype
    assert a.volume.dtype == b.volume.dtype
    assert a.vol_scale == b.vol_scale
    np.testing.assert_array_equal(a.dclose, b.dclose)
    np.testing.assert_array_equal(a.dohl, b.dohl)
    np.testing.assert_array_equal(a.volume, b.volume)
    np.testing.assert_array_equal(a.maskbits, b.maskbits)
    # unrepresentable input rejected by both
    bad = bars.copy()
    i = tuple(np.argwhere(mask)[0])
    bad[i][3] += 0.005
    assert wire.encode(bad, mask, use_native=True) is None
    assert wire.encode(bad, mask, use_native=False) is None
    # a NaN lane after a genuine violation must not launder the batch
    # (ordered-comparison maxima would reset on NaN); NaN alone rejects too
    vi = np.argwhere(mask[0])
    for fields in ((3,), (4,), (3, 4)):
        bad = bars.copy()
        bad[0][tuple(vi[0])][3] += 0.3          # off-tick close early
        for f in fields:
            bad[0][tuple(vi[-1])][f] = np.nan   # NaN in a later lane
        assert wire.encode(bad, mask, use_native=True) is None, fields
        assert wire.encode(bad, mask, use_native=False) is None, fields
    nan_only = bars.copy()
    nan_only[0][tuple(vi[0])][4] = np.nan
    assert wire.encode(nan_only, mask, use_native=True) is None
    assert wire.encode(nan_only, mask, use_native=False) is None


def test_wire_encode_threaded_matches_single(rng):
    """Chunked multi-thread encode is bit-identical to one pass, including
    the merged narrowing stats."""
    from replication_of_minute_frequency_factor_tpu import native
    cols = synth_day(rng, n_codes=30, missing_prob=0.1, zero_volume_prob=0.1)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = np.stack([g.bars, g.bars]), np.stack([g.mask, g.mask])
    one = native.wire_encode_native(bars, mask, n_threads=1)
    many = native.wire_encode_native(bars, mask, n_threads=4)
    for a, b in zip(one, many):
        np.testing.assert_array_equal(a, b)
    # unrepresentable detected regardless of which chunk holds it
    bad = bars.copy()
    bad[1, -1, 100, 3] += 0.005
    m2 = mask.copy()
    m2[1, -1, 100] = True
    assert native.wire_encode_native(bad, m2, n_threads=4) is None


def test_abi_and_slot_formula_parity(rng):
    times = np.concatenate([sessions.GRID_TIMES,
                            np.array([92900000, 113000000, 120000000,
                                      150000000, 93000001], np.int64)])
    want = sessions.time_to_slot(times)
    # native slot conversion is only observable through placement; pack a
    # single ticker with value = slot position
    n = len(times)
    code = np.array(["600000"] * n)
    v = np.arange(n, dtype=np.float64)
    g = grid_day(code, times, v, v, v, v, v, use_native=True)
    placed = np.flatnonzero(g.mask[0])
    np.testing.assert_array_equal(np.sort(placed),
                                  np.sort(want[want >= 0]))
