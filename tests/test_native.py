"""Native C++ grid packer == numpy packer, bit for bit."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import native, sessions
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="no C++ toolchain")


def test_native_matches_numpy(rng):
    cols = synth_day(rng, n_codes=20, missing_prob=0.1, zero_volume_prob=0.1,
                     short_day_codes=3, constant_price_codes=2)
    # inject off-grid rows the packer must drop: lunch break + sub-minute
    cols["time"][::37] = 120000000
    cols["time"][5] = 93000500
    a = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], use_native=True)
    b = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], use_native=False)
    np.testing.assert_array_equal(a.mask, b.mask)
    np.testing.assert_array_equal(a.bars, b.bars)
    np.testing.assert_array_equal(a.codes, b.codes)


def test_native_unknown_codes_and_pinned_axis(rng):
    cols = synth_day(rng, n_codes=4)
    pinned = np.array(["600000", "600002", "999999"], dtype=object)
    a = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], codes=pinned,
                 use_native=True)
    b = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"], codes=pinned,
                 use_native=False)
    np.testing.assert_array_equal(a.mask, b.mask)
    np.testing.assert_array_equal(a.bars, b.bars)
    assert not a.mask[list(a.codes).index("999999")].any()


def test_native_last_write_wins():
    code = np.array(["600000", "600000"])
    time = np.array([93000000, 93000000], np.int64)
    one = np.array([1.0, 2.0])
    g = grid_day(code, time, one, one, one, one, one, use_native=True)
    assert g.bars[0, 0, 0] == 2.0


def test_native_wire_encode_matches_numpy(rng):
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=12, missing_prob=0.1, zero_volume_prob=0.1,
                     short_day_codes=2)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None], g.mask[None]
    a = wire.encode(bars, mask, use_native=True)
    b = wire.encode(bars, mask, use_native=False)
    assert a is not None and b is not None
    np.testing.assert_array_equal(a.base, b.base)
    # identical narrowing choices across the three mode ladders
    # (int4-pair/int8/int16 deltas, tight/wick/per-field OHL,
    # 10-bit/u16/i32 volume)
    assert a.dclose.dtype == b.dclose.dtype
    assert a.dohl.dtype == b.dohl.dtype
    assert a.volume.dtype == b.volume.dtype
    assert a.vol_scale == b.vol_scale
    np.testing.assert_array_equal(a.dclose, b.dclose)
    np.testing.assert_array_equal(a.dohl, b.dohl)
    np.testing.assert_array_equal(a.volume, b.volume)
    np.testing.assert_array_equal(a.maskbits, b.maskbits)
    # unrepresentable input rejected by both
    bad = bars.copy()
    i = tuple(np.argwhere(mask)[0])
    bad[i][3] += 0.005
    assert wire.encode(bad, mask, use_native=True) is None
    assert wire.encode(bad, mask, use_native=False) is None
    # a NaN lane after a genuine violation must not launder the batch
    # (ordered-comparison maxima would reset on NaN); NaN alone rejects too
    vi = np.argwhere(mask[0])
    for fields in ((3,), (4,), (3, 4)):
        bad = bars.copy()
        bad[0][tuple(vi[0])][3] += 0.3          # off-tick close early
        for f in fields:
            bad[0][tuple(vi[-1])][f] = np.nan   # NaN in a later lane
        assert wire.encode(bad, mask, use_native=True) is None, fields
        assert wire.encode(bad, mask, use_native=False) is None, fields
    nan_only = bars.copy()
    nan_only[0][tuple(vi[0])][4] = np.nan
    assert wire.encode(nan_only, mask, use_native=True) is None
    assert wire.encode(nan_only, mask, use_native=False) is None


def test_wire_int4_dclose_mode_pinned():
    """The narrowest dclose rung (int4-pair, mode 0) specifically: a day
    whose tick deltas stay within +/-7 must select it on BOTH encoders,
    pack byte-identically (even slot in the low nibble), and decode to
    the exact tick walk — including across masked gaps and a widen-only
    escalation at exactly +/-8."""
    from replication_of_minute_frequency_factor_tpu.data import wire

    deltas = np.zeros(240, np.int64)
    deltas[1], deltas[2], deltas[3] = 7, -7, 1    # boundary values
    deltas[9], deltas[11], deltas[100] = 5, -3, 2  # odd + even slots
    mask = np.ones((1, 1, 240), bool)
    mask[0, 0, 4:9] = False                        # gap accumulates
    ct = 1000 + np.cumsum(deltas)
    close = (ct * 0.01).astype(np.float32)
    vol = np.full(240, 500.0, np.float32)
    bars = np.stack([close, close, close, close, vol], -1)[None, None]
    encs = []
    for use_native in (True, False):
        w = wire.encode(bars, mask, use_native=use_native)
        assert w.dclose.shape[-1] == 120 and w.dclose.dtype == np.uint8
        encs.append(w)
        ob, om = map(np.asarray, wire.decode(*w.arrays))
        np.testing.assert_array_equal(om, mask)
        got = np.round(ob[0, 0, :, 3] / 0.01).astype(np.int64)
        np.testing.assert_array_equal(got[mask[0, 0]], ct[mask[0, 0]])
    np.testing.assert_array_equal(encs[0].dclose, encs[1].dclose)
    # +/-8 is out of the int4 range: both encoders widen to int8 and the
    # decoded ticks are unchanged
    d8 = deltas.copy()
    d8[11] = 8
    ct8 = 1000 + np.cumsum(d8)
    c8 = (ct8 * 0.01).astype(np.float32)
    b8 = np.stack([c8, c8, c8, c8, vol], -1)[None, None]
    for use_native in (True, False):
        f = {}
        w8 = wire.encode(b8, mask, use_native=use_native, floor=f)
        assert w8.dclose.dtype == np.int8 and f["dclose_mode"] == 1
        ob8, _ = map(np.asarray, wire.decode(*w8.arrays))
        got8 = np.round(ob8[0, 0, :, 3] / 0.01).astype(np.int64)
        np.testing.assert_array_equal(got8[mask[0, 0]], ct8[mask[0, 0]])


def test_wire_tight_ohl_and_vol10_layout_pinned():
    """Byte layouts of the other two narrowest rungs, deterministically:
    tight OHL = int4 body | 2-bit wicks (high << 4, low << 6); vol10 =
    four 10-bit values per 5 bytes, little-endian bit stream. Checked
    against hand-computed bytes on both encoders, plus exact decode."""
    from replication_of_minute_frequency_factor_tpu.data import wire

    tick = 0.01
    ct = np.full(240, 2000, np.int64)         # flat close at 20.00 CNY
    dop = np.zeros(240, np.int64)
    h_off = np.zeros(240, np.int64)
    l_off = np.zeros(240, np.int64)
    # slot 0: open 3 ticks above close, wicks 0 -> byte (3&0xF) = 0x03
    dop[0] = 3
    # slot 1: open 2 below, high wick 1, low wick 2
    #   -> (-2 & 0xF) | (1 << 4) | (2 << 6) = 0x0E | 0x10 | 0x80 = 0x9E
    dop[1], h_off[1], l_off[1] = -2, 1, 2
    # slot 2: boundary body -8 (allowed), wicks 3
    #   -> 0x08 | 0x30 | 0xC0 = 0xF8
    dop[2], h_off[2], l_off[2] = -8, 3, 3
    ot = ct + dop
    ht = np.maximum(ct, ot) + h_off
    lt = np.minimum(ct, ot) - l_off
    vol_lots = np.zeros(240, np.int64)
    vol_lots[:4] = [1, 2, 3, 1023]  # one full 5-byte group
    bars = np.stack([(ot * tick), (ht * tick), (lt * tick), (ct * tick),
                     vol_lots * 100.0], -1).astype(np.float32)[None, None]
    mask = np.ones((1, 1, 240), bool)
    for use_native in (True, False):
        w = wire.encode(bars, mask, use_native=use_native)
        assert w.dohl.shape[-1] == 1 and w.volume.shape[-1] == 300
        assert w.vol_scale == 100.0
        got_ohl = w.dohl[0, 0, :3, 0]
        np.testing.assert_array_equal(got_ohl, [0x03, 0x9E, 0xF8])
        # vol10 group: v=[1,2,3,1023] ->
        # b0=1, b1=(0)|(2&0x3F)<<2=8, b2=(2>>6)|(3&0xF)<<4=0x30,
        # b3=(3>>4)|(1023&3)<<6=0xC0, b4=1023>>2=0xFF
        np.testing.assert_array_equal(
            w.volume[0, 0, :5], [0x01, 0x08, 0x30, 0xC0, 0xFF])
        ob, om = map(np.asarray, wire.decode(*w.arrays))
        np.testing.assert_array_equal(om, mask)
        np.testing.assert_array_equal(
            np.round(ob[0, 0, :, 0] / tick).astype(np.int64), ot)
        np.testing.assert_array_equal(
            np.round(ob[0, 0, :, 1] / tick).astype(np.int64), ht)
        np.testing.assert_array_equal(
            np.round(ob[0, 0, :, 2] / tick).astype(np.int64), lt)
        np.testing.assert_array_equal(ob[0, 0, :, 4], vol_lots * 100.0)


def test_wire_encode_threaded_matches_single(rng):
    """Chunked multi-thread encode is bit-identical to one pass, including
    the merged narrowing stats."""
    from replication_of_minute_frequency_factor_tpu import native
    cols = synth_day(rng, n_codes=30, missing_prob=0.1, zero_volume_prob=0.1)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = np.stack([g.bars, g.bars]), np.stack([g.mask, g.mask])
    one = native.wire_encode_native(bars, mask, n_threads=1)
    many = native.wire_encode_native(bars, mask, n_threads=4)
    for a, b in zip(one, many):
        np.testing.assert_array_equal(a, b)
    # unrepresentable detected regardless of which chunk holds it
    bad = bars.copy()
    bad[1, -1, 100, 3] += 0.005
    m2 = mask.copy()
    m2[1, -1, 100] = True
    assert native.wire_encode_native(bad, m2, n_threads=4) is None


def test_abi_and_slot_formula_parity(rng):
    times = np.concatenate([sessions.GRID_TIMES,
                            np.array([92900000, 113000000, 120000000,
                                      150000000, 93000001], np.int64)])
    want = sessions.time_to_slot(times)
    # native slot conversion is only observable through placement; pack a
    # single ticker with value = slot position
    n = len(times)
    code = np.array(["600000"] * n)
    v = np.arange(n, dtype=np.float64)
    g = grid_day(code, times, v, v, v, v, v, use_native=True)
    placed = np.flatnonzero(g.mask[0])
    np.testing.assert_array_equal(np.sort(placed),
                                  np.sort(want[want >= 0]))


def _assert_wire_equal(a, b):
    assert (a is None) == (b is None)
    if a is None:
        return
    for x, y, nm in zip(a.arrays, b.arrays,
                        ("base", "dclose", "dohl", "volume", "mask", "vs")):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype, nm
        np.testing.assert_array_equal(x, y, err_msg=nm)


def test_masked_lane_garbage_is_zeroed_not_rejected(rng):
    """Garbage (NaN/inf/off-tick) parked on a masked-OUT lane must not
    reject the batch or change the encoding — the numpy oracle ignores
    dead lanes entirely, and the native fast path must match."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=6, missing_prob=0.2)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None].copy(), g.mask[None]
    dead = np.argwhere(~mask[0])
    assert len(dead) >= 3
    bars[0][tuple(dead[0])] = np.nan
    bars[0][tuple(dead[1])][3] = np.inf
    bars[0][tuple(dead[2])][0] = 12.34567  # off-tick on a dead lane
    a = wire.encode(bars, mask, use_native=True)
    b = wire.encode(bars, mask, use_native=False)
    assert a is not None
    _assert_wire_equal(a, b)
    clean = wire.encode(np.where(mask[..., None], bars, 0.0).astype(
        np.float32), mask, use_native=True)
    _assert_wire_equal(a, clean)


def test_high_price_ticker_bit_parity(rng):
    """A Moutai-class (~1700 CNY) ticker: the f32 sweep's relative
    tolerance keeps it conclusive (no double fallback), and the encoding
    must stay bit-identical to the numpy oracle."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=6, missing_prob=0.1)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None].copy(), g.mask[None]
    # rescale one ticker to ~1700 CNY (Moutai-class), tick-aligned
    hot = np.round(bars[0, 2] * 37.0, 2).astype(np.float32)
    bars[0, 2] = np.where(mask[0, 2, :, None], hot, 0.0)
    a = wire.encode(bars, mask, use_native=True)
    b = wire.encode(bars, mask, use_native=False)
    _assert_wire_equal(a, b)
    if a is not None:
        dec, dm = wire.decode(*a.arrays)
        np.testing.assert_allclose(
            np.asarray(dec)[0, 2][mask[0, 2]],
            bars[0, 2][mask[0, 2]], rtol=3e-7)
    # an off-tick value on the high-priced ticker still rejects via the
    # double sweep (both paths agree)
    vi = np.argwhere(mask[0, 2])
    bad = bars.copy()
    bad[0, 2][tuple(vi[0])][3] = bad[0, 2][tuple(vi[0])][3] + 0.005
    assert wire.encode(bad, mask, use_native=True) is None
    assert wire.encode(bad, mask, use_native=False) is None


def test_fractional_large_volume_rejected_by_both(rng):
    """4194304.5 is f32-representable (spacing 0.5 between 2^22 and 2^23):
    an absolute integrality check must reject it on both paths — an
    implicit relative tolerance would wave it through."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=4)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None].copy(), g.mask[None]
    vi = np.argwhere(mask[0])
    bars[0][tuple(vi[0])][4] = 4194304.5
    assert wire.encode(bars, mask, use_native=True) is None
    assert wire.encode(bars, mask, use_native=False) is None


def test_boundary_tick_magnitudes_stay_bit_parity(rng):
    """Near the 2^22-tick close bound the f32 product can round to a
    different integer tick than the double path; those magnitudes must
    route to the double sweep so native stays bit-identical to numpy."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=4, missing_prob=0.1)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None].copy(), g.mask[None]
    # park one ticker just under the 2^22 tick cap (~41942 CNY) with
    # deliberately non-grid f32 PRICES (volume untouched — a scaled
    # volume would reject both paths and make the test vacuous): the
    # magnitude-relative tolerance exceeds one tick up there, so both
    # paths accept and must agree on every rounded tick
    t = bars[0, 1]
    scale = 41942.0 / np.maximum(t[..., 3:4], 1e-6)
    bars[0, 1, :, :4] = np.where(mask[0, 1, :, None],
                                 (t[..., :4] * scale).astype(np.float32),
                                 0.0)
    a = wire.encode(bars, mask, use_native=True)
    assert a is not None, "boundary batch must actually encode"
    b = wire.encode(bars, mask, use_native=False)
    _assert_wire_equal(a, b)


def test_tiny_negative_volume_rejected_by_both(rng):
    """-0.0004 volume rounds to -0.0 which passes a rounded >=0 check;
    the sign test must use the RAW value so native matches the numpy
    oracle's vv.min() < 0 rejection."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=4)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None].copy(), g.mask[None]
    vi = np.argwhere(mask[0])
    bars[0][tuple(vi[0])][4] = -0.0004
    assert wire.encode(bars, mask, use_native=True) is None
    assert wire.encode(bars, mask, use_native=False) is None
    # exact -0.0 is a legitimate zero volume for both
    bars[0][tuple(vi[0])][4] = -0.0
    a = wire.encode(bars, mask, use_native=True)
    b = wire.encode(bars, mask, use_native=False)
    _assert_wire_equal(a, b)
    assert a is not None


def test_double_sweep_covered_and_bit_parity(rng):
    """Above kBigF=2e6 ticks (> ~20,000 CNY) every lane routes to the
    double-precision sweep; its output must be bit-identical to the (f64)
    numpy oracle, and off-tick values there must still reject."""
    from replication_of_minute_frequency_factor_tpu.data import wire
    cols = synth_day(rng, n_codes=4, missing_prob=0.1)
    g = grid_day(cols["code"], cols["time"], cols["open"], cols["high"],
                 cols["low"], cols["close"], cols["volume"])
    bars, mask = g.bars[None].copy(), g.mask[None]
    t = bars[0, 1]
    scale = 30000.0 / np.maximum(t[..., 3:4], 1e-6)  # ~3e6 ticks
    bars[0, 1, :, :4] = np.where(mask[0, 1, :, None],
                                 (t[..., :4] * scale).astype(np.float32),
                                 0.0)
    a = wire.encode(bars, mask, use_native=True)
    b = wire.encode(bars, mask, use_native=False)
    assert a is not None, "3e6-tick batch must encode via the double sweep"
    _assert_wire_equal(a, b)
    # Above ~2.08e6 ticks the relative tolerance exceeds 0.5 ticks, so
    # EVERY value is within tolerance of some integer tick — "off-grid"
    # is not expressible in f32 there (its own spacing is ~0.2 ticks) and
    # rejection is impossible by design. A perturbed price must therefore
    # snap to the nearest tick identically on both paths.
    bad = bars.copy()
    vi = np.argwhere(mask[0, 1])
    bad[0, 1][tuple(vi[0])][3] += 0.045  # ~4.5 ticks: snaps, not rejects
    ra = wire.encode(bad, mask, use_native=True)
    rb = wire.encode(bad, mask, use_native=False)
    assert ra is not None and rb is not None
    _assert_wire_equal(ra, rb)
