"""Golden loader fixture: a committed, hand-built day file + daily-PV
table (tests/golden/, provenance in tools/make_golden_fixture.py)
exercising the messy CSMAR-export contract end to end — integer stock
codes, an 11:30 bar (the reference's trade-minute formula would alias it
onto 13:00; the loader must drop it, sessions.py), sub-minute and
pre-open stamps, a 15:00 closing-auction row, duplicate (code, slot)
rows, zero-volume bars, a limit-locked stock, a halted stock, and
compact-``YYYYMMDD`` date strings (VERDICT r2 #8; day-file contract
reference MinuteFrequentFactorCICC.py:68-78).

The pinned factor values are the PRODUCTION path's (grid -> fused jax
graph). They intentionally differ from what the raw-row oracle would
produce on this file: the reference ran on pre-cleaned data, so its
kernels never see off-grid rows — our loader enforces that cleaning,
e.g. the halted stock (whose only row is off-grid) is NaN here rather
than taking trade_headRatio's 0.125 empty-volume fallback.
"""

import os

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import Factor
from replication_of_minute_frequency_factor_tpu.config import Config
from replication_of_minute_frequency_factor_tpu.data import io as dio
from replication_of_minute_frequency_factor_tpu.data.minute import grid_day
from replication_of_minute_frequency_factor_tpu.pipeline import (
    compute_exposures)

GOLDEN = os.path.join(os.path.dirname(__file__), "golden")
DAY_FILE = os.path.join(GOLDEN, "20240102_cleaned.parquet")
PV_FILE = os.path.join(GOLDEN, "daily_pv.parquet")


def test_day_file_discovery():
    files = dio.list_day_files(GOLDEN)
    assert [(str(d), os.path.basename(p)) for d, p in files] == [
        ("2024-01-02", "20240102_cleaned.parquet")]


def test_int_codes_normalize_like_daily_pv():
    """Minute and PV readers must agree on code spelling, or the
    evaluation join silently empties ('2' vs '000002')."""
    day = dio.read_minute_day(DAY_FILE)
    assert day["code"].dtype.kind == "U"
    assert sorted(set(day["code"])) == ["000002", "300750", "600519",
                                       "999999"]
    pv = dio.read_daily_pv(PV_FILE, ["code", "date", "pct_change"])
    assert sorted(set(pv["code"])) == ["000002", "300750", "600519",
                                      "999999"]
    # compact YYYYMMDD strings coerced to real dates
    assert sorted(set(map(str, pv["date"]))) == ["2024-01-02",
                                                 "2024-01-03"]


def test_grid_drops_exactly_the_offgrid_rows():
    day = dio.read_minute_day(DAY_FILE)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"])
    assert list(g.codes) == ["000002", "300750", "600519", "999999"]
    counts = {str(c): int(g.mask[i].sum()) for i, c in enumerate(g.codes)}
    # 000002 wrote 245 rows: 240 on-grid + 11:30 + sub-minute + pre-open
    # + 15:00 (all dropped) + one duplicate 09:31 (last wins, no new slot)
    assert counts == {"000002": 240, "300750": 18, "600519": 240,
                      "999999": 0}
    i2 = list(g.codes).index("000002")
    # duplicate (code, slot): the later 7.77/777 row overwrote 09:31
    np.testing.assert_allclose(
        g.bars[i2, 1], [7.77, 7.77, 7.77, 7.77, 777.0], rtol=1e-6)


#: production-path pins (f32; regenerate deliberately, never casually —
#: tools/make_golden_fixture.py is the fixture's provenance)
PINNED = {
    "000002": {"vol_return1min": 8.15243402e-04, "mmt_pm": 1.0,
               "liq_openvol": 100.0, "liq_closevol": 1000.0,
               "trade_headRatio": 0.128823757, "mmt_am": 1.0,
               "doc_vol5_ratio": 0.0372305550},
    # AM-only stock: PM-dependent factors have no qualifying rows ->
    # absent in the reference's long output -> NaN in the dense one
    "300750": {"vol_return1min": 2.27243390e-06, "mmt_pm": np.nan,
               "liq_openvol": 300.0, "liq_closevol": np.nan,
               "trade_headRatio": 0.235294119, "mmt_am": 1.02999997,
               "doc_vol5_ratio": 0.294117659},
    # limit-locked: zero return variance, constant everything
    "600519": {"vol_return1min": 0.0, "mmt_pm": 1.0,
               "liq_openvol": 200.0, "liq_closevol": 600.0,
               "trade_headRatio": 0.129166663, "mmt_am": 1.0,
               "mmt_ols_qrs": 0.0, "vol_upRatio": np.nan},
    # halted (only row off-grid): everything NaN — including
    # trade_headRatio, whose 0.125 empty-volume fallback the reference
    # only reaches when a row EXISTS with zero volume
    "999999": {"vol_return1min": np.nan, "mmt_pm": np.nan,
               "liq_openvol": np.nan, "trade_headRatio": np.nan},
}


def test_pinned_factor_values(tmp_path):
    names = sorted({n for v in PINNED.values() for n in v})
    table = compute_exposures(
        GOLDEN, names, cfg=Config(minute_dir=GOLDEN),
        cache_path=str(tmp_path / "golden.parquet"), progress=False)
    assert not table.failures
    by_code = {table.columns["code"][i]: i for i in range(len(table))}
    for code, pins in PINNED.items():
        i = by_code[code]
        for name, want in pins.items():
            got = float(table.columns[name][i])
            if np.isnan(want):
                assert np.isnan(got), (code, name, got)
            else:
                np.testing.assert_allclose(got, want, rtol=1e-5,
                                           atol=1e-12,
                                           err_msg=f"{code}/{name}")


def test_evaluation_joins_golden_pv(tmp_path):
    """The full user path on the fixture: compute -> cache -> coverage
    -> ic_test against the golden PV (int codes + compact dates) — the
    join must be non-empty, proving code/date normalization agrees
    across both readers."""
    table = compute_exposures(
        GOLDEN, ["vol_return1min"], cfg=Config(minute_dir=GOLDEN),
        cache_path=str(tmp_path / "f.parquet"), progress=False)
    f = Factor("vol_return1min").set_exposure(
        table.columns["code"], table.columns["date"],
        table.columns["vol_return1min"])
    cov = f.coverage(plot=False, return_df=True)
    # 3 of 4 stocks produced a value (halted one is NaN)
    assert list(cov["coverage"]) == [3]
    ic = f.ic_test(future_days=1, plot=False, return_df=True,
                   daily_pv_path=PV_FILE)
    # one usable date (2024-01-02 with 2024-01-03's forward return);
    # the IC is defined over the 3 covered stocks
    assert len(ic["date"]) == 1
    assert np.isfinite(ic["IC"][0])


@pytest.mark.parametrize("use_native", [False, True])
def test_grid_native_numpy_agree_on_fixture(use_native):
    from replication_of_minute_frequency_factor_tpu import native
    if use_native and not native.available():
        pytest.skip("native packer not built")
    day = dio.read_minute_day(DAY_FILE)
    g = grid_day(day["code"], day["time"], day["open"], day["high"],
                 day["low"], day["close"], day["volume"],
                 use_native=use_native)
    ref = grid_day(day["code"], day["time"], day["open"], day["high"],
                   day["low"], day["close"], day["volume"],
                   use_native=False)
    np.testing.assert_array_equal(g.mask, ref.mask)
    np.testing.assert_array_equal(g.bars, ref.bars)
