"""Symbolic factor search: interpreter correctness + signal recovery."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import search


@pytest.fixture
def day_batch(rng):
    D, T = 3, 40
    close = 10 * np.exp(np.cumsum(rng.normal(0, 1e-3, (D, T, 240)), -1))
    open_ = close * (1 + rng.normal(0, 1e-4, close.shape))
    high = np.maximum(open_, close) * 1.0002
    low = np.minimum(open_, close) * 0.9998
    vol = rng.integers(1, 10000, close.shape).astype(np.float64)
    bars = np.stack([open_, high, low, close, vol], -1).astype(np.float32)
    mask = rng.random((D, T, 240)) > 0.1
    return bars, mask


def test_interpreter_matches_hand_eval(day_batch):
    bars, mask = day_batch
    # skeleton: PUSH close, UNARY z, PUSH vshare, UNARY id, BINARY *
    skel = (search.PUSH, search.UNARY, search.PUSH, search.UNARY,
            search.BINARY)
    genome = np.array([[3, 4, 6, 0, 2]], np.int32)  # z(close) * vshare
    got = np.asarray(search.eval_programs(genome, bars, mask, skel))[0]

    c = bars[..., 3]
    v = bars[..., 4]
    mu = np.where(mask, c, 0).sum(-1) / mask.sum(-1)
    var = (np.where(mask, (c - mu[..., None]) ** 2, 0).sum(-1)
           / (mask.sum(-1) - 1))
    z = (c - mu[..., None]) / np.sqrt(var)[..., None]
    vs = v / np.maximum(np.where(mask, v, 0).sum(-1, keepdims=True), 1)
    want = np.where(mask, z * vs, 0).sum(-1) / mask.sum(-1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-6)


def test_describe_roundtrip():
    skel = search.DEFAULT_SKELETON
    rng = np.random.default_rng(0)
    g = search.random_population(rng, 1, skel)[0]
    s = search.describe(g, skel)
    assert s.startswith("mean(") and s.count("(") == s.count(")")


@pytest.mark.slow  # GA generations on one core: an evolution seed sweep
def test_evolve_recovers_planted_signal(day_batch, rng):
    bars, mask = day_batch
    # forward return = cross-sectional signal proportional to mean intrabar
    # return (feature 'ret' under identity + mean) + small noise
    o = bars[..., 0]
    c = bars[..., 3]
    ret = np.where(mask, (c - o) / o, 0.0)
    signal = ret.sum(-1) / np.maximum(mask.sum(-1), 1)
    fwd = signal + rng.normal(0, signal.std() * 0.3, signal.shape)
    fwd_valid = np.ones_like(fwd, bool)

    res = search.evolve(bars.astype(np.float32), mask,
                        fwd.astype(np.float32), fwd_valid,
                        pop=256, generations=6, seed=1, device_batch=256)
    assert res.fitness > 0.5, search.describe(res.genome)
    # monotone-ish improvement
    assert res.history[-1] >= res.history[0]


def test_fitness_chunked_matches_unchunked(day_batch, rng):
    """Population chunking (the HBM bound for 10k-candidate fitness calls)
    must not change any candidate's fitness — including a padded tail."""
    bars, mask = day_batch
    fwd = rng.normal(0, 0.02, bars.shape[:2]).astype(np.float32)
    fwd_valid = np.ones_like(fwd, bool)
    pop = search.random_population(rng, 101)  # 101 % 16 != 0 -> pad path

    whole = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid,
                                      chunk=101))
    chunked = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid,
                                        chunk=16))
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-7)


def test_auto_chunk_derivation():
    """Pin the shape -> chunk formula that bounds fitness HBM temporaries
    (the ladder's 10k x [1,1000,240] config must actually chunk)."""
    ladder_shape = (1, 1000, 240)
    chunk = search.auto_chunk(ladder_shape)
    assert chunk == search._CHUNK_ELEMS // (1000 * 240)
    assert chunk < 10_000  # the OOM config takes the chunked path
    # ~30 live [chunk, D, T, 240] f32 temporaries (the round-3 op tables
    # under jnp.select's materialise-all-branches) must fit a 16 GB chip
    assert chunk * 1000 * 240 * 4 * 30 < 16e9
    # tiny day tensors stay unchunked; degenerate shapes never hit 0
    assert search.auto_chunk((3, 40, 240)) > 1000
    assert search.auto_chunk((244, 5000, 240)) == 1


def test_fitness_auto_chunk_executes(day_batch, rng):
    """chunk=None resolves from the static day shape and runs."""
    bars, mask = day_batch
    fwd = rng.normal(0, 0.02, bars.shape[:2]).astype(np.float32)
    fwd_valid = np.ones_like(fwd, bool)
    pop = search.random_population(rng, 64)
    auto = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid))
    explicit = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid,
                                         chunk=64))
    np.testing.assert_allclose(auto, explicit, rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# round-3 genome extensions: windowed ops, time/value masks, aggregators
# (VERDICT r2 #6 — the fixed-skeleton genome could not express most of
# the reference's factor families)


def _np_windowed(x, m, w, stat):
    """Independent trailing-window oracle, f64."""
    D, T, L = x.shape
    out = np.zeros((D, T, L))
    for i in range(L):
        lo = max(0, i - w + 1)
        xs = x[..., lo:i + 1]
        ms = m[..., lo:i + 1]
        n = ms.sum(-1)
        s = np.where(ms, xs, 0.0).sum(-1)
        if stat == "mean":
            out[..., i] = np.where(n > 0, s / np.maximum(n, 1), 0.0)
        elif stat == "std":
            mu = s / np.maximum(n, 1)
            m2 = np.where(ms, xs * xs, 0.0).sum(-1) / np.maximum(n, 1)
            out[..., i] = np.where(
                n > 0, np.sqrt(np.maximum(m2 - mu * mu, 0.0)), 0.0)
    return out


def test_rolling_unary_ops_match_numpy(day_batch):
    bars, mask = day_batch
    x = bars[..., 3].astype(np.float64)  # close
    for k, (w, stat) in {8: (search.ROLL_FAST, "mean"),
                         9: (search.ROLL_SLOW, "mean"),
                         10: (search.ROLL_FAST, "std"),
                         11: (search.ROLL_SLOW, "std")}.items():
        got = np.asarray(search._apply_unary(
            np.int32(k), x.astype(np.float32), mask))
        want = _np_windowed(x, mask, w, stat)
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)


def test_rolling_corr_matches_numpy(day_batch):
    bars, mask = day_batch
    a = bars[..., 3].astype(np.float64)
    b = bars[..., 4].astype(np.float64)
    got = np.asarray(search.rolling_corr(
        a.astype(np.float32), b.astype(np.float32), mask,
        search.ROLL_SLOW))
    # independent windowed pearson
    D, T, L = a.shape
    w = search.ROLL_SLOW
    want = np.zeros((D, T, L))
    for i in range(L):
        lo = max(0, i - w + 1)
        ms = mask[..., lo:i + 1]
        n = ms.sum(-1)
        for d in range(D):
            for t in range(T):
                if n[d, t] < 2:
                    continue
                av = a[d, t, lo:i + 1][ms[d, t]]
                bv = b[d, t, lo:i + 1][ms[d, t]]
                da = av - av.mean()
                db = bv - bv.mean()
                den = np.sqrt((da * da).mean() * (db * db).mean())
                if den > 0:
                    want[d, t, i] = (da * db).mean() / den
    # f32 cumsum-vs-two-pass noise on near-constant windows: compare
    # where the result is away from the degenerate gate
    far = np.abs(want) > 1e-3
    np.testing.assert_allclose(got[far], want[far], rtol=0.05, atol=5e-3)
    assert np.all(np.abs(got) <= 1.0 + 1e-5)


def test_mask_primitives(day_batch):
    bars, mask = day_batch
    ret = (bars[..., 3] - bars[..., 0]) / bars[..., 0]
    for k, want in {
        0: mask & (np.arange(240) < 120),
        1: mask & (np.arange(240) >= 120),
        2: mask & (np.arange(240) < 30),
        3: mask & (np.arange(240) >= 210),
        4: mask & (ret > 0),
        5: mask & (ret < 0),
    }.items():
        got = np.asarray(search._apply_mask(np.int32(k), ret, mask))
        np.testing.assert_array_equal(got, want, err_msg=f"mask op {k}")


def test_agg_primitives_and_composition(day_batch):
    """AGG reduces under the entry mask and composes through BINARY:
    the vol_upRatio shape std(ret|ret>0)/std(ret) evaluates correctly."""
    bars, mask = day_batch
    o = bars[..., 0].astype(np.float64)
    c = bars[..., 3].astype(np.float64)
    ret = (c - o) / o
    # genome on RICH_SKELETON: (ret, id, pos, std, ret, id, std, /)
    genome = np.array([[5, 0, 4, 1, 5, 0, 1, 3]], np.int32)
    got = np.asarray(search.eval_programs(
        genome, bars, mask, search.RICH_SKELETON))[0]

    def np_std1(v):
        return np.std(v, ddof=1) if v.size >= 2 else np.nan

    D, T = mask.shape[:2]
    want = np.full((D, T), np.nan)
    for d in range(D):
        for t in range(T):
            r = ret[d, t][mask[d, t]]
            up = r[r > 0]
            den = np_std1(r)
            num = np_std1(up)
            if np.isfinite(den) and den > 1e-6 and np.isfinite(num):
                want[d, t] = num / den
    ok = np.isfinite(want)
    assert ok.any()
    np.testing.assert_allclose(got[ok], want[ok], rtol=2e-3, atol=1e-5)
    # describe renders the same program readably
    s = search.describe(genome[0], search.RICH_SKELETON)
    assert s == "mean((std(id(ret)[pos]) / std(id(ret))))"


@pytest.mark.slow  # GA generations on one core: an evolution seed sweep
def test_rich_skeleton_recovers_planted_upratio(day_batch, rng):
    """Plant a vol_upRatio-shaped forward return; the GA on the
    ratio-of-aggregates skeleton must find a high-IC program
    (VERDICT r2 #6's named recovery demonstration, in-test form)."""
    bars, mask = day_batch
    o = bars[..., 0].astype(np.float64)
    c = bars[..., 3].astype(np.float64)
    ret = np.where(mask, (c - o) / o, np.nan)
    with np.errstate(invalid="ignore"):
        up = np.where(ret > 0, ret, np.nan)
        num = np.nanstd(up, axis=-1, ddof=1)
        den = np.nanstd(ret, axis=-1, ddof=1)
    signal = num / den
    fwd = np.nan_to_num(signal - np.nanmean(signal, axis=-1,
                                            keepdims=True))
    fwd_valid = np.isfinite(signal)

    res = search.evolve(bars.astype(np.float32), mask,
                        fwd.astype(np.float32), fwd_valid,
                        pop=384, generations=8, seed=3,
                        skeleton=search.RICH_SKELETON, device_batch=384)
    assert res.fitness > 0.8, search.describe(res.genome,
                                              search.RICH_SKELETON)


def test_time_mask_factor_recovery(day_batch, rng):
    """A trade_tailRatio-shaped signal (last-30-minute volume share,
    reference MinuteFrequentFactorCalculateMethodsCICC.py:1280-1306) is
    recoverable through the MASK primitive on a 3-slot skeleton — the
    session masks pull their weight in search, not just in evaluation."""
    bars, mask = day_batch
    v = bars[..., 4].astype(np.float64)
    tail = mask & (np.arange(240) >= 210)
    signal = (np.where(tail, v, 0.0).sum(-1)
              / np.maximum(np.where(mask, v, 0.0).sum(-1), 1.0))
    fwd = (signal - signal.mean(-1, keepdims=True)).astype(np.float32)
    fwd_valid = np.isfinite(signal)

    skel = (search.PUSH, search.MASK, search.AGG)
    res = search.evolve(bars.astype(np.float32), mask, fwd, fwd_valid,
                        pop=128, generations=6, seed=5, skeleton=skel,
                        device_batch=128)
    assert res.fitness > 0.95, search.describe(res.genome, skel)
    # the recovered program actually uses a time mask (pos/neg value
    # masks cannot reproduce a pure session-window share this well)
    desc = search.describe(res.genome, skel)
    assert any(t in desc for t in ("last30", "first30", "am", "pm")), desc
