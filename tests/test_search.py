"""Symbolic factor search: interpreter correctness + signal recovery."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu import search


@pytest.fixture
def day_batch(rng):
    D, T = 3, 40
    close = 10 * np.exp(np.cumsum(rng.normal(0, 1e-3, (D, T, 240)), -1))
    open_ = close * (1 + rng.normal(0, 1e-4, close.shape))
    high = np.maximum(open_, close) * 1.0002
    low = np.minimum(open_, close) * 0.9998
    vol = rng.integers(1, 10000, close.shape).astype(np.float64)
    bars = np.stack([open_, high, low, close, vol], -1).astype(np.float32)
    mask = rng.random((D, T, 240)) > 0.1
    return bars, mask


def test_interpreter_matches_hand_eval(day_batch):
    bars, mask = day_batch
    # skeleton: PUSH close, UNARY z, PUSH vshare, UNARY id, BINARY *
    skel = (search.PUSH, search.UNARY, search.PUSH, search.UNARY,
            search.BINARY)
    genome = np.array([[3, 4, 6, 0, 2]], np.int32)  # z(close) * vshare
    got = np.asarray(search.eval_programs(genome, bars, mask, skel))[0]

    c = bars[..., 3]
    v = bars[..., 4]
    mu = np.where(mask, c, 0).sum(-1) / mask.sum(-1)
    var = (np.where(mask, (c - mu[..., None]) ** 2, 0).sum(-1)
           / (mask.sum(-1) - 1))
    z = (c - mu[..., None]) / np.sqrt(var)[..., None]
    vs = v / np.maximum(np.where(mask, v, 0).sum(-1, keepdims=True), 1)
    want = np.where(mask, z * vs, 0).sum(-1) / mask.sum(-1)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=1e-6)


def test_describe_roundtrip():
    skel = search.DEFAULT_SKELETON
    rng = np.random.default_rng(0)
    g = search.random_population(rng, 1, skel)[0]
    s = search.describe(g, skel)
    assert s.startswith("mean(") and s.count("(") == s.count(")")


def test_evolve_recovers_planted_signal(day_batch, rng):
    bars, mask = day_batch
    # forward return = cross-sectional signal proportional to mean intrabar
    # return (feature 'ret' under identity + mean) + small noise
    o = bars[..., 0]
    c = bars[..., 3]
    ret = np.where(mask, (c - o) / o, 0.0)
    signal = ret.sum(-1) / np.maximum(mask.sum(-1), 1)
    fwd = signal + rng.normal(0, signal.std() * 0.3, signal.shape)
    fwd_valid = np.ones_like(fwd, bool)

    res = search.evolve(bars.astype(np.float32), mask,
                        fwd.astype(np.float32), fwd_valid,
                        pop=256, generations=6, seed=1, device_batch=256)
    assert res.fitness > 0.5, search.describe(res.genome)
    # monotone-ish improvement
    assert res.history[-1] >= res.history[0]


def test_fitness_chunked_matches_unchunked(day_batch, rng):
    """Population chunking (the HBM bound for 10k-candidate fitness calls)
    must not change any candidate's fitness — including a padded tail."""
    bars, mask = day_batch
    fwd = rng.normal(0, 0.02, bars.shape[:2]).astype(np.float32)
    fwd_valid = np.ones_like(fwd, bool)
    pop = search.random_population(rng, 101)  # 101 % 16 != 0 -> pad path

    whole = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid,
                                      chunk=101))
    chunked = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid,
                                        chunk=16))
    np.testing.assert_allclose(chunked, whole, rtol=1e-5, atol=1e-7)


def test_auto_chunk_derivation():
    """Pin the shape -> chunk formula that bounds fitness HBM temporaries
    (the ladder's 10k x [1,1000,240] config must actually chunk)."""
    ladder_shape = (1, 1000, 240)
    chunk = search.auto_chunk(ladder_shape)
    assert chunk == search._CHUNK_ELEMS // (1000 * 240)
    assert chunk < 10_000  # the OOM config takes the chunked path
    # ~8 live [chunk, D, T, 240] f32 temporaries must fit a 16 GB chip
    assert chunk * 1000 * 240 * 4 * 8 < 16e9
    # tiny day tensors stay unchunked; degenerate shapes never hit 0
    assert search.auto_chunk((3, 40, 240)) > 4000
    assert search.auto_chunk((244, 5000, 240)) == 1


def test_fitness_auto_chunk_executes(day_batch, rng):
    """chunk=None resolves from the static day shape and runs."""
    bars, mask = day_batch
    fwd = rng.normal(0, 0.02, bars.shape[:2]).astype(np.float32)
    fwd_valid = np.ones_like(fwd, bool)
    pop = search.random_population(rng, 64)
    auto = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid))
    explicit = np.asarray(search.fitness(pop, bars, mask, fwd, fwd_valid,
                                         chunk=64))
    np.testing.assert_allclose(auto, explicit, rtol=1e-5, atol=1e-7)
