"""Pallas rolling-moment kernel == XLA conv formulation (interpret mode)."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit)
from replication_of_minute_frequency_factor_tpu.models.registry import (
    factor_names)
from replication_of_minute_frequency_factor_tpu.ops.pallas_rolling import (
    rolling_window_stats_pallas)
from replication_of_minute_frequency_factor_tpu.ops.rolling import (
    rolling_window_stats)

# derived from the registry so a new rolling-family factor is
# covered automatically instead of silently skipped
ROLLING_FACTORS = tuple(n for n in factor_names()
                        if n.startswith("mmt_ols_"))
assert len(ROLLING_FACTORS) >= 5, ROLLING_FACTORS


@pytest.fixture
def data(rng):
    shape = (3, 240)
    low = 10.0 * np.exp(np.cumsum(rng.normal(0, 1e-3, shape), -1))
    high = low * (1 + np.abs(rng.normal(0, 5e-4, shape)))
    mask = rng.random(shape) > 0.1
    mask[1] = True          # one full row
    mask[2, :200] = False   # one row with <50-bar tail only
    return (low.astype(np.float32), high.astype(np.float32), mask)


def test_pallas_matches_conv(data):
    low, high, mask = data
    a = rolling_window_stats(low, high, mask, 50, impl="conv")
    b = rolling_window_stats_pallas(low, high, mask, 50, interpret=True)
    np.testing.assert_array_equal(np.asarray(a["valid"]),
                                  np.asarray(b["valid"]))
    valid = np.asarray(a["valid"])
    for k in ("mean_x", "mean_y", "cov", "var_x", "var_y"):
        np.testing.assert_allclose(
            np.asarray(a[k])[valid], np.asarray(b[k])[valid],
            rtol=2e-5, atol=1e-9, err_msg=k)


def test_rolling_factors_through_pallas(data):
    """The mmt_ols_* family end to end under rolling_impl='pallas'."""
    low, high, mask = data
    bars = np.stack([low, high * 1.0001, low * 0.9999, high,
                     np.full_like(low, 100.0)], axis=-1)
    conv = compute_factors_jit(bars, mask, names=ROLLING_FACTORS,
                               rolling_impl="conv")
    pal = compute_factors_jit(bars, mask, names=ROLLING_FACTORS,
                              rolling_impl="pallas")
    for k in ROLLING_FACTORS:
        a, b = np.asarray(conv[k]), np.asarray(pal[k])
        np.testing.assert_array_equal(np.isfinite(a), np.isfinite(b))
        ok = np.isfinite(a)
        np.testing.assert_allclose(a[ok], b[ok], rtol=5e-4, atol=1e-6,
                                   err_msg=k)
