"""Kernel purity under ``jax.transfer_guard("disallow")`` — the runtime
twin of graftlint rule GL-A3 (docs/static-analysis.md).

The whole module runs with implicit host<->device transfers disallowed
(conftest ``TRANSFER_GUARDED_MODULES``): inputs are placed explicitly
with ``jax.device_put``, results fetched explicitly with
``jax.device_get``, and the fused 58-factor graph computes in between.
Any kernel that grows a hidden ``.item()``/``float()``/numpy round-trip
— or any code path that silently ships a host array to device — raises
``XlaRuntimeError`` here, the runtime complement to the AST rule's
static view. ``@pytest.mark.transfers`` is the documented opt-out and
is exercised below so the escape hatch stays working.
"""

import jax
import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit, factor_names)


def _device_day_batch(seed=0, days=2, tickers=3):
    rng = np.random.default_rng(seed)
    close = 10.0 * np.exp(np.cumsum(
        rng.standard_normal((days, tickers, 240)) * 1e-3, axis=-1))
    bars = np.stack([close * 0.999, close * 1.001, close * 0.998,
                     close, rng.integers(0, 5000,
                                         (days, tickers, 240))],
                    axis=-1).astype(np.float32)
    mask = rng.random((days, tickers, 240)) > 0.05
    mask[:, 0] = True  # one full-coverage ticker per day
    return jax.device_put(bars), jax.device_put(mask)


def test_all_58_kernels_compute_without_implicit_transfers():
    """The acceptance contract: every registered kernel traces,
    compiles, and executes with the guard up — explicit placement in,
    explicit fetch out, zero implicit syncs in between."""
    bars, mask = _device_day_batch()
    names = factor_names()
    assert len(names) == 58
    out = compute_factors_jit(bars, mask)
    assert set(out) == set(names)
    host = jax.device_get(out)  # explicit d2h: allowed by design
    for name, v in host.items():
        assert v.shape == (2, 3), name
        assert np.isfinite(v).any() or np.isnan(v).all(), name


def test_guard_is_actually_armed():
    """An implicit device->host sync must raise inside this module —
    otherwise the whole file is a placebo."""
    x = jax.device_put(np.ones(4, np.float32))
    with pytest.raises(Exception, match="[Dd]isallowed"):
        float(x[0])  # float() of a device array = implicit sync


@pytest.mark.transfers
def test_transfers_marker_opts_out():
    """The documented escape hatch: a marked test may transfer
    implicitly (this is how bench/eval-layer tests coexist with the
    guard if they ever join TRANSFER_GUARDED_MODULES)."""
    x = jax.device_put(np.ones(4, np.float32))
    assert float(x[0]) == 1.0
