"""Attribution layer (telemetry/attribution.py): crash-safe trace
capture, trace post-processing, XLA compile/cost telemetry, and
wall-clock reconciliation."""

import gzip
import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from replication_of_minute_frequency_factor_tpu.config import Config
from replication_of_minute_frequency_factor_tpu.data.synthetic import (
    synth_day)
from replication_of_minute_frequency_factor_tpu.pipeline import (
    compute_exposures)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    Telemetry, get_telemetry, set_telemetry)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    attribution as attr)

FIXTURE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "fixtures", "synthetic_trace.trace.json")

NAMES = ("vol_return1min", "mmt_am", "liq_openvol")


# --------------------------------------------------------------------------
# reconcile
# --------------------------------------------------------------------------


def test_reconcile_fully_attributed_is_ok():
    block = attr.reconcile(10.0, {"io": 4.0, "device": 6.0})
    assert block["ok"]
    assert block["unattributed_s"] == 0.0
    assert block["attributed_s"] == 10.0


def test_reconcile_flags_large_unattributed_residual():
    block = attr.reconcile(10.0, {"io": 4.0})
    assert not block["ok"]
    assert block["unattributed_s"] == 6.0
    assert block["unattributed_frac"] == 0.6
    with pytest.raises(attr.ReconciliationError):
        attr.reconcile(10.0, {"io": 4.0}, strict=True)


def test_reconcile_overlap_is_reported_not_flagged():
    """Pipelined stages legitimately sum past the wall; only MISSING
    attribution is a measurement gap."""
    block = attr.reconcile(10.0, {"io": 8.0, "device": 7.0})
    assert block["ok"]
    assert block["overlap_s"] == 5.0
    assert block["unattributed_s"] == 0.0


def test_reconcile_absolute_floor_tolerates_microruns():
    # 50% unattributed but only 5 ms — interpreter slack, not a gap
    assert attr.reconcile(0.010, {"io": 0.005})["ok"]
    assert not attr.reconcile(10.0, {"io": 5.0})["ok"]


def test_reconcile_drops_non_second_entries():
    block = attr.reconcile(1.0, {"io": 1.0, "ingest_MB": 500.0,
                                 "dispatch_floor_ms": 3.0, "ok": True})
    assert set(block["stages"]) == {"io"}
    assert block["ok"]


# --------------------------------------------------------------------------
# trace post-processing
# --------------------------------------------------------------------------


def test_classify_op_precedence():
    assert attr.classify_op("all-reduce.3") == "collective"
    assert attr.classify_op("fusion.123") == "fusion"
    assert attr.classify_op("copy-start.2") == "infeed_outfeed"
    assert attr.classify_op("copy.9") == "data_movement"
    assert attr.classify_op("dynamic-update-slice.1") == "data_movement"
    assert attr.classify_op("dot.5") == "matmul_conv"
    assert attr.classify_op("frobnicate") == "other"


def test_fixture_breakdown_per_op_class():
    events, procs = attr.load_trace_events(FIXTURE)
    assert procs == {1: "/device:TPU:0", 2: "/host:CPU"}
    bd = attr.device_op_breakdown(events, procs)
    assert bd["device_pids"] == ["/device:TPU:0"]
    # host-side events (grid/factor_batch/python frames) must not count
    assert bd["total_device_us"] == pytest.approx(255.0)
    assert bd["by_class_us"] == pytest.approx({
        "fusion": 150.0, "data_movement": 40.0, "collective": 20.0,
        "matmul_conv": 40.0, "other": 5.0})
    # instance suffixes aggregate per op
    top = {row["op"]: row["us"] for row in bd["top_ops_us"]}
    assert top["fusion"] == pytest.approx(150.0)


def test_fixture_stage_annotations():
    events, _ = attr.load_trace_events(FIXTURE)
    totals = attr.stage_annotation_totals(events)
    assert totals == {"grid": 500.0, "factor_batch": 300.0}


def test_load_trace_events_gz_and_summarize(tmp_path):
    with open(FIXTURE) as fh:
        doc = fh.read()
    gz = tmp_path / "cap" / "host.trace.json.gz"
    gz.parent.mkdir()
    with gzip.open(gz, "wt") as fh:
        fh.write(doc)
    summary = attr.summarize_trace_dir(str(tmp_path / "cap"))
    assert summary["files"] == 1
    assert summary["events"] == 12
    assert summary["device_breakdown"]["total_device_us"] == \
        pytest.approx(255.0)


def test_unreadable_trace_file_is_empty_not_fatal(tmp_path):
    bad = tmp_path / "bad.trace.json"
    bad.write_text("{not json")
    events, procs = attr.load_trace_events(str(bad))
    assert events == [] and procs == {}


# --------------------------------------------------------------------------
# TraceCapture
# --------------------------------------------------------------------------


def _trace_files(root):
    return [os.path.join(r, f) for r, _, fs in os.walk(root) for f in fs]


def test_trace_capture_writes_nonempty_dir(tmp_path):
    import jax
    import jax.numpy as jnp

    tel = Telemetry(annotate_spans=False)
    pdir = str(tmp_path / "cap")
    with attr.TraceCapture(pdir, telemetry=tel):
        jax.block_until_ready(jax.jit(lambda x: x * 2)(jnp.ones(8)))
    assert _trace_files(pdir), "no trace files captured"
    assert tel.registry.counter_value("attribution.trace_captures") == 1


def test_trace_capture_stops_on_body_exception(tmp_path):
    """The crash-safety contract: stop_trace runs (and the trace lands
    on disk) even when the body raises — and the exception propagates
    unmasked."""
    import jax
    import jax.numpy as jnp

    tel = Telemetry(annotate_spans=False)
    pdir = str(tmp_path / "cap")
    with pytest.raises(ValueError, match="boom"):
        with attr.TraceCapture(pdir, telemetry=tel):
            jax.block_until_ready(jax.jit(lambda x: x + 1)(jnp.ones(4)))
            raise ValueError("boom")
    assert _trace_files(pdir), "crash path must still flush the trace"
    # a second capture must work (the profiler was really stopped)
    with attr.TraceCapture(str(tmp_path / "cap2"), telemetry=tel):
        pass
    assert tel.registry.counter_value(
        "attribution.trace_stop_failures") == 0


def test_trace_capture_none_dir_is_noop():
    tel = Telemetry(annotate_spans=False)
    with attr.TraceCapture(None, telemetry=tel) as tc:
        assert not tc.active
    assert tel.registry.counter_value("attribution.trace_captures") == 0


def test_trace_capture_attributes_its_own_cost(tmp_path):
    tel = Telemetry(annotate_spans=False)
    timer = tel.stage_timer()
    with attr.TraceCapture(str(tmp_path / "cap"), telemetry=tel,
                           timer=timer):
        pass
    assert timer.totals().get("trace_capture", 0.0) > 0.0


# --------------------------------------------------------------------------
# pipeline integration (satellite: the old pipeline start_trace had no
# stop on failure paths)
# --------------------------------------------------------------------------


def _write_days(tmp_path, rng, n=3):
    d = tmp_path / "kline"
    d.mkdir()
    for i in range(n):
        ds = str(np.datetime64("2024-01-02") + i)
        cols = synth_day(rng, n_codes=6, date=ds, missing_prob=0.05)
        arrays = {"code": pa.array([str(c) for c in cols["code"]]),
                  "time": pa.array(cols["time"])}
        for k in ("open", "high", "low", "close", "volume"):
            arrays[k] = pa.array(cols[k])
        pq.write_table(pa.table(arrays),
                       str(d / (ds.replace("-", "") + ".parquet")))
    return str(d)


def test_pipeline_trace_nonempty_and_reconciled(tmp_path, rng):
    """Acceptance shape: a pipeline run with profile_dir set leaves a
    non-empty trace AND a reconciliation block whose stage terms cover
    the wall within tolerance (unattributed_s explicit)."""
    md = _write_days(tmp_path, rng)
    pdir = str(tmp_path / "trace")
    tel = Telemetry(annotate_spans=False)
    t = compute_exposures(
        md, NAMES, cfg=Config(days_per_batch=2, profile_dir=pdir),
        progress=False, telemetry=tel)
    assert _trace_files(pdir), "profile_dir produced no trace files"
    block = t.reconciliation
    assert block["ok"], block
    assert "unattributed_s" in block
    # the capture's own cost is a named stage, not a residual
    assert "trace_capture" in block["stages"]
    assert tel.registry.counter_value("attribution.trace_captures") == 1


def test_pipeline_trace_stopped_on_abort(tmp_path, rng, monkeypatch):
    """A mid-run abort (wedged device, circuit breaker) must still stop
    the trace and flush it to disk — the failure-mode capture is the
    one that matters most."""
    import replication_of_minute_frequency_factor_tpu.pipeline as pl

    md = _write_days(tmp_path, rng)
    pdir = str(tmp_path / "trace")

    def explode(*a, **kw):
        raise RuntimeError("synthetic device wedge")

    monkeypatch.setattr(pl, "_run_device_pipeline", explode)
    with pytest.raises(RuntimeError, match="synthetic device wedge"):
        compute_exposures(
            md, NAMES, cfg=Config(days_per_batch=2, profile_dir=pdir),
            progress=False, telemetry=Telemetry(annotate_spans=False))
    assert _trace_files(pdir), "abort path dropped the trace"
    # the profiler must really be stopped: a fresh capture succeeds
    tel2 = Telemetry(annotate_spans=False)
    with attr.TraceCapture(str(tmp_path / "t2"), telemetry=tel2):
        pass
    assert tel2.registry.counter_value(
        "attribution.trace_start_failures") == 0


# --------------------------------------------------------------------------
# XLA compile / cost telemetry
# --------------------------------------------------------------------------


def test_compile_with_telemetry_records_cost_and_size():
    import jax
    import jax.numpy as jnp

    tel = Telemetry(annotate_spans=False)
    lowered = jax.jit(lambda x: jnp.tanh(x @ x).sum()).lower(
        jnp.ones((16, 16)))
    compiled = attr.compile_with_telemetry("toy", lowered, telemetry=tel)
    assert compiled(jnp.ones((16, 16))).shape == ()
    reg = tel.registry
    assert reg.counter_value("xla.compiles", fn="toy") == 1
    st = reg.histogram_stats("xla.compile_seconds", fn="toy")
    assert st and st["count"] == 1 and st["sum"] > 0
    assert reg.gauge_value("xla.hlo_module_bytes", fn="toy") > 0
    # cost_analysis on CPU reports flops + bytes accessed for this graph
    assert reg.gauge_value("xla.flops", fn="toy") > 0
    assert reg.gauge_value("xla.bytes_accessed", fn="toy") > 0
    # the event ties them together for the JSONL stream
    assert any(e["name"] == "xla_compile" and e["data"]["fn"] == "toy"
               for e in tel._events)


def test_install_compile_listeners_feed_current_telemetry():
    """jax.monitoring durations land in whatever telemetry is current
    AT FIRE TIME (there is no listener-removal API, so the hook must
    not capture an instance)."""
    import jax
    import jax.numpy as jnp

    assert attr.install_compile_listeners()
    assert attr.install_compile_listeners()  # idempotent
    prev = get_telemetry()
    tel = set_telemetry(Telemetry(annotate_spans=False))
    try:
        # a shape this suite never compiles elsewhere -> fresh compile
        jax.block_until_ready(
            jax.jit(lambda x: (x * 3 + 1).sum())(jnp.ones((7, 13))))
    finally:
        set_telemetry(prev)
    st = tel.registry.histogram_stats("xla.backend_compile_seconds")
    assert st and st["count"] >= 1


def test_xla_summary_lands_in_manifest(tmp_path):
    import jax
    import jax.numpy as jnp

    tel = Telemetry(annotate_spans=False)
    attr.compile_with_telemetry(
        "m", jax.jit(lambda x: x * 2).lower(jnp.ones(4)), telemetry=tel)
    tel.registry.observe("xla.backend_compile_seconds", 0.25)
    tel.registry.counter("xla.compilation_cache", outcome="hit")
    paths = tel.write(str(tmp_path / "out"))
    with open(paths["manifest"]) as fh:
        manifest = json.load(fh)
    xla = manifest["xla"]
    assert xla["backend_compiles"] == 1
    assert xla["compilation_cache"] == {"hits": 1, "misses": 0}
    assert any(k.startswith("xla.compile_seconds{fn=m}")
               for k in xla["per_jit"])


def test_build_report_embeds_trace_summary(tmp_path):
    import shutil

    cap = tmp_path / "cap"
    cap.mkdir()
    shutil.copy(FIXTURE, cap / "x.trace.json")
    report = attr.build_report({"io": 1.0, "device": 8.5}, wall_s=10.0,
                               profile_dir=str(cap))
    assert report["schema"] == attr.REPORT_SCHEMA
    assert report["reconciliation"]["ok"]
    assert report["trace"]["files"] == 1
    assert report["trace"]["device_breakdown"]["by_class_us"]["fusion"] \
        == pytest.approx(150.0)
    out = attr.write_report(str(tmp_path / "attribution.json"), report)
    with open(out) as fh:
        assert json.load(fh)["reconciliation"]["ok"]
