"""Telemetry subsystem: registry merge/label semantics, histogram
percentiles, span nesting + Chrome-trace export, JSONL schema
validation, manifest provenance, and the device-pipeline smoke test
(queue-depth/retry gauges under an injected failure)."""

import json
import os

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.config import Config
from replication_of_minute_frequency_factor_tpu.telemetry import (
    SCHEMA_VERSION, Histogram, MetricsRegistry, SpanTracer, Telemetry,
    get_telemetry, set_telemetry, validate_record)
from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
    validate_dir)

from test_pipeline import _write_day


# --------------------------------------------------------------------------
# registry
# --------------------------------------------------------------------------

def test_counter_label_semantics():
    r = MetricsRegistry()
    r.counter("reqs", 1, kind="wire")
    r.counter("reqs", 2, kind="wire")
    r.counter("reqs", 5, kind="raw")
    r.counter("reqs")  # unlabeled is its own series
    assert r.counter_value("reqs", kind="wire") == 3
    assert r.counter_value("reqs", kind="raw") == 5
    assert r.counter_value("reqs") == 1
    assert r.counter_total("reqs") == 9
    assert r.counter_value("nope") == 0.0
    snap = r.snapshot()
    assert snap["counters"]["reqs{kind=wire}"] == 3
    assert snap["counters"]["reqs"] == 1


def test_labels_are_order_insensitive():
    r = MetricsRegistry()
    r.counter("m", 1, a="1", b="2")
    r.counter("m", 1, b="2", a="1")
    assert r.counter_value("m", b="2", a="1") == 2
    assert list(r.snapshot()["counters"]) == ["m{a=1,b=2}"]


def test_gauge_last_write_wins():
    r = MetricsRegistry()
    r.gauge("depth", 1)
    r.gauge("depth", 4)
    r.gauge("depth", 2)
    assert r.gauge_value("depth") == 2
    assert r.gauge_value("absent") is None


def test_registry_merge_semantics():
    a, b = MetricsRegistry(), MetricsRegistry()
    a.counter("c", 2, k="x")
    b.counter("c", 3, k="x")
    b.counter("c", 7, k="y")
    a.gauge("g", 1)
    b.gauge("g", 9)  # b is the later writer: last-write-wins
    for v in (1, 2, 3):
        a.observe("h", v)
    for v in (10, 20):
        b.observe("h", v)
    a.merge(b)
    assert a.counter_value("c", k="x") == 5
    assert a.counter_value("c", k="y") == 7
    assert a.gauge_value("g") == 9
    st = a.histogram_stats("h")
    assert st["count"] == 5 and st["sum"] == 36
    assert st["min"] == 1 and st["max"] == 20


def test_histogram_percentiles():
    h = Histogram()
    for v in range(1, 101):
        h.observe(v)
    st = h.stats()
    assert st["count"] == 100 and st["sum"] == 5050
    assert st["min"] == 1 and st["max"] == 100
    assert 49 <= st["p50"] <= 52
    assert 94 <= st["p95"] <= 97
    assert Histogram().stats()["p50"] is None  # empty: no percentiles


def test_histogram_is_bounded_but_exact_on_aggregates():
    h = Histogram(bound=128)
    n = 50_000
    for v in range(n):
        h.observe(v)
    assert h.count == n                       # aggregates stay exact
    assert h.total == n * (n - 1) / 2
    assert h.max == n - 1 and h.min == 0
    assert len(h._samples) <= 128             # reservoir stays bounded
    # decimated reservoir still spans the stream: p50 within a few %
    assert abs(h.stats()["p50"] - n / 2) < n * 0.1


# --------------------------------------------------------------------------
# spans
# --------------------------------------------------------------------------

def test_span_nesting_and_timer_parity():
    reg = MetricsRegistry()
    tr = SpanTracer(registry=reg, annotate=False)
    with tr("outer"):
        with tr("inner"):
            pass
        with tr("inner"):
            pass
    events = tr.events()
    assert [e["name"] for e in events] == ["inner", "inner", "outer"]
    by_name = {e["name"]: e for e in events}
    assert by_name["inner"]["depth"] == 1 and by_name["outer"]["depth"] == 0
    # spans nest in time: inner lies within outer
    outer, inner = by_name["outer"], events[0]
    assert inner["ts_us"] >= outer["ts_us"]
    assert (inner["ts_us"] + inner["dur_us"]
            <= outer["ts_us"] + outer["dur_us"] + 1)
    # Timer-compatible accounting
    totals = tr.totals()
    assert set(totals) == {"outer", "inner"}
    assert totals["outer"] >= totals["inner"] > 0
    assert "inner:" in tr.report() and "x2" in tr.report()
    # registry histograms fed per span name
    assert reg.histogram_stats("span_seconds", span="inner")["count"] == 2


def test_chrome_trace_export_round_trip(tmp_path):
    tr = SpanTracer(annotate=False)
    with tr("a"):
        with tr("b"):
            pass
    path = str(tmp_path / "trace.json")
    tr.write_chrome_trace(path)
    with open(path) as fh:
        trace = json.load(fh)
    evs = trace["traceEvents"]
    assert len(evs) == 2
    for e in evs:
        assert e["ph"] == "X"
        assert set(e) >= {"name", "pid", "tid", "ts", "dur"}
        assert isinstance(e["ts"], (int, float))
    assert {e["name"] for e in evs} == {"a", "b"}


def test_span_retention_is_bounded():
    tr = SpanTracer(annotate=False, max_events=10)
    for _ in range(25):
        with tr("s"):
            pass
    assert len(tr.events()) == 10
    assert tr.dropped_spans == 15
    assert tr.totals()["s"] > 0  # aggregation continues past the bound


# --------------------------------------------------------------------------
# JSONL schema
# --------------------------------------------------------------------------

def test_validate_record_rejects_malformed():
    ok = {"schema": SCHEMA_VERSION, "ts": 1.0, "kind": "counter",
          "name": "x", "labels": {}, "value": 1}
    assert validate_record(ok) == []
    assert validate_record("nope")
    assert validate_record({})  # missing everything
    bad_ver = dict(ok, schema=SCHEMA_VERSION + 1)
    assert any("schema" in p for p in validate_record(bad_ver))
    bad_kind = dict(ok, kind="mystery")
    assert any("kind" in p for p in validate_record(bad_kind))
    missing = {k: v for k, v in ok.items() if k != "value"}
    assert any("value" in p for p in validate_record(missing))
    bad_type = dict(ok, value="fast")
    assert any("value" in p for p in validate_record(bad_type))


def test_write_bundle_is_schema_valid(tmp_path):
    tel = Telemetry(annotate_spans=False)
    tel.counter("c", 2, kind="wire")
    tel.gauge("g", 1)
    for v in (1, 2, 3):
        tel.observe("h", v)
    with tel.span("s"):
        pass
    tel.event("note", detail="hello")
    d = str(tmp_path / "tel")
    paths = tel.write(d, cfg=Config())
    assert set(paths) == {"manifest", "metrics", "trace"}
    report = validate_dir(d)
    assert report["ok"], report["problems"]
    # every artifact kind present in the stream
    assert {"manifest", "counter", "gauge", "histogram", "span",
            "event"} <= set(report["kinds"])


def test_validate_dir_flags_corruption(tmp_path):
    tel = Telemetry(annotate_spans=False)
    tel.counter("c")
    d = str(tmp_path / "tel")
    tel.write(d, cfg=Config())
    with open(os.path.join(d, "metrics.jsonl"), "a") as fh:
        fh.write('{"schema": 999, "kind": "counter"}\n')
        fh.write('not json at all\n')
    report = validate_dir(d)
    assert not report["ok"]
    assert len(report["problems"]) >= 2


def test_manifest_provenance(tmp_path):
    from replication_of_minute_frequency_factor_tpu.telemetry.manifest \
        import build_manifest, config_hash
    cfg = Config(days_per_batch=4)
    m = build_manifest(cfg)
    assert m["schema"] == SCHEMA_VERSION
    assert m["config"]["days_per_batch"] == 4
    assert m["config_hash"] == config_hash(cfg)
    assert len(m["config_hash"]) == 64
    assert m["versions"]["jax"] and m["versions"]["numpy"]
    assert m["wire_spec"]["n_slots"] == 240
    # config hash is stable and config-sensitive
    assert config_hash(cfg) == config_hash(Config(days_per_batch=4))
    assert config_hash(cfg) != config_hash(Config(days_per_batch=5))


def test_get_set_telemetry_roundtrip():
    prev = get_telemetry()
    try:
        mine = Telemetry(annotate_spans=False)
        assert set_telemetry(mine) is mine
        assert get_telemetry() is mine
    finally:
        set_telemetry(prev)


# --------------------------------------------------------------------------
# pipeline integration
# --------------------------------------------------------------------------

@pytest.fixture
def minute_dir(tmp_path, rng):
    d = tmp_path / "kline"
    d.mkdir()
    for ds in ("2024-01-02", "2024-01-03", "2024-01-04"):
        _write_day(str(d), rng, ds, missing_prob=0.05)
    return str(d)


def test_pipeline_smoke_populates_gauges_under_failure(
        minute_dir, tmp_path, monkeypatch):
    """_run_device_pipeline under one injected transient device failure:
    the injected Telemetry must come back with queue-depth gauges,
    per-stage histograms, the retry counter, and the encode-kind
    counter — the observability contract ISSUE 1 names."""
    from replication_of_minute_frequency_factor_tpu import pipeline as pl

    real = pl.compute_packed_prepared
    calls = {"n": 0}

    def flaky(*a, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("injected transport failure")
        return real(*a, **kw)

    monkeypatch.setattr(pl, "compute_packed_prepared", flaky)
    tel = Telemetry(annotate_spans=False)
    t = pl.compute_exposures(minute_dir, ["vol_return1min"],
                             cache_path=str(tmp_path / "c.parquet"),
                             cfg=Config(days_per_batch=2),
                             progress=False, telemetry=tel)
    assert not t.failures and len(np.unique(t.columns["date"])) == 3

    reg = tel.registry
    # retry path was exercised and counted
    assert reg.counter_total("pipeline.retries") >= 1
    # queue-depth: gauge sampled and distribution retained
    assert reg.gauge_value("pipeline.queue_depth") is not None
    assert reg.histogram_stats("pipeline.queue_depth")["count"] > 0
    # in-flight gauge settled back to zero
    assert reg.gauge_value("pipeline.inflight_batches") == 0
    # per-stage histograms for the hot stages; the pipeline's stage
    # timer tags every observation with the run's rolling backend
    # (docs/observability.md) so attribution can split stage time by
    # rolling_impl
    for stage in ("io", "grid", "pack", "device"):
        st = reg.histogram_stats("span_seconds", span=stage,
                                 rolling_impl="conv")
        assert st is not None and st["count"] > 0, stage
    # every batch's encode kind is classified
    assert reg.counter_total("pipeline.encode_kind") \
        == reg.counter_value("pipeline.batches_launched") \
        - reg.counter_total("pipeline.retries")
    # completion accounting
    assert reg.counter_value("pipeline.batches_completed") == 2
    assert reg.counter_value("pipeline.days_completed") == 3
    # Timer semantics still flow to the result object
    assert {"io", "grid", "device"} <= set(t.timings)


def test_pipeline_counts_failed_days_and_breaker(minute_dir, tmp_path,
                                                 monkeypatch):
    from replication_of_minute_frequency_factor_tpu import pipeline as pl

    def dead(*a, **kw):
        raise RuntimeError("dead device")

    monkeypatch.setattr(pl, "compute_packed_prepared", dead)
    tel = Telemetry(annotate_spans=False)
    with pytest.raises(RuntimeError, match="consecutive"):
        pl.compute_exposures(minute_dir, ["vol_return1min"],
                             cache_path=str(tmp_path / "c.parquet"),
                             cfg=Config(days_per_batch=1),
                             progress=False, telemetry=tel)
    reg = tel.registry
    assert reg.counter_value("pipeline.circuit_breaker_trips") == 1
    assert reg.gauge_value("pipeline.breaker_consecutive_failures") == 3
    assert reg.counter_total("pipeline.failed_days") >= 3


def test_cli_telemetry_dir_end_to_end(tmp_path, capsys):
    """`python -m <pkg> --telemetry-dir DIR` (no subcommand) runs the
    synthetic pipeline and writes a schema-valid bundle — the
    acceptance-criterion invocation, also smoke-checked by
    run_tests.sh."""
    from replication_of_minute_frequency_factor_tpu.__main__ import main

    d = str(tmp_path / "tel")
    prev = get_telemetry()
    try:
        rc = main(["--telemetry-dir", d])
    finally:
        set_telemetry(prev)  # the CLI installs its own instance
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["rows"] > 0
    report = validate_dir(d)
    assert report["ok"], report["problems"]
    # the stream carries the pipeline's queue-depth + stage histograms
    with open(os.path.join(d, "metrics.jsonl")) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    hists = {r["name"] for r in recs if r["kind"] == "histogram"}
    assert "pipeline.queue_depth" in hists
    assert "span_seconds" in hists
    gauges = {r["name"] for r in recs if r["kind"] == "gauge"}
    assert "pipeline.queue_depth" in gauges
    # manifest is both a file and the stream's first record
    assert recs[0]["kind"] == "manifest"
    assert recs[0]["payload"]["run_kind"] == "synthetic_pipeline"
    with open(os.path.join(d, "trace.json")) as fh:
        assert json.load(fh)["traceEvents"]


def test_cli_profile_dir_produces_trace_and_attribution(tmp_path, capsys):
    """`--telemetry-dir DIR --profile-dir PDIR` (the acceptance-
    criterion invocation): non-empty profiler trace, an attribution
    report whose stage terms cover the wall within tolerance
    (unattributed_s explicit), and compile metrics in JSONL+manifest."""
    from replication_of_minute_frequency_factor_tpu.__main__ import main

    d = str(tmp_path / "tel")
    pdir = str(tmp_path / "prof")
    prev = get_telemetry()
    import jax
    jax.clear_caches()  # earlier tests compiled these shapes already;
    # the compile-metrics assertions below need a real compile to fire
    try:
        rc = main(["--telemetry-dir", d, "--profile-dir", pdir])
    finally:
        set_telemetry(prev)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["reconciliation_ok"] is True
    found = [os.path.join(r, f) for r, _, fs in os.walk(pdir) for f in fs]
    assert found, "profile dir is empty"
    with open(os.path.join(d, "attribution.json")) as fh:
        report = json.load(fh)
    block = report["reconciliation"]
    assert block["ok"] and "unattributed_s" in block
    assert report["trace"]["files"] >= 1
    assert report["trace"]["events"] > 0
    # compile telemetry reached the stream and the manifest
    with open(os.path.join(d, "metrics.jsonl")) as fh:
        recs = [json.loads(ln) for ln in fh if ln.strip()]
    hists = {r["name"] for r in recs if r["kind"] == "histogram"}
    assert "xla.backend_compile_seconds" in hists
    with open(os.path.join(d, "manifest.json")) as fh:
        assert json.load(fh)["xla"]["backend_compiles"] >= 1
    report2 = validate_dir(d)
    assert report2["ok"], report2["problems"]
