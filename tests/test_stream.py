"""ISSUE 7 acceptance gates: the online intraday factor engine.

The load-bearing claim of stream/ is the incremental-carry contract:
folding a day minute-by-minute (or cohort-by-cohort) through
``init_carry / update / finalize`` reproduces the full-day batch
exposures BITWISE — no ulp pins needed for any of the 58 kernels,
because the carry keeps the bar prefix authoritative and ``finalize``
runs the SAME jitted batch formulation over it, injecting only the
reorder-exact accumulators (integer counts, pure selections; see
ops/incremental.py). The reference frame is the jitted batch graph
(``compute_factors_jit``): jitted-vs-jitted comparisons are what XLA
keeps stable per module shape (the eager op-by-op path differs at ulp
level through fusion, same as every other parity suite in this repo).

Every test here runs under ``jax.transfer_guard("disallow")``
(conftest.TRANSFER_GUARDED_MODULES): the engine moves data only by
explicit ``device_put``/``device_get``.
"""

import time

import jax
import numpy as np
import pytest

import bench
from replication_of_minute_frequency_factor_tpu import pipeline
from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit, factor_names, stream_requirements)
from replication_of_minute_frequency_factor_tpu.ops import incremental
from replication_of_minute_frequency_factor_tpu.stream import carry as sc
from replication_of_minute_frequency_factor_tpu.stream.engine import (
    StreamEngine)

#: one kernel per family shape class (the sharded smoke's set): cheap
#: snapshot graphs for the structural tests; the all-58 sweep below is
#: the exhaustive gate
_FAMILY_NAMES = ("vol_return1min", "mmt_ols_qrs", "doc_kurt",
                 "doc_pdf60", "trade_headRatio", "liq_openvol",
                 "mmt_am")


def _day(tickers=16, seed=0):
    rng = np.random.default_rng(seed)
    bars, mask = bench.make_batch(rng, n_days=1, n_tickers=tickers)
    return bars[0], mask[0]          # [T, 240, 5], [T, 240]


def _feed(eng, bars, mask, lo, hi, micro=8):
    s = lo
    while s < hi:
        e = min(s + micro, hi)
        eng.ingest_minutes(
            np.ascontiguousarray(np.swapaxes(bars[:, s:e], 0, 1)),
            np.ascontiguousarray(mask[:, s:e].T))
        s = e


# --------------------------------------------------------------------------
# THE parity gate: 240 increments == full day, all 58 kernels, bitwise
# --------------------------------------------------------------------------


def test_stream_240_increment_parity_all_58():
    """Feeding 240 per-minute increments reproduces the full-day batch
    exposure for every registered kernel — bitwise (any future pin
    would be declared HERE per kernel, like tests/test_sharded_resident
    documents its two ulp factors; today there are none)."""
    names = factor_names()
    assert len(names) == 58
    bars, mask = _day(tickers=24, seed=42)
    want = jax.device_get(compute_factors_jit(jax.device_put(bars),
                                              jax.device_put(mask)))
    got = pipeline.compute_exposures_streamed(bars, mask, micro_batch=16)
    bad = [n for n in names
           if not np.array_equal(want[n], got[n], equal_nan=True)]
    assert bad == [], f"streamed fold diverged from batch for {bad}"


# --------------------------------------------------------------------------
# partial-day prefix consistency + readiness (monotone, sound)
# --------------------------------------------------------------------------


def test_partial_day_prefix_matches_batch_and_readiness_monotone():
    """At every sampled minute t, the streamed snapshot equals the
    batch graph run on the prefix-masked day (absent tail slots zeroed
    and masked out), the readiness plane is monotone in t, and
    readiness is SOUND: a not-ready lane's exposure is NaN."""
    bars, mask = _day(tickers=12, seed=3)
    names = _FAMILY_NAMES
    eng = StreamEngine(12, names=names)
    last_ready = None
    for t_stop in (0, 1, 7, 51, 120, 240):
        eng.reset()
        _feed(eng, bars, mask, 0, t_stop)
        exp, ready = jax.device_get(eng.snapshot())
        pb = np.where(mask[:, :t_stop, None], bars[:, :t_stop], 0.0)
        pbars = np.concatenate(
            [pb, np.zeros_like(bars[:, t_stop:])], axis=1
        ).astype(np.float32)
        pmask = np.concatenate(
            [mask[:, :t_stop], np.zeros_like(mask[:, t_stop:])], axis=1)
        want = jax.device_get(compute_factors_jit(
            jax.device_put(pbars), jax.device_put(pmask), names=names))
        for j, n in enumerate(names):
            np.testing.assert_array_equal(
                want[n], exp[j],
                err_msg=f"prefix t={t_stop} factor {n}")
            assert not np.any(~ready[j] & ~np.isnan(exp[j])), \
                f"unready lane with non-NaN exposure: {n} at t={t_stop}"
        if last_ready is not None:
            assert not np.any(last_ready & ~ready), \
                f"readiness regressed by minute {t_stop}"
        last_ready = ready


def test_stream_requirements_cover_all_58():
    """Every canonical kernel declares a readiness requirement naming a
    real window counter — a new kernel without a streaming contract
    fails loudly at registry load, not silently at serve time."""
    reqs = stream_requirements()
    assert set(reqs) >= set(factor_names())
    for name, (counter, minimum) in reqs.items():
        assert counter in incremental.WINDOW_COUNTERS, name
        assert minimum >= 1, name


# --------------------------------------------------------------------------
# cohort path == scan path (bit-identical carry, not just exposures)
# --------------------------------------------------------------------------


def test_cohort_ingest_equals_scan_ingest_bitwise():
    """Streaming the same minutes as K-ticker cohorts (live-feed path,
    padding rows dropped) leaves a carry BIT-IDENTICAL to the
    whole-minute scan path — compared on the full serialized state
    (bars, mask, cursor, every accumulator), which subsumes exposure
    parity without compiling a kernel graph."""
    T, K = 12, 5   # K does not divide T: the pad path is exercised
    bars, mask = _day(tickers=T, seed=7)
    scan_eng = StreamEngine(T, names=_FAMILY_NAMES[:1])
    _feed(scan_eng, bars, mask, 0, 60, micro=6)
    cohort_eng = StreamEngine(
        T, names=_FAMILY_NAMES[:1],
        executables=scan_eng.executables)  # shared compile cache
    for t in range(60):
        for c0 in range(0, T, K):
            sel = np.arange(c0, min(c0 + K, T))
            present = mask[sel, t]
            idx = np.where(present, sel, T).astype(np.int32)
            rows = np.ascontiguousarray(bars[sel, t])
            if len(sel) < K:
                idx = np.concatenate(
                    [idx, np.full(K - len(sel), T, np.int32)])
                rows = np.concatenate(
                    [rows, np.zeros((K - len(sel), 5), np.float32)])
            cohort_eng.ingest_cohort(rows, idx)
        cohort_eng.advance()
    a, b = scan_eng.save(), cohort_eng.save()
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


# --------------------------------------------------------------------------
# mid-day restart: serialize -> restore -> identical tail
# --------------------------------------------------------------------------


def test_midday_restart_produces_identical_tail():
    """Snapshot the carry at minute 120, restore it into a FRESH
    engine, stream the remaining 120 minutes into both — exposures and
    serialized carries are bit-identical (the carry IS the complete
    streaming state)."""
    T = 12
    bars, mask = _day(tickers=T, seed=11)
    names = _FAMILY_NAMES[:3]
    eng = StreamEngine(T, names=names)
    _feed(eng, bars, mask, 0, 120)
    snap = eng.save()
    assert int(snap["t"]) == 120
    restored = StreamEngine(T, names=names,
                            executables=eng.executables).restore(snap)
    assert restored.minutes == 120
    _feed(eng, bars, mask, 120, 240)
    _feed(restored, bars, mask, 120, 240)
    a, _ = jax.device_get(eng.snapshot())
    b, _ = jax.device_get(restored.snapshot())
    np.testing.assert_array_equal(a, b)
    sa, sb = eng.save(), restored.save()
    for k in sa:
        np.testing.assert_array_equal(sa[k], sb[k], err_msg=k)


def test_restored_carry_on_different_ticker_sharding_finalizes_identically():
    """ISSUE 13 satellite pin: a mid-day stream carry saved from an
    UNSHARDED engine and restored onto a tickers-``NamedSharding``
    placement (a 4-shard submesh of the 8 virtual devices) must
    finalize identically — snapshot exposures, readiness AND the
    continued fold after more ingest are all bitwise. The carry is
    pure state; placement is an execution detail."""
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)

    T = 16
    bars, mask = _day(tickers=T, seed=17)
    names = _FAMILY_NAMES[:4]  # incl. doc_pdf60: the one global rank
    plain = StreamEngine(T, names=names)
    _feed(plain, bars, mask, 0, 97)  # mid-day, mid-micro-batch
    snap = plain.save()
    sharded = StreamEngine(T, names=names,
                           mesh=resident_mesh(4)).restore(snap)
    carry_leaf = sharded.carry["bars"]
    assert len(carry_leaf.sharding.device_set) == 4  # really placed
    ea, ra = jax.device_get(plain.snapshot())
    eb, rb = jax.device_get(sharded.snapshot())
    np.testing.assert_array_equal(ea, eb)
    np.testing.assert_array_equal(ra, rb)
    # the continued fold stays bitwise through scan, cohort and
    # advance updates on the sharded placement
    _feed(plain, bars, mask, 97, 140)
    _feed(sharded, bars, mask, 97, 140)
    rows = np.ascontiguousarray(bars[:3, 140]).astype(np.float32)
    idx = np.array([0, 5, T], np.int32)  # incl. a dropped pad row
    for eng in (plain, sharded):
        eng.ingest_cohort(rows, idx)
        eng.advance()
    ea2, _ = jax.device_get(plain.snapshot())
    eb2, _ = jax.device_get(sharded.snapshot())
    np.testing.assert_array_equal(ea2, eb2)
    # and the round trip BACK to an unsharded engine is lossless
    back = StreamEngine(T, names=names).restore(sharded.save())
    ea3, _ = jax.device_get(back.snapshot())
    np.testing.assert_array_equal(ea2, ea3)


def test_sharded_engine_rejects_nondividing_universe():
    """A universe that does not divide over the mesh's ticker shards
    must fail loudly at construction, not as a GSPMD shape error at
    first ingest."""
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)

    with pytest.raises(ValueError, match="divide"):
        StreamEngine(15, names=_FAMILY_NAMES[:1], mesh=resident_mesh(4))


def test_carry_roundtrip_preserves_every_leaf():
    """carry_to_host / carry_from_host is a lossless flat snapshot."""
    c = sc.init_carry(4)
    host = sc.carry_from_host(sc.carry_to_host(jax.device_put(c)))
    assert set(host) == set(c)
    for k in ("bars", "mask", "t"):
        np.testing.assert_array_equal(host[k], c[k], err_msg=k)
    assert set(host["inc"]) == set(c["inc"])
    for k in c["inc"]:
        np.testing.assert_array_equal(host["inc"][k], c["inc"][k],
                                      err_msg=f"inc/{k}")


# --------------------------------------------------------------------------
# guardrails
# --------------------------------------------------------------------------


def test_over_ingest_past_240_slots_raises():
    T = 4
    bars, mask = _day(tickers=T, seed=1)
    eng = StreamEngine(T, names=_FAMILY_NAMES[:1])
    _feed(eng, bars, mask, 0, 240)
    with pytest.raises(ValueError, match="overruns"):
        eng.ingest_minutes(
            np.zeros((1, T, 5), np.float32), np.zeros((1, T), bool))
    with pytest.raises(ValueError, match="advancing past"):
        eng.advance()


def test_restore_rejects_wrong_universe_size():
    eng = StreamEngine(4, names=_FAMILY_NAMES[:1])
    snap = eng.save()
    other = StreamEngine(6, names=_FAMILY_NAMES[:1],
                         executables=eng.executables)
    with pytest.raises(ValueError, match="sized for 6"):
        other.restore(snap)


def test_ticker_count_mismatch_raises():
    eng = StreamEngine(4, names=_FAMILY_NAMES[:1])
    with pytest.raises(ValueError, match="engine holds"):
        eng.ingest_minutes(np.zeros((1, 5, 5), np.float32),
                           np.zeros((1, 5), bool))


@pytest.mark.transfers  # bench is a boundary layer: it materializes
def test_stream_bench_smoke_record():
    """bench.stream_smoke: the CPU acceptance evidence — zero compiles
    after warmup across every ingest shape, streamed-vs-full-day
    parity on the seeded day, and the declared r9_stream_intraday_v1
    stamp on the bars/sec record. Since ISSUE 18 the smoke runs BOTH
    finalize impls (zero compiles + clean parity each) and checks the
    fast statistic fold survives a cohort<->scan ingest mix
    bit-identically."""
    r = bench.stream_smoke()
    assert r["ok"], r
    assert r["methodology"] == "r9_stream_intraday_v1"
    assert r["compiles_during_load"] == 0
    assert r["parity_mismatched"] == []
    assert r["updates"] > 0 and r["bars"] > 0
    assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]
    assert r["bars_per_s"] > 0
    assert set(r["impls"]) == {"exact", "fast"}
    for impl, v in r["impls"].items():
        assert v["finalize_impl_resolved"] == impl
        assert v["compiles_during_load"] == 0, impl
        assert v["parity_mismatched"] == [], impl
    assert r["fast_fold_mix"]["leaves_differ"] == []
    assert r["fast_fold_mix"]["snapshot_bitwise"] is True


def test_warm_engine_ingest_compiles_nothing():
    """After warmup at the declared shapes, steady-state ingest +
    snapshot trigger ZERO compiles (the r9 acceptance signal, measured
    by the same xla.compiles counter serve uses)."""
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        get_telemetry)
    T = 8
    bars, mask = _day(tickers=T, seed=5)
    eng = StreamEngine(T, names=_FAMILY_NAMES[:2])
    eng.warmup(micro_batches=(4,), cohorts=(3,))
    reg = get_telemetry().registry
    before = reg.counter_total("xla.compiles")
    _feed(eng, bars, mask, 0, 16, micro=4)
    rows = np.ascontiguousarray(bars[:3, 16])
    idx = np.arange(3, dtype=np.int32)
    eng.ingest_cohort(rows, idx)
    eng.advance()
    eng.snapshot()
    assert int(reg.counter_total("xla.compiles") - before) == 0


def test_staleness_none_until_first_ingest_then_counts_up():
    """ISSUE 16 satellite: ``staleness_s`` is the wall-clock freshness
    signal healthz / the fleet rollup / the SLO timeline sampler read.
    A just-opened engine is unfed, not stale (None); after an applied
    ingest it counts up from ~0 and a later ingest resets it."""
    T = 6
    eng = StreamEngine(T, names=_FAMILY_NAMES[:1])
    assert eng.staleness_s() is None
    bars, mask = _day(tickers=T)
    _feed(eng, bars, mask, 0, 2)
    s1 = eng.staleness_s()
    assert s1 is not None and 0.0 <= s1 < 60.0
    time.sleep(0.05)
    s2 = eng.staleness_s()
    assert s2 > s1
    _feed(eng, bars, mask, 2, 4)
    assert eng.staleness_s() < s2
