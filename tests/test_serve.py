"""The serving layer (ISSUE 6): warm-executable reuse, device-resident
exposure cache, request coalescing, load shedding, HTTP binding.

This module runs under ``jax.transfer_guard("disallow")``
(conftest.TRANSFER_GUARDED_MODULES): the in-process client must hand
back HOST data — any implicit transfer on the calling thread raises.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from replication_of_minute_frequency_factor_tpu.serve import (
    DeviceExposureCache, FactorServer, LoadShedError, Query, ServeConfig,
    SyntheticSource, serve_http)
from replication_of_minute_frequency_factor_tpu.serve.engine import (
    ServeEngine)
from replication_of_minute_frequency_factor_tpu.telemetry import Telemetry

NAMES = ("vol_return1min", "mmt_am", "liq_openvol")


def _server(n_days=8, n_tickers=32, names=NAMES, start=True,
            stream=False, stream_batches=(1,), **scfg):
    tel = Telemetry()
    src = SyntheticSource(n_days=n_days, n_tickers=n_tickers, seed=3)
    srv = FactorServer(src, names=names, telemetry=tel,
                       serve_cfg=ServeConfig(**scfg), start=start,
                       stream=stream, stream_batches=stream_batches)
    return srv, tel


def _day_minutes(src, lo, hi):
    """Host ``(bars [B, T, 5], present [B, T])`` for minutes
    ``[lo, hi)`` of the source's day 0."""
    bars, mask = src.slab(0, 1)
    return (np.ascontiguousarray(np.swapaxes(bars[0][:, lo:hi], 0, 1)),
            np.ascontiguousarray(mask[0][:, lo:hi].T))


# --------------------------------------------------------------------------
# warm executables + exposure cache
# --------------------------------------------------------------------------


def test_second_identical_request_compiles_nothing():
    """The acceptance gate: request 1 compiles the block executable
    (xla.compiles >= 1 through compile_with_telemetry); request 2 over
    the same range must be answered warm — compile counter delta ZERO
    and an exposure-cache hit."""
    srv, tel = _server()
    try:
        c = srv.client()
        r1 = c.factors(0, 4)
        reg = tel.registry
        after_first = reg.counter_total("xla.compiles")
        assert after_first >= 1
        assert reg.counter_value("serve.executables", outcome="miss") >= 1
        r2 = c.factors(0, 4)
        assert reg.counter_total("xla.compiles") == after_first
        assert reg.counter_value("serve.cache", outcome="hit") == 1
        assert reg.counter_value("serve.cache", outcome="miss") == 1
        assert reg.counter_total("serve.dispatches") == 1
        for n in NAMES:
            np.testing.assert_array_equal(r1["exposures"][n],
                                          r2["exposures"][n])
    finally:
        srv.close()


def test_served_exposures_match_direct_compute():
    """The served block is the same fused graph the batch engine runs,
    through the wire codec: values must match a direct
    compute_factors_jit over the raw slab within the documented decode
    wobble (prices decode within 1 ulp of the raw f32 cast —
    data/wire.py — which minute-return differencing amplifies a few
    orders of magnitude; return-volatility kernels sit around 1e-4
    relative worst-case, measured across seeds)."""
    from replication_of_minute_frequency_factor_tpu.models.registry import (
        compute_factors_jit)
    srv, _ = _server()
    try:
        r = srv.client().factors(1, 5)
        bars, mask = srv.source.slab(1, 5)
        direct = compute_factors_jit(jax.device_put(bars),
                                     jax.device_put(mask), names=NAMES)
        for n in NAMES:
            np.testing.assert_allclose(
                np.asarray(r["exposures"][n], np.float32),
                jax.device_get(direct[n]), rtol=2e-4, atol=1e-7)
    finally:
        srv.close()


def test_ic_and_decile_answers_are_consistent():
    """IC lies in [-1, 1] where defined, the last `horizon` days are
    NaN (no forward close), and decile counts sum to the per-day valid
    cross-section."""
    srv, _ = _server()
    try:
        c = srv.client()
        ic = c.ic("vol_return1min", 0, 6, horizon=2)
        arr = np.asarray(ic["ic"], np.float64)
        assert arr.shape == (6,)
        assert np.all(np.isnan(arr[-2:]))
        finite = arr[np.isfinite(arr)]
        assert finite.size and np.all(np.abs(finite) <= 1.0 + 1e-6)
        dec = c.decile("mmt_am", 0, 6, horizon=1, group_num=4)
        counts = np.asarray(dec["counts"])
        assert counts.shape == (6, 4)
        assert counts.sum() > 0
        mean_ret = np.asarray(dec["mean_fwd_ret"], np.float64)
        assert np.all(np.isnan(mean_ret[-1]))  # no forward day in block
    finally:
        srv.close()


def test_cache_eviction_under_small_byte_budget():
    """A budget sized for ~1 block forces LRU eviction on the second
    range and a re-miss on the first; counters and the bytes gauge must
    say so."""
    srv, tel = _server(cache_bytes=0)  # probe: disabled cache still works
    try:
        srv.client().factors(0, 2)
        assert tel.registry.counter_total("serve.cache_oversize") == 1
    finally:
        srv.close()

    # size the budget from a real block: fits one, not two
    src = SyntheticSource(n_days=8, n_tickers=32, seed=3)
    probe_tel = Telemetry()
    probe = FactorServer(src, names=NAMES, telemetry=probe_tel)
    try:
        probe.client().factors(0, 2)
        block_bytes = probe_tel.registry.gauge_value("serve.cache_bytes")
    finally:
        probe.close()
    assert block_bytes and block_bytes > 0

    srv, tel = _server(cache_bytes=int(block_bytes * 1.5))
    try:
        c = srv.client()
        c.factors(0, 2)                 # miss, cached
        c.factors(2, 4)                 # miss, evicts [0, 2)
        c.factors(0, 2)                 # miss again, evicts [2, 4)
        reg = tel.registry
        assert reg.counter_value("serve.cache", outcome="miss") == 3
        assert reg.counter_total("serve.cache_evictions") == 2
        assert reg.gauge_value("serve.cache_bytes") <= block_bytes * 1.5
        assert reg.gauge_value("serve.cache_entries") == 1
    finally:
        srv.close()


@pytest.mark.transfers  # builds device arrays directly on this thread
def test_expcache_lru_order_and_delete():
    """Unit-level LRU semantics: a get() refreshes recency, eviction
    deletes the device buffers."""
    tel = Telemetry()
    cache = DeviceExposureCache(byte_budget=3 * 4 * 10, telemetry=tel)

    def entry():
        return {"x": jnp.zeros(10, jnp.float32)}  # 40 bytes

    a, b, c = entry(), entry(), entry()
    cache.put("a", a)
    cache.put("b", b)
    cache.put("c", c)
    assert cache.get("a") is not None   # refresh a: LRU is now b
    cache.put("d", entry())             # evicts b
    assert cache.get("b") is None
    assert cache.get("a") is not None
    assert b["x"].is_deleted()
    assert not a["x"].is_deleted()
    assert tel.registry.counter_total("serve.cache_evictions") == 1


# --------------------------------------------------------------------------
# coalescing + queue
# --------------------------------------------------------------------------


def test_concurrent_identical_range_queries_coalesce():
    """K queued queries over one fresh range drain as ONE micro-batch
    and are answered by ONE device dispatch — counter-asserted."""
    srv, tel = _server(start=False)
    try:
        futs = [srv.submit(Query("factors", 2, 6, names=("mmt_am",)))
                for _ in range(6)]
        futs.append(srv.submit(Query("ic", 2, 6, factor="mmt_am")))
        futs.append(srv.submit(Query("decile", 2, 6,
                                     factor="vol_return1min")))
        srv.start()
        results = [f.result(120) for f in futs]
        reg = tel.registry
        assert reg.counter_total("serve.dispatches") == 1
        assert reg.counter_value("serve.coalesced_dispatches") == 1
        assert reg.counter_value("serve.coalesced_requests") == 8
        assert reg.histogram_stats("serve.batch_size")["max"] == 8
        for r in results[:6]:
            np.testing.assert_array_equal(r["exposures"]["mmt_am"],
                                          results[0]["exposures"]["mmt_am"])
    finally:
        srv.close()


def test_mixed_ranges_in_one_batch_dispatch_per_range():
    srv, tel = _server(start=False)
    try:
        f1 = [srv.submit(Query("factors", 0, 2)) for _ in range(3)]
        f2 = [srv.submit(Query("factors", 2, 4)) for _ in range(2)]
        srv.start()
        for f in f1 + f2:
            f.result(120)
        reg = tel.registry
        assert reg.counter_total("serve.dispatches") == 2
        assert reg.counter_value("serve.coalesced_requests") == 5
    finally:
        srv.close()


def test_full_queue_sheds():
    srv, tel = _server(start=False, queue_limit=2)
    try:
        srv.submit(Query("factors", 0, 2))
        srv.submit(Query("factors", 0, 2))
        with pytest.raises(LoadShedError, match="queue full"):
            srv.submit(Query("factors", 0, 2))
        assert tel.registry.counter_value("serve.load_shed",
                                          reason="queue_full") == 1
        srv.start()  # drain the two queued requests on close
    finally:
        srv.close()


def test_validation_errors_raise_on_the_callers_thread():
    srv, _ = _server()
    try:
        with pytest.raises(ValueError, match="outside"):
            srv.submit(Query("factors", 0, 99))
        with pytest.raises(ValueError, match="unknown factor"):
            srv.submit(Query("ic", 0, 4, factor="nope"))
        with pytest.raises(ValueError, match="horizon"):
            srv.submit(Query("ic", 0, 2, factor="mmt_am", horizon=5))
        with pytest.raises(ValueError, match="kind"):
            srv.submit(Query("frobnicate", 0, 2))
    finally:
        srv.close()


# --------------------------------------------------------------------------
# breaker / load shedding
# --------------------------------------------------------------------------


def _boom(bars, mask):
    raise RuntimeError("injected device failure")


def test_breaker_opens_and_sheds_after_consecutive_failures():
    srv, tel = _server(breaker_threshold=2, breaker_cooldown_s=30.0)
    try:
        srv.engine.build_block = _boom
        for _ in range(2):
            with pytest.raises(RuntimeError, match="injected"):
                srv.submit(Query("factors", 0, 2)).result(60)
        with pytest.raises(LoadShedError, match="breaker open"):
            srv.submit(Query("factors", 0, 2))
        reg = tel.registry
        assert reg.counter_total("serve.breaker_trips") == 1
        assert reg.counter_value("serve.load_shed", reason="breaker") == 1
        assert reg.gauge_value("serve.breaker_consecutive_failures") == 2
    finally:
        srv.close()


def test_breaker_half_open_probe_recovers():
    srv, tel = _server(breaker_threshold=1, breaker_cooldown_s=0.15)
    try:
        srv.engine.build_block = _boom
        with pytest.raises(RuntimeError, match="injected"):
            srv.submit(Query("factors", 0, 2)).result(60)
        with pytest.raises(LoadShedError):
            srv.submit(Query("factors", 0, 2))
        # heal the engine, wait out the cooldown: the next request is
        # the half-open probe and closes the breaker on success
        srv.engine = ServeEngine(srv.names, telemetry=srv.telemetry,
                                 executables=srv.executables)
        time.sleep(0.2)
        r = srv.submit(Query("factors", 0, 2)).result(60)
        assert "exposures" in r
        assert tel.registry.gauge_value(
            "serve.breaker_consecutive_failures") == 0
        r2 = srv.submit(Query("factors", 2, 4)).result(60)
        assert "exposures" in r2
    finally:
        srv.close()


# --------------------------------------------------------------------------
# HTTP binding
# --------------------------------------------------------------------------


def _post(port, doc, path="/v1/query"):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as resp:
        return resp.status, json.loads(resp.read())


def test_http_round_trip_matches_in_process_client():
    srv, tel = _server()
    httpd = None
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        status, via_http = _post(port, {"kind": "ic", "start": 0,
                                        "end": 4,
                                        "factor": "vol_return1min"})
        assert status == 200
        direct = srv.client().ic("vol_return1min", 0, 4)
        assert via_http["mean_ic"] == direct["mean_ic"]
        np.testing.assert_array_equal(
            np.asarray(via_http["ic"], np.float64),
            np.asarray(direct["ic"], np.float64))
        # factors round-trip
        status, r = _post(port, {"kind": "factors", "start": 0, "end": 2,
                                 "names": ["mmt_am"]})
        assert status == 200 and list(r["exposures"]) == ["mmt_am"]
        assert len(r["exposures"]["mmt_am"]) == 2
        # health + metrics surfaces
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            h = json.loads(resp.read())
        assert h["ok"] and h["breaker_open"] is False
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/metrics",
                timeout=30) as resp:
            snap = json.loads(resp.read())
        assert "serve.dispatches" in snap["counters"]
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


def test_http_error_codes():
    srv, _ = _server(breaker_threshold=1, breaker_cooldown_s=30.0)
    httpd = None
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"kind": "factors", "start": 0, "end": 99})
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"kind": "factors"}, path="/v1/nope")
        assert e.value.code == 404
        # a failing engine: 500 on the dispatch, then 503 once shedding
        srv.engine.build_block = _boom
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"kind": "factors", "start": 0, "end": 2})
        assert e.value.code == 500
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"kind": "factors", "start": 0, "end": 2})
        assert e.value.code == 503
        assert json.loads(e.value.read())["shed"] is True
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


def test_shed_503_carries_retry_after_header():
    """ISSUE 11 satellite: both shed shapes answer 503 WITH a
    ``Retry-After`` backoff hint derived from the breaker cooldown —
    the remaining cooldown on a breaker shed, the full cooldown on a
    full-queue shed."""
    # breaker-open shed: remaining cooldown (<= 30 s, >= 1 s rounded)
    srv, _ = _server(breaker_threshold=1, breaker_cooldown_s=30.0)
    httpd = None
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        srv.engine.build_block = _boom
        with pytest.raises(urllib.error.HTTPError):
            _post(port, {"kind": "factors", "start": 0, "end": 2})
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"kind": "factors", "start": 0, "end": 2})
        assert e.value.code == 503
        retry = int(e.value.headers["Retry-After"])
        assert 1 <= retry <= 30
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()
    # full-queue shed: the cooldown as the backoff hint
    srv2, _ = _server(start=False, queue_limit=1,
                      breaker_cooldown_s=7.0)
    httpd2 = None
    try:
        httpd2, _t = serve_http(srv2)
        port = httpd2.server_address[1]
        srv2.submit(Query("factors", 0, 2))  # fills the queue
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"kind": "factors", "start": 0, "end": 2})
        assert e.value.code == 503
        assert int(e.value.headers["Retry-After"]) == 7
        srv2.start()  # drain on close
    finally:
        if httpd2 is not None:
            httpd2.shutdown()
        srv2.close()


def test_load_shed_error_carries_retry_after_attr():
    """The in-process face of the same hint: LoadShedError.retry_after_s
    is set on both shed shapes (the fleet router reads it to pick the
    pod Retry-After)."""
    srv, _ = _server(start=False, queue_limit=1, breaker_cooldown_s=5.0)
    try:
        srv.submit(Query("factors", 0, 2))
        with pytest.raises(LoadShedError) as e:
            srv.submit(Query("factors", 0, 2))
        assert e.value.retry_after_s == 5.0
        srv.start()
    finally:
        srv.close()


def test_health_carries_replica_identity_block():
    """ISSUE 11 satellite: healthz (served from FactorServer.health so
    the standalone server and the fleet rollup share one shape) gains
    the ``replica`` identity block — label, device set, breaker
    state."""
    srv, _ = _server(breaker_threshold=1, breaker_cooldown_s=30.0)
    try:
        h = srv.health()
        rep = h["replica"]
        assert rep["label"] == "standalone"  # no identity passed
        assert rep["breaker"] == "closed"
        assert rep["devices"] == [str(d) for d in jax.devices()]
        # breaker state tracks the ladder
        srv.engine.build_block = _boom
        with pytest.raises(RuntimeError, match="injected"):
            srv.submit(Query("factors", 0, 2)).result(60)
        assert srv.health()["replica"]["breaker"] == "open"
        # the HTTP payload is the same dict
        httpd, _t = serve_http(srv)
        try:
            port = httpd.server_address[1]
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{port}/healthz",
                    timeout=30) as resp:
                via_http = json.loads(resp.read())
            assert via_http["replica"]["label"] == "standalone"
            assert via_http["replica"]["breaker"] == "open"
        finally:
            httpd.shutdown()
    finally:
        srv.close()


# --------------------------------------------------------------------------
# smoke + load path (the r8_serve_v1 record)
# --------------------------------------------------------------------------


def test_serve_bench_smoke_record():
    """bench.serve_smoke: the CPU acceptance evidence — zero compiles
    during load, >=1 coalesced dispatch, cache hits > 0, and the
    declared r8_serve_v1 stamp on the p50/p99/QPS record."""
    import bench
    r = bench.serve_smoke()
    assert r["ok"], r
    assert r["methodology"] == "r8_serve_v1"
    assert r["compiles_during_load"] == 0
    assert r["coalesced_dispatches"] >= 1
    assert r["cache_hits"] > 0
    assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]


def test_concurrent_clients_under_load_all_answered(monkeypatch):
    """A mini load test through the live queue: N threads, every
    request answered, nothing shed, per-request latency histogram
    populated. Runs with the runtime lock-assert twin armed
    (ISSUE 19): the breaker state and registry mutate from caller and
    worker threads under load, so a lock-discipline regression raises
    a named LockAssertionError instead of flaking."""
    monkeypatch.setenv("MFF_LOCK_ASSERT", "1")
    srv, tel = _server(n_days=8, n_tickers=24)
    try:
        c = srv.client()
        errors = []

        def client_loop(tid):
            try:
                for j in range(6):
                    kind = (tid + j) % 3
                    if kind == 0:
                        c.factors(0, 4, names=("mmt_am",))
                    elif kind == 1:
                        c.ic("vol_return1min", 0, 4)
                    else:
                        c.decile("liq_openvol", 0, 4)
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(e)

        threads = [threading.Thread(target=client_loop, args=(i,))
                   for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        reg = tel.registry
        assert reg.counter_total("serve.load_shed") == 0
        assert reg.counter_total("serve.failures") == 0
        stats = reg.histogram_stats("serve.request_seconds", kind="ic")
        assert stats and stats["count"] >= 8
        assert reg.counter_value("serve.cache", outcome="hit") > 0
    finally:
        srv.close()


def test_cli_serve_demo(capsys):
    from replication_of_minute_frequency_factor_tpu.__main__ import main
    rc = main(["serve", "--demo", "6", "--synthetic-days", "6",
               "--synthetic-tickers", "16",
               "--factors", "vol_return1min,mmt_am"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["demo_requests"] == 6
    assert out["dispatches"] >= 1 and out["cache_hits"] >= 1


# --------------------------------------------------------------------------
# streaming integration (ISSUE 7): ingest + intraday through the queue
# --------------------------------------------------------------------------


def test_stream_ingest_then_intraday_roundtrip():
    """Minute bars ingested through the queue advance the carry; an
    intraday query returns host exposures + the readiness plane at the
    carry's minute, and the SECOND snapshot compiles nothing (the
    stream engine shares the server's executable cache)."""
    srv, tel = _server(stream=True, stream_batches=(8,))
    try:
        c = srv.client()
        bars, present = _day_minutes(srv.source, 0, 8)
        r = c.ingest(bars, present)
        assert r["minute"] == 8
        assert r["bars"] == int(present.sum())
        snap = c.intraday()
        assert snap["minute"] == 8
        assert set(snap["exposures"]) == set(NAMES)
        assert set(snap["ready"]) == set(NAMES)
        assert len(snap["exposures"]["mmt_am"]) == srv.source.n_tickers
        reg = tel.registry
        before = reg.counter_total("xla.compiles")
        sub = c.intraday(names=("mmt_am",))
        assert list(sub["exposures"]) == ["mmt_am"]
        assert reg.counter_total("xla.compiles") == before
        assert reg.counter_total("stream.snapshots") == 2
    finally:
        srv.close()


def test_stream_ingest_applies_before_intraday_in_one_microbatch():
    """Latest-view semantics: with the worker paused, an intraday
    query enqueued BEFORE an ingest still answers from the advanced
    carry once the batch drains — ingests apply first."""
    srv, _ = _server(stream=True, stream_batches=(4,), start=False)
    try:
        bars, present = _day_minutes(srv.source, 0, 4)
        f_q = srv.submit(Query("intraday"))
        f_i = srv.ingest(bars, present)
        srv.start()
        assert f_i.result(60)["minute"] == 4
        assert f_q.result(60)["minute"] == 4
    finally:
        srv.close()


def test_concurrent_intraday_queries_coalesce_to_one_snapshot():
    """K intraday queries in one micro-batch → ONE snapshot dispatch
    (counter-asserted, the same coalescing contract as block
    queries)."""
    srv, tel = _server(stream=True, start=False)
    try:
        futures = [srv.submit(Query("intraday")) for _ in range(6)]
        srv.start()
        answers = [f.result(60) for f in futures]
        assert all(a["minute"] == 0 for a in answers)
        reg = tel.registry
        assert reg.counter_total("stream.snapshots") == 1
        assert reg.counter_total("serve.coalesced_dispatches") == 1
        assert reg.counter_value("serve.coalesced_requests") == 6
    finally:
        srv.close()


def test_stream_validation_errors():
    """intraday/ingest against a non-streaming server and malformed
    ingest shapes fail fast on the caller's thread."""
    srv, _ = _server()
    try:
        with pytest.raises(ValueError, match="stream=True"):
            srv.submit(Query("intraday"))
        with pytest.raises(ValueError, match="stream=True"):
            srv.ingest(np.zeros((1, 32, 5), np.float32),
                       np.zeros((1, 32), bool))
    finally:
        srv.close()
    srv2, _ = _server(stream=True)
    try:
        with pytest.raises(ValueError, match="bars \\[B, T, 5\\]"):
            srv2.ingest(np.zeros((1, 32, 4), np.float32),
                        np.zeros((1, 32), bool))
        with pytest.raises(ValueError, match="stream engine"):
            srv2.ingest(np.zeros((1, 16, 5), np.float32),
                        np.zeros((1, 16), bool))
        with pytest.raises(ValueError, match="unknown factor"):
            srv2.submit(Query("intraday", names=("nope",)))
    finally:
        srv2.close()


def test_http_ingest_and_intraday_roundtrip():
    """POST /v1/ingest advances the carry; kind=intraday via
    /v1/query reads it back; /healthz reports the minute cursor."""
    srv, _ = _server(stream=True, stream_batches=(2,))
    httpd = None
    try:
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        bars, present = _day_minutes(srv.source, 0, 2)
        status, r = _post(port, {"bars": bars.tolist(),
                                 "present": present.tolist()},
                          path="/v1/ingest")
        assert status == 200 and r["minute"] == 2
        status, snap = _post(port, {"kind": "intraday",
                                    "names": ["mmt_am"]})
        assert status == 200 and snap["minute"] == 2
        assert len(snap["ready"]["mmt_am"]) == srv.source.n_tickers
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=30) as resp:
            h = json.loads(resp.read())
        assert h["stream_minute"] == 2
        # malformed ingest → 400, not a worker-thread crash
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(port, {"bars": [[1, 2]]}, path="/v1/ingest")
        assert e.value.code == 400
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


def test_stream_ingest_failure_bumps_breaker_and_sheds():
    """A failing carry update fails its own future, opens the breaker
    after the threshold, and subsequent ingests shed — backpressure
    reaches the feed as an error."""
    srv, tel = _server(stream=True, breaker_threshold=1,
                       breaker_cooldown_s=30.0)
    try:
        srv.stream_engine.ingest_minutes = _boom
        bars, present = _day_minutes(srv.source, 0, 1)
        with pytest.raises(RuntimeError, match="injected"):
            srv.ingest(bars, present).result(60)
        with pytest.raises(LoadShedError):
            srv.ingest(bars, present)
        assert tel.registry.counter_value("serve.failures",
                                          stage="ingest") == 1
    finally:
        srv.close()


# --------------------------------------------------------------------------
# SLO plane surfaces (ISSUE 16)
# --------------------------------------------------------------------------


def test_http_slo_and_timeline_surfaces():
    """``GET /v1/slo`` serves the burn-rate summary as JSON and the
    ``slo_*``-only Prometheus view; ``GET /v1/timeline`` serves the
    frame ring with name/since/limit filters and 400s a malformed
    query."""
    # cold CPU dispatches overrun the default 250 ms latency budget —
    # lift it so the surface test reads a quiet plane
    srv, tel = _server(stream=True, stream_batches=(2,),
                       slo_latency_ms=10_000.0)
    httpd = None
    try:
        srv.client().factors(0, 2)
        srv.timeline.sample()  # bank a frame (and an SLO evaluation)
        httpd, _t = serve_http(srv)
        port = httpd.server_address[1]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/slo", timeout=30) as resp:
            doc = json.loads(resp.read())
        s = doc["slo"]
        assert s["available"] and s["frames"] >= 1
        # a streaming server declares all three serve objectives
        assert {"availability", "latency",
                "freshness"} <= set(s["objectives"])
        assert s["alerts"] == 0 and doc["evaluation"]
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/slo?format=prometheus",
                timeout=30) as resp:
            text = resp.read().decode()
        assert "slo_burn_rate" in text
        assert "serve_requests" not in text  # the slo-only view
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}"
                f"/v1/timeline?name=serve.requests&limit=5",
                timeout=30) as resp:
            t = json.loads(resp.read())
        assert t["count"] >= 1 and len(t["frames"]) == t["count"]
        assert all("serve.requests" in k
                   for f in t["frames"] for k in f["series"])
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                f"http://127.0.0.1:{port}/v1/timeline?since=yesterday",
                timeout=30)
        assert e.value.code == 400
    finally:
        if httpd is not None:
            httpd.shutdown()
        srv.close()


def test_healthz_reports_flight_and_staleness():
    """ISSUE 16 satellites: the healthz flight block counts suppressed
    dumps next to written ones, and a streaming server reports
    wall-clock ``stream_staleness_s`` (None before the first ingest,
    a number after)."""
    srv, _ = _server(stream=True)
    try:
        h = srv.health()
        assert h["flight"] == {"requests": 0, "dumps": 0,
                               "suppressed": 0}
        assert h["stream_staleness_s"] is None
        srv.flight.dump("breaker_trip")
        srv.flight.dump("breaker_trip")  # inside the 1 s rate limit
        bars, present = _day_minutes(srv.source, 0, 2)
        srv.ingest(bars, present).result(120)
        h = srv.health()
        assert h["flight"]["suppressed"] == 1
        assert isinstance(h["stream_staleness_s"], float)
        assert h["stream_staleness_s"] >= 0.0
    finally:
        srv.close()


def test_healthz_reports_resolved_stream_finalize_impl():
    """ISSUE 18 satellite: a streaming server reports the RESOLVED
    snapshot finalize impl in healthz — 'exact' by default, 'fast'
    when requested via ServeConfig AND a foldable kernel is served
    (the degrade-to-exact case is what an operator needs to see)."""
    srv, _ = _server(stream=True)
    try:
        assert srv.health()["stream_finalize_impl"] == "exact"
    finally:
        srv.close()
    srv, _ = _server(stream=True, stream_finalize_impl="fast")
    try:
        assert srv.stream_engine.finalize_impl_resolved == "fast"
        assert srv.health()["stream_finalize_impl"] == "fast"
    finally:
        srv.close()
    # a batch-served (non-streaming) server reports nothing here
    srv, _ = _server()
    try:
        assert "stream_finalize_impl" not in srv.health()
    finally:
        srv.close()
