"""Differential tests against the reference's *actual* kernel code.

The reference's ``cal_*`` functions (read from ``/root/reference``, the
real polars expression graphs, quirks and all) execute on the polars shim
(tools/refdiff/polars_shim) and must match this repo's numpy oracle at
f64-tight tolerances. Together with the oracle↔JAX golden-parity suite
(tests/test_parity.py) this closes the chain from the reference's own
source to the production TPU path. VERDICT.md round-1 "Missing #3".
"""

import os

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.data import synth_day
from tools.refdiff import harness

pytestmark = pytest.mark.skipif(
    not os.path.exists(os.path.join(harness.REFERENCE_DIR,
                                    harness._KERNELS)),
    reason="reference tree not mounted")

SCENARIOS = {
    "clean": dict(n_codes=6),
    "ragged": dict(n_codes=6, missing_prob=0.1),
    "zero_volume": dict(n_codes=6, zero_volume_prob=0.15),
    "constant_price": dict(n_codes=6, constant_price_codes=2),
    "short_days": dict(n_codes=6, short_day_codes=2),
    "everything": dict(n_codes=8, missing_prob=0.07, zero_volume_prob=0.1,
                       constant_price_codes=2, short_day_codes=2),
}


@pytest.mark.parametrize("label", sorted(SCENARIOS))
def test_reference_code_matches_oracle(label):
    rng = np.random.default_rng(42)
    day = synth_day(rng, **SCENARIOS[label])
    mismatches = harness.compare_day(day)
    assert not mismatches, "\n".join(mismatches[:20])


def test_all_58_kernels_are_exercised():
    mod = harness.load_reference_kernels()
    ref_names = sorted(n[4:] for n in dir(mod) if n.startswith("cal_"))
    from replication_of_minute_frequency_factor_tpu.models import (
        factor_names)
    assert ref_names == sorted(factor_names())


def test_shim_never_replaces_a_real_polars():
    """The shim must only ever be chosen when polars is absent — a real
    install must take precedence (it IS the reference engine)."""
    import importlib.util
    mod = harness.install_shim()
    if getattr(mod, "__is_refdiff_shim__", False):
        assert importlib.util.find_spec("polars") is None
    else:
        # a real polars won: it must actually be the importable wheel
        assert importlib.util.find_spec("polars") is not None
        assert mod.__name__ == "polars"


def test_sys_modules_not_left_mutated():
    """Reference exec installs 'polars'/'Factor' only for the exec's
    duration; afterwards a genuine `import polars` must not silently
    resolve to refdiff internals (ADVICE r2)."""
    import importlib.util
    import sys
    harness.load_reference_kernels()
    harness.load_reference_factor_module()
    for name in ("polars", "Factor"):
        mod = sys.modules.get(name)
        assert mod is None or not getattr(mod, "__is_refdiff_shim__",
                                          False), name
    assert "Factor" not in sys.modules
    if importlib.util.find_spec("polars") is None:
        assert "polars" not in sys.modules


def test_reference_exec_is_hash_pinned(tmp_path, monkeypatch):
    """A reference file whose bytes differ from the audited snapshot
    must fail closed before exec (ADVICE r2 medium: the file runs
    in-process, so provenance is the containment)."""
    src = os.path.join(harness.REFERENCE_DIR, "Factor.py")
    tampered = tmp_path / "Factor.py"
    tampered.write_bytes(open(src, "rb").read() + b"\n# tampered\n")
    monkeypatch.setattr(harness, "REFERENCE_DIR", str(tmp_path))
    with pytest.raises(RuntimeError, match="unpinned reference file"):
        harness._verified_reference_source("Factor.py")
    # explicit opt-out accepts the risk
    monkeypatch.setenv("REFDIFF_ALLOW_UNPINNED", "1")
    path, source = harness._verified_reference_source("Factor.py")
    assert path == str(tampered) and source.endswith(b"# tampered\n")
    # the pristine snapshot passes, and the returned bytes ARE the
    # audited bytes (hash-and-exec share one read — no TOCTOU window)
    monkeypatch.setattr(harness, "REFERENCE_DIR",
                        os.path.dirname(src))
    monkeypatch.delenv("REFDIFF_ALLOW_UNPINNED")
    path, source = harness._verified_reference_source("Factor.py")
    assert path == src
    import hashlib
    assert (hashlib.sha256(source).hexdigest()
            == harness._REFERENCE_SHA256["Factor.py"])


@pytest.mark.parametrize("weight_param", [None, "tmc", "cmc"])
def test_reference_eval_matches_repo(tmp_path, weight_param):
    """ic_test + group_test value parity against the reference's actual
    Factor.py code (monthly rebalance)."""
    fails = harness.compare_eval(rng_seed=7, weight_param=weight_param,
                                 tmp_dir=str(tmp_path))
    assert not fails, "\n".join(fails[:20])


def test_reference_eval_weekly(tmp_path):
    fails = harness.compare_eval(rng_seed=11, frequency="weekly",
                                 weight_param="tmc", tmp_dir=str(tmp_path))
    assert not fails, "\n".join(fails[:20])


def test_reference_final_exposure_matches_repo():
    """cal_final_exposure parity across all (mode, method, frequency)
    configs against the reference's actual MinuteFrequentFactorCICC.py."""
    try:
        fails = harness.compare_final_exposure(rng_seed=5, n_days=50)
    except harness.RefdiffUnsupported as e:
        pytest.skip(str(e))
    assert not fails, "\n".join(fails[:20])


@pytest.mark.parametrize("precompute_days", [0, 3])
def test_reference_pipeline_matches_repo(tmp_path, precompute_days):
    """cal_exposure_by_min_data (incl. incremental resume) parity against
    the reference's actual driver code."""
    try:
        fails = harness.compare_pipeline(str(tmp_path), n_days=5,
                                         precompute_days=precompute_days)
    except harness.RefdiffUnsupported as e:
        pytest.skip(str(e))
    assert not fails, "\n".join(fails[:20])
