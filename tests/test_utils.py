"""Aux subsystems: timers, failure report, data sanitizer
(SURVEY.md §5)."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.config import Config
from replication_of_minute_frequency_factor_tpu.pipeline import (
    compute_exposures)
from replication_of_minute_frequency_factor_tpu.utils import (
    FailureReport, Timer)
from replication_of_minute_frequency_factor_tpu.utils.debug import (
    DayDataError, validate_batch)

from test_pipeline import _write_day


def test_timer_accumulates():
    t = Timer()
    with t("a"):
        pass
    with t("a"):
        pass
    with t("b"):
        pass
    totals = t.totals()
    assert set(totals) == {"a", "b"}
    assert "a:" in t.report() and "x2" in t.report()


def test_failure_report():
    r = FailureReport()
    assert not r and "no failures" in r.summary()
    try:
        raise ValueError("boom")
    except ValueError as e:
        r.record("2024-01-02", "f.parquet", e)
    assert len(r) == 1
    assert "boom" in r.summary()
    assert r.keys() == ["2024-01-02"]


def test_validate_batch_catches_corruption():
    bars = np.ones((2, 4, 240, 5), np.float32)
    mask = np.ones((2, 4, 240), bool)
    assert validate_batch(bars, mask, raise_=False) == []
    bars[0, 0, 5, 3] = np.nan          # NaN close on a valid lane
    bars[1, 2, 7, 4] = -1.0            # negative volume
    bars[0, 1, 9, 1] = 0.5             # high < low (low is 1.0)
    probs = validate_batch(bars, mask, raise_=False)
    assert len(probs) == 3
    with pytest.raises(DayDataError):
        validate_batch(bars, mask)
    # corruption on a masked lane is fine
    bars2 = np.ones((1, 2, 240, 5), np.float32)
    mask2 = np.ones((1, 2, 240), bool)
    bars2[0, 0, 0, 0] = np.nan
    mask2[0, 0, 0] = False
    assert validate_batch(bars2, mask2, raise_=False) == []


def test_pipeline_debug_validate_and_timings(tmp_path, rng):
    d = tmp_path / "kline"
    d.mkdir()
    _write_day(str(d), rng, "2024-01-02")
    cfg = Config(days_per_batch=1, debug_validate=True)
    t = compute_exposures(str(d), ("mmt_am",), cfg=cfg, progress=False)
    assert len(t) > 0
    assert {"io", "grid", "device"} <= set(t.timings)
