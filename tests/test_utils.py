"""Aux subsystems: timers, failure report, data sanitizer
(SURVEY.md §5)."""

import numpy as np
import pytest

from replication_of_minute_frequency_factor_tpu.config import Config
from replication_of_minute_frequency_factor_tpu.pipeline import (
    compute_exposures)
from replication_of_minute_frequency_factor_tpu.utils import (
    FailureReport, Timer)
from replication_of_minute_frequency_factor_tpu.utils.debug import (
    DayDataError, validate_batch)

from test_pipeline import _write_day


def test_timer_accumulates():
    t = Timer()
    with t("a"):
        pass
    with t("a"):
        pass
    with t("b"):
        pass
    totals = t.totals()
    assert set(totals) == {"a", "b"}
    assert "a:" in t.report() and "x2" in t.report()


def test_failure_report():
    r = FailureReport()
    assert not r and "no failures" in r.summary()
    try:
        raise ValueError("boom")
    except ValueError as e:
        r.record("2024-01-02", "f.parquet", e)
    assert len(r) == 1
    assert "boom" in r.summary()
    assert r.keys() == ["2024-01-02"]


def test_validate_batch_catches_corruption():
    bars = np.ones((2, 4, 240, 5), np.float32)
    mask = np.ones((2, 4, 240), bool)
    assert validate_batch(bars, mask, raise_=False) == []
    bars[0, 0, 5, 3] = np.nan          # NaN close on a valid lane
    bars[1, 2, 7, 4] = -1.0            # negative volume
    bars[0, 1, 9, 1] = 0.5             # high < low (low is 1.0)
    probs = validate_batch(bars, mask, raise_=False)
    assert len(probs) == 3
    with pytest.raises(DayDataError):
        validate_batch(bars, mask)
    # corruption on a masked lane is fine
    bars2 = np.ones((1, 2, 240, 5), np.float32)
    mask2 = np.ones((1, 2, 240), bool)
    bars2[0, 0, 0, 0] = np.nan
    mask2[0, 0, 0] = False
    assert validate_batch(bars2, mask2, raise_=False) == []


def test_pipeline_debug_validate_and_timings(tmp_path, rng):
    d = tmp_path / "kline"
    d.mkdir()
    _write_day(str(d), rng, "2024-01-02")
    cfg = Config(days_per_batch=1, debug_validate=True)
    t = compute_exposures(str(d), ("mmt_am",), cfg=cfg, progress=False)
    assert len(t) > 0
    assert {"io", "grid", "device"} <= set(t.timings)


def test_compilation_cache_populates(tmp_path):
    """Config.compilation_cache_dir routes compiled executables to disk:
    after one pipeline-style jitted call, the directory must hold cache
    entries (so driver re-runs skip the fused-graph compile)."""
    import jax
    import jax.numpy as jnp

    from replication_of_minute_frequency_factor_tpu.config import (
        Config, apply_compilation_cache)

    d = str(tmp_path / "xla_cache")
    prev_t = jax.config.jax_persistent_cache_min_compile_time_secs
    prev_s = jax.config.jax_persistent_cache_min_entry_size_bytes
    apply_compilation_cache(Config(compilation_cache_dir=d))
    # production code leaves persistence thresholds alone; drop them so
    # this sub-second CPU graph persists
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
    try:
        @jax.jit
        def f(x):
            return (x * 3.0 + 1.0).sum()

        f(jnp.arange(128, dtype=jnp.float32)).block_until_ready()
        import os
        assert os.path.isdir(d) and len(os.listdir(d)) > 0
    finally:
        # an unset-dir call must restore the pre-mutation state (the
        # sticky-global regression this guards against)
        apply_compilation_cache(Config())
        assert jax.config.jax_compilation_cache_dir != d
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          prev_t)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes",
                          prev_s)
