"""ISSUE 15: the ``markets/`` session-spec subsystem.

Three gate families:

* **spec pinning** — the ``cn_ashare_240`` instance reproduces every
  seed constant of ``sessions.py`` byte-for-byte (the canonical-shape
  bitwise acceptance rests on this), and the registry's conflict/
  idempotence rules hold;
* **session-generic device paths** — the ingest wire round-trips at
  390/150/1440 slots, and the 58 kernels produce an answer at every
  registered shape through the same fused graphs;
* **S-increment stream parity** — the 240-increment bitwise gate of
  tests/test_stream.py generalized: streaming every minute of a
  us_390 and a crypto_1440 day through the session-sized carry
  finalizes BITWISE equal to the batch graph, mid-day save/restore is
  bit-identical to never stopping, and the readiness (window, min)
  contract stays monotone and sound at a non-240 session length.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax

from replication_of_minute_frequency_factor_tpu import sessions
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.markets import (
    CN_ASHARE_240,
    SESSIONS,
    SessionSpec,
    get_session,
    is_default,
    register_session,
    session_names,
)
from replication_of_minute_frequency_factor_tpu.models.registry import (
    compute_factors_jit,
    factor_names,
)
from replication_of_minute_frequency_factor_tpu.stream.engine import (
    StreamEngine,
)

SENTINELS = (
    "T_AM_OPEN", "T_AM_CLOSE", "T_NOON", "T_PM_OPEN", "T_PM_CLOSE",
    "T_LAST30_OPEN", "T_BETWEEN_OPEN", "T_BETWEEN_CLOSE",
    "T_CLOSE_AUCTION", "T_TAIL20", "T_TAIL50", "T_HEAD_END",
    "T_TOP20_END", "T_TOP50_END",
)


def _session_batch(rng, spec, n_days=2, n_tickers=8, missing=0.05):
    """Tick-aligned synthetic bars on one session's grid."""
    shape = (n_days, n_tickers, spec.n_slots)
    close = np.round(10.0 * np.exp(np.cumsum(
        rng.standard_normal(shape, dtype=np.float32) * np.float32(1e-3),
        axis=-1)), 2)
    open_ = np.round(close * (1 + rng.standard_normal(
        shape, dtype=np.float32) * np.float32(1e-4)), 2)
    high = np.maximum(open_, close)
    low = np.minimum(open_, close)
    volume = (rng.integers(0, 1000, shape) * 100).astype(np.float32)
    bars = np.stack([open_, high, low, close, volume], axis=-1)
    mask = rng.random(shape, dtype=np.float32) >= missing
    bars = np.where(mask[..., None], bars, 0.0).astype(np.float32)
    return bars, mask


def _bitwise(a, b):
    a, b = np.asarray(a, np.float32), np.asarray(b, np.float32)
    return (a.view(np.uint32) == b.view(np.uint32)).all()


# --------------------------------------------------------------------------
# spec pinning: cn_ashare_240 IS the seed's sessions.py
# --------------------------------------------------------------------------


def test_cn_spec_matches_seed_constants_bitwise():
    assert CN_ASHARE_240.n_slots == sessions.N_SLOTS == 240
    assert (CN_ASHARE_240.grid_times == sessions.GRID_TIMES).all()
    assert sessions.GRID_TIMES is CN_ASHARE_240.grid_times
    for name in SENTINELS:
        assert getattr(CN_ASHARE_240, name) == getattr(sessions, name), \
            name


def test_cn_time_to_slot_matches_seed_formula():
    # every whole minute of the day, plus sub-minute off-grid stamps
    msm = np.arange(24 * 60)
    times = (msm // 60) * 10_000_000 + (msm % 60) * 100_000
    got = CN_ASHARE_240.time_to_slot(times)
    # the seed formula, inlined (pre-ISSUE-15 sessions.py)
    hm = times // 10_000_000 * 60 + (times % 10_000_000) // 100_000
    am = (hm >= 570) & (hm < 690)
    pm = (hm >= 780) & (hm < 900)
    want = np.where(am, hm - 570, np.where(pm, hm - 780 + 120, -1))
    assert (got == want).all()
    # sub-minute components are off-grid
    assert (CN_ASHARE_240.time_to_slot(times + 30_000) == -1).all()
    # slot_to_time inverts on-grid slots
    assert (CN_ASHARE_240.slot_to_time(np.arange(240))
            == sessions.GRID_TIMES).all()


def test_registry_ships_the_four_specs():
    assert {"cn_ashare_240", "us_390", "hk_halfday",
            "crypto_1440"} <= set(session_names())
    assert get_session(None) is CN_ASHARE_240
    assert get_session("us_390").n_slots == 390
    assert get_session("hk_halfday").n_slots == 150
    assert get_session("crypto_1440").n_slots == 1440
    assert is_default(None) and is_default("cn_ashare_240")
    assert not is_default("us_390")
    with pytest.raises(KeyError):
        get_session("nasdaq_totally_real")


def test_register_session_conflict_and_idempotence():
    spec = SessionSpec(name="test_tiny_60", segments=((600, 60),))
    try:
        assert register_session(spec) is spec
        # same spec again: idempotent
        register_session(SessionSpec(name="test_tiny_60",
                                     segments=((600, 60),)))
        # DIFFERENT layout under the same name: refused
        with pytest.raises(ValueError, match="already registered"):
            register_session(SessionSpec(name="test_tiny_60",
                                         segments=((600, 61),)))
    finally:
        SESSIONS.pop("test_tiny_60", None)


def test_spec_is_hashable_static_jit_key():
    # two equal constructions hash equal (shared compiled executables);
    # the derived-sentinel rules reproduce cn's constants semantically
    a = SessionSpec(name="x", segments=((570, 120), (780, 120)))
    b = SessionSpec(name="x", segments=((570, 120), (780, 120)))
    assert a == b and hash(a) == hash(b)
    us = get_session("us_390")
    assert us.T_CLOSE_AUCTION == us.grid_times[390 - 3]
    assert us.T_LAST30_OPEN == us.grid_times[390 - 30]
    assert us.T_AM_OPEN == us.grid_times[0]
    assert us.T_PM_CLOSE == us.grid_times[-1]


def test_spec_validation():
    with pytest.raises(ValueError, match="no segments"):
        SessionSpec(name="empty", segments=())
    with pytest.raises(ValueError, match="leaves the day"):
        SessionSpec(name="overflow", segments=((23 * 60, 120),))


# --------------------------------------------------------------------------
# wire: session-generic encode/decode
# --------------------------------------------------------------------------


@pytest.mark.parametrize("sname", ["us_390", "hk_halfday",
                                   "crypto_1440"])
def test_wire_roundtrip_non_default_sessions(rng, sname):
    """Encode at a non-240 slot count (the sub-byte packings gate on
    divisibility: us_390 misses the vol10 divisor and widens to u16,
    crypto_1440 packs fully) and decode back exactly: mask bitwise,
    prices within the documented 1-ulp tick wobble, volumes exact."""
    spec = get_session(sname)
    bars, mask = _session_batch(rng, spec, n_days=2, n_tickers=6)
    w = wire.encode(bars, mask)
    assert w is not None, f"{sname} batch must be representable"
    # decode re-derives the slot count from dohl's slot axis
    assert w.dohl.shape[-2] == spec.n_slots
    dec_bars, dec_m = jax.device_get(
        wire.decode(*[jax.device_put(a) for a in w.arrays]))
    assert (np.asarray(dec_m) == mask).all()
    dec_bars = np.asarray(dec_bars)
    assert np.allclose(dec_bars[..., :4],
                       np.where(mask[..., None], bars, 0.0)[..., :4],
                       rtol=3e-7, atol=0)
    assert (dec_bars[..., 4] == bars[..., 4] * mask).all()


def test_wire_240_mask_path_unchanged(rng):
    """The canonical layout must keep its exact pre-ISSUE-15 decode
    graph: no pad-bit slice is traced when S % 8 == 0 (the jaxpr has
    no ``slice`` over the mask bits beyond the packed-buffer ones)."""
    bars, mask = _session_batch(rng, CN_ASHARE_240, n_days=1,
                                n_tickers=4)
    w = wire.encode(bars, mask)
    assert w is not None
    dec_bars, dec_m = jax.device_get(
        wire.decode(*[jax.device_put(a) for a in w.arrays]))
    assert (np.asarray(dec_m) == mask).all()


# --------------------------------------------------------------------------
# the 58 kernels at every registered shape
# --------------------------------------------------------------------------


def test_all_kernels_run_at_every_registered_session(rng):
    names = factor_names()
    for sname in session_names():
        spec = get_session(sname)
        bars, mask = _session_batch(rng, spec, n_days=1, n_tickers=4)
        out = compute_factors_jit(jax.device_put(bars),
                                  jax.device_put(mask), names=names,
                                  session=spec)
        for n in names:
            assert np.asarray(out[n]).shape == (1, 4), (sname, n)


def test_session_shape_mismatch_fails_loudly(rng):
    """A 240-shaped tensor under a 390-slot session must error at
    trace time (grid-times broadcast), never silently alias."""
    bars, mask = _session_batch(rng, CN_ASHARE_240, n_days=1,
                                n_tickers=4)
    with pytest.raises(Exception):
        compute_factors_jit(jax.device_put(bars), jax.device_put(mask),
                            names=("mmt_pm",), session="us_390")


# --------------------------------------------------------------------------
# S-increment stream parity (the acceptance gate, tier-1)
# --------------------------------------------------------------------------


@pytest.mark.parametrize("sname", ["us_390", "crypto_1440"])
def test_stream_increment_parity_bitwise(rng, sname):
    """The 240-increment parity gate generalized to S increments: all
    58 kernels streamed minute-by-minute through the session-sized
    carry finalize BITWISE equal to the full-day batch graph."""
    spec = get_session(sname)
    names = factor_names()
    bars, mask = _session_batch(rng, spec, n_days=1, n_tickers=6)
    day_bars, day_mask = bars[0], mask[0]

    batch = compute_factors_jit(jax.device_put(day_bars),
                                jax.device_put(day_mask), names=names,
                                session=spec)
    batch_stack = np.stack([np.asarray(batch[n]) for n in names])

    eng = StreamEngine(6, names=names, session=spec)
    eng.warmup(micro_batches=(spec.n_slots,))
    eng.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(day_bars, 0, 1)),
        np.ascontiguousarray(day_mask.T))
    assert eng.minutes == spec.n_slots
    exposures, ready = (np.asarray(x) for x in eng.snapshot())
    bad = [n for j, n in enumerate(names)
           if not _bitwise(exposures[j], batch_stack[j])]
    assert not bad, f"{sname}: non-bitwise streamed kernels: {bad}"


def test_stream_save_restore_midday_crypto(rng):
    """Satellite: mid-day save/restore at the 1440-slot session is
    bit-identical to never stopping — the carry IS the complete
    streaming state at any slot count."""
    spec = get_session("crypto_1440")
    names = ("mmt_pm", "vol_return1min", "doc_pdf60", "mmt_ols_qrs")
    bars, mask = _session_batch(rng, spec, n_days=1, n_tickers=4)
    day_bars = np.ascontiguousarray(np.swapaxes(bars[0], 0, 1))
    day_mask = np.ascontiguousarray(mask[0].T)
    half = spec.n_slots // 2

    straight = StreamEngine(4, names=names, session=spec)
    straight.ingest_minutes(day_bars, day_mask)

    first = StreamEngine(4, names=names, session=spec)
    first.ingest_minutes(day_bars[:half], day_mask[:half])
    snap = first.save()

    resumed = StreamEngine(4, names=names, session=spec)
    resumed.restore(snap)
    assert resumed.minutes == half
    resumed.ingest_minutes(day_bars[half:], day_mask[half:])

    a, ra = (np.asarray(x) for x in straight.snapshot())
    b, rb = (np.asarray(x) for x in resumed.snapshot())
    assert _bitwise(a, b)
    assert (ra == rb).all()


def test_stream_restore_rejects_wrong_session(rng):
    """A 240-day snapshot must not restore into a 1440-slot engine."""
    cn = StreamEngine(4, names=("mmt_pm",))
    snap = cn.save()
    crypto = StreamEngine(4, names=("mmt_pm",), session="crypto_1440")
    with pytest.raises(ValueError, match="slot"):
        crypto.restore(snap)


def test_readiness_monotone_and_sound_crypto(rng):
    """Satellite: the (window counter, min) readiness contract at a
    non-240 session length — monotone over the fold, and SOUND: a
    not-ready lane's exposure is NaN at every probed prefix."""
    spec = get_session("crypto_1440")
    names = factor_names()
    bars, mask = _session_batch(rng, spec, n_days=1, n_tickers=4,
                                missing=0.3)
    day_bars = np.ascontiguousarray(np.swapaxes(bars[0], 0, 1))
    day_mask = np.ascontiguousarray(mask[0].T)

    eng = StreamEngine(4, names=names, session=spec)
    probes = (60, 360, 720, 1200, spec.n_slots)
    prev_ready = np.zeros((len(names), 4), bool)
    s = 0
    for stop in probes:
        eng.ingest_minutes(day_bars[s:stop], day_mask[s:stop])
        s = stop
        exposures, ready = (np.asarray(x) for x in eng.snapshot())
        # monotone: a ready lane never un-readies
        assert (ready | ~prev_ready).all()
        # sound: not-ready => NaN (ready lanes may still be NaN)
        assert np.isnan(exposures[~ready]).all(), stop
        prev_ready = ready
    assert prev_ready.any()


def test_day_boundary_rolling_us390(rng):
    """Satellite: rolling-moment day isolation across a us_390 session
    break — the 50-minute windows of the mmt_ols_* family never reach
    across the day boundary, so day 1's exposures in a 2-day batch are
    BITWISE the same day computed alone, at the non-240 shape."""
    spec = get_session("us_390")
    names = ("mmt_ols_qrs", "mmt_ols_corr_mean", "mmt_ols_beta_mean",
             "mmt_ols_beta_zscore_last", "mmt_ols_corr_square_mean")
    bars, mask = _session_batch(rng, spec, n_days=2, n_tickers=5)
    both = compute_factors_jit(jax.device_put(bars),
                               jax.device_put(mask), names=names,
                               session=spec)
    solo = compute_factors_jit(jax.device_put(bars[1:]),
                               jax.device_put(mask[1:]), names=names,
                               session=spec)
    for n in names:
        assert _bitwise(np.asarray(both[n])[1], np.asarray(solo[n])[0]), n


# --------------------------------------------------------------------------
# regress: session sub-series keying (satellite, both directions)
# --------------------------------------------------------------------------


def test_regress_session_series_isolation():
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        regress)

    base = {"metric": "m_x", "value": 100.0, "methodology": "r6_resident_v2"}
    # direction 1: a non-default session suffixes the methodology —
    # its records form their OWN group and never join the 240 series
    us = dict(base, session="us_390", value=400.0)
    assert regress.effective_methodology(us) == \
        "r6_resident_v2+session=us_390"
    entries = [
        {"n": i, "source": f"BENCH_r0{i}.json",
         "record": dict(base, value=100.0 + i)} for i in range(3)]
    verdict = regress.evaluate(entries, candidate=us)
    # a 4x value under a fresh session series is a DECLARED break:
    # reported with no baseline, never flagged
    assert verdict["ok"], verdict
    row = next(r for r in verdict["groups"]
               if r["methodology"].endswith("+session=us_390"))
    assert row["n_baseline"] == 0
    # direction 2: the same 4x value WITHOUT the session stamp (or
    # stamped canonical) gates against the banked 240 baseline and
    # flags
    for cand in (dict(base, value=400.0),
                 dict(base, value=400.0, session="cn_ashare_240")):
        v2 = regress.evaluate(entries, candidate=cand)
        assert not v2["ok"], cand
        assert any(f["methodology"] == "r6_resident_v2"
                   for f in v2["flagged"])


def test_regress_derived_series_inherit_session_suffix():
    from replication_of_minute_frequency_factor_tpu.telemetry import (
        regress)

    rec = {"metric": "m_y", "value": 10.0, "methodology": "r8_serve_v1",
           "session": "crypto_1440", "p99_ms": 5.0}
    derived = regress.derive_records(rec)
    assert derived, "p99 sub-series expected"
    for d in derived:
        assert d["methodology"] == "r8_serve_v1+session=crypto_1440"
        # and re-keying the derived record does not double-suffix
        assert regress.effective_methodology(d) == d["methodology"]


# --------------------------------------------------------------------------
# analysis: per-session Tier B fingerprints
# --------------------------------------------------------------------------


def test_session_tier_clean_and_fingerprinted():
    from replication_of_minute_frequency_factor_tpu.analysis.jaxpr_tier \
        import SESSION_TRACE_WRAPPERS, run_session_tier

    violations, fps = run_session_tier()
    assert not violations, [str(v) for v in violations]
    assert set(fps) == set(session_names())
    for sname, rows in fps.items():
        assert set(rows) == set(SESSION_TRACE_WRAPPERS), sname
        for fp in rows.values():
            assert fp["traced"] and fp["n_eqns"] > 0


def test_committed_report_carries_session_fingerprints():
    import json
    import os

    from replication_of_minute_frequency_factor_tpu.analysis.report \
        import repo_root

    path = os.path.join(repo_root(), "analysis_report.json")
    with open(path) as fh:
        rep = json.load(fh)
    sessions_blk = rep.get("jaxpr", {}).get("sessions")
    assert sessions_blk, "per-session fingerprints must be committed"
    assert {"cn_ashare_240", "us_390", "hk_halfday",
            "crypto_1440"} <= set(sessions_blk)


# --------------------------------------------------------------------------
# serve: discovery persistence reload (satellite, PR 14 residue)
# --------------------------------------------------------------------------


@pytest.mark.transfers
def test_research_reload_round_trip(tmp_path):
    """FactorServer(research=True) restart reloads persisted
    ``disc_<hash>`` records from research_dir: the discovered name is
    back in the live registry, in the server's factor set (split as
    discovered, not builtin), and immediately queryable."""
    from replication_of_minute_frequency_factor_tpu import search
    from replication_of_minute_frequency_factor_tpu.research import (
        registry as rreg)
    from replication_of_minute_frequency_factor_tpu.serve.service import (
        FactorServer, ServeConfig)
    from replication_of_minute_frequency_factor_tpu.serve.source import (
        SyntheticSource)

    rdir = str(tmp_path / "research")
    genome = search.random_population(np.random.default_rng(3), 1,
                                      search.DEFAULT_SKELETON)[0]
    rec = rreg.register_genome(genome, fitness=0.25, mean_ic=0.1,
                               save_dir=rdir)
    # simulate process death: in-memory registry gone, JSON survives
    rreg.DISCOVERED.pop(rec.name, None)

    cfg = ServeConfig(research_dir=rdir, hbm_sample_period_s=0)
    server = FactorServer(SyntheticSource(n_days=4, n_tickers=8),
                          names=("vol_return1min",), serve_cfg=cfg,
                          start=True, research=True)
    try:
        assert rec.name in rreg.discovered_names()
        listing = server.factor_list()
        assert rec.name in listing["discovered"]
        assert rec.name not in listing["builtin"]
        # the reloaded factor answers through the normal query leg
        from replication_of_minute_frequency_factor_tpu.serve.service \
            import ServeClient
        ans = ServeClient(server).factors(0, 2, names=(rec.name,))
        assert np.asarray(
            ans["exposures"][rec.name]).shape[-1] == 8
    finally:
        server.close()


def test_research_reload_skips_corrupt_records(tmp_path):
    """One corrupted record must be skipped loudly, not take the
    server down (and must not register)."""
    from replication_of_minute_frequency_factor_tpu.serve.service import (
        FactorServer, ServeConfig)
    from replication_of_minute_frequency_factor_tpu.serve.source import (
        SyntheticSource)

    rdir = tmp_path / "research"
    rdir.mkdir()
    (rdir / "disc_deadbeef00.json").write_text("{not json")
    cfg = ServeConfig(research_dir=str(rdir), hbm_sample_period_s=0)
    server = FactorServer(SyntheticSource(n_days=2, n_tickers=8),
                          names=("vol_return1min",), serve_cfg=cfg,
                          start=False, research=True)
    try:
        assert server.names == ("vol_return1min",)
        assert server.telemetry.registry.counter_total(
            "discover.reload_failures") >= 1
    finally:
        server.close()
