"""bench.py's attribution surfaces: the resident-path one-shot diag
(VERDICT r5 weak #5) and the reconciliation component filter — on tiny
shapes, since the full 58-graph is the TPU session's job."""

import numpy as np
import pytest

import bench
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.pipeline import (
    compute_packed_prepared)

NAMES = ("vol_return1min", "mmt_am", "liq_openvol")


def _batches(n=2, days=2, tickers=32):
    rng = np.random.default_rng(7)
    return [bench.make_batch(rng, n_days=days, n_tickers=tickers)
            for _ in range(n)]


def _stream_results(batches, names, use_wire):
    """The stream loop's device half, one batch per execute — what the
    timed fallback loop materializes and the diag compares against."""
    outs = []
    for b, m in batches:
        w = wire.encode(b, m) if use_wire else None
        if w is not None:
            buf, spec = wire.pack_arrays(w.arrays)
            kind = "wire"
        else:
            buf, spec = wire.pack_arrays((b, m.view(np.uint8)))
            kind = "raw"
        outs.append(np.asarray(compute_packed_prepared(
            buf, spec, kind, names=names, replicate_quirks=True)))
    return outs


def test_resident_diag_matches_stream():
    batches = _batches()
    use_wire = wire.encode(*batches[0]) is not None
    stream = _stream_results(batches, NAMES, use_wire)
    diag = bench.resident_diag(batches, NAMES, use_wire, stream)
    assert diag["equal"] is True, diag
    assert diag["max_abs_diff"] == pytest.approx(0.0, abs=1e-5)
    assert diag["batches"] == 2
    assert set(diag["phases"]) >= {"encode_s", "ingest_s", "compute_s",
                                   "fetch_s"}
    assert diag["total_s"] >= 0


def test_resident_diag_detects_divergence():
    batches = _batches()
    use_wire = wire.encode(*batches[0]) is not None
    stream = _stream_results(batches, NAMES, use_wire)
    stream[1] = stream[1] + np.float32(0.5)  # corrupt one batch
    diag = bench.resident_diag(batches, NAMES, use_wire, stream)
    assert diag["equal"] is False
    assert diag["max_abs_diff"] >= 0.4


def test_resident_diag_without_stream_results_is_inconclusive():
    batches = _batches(n=1)
    use_wire = wire.encode(*batches[0]) is not None
    diag = bench.resident_diag(batches, NAMES, use_wire, None)
    assert diag["equal"] is None and "note" in diag


def test_run_resident_keep_results_shapes():
    batches = _batches(n=2, days=3, tickers=32)
    use_wire = wire.encode(*batches[0]) is not None
    phases, kind, results = bench.run_resident(
        batches, NAMES, use_wire, group=2, keep_results=True)
    assert kind in ("wire", "raw")
    assert len(results) == 2
    assert results[0].shape[0] == len(NAMES)  # [F, D, T]
    assert results[0].shape[1] == 3
    # timed loops don't keep results
    _, _, none_results = bench.run_resident(
        batches, NAMES, use_wire, group=2)
    assert none_results is None
