"""Factor-health plane (ISSUE 12) — docs/observability.md "factor.*".

Coverage map:

* the fused on-device ``[F, 9]`` stats sketch matches the host numpy
  recompute for ALL 58 factors (counts/min/max exact, moments within
  f32 reduction tolerance) and NEVER perturbs the exposures (bitwise);
* sharded and single-device modules produce the same stats payload
  (exactly-associative columns bitwise, moment sums ulp-pinned — the
  result-wire encode's associativity contract, applied to stats);
* drift detection fires in BOTH directions: an injected coverage
  collapse produces a schema-valid flight dump naming the factor,
  while a stable seeded run produces zero dumps;
* baseline updates require a justification (graftlint's contract);
* the result wire's per-factor widen counters and spill occupancy;
* the serve/stream integration: healthz carries the block, intraday
  snapshots feed readiness lag, IC answers feed realized-IC health;
* drift dumps carry the schema-v3 identity stamps and fold through
  ``telemetry.aggregate`` with identity intact.
"""

import json
import os

import numpy as np
import pytest

import jax

from replication_of_minute_frequency_factor_tpu import pipeline
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.models.registry import (
    factor_names)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    Telemetry)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    factorplane as fp)
from replication_of_minute_frequency_factor_tpu.telemetry.validate import (
    validate_dump)

NAMES = ("vol_return1min", "mmt_am", "liq_openvol")


def _batch(rng, days=2, tickers=16):
    shape = (days, tickers, 240)
    close = 10.0 * np.exp(np.cumsum(
        rng.standard_normal(shape).astype(np.float32) * 1e-3, axis=-1))
    open_ = close * (1 + rng.standard_normal(shape).astype(np.float32)
                     * 1e-4)
    bars = np.stack([open_, np.maximum(open_, close) * 1.0002,
                     np.minimum(open_, close) * 0.9998, close,
                     (rng.integers(0, 1000, shape) * 100.0)
                     .astype(np.float32)], axis=-1).astype(np.float32)
    mask = rng.random(shape) > 0.05
    return bars, mask


def _stats(names, coverage=1.0, mean=0.0, std=1.0, lanes=1000.0):
    """A synthetic [F, 9] sketch row set for plane-level tests."""
    out = np.zeros((len(names), fp.N_STATS), np.float32)
    out[:, 0] = lanes
    out[:, 1] = coverage * lanes
    out[:, 2] = lanes - coverage * lanes
    out[:, 5] = mean
    out[:, 6] = std
    out[:, 7] = mean - 2 * std
    out[:, 8] = mean + 2 * std
    return out


# --------------------------------------------------------------------------
# fused stats: parity + bitwise non-perturbation
# --------------------------------------------------------------------------


@pytest.mark.slow
def test_fused_stats_match_host_recompute_all_58(rng):
    """The acceptance parity gate over the full factor set (the quick
    tier runs the same check as bench.factorplane_smoke)."""
    names = factor_names()
    bars, mask = _batch(rng, days=2, tickers=16)
    arrays = (bars, mask.view(np.uint8))
    out, st = pipeline.compute_packed(arrays, "raw", names,
                                      factor_stats=True)
    exp = np.asarray(out)
    dev = np.asarray(st)
    host = fp.factor_stats_host(exp)
    assert np.array_equal(dev[:, :5], host[:, :5])
    assert np.array_equal(dev[:, 7:], host[:, 7:], equal_nan=True)
    assert np.allclose(dev[:, 5:7], host[:, 5:7], rtol=1e-4,
                       atol=1e-6, equal_nan=True)


def test_fused_stats_do_not_perturb_exposures(rng):
    bars, mask = _batch(rng)
    arrays = (bars, mask.view(np.uint8))
    with_stats, st = pipeline.compute_packed(arrays, "raw", NAMES,
                                             factor_stats=True)
    plain = pipeline.compute_packed(arrays, "raw", NAMES)
    assert np.array_equal(np.asarray(with_stats), np.asarray(plain),
                          equal_nan=True)
    dev = np.asarray(st)
    host = fp.factor_stats_host(np.asarray(plain))
    assert np.array_equal(dev[:, :5], host[:, :5])
    assert np.array_equal(dev[:, 7:], host[:, 7:], equal_nan=True)


def test_stats_layout_counts_nan_and_inf(rng):
    x = rng.standard_normal((2, 4, 7)).astype(np.float32)
    x[0, 0, :3] = np.nan
    x[0, 1, 0] = np.inf
    x[1, 2, 1] = -np.inf
    dev = np.asarray(fp.factor_stats_block(jax.device_put(x)))
    host = fp.factor_stats_host(x)
    assert np.array_equal(dev[:, :5], host[:, :5])
    i = fp.STAT_FIELDS.index
    assert dev[0, i("nan")] == 3 and dev[0, i("posinf")] == 1
    assert dev[1, i("neginf")] == 1
    assert dev[0, i("lanes")] == 28 and dev[0, i("finite")] == 24


def test_all_nan_factor_reports_nan_moments():
    x = np.full((1, 3, 5), np.nan, np.float32)
    dev = np.asarray(fp.factor_stats_block(jax.device_put(x)))
    assert dev[0, 1] == 0  # finite
    assert np.isnan(dev[0, 5:]).all()


def test_resident_scan_carries_stats_side_output(rng):
    """The resident scan's (result, stats) tuple: stats per batch match
    the per-batch recompute; the main accumulator is bitwise the
    stats-off run's."""
    batches = [_batch(rng) for _ in range(2)]
    packs = [wire.pack_arrays((b, m.view(np.uint8)))
             for b, m in batches]
    spec = packs[0][1]
    dbufs = tuple(jax.device_put(p[0]) for p in packs)
    ys, stats = pipeline.compute_packed_resident(
        dbufs, spec, "raw", NAMES, factor_stats=True)
    dbufs2 = tuple(jax.device_put(p[0]) for p in packs)
    plain = pipeline.compute_packed_resident(dbufs2, spec, "raw", NAMES)
    ys, stats, plain = (np.asarray(ys), np.asarray(stats),
                        np.asarray(plain))
    assert np.array_equal(ys, plain, equal_nan=True)
    assert stats.shape == (2, len(NAMES), fp.N_STATS)
    for i in range(2):
        host = fp.factor_stats_host(ys[i])
        assert np.array_equal(stats[i, :, :5], host[:, :5])
        assert np.array_equal(stats[i, :, 7:], host[:, 7:],
                              equal_nan=True)


def test_sharded_stats_match_single_device(rng):
    """The associativity contract: counts/min/max bitwise between the
    sharded and single-device modules (GSPMD owns the cross-shard
    reductions; min/max/counts are exactly associative), moment sums
    within a tight ulp band."""
    from replication_of_minute_frequency_factor_tpu.parallel import (
        resident_mesh)
    from replication_of_minute_frequency_factor_tpu.parallel.mesh import (
        put_packed_year)
    mesh = resident_mesh()
    n_shards = mesh.devices.size
    assert n_shards > 1  # the 8-device virtual harness
    tickers = 4 * n_shards
    batches = [_batch(rng, tickers=tickers) for _ in range(2)]
    # single-device stats
    packs = [wire.pack_arrays((b, m.view(np.uint8)))
             for b, m in batches]
    dbufs = tuple(jax.device_put(p[0]) for p in packs)
    _, single = pipeline.compute_packed_resident(
        dbufs, packs[0][1], "raw", NAMES, factor_stats=True)
    single = np.asarray(single)
    # sharded stats over the same bytes
    spacks = [wire.pack_sharded((b, m.view(np.uint8)), n_shards)
              for b, m in batches]
    stacked = put_packed_year(
        np.stack([p[0] for p in spacks]), mesh)
    _, sharded = pipeline.compute_packed_resident_sharded(
        stacked, spacks[0][1], "raw", mesh, NAMES,
        factor_stats=tickers)
    sharded = np.asarray(sharded)
    assert np.array_equal(single[:, :, :5], sharded[:, :, :5])
    assert np.array_equal(single[:, :, 7:], sharded[:, :, 7:],
                          equal_nan=True)
    fin_s, fin_h = single[:, :, 5:7], sharded[:, :, 5:7]
    scale = np.maximum(np.abs(fin_s), 1e-6)
    assert (np.abs(fin_s - fin_h)
            <= 32 * np.finfo(np.float32).eps * scale).all()


# --------------------------------------------------------------------------
# the host plane: gauges, drift, baselines
# --------------------------------------------------------------------------


def test_observe_block_publishes_gauges():
    tel = Telemetry()
    plane = tel.factorplane
    s = plane.observe_block(NAMES, _stats(NAMES, coverage=0.9),
                            boundary="test")
    assert s["factors"] == len(NAMES)
    g = tel.registry.snapshot()["gauges"]
    for n in NAMES:
        assert abs(g[f"factor.coverage_frac{{factor={n}}}"] - 0.9) < 1e-6
    summary = plane.summary()
    assert summary["available"] is True
    assert abs(summary["coverage_frac"] - 0.9) < 1e-6
    assert summary["worst_coverage"]["factor"] in NAMES


def test_garbage_stats_never_raise():
    tel = Telemetry()
    plane = tel.factorplane
    assert plane.observe_block(NAMES, np.zeros((2, 2))) == {}
    assert plane.observe_block(NAMES, "nope") == {}
    assert tel.registry.counter_total("factor.sample_failures") == 2
    assert plane.summary()["available"] is False


def test_coverage_collapse_trips_named_validated_dump(tmp_path):
    tel = Telemetry()
    plane = fp.FactorPlane(telemetry=tel, dump_dir=str(tmp_path),
                           burst=3)
    base = _stats(NAMES, coverage=0.95)
    plane.observe_block(NAMES, base)          # banks baselines
    collapsed = base.copy()
    victim = NAMES[1]
    collapsed[1, 1] = 0.1 * collapsed[1, 0]   # coverage 0.95 -> 0.1
    collapsed[1, 2] = collapsed[1, 0] - collapsed[1, 1]
    dumps = []
    for _ in range(3):
        s = plane.observe_block(NAMES, collapsed)
        assert victim in s["drifting"]
        dumps.extend(s["burst_dumps"])
    assert len(dumps) == 1  # burst logic: N consecutive, then reset
    rep = validate_dump(dumps[0])
    assert rep["ok"], rep
    doc = [json.loads(line) for line in open(dumps[0])]
    header = [r for r in doc if r.get("kind") == "dump"][0]
    extra = header["data"]["extra"]
    assert extra["factor"] == victim
    assert any("coverage_frac" in r for r in extra["reasons"])
    # schema-v3 identity stamps ride the dump (aggregate folds it)
    assert "process_index" in header and "host" in header
    assert tel.registry.counter_value("factor.drift_bursts",
                                      factor=victim) == 1


def test_moment_drift_and_std_collapse_trip(tmp_path):
    plane = fp.FactorPlane(telemetry=Telemetry(),
                           dump_dir=str(tmp_path), burst=2)
    plane.observe_block(NAMES, _stats(NAMES, mean=1.0, std=0.1))
    shifted = _stats(NAMES, mean=50.0, std=0.1)      # z = 490
    s1 = plane.observe_block(NAMES, shifted)
    s2 = plane.observe_block(NAMES, shifted)
    assert s1["drifting"] and s2["bursts"] == len(NAMES)
    # std collapse alone (mean unchanged) also counts
    plane2 = fp.FactorPlane(telemetry=Telemetry(), burst=2)
    plane2.observe_block(NAMES, _stats(NAMES, mean=1.0, std=1.0))
    s = plane2.observe_block(NAMES, _stats(NAMES, mean=1.0,
                                           std=1e-4))
    assert s["drifting"] == list(NAMES)


def test_stable_run_produces_zero_dumps(tmp_path, rng):
    plane = fp.FactorPlane(telemetry=Telemetry(),
                           dump_dir=str(tmp_path), burst=2)
    base = _stats(NAMES, coverage=0.95, mean=1.0, std=0.5)
    for _ in range(10):
        jitter = base.copy()
        jitter[:, 5] += rng.standard_normal(len(NAMES)) * 0.01
        s = plane.observe_block(NAMES, jitter)
        assert s["bursts"] == 0 and not s["drifting"]
    assert plane.summary()["drift"]["bursts"] == 0
    assert not os.listdir(tmp_path)


def test_baseline_update_requires_justification():
    plane = fp.FactorPlane(telemetry=Telemetry())
    plane.observe_block(NAMES, _stats(NAMES, mean=1.0))
    plane.observe_block(NAMES, _stats(NAMES, mean=2.0))
    with pytest.raises(ValueError, match="justification"):
        plane.update_baseline()
    with pytest.raises(ValueError, match="justification"):
        plane.update_baseline(justification="   ")
    moved = plane.update_baseline(
        justification="universe re-seeded for the test")
    assert moved == len(NAMES)
    assert plane.bank_baseline()[NAMES[0]]["mean"] == 2.0


def test_consecutive_drift_resets_on_recovery(tmp_path):
    """A transient blip shorter than the burst never dumps — the
    mesh-plane skew-burst semantics, per factor."""
    plane = fp.FactorPlane(telemetry=Telemetry(),
                           dump_dir=str(tmp_path), burst=3)
    good = _stats(NAMES, coverage=0.95)
    bad = _stats(NAMES, coverage=0.1)
    plane.observe_block(NAMES, good)
    for _ in range(2):
        plane.observe_block(NAMES, bad)
    plane.observe_block(NAMES, good)   # recovery resets the counters
    s = plane.observe_block(NAMES, bad)
    assert s["bursts"] == 0 and not os.listdir(tmp_path)


# --------------------------------------------------------------------------
# widen / stream / IC health
# --------------------------------------------------------------------------


def test_widen_rates_accumulate_per_factor():
    tel = Telemetry()
    plane = tel.factorplane
    plane.observe_widen(NAMES, {"mmt_am": 2}, slices_per_factor=8)
    plane.observe_widen(NAMES, [0, 2, 1], slices_per_factor=8)
    g = tel.registry.snapshot()["gauges"]
    assert abs(g["factor.widen_rate{factor=mmt_am}"] - 0.25) < 1e-6
    assert abs(g["factor.widen_rate{factor=liq_openvol}"]
               - 1 / 16) < 1e-6
    summary = plane.summary()
    assert summary["widen"]["slices"] == len(NAMES) * 16
    assert summary["widen"]["worst"]["factor"] == "mmt_am"
    assert abs(summary["widen_rate"] - 5 / 48) < 1e-6


def test_decode_block_per_factor_widen_counters(rng):
    from replication_of_minute_frequency_factor_tpu.data import (
        result_wire as rw)
    import jax.numpy as jnp
    tel = Telemetry()
    days, tickers = 2, 24
    # vol_upVol is one of the STRICT-pinned factors (rtol-only bound):
    # a heavy-tailed slice — tiny lanes sharing a slice with huge
    # ones — fails the on-device round-trip check and widens (exactly
    # the ROADMAP question these counters instrument)
    names = ("vol_return1min", "vol_upVol", "liq_openvol")
    raw = rng.standard_normal((len(names), days, tickers)) \
        .astype(np.float32)
    raw[1] = 10.0 ** rng.uniform(-5, 6, (days, tickers)) \
        .astype(np.float32)
    spec = rw.ResultWireSpec.for_names(names, days=days)
    payload = np.asarray(jax.jit(rw.encode_block, static_argnums=1)(
        jnp.asarray(raw), spec))
    dec, v = rw.decode_block(payload, len(names), days, tickers,
                             spec.spill_rows, telemetry=tel,
                             names=names)
    assert v["widened_by_factor"].get("vol_upVol") == days
    assert "vol_return1min" not in v["widened_by_factor"]
    assert tel.registry.counter_value("result.widen_count",
                                      factor="vol_upVol") == days
    occ = tel.registry.gauge_value("result.spill_occupancy_frac")
    assert occ is not None and occ > 0
    with pytest.raises(ValueError, match="names"):
        rw.decode_block(payload, len(names), days, tickers,
                        spec.spill_rows, telemetry=tel,
                        names=names[:-1])


def test_observe_stream_readiness_lag():
    tel = Telemetry()
    plane = tel.factorplane
    s = plane.observe_stream(NAMES, _stats(NAMES),
                             ready_frac=[1.0, 0.5, 0.25], minute=120)
    assert s["stream"]["least_ready"]["factor"] == "liq_openvol"
    g = tel.registry.snapshot()["gauges"]
    assert abs(g["stream.readiness_lag"]
               - (1 - (1.0 + 0.5 + 0.25) / 3)) < 1e-6
    assert plane.summary()["stream"]["minute"] == 120


def test_note_ic_rolls_per_factor_horizon():
    tel = Telemetry()
    plane = tel.factorplane
    for v in (0.1, 0.3):
        plane.note_ic("mmt_am", v, horizon=1)
    plane.note_ic("mmt_am", None, horizon=1)      # ignored
    plane.note_ic("mmt_am", float("nan"), horizon=1)
    g = tel.registry.snapshot()["gauges"]
    assert abs(g["factor.realized_ic_rolling{factor=mmt_am,horizon=1}"]
               - 0.2) < 1e-6
    ic = plane.summary()["ic"]
    assert ic["mmt_am@1"]["n"] == 2


# --------------------------------------------------------------------------
# integration: stream engine, serve, fleet health
# --------------------------------------------------------------------------


def test_stream_snapshot_stats_bitwise_and_warm(rng):
    from replication_of_minute_frequency_factor_tpu.stream.engine import (
        StreamEngine)
    tel = Telemetry()
    eng = StreamEngine(8, names=NAMES, telemetry=tel)
    eng.warmup(micro_batches=(4,))
    bars, mask = _batch(rng, days=1, tickers=8)
    eng.ingest_minutes(
        np.ascontiguousarray(np.swapaxes(bars[0][:, :8], 0, 1)),
        np.ascontiguousarray(mask[0][:, :8].T))
    before = tel.registry.counter_total("xla.compiles")
    exp, ready, st = jax.device_get(eng.snapshot_stats())
    assert tel.registry.counter_total("xla.compiles") == before
    exp0, ready0 = jax.device_get(eng.snapshot())
    assert np.array_equal(exp, exp0, equal_nan=True)
    assert np.array_equal(ready, ready0)
    host = fp.factor_stats_host(exp)
    assert np.array_equal(st[:, :5], host[:, :5])
    # wire twin: stats identical (computed pre-encode)
    _pay, ready_w, st_w = jax.device_get(eng.snapshot_wire_stats())
    assert np.array_equal(ready_w, ready0)
    assert np.array_equal(st_w[:, :5], st[:, :5])


def test_serve_health_and_intraday_feed_the_plane(rng):
    from replication_of_minute_frequency_factor_tpu.serve import (
        FactorServer, Query, ServeConfig, SyntheticSource)
    tel = Telemetry()
    src = SyntheticSource(n_days=6, n_tickers=8, seed=3)
    srv = FactorServer(src, names=NAMES, telemetry=tel,
                       serve_cfg=ServeConfig(), stream=True,
                       stream_batches=(4,))
    try:
        c = srv.client()
        c.factors(0, 2)
        c.ic("mmt_am", 0, 4, horizon=1)
        c.intraday()
        h = srv.health()
        fh = h["factor_health"]
        assert fh["available"] is True
        assert fh["worst_coverage"]["factor"] in NAMES
        # one sample per block BUILD — the factors query built (0, 2)
        # and the IC query built (0, 4) — plus the intraday snapshot
        assert tel.registry.counter_value(
            "factor.samples", boundary="serve.block") == 2
        assert tel.registry.counter_value(
            "factor.samples", boundary="serve.intraday") == 1
        # IC answer fed realized-IC health through the AOT IC graph
        assert fh["ic"] and "mmt_am@1" in fh["ic"]
        # readiness lag published for the (empty) carry
        assert tel.registry.gauge_value("stream.readiness_lag") \
            is not None
        # a cache hit re-serves observed data without a new sample
        c.factors(0, 2)
        assert tel.registry.counter_value(
            "factor.samples", boundary="serve.block") == 2
    finally:
        srv.close()


def test_fleet_pod_health_rolls_up_factor_health():
    from replication_of_minute_frequency_factor_tpu.fleet import (
        FactorFleet)
    from replication_of_minute_frequency_factor_tpu.serve import (
        Query, SyntheticSource)
    src = SyntheticSource(n_days=6, n_tickers=8, seed=3)
    fleet = FactorFleet(src, 2, names=NAMES)
    try:
        fleet.submit(Query("factors", 0, 2)).result(60)
        h = fleet.health()
        pod_fh = h["pod"]["factor_health"]
        assert set(pod_fh["replicas"]) == {"r0", "r1"}
        served = [r for r in pod_fh["replicas"].values()
                  if r["available"]]
        assert served and served[0]["worst_coverage"]["factor"] in NAMES
        # the rollup reads the replica healthz payloads verbatim
        for label, rep in h["replicas"].items():
            assert rep["factor_health"]["available"] == \
                pod_fh["replicas"][label]["available"]
    finally:
        fleet.close()


def test_prometheus_exports_p99_quantile():
    from replication_of_minute_frequency_factor_tpu.telemetry.opsplane \
        import to_prometheus
    tel = Telemetry()
    for i in range(200):
        tel.observe("serve.request_seconds", i / 1000.0)
    text = to_prometheus(tel.registry)
    assert 'quantile="0.99"' in text
    stats = tel.registry.histogram_stats("serve.request_seconds")
    assert stats["p99"] is not None and stats["p99"] >= stats["p95"]


def test_aggregate_folds_factor_gauges_with_identity(tmp_path):
    """Two per-host bundles carrying factor.* gauges fold into one
    schema-valid pod bundle (the fleet/multihost contract)."""
    from replication_of_minute_frequency_factor_tpu.telemetry.aggregate \
        import aggregate_dirs
    from replication_of_minute_frequency_factor_tpu.telemetry.validate \
        import validate_dir
    dirs = []
    for i in range(2):
        tel = Telemetry()
        tel.factorplane.observe_block(NAMES,
                                      _stats(NAMES, coverage=0.9))
        tel.counter("factor.samples", boundary="test")
        d = str(tmp_path / f"host{i}")
        tel.write(d, process_index=i, host=f"host{i}")
        dirs.append(d)
    pod = str(tmp_path / "pod")
    agg = aggregate_dirs(dirs, pod)
    assert agg["ok"], agg
    val = validate_dir(pod)
    assert val["ok"], val
    merged = [json.loads(line)
              for line in open(os.path.join(pod, "metrics.jsonl"))]
    counters = [r for r in merged if r.get("kind") == "counter"
                and r.get("name") == "factor.samples"]
    assert counters and counters[0]["value"] == 2.0
