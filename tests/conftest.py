"""Test harness: force an 8-device virtual CPU mesh.

This is the standard hardware-free multi-device trick
(``xla_force_host_platform_device_count``) — SURVEY.md §4 item 4.

Note: in this container a ``sitecustomize`` hook may have imported jax and
registered the experimental TPU platform before pytest starts, so setting
env vars alone is not enough — we also flip ``jax_platforms`` via
``jax.config`` (effective as long as no backend has been used yet). If the
TPU tunnel is wedged the *interpreter itself* can hang at startup; use
``./run_tests.sh`` (which unsets ``PALLAS_AXON_POOL_IPS``) for a
hermetic CPU-only run.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)
