"""Test harness: force an 8-device virtual CPU mesh.

This is the standard hardware-free multi-device trick
(``xla_force_host_platform_device_count``) — SURVEY.md §4 item 4.

Note: in this container a ``sitecustomize`` hook may have imported jax and
registered the experimental TPU platform before pytest starts, so setting
env vars alone is not enough — we also flip ``jax_platforms`` via
``jax.config`` (effective as long as no backend has been used yet). If the
TPU tunnel is wedged the *interpreter itself* can hang at startup; use
``./run_tests.sh`` (which unsets ``PALLAS_AXON_POOL_IPS``) for a
hermetic CPU-only run.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(0)


#: modules whose tests run under ``jax.transfer_guard("disallow")`` —
#: the runtime twin of graftlint rule GL-A3 (docs/static-analysis.md):
#: inside these, any IMPLICIT host<->device transfer (``float(arr)``,
#: ``.item()``, a numpy operand silently shipped to device, a hidden
#: sync inside a kernel) raises, while explicit ``jax.device_put``/
#: ``device_get``/``jnp.asarray`` and jit-compiled constants stay
#: legal. A test that legitimately transfers opts out with
#: ``@pytest.mark.transfers``.
#: test_serve joins (ISSUE 6): the serving layer's device-facing half
#: (engine/expcache) must move data only by explicit put/get — the
#: in-process client returns HOST results, so the calling thread never
#: transfers implicitly; serve tests that do transfer on the test
#: thread opt out per test.
#: test_stream joins (ISSUE 7): the streaming engine's carry lives
#: device-resident and moves only by explicit put (reset/restore/
#: ingest) and explicit get (save/the tests' device_get) — the whole
#: carry contract is exercised under the guard.
#: test_fleet joins (ISSUE 11): the pod router forwards host data only
#: — replicas' device work stays on their worker threads, the one
#: declared fan-out normalization is host-on-host, and the replica
#: liveness probe moves data only by explicit put.
#: test_research joins (ISSUE 14): the discovery loop moves data only
#: by explicit put (genomes, the day slab) and its one per-generation
#: fetch is the explicit ``np.asarray`` boundary sync — the whole
#: 1-sync/generation budget is exercised under the guard.
#: test_edge joins (ISSUE 20): the evented front door hands HOST bytes
#: only — the device fetch happens on the server's worker threads at
#: the declared serve/service.py boundary, never on the loop, aux or
#: client thread.
TRANSFER_GUARDED_MODULES = {"test_kernel_purity", "test_serve",
                            "test_stream", "test_opsplane",
                            "test_fleet", "test_research",
                            "test_edge"}


@pytest.fixture(autouse=True)
def _disallow_implicit_transfers(request):
    mod = getattr(request.module, "__name__", "").rsplit(".", 1)[-1]
    if (mod in TRANSFER_GUARDED_MODULES
            and request.node.get_closest_marker("transfers") is None):
        with jax.transfer_guard("disallow"):
            yield
    else:
        yield
