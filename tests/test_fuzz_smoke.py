"""The differential fuzz harnesses stay runnable and clean on a seed window.

The long-run sweeps live in tools/fuzz/ and are driven out-of-band
(README there records the cleared seed-run tallies); this smoke keeps the
harness entry points from rotting.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REFERENCE = "/root/reference"

# the harness subprocess re-compiles the differential kernels from
# scratch on one CPU core — minutes, not seconds, so the fuzz
# regression smoke lives in the slow tier (full suite / nightly), not
# in tier-1 or run_tests.sh --quick. It also differentials against the
# reference's actual cal_* sources (tools/refdiff/harness.py reads and
# hash-verifies them from /root/reference), so on hosts without that
# checkout the smoke must skip loudly rather than fail on a
# FileNotFoundError that looks like a harness bug.
pytestmark = [
    pytest.mark.slow,
    pytest.mark.skipif(
        not os.path.isdir(REFERENCE),
        reason=(
            f"reference checkout absent: {REFERENCE} does not exist on "
            f"this host, and tools/refdiff/harness.py loads the "
            f"reference's actual cal_* source files from there for the "
            f"differential — nothing to diff against, skipping the "
            f"fuzz smoke (not a failure)")),
]


def run_harness(name, lo, hi, timeout=400):
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, "tools", "fuzz", name),
             str(lo), str(hi)],
            capture_output=True, text=True, timeout=timeout, env=env)
    except subprocess.TimeoutExpired:
        # distinguish a slow host (two jit compiles + interpret-mode
        # Pallas on 1 CPU core can brush the budget) from a harness bug
        raise AssertionError(
            f"{name} [{lo},{hi}) exceeded the {timeout}s smoke budget — "
            f"harness slowness, not a differential failure; raise the "
            f"budget if this host is simply slow") from None
    assert out.returncode == 0, out.stderr[-2000:]
    last = [l for l in out.stdout.splitlines() if l.startswith("DONE")]
    assert last and ", 0 failures" in last[0], out.stdout[-2000:]



def test_fuzz_refdiff_seed_window():
    run_harness("fuzz_refdiff.py", 200, 203)
