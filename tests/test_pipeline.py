"""L2 pipeline: IO contracts, incremental resume, failure isolation,
atomic cache (SURVEY.md §4 item 3)."""

import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from replication_of_minute_frequency_factor_tpu.data import io as dio
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day
from replication_of_minute_frequency_factor_tpu.config import Config
from replication_of_minute_frequency_factor_tpu.pipeline import (
    ExposureTable, compute_exposures)

NAMES = ("vol_return1min", "mmt_am", "liq_openvol")


def _write_day(dirpath, rng, date_str, n_codes=6, **kw):
    cols = synth_day(rng, n_codes=n_codes, date=date_str, **kw)
    arrays = {
        "code": pa.array([str(c) for c in cols["code"]]),
        "time": pa.array(cols["time"]),
    }
    for k in ("open", "high", "low", "close", "volume"):
        arrays[k] = pa.array(cols[k])
    table = pa.table(arrays)
    name = date_str.replace("-", "") + "_cleaned.parquet"
    pq.write_table(table, os.path.join(dirpath, name))


@pytest.fixture
def minute_dir(tmp_path, rng):
    d = tmp_path / "kline"
    d.mkdir()
    for ds in ("2024-01-02", "2024-01-03", "2024-01-04"):
        _write_day(str(d), rng, ds, missing_prob=0.05)
    return str(d)


def _cfg(**kw):
    kw.setdefault("days_per_batch", 2)
    return Config(**kw)


def test_day_file_listing_and_date_parse(minute_dir):
    files = dio.list_day_files(minute_dir)
    assert [str(d) for d, _ in files] == [
        "2024-01-02", "2024-01-03", "2024-01-04"]
    assert dio.parse_day_filename("foo.parquet") is None
    assert dio.parse_day_filename("20240102.parquet") == np.datetime64(
        "2024-01-02")


def test_compute_exposures_end_to_end(minute_dir, tmp_path):
    cache = str(tmp_path / "factors.parquet")
    t = compute_exposures(minute_dir, NAMES, cache_path=cache, cfg=_cfg(),
                          progress=False)
    assert t.factor_names == NAMES
    assert len(np.unique(t.columns["date"])) == 3
    # sorted by (date, code)
    order = np.lexsort((t.columns["code"], t.columns["date"]))
    assert (order == np.arange(len(t))).all()
    # cache written and loadable
    t2 = ExposureTable.load(cache)
    assert len(t2) == len(t)
    np.testing.assert_allclose(
        t2.columns["vol_return1min"], t.columns["vol_return1min"])


def test_compute_exposures_sharded_matches_single(minute_dir):
    """cfg.mesh_shape shards the pipeline's tickers axis over the 8-device
    virtual mesh; results must equal the single-device run exactly."""
    import jax
    if len(jax.devices()) < 2:
        pytest.skip("needs the virtual multi-device mesh")
    single = compute_exposures(minute_dir, NAMES, cfg=_cfg(),
                               progress=False)
    sharded = compute_exposures(
        minute_dir, NAMES, cfg=Config(days_per_batch=2,
                                      mesh_shape=(1, len(jax.devices()))),
        progress=False)
    np.testing.assert_array_equal(single.columns["code"],
                                  sharded.columns["code"])
    for n in NAMES:
        np.testing.assert_allclose(single.columns[n], sharded.columns[n],
                                   rtol=1e-6, equal_nan=True)


def test_incremental_resume_only_computes_new_days(minute_dir, tmp_path, rng):
    cache = str(tmp_path / "factors.parquet")
    compute_exposures(minute_dir, NAMES, cache_path=cache, cfg=_cfg(),
                      progress=False)
    base = ExposureTable.load(cache)
    # add a new day; only it should be computed, and old rows must survive
    _write_day(minute_dir, rng, "2024-01-05")
    seen = []
    t = compute_exposures(minute_dir, NAMES, cache_path=cache, cfg=_cfg(),
                          progress=False, fault_hook=lambda d: seen.append(d))
    assert seen == [np.datetime64("2024-01-05")]
    assert t.max_date == np.datetime64("2024-01-05")
    old = t.columns["date"] < np.datetime64("2024-01-05")
    assert old.sum() == len(base)


def test_failed_day_is_skipped_and_reported(minute_dir, tmp_path):
    bad = np.datetime64("2024-01-03")

    def hook(date):
        if date == bad:
            raise RuntimeError("injected fault")

    cache = str(tmp_path / "f.parquet")
    t = compute_exposures(minute_dir, NAMES, cfg=_cfg(), progress=False,
                          fault_hook=hook, cache_path=cache)
    assert len(t.failures) == 1
    assert t.failures.keys() == [str(bad)]
    assert "injected fault" in t.failures.summary()
    assert bad not in t.columns["date"]
    assert len(np.unique(t.columns["date"])) == 2
    # the ledger persists next to the cache for post-run inspection
    import json
    with open(cache + ".failures.json") as fh:
        rec = json.load(fh)
    assert rec[0]["key"] == str(bad) and "injected fault" in rec[0]["error"]
    # a clean rerun does NOT reattempt the lost mid-history day (resume
    # filters past the cache max), so the ledger carries forward — it is
    # --retry-failed's only pointer to the day
    compute_exposures(minute_dir, NAMES, cfg=_cfg(), progress=False,
                      cache_path=cache)
    with open(cache + ".failures.json") as fh:
        assert [r["key"] for r in json.load(fh)] == [str(bad)]
    # retry_failed recovers it; only then is the ledger cleared
    t2 = compute_exposures(minute_dir, NAMES, cfg=_cfg(), progress=False,
                           cache_path=cache, retry_failed=True)
    assert str(bad) in set(map(str, t2.columns["date"]))
    assert not os.path.exists(cache + ".failures.json")


def test_corrupted_day_file_is_skipped_and_reported(minute_dir, tmp_path):
    """I/O failures isolate per day exactly like kernel failures (C8:
    reference catches *any* per-file exception, MinuteFrequentFactorCICC.py
    :20-25) — a truncated/garbage parquet must not take down the run."""
    bad = [f for f in os.listdir(minute_dir) if f.startswith("20240103")][0]
    with open(os.path.join(minute_dir, bad), "wb") as fh:
        fh.write(b"not a parquet file")
    t = compute_exposures(minute_dir, NAMES, cfg=_cfg(), progress=False,
                          cache_path=str(tmp_path / "f.parquet"))
    assert t.failures.keys() == ["2024-01-03"]
    assert np.datetime64("2024-01-03") not in t.columns["date"]
    assert len(np.unique(t.columns["date"])) == 2


def test_wire_unrepresentable_day_falls_back_to_raw(tmp_path, rng):
    """Off-tick prices make wire.encode return None; the pipeline must
    ship raw f32 and produce the same numbers it would with wire off."""
    d = tmp_path / "kline_offtick"
    d.mkdir()
    cols = synth_day(rng, n_codes=6, date="2024-01-02")
    for k in ("open", "high", "low", "close"):
        cols[k] = cols[k] + 0.0005  # off the 0.01 CNY tick grid
    arrays = {"code": pa.array([str(c) for c in cols["code"]]),
              "time": pa.array(cols["time"])}
    for k in ("open", "high", "low", "close", "volume"):
        arrays[k] = pa.array(cols[k])
    pq.write_table(pa.table(arrays), str(d / "20240102.parquet"))

    on = compute_exposures(str(d), NAMES, cfg=_cfg(), progress=False)
    off = compute_exposures(
        str(d), NAMES, cfg=Config(days_per_batch=2, wire_transfer=False),
        progress=False)
    assert len(on) == 6 and not on.failures
    assert "wire_encode" in on.timings  # encode attempted, fell back
    for n in NAMES:
        np.testing.assert_allclose(on.columns[n], off.columns[n],
                                   rtol=1e-6, equal_nan=True)


def test_mesh_shape_days_axis_rejected(minute_dir):
    with pytest.raises(ValueError, match="tickers axis only"):
        compute_exposures(
            minute_dir, NAMES, cfg=Config(days_per_batch=2,
                                          mesh_shape=(2, 2)),
            progress=False)


def test_atomic_write_leaves_no_temp_on_failure(tmp_path):
    import pyarrow as pa
    path = str(tmp_path / "out.parquet")

    class Boom:
        pass

    with pytest.raises(Exception):
        dio.write_parquet_atomic(Boom(), path)  # not a table -> raises
    assert not os.path.exists(path)
    assert [f for f in os.listdir(tmp_path) if f.endswith(".tmp")] == []


def test_numpy_backend_matches_jax_backend(minute_dir):
    t_jax = compute_exposures(minute_dir, NAMES, cfg=_cfg(), progress=False)
    t_np = compute_exposures(
        minute_dir, NAMES, cfg=Config(days_per_batch=2, backend="numpy"),
        progress=False)
    assert len(t_np) == len(t_jax)
    np.testing.assert_array_equal(t_np.columns["code"],
                                  t_jax.columns["code"])
    for n in NAMES:
        a, b = t_np.columns[n], t_jax.columns[n]
        both = np.isfinite(a) & np.isfinite(b)
        np.testing.assert_array_equal(np.isfinite(a), np.isfinite(b),
                                      err_msg=f"{n}: NaN pattern differs")
        np.testing.assert_allclose(a[both], b[both], rtol=2e-4, atol=1e-6,
                                   err_msg=f"{n}: values differ")


def test_single_factor_view_matches_reference_shape(minute_dir):
    t = compute_exposures(minute_dir, NAMES, cfg=_cfg(), progress=False)
    one = t.single("mmt_am")
    assert set(one) == {"code", "date", "mmt_am"}
    assert len(one["mmt_am"]) == len(t)


def test_profile_trace_capture(minute_dir, tmp_path):
    """cfg.profile_dir writes an inspectable jax.profiler trace."""
    import os
    pdir = str(tmp_path / "trace")
    compute_exposures(minute_dir, ("vol_return1min",),
                      cfg=Config(days_per_batch=2, profile_dir=pdir),
                      progress=False)
    found = [os.path.join(r, f) for r, _, fs in os.walk(pdir) for f in fs]
    assert found, "no trace files captured"


def test_failed_day_retry_semantics(minute_dir, tmp_path, rng):
    """Resume is keyed on the cache's max date (reference :79-81), which
    gives failed days two different fates (pinned by the pipeline fuzz):
    a failed day NEWER than everything cached is retried on the next run
    (self-healing), while one OLDER than the cache max stays skipped —
    exactly the reference's behavior, where a dropped mid-history day is
    lost until the cache is rebuilt."""
    cache = str(tmp_path / "f.parquet")

    def fail_on(target):
        def hook(date):
            if str(date) == target:
                raise RuntimeError("injected")
        return hook

    # fail the LAST day -> cache max is an earlier day -> retried
    t1 = compute_exposures(minute_dir, NAMES, cache_path=cache, cfg=_cfg(),
                           progress=False, fault_hook=fail_on("2024-01-04"))
    assert set(map(str, np.unique(t1.columns["date"]))) == {
        "2024-01-02", "2024-01-03"}
    t2 = compute_exposures(minute_dir, NAMES, cache_path=cache, cfg=_cfg(),
                           progress=False)
    assert set(map(str, np.unique(t2.columns["date"]))) == {
        "2024-01-02", "2024-01-03", "2024-01-04"}
    assert not t2.failures

    # fail a MIDDLE day -> cache max is the last day -> stays lost
    cache2 = str(tmp_path / "g.parquet")
    t3 = compute_exposures(minute_dir, NAMES, cache_path=cache2, cfg=_cfg(),
                           progress=False, fault_hook=fail_on("2024-01-03"))
    assert set(map(str, np.unique(t3.columns["date"]))) == {
        "2024-01-02", "2024-01-04"}
    t4 = compute_exposures(minute_dir, NAMES, cache_path=cache2, cfg=_cfg(),
                           progress=False)
    assert set(map(str, np.unique(t4.columns["date"]))) == {
        "2024-01-02", "2024-01-04"}

    # the clean rerun must NOT erase the ledger: the lost day was not
    # reattempted, and the ledger is --retry-failed's only pointer to it
    import json
    import os
    with open(cache2 + ".failures.json") as fh:
        assert [r["key"] for r in json.load(fh)] == ["2024-01-03"]

    # retry_failed re-lists the ledger day and recovers it
    t5 = compute_exposures(minute_dir, NAMES, cache_path=cache2,
                           cfg=_cfg(), progress=False, retry_failed=True)
    assert set(map(str, np.unique(t5.columns["date"]))) == {
        "2024-01-02", "2024-01-03", "2024-01-04"}
    assert not t5.failures
    # everything recovered -> ledger gone; a further retry is a no-op
    assert not os.path.exists(cache2 + ".failures.json")
    t6 = compute_exposures(minute_dir, NAMES, cache_path=cache2,
                           cfg=_cfg(), progress=False, retry_failed=True)
    assert len(t6) == len(t5)


def test_ledger_entry_survives_until_resolved(minute_dir, tmp_path):
    """A ledger day the run cannot resolve (its file vanished, or the
    run aborted before reaching it) must KEEP its entry — dropping it
    would strand the day forever behind the resume filter. Malformed
    ledgers are tolerated, not fatal."""
    import json
    cache = str(tmp_path / "f.parquet")
    compute_exposures(minute_dir, NAMES, cache_path=cache, cfg=_cfg(),
                      progress=False)
    ledger = cache + ".failures.json"
    phantom = [{"key": "2023-12-29", "source": "gone.parquet",
                "error": "RuntimeError: old failure", "trace": ""}]
    with open(ledger, "w") as fh:
        json.dump(phantom, fh)
    # retry run: the day's file no longer exists -> unresolved -> carried
    t = compute_exposures(minute_dir, NAMES, cache_path=cache,
                          cfg=_cfg(), progress=False, retry_failed=True)
    assert not t.failures
    with open(ledger) as fh:
        assert [r["key"] for r in json.load(fh)] == ["2023-12-29"]
    # malformed ledger: ignored with a warning, never a crash; the
    # malformed content is replaced by this run's (empty) truth only if
    # nothing is lost — here the bad entry is unparseable, so it drops
    with open(ledger, "w") as fh:
        fh.write('["2023-12-29"]')  # list of strings, not records
    t = compute_exposures(minute_dir, NAMES, cache_path=cache,
                          cfg=_cfg(), progress=False, retry_failed=True)
    assert not t.failures
    assert not os.path.exists(ledger)


def test_concat_rejects_schema_drift():
    a = ExposureTable.empty(["vol_return1min"])
    b = ExposureTable.empty(["mmt_pm"])
    with pytest.raises(ValueError, match="columns"):
        ExposureTable.concat([a, b])
    # same column set, different order: reconciles to part 0's order
    c = ExposureTable({"code": np.array([], dtype=object),
                       "date": np.array([], dtype="datetime64[D]"),
                       "mmt_pm": np.array([], dtype=np.float32)})
    d = ExposureTable({"mmt_pm": np.array([], dtype=np.float32),
                       "code": np.array([], dtype=object),
                       "date": np.array([], dtype="datetime64[D]")})
    out = ExposureTable.concat([c, d])
    assert list(out.columns) == list(c.columns)


# --- reference-output differential (promoted from xfail, ISSUE 6) -------
# The live polars-backend differential needs the audited reference tree
# mounted; containers without it used to xfail here, leaving tier-1 with
# ZERO executing reference-parity coverage. tests/fixtures/
# refdiff_snapshot.json now vendors the reference outputs for this
# module's exact ``minute_dir`` fixture input (generated + audited via
# tools/make_refdiff_fixture.py — the f64 oracle, whose parity with the
# reference's actual cal_* code tools/refdiff enforces whenever the
# tree IS mounted), so the comparison always executes.

_REFERENCE_MOUNTED = os.path.exists(os.path.join(
    os.environ.get("REFDIFF_REFERENCE_DIR", "/root/reference"),
    "MinuteFrequentFactorCalculateMethodsCICC.py"))
_SNAPSHOT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "fixtures", "refdiff_snapshot.json")


def _load_snapshot():
    import json
    with open(_SNAPSHOT_PATH) as fh:
        doc = json.load(fh)
    rows = doc["rows"]
    for n in doc["provenance"]["names"]:
        rows[n] = np.asarray([np.nan if v is None else v
                              for v in rows[n]], np.float32)
    return doc["provenance"], rows


def _assert_matches_snapshot(table, rtol, atol):
    prov, rows = _load_snapshot()
    assert len(table) == len(rows["code"])
    np.testing.assert_array_equal(
        table.columns["code"].astype(str), rows["code"])
    np.testing.assert_array_equal(
        table.columns["date"].astype(str), rows["date"])
    for n in prov["names"]:
        a, b = table.columns[n], rows[n]
        np.testing.assert_array_equal(np.isnan(a), np.isnan(b), err_msg=n)
        f = ~np.isnan(b)
        np.testing.assert_allclose(a[f], b[f], rtol=rtol, atol=atol,
                                   err_msg=n)
    return prov


def test_numpy_backend_matches_reference_snapshot(minute_dir):
    """The f64 oracle over the fixture days must reproduce the vendored
    reference-output snapshot to f32 round-trip exactness — the oracle's
    semantics are pinned to the last audited differential run, in
    tier-1, on every container."""
    prov, _ = _load_snapshot()
    t = compute_exposures(minute_dir, prov["names"],
                          cfg=_cfg(backend="numpy"), progress=False)
    _assert_matches_snapshot(t, rtol=0.0, atol=0.0)


def test_jax_backend_matches_reference_snapshot(minute_dir):
    """The production f32 device path against the same vendored
    reference outputs (f32-vs-f64 formulation tolerance)."""
    prov, _ = _load_snapshot()
    t = compute_exposures(minute_dir, prov["names"],
                          cfg=_cfg(backend="jax"), progress=False)
    _assert_matches_snapshot(t, rtol=5e-5, atol=1e-6)


@pytest.mark.skipif(not _REFERENCE_MOUNTED,
                    reason="audited reference tree not mounted "
                           "(REFDIFF_REFERENCE_DIR); the vendored "
                           "snapshot tests above cover this input in "
                           "tier-1")
def test_polars_backend_matches_numpy_backend(minute_dir, tmp_path):
    """backend='polars' runs the reference's actual kernel code (on the
    shim here); its exposures must match the numpy oracle backend."""
    names = ["vol_return1min", "mmt_pm", "doc_pdf60"]
    t_pl = compute_exposures(minute_dir, names,
                             cache_path=str(tmp_path / "pl.parquet"),
                             cfg=_cfg(backend="polars"), progress=False)
    t_np = compute_exposures(minute_dir, names,
                             cache_path=str(tmp_path / "np.parquet"),
                             cfg=_cfg(backend="numpy"), progress=False)
    assert len(t_pl) == len(t_np)
    np.testing.assert_array_equal(t_pl.columns["code"], t_np.columns["code"])
    for n in names:
        a, b = t_pl.columns[n], t_np.columns[n]
        np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
        f = ~np.isnan(a)
        np.testing.assert_allclose(a[f], b[f], rtol=1e-5, atol=1e-7)


class _Flaky:
    """Wraps compute_packed_prepared; fails the first ``fail_first``
    calls, then passes through."""

    def __init__(self, real, fail_first=0):
        self.real = real
        self.fail_first = fail_first
        self.calls = 0

    def __call__(self, *a, **kw):
        self.calls += 1
        if self.calls <= self.fail_first:
            raise RuntimeError(f"injected transport failure #{self.calls}")
        return self.real(*a, **kw)


def test_transient_device_failure_is_retried(minute_dir, tmp_path,
                                             monkeypatch):
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    flaky = _Flaky(pl.compute_packed_prepared, fail_first=1)
    monkeypatch.setattr(pl, "compute_packed_prepared", flaky)
    t = compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(), progress=False)
    # one retry, zero lost days
    assert len(t.failures) == 0
    assert len(np.unique(t.columns["date"])) == 3
    assert flaky.calls >= 2


def test_dead_device_trips_circuit_breaker_and_saves_progress(
        minute_dir, tmp_path, monkeypatch):
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    flaky = _Flaky(pl.compute_packed_prepared, fail_first=10 ** 9)
    monkeypatch.setattr(pl, "compute_packed_prepared", flaky)
    cache = str(tmp_path / "c.parquet")
    with pytest.raises(RuntimeError, match="consecutive"):
        compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=cache,
                          cfg=_cfg(days_per_batch=1), progress=False)
    # the failure ledger still lands next to the cache (crash-consistent)
    import os
    assert os.path.exists(cache + ".failures.json")


def test_single_bad_batch_is_skipped_not_fatal(minute_dir, tmp_path,
                                               monkeypatch):
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    real = pl.compute_packed_prepared

    calls = {"n": 0}

    def fail_second_batch(*a, **kw):
        calls["n"] += 1
        # batch 2 fails on BOTH attempts (launch + retry)
        if calls["n"] in (2, 3):
            raise RuntimeError("injected persistent failure")
        return real(*a, **kw)

    monkeypatch.setattr(pl, "compute_packed_prepared", fail_second_batch)
    t = compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(days_per_batch=1), progress=False)
    assert len(t.failures) == 1  # exactly the injected batch's day
    assert len(np.unique(t.columns["date"])) == 2


def test_cache_topup_computes_only_missing_factors(minute_dir, tmp_path,
                                                   caplog):
    """Adding a factor to an existing multi-factor cache tops up the new
    column over cached days instead of recomputing everything; values
    equal a from-scratch run, and new days still append incrementally."""
    import logging
    cache = str(tmp_path / "f.parquet")
    two = ["vol_return1min", "mmt_pm"]
    three = two + ["liq_openvol"]
    compute_exposures(minute_dir, two, cache_path=cache, cfg=_cfg(),
                      progress=False)
    with caplog.at_level(logging.INFO):
        got = compute_exposures(minute_dir, three, cache_path=cache,
                                cfg=_cfg(), progress=False)
    assert any("topping up" in r.message for r in caplog.records)
    assert not any("recomputing all days" in r.message
                   for r in caplog.records)
    fresh = compute_exposures(minute_dir, three,
                              cache_path=str(tmp_path / "g.parquet"),
                              cfg=_cfg(), progress=False)
    assert len(got) == len(fresh)
    np.testing.assert_array_equal(got.columns["code"],
                                  fresh.columns["code"])
    for n in three:
        a, b = got.columns[n], fresh.columns[n]
        np.testing.assert_array_equal(np.isnan(a), np.isnan(b))
        f = ~np.isnan(a)
        np.testing.assert_allclose(a[f], b[f], rtol=1e-6)
    # and the merged cache file itself now carries all three columns
    reread = compute_exposures(minute_dir, three, cache_path=cache,
                               cfg=_cfg(), progress=False)
    assert set(three) <= set(reread.factor_names)


def test_cache_topup_falls_back_when_day_files_changed(minute_dir,
                                                       tmp_path, caplog):
    """If the cached rows can't be aligned with a top-up pass (a day
    file vanished), the old full-recompute fallback still applies."""
    import logging
    import shutil
    md2 = str(tmp_path / "kline2")
    shutil.copytree(minute_dir, md2)
    cache = str(tmp_path / "f.parquet")
    compute_exposures(md2, ["vol_return1min"], cache_path=cache,
                      cfg=_cfg(minute_dir=md2), progress=False)
    # remove the FIRST day: cached rows for it can no longer align
    first = sorted(os.listdir(md2))[0]
    os.remove(os.path.join(md2, first))
    with caplog.at_level(logging.WARNING):
        got = compute_exposures(md2, ["vol_return1min", "mmt_pm"],
                                cache_path=cache,
                                cfg=_cfg(minute_dir=md2), progress=False)
    assert any("recomputing all days" in r.message
               for r in caplog.records)
    # result covers exactly the surviving day files
    fresh = compute_exposures(md2, ["vol_return1min", "mmt_pm"],
                              cache_path=str(tmp_path / "h.parquet"),
                              cfg=_cfg(minute_dir=md2), progress=False)
    assert len(got) == len(fresh)


def test_subset_request_never_shrinks_the_cache(minute_dir, tmp_path, rng):
    """A --factors subset against a wider cache must not prune and
    overwrite it: the persisted factor set only grows, and new days
    carry values for every cached factor (the fused graph computes the
    union in one pass)."""
    cache = str(tmp_path / "f.parquet")
    wide = ["vol_return1min", "mmt_pm", "liq_openvol"]
    compute_exposures(minute_dir, wide, cache_path=cache, cfg=_cfg(),
                      progress=False)
    # pure cache read of ONE factor: cache stays 3-wide
    t = compute_exposures(minute_dir, ["mmt_pm"], cache_path=cache,
                          cfg=_cfg(), progress=False)
    assert set(wide) <= set(ExposureTable.load(cache).factor_names)
    assert set(wide) <= set(t.factor_names)  # union returned to caller
    # subset request + a NEW day: the new day lands with ALL columns
    _write_day(minute_dir, rng, "2024-01-05")
    compute_exposures(minute_dir, ["mmt_pm"], cache_path=cache,
                      cfg=_cfg(), progress=False)
    reread = ExposureTable.load(cache)
    assert set(wide) <= set(reread.factor_names)
    new_rows = reread.columns["date"] == np.datetime64("2024-01-05")
    assert new_rows.any()
    # the unrequested factor has REAL values on the new day, not a
    # silent all-NaN hole
    assert np.isfinite(
        reread.columns["liq_openvol"][new_rows].astype(float)).any()


def test_poisoned_day_is_isolated_from_its_batch(minute_dir, tmp_path,
                                                 monkeypatch):
    """A 3-day batch whose device compute fails twice must not record
    all 3 days: per-day isolation re-runs each alone and only the day
    that fails individually is lost. Call sequence: 1 = batch launch,
    2 = batch retry, 3/4/5 = per-day isolation (day 2 poisoned)."""
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    real = pl.compute_packed_prepared
    calls = {"n": 0}

    def poisoned(*a, **kw):
        calls["n"] += 1
        if calls["n"] in (1, 2, 4):
            raise RuntimeError("injected device failure")
        return real(*a, **kw)

    monkeypatch.setattr(pl, "compute_packed_prepared", poisoned)
    t = compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(days_per_batch=3), progress=False)
    assert calls["n"] == 5
    assert t.failures.keys() == ["2024-01-03"]
    assert set(map(str, np.unique(t.columns["date"]))) == {
        "2024-01-02", "2024-01-04"}


def test_transient_batch_failure_isolates_to_zero_losses(
        minute_dir, tmp_path, monkeypatch):
    """If every day passes alone after the batch failed twice (a purely
    transient interaction), nothing is recorded and the breaker resets."""
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    real = pl.compute_packed_prepared
    calls = {"n": 0}

    def flaky_twice(*a, **kw):
        calls["n"] += 1
        if calls["n"] in (1, 2):
            raise RuntimeError("transient")
        return real(*a, **kw)

    monkeypatch.setattr(pl, "compute_packed_prepared", flaky_twice)
    t = compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(days_per_batch=3), progress=False)
    assert not t.failures
    assert len(np.unique(t.columns["date"])) == 3


def test_hostfail_batch_isolates_innocent_days(minute_dir, tmp_path,
                                               monkeypatch):
    """A multi-day batch whose host prep (grid) fails isolates per day
    too: the deterministic bad day fails alone, batch-mates survive."""
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    real_grid = pl._grid_batch

    def bad_grid(day_data, shard_mult=1):
        if any(str(d) == "2024-01-03" for d, _ in day_data):
            raise RuntimeError("injected grid failure")
        return real_grid(day_data, shard_mult=shard_mult)

    monkeypatch.setattr(pl, "_grid_batch", bad_grid)
    t = compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(days_per_batch=3), progress=False)
    assert t.failures.keys() == ["2024-01-03"]
    assert set(map(str, np.unique(t.columns["date"]))) == {
        "2024-01-02", "2024-01-04"}


def test_repeated_isolation_still_trips_the_breaker(minute_dir, tmp_path,
                                                    monkeypatch, rng):
    """A transport that fails every multi-day batch but passes days solo
    must NOT grind the whole file list at 2+N launches per batch: each
    isolation event counts toward the circuit breaker."""
    for ds in ("2024-01-05", "2024-01-08", "2024-01-09"):
        _write_day(minute_dir, rng, ds, missing_prob=0.05)  # 6 days total
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    real = pl.compute_packed_prepared
    calls = {"n": 0, "cycle": 0}

    def per_batch_flaky(*a, **kw):
        # per 2-day batch: launch fail, retry fail, two solo passes
        calls["cycle"] = calls["cycle"] % 4 + 1
        calls["n"] += 1
        if calls["cycle"] in (1, 2):
            raise RuntimeError("flaky transport")
        return real(*a, **kw)

    monkeypatch.setattr(pl, "compute_packed_prepared", per_batch_flaky)
    with pytest.raises(RuntimeError, match="consecutive"):
        compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(days_per_batch=2), progress=False)
    # the solo-recovered days were preserved before the abort
    t = ExposureTable.load(str(tmp_path / "c.parquet"))
    assert len(np.unique(t.columns["date"])) >= 4


def test_isolation_gives_up_against_a_dead_device(minute_dir, tmp_path,
                                                  monkeypatch):
    """When the first two solo launches also fail, the rest of the batch
    is recorded WITHOUT more launches (each would just hang out its
    timeout on a dead device); --retry-failed can recover them later."""
    from replication_of_minute_frequency_factor_tpu import pipeline as pl
    calls = {"n": 0}

    def dead(*a, **kw):
        calls["n"] += 1
        raise RuntimeError("dead device")

    monkeypatch.setattr(pl, "compute_packed_prepared", dead)
    t = compute_exposures(minute_dir, ["vol_return1min"],
                          cache_path=str(tmp_path / "c.parquet"),
                          cfg=_cfg(days_per_batch=3), progress=False)
    # 2 batch attempts + 2 solo attempts, then give-up: day 3 recorded
    # with zero further launches
    assert calls["n"] == 4
    assert sorted(t.failures.keys()) == ["2024-01-02", "2024-01-03",
                                         "2024-01-04"]


def test_int_coded_files_match_str_coded_files(tmp_path, rng):
    """The device pipeline's int-code fast path (raw reader + integer
    grid axis, normalized once per batch) must be value- and
    code-identical to the string path a CSMAR string export takes —
    including a code below 100000, whose zero-padding is exactly what
    the normalization exists for."""
    d_int = tmp_path / "kline_int"
    d_str = tmp_path / "kline_str"
    d_int.mkdir()
    d_str.mkdir()
    rng2 = np.random.default_rng(11)
    for ds in ("2024-01-02", "2024-01-03", "2024-01-04"):
        cols = synth_day(rng2, n_codes=7, date=ds, missing_prob=0.05)
        # one low code to force real zero-padding ('000123')
        lowest = np.sort(np.unique(cols["code"]))[0]
        code_str = np.where(cols["code"] == lowest, "000123",
                            cols["code"])
        arrays = {"time": pa.array(cols["time"])}
        for k in ("open", "high", "low", "close", "volume"):
            arrays[k] = pa.array(cols[k])
        name = ds.replace("-", "") + ".parquet"
        pq.write_table(pa.table(dict(
            code=pa.array(code_str.astype(str)), **arrays)),
            os.path.join(str(d_str), name))
        pq.write_table(pa.table(dict(
            code=pa.array(code_str.astype(np.int64)), **arrays)),
            os.path.join(str(d_int), name))
    t_int = compute_exposures(str(d_int), NAMES, cache_path=None,
                              cfg=_cfg(), progress=False)
    t_str = compute_exposures(str(d_str), NAMES, cache_path=None,
                              cfg=_cfg(), progress=False)
    assert list(t_int.columns["code"]) == list(t_str.columns["code"])
    assert "000123" in set(t_int.columns["code"])
    assert (t_int.columns["date"] == t_str.columns["date"]).all()
    for n in NAMES:
        np.testing.assert_array_equal(t_int.columns[n], t_str.columns[n])


def test_int_codes_to_str_never_truncates_wide_codes():
    """numpy 2.x np.char.zfill allocates U6 and silently truncates a
    7-digit code ('1000000' -> '100000'), which would merge two tickers
    onto one axis entry; the fallback must keep every digit."""
    got = dio.int_codes_to_str(np.array([1_000_000, 100_000, 2]))
    assert list(got) == ["1000000", "100000", "000002"]
    # fast path bit-equivalence incl. zero padding
    got = dio.int_codes_to_str(np.array([0, 123, 999_999]))
    assert list(got) == ["000000", "000123", "999999"]
    assert dio.int_codes_to_str(np.array([], dtype=np.int64)).size == 0
