"""ISSUE 5 acceptance gates: the mesh-native resident scan.

On the 8-virtual-CPU-device mesh (conftest), the tickers-sharded
resident scan must reproduce the single-device resident scan for all
58 factors — bitwise for every collective-free kernel (including the
``doc_pdf*`` family, whose global rank routes through the all_gather
collective and ranks the identical full frame on every shard). The
ONLY exception is the ``vol_upRatio``/``vol_downRatio`` pair, whose
``sqrt/sqrt`` division XLA fuses shape-dependently (observed between
ANY two module shapes, sharded or not) — pinned at <= 16 f32 ulps.

Also gated here: the overlapped-ingest loop's O(1) sync budget and its
``resident.ingest_hidden_s`` metric, and the donation contract
("dead to the caller" is machine-checked, loud and typed).
"""

import warnings

import jax
import numpy as np
import pytest

import bench
from replication_of_minute_frequency_factor_tpu import pipeline
from replication_of_minute_frequency_factor_tpu.config import get_config
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.models.registry import (
    factor_names)
from replication_of_minute_frequency_factor_tpu.parallel import (
    put_packed_year, resident_mesh)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    get_telemetry)

N_SHARDS = 8


def _make_year(n_batches=3, days=2, tickers=32, seed=0):
    rng = np.random.default_rng(seed)
    return [bench.make_batch(rng, n_days=days, n_tickers=tickers)
            for _ in range(n_batches)]


def test_sharded_resident_matches_single_device_all_58():
    """THE parity gate: all 58 factors, sharded vs single-device
    resident scan, on 8 virtual CPU devices."""
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    names = tuple(factor_names())
    assert len(names) == 58
    batches = _make_year()
    bufs, spec, kind = bench.encode_year(batches, use_wire=True)
    assert kind == "wire"
    want = np.asarray(pipeline.compute_packed_resident(
        tuple(jax.device_put(b) for b in bufs), spec, kind,
        names=names))

    stacks, sspec, skind, t_pad = bench.encode_year_sharded(
        batches, use_wire=True, n_shards=N_SHARDS)
    assert skind == "wire" and t_pad == batches[0][0].shape[1]
    mesh = resident_mesh(N_SHARDS)
    d = put_packed_year(np.stack(stacks), mesh)
    got = np.asarray(pipeline.compute_packed_resident_sharded(
        d, sspec, skind, mesh, names))
    assert got.shape == want.shape

    ulp_pair = bench._ULP_FACTORS
    for j, n in enumerate(names):
        a, b = want[:, j], got[:, j]
        if n in ulp_pair:
            # XLA fuses this kernel's sqrt/sqrt division differently
            # per module shape (ulp-level, not a sharding artifact);
            # NaN pattern must still match exactly
            assert np.array_equal(np.isnan(a), np.isnan(b)), n
            f = np.isfinite(a)
            scale = np.abs(a[f]).max(initial=1.0) or 1.0
            assert np.abs(a[f] - b[f]).max(initial=0.0) \
                <= 16 * np.finfo(np.float32).eps * scale, n
        else:
            np.testing.assert_array_equal(
                a, b, err_msg=f"factor {n} diverged under sharding")


def test_sharded_resident_pads_nondividing_tickers():
    """30 tickers over 8 shards: encode_year_sharded pads with masked
    lanes to the shard multiple and keep_results slices back — values
    must equal the single-device run on the UNPADDED batches."""
    names = ("vol_return1min", "doc_pdf60", "trade_headRatio")
    batches = _make_year(n_batches=2, tickers=30, seed=3)
    mesh = resident_mesh(N_SHARDS)
    _, _, single = bench.run_resident(batches, names, True,
                                      group=2, keep_results=True)
    _, _, sharded = bench.run_resident_sharded(
        batches, names, True, 2, mesh, keep_results=True)
    assert len(sharded) == len(single) == 2
    for s, r in zip(single, sharded):
        assert np.asarray(r).shape == np.asarray(s).shape  # sliced back
        np.testing.assert_array_equal(np.asarray(s), np.asarray(r))


def test_overlapped_ingest_syncs_and_hidden_metric():
    """Sync budget: 1 + number of scan groups host-blocking syncs per
    year (NOT O(batches)) — ingest contributes ZERO blocking syncs —
    and the overlap metric fires when more than one group runs."""
    names = ("vol_return1min", "mmt_am")
    batches = _make_year(n_batches=4, tickers=16, seed=5)
    mesh = resident_mesh(N_SHARDS)
    reg = get_telemetry().registry
    before = reg.counter_total("bench.host_blocking_syncs")
    phases, _kind, _ = bench.run_resident_sharded(
        batches, names, True, 2, mesh)
    syncs = int(reg.counter_total("bench.host_blocking_syncs") - before)
    n_groups = 2
    assert syncs <= 1 + n_groups, syncs
    assert phases["ingest_hidden_s"] > 0
    assert phases["compile_s"] >= 0 and phases["compute_s"] > 0
    # the gauge is the bench-record-independent surface of the same
    # number (docs/observability.md)
    assert reg.gauge_value("resident.ingest_hidden_s",
                           n_shards=str(N_SHARDS)) > 0


def test_sharded_smoke_verdict():
    """The run_tests.sh --quick smoke's one-line verdict is green on
    the virtual mesh."""
    r = bench.sharded_smoke()
    assert r["ok"] is True, r
    assert r["n_shards"] == N_SHARDS
    assert r["scan_groups"] >= 2 and r["ingest_hidden_s"] > 0
    assert r["mismatched"] == []


def test_sharded_and_plain_scan_twins_share_one_function():
    """Same pin as the r6 twins: a graph fix must land in both the
    donated and plain sharded executables."""
    assert (pipeline._compute_packed_scan_sharded_jit.__wrapped__
            is pipeline._compute_packed_scan_sharded_jit_donated
            .__wrapped__)


# --------------------------------------------------------------------------
# donation contract (ISSUE 5 satellite: pipeline.py:196-199 docstring,
# machine-checked)
# --------------------------------------------------------------------------


@pytest.fixture
def _force_donation(monkeypatch):
    """CPU backends never donate (pipeline._donate_device_buffers);
    force the donated twins so the contract is testable hermetically.
    jax emits a per-compile 'donation not implemented' warning on CPU —
    expected here."""
    monkeypatch.setattr(pipeline, "_donate_device_buffers",
                        lambda cfg=None: True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        yield


def _packed_batch(tickers=8):
    b, m = bench.make_batch(np.random.default_rng(0), n_days=1,
                            n_tickers=tickers)
    buf, spec = wire.pack_arrays((b, m.view(np.uint8)))
    return buf, spec


def test_donated_resident_buffer_reuse_is_loud_and_typed(
        _force_donation):
    """After compute_packed_resident donated the buffers, the handles
    are dead to the caller ON EVERY BACKEND: any reuse raises jax's
    typed deletion RuntimeError (not a silent wrong answer, not an
    XLA-internal crash on hardware only)."""
    buf, spec = _packed_batch()
    names = ("vol_return1min",)
    d = jax.device_put(buf)
    out = np.asarray(pipeline.compute_packed_resident(
        (d,), spec, "raw", names))
    assert out.shape[0] == 1
    assert d.is_deleted()  # the docstring's "dead to the caller", true
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(jax.jit(lambda x: x)(d))


def test_debug_validate_guard_names_the_contract(_force_donation):
    """With Config.debug_validate, reusing a donated handle in the
    packed entry points raises DonatedBufferError with the contract
    spelled out — instead of jax's generic message at first use."""
    buf, spec = _packed_batch()
    names = ("vol_return1min",)
    d = jax.device_put(buf)
    pipeline.compute_packed_resident((d,), spec, "raw", names)
    cfg = get_config()
    old = cfg.debug_validate
    cfg.debug_validate = True
    try:
        with pytest.raises(pipeline.DonatedBufferError,
                           match="donated.*device_put a fresh"):
            pipeline.compute_packed_resident((d,), spec, "raw", names)
    finally:
        cfg.debug_validate = old


def test_sharded_resident_donation_contract(_force_donation):
    """The sharded twin enforces the same contract on its stacked
    year."""
    names = ("vol_return1min",)
    batches = _make_year(n_batches=2, tickers=16, seed=7)
    stacks, spec, kind, _ = bench.encode_year_sharded(
        batches, use_wire=True, n_shards=N_SHARDS)
    mesh = resident_mesh(N_SHARDS)
    d = put_packed_year(np.stack(stacks), mesh)
    np.asarray(pipeline.compute_packed_resident_sharded(
        d, spec, kind, mesh, names))
    assert d.is_deleted()
    cfg = get_config()
    old = cfg.debug_validate
    cfg.debug_validate = True
    try:
        with pytest.raises(pipeline.DonatedBufferError):
            pipeline.compute_packed_resident_sharded(
                d, spec, kind, mesh, names)
    finally:
        cfg.debug_validate = old
