"""ISSUE 5 acceptance gates: the mesh-native resident scan.

On the 8-virtual-CPU-device mesh (conftest), the tickers-sharded
resident scan must reproduce the single-device resident scan for all
58 factors — bitwise for every collective-free kernel (including the
``doc_pdf*`` family, whose global rank routes through the all_gather
collective and ranks the identical full frame on every shard). The
ONLY exception is the ``vol_upRatio``/``vol_downRatio`` pair, whose
``sqrt/sqrt`` division XLA fuses shape-dependently (observed between
ANY two module shapes, sharded or not) — pinned at <= 16 f32 ulps.

Also gated here: the overlapped-ingest loop's O(1) sync budget and its
``resident.ingest_hidden_s`` metric, and the donation contract
("dead to the caller" is machine-checked, loud and typed).
"""

import warnings

import jax
import numpy as np
import pytest

import bench
from replication_of_minute_frequency_factor_tpu import pipeline
from replication_of_minute_frequency_factor_tpu.config import get_config
from replication_of_minute_frequency_factor_tpu.data import wire
from replication_of_minute_frequency_factor_tpu.models.registry import (
    factor_names)
from replication_of_minute_frequency_factor_tpu.parallel import (
    put_packed_year, put_packed_year_2d, put_span_carry, resident_mesh)
from replication_of_minute_frequency_factor_tpu.stream import (
    carry as scarry)
from replication_of_minute_frequency_factor_tpu.telemetry import (
    get_telemetry)

N_SHARDS = 8
#: the ISSUE 13 acceptance mesh: 2 day-shards x 4 ticker-shards over
#: the 8 virtual devices
MESH_2D = (2, 4)


def _make_year(n_batches=3, days=2, tickers=32, seed=0):
    rng = np.random.default_rng(seed)
    return [bench.make_batch(rng, n_days=days, n_tickers=tickers)
            for _ in range(n_batches)]


def test_sharded_resident_matches_single_device_all_58():
    """THE parity gate: all 58 factors, sharded vs single-device
    resident scan, on 8 virtual CPU devices."""
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    names = tuple(factor_names())
    assert len(names) == 58
    batches = _make_year()
    bufs, spec, kind = bench.encode_year(batches, use_wire=True)
    assert kind == "wire"
    want = np.asarray(pipeline.compute_packed_resident(
        tuple(jax.device_put(b) for b in bufs), spec, kind,
        names=names))

    stacks, sspec, skind, t_pad = bench.encode_year_sharded(
        batches, use_wire=True, n_shards=N_SHARDS)
    assert skind == "wire" and t_pad == batches[0][0].shape[1]
    mesh = resident_mesh(N_SHARDS)
    d = put_packed_year(np.stack(stacks), mesh)
    got = np.asarray(pipeline.compute_packed_resident_sharded(
        d, sspec, skind, mesh, names))
    assert got.shape == want.shape

    ulp_pair = bench._ULP_FACTORS
    for j, n in enumerate(names):
        a, b = want[:, j], got[:, j]
        if n in ulp_pair:
            # XLA fuses this kernel's sqrt/sqrt division differently
            # per module shape (ulp-level, not a sharding artifact);
            # NaN pattern must still match exactly
            assert np.array_equal(np.isnan(a), np.isnan(b)), n
            f = np.isfinite(a)
            scale = np.abs(a[f]).max(initial=1.0) or 1.0
            assert np.abs(a[f] - b[f]).max(initial=0.0) \
                <= 16 * np.finfo(np.float32).eps * scale, n
        else:
            np.testing.assert_array_equal(
                a, b, err_msg=f"factor {n} diverged under sharding")


def test_sharded_resident_pads_nondividing_tickers():
    """30 tickers over 8 shards: encode_year_sharded pads with masked
    lanes to the shard multiple and keep_results slices back — values
    must equal the single-device run on the UNPADDED batches."""
    names = ("vol_return1min", "doc_pdf60", "trade_headRatio")
    batches = _make_year(n_batches=2, tickers=30, seed=3)
    mesh = resident_mesh(N_SHARDS)
    _, _, single = bench.run_resident(batches, names, True,
                                      group=2, keep_results=True)
    _, _, sharded = bench.run_resident_sharded(
        batches, names, True, 2, mesh, keep_results=True)
    assert len(sharded) == len(single) == 2
    for s, r in zip(single, sharded):
        assert np.asarray(r).shape == np.asarray(s).shape  # sliced back
        np.testing.assert_array_equal(np.asarray(s), np.asarray(r))


def test_overlapped_ingest_syncs_and_hidden_metric():
    """Sync budget: 1 + number of scan groups host-blocking syncs per
    year (NOT O(batches)) — ingest contributes ZERO blocking syncs —
    and the overlap metric fires when more than one group runs."""
    names = ("vol_return1min", "mmt_am")
    batches = _make_year(n_batches=4, tickers=16, seed=5)
    mesh = resident_mesh(N_SHARDS)
    reg = get_telemetry().registry
    before = reg.counter_total("bench.host_blocking_syncs")
    phases, _kind, _ = bench.run_resident_sharded(
        batches, names, True, 2, mesh)
    syncs = int(reg.counter_total("bench.host_blocking_syncs") - before)
    n_groups = 2
    assert syncs <= 1 + n_groups, syncs
    assert phases["ingest_hidden_s"] > 0
    assert phases["compile_s"] >= 0 and phases["compute_s"] > 0
    # the gauge is the bench-record-independent surface of the same
    # number (docs/observability.md)
    assert reg.gauge_value("resident.ingest_hidden_s",
                           n_shards=str(N_SHARDS)) > 0


def test_sharded_smoke_verdict():
    """The run_tests.sh --quick smoke's one-line verdict is green on
    the virtual mesh."""
    r = bench.sharded_smoke()
    assert r["ok"] is True, r
    assert r["n_shards"] == N_SHARDS
    assert r["scan_groups"] >= 2 and r["ingest_hidden_s"] > 0
    assert r["mismatched"] == []


def test_sharded_and_plain_scan_twins_share_one_function():
    """Same pin as the r6 twins: a graph fix must land in both the
    donated and plain sharded executables."""
    assert (pipeline._compute_packed_scan_sharded_jit.__wrapped__
            is pipeline._compute_packed_scan_sharded_jit_donated
            .__wrapped__)


# --------------------------------------------------------------------------
# ISSUE 13: the 2-D (days, tickers) pipelined resident scan
# --------------------------------------------------------------------------


def _run_2d(batches, names, mesh_shape=MESH_2D, group=None,
            keep_carry=False):
    """One pass through the real bench loop (encode_year_2d + carry
    threading + consolidated fetch) at test scale."""
    mesh = resident_mesh(shape=mesh_shape)
    group = group or len(batches)
    return bench.run_resident_2d(batches, names, True, group, mesh,
                                 keep_results=True,
                                 keep_carry=keep_carry)


def test_resident_2d_matches_single_device_all_58():
    """THE r12 parity gate: all 58 factors, the (2, 4) 2-D scan vs the
    single-device resident scan at the same scan-group structure —
    bitwise for 56, the documented _ULP_FACTORS pair pinned <= 16 f32
    ulps (the same pin set as the 1-D gate: the day split adds NO new
    divergence)."""
    assert len(jax.devices()) == 8, "conftest must force 8 CPU devices"
    names = tuple(factor_names())
    assert len(names) == 58
    batches = _make_year(n_batches=3, days=4, tickers=32)
    _, _, single = bench.run_resident(batches, names, True,
                                      group=3, keep_results=True)
    _, _, sharded, _ = _run_2d(batches, names)
    ulp_pair = bench._ULP_FACTORS
    for s, r in zip(single, sharded):
        for j, n in enumerate(names):
            a, b = np.asarray(s[j]), np.asarray(r[j])
            assert a.shape == b.shape, n  # both paddings sliced back
            if n in ulp_pair:
                assert np.array_equal(np.isnan(a), np.isnan(b)), n
                f = np.isfinite(a)
                scale = np.abs(a[f]).max(initial=1.0) or 1.0
                assert np.abs(a[f] - b[f]).max(initial=0.0) \
                    <= 16 * np.finfo(np.float32).eps * scale, n
            else:
                np.testing.assert_array_equal(
                    a, b, err_msg=f"factor {n} diverged on the 2-D "
                    "mesh")


def test_cross_day_carry_handoff_at_shard_boundary():
    """ISSUE 13 satellite: a day-shard boundary mid-window. With 4-day
    batches on (2, 4), days 0-1 and 2-3 of EVERY batch live on
    different day-shards; the rolling-moment family must stay bitwise
    across that split, and the year-end carry handed off through the
    ppermute leg must bit-equal the single-device stream/carry
    prefix-state fold over the same decoded days."""
    names = ("mmt_ols_qrs", "doc_kurt", "doc_vol10_ratio",
             "vol_return1min")
    batches = _make_year(n_batches=2, days=4, tickers=32, seed=21)
    _, _, single = bench.run_resident(batches, names, True,
                                      group=2, keep_results=True)
    _, _, sharded, carry = _run_2d(batches, names, keep_carry=True)
    for s, r in zip(single, sharded):
        np.testing.assert_array_equal(np.asarray(s), np.asarray(r))
    # reference fold: the SAME wire-decoded days through
    # stream/carry's span_prefix_state, single device, global order
    bufs, spec, kind = bench.encode_year(batches, True)
    assert kind == "wire"
    dec = jax.jit(lambda b: wire.decode(*wire.unpack(b, spec)))
    state = jax.device_put({**scarry.init_span_state(32),
                            "day": np.full(32, -1, np.int32)})
    fold = jax.jit(lambda s, b, n: scarry.combine_span_state(
        s, scarry.span_prefix_state(*dec(b), day_base=n * 4)))
    for n_, b in enumerate(bufs):
        state = fold(state, jax.device_put(b), np.int32(n_))
    ref = jax.device_get(state)
    assert np.array_equal(ref["n_bars"], carry["n_bars"])
    assert np.array_equal(ref["has"], carry["has"])
    assert np.array_equal(np.isnan(ref["last_close"]),
                          np.isnan(carry["last_close"]))
    f = ref["has"]
    np.testing.assert_array_equal(ref["last_close"][f],
                                  carry["last_close"][f])
    # the carry is the finalize-inject pair: a lane's n_bars is its
    # LAST day's bar count, not a year total
    assert carry["n_bars"].max() <= 240


def test_carry_threads_across_pipelined_groups():
    """Pipelining must not change the carry: two scan groups threading
    the carry on device (group=1) end in the SAME year-end state as
    one group over the whole year — newer days win per lane across the
    group boundary exactly as they do across scan steps."""
    names = ("vol_return1min",)
    batches = _make_year(n_batches=2, days=2, tickers=32, seed=5)
    _, _, _, one = _run_2d(batches, names, group=2, keep_carry=True)
    _, _, _, piped = _run_2d(batches, names, group=1, keep_carry=True)
    for k in ("last_close", "n_bars", "has"):
        np.testing.assert_array_equal(one[k], piped[k], err_msg=k)


def test_resident_2d_pads_both_axes():
    """3-day x 30-ticker batches on (2, 4): the days axis pads to 4
    with fully-masked filler days, tickers to 32 with masked lanes —
    values must equal the single-device run on the UNPADDED batches,
    and both paddings must land in the per-axis pad-waste gauges."""
    names = ("vol_return1min", "doc_pdf60", "trade_headRatio")
    batches = _make_year(n_batches=2, days=3, tickers=30, seed=3)
    _, _, single = bench.run_resident(batches, names, True,
                                      group=2, keep_results=True)
    _, _, sharded, _ = _run_2d(batches, names)
    for s, r in zip(single, sharded):
        assert np.asarray(r).shape == np.asarray(s).shape
        np.testing.assert_array_equal(np.asarray(s), np.asarray(r))
    waste = get_telemetry().meshplane.summary()[
        "pad_waste_frac_by_axis"]
    assert waste["days"] == pytest.approx(0.25)
    assert waste["tickers"] == pytest.approx(1 - 30 / 32)


def test_resident_2d_sync_budget_and_handoff_count():
    """The acceptance sync budget: the 2-D loop's measured
    host-blocking syncs per year stay <= the 1-D sharded loop's
    1 + n_groups (the carry threads on device, never fetched by the
    timed loop), and every group counts one carry-handoff collective
    dispatch."""
    names = ("vol_return1min", "mmt_am")
    batches = _make_year(n_batches=4, days=2, tickers=16, seed=7)
    reg = get_telemetry().registry
    before = reg.counter_total("bench.host_blocking_syncs")
    h0 = reg.counter_value("mesh.collective_dispatches",
                           label="carry_handoff")
    phases, _, _, carry = _run_2d(batches, names, mesh_shape=(2, 2),
                                  group=2)
    syncs = int(reg.counter_total("bench.host_blocking_syncs") - before)
    handoffs = int(reg.counter_value("mesh.collective_dispatches",
                                     label="carry_handoff") - h0)
    n_groups = 2
    assert syncs <= 1 + n_groups, syncs
    assert carry is None  # not fetched unless asked
    assert handoffs == n_groups
    assert phases["ingest_hidden_s"] > 0  # pipelined ingest overlapped


def test_resident_2d_smoke_verdict():
    """The run_tests.sh --quick smoke's one-line verdict is green on
    the virtual (2, 4) mesh (restricted family set here; the shell
    smoke runs all 58)."""
    r = bench.resident_2d_smoke(names=bench._SMOKE_FACTORS)
    assert r["ok"] is True, r
    assert r["mesh_shape"] == [2, 4]
    assert r["carry_handoffs"] > 0 and r["carry_ok"] is True
    assert r["syncs_2d"] <= r["syncs_1d"]
    assert r["mismatched"] == []


def test_2d_scan_twins_share_one_function():
    """Same pin as the r6/r7 twins: a graph fix must land in both the
    donated and plain 2-D executables."""
    assert (pipeline._compute_packed_scan_2d_jit.__wrapped__
            is pipeline._compute_packed_scan_2d_jit_donated.__wrapped__)


# --------------------------------------------------------------------------
# donation contract (ISSUE 5 satellite: pipeline.py:196-199 docstring,
# machine-checked)
# --------------------------------------------------------------------------


@pytest.fixture
def _force_donation(monkeypatch):
    """CPU backends never donate (pipeline._donate_device_buffers);
    force the donated twins so the contract is testable hermetically.
    jax emits a per-compile 'donation not implemented' warning on CPU —
    expected here."""
    monkeypatch.setattr(pipeline, "_donate_device_buffers",
                        lambda cfg=None: True)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        yield


def _packed_batch(tickers=8):
    b, m = bench.make_batch(np.random.default_rng(0), n_days=1,
                            n_tickers=tickers)
    buf, spec = wire.pack_arrays((b, m.view(np.uint8)))
    return buf, spec


def test_donated_resident_buffer_reuse_is_loud_and_typed(
        _force_donation):
    """After compute_packed_resident donated the buffers, the handles
    are dead to the caller ON EVERY BACKEND: any reuse raises jax's
    typed deletion RuntimeError (not a silent wrong answer, not an
    XLA-internal crash on hardware only)."""
    buf, spec = _packed_batch()
    names = ("vol_return1min",)
    d = jax.device_put(buf)
    out = np.asarray(pipeline.compute_packed_resident(
        (d,), spec, "raw", names))
    assert out.shape[0] == 1
    assert d.is_deleted()  # the docstring's "dead to the caller", true
    with pytest.raises(RuntimeError, match="deleted"):
        np.asarray(jax.jit(lambda x: x)(d))


def test_debug_validate_guard_names_the_contract(_force_donation):
    """With Config.debug_validate, reusing a donated handle in the
    packed entry points raises DonatedBufferError with the contract
    spelled out — instead of jax's generic message at first use."""
    buf, spec = _packed_batch()
    names = ("vol_return1min",)
    d = jax.device_put(buf)
    pipeline.compute_packed_resident((d,), spec, "raw", names)
    cfg = get_config()
    old = cfg.debug_validate
    cfg.debug_validate = True
    try:
        with pytest.raises(pipeline.DonatedBufferError,
                           match="donated.*device_put a fresh"):
            pipeline.compute_packed_resident((d,), spec, "raw", names)
    finally:
        cfg.debug_validate = old


def test_resident_2d_donation_contract(_force_donation):
    """The 2-D twin enforces the stacked-year contract too; the tiny
    threaded carry is NOT donated — the caller reuses it across
    groups."""
    names = ("vol_return1min",)
    batches = _make_year(n_batches=2, days=2, tickers=16, seed=9)
    stacks, spec, kind, t_pad, _d_pad = bench.encode_year_2d(
        batches, True, *MESH_2D)
    mesh = resident_mesh(shape=MESH_2D)
    d = put_packed_year_2d(np.stack(stacks), mesh)
    cin = put_span_carry(scarry.init_span_state(t_pad), mesh)
    ys, carry = pipeline.compute_packed_resident_2d(
        d, spec, kind, mesh, names, carry_in=cin)
    np.asarray(ys)
    assert d.is_deleted()
    assert not cin["n_bars"].is_deleted()  # the carry stays usable
    cfg = get_config()
    old = cfg.debug_validate
    cfg.debug_validate = True
    try:
        with pytest.raises(pipeline.DonatedBufferError):
            pipeline.compute_packed_resident_2d(
                d, spec, kind, mesh, names, carry_in=cin)
    finally:
        cfg.debug_validate = old


def test_sharded_resident_donation_contract(_force_donation):
    """The sharded twin enforces the same contract on its stacked
    year."""
    names = ("vol_return1min",)
    batches = _make_year(n_batches=2, tickers=16, seed=7)
    stacks, spec, kind, _ = bench.encode_year_sharded(
        batches, use_wire=True, n_shards=N_SHARDS)
    mesh = resident_mesh(N_SHARDS)
    d = put_packed_year(np.stack(stacks), mesh)
    np.asarray(pipeline.compute_packed_resident_sharded(
        d, spec, kind, mesh, names))
    assert d.is_deleted()
    cfg = get_config()
    old = cfg.debug_validate
    cfg.debug_validate = True
    try:
        with pytest.raises(pipeline.DonatedBufferError):
            pipeline.compute_packed_resident_sharded(
                d, spec, kind, mesh, names)
    finally:
        cfg.debug_validate = old
