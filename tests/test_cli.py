"""CLI driver (``__main__.py``): the notebook-replacement workflow
compute -> evaluate -> list, end to end on synthetic day files."""

import json
import os

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from replication_of_minute_frequency_factor_tpu.__main__ import main
from replication_of_minute_frequency_factor_tpu.data.synthetic import synth_day


@pytest.fixture
def workspace(tmp_path, rng):
    kline = tmp_path / "kline"
    kline.mkdir()
    days = ["2024-01-02", "2024-01-03", "2024-01-04", "2024-01-05",
            "2024-01-08", "2024-01-09", "2024-01-10", "2024-01-11"]
    codes = None
    for ds in days:
        cols = synth_day(rng, n_codes=8, date=ds, missing_prob=0.05)
        arrays = {"code": pa.array([str(c) for c in cols["code"]]),
                  "time": pa.array(cols["time"])}
        for k in ("open", "high", "low", "close", "volume"):
            arrays[k] = pa.array(cols[k])
        pq.write_table(pa.table(arrays),
                       str(kline / (ds.replace("-", "") + ".parquet")))
        codes = sorted({str(c) for c in cols["code"]})
    dd = np.array(days, dtype="datetime64[D]")
    rows = {k: [] for k in ("code", "date", "pct_change", "tmc", "cmc")}
    for c in codes:
        rows["code"] += [c] * len(dd)
        rows["date"].append(dd)
        rows["pct_change"].append(rng.normal(0, 0.01, len(dd)))
        mc = rng.uniform(1e9, 5e10)
        rows["tmc"].append(np.full(len(dd), mc))
        rows["cmc"].append(np.full(len(dd), mc * 0.7))
    pv = str(tmp_path / "pv.parquet")
    pq.write_table(pa.table({
        "code": pa.array(rows["code"]),
        "date": pa.array(np.concatenate(rows["date"])),
        "pct_change": pa.array(np.concatenate(rows["pct_change"])),
        "tmc": pa.array(np.concatenate(rows["tmc"])),
        "cmc": pa.array(np.concatenate(rows["cmc"])),
    }), pv)
    return str(kline), pv, str(tmp_path / "factors.parquet"), str(tmp_path)


def test_compute_then_evaluate(workspace, capsys):
    kline, pv, cache, tmp = workspace
    rc = main(["compute", "--minute-dir", kline, "--cache", cache,
               "--factors", "vol_return1min,mmt_pm", "--days-per-batch",
               "2", "--quiet"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["days"] == 8 and out["factors"] == 2
    assert os.path.exists(cache)

    plots = os.path.join(tmp, "charts")
    rc = main(["evaluate", "--factor", "vol_return1min", "--cache", cache,
               "--daily-pv", pv, "--future-days", "1",
               "--frequency", "week", "--plots", plots])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert np.isfinite(out["IC"]) and np.isfinite(out["rank_ICIR"])
    for kind in ("coverage", "ic", "group"):
        assert os.path.exists(
            os.path.join(plots, f"vol_return1min_{kind}.png")), kind

    # unknown factor: clean error, not a traceback
    rc = main(["evaluate", "--factor", "nope", "--cache", cache,
               "--daily-pv", pv])
    assert rc == 2


def test_list_factors(capsys):
    assert main(["list-factors", "--json"]) == 0
    names = json.loads(capsys.readouterr().out)
    assert len(names) == 58 and "doc_kurt" in names


def test_compute_rejects_unknown_factor(workspace, capsys):
    kline, _, cache, _ = workspace
    rc = main(["compute", "--minute-dir", kline, "--cache", cache,
               "--factors", "vol_return1mim", "--quiet"])
    assert rc == 2
    assert not os.path.exists(cache)


def test_evaluate_disjoint_pv_reports_null_stats(workspace, capsys,
                                                 tmp_path):
    """No shared (code, date) cross-section: stats must come back null
    (not a float(None) traceback)."""
    kline, pv, cache, tmp = workspace
    assert main(["compute", "--minute-dir", kline, "--cache", cache,
                 "--factors", "mmt_pm", "--quiet"]) == 0
    capsys.readouterr()
    other = str(tmp_path / "pv_other.parquet")
    dd = np.array(["2030-01-02", "2030-01-03"], dtype="datetime64[D]")
    pq.write_table(pa.table({
        "code": pa.array(["999999"] * 2), "date": pa.array(dd),
        "pct_change": pa.array([0.01, -0.01]),
        "tmc": pa.array([1e9, 1e9]), "cmc": pa.array([7e8, 7e8]),
    }), other)
    rc = main(["evaluate", "--factor", "mmt_pm", "--cache", cache,
               "--daily-pv", other])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["IC"] is None and out["rank_ICIR"] is None


def test_doctor_reports_environment(capsys):
    """doctor must produce a well-formed report in ANY environment — its
    whole purpose is diagnosing degraded ones, so only the report's shape
    (and rc consistency) is asserted, not environment health."""
    rc = main(["doctor"])
    out = json.loads(capsys.readouterr().out)
    assert "device_probe" in out
    assert (rc == 0) == (out["device_probe"] == "ok")
    assert out["native_encoder"].startswith(("built", "unavailable"))
    assert "config" in out and "days_per_batch" in out["config"]
